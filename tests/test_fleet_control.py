"""Unit tests for the fleet control plane (distributed/fleet_control.py)
and the rank-merged checkpoint loader (CheckpointManager.load_merged)."""
import json
import os
import threading
import time
import warnings

import numpy as np
import pytest

from paddle_tpu.distributed import fleet_control as fc
from paddle_tpu.distributed.fleet_control import (
    FleetAgreementTimeout, FleetBarrier, FleetController, fleet_env,
    fleet_rank, fleet_world_size, live_members,
    newest_mutual_checkpoint_step, propose_reform, read_commit,
    read_members, write_member)


# ---------------------------------------------------------------------------
# world math / rank layout
# ---------------------------------------------------------------------------
def test_fleet_world_size_math():
    assert fleet_world_size(8, 8) == 8
    assert fleet_world_size(7, 8) == 4   # largest pow2 divisor fillable
    assert fleet_world_size(4, 8) == 4
    assert fleet_world_size(3, 8) == 2
    assert fleet_world_size(1, 8) == 1
    assert fleet_world_size(0, 8) == 0
    assert fleet_world_size(16, 8) == 8  # never exceeds the logical world


def test_fleet_rank_is_dense_over_sorted_members():
    assert fleet_rank(0, [0, 1]) == 0
    assert fleet_rank(1, [0, 1]) == 1
    # after host 0 is lost, host 1 becomes rank 0 of the new formation
    assert fleet_rank(1, [1]) == 0
    assert fleet_rank(3, [3, 1]) == 1


# ---------------------------------------------------------------------------
# membership + liveness
# ---------------------------------------------------------------------------
def test_membership_roundtrip_and_liveness(tmp_path):
    d = str(tmp_path)
    write_member(d, 0, capacity=4, epoch=0, ranks=[0])
    write_member(d, 1, capacity=4, epoch=0, ranks=[1])
    members = read_members(d)
    assert sorted(members) == [0, 1]
    assert members[0]["capacity"] == 4 and members[1]["ranks"] == [1]
    assert sorted(live_members(d, timeout_s=60.0)) == [0, 1]
    # a host that stops refreshing ages out
    now = time.time() + 120
    assert sorted(live_members(d, timeout_s=60.0, now=now)) == []


def test_done_member_departed_not_lost(tmp_path):
    d = str(tmp_path)
    write_member(d, 0, capacity=4, epoch=0)
    write_member(d, 1, capacity=4, epoch=0, status="done")
    assert sorted(live_members(d, timeout_s=60.0)) == [0]
    ctl = FleetController(d, host=0, capacity=4, logical_world=8,
                         member_timeout_s=60.0)
    commit = fc.FleetCommit({"epoch": 0, "members": [0, 1], "world": 8})
    assert ctl.lost_members(commit) == []  # departed cleanly, not lost


def test_wedged_host_counts_as_lost_via_heartbeats(tmp_path):
    """A host whose launcher still refreshes but whose every trainer
    heartbeat went stale is wedged — liveness from the heartbeat files,
    not just the membership record."""
    from paddle_tpu.observability.heartbeat import heartbeat_path
    d = str(tmp_path / "fleet")
    hb = str(tmp_path / "hb")
    os.makedirs(hb)
    write_member(d, 0, capacity=4, epoch=0, ranks=[0])
    write_member(d, 1, capacity=4, epoch=0, ranks=[1])
    old = time.time() - 1000
    for rank, t in ((0, time.time()), (1, old)):
        with open(heartbeat_path(hb, rank), "w") as f:
            json.dump({"rank": rank, "step": 3, "t": t}, f)
    live = live_members(d, timeout_s=60.0, heartbeat_dir=hb,
                        stall_timeout_s=30.0)
    assert sorted(live) == [0]  # host 1's only rank stalled -> lost


# ---------------------------------------------------------------------------
# two-phase agreement
# ---------------------------------------------------------------------------
def _make_ctl(d, host, n=2, capacity=4, logical=8, **kw):
    kw.setdefault("member_timeout_s", 5.0)
    kw.setdefault("agreement_timeout_s", 20.0)
    return FleetController(d, host=host, capacity=capacity,
                           logical_world=logical, **kw)


def test_two_phase_agreement_two_hosts(tmp_path):
    d = str(tmp_path)
    ctls = [_make_ctl(d, h) for h in range(2)]
    results = {}

    def run(h):
        results[h] = ctls[h].form(expect=[0, 1])

    threads = [threading.Thread(target=run, args=(h,)) for h in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert results[0] == results[1]
    assert results[0].members == [0, 1]
    assert results[0].world == 8
    assert results[0]["coordinator"] == 0
    # the commit is durable: a late reader adopts the same record
    assert read_commit(d, 0) == results[0]


def test_reform_excludes_stale_host_and_converges(tmp_path):
    """Host 2 dies before the re-form: its membership ages out, the two
    survivors' proposals converge on {0,1} and commit world 4 of the
    logical 8 (3 hosts x capacity 4 = capacity 8 shrank to 8->...->4)."""
    d = str(tmp_path)
    write_member(d, 2, capacity=4, epoch=1)  # the dead host's last record
    ctls = [_make_ctl(d, h, member_timeout_s=0.8) for h in range(2)]
    for c in ctls:
        c.epoch = 1
    time.sleep(1.0)  # host 2's record goes stale
    for c in ctls:   # the survivors' launchers have been ticking all along
        c.tick(min_interval_s=0.0)
    results = {}

    def run(h):
        results[h] = ctls[h].form(epoch=1)

    threads = [threading.Thread(target=run, args=(h,)) for h in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert results[0] == results[1]
    assert results[0].members == [0, 1]
    assert results[0].world == 8  # 2 survivors x capacity 4 fill 8

    # single survivor at the next epoch: world shrinks to its capacity
    solo = _make_ctl(d, 0, member_timeout_s=0.5)
    solo.epoch = 2
    time.sleep(0.8)
    commit = solo.form(epoch=2)
    assert commit.members == [0] and commit.world == 4


def test_reset_rendezvous_sweeps_previous_run(tmp_path):
    """A reused --fleet_dir must not replay the previous run's
    agreement: stale commits/proposals/barrier markers/done-members are
    swept at startup; fresh membership survives."""
    d = str(tmp_path)
    propose_reform(d, 0, epoch=1, members=[0], world=4, restore_step=9)
    fc._write_json(fc._commit_path(d, 1),
                   {"epoch": 1, "members": [0], "world": 4,
                    "restore_step": 9})
    os.makedirs(os.path.join(d, "barrier.e0.n1"))
    write_member(d, 1, capacity=4, epoch=3, status="done")  # old run done
    write_member(d, 0, capacity=4, epoch=0)                 # fresh peer
    ctl = _make_ctl(d, 1)
    ctl.reset_rendezvous()
    assert read_commit(d, 1) is None
    assert fc.read_proposals(d, 1) == {}
    assert not os.path.isdir(os.path.join(d, "barrier.e0.n1"))
    members = read_members(d)
    assert sorted(members) == [0]  # done-record swept, fresh one kept
    assert not ctl.reform_requested()


def test_agreement_timeout_raises(tmp_path):
    ctl = _make_ctl(str(tmp_path), 0, agreement_timeout_s=0.5)
    with pytest.raises(FleetAgreementTimeout):
        ctl.await_members([0, 1], timeout_s=0.5)


def test_reform_requested_channel(tmp_path):
    d = str(tmp_path)
    ctl = _make_ctl(d, 0)
    assert not ctl.reform_requested()
    propose_reform(d, 1, epoch=1, members=[1], world=4, restore_step=None)
    assert ctl.reform_requested()


def test_fleet_barrier_synchronizes(tmp_path):
    d = str(tmp_path)
    barriers = [FleetBarrier(d, h, [0, 1], timeout_s=10.0)
                for h in range(2)]
    order = []

    def run(h, delay):
        time.sleep(delay)
        barriers[h]()
        order.append(h)

    threads = [threading.Thread(target=run, args=(0, 0.0)),
               threading.Thread(target=run, args=(1, 0.3))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(15)
    assert sorted(order) == [0, 1]
    lone = FleetBarrier(d, 0, [0, 1], epoch=9, timeout_s=0.3)
    with pytest.raises(FleetAgreementTimeout):
        lone()  # the peer never arrives at this epoch's barrier


# ---------------------------------------------------------------------------
# restore-step agreement off the journals
# ---------------------------------------------------------------------------
def _write_journal(directory, rank, events):
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"journal.rank{rank}.jsonl")
    with open(path, "a") as f:
        for seq, (kind, fields) in enumerate(events):
            rec = {"v": 1, "run_id": f"r{rank}", "rank": rank,
                   "seq": seq, "t": 1000.0 + seq, "kind": kind}
            rec.update(fields)
            f.write(json.dumps(rec) + "\n")


def test_newest_mutual_checkpoint_step(tmp_path):
    d = str(tmp_path)
    # rank 0 staged 2,4,6 and committed 2,4 (6 staged, never published);
    # rank 1 staged 2,4 only — mutual newest is 4
    _write_journal(d, 0, [("checkpoint_save", {"step": 2}),
                          ("checkpoint_commit", {"step": 2}),
                          ("checkpoint_save", {"step": 4}),
                          ("checkpoint_commit", {"step": 4}),
                          ("checkpoint_save", {"step": 6})])
    _write_journal(d, 1, [("checkpoint_save", {"step": 2}),
                          ("checkpoint_save", {"step": 4})])
    assert newest_mutual_checkpoint_step(d, [0, 1]) == 4
    assert newest_mutual_checkpoint_step(d, [0]) == 4
    # a survivor with no journal -> nothing provably restorable
    assert newest_mutual_checkpoint_step(d, [0, 7]) is None


def test_reconstruct_timeline_carries_saves_and_reforms(tmp_path):
    from paddle_tpu.observability.journal import (read_journal,
                                                  reconstruct_timeline)
    d = str(tmp_path)
    _write_journal(d, 0, [("checkpoint_save", {"step": 2}),
                          ("reform", {"epoch": 1, "world": 4,
                                      "members": [0],
                                      "restore_step": 2})])
    tl = reconstruct_timeline(
        read_journal(os.path.join(d, "journal.rank0.jsonl")))
    inc = tl["incarnations"][0]
    assert inc["saves"] == [2]
    assert inc["reforms"] == [{"epoch": 1, "world": 4, "members": [0],
                               "restore_step": 2}]


# ---------------------------------------------------------------------------
# env contract + metrics
# ---------------------------------------------------------------------------
def test_env_contract_roundtrip(tmp_path):
    d = str(tmp_path)
    ctl = _make_ctl(d, 1)
    commit = fc.FleetCommit({"epoch": 3, "members": [0, 1], "world": 8,
                             "restore_step": 40})
    env = ctl.env_for_workers(commit)
    fl = fleet_env(env)
    assert fl is not None
    assert fl.dir == d and fl.epoch == 3 and fl.host == 1
    assert fl.hosts == [0, 1] and fl.world == 8
    assert fl.restore_step == 40
    assert fl.rank == 1 and fl.n_hosts == 2
    assert fleet_env({}) is None


def test_fleet_gauges_reach_prometheus(tmp_path):
    from paddle_tpu.core.monitor import prometheus_text
    ctl = _make_ctl(str(tmp_path), 0)
    commit = ctl.form(expect=[0])
    assert commit.members == [0]
    text = prometheus_text()
    assert "fleet_members 1" in text
    assert "fleet_epoch" in text and "fleet_reform_count" in text


def test_chaos_lose_host_parses():
    from paddle_tpu.testing import chaos
    os.environ["PADDLE_TPU_CHAOS"] = "lose_host@4:host=1"
    try:
        chaos.reload()
        assert chaos.enabled()
        d = chaos._directives()[0]
        assert d.kind == "lose_host" and d.step == 4 and d.rank == 1
    finally:
        del os.environ["PADDLE_TPU_CHAOS"]
        chaos.reload()


# ---------------------------------------------------------------------------
# rank-merged checkpoint load (satellite: _read world-mismatch routing)
# ---------------------------------------------------------------------------
def _two_host_checkpoint(root, step, state0, state1, extra=None):
    from paddle_tpu.checkpoint import CheckpointManager
    m0 = CheckpointManager(root, rank=0, world_size=2)
    m1 = CheckpointManager(root, rank=1, world_size=2)
    m0.save(step, state0, extra=extra or {}, sync=True)
    m1.save(step, state1, sync=True)
    m0.commit(step)
    m0.close()
    m1.close()


def test_load_merged_reassembles_rank_complete_state(tmp_path):
    from paddle_tpu.checkpoint import CheckpointManager
    root = str(tmp_path)
    w = np.arange(8).astype(np.float32)
    _two_host_checkpoint(root, 5,
                         {"w": w, "r0": np.ones(2, np.float32)},
                         {"w": w, "r1": np.full(2, 3.0, np.float32)},
                         extra={"program_fingerprint": "fp"})
    mm = CheckpointManager(root, rank=0, world_size=1)
    ck = mm.load()  # on_mismatch='convert' default routes through merge
    assert ck is not None and ck.step == 5
    assert sorted(ck.state) == ["r0", "r1", "w"]
    assert np.array_equal(ck.state["w"], w)
    assert np.array_equal(ck.state["r1"], np.full(2, 3.0, np.float32))
    assert ck.extra["merged_from_world"] == 2
    assert ck.extra["program_fingerprint"] == "fp"
    mm.close()


def test_load_merged_refuses_diverged_ranks(tmp_path):
    from paddle_tpu.checkpoint import CheckpointManager, CheckpointError
    root = str(tmp_path)
    w = np.arange(8).astype(np.float32)
    _two_host_checkpoint(root, 7, {"w": w}, {"w": w + 1})
    mm = CheckpointManager(root, rank=0, world_size=1)
    with pytest.raises(CheckpointError, match="differ between writer"):
        mm.load(step=7)
    mm.close()


def test_load_on_mismatch_error_names_both_worlds(tmp_path):
    from paddle_tpu.checkpoint import CheckpointManager, CheckpointError
    root = str(tmp_path)
    w = np.arange(8).astype(np.float32)
    _two_host_checkpoint(root, 3, {"w": w}, {"w": w})
    mm = CheckpointManager(root, rank=0, world_size=1)
    with pytest.raises(CheckpointError) as ei:
        mm.load(step=3, on_mismatch="error")
    assert "world of 2" in str(ei.value)
    assert "world of 1" in str(ei.value)
    with pytest.raises(ValueError):
        mm.load(on_mismatch="sideways")
    mm.close()


def test_load_on_mismatch_warn_keeps_old_behaviour(tmp_path):
    from paddle_tpu.checkpoint import CheckpointManager
    root = str(tmp_path)
    w = np.arange(8).astype(np.float32)
    _two_host_checkpoint(root, 3, {"w": w, "r0": np.ones(1, np.float32)},
                         {"w": w, "r1": np.ones(1, np.float32)})
    mm = CheckpointManager(root, rank=0, world_size=1)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        ck = mm.load(step=3, on_mismatch="warn")
    assert any("NOT merged" in str(w_.message) for w_ in caught)
    assert "r1" not in ck.state  # own shard only
    mm.close()


def test_load_merged_grown_world_serves_rankless_reader(tmp_path):
    """1 -> 2 growth: the new rank 1 has no shard of its own in the old
    layout; on_mismatch='convert' serves it the merged (complete)
    state."""
    from paddle_tpu.checkpoint import CheckpointManager
    root = str(tmp_path)
    w = np.arange(4).astype(np.float32)
    m = CheckpointManager(root)  # world_size=1 commit
    m.save(9, {"w": w}, sync=True)
    m.close()
    grown = CheckpointManager(root, rank=1, world_size=2)
    ck = grown.load(step=9)
    assert ck is not None and np.array_equal(ck.state["w"], w)
    grown.close()


def test_load_merged_unshards_recorded_zero_plan(tmp_path):
    """A recorded zero_shard_plan whose dp degree differs from the new
    world is routed through unshard_state to the plain layout (bucket
    padding is world-dependent); the plan leaves the sidecar."""
    from paddle_tpu.checkpoint import CheckpointManager
    plan = {"dp_degree": 2, "stage": 1, "buckets": [{
        "name": "zero1/b0_adam", "op_type": "adam", "dtype": "float32",
        "grad_dtype": "float32", "raw_len": 3, "padded_len": 4,
        "shard_len": 2,
        "params": [{"param": "fc.w", "grad": "fc.w@GRAD", "offset": 0,
                    "numel": 3, "shape": [3]}],
        "slots": {"moment1": "zero1/b0_adam@moment1"},
        "scalars": {},
        "orig_slots": {"fc.w": {"moment1": "fc.w_moment1_0"}},
        "grad_shard": "g", "param_bucket": None}]}
    root = str(tmp_path)
    m = CheckpointManager(root)
    m.save(4, {"fc.w": np.ones(3, np.float32),
               "zero1/b0_adam@moment1":
               np.array([1., 2., 3., 0.], np.float32)},
           extra={"zero_shard_plan": plan, "dp_degree": 2}, sync=True)
    m.close()
    mm = CheckpointManager(root)
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        ck = mm.load_merged(step=4, world=4)
    assert "zero_shard_plan" not in ck.extra
    assert np.array_equal(ck.state["fc.w_moment1_0"],
                          np.array([1., 2., 3.], np.float32))
    assert "zero1/b0_adam@moment1" not in ck.state
    mm.close()

"""Dygraph runtime tests: eager dispatch, tape autograd, Layer system.

Mirrors the reference's imperative tests
(/root/reference/python/paddle/fluid/tests/unittests/test_imperative_basic.py
 and test_imperative_auto_prune.py patterns): numerics checked against numpy
and against jax.grad ground truth.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.dygraph import (Tensor, to_tensor, no_grad, grad, Layer,
                                Sequential, trace_op)


def test_eager_basic_math():
    x = to_tensor(np.array([1.0, 2.0, 3.0], dtype=np.float32))
    y = to_tensor(np.array([4.0, 5.0, 6.0], dtype=np.float32))
    z = x * y + 2.0
    np.testing.assert_allclose(z.numpy(), [6.0, 12.0, 20.0], rtol=1e-6)
    assert z.stop_gradient  # no grad requested anywhere


def test_backward_simple():
    x = to_tensor(np.array([1.0, 2.0, 3.0], dtype=np.float32),
                  stop_gradient=False)
    y = (x * x).sum()
    assert not y.stop_gradient
    y.backward()
    np.testing.assert_allclose(x.gradient(), [2.0, 4.0, 6.0], rtol=1e-6)


def test_backward_chain_vs_jax():
    xv = np.random.RandomState(0).randn(4, 5).astype(np.float32)
    wv = np.random.RandomState(1).randn(5, 3).astype(np.float32)

    x = to_tensor(xv, stop_gradient=False)
    w = to_tensor(wv, stop_gradient=False)
    out = trace_op("matmul", {"X": x, "Y": w}, {}, ["Out"])
    act = trace_op("tanh", {"X": out}, {}, ["Out"])
    loss = act.mean()
    loss.backward()

    def ref(xv, wv):
        return jnp.mean(jnp.tanh(xv @ wv))

    gx, gw = jax.grad(ref, argnums=(0, 1))(xv, wv)
    np.testing.assert_allclose(x.gradient(), gx, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w.gradient(), gw, rtol=1e-5, atol=1e-6)


def test_grad_accumulation_and_clear():
    x = to_tensor(np.ones(3, dtype=np.float32), stop_gradient=False)
    (x * 2.0).sum().backward()
    (x * 3.0).sum().backward()
    np.testing.assert_allclose(x.gradient(), [5.0] * 3, rtol=1e-6)
    x.clear_gradient()
    assert x.grad is None


def test_stop_gradient_prunes():
    x = to_tensor(np.ones(3, dtype=np.float32), stop_gradient=False)
    y = to_tensor(np.ones(3, dtype=np.float32), stop_gradient=True)
    ((x + y) * 2.0).sum().backward()
    assert x.gradient() is not None
    assert y.gradient() is None


def test_no_grad_context():
    x = to_tensor(np.ones(3, dtype=np.float32), stop_gradient=False)
    with no_grad():
        y = (x * 2.0).sum()
    assert y.stop_gradient
    assert y._grad_node is None


def test_paddle_grad_api():
    x = to_tensor(np.array([2.0], dtype=np.float32), stop_gradient=False)
    y = x * x * x
    (gx,) = grad(y, x)
    np.testing.assert_allclose(gx.numpy(), [12.0], rtol=1e-6)
    assert x.grad is None  # paddle.grad must not touch .grad


def test_diamond_graph():
    # d = (x*2) + (x*3): both branches feed one consumer
    x = to_tensor(np.ones(2, dtype=np.float32), stop_gradient=False)
    a = x * 2.0
    b = x * 3.0
    d = (a + b).sum()
    d.backward()
    np.testing.assert_allclose(x.gradient(), [5.0, 5.0], rtol=1e-6)


def test_getitem_grad():
    x = to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3),
                  stop_gradient=False)
    y = x[0].sum()
    y.backward()
    np.testing.assert_allclose(x.gradient(),
                               [[1, 1, 1], [0, 0, 0]], rtol=1e-6)


def test_register_hook():
    x = to_tensor(np.ones(3, dtype=np.float32), stop_gradient=False)
    h = x.register_hook(lambda g: g * 10.0)
    (x * 1.0).sum().backward()
    np.testing.assert_allclose(x.gradient(), [10.0] * 3, rtol=1e-6)
    h.remove()


def test_dropout_fwd_bwd_mask_consistency():
    # mask positions in grad must equal mask in forward (shared PRNG key)
    paddle.seed(1234)
    x = to_tensor(np.ones((100,), dtype=np.float32), stop_gradient=False)
    out = trace_op("dropout", {"X": x},
                   {"dropout_prob": 0.5,
                    "dropout_implementation": "upscale_in_train"}, ["Out"])
    out.sum().backward()
    fwd_mask = np.asarray(out.numpy()) != 0
    grad_mask = np.asarray(x.gradient()) != 0
    np.testing.assert_array_equal(fwd_mask, grad_mask)


class _MLP(Layer):
    def __init__(self):
        super().__init__()
        self.w = self.create_parameter([4, 8])
        self.b = self.create_parameter([8], is_bias=True)

    def forward(self, x):
        return trace_op("elementwise_add",
                        {"X": trace_op("matmul", {"X": x, "Y": self.w},
                                       {}, ["Out"]),
                         "Y": self.b}, {"axis": -1}, ["Out"])


def test_layer_parameters_and_state_dict():
    m = _MLP()
    assert len(m.parameters()) == 2
    names = dict(m.named_parameters())
    assert set(names) == {"w", "b"}
    sd = m.state_dict()
    m2 = _MLP()
    m2.set_state_dict({k: v.numpy() for k, v in sd.items()})
    np.testing.assert_allclose(m2.w.numpy(), m.w.numpy())


def test_layer_forward_backward():
    m = _MLP()
    x = to_tensor(np.random.RandomState(0).randn(2, 4).astype(np.float32))
    out = m(x)
    out.mean().backward()
    assert m.w.gradient() is not None
    assert m.w.gradient().shape == (4, 8)
    m.clear_gradients()
    assert m.w.grad is None


def test_sequential_and_sublayers():
    m = Sequential(_MLP(), _MLP())
    assert len(m.sublayers()) == 2
    assert len(m.parameters()) == 4
    m.eval()
    assert all(not l.training for l in m.sublayers())
    m.train()
    assert all(l.training for l in m.sublayers())


def test_shared_parameter_dedup():
    m = Sequential(_MLP())
    m2 = Sequential(m[0])  # same underlying layer
    assert len(m2.parameters()) == 2


def test_backward_twice_raises():
    x = to_tensor(np.ones(2, dtype=np.float32), stop_gradient=False)
    y = (x * 2.0).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()
    # retain_graph allows it
    z = (x * 2.0).sum()
    z.backward(retain_graph=True)
    z.backward()
    np.testing.assert_allclose(x.gradient(), [6.0, 6.0], rtol=1e-6)


def test_no_grad_vars_blocks():
    x = to_tensor(np.ones(2, dtype=np.float32), stop_gradient=False)
    w = to_tensor(np.full(2, 3.0, dtype=np.float32), stop_gradient=False)
    y = x * w
    (gx,) = grad(y.sum(), x, no_grad_vars=[w])
    np.testing.assert_allclose(gx.numpy(), [3.0, 3.0], rtol=1e-6)


def test_sublayer_nonpersistable_buffer_excluded():
    class Sub(Layer):
        def __init__(self):
            super().__init__()
            self.register_buffer("tmp", np.zeros(2, np.float32),
                                 persistable=False)
            self.register_buffer("mu", np.ones(2, np.float32))

        def forward(self, x):
            return x

    class Top(Layer):
        def __init__(self):
            super().__init__()
            self.s = Sub()

        def forward(self, x):
            return x

    sd = Top().state_dict()
    assert "s.mu" in sd and "s.tmp" not in sd


def test_setattr_none_unregisters_sublayer():
    class M(Layer):
        def __init__(self):
            super().__init__()
            self.fc = _MLP()

        def forward(self, x):
            return x

    m = M()
    assert len(m.parameters()) == 2
    m.fc = None
    assert len(m.parameters()) == 0
    assert m.fc is None


def test_top_level_api_promoted():
    assert paddle.to_tensor is not None
    assert paddle.Tensor is Tensor
    t = paddle.to_tensor([1.0, 2.0])
    assert isinstance(t, Tensor)

"""FleetWrapper / BoxWrapper / HeterWrapper client classes (C24).

Reference: framework/fleet/{fleet_wrapper.h:66, box_wrapper.h:333,
heter_wrapper.h:54} — the industrial-PS client surface, here wrapping
the KV tier / HBM-table / KV-queue capabilities.
"""
import threading

import numpy as np

from paddle_tpu.distributed.fleet.utils.fleet_wrapper import (
    BoxWrapper, FleetWrapper, HeterWrapper)


def _server():
    from paddle_tpu.distributed.ps.kv_server import KVServer
    srv = KVServer("127.0.0.1:0")
    srv.serve_in_thread()
    return srv


def test_fleet_wrapper_sparse_round_trip():
    srv = _server()
    try:
        fw = FleetWrapper()
        fw.init_worker([srv.endpoint], trainer_id=0)
        V, D = 16, 4
        fw.init_table("fw_emb", np.zeros((V, D), np.float32),
                      optimizer="sgd")
        keys = np.array([2, 7, 2])
        vals = fw.pull_sparse_vars_sync("fw_emb", keys)
        assert vals.shape == (3, D) and not vals.any()
        # batch-size scaling: grad/batch applied server-side at lr 1
        g = np.ones((3, D), np.float32)
        fw.push_sparse_vars_async("fw_emb", keys, g, lr=1.0,
                                  batch_size=2)
        got = fw.pull_sparse_vars_sync("fw_emb", np.array([2, 7]))
        # key 2 pushed twice (duplicates merged): -2*(1/2); key 7 once
        np.testing.assert_allclose(got[0], -1.0 * np.ones(D))
        np.testing.assert_allclose(got[1], -0.5 * np.ones(D))
        # dense path
        fw._require_worker().init_param("w0", np.ones(3, np.float32))
        fw.push_dense_vars_async(["w0"], [np.full(3, 0.5, np.float32)],
                                 lr=1.0)
        (w0,) = fw.pull_dense_vars(["w0"])
        np.testing.assert_allclose(w0, 0.5 * np.ones(3))
        fw.stop_worker()
    finally:
        srv.stop()


def test_box_wrapper_device_resident_table():
    box = BoxWrapper()
    V, D = 8, 2
    box.create_table("box_emb", np.arange(V * D, dtype=np.float32)
                     .reshape(V, D))
    keys = np.array([[1, 3]])
    out = np.asarray(box.pull_sparse("box_emb", keys))
    np.testing.assert_allclose(out, [[[2, 3], [6, 7]]])
    box.push_sparse("box_emb", keys, np.ones((1, 2, D), np.float32),
                    lr=1.0)
    out2 = np.asarray(box.pull_sparse("box_emb", keys))
    np.testing.assert_allclose(out2, [[[1, 2], [5, 6]]])


def test_heter_wrapper_relay():
    srv = _server()
    try:
        a = HeterWrapper([srv.endpoint], timeout=20.0)
        b = HeterWrapper([srv.endpoint], timeout=20.0)

        def peer():
            x = b.recv("act")
            b.send("grad", x + 1.0)

        t = threading.Thread(target=peer)
        t.start()
        a.send("act", np.array([1.0, 2.0], np.float32))
        got = a.recv("grad")
        t.join(timeout=20)
        np.testing.assert_allclose(got, [2.0, 3.0])
        a.close()
        b.close()
    finally:
        srv.stop()

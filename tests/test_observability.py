"""Telemetry tier (docs/observability.md): run journal, rank heartbeats,
executor step telemetry, metrics sidecar, and the supervisor's
heartbeat stall deadline.

Tier-1 keeps the cheap units and the in-process integration (one train
step -> monitor gauges + journal events + heartbeat file).  The two
acceptance scenarios are ``slow``: a chaos-wedged rank (permanent
collective_fail) detected by the stall deadline and torn down by the
real launcher with elastic re-form, and a kill/resume 8->4->8 run whose
restart timeline reconstructs from the journals alone.
"""
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu import observability as obs
from paddle_tpu.core import monitor
from paddle_tpu.core.program import _reset_unique_names

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "elastic_worker.py")


def _build_train():
    from paddle_tpu.static import layers
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss)
    return main, startup, loss


# ---------------------------------------------------------------------------
# journal units
# ---------------------------------------------------------------------------
def test_journal_schema_and_seq_chain(tmp_path):
    j = obs.RunJournal(str(tmp_path), run_id="r1", rank=3)
    j.event("step", step=1, wall_ms=2.5)
    j.event("checkpoint_commit", step=1, path="/x")
    j.close()
    events = obs.read_journal(str(tmp_path / "journal.rank3.jsonl"))
    assert [e["kind"] for e in events] == ["step", "checkpoint_commit"]
    for e in events:
        assert e["v"] == 1 and e["run_id"] == "r1" and e["rank"] == 3
        assert "t" in e
    assert [e["seq"] for e in events] == [0, 1]


def test_journal_appends_across_incarnations(tmp_path):
    a = obs.RunJournal(str(tmp_path), run_id="runA", rank=0)
    a.event("step", step=1)
    a.close()
    b = obs.RunJournal(str(tmp_path), run_id="runB", rank=0)
    b.event("restore", step=1, global_step=1)
    b.event("step", step=2)
    b.close()
    events = obs.read_journal(str(tmp_path / "journal.rank0.jsonl"))
    assert len(events) == 3  # append-only: both incarnations, one file
    tl = obs.reconstruct_timeline(events)
    assert tl["n_incarnations"] == 2
    assert tl["incarnations"][0]["run_id"] == "runA"
    assert tl["incarnations"][1]["restored_step"] == 1
    assert tl["incarnations"][1]["steps"] == [2]


def test_journal_skips_torn_lines_strict_raises(tmp_path):
    path = str(tmp_path / "journal.rank0.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "step", "seq": 0}) + "\n")
        f.write('{"kind": "step", "se')  # SIGKILL mid-write
    events = obs.read_journal(path)
    assert len(events) == 1
    with pytest.raises(ValueError):
        obs.read_journal(path, strict=True)


def test_journal_append_after_sigkill_tear_seals_the_fragment(tmp_path):
    """A new incarnation appending onto a torn tail must not weld its
    run_start onto the fragment: the writer seals the tear with a
    newline, the reader skips the fragment, every later event parses."""
    path = str(tmp_path / "journal.rank0.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"kind": "step", "seq": 0,
                            "run_id": "a"}) + "\n")
        f.write('{"kind": "chaos", "direc')  # died mid-write
    j = obs.RunJournal(str(tmp_path), run_id="b", rank=0)
    j.event("restore", step=1)
    j.event("step", step=2)
    j.close()
    events = obs.read_journal(path)
    assert [e["kind"] for e in events] == ["step", "restore", "step"]
    tl = obs.reconstruct_timeline(events)
    assert tl["n_incarnations"] == 2


def test_journal_emit_disabled_is_noop(tmp_path, monkeypatch):
    monkeypatch.delenv(obs.JOURNAL_ENV, raising=False)
    obs.set_journal_dir(None)
    obs.emit("step", step=1)  # must not throw, must not create files
    assert obs.get_journal() is None


# ---------------------------------------------------------------------------
# heartbeat units
# ---------------------------------------------------------------------------
def test_heartbeat_write_read_and_stall(tmp_path):
    d = str(tmp_path)
    w0 = obs.HeartbeatWriter(d, rank=0)
    w1 = obs.HeartbeatWriter(d, rank=1)
    w0.beat(5)
    w1.beat(7)
    beats = obs.read_heartbeats(d)
    assert beats[0]["step"] == 5 and beats[1]["step"] == 7
    now = time.time()
    assert obs.stalled_ranks(d, 10.0, now=now) == []
    # age rank 1's beat past the deadline
    rec = json.load(open(obs.heartbeat.heartbeat_path(d, 1)))
    rec["t"] = now - 60
    json.dump(rec, open(obs.heartbeat.heartbeat_path(d, 1), "w"))
    assert obs.stalled_ranks(d, 10.0, now=now) == [1]
    # the live-ranks filter drops ranks the supervisor no longer owns
    assert obs.stalled_ranks(d, 10.0, ranks=[0], now=now) == []
    # a rank with no file yet (still compiling) is never stalled
    assert obs.stalled_ranks(d, 10.0, ranks=[0, 1, 2], now=now) == [1]


def test_watchdog_tears_down_stalled_rank(tmp_path):
    """watch_local_trainers with a heartbeat dir treats a stale-beat
    LIVE rank like a dead one: pod killed, RuntimeError raised."""
    from paddle_tpu.distributed.launch_utils import (TrainerProc,
                                                     watch_local_trainers)
    d = str(tmp_path)
    tp = TrainerProc()
    tp.proc = subprocess.Popen([sys.executable, "-c",
                                "import time; time.sleep(60)"])
    tp.rank = 0
    w = obs.HeartbeatWriter(d, rank=0)
    w.beat(1)
    try:
        # fresh beat: healthy
        alive = watch_local_trainers([tp], 1, heartbeat_dir=d,
                                     stall_timeout_s=30.0)
        assert [t.rank for t in alive] == [0]
        rec = json.load(open(obs.heartbeat.heartbeat_path(d, 0)))
        rec["t"] -= 3600
        json.dump(rec, open(obs.heartbeat.heartbeat_path(d, 0), "w"))
        with pytest.raises(RuntimeError, match="stalled"):
            watch_local_trainers([tp], 1, heartbeat_dir=d,
                                 stall_timeout_s=30.0)
        assert tp.proc.poll() is not None  # wedged rank was torn down
    finally:
        if tp.proc.poll() is None:
            tp.proc.kill()
            tp.proc.wait()


# ---------------------------------------------------------------------------
# monitor: collision guard + /stats compatibility
# ---------------------------------------------------------------------------
def test_monitor_refuses_cross_kind_name_collision():
    monitor.stat_add("obs.collide.counter")
    with pytest.raises(ValueError, match="already registered"):
        monitor.gauge_set("obs.collide.counter", 1.0)
    with pytest.raises(ValueError, match="already registered"):
        monitor.hist_observe("obs.collide.counter", 1.0)
    monitor.gauge_set("obs.collide.gauge", 2.0)
    with pytest.raises(ValueError, match="already registered"):
        monitor.stat_add("obs.collide.gauge")
    # same-kind re-registration stays legal, snapshot stays merged
    monitor.stat_add("obs.collide.counter", 2)
    snap = monitor.monitor_snapshot("obs.collide.")
    assert snap["obs.collide.counter"] == 3
    assert snap["obs.collide.gauge"] == 2.0
    monitor.stat_reset("obs.collide.counter")
    monitor.stat_reset("obs.collide.gauge")


# ---------------------------------------------------------------------------
# executor step telemetry (integration)
# ---------------------------------------------------------------------------
def test_train_step_telemetry_gauges_journal_heartbeat(tmp_path,
                                                       monkeypatch):
    jdir = str(tmp_path / "journal")
    hdir = str(tmp_path / "hb")
    monkeypatch.setenv(obs.HEARTBEAT_ENV, hdir)
    monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "1e9")
    obs.heartbeat._reset_for_tests()
    obs.set_journal_dir(jdir)
    try:
        main, startup, loss = _build_train()
        exe, scope = static.Executor(), static.Scope()
        rng = np.random.RandomState(0)
        feed = {"x": rng.rand(4, 8).astype(np.float32),
                "y": rng.rand(4, 1).astype(np.float32)}
        steps_before = monitor.stat_get("train.steps")
        with static.scope_guard(scope):
            exe.run(startup)  # startup is NOT a train step: no telemetry
            assert monitor.stat_get("train.steps") == steps_before
            for _ in range(3):
                exe.run(main, feed=feed, fetch_list=[loss])
        assert monitor.stat_get("train.steps") == steps_before + 3
        assert monitor.hist_snapshot("train.step_ms")["count"] >= 3
        assert monitor.gauge_get("train.tokens_per_sec") > 0
        assert monitor.gauge_get("train.mfu") > 0  # peak armed via env
        assert monitor.gauge_get("executor.retraces") >= 1
        assert monitor.gauge_get("hbm.predicted_peak_bytes") > 0
    finally:
        obs.set_journal_dir(None)
        obs.heartbeat._reset_for_tests()
    events = obs.read_rank_journals(jdir)[0]
    kinds = [e["kind"] for e in events]
    assert kinds.count("step") == 3
    assert "compile" in kinds
    step_ev = next(e for e in events if e["kind"] == "step")
    assert step_ev["wall_ms"] > 0 and step_ev["tokens_per_sec"] > 0
    beats = obs.read_heartbeats(hdir)
    assert beats[0]["beats"] == 3


def test_run_steps_telemetry_counts_micro_steps(tmp_path):
    obs.set_journal_dir(str(tmp_path))
    try:
        main, startup, loss = _build_train()
        exe, scope = static.Executor(), static.Scope()
        rng = np.random.RandomState(0)
        k = 4
        feed = {"x": rng.rand(k, 2, 8).astype(np.float32),
                "y": rng.rand(k, 2, 1).astype(np.float32)}
        before = monitor.stat_get("train.steps")
        with static.scope_guard(scope):
            exe.run(startup)
            exe.run_steps(main, feed=feed, fetch_list=[loss])
        assert monitor.stat_get("train.steps") == before + k
    finally:
        obs.set_journal_dir(None)
    events = obs.read_rank_journals(str(tmp_path))[0]
    step_ev = next(e for e in events if e["kind"] == "step")
    assert step_ev["micro_steps"] == k
    compile_ev = next(e for e in events if e["kind"] == "compile")
    assert compile_ev["mode"] == "run_steps"


def test_compiled_program_mfu_scales_by_mesh_chips(monkeypatch):
    """The MFU denominator must be chips * peak on a multi-device
    dispatch — a global-batch step priced against ONE chip's peak would
    read 8x the true MFU on the 8-device mesh."""
    import jax
    from paddle_tpu.distributed.compiled_program import CompiledProgram
    from paddle_tpu.static.executor import _wrapper_chips
    monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "1e9")
    main, startup, loss = _build_train()
    cp = CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    exe, scope = static.Executor(), static.Scope()
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(8, 8).astype(np.float32),
            "y": rng.rand(8, 1).astype(np.float32)}
    with static.scope_guard(scope):
        exe.run(startup)
        exe.run(cp, feed=feed, fetch_list=[loss])
    n_dev = len(jax.devices())
    assert _wrapper_chips(cp) == n_dev
    # an unbuilt wrapper (no mesh yet) falls back to 1
    assert _wrapper_chips(object()) == 1
    assert monitor.gauge_get("train.mfu") > 0


def test_chaos_injection_is_journaled(tmp_path, monkeypatch):
    from paddle_tpu.testing import chaos
    obs.set_journal_dir(str(tmp_path))
    try:
        monkeypatch.setenv(chaos.CHAOS_ENV, "collective_fail@7:times=1")
        chaos.reload()
        with pytest.raises(chaos.ChaosCollectiveError):
            chaos.collective_hook(7)
    finally:
        monkeypatch.setenv(chaos.CHAOS_ENV, "")
        chaos.reload()
        obs.set_journal_dir(None)
    events = obs.read_rank_journals(str(tmp_path))[0]
    fired = [e for e in events if e["kind"] == "chaos"]
    assert fired and fired[0]["directive"] == "collective_fail"
    assert fired[0]["step"] == 7


def test_chaos_collective_fail_rank_filter(monkeypatch):
    from paddle_tpu.testing import chaos
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    monkeypatch.setenv(chaos.CHAOS_ENV, "collective_fail@1:rank=1")
    chaos.reload()
    chaos.collective_hook(1)  # rank mismatch: no injection
    monkeypatch.setenv(chaos.CHAOS_ENV, "collective_fail@1:rank=0")
    chaos.reload()
    with pytest.raises(chaos.ChaosCollectiveError):
        chaos.collective_hook(1)
    monkeypatch.setenv(chaos.CHAOS_ENV, "")
    chaos.reload()


# ---------------------------------------------------------------------------
# metrics sidecar
# ---------------------------------------------------------------------------
def test_metrics_sidecar_scrape():
    monitor.stat_add("obs.sidecar.pings", 3)
    srv = obs.start_metrics_server(port=0)
    try:
        url = f"http://{srv.host}:{srv.port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/plain")
            body = r.read().decode()
        assert "obs_sidecar_pings_total" in body
        with urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}/healthz", timeout=10) as r:
            assert json.loads(r.read())["status"] == "ok"
    finally:
        srv.stop()
        monitor.stat_reset("obs.sidecar.pings")


# ---------------------------------------------------------------------------
# acceptance e2e (slow)
# ---------------------------------------------------------------------------
def _worker_env(**extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for k in ("PADDLE_TPU_CHAOS", obs.JOURNAL_ENV, obs.HEARTBEAT_ENV):
        env.pop(k, None)
    env.update(extra)
    return env


def _run_worker(root, out, world, steps, env=None, timeout=300):
    return subprocess.run(
        [sys.executable, WORKER, root, out, str(world), str(steps)],
        env=env or _worker_env(), capture_output=True, text=True,
        timeout=timeout)


@pytest.mark.slow
def test_wedged_rank_stall_detected_and_reformed(tmp_path, monkeypatch,
                                                 capfd):
    """THE wedge scenario: a permanent collective_fail leaves rank 1
    alive but wedged mid-step (retrying forever, heartbeat frozen).
    Process liveness says healthy; the heartbeat stall deadline says
    lost — the launcher tears the pod down and elastically re-forms
    from the survivor, which finishes the schedule."""
    from paddle_tpu.distributed import launch
    base = str(tmp_path)
    hb = os.path.join(base, "hb")
    steps = 4
    monkeypatch.setenv("PADDLE_TPU_ELASTIC_TEST_DIR", base)
    monkeypatch.setenv("ELASTIC_TOTAL_STEPS", str(steps))
    # rank 1 wedges at its 2nd train step and never recovers
    monkeypatch.setenv("PADDLE_TPU_CHAOS",
                       "collective_fail@2:times=1000000000:rank=1")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.setenv(obs.JOURNAL_ENV, os.path.join(base, "journal"))
    rc = launch.main(["--elastic", "--max_restarts", "2",
                      "--nproc_per_node", "2", "--term_grace", "30",
                      "--heartbeat_dir", hb, "--stall_timeout", "6",
                      "--log_dir", os.path.join(base, "logs"), WORKER])
    assert rc == 0
    err = capfd.readouterr().err
    assert "stalled: no heartbeat" in err, err[-2000:]
    # the re-formed (restart 1) pod ran one "host" = world 4 and finished
    out = os.path.join(base, "out_rank0_r1.json")
    assert os.path.exists(out), os.listdir(base)
    rep = json.load(open(out))
    assert rep["restart"] == 1 and rep["world"] == 4
    assert sorted(map(int, rep["losses"])) or rep["resumed_global"] >= 1
    # the wedged rank's journal recorded the injections and its retries
    journals = obs.read_rank_journals(os.path.join(base, "journal"))
    r1 = journals.get(1, [])
    assert any(e["kind"] == "chaos" and
               e["directive"] == "collective_fail" for e in r1)
    assert any(e["kind"] == "collective_retry" for e in r1)


@pytest.mark.slow
def test_kill_resume_timeline_reconstructs_from_journals(tmp_path):
    """Acceptance: a chaos kill/resume 8->4->8 elastic run is
    reconstructable post-hoc from the run journals ALONE — three
    incarnations, each resume's restore step, the topology reanchors,
    checkpoint commits and the injected kills, in order."""
    steps = 5
    root = str(tmp_path / "ckpts")
    jdir = str(tmp_path / "journal")
    env = lambda **kw: _worker_env(**{obs.JOURNAL_ENV: jdir, **kw})  # noqa: E731

    outA = str(tmp_path / "a.json")
    p = _run_worker(root, outA, 8, steps,
                    env=env(PADDLE_TPU_CHAOS="kill@2"))
    assert p.returncode == -signal.SIGKILL, (p.returncode, p.stderr[-2000:])
    outB = str(tmp_path / "b.json")
    p = _run_worker(root, outB, 4, steps,
                    env=env(PADDLE_TPU_CHAOS="kill@3:signal=term"))
    assert p.returncode == 143, (p.returncode, p.stderr[-2000:])
    outC = str(tmp_path / "c.json")
    p = _run_worker(root, outC, 8, steps, env=env())
    assert p.returncode == 0, p.stderr[-3000:]
    final = json.load(open(outC))

    events = obs.read_rank_journals(jdir)[0]
    tl = obs.reconstruct_timeline(events)
    assert tl["n_incarnations"] == 3, tl
    first, second, third = tl["incarnations"]
    # incarnation 1: fresh start (no restore), died to an injected kill
    assert first["restored_step"] is None
    assert any(c["directive"] == "kill" for c in first["chaos"])
    assert first["steps"], "no steps journaled before the kill"
    assert first["commits"], "no checkpoint commit before the kill"
    # incarnation 2: restored, re-anchored onto the 4-device world
    assert second["restored_step"] is not None
    assert any(r["world"] == 4 for r in second["reanchors"])
    # incarnation 3: restored again, re-anchored back to 8, ran to done
    assert third["restored_step"] is not None
    assert any(r["world"] == 8 for r in third["reanchors"])
    assert third["restored_global"] == final["resumed_global"]
    # the journal's step record is gap-free within each incarnation
    for inc in (first, second, third):
        seqs = [e["seq"] for e in events
                if e["run_id"] == inc["run_id"]]
        assert seqs == list(range(len(seqs))), inc["run_id"]

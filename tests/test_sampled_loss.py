"""Sampled-loss family: nce / hierarchical_sigmoid / sampled softmax
(reference: fluid/tests/unittests/test_nce.py, test_hsigmoid_op.py,
test_sample_logits_op.py; ops: nce_op.h:84, hierarchical_sigmoid_op.h:70,
sample_logits_op.cc)."""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers


def _run(main, startup, feed, fetch):
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        return exe.run(main, feed=feed, fetch_list=fetch)


def np_hsigmoid(xv, lv, wv, bv, V):
    """Reference bit-code math (matrix_bit_code.h:106 SimpleCode +
    hierarchical_sigmoid_op.h:118 softrelu CE, incl. the out-of-path
    log(2) terms the reference keeps)."""
    Bn = xv.shape[0]
    code_len = (V - 1).bit_length()
    out = np.zeros((Bn, 1), np.float64)
    for i in range(Bn):
        c = int(lv[i, 0]) + V
        length = c.bit_length() - 1
        for j in range(code_len):
            if j < length:
                idx = (c >> (j + 1)) - 1
                bit = (c >> j) & 1
                p = np.clip(xv[i] @ wv[idx] + bv[idx, 0], -40, 40)
            else:
                p, bit = 0.0, 0
            out[i, 0] += np.log1p(np.exp(p)) - bit * p
    return out


def test_hsigmoid_matches_numpy_and_fd():
    B, D, V = 4, 6, 10
    rng = np.random.RandomState(0)
    xv = rng.rand(B, D).astype(np.float32)
    lv = rng.randint(0, V, (B, 1)).astype(np.int64)
    wv = rng.rand(V - 1, D).astype(np.float32)
    bv = rng.rand(V - 1, 1).astype(np.float32)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, D])
        lab = layers.data("lab", [-1, 1], dtype="int64")
        out = layers.hsigmoid(
            x, lab, V,
            param_attr=static.ParamAttr(
                name="hs_w", initializer=static.NumpyArrayInitializer(wv)),
            bias_attr=static.ParamAttr(
                name="hs_b", initializer=static.NumpyArrayInitializer(bv)))
        loss = layers.mean(out)
        grads = static.append_backward(loss)
    gw_name = [g.name for p, g in grads if p.name == "hs_w"][0]
    o, _, gw = _run(main, startup, {"x": xv, "lab": lv},
                    [out, loss, gw_name])

    ref = np_hsigmoid(xv.astype(np.float64), lv, wv.astype(np.float64),
                      bv.astype(np.float64), V)
    np.testing.assert_allclose(np.asarray(o), ref, rtol=1e-4, atol=1e-5)

    gw = np.asarray(gw)
    assert np.isfinite(gw).all()
    eps = 1e-3
    for (r, cidx) in [(0, 0), (3, 2)]:
        wp, wm = wv.copy(), wv.copy()
        wp[r, cidx] += eps
        wm[r, cidx] -= eps
        fd = (np_hsigmoid(xv, lv, wp, bv, V).mean()
              - np_hsigmoid(xv, lv, wm, bv, V).mean()) / (2 * eps)
        np.testing.assert_allclose(gw[r, cidx], fd, rtol=2e-2, atol=1e-4)


def test_hsigmoid_custom_tree():
    # explicit PathTable/PathCode (CustomCode): a 4-class tree
    B, D, V = 3, 5, 4
    rng = np.random.RandomState(1)
    xv = rng.rand(B, D).astype(np.float32)
    lv = np.array([[0], [2], [3]], np.int64)
    # class c path: node ids / branch bits, padded with -1
    table = np.array([[0, 1, -1], [0, 2, -1], [0, 2, 1]], np.int64)
    code = np.array([[0, 0, 0], [1, 0, 0], [1, 1, 1]], np.int64)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, D])
        lab = layers.data("lab", [-1, 1], dtype="int64")
        pt = layers.data("pt", [-1, 3], dtype="int64")
        pc = layers.data("pc", [-1, 3], dtype="int64")
        out = layers.hsigmoid(
            x, lab, V, is_custom=True, path_table=pt, path_code=pc,
            param_attr=static.ParamAttr(name="hsc_w"),
            bias_attr=False)
    (o,) = _run(main, startup,
                {"x": xv, "lab": lv, "pt": table, "pc": code}, [out])
    o = np.asarray(o)
    assert o.shape == (B, 1) and np.isfinite(o).all() and (o > 0).all()


def test_nce_trains_down():
    B, D, V = 8, 6, 12
    rng = np.random.RandomState(0)
    xv = rng.rand(B, D).astype(np.float32)
    lv = rng.randint(0, V, (B, 1)).astype(np.int64)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, D])
        lab = layers.data("lab", [-1, 1], dtype="int64")
        cost = layers.nce(x, lab, num_total_classes=V, num_neg_samples=5,
                          sampler="log_uniform", seed=1)
        loss = layers.mean(cost)
        static.SGD(learning_rate=0.1).minimize(loss)
    exe = static.Executor()
    scope = static.Scope()
    losses = []
    with static.scope_guard(scope):
        exe.run(startup)
        for _ in range(60):
            (lval,) = exe.run(main, feed={"x": xv, "lab": lv},
                              fetch_list=[loss])
            losses.append(float(lval))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_nce_custom_dist_and_uniform():
    B, D, V = 4, 5, 8
    rng = np.random.RandomState(2)
    xv = rng.rand(B, D).astype(np.float32)
    lv = rng.randint(0, V, (B, 1)).astype(np.int64)
    for sampler, dist in (("uniform", None),
                          ("custom_dist", [1.0 / 8] * 8)):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = layers.data("x", [-1, D])
            lab = layers.data("lab", [-1, 1], dtype="int64")
            cost = layers.nce(x, lab, num_total_classes=V,
                              num_neg_samples=3, sampler=sampler,
                              custom_dist=dist, seed=5)
        (c,) = _run(main, startup, {"x": xv, "lab": lv}, [cost])
        c = np.asarray(c)
        assert c.shape == (B, 1) and np.isfinite(c).all() and (c > 0).all()


def test_sampled_softmax_trains_down():
    B, D, V = 8, 6, 12
    rng = np.random.RandomState(0)
    xv = rng.rand(B, D).astype(np.float32)
    lv = rng.randint(0, V, (B, 1)).astype(np.int64)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, D])
        lab = layers.data("lab", [-1, 1], dtype="int64")
        logits = layers.fc(x, V)
        sloss = layers.mean(layers.sampled_softmax_with_cross_entropy(
            logits, lab, num_samples=6, seed=3))
        static.SGD(learning_rate=0.2).minimize(sloss)
    exe = static.Executor()
    scope = static.Scope()
    losses = []
    with static.scope_guard(scope):
        exe.run(startup)
        for _ in range(80):
            (lval,) = exe.run(main, feed={"x": xv, "lab": lv},
                              fetch_list=[sloss])
            losses.append(float(lval))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_book_word2vec_nce_variant():
    """book/test_word2vec.py variant using the NCE loss head
    (VERDICT round-2 item 7): learnable synthetic n-gram task, loss
    must fall."""
    vocab, emb_dim, ctx_n = 40, 16, 4
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ctx = layers.data("ctx", [-1, ctx_n], dtype="int64")
        nxt = layers.data("next", [-1, 1], dtype="int64")
        e = layers.embedding(ctx, size=[vocab, emb_dim])
        flat = layers.reshape(e, [-1, ctx_n * emb_dim])
        h = layers.fc(flat, size=32, act="relu")
        cost = layers.nce(h, nxt, num_total_classes=vocab,
                          num_neg_samples=8, sampler="log_uniform",
                          seed=7)
        loss = layers.mean(cost)
        static.Adam(learning_rate=5e-3).minimize(loss)

    rng = np.random.RandomState(0)
    exe = static.Executor()
    scope = static.Scope()
    losses = []
    with static.scope_guard(scope):
        exe.run(startup)
        for i in range(80):
            c = rng.randint(0, vocab, (32, ctx_n)).astype(np.int64)
            n = c[:, :1]  # next word = first context word (learnable)
            (lval,) = exe.run(main, feed={"ctx": c, "next": n},
                              fetch_list=[loss])
            losses.append(float(lval))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])

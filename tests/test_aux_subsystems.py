"""Auxiliary subsystems (SURVEY.md §5): profiler, flags, monitor,
auto-checkpoint, debugger, NaN check."""
import json
import os

import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.static as static
from paddle_tpu.static import layers


def test_flags_get_set_roundtrip():
    v = paddle_tpu.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]
    assert v is False
    paddle_tpu.set_flags({"FLAGS_check_nan_inf": True})
    assert paddle_tpu.get_flags(["check_nan_inf"])["check_nan_inf"] is True
    paddle_tpu.set_flags({"FLAGS_check_nan_inf": False})
    with pytest.raises(ValueError):
        paddle_tpu.get_flags("FLAGS_not_a_flag")
    # parity flags registered
    assert paddle_tpu.get_flags("FLAGS_fraction_of_gpu_memory_to_use")


def test_monitor_counters():
    from paddle_tpu.core.monitor import stat_add, stat_get, stat_reset
    stat_reset()
    stat_add("my_counter", 3)
    stat_add("my_counter")
    assert stat_get("my_counter") == 4
    stat_reset("my_counter")
    assert stat_get("my_counter") == 0


def test_profiler_records_and_exports(tmp_path, capsys):
    from paddle_tpu import profiler as prof
    path = str(tmp_path / "profile")
    with prof.profiler(state="CPU", profile_path=path):
        with prof.RecordEvent("my_block"):
            _ = sum(range(1000))
    out = capsys.readouterr().out
    assert "my_block" in out
    with open(path + ".json") as f:
        trace = json.load(f)
    assert any(e["name"] == "my_block" for e in trace["traceEvents"])


def test_executor_records_events_and_stats():
    from paddle_tpu.core.monitor import stat_get, stat_reset
    stat_reset()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4])
        y = layers.fc(x, 2)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[y])
    assert stat_get("executor_run_times") >= 1


def test_nan_inf_check_raises():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 2])
        y = layers.log(x)  # log(-1) = nan
    exe = static.Executor()
    scope = static.Scope()
    paddle_tpu.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with static.scope_guard(scope):
            exe.run(startup)
            with pytest.raises(RuntimeError, match="non-finite"):
                exe.run(main, feed={"x": -np.ones((2, 2), np.float32)},
                        fetch_list=[y])
    finally:
        paddle_tpu.set_flags({"FLAGS_check_nan_inf": False})


def test_debugger_dot_dump(tmp_path):
    from paddle_tpu.utils import draw_block_graphviz, print_program
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4])
        y = layers.fc(x, 2)
        loss = layers.mean(y)
        static.SGD(learning_rate=0.1).minimize(loss)
    p = str(tmp_path / "g.dot")
    draw_block_graphviz(main.global_block(), path=p)
    dot = open(p).read()
    assert "digraph G" in dot and "mul" in dot
    text = print_program(main, skip_vars=True)
    assert "sgd" in text


def test_checkpoint_saver_roundtrip(tmp_path):
    from paddle_tpu.incubate.checkpoint import (CheckpointSaver,
                                                SerializableBase)

    class Obj(SerializableBase):
        def __init__(self, v):
            self.v = v

        def serialize(self, path):
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "v.json"), "w") as f:
                json.dump(self.v, f)

        def deserialize(self, path):
            with open(os.path.join(path, "v.json")) as f:
                self.v = json.load(f)

    root = str(tmp_path / "ckpt")
    saver = CheckpointSaver()
    for i in range(5):
        saver.save_checkpoint(root, [Obj(i)], max_keep=3)
    assert saver.get_last_checkpoint_no(root) == 4
    o = Obj(None)
    saver.load_checkpoint(root, [o])
    assert o.v == 4
    # pruned to max_keep
    import glob
    assert len(glob.glob(os.path.join(root, "__paddle_checkpoint__.*"))) == 3


def test_auto_checkpoint_resume(tmp_path, monkeypatch):
    """Kill-and-restart epoch resume (reference test_auto_checkpoint.py)."""
    monkeypatch.setenv("PADDLE_RUNNING_ENV", "PADDLE_EDL_AUTO_CHECKPOINT")
    monkeypatch.setenv("PADDLE_JOB_ID", "job_test_1")
    monkeypatch.setenv("PADDLE_EDL_HDFS_CHECKPOINT_PATH", str(tmp_path))
    monkeypatch.setenv("PADDLE_EDL_SAVE_CHECKPOINT_INTER", "0")
    import paddle_tpu.incubate.checkpoint.auto_checkpoint as acp
    acp.g_checker = None  # re-read env

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4])
        pred = layers.fc(x, 1)
        loss = layers.mean(layers.square(pred))
        static.SGD(learning_rate=0.1).minimize(loss)
    exe = static.Executor()
    scope = static.Scope()
    xb = np.ones((4, 4), np.float32)
    seen = []
    with static.scope_guard(scope):
        exe.run(startup)
        for epoch in acp.train_epoch_range(3):
            seen.append(epoch)
            exe.run(main, feed={"x": xb}, fetch_list=[loss])
            if epoch == 1:
                break  # simulated failure DURING epoch 1 (before its
                # end-of-epoch checkpoint commits)
    assert seen == [0, 1]

    w_name = main.all_parameters()[0].name
    with static.scope_guard(scope):
        w_trained = np.asarray(scope.get(w_name)).copy()

    # restart: epoch 0 committed, the interrupted epoch 1 re-runs — and
    # the checkpointed WEIGHTS are restored, not reinitialized
    acp.g_checker = None
    seen2 = []
    scope2 = static.Scope()
    with static.scope_guard(scope2):
        exe2 = static.Executor()
        exe2.run(startup)
        w_fresh = np.asarray(scope2.get(w_name)).copy()
        for epoch in acp.train_epoch_range(3):
            if not seen2:
                # first executor.run of the resumed job attaches + restores
                exe2.run(main, feed={"x": xb}, fetch_list=[loss])
                w_resumed = np.asarray(scope2.get(w_name))
            else:
                exe2.run(main, feed={"x": xb}, fetch_list=[loss])
            seen2.append(epoch)
    assert seen2 == [1, 2], seen2
    # resumed weights came from the checkpoint (epoch-0 trained state),
    # not the fresh same-seed init the startup program produced
    assert not np.allclose(w_resumed, w_fresh)


def test_per_op_nan_scan_names_offending_op():
    """Eager mode + FLAGS_check_nan_inf: the error must name the op that
    produced the NaN (reference nan_inf_utils_detail per-op scan)."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 2])
        y = layers.log(x)          # log(-1) = nan  <- offending op
        z = layers.scale(y, 2.0)   # downstream op must not be blamed
    exe = static.Executor()
    scope = static.Scope()
    paddle_tpu.set_flags({"FLAGS_check_nan_inf": True,
                          "FLAGS_eager_run": True})
    try:
        with static.scope_guard(scope):
            exe.run(startup)
            with pytest.raises(RuntimeError, match="op 'log'"):
                exe.run(main, feed={"x": -np.ones((2, 2), np.float32)},
                        fetch_list=[z])
    finally:
        paddle_tpu.set_flags({"FLAGS_check_nan_inf": False,
                              "FLAGS_eager_run": False})


def test_explicit_program_roles():
    """program_guard stamps the two-program contract: a startup program
    containing non-init ops still runs eagerly; a main program containing
    only init ops still takes the jit path."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 2])
        layers.scale(x, 2.0)
    assert main._role == "main" and startup._role == "startup"
    exe = static.Executor()
    # a startup program with a non-init op (scale after init) is still
    # treated as startup
    with static.program_guard(static.Program(), static.Program()):
        pass
    sp = static.Program()
    sp._role = "startup"
    assert exe._program_is_startup(sp)
    mp = static.Program()
    mp._role = "main"
    assert not exe._program_is_startup(mp)


def test_install_check():
    from paddle_tpu.install_check import run_check
    run_check()  # raises on failure


def test_data_feeder():
    import numpy as np
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    from paddle_tpu.io import DataFeeder
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        img = layers.data("img", [-1, 4])
        lbl = layers.data("lbl", [-1, 1], dtype="int64")
        pred = layers.fc(img, 3)
    feeder = DataFeeder(feed_list=[img, lbl])
    batch = [(np.ones(4) * i, [i % 3]) for i in range(5)]
    feed = feeder.feed(batch)
    assert feed["img"].shape == (5, 4) and feed["img"].dtype == np.float32
    assert feed["lbl"].shape == (5, 1) and feed["lbl"].dtype == np.int64
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        (out,) = exe.run(main, feed=feed, fetch_list=[pred])
    assert np.asarray(out).shape == (5, 3)


def test_weighted_average():
    import pytest
    from paddle_tpu.utils import WeightedAverage
    wa = WeightedAverage()
    with pytest.raises(ValueError):
        wa.eval()
    wa.add(1.0, weight=1)
    wa.add(3.0, weight=3)
    assert abs(wa.eval() - 2.5) < 1e-9
    wa.reset()
    wa.add([2.0, 4.0])  # arrays reduce to their mean
    assert abs(wa.eval() - 3.0) < 1e-9

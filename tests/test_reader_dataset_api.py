"""Fluid-era data pipeline parity: paddle.reader decorators,
paddle.batch, and the paddle.dataset reader-creator modules (reference
python/paddle/reader/decorator.py, batch.py, dataset/)."""
import gzip
import os
import struct

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import reader as rdr
from paddle_tpu import batch


def _range_reader(n):
    def reader():
        for i in range(n):
            yield i

    return reader


def test_map_shuffle_chain_compose():
    doubled = rdr.map_readers(lambda x: 2 * x, _range_reader(5))
    assert list(doubled()) == [0, 2, 4, 6, 8]

    import random
    random.seed(0)
    shuffled = list(rdr.shuffle(_range_reader(10), 4)())
    assert sorted(shuffled) == list(range(10)) and shuffled != list(
        range(10))

    chained = rdr.chain(_range_reader(2), _range_reader(3))
    assert list(chained()) == [0, 1, 0, 1, 2]

    composed = rdr.compose(_range_reader(3),
                           rdr.map_readers(lambda x: (x, x * x),
                                           _range_reader(3)))
    assert list(composed()) == [(0, 0, 0), (1, 1, 1), (2, 2, 4)]
    with pytest.raises(rdr.ComposeNotAligned):
        list(rdr.compose(_range_reader(2), _range_reader(3))())


def test_buffered_firstn_cache_xmap():
    assert list(rdr.buffered(_range_reader(7), 3)()) == list(range(7))
    assert list(rdr.firstn(_range_reader(100), 4)()) == [0, 1, 2, 3]

    calls = []

    def counting_reader():
        calls.append(1)
        return iter(range(3))

    cached = rdr.cache(counting_reader)
    assert list(cached()) == [0, 1, 2]
    assert list(cached()) == [0, 1, 2]
    assert calls == [1]  # source consumed exactly once

    mapped = sorted(rdr.xmap_readers(lambda x: x + 10, _range_reader(20),
                                     process_num=3, buffer_size=4)())
    assert mapped == [x + 10 for x in range(20)]
    ordered = list(rdr.xmap_readers(lambda x: x * 3, _range_reader(20),
                                    process_num=3, buffer_size=4,
                                    order=True)())
    assert ordered == [x * 3 for x in range(20)]


def test_reader_errors_propagate():
    def bad_reader():
        yield 1
        raise RuntimeError("corrupt sample")

    with pytest.raises(RuntimeError, match="corrupt sample"):
        list(rdr.buffered(bad_reader, 2)())

    def bad_mapper(x):
        if x == 5:
            raise ValueError("mapper blew up")
        return x

    with pytest.raises(ValueError, match="mapper blew up"):
        list(rdr.xmap_readers(bad_mapper, _range_reader(10), 2, 4)())
    with pytest.raises(ValueError, match="mapper blew up"):
        list(rdr.xmap_readers(bad_mapper, _range_reader(10), 2, 4,
                              order=True)())


def test_batch():
    b = batch(_range_reader(7), 3)
    assert list(b()) == [[0, 1, 2], [3, 4, 5], [6]]
    b2 = batch(_range_reader(7), 3, drop_last=True)
    assert list(b2()) == [[0, 1, 2], [3, 4, 5]]
    with pytest.raises(ValueError):
        batch(_range_reader(3), 0)


def _write_idx_mnist(tmp_path, n=8):
    imgs = np.arange(n * 28 * 28, dtype=np.uint8).reshape(n, 28, 28)
    labels = (np.arange(n) % 10).astype(np.uint8)
    ip = os.path.join(tmp_path, "train-images-idx3-ubyte.gz")
    lp = os.path.join(tmp_path, "train-labels-idx1-ubyte.gz")
    with gzip.open(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28))
        f.write(imgs.tobytes())
    with gzip.open(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, n))
        f.write(labels.tobytes())
    return ip, lp


def test_dataset_mnist_reader(tmp_path, monkeypatch):
    from paddle_tpu.dataset import mnist
    ip, lp = _write_idx_mnist(str(tmp_path))
    # point DATA_HOME's mnist dir at the fixture
    import paddle_tpu.vision.datasets as vd
    monkeypatch.setattr(vd, "DATA_HOME", str(tmp_path))
    os.makedirs(os.path.join(str(tmp_path), "mnist"), exist_ok=True)
    os.rename(ip, os.path.join(str(tmp_path), "mnist",
                               "train-images-idx3-ubyte.gz"))
    os.rename(lp, os.path.join(str(tmp_path), "mnist",
                               "train-labels-idx1-ubyte.gz"))
    samples = list(mnist.train()())
    assert len(samples) == 8
    img, label = samples[0]
    assert img.shape == (784,) and img.dtype == np.float32
    assert img.min() >= -1.0 and img.max() <= 1.0
    assert isinstance(label, int)

    # the canonical composed pipeline
    pipeline = paddle_tpu.batch(rdr.shuffle(mnist.train(), 4), 3)
    batches = list(pipeline())
    assert sum(len(b) for b in batches) == 8


def test_dataset_common_split_and_cluster(tmp_path, monkeypatch):
    from paddle_tpu.dataset import common
    monkeypatch.chdir(tmp_path)
    common.split(_range_reader(10), 4,
                 suffix=str(tmp_path / "chunk-%05d.pickle"))
    import glob
    files = sorted(glob.glob(str(tmp_path / "chunk-*.pickle")))
    assert len(files) >= 2
    r0 = common.cluster_files_reader(
        str(tmp_path / "chunk-*.pickle"), trainer_count=2, trainer_id=0)
    r1 = common.cluster_files_reader(
        str(tmp_path / "chunk-*.pickle"), trainer_count=2, trainer_id=1)
    assert sorted(list(r0()) + list(r1())) == list(range(10))


def test_dataset_mq2007(tmp_path):
    letor = (
        "2 qid:1 1:0.1 2:0.2 #docid=a\n"
        "0 qid:1 1:0.3 2:0.1 #docid=b\n"
        "1 qid:2 1:0.5 2:0.5 #docid=c\n"
    )
    p = tmp_path / "train.txt"
    p.write_text(letor)
    from paddle_tpu.dataset import mq2007
    points = list(mq2007.train(format="pointwise",
                               data_file=str(p))())
    assert len(points) == 3 and points[0][1] == 2
    pairs = list(mq2007.train(format="pairwise", data_file=str(p))())
    # only qid:1 has a comparable pair (rel 2 vs 0)
    assert len(pairs) == 1
    one, hi, lo = pairs[0]
    np.testing.assert_allclose(hi, [0.1, 0.2])
    lists = list(mq2007.train(format="listwise", data_file=str(p))())
    assert len(lists) == 2 and lists[0][0] == [2, 0]


def test_dataset_image_transform():
    from paddle_tpu.dataset import image as img
    im = np.random.RandomState(0).randint(
        0, 255, (32, 48, 3), np.uint8)
    r = img.resize_short(im, 16)
    assert min(r.shape[:2]) == 16 and r.shape[1] > r.shape[0]
    c = img.center_crop(r, 16)
    assert c.shape[:2] == (16, 16)
    out = img.simple_transform(im, 24, 16, is_train=False)
    assert out.shape == (3, 16, 16) and out.dtype == np.float32

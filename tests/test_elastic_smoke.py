"""Tier-1 elastic-resume gate (NOT marked slow — losing the ability to
resume a preempted job on a shrunk mesh must fail the suite, not wait
for the next real preemption).

Drives tools/elastic_smoke.py: elasticized training on the full
8-device mesh with per-step checkpoints, "kill", topology-shifted
restore onto 4 devices, continue on re-bucketed micro-feeds — loss
trace and params must be BITWISE equal to the uninterrupted run.  The
full chaos-driven 8→4→8 kill/shrink/regrow matrix is in
tests/test_elastic.py (slow).
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_elastic_smoke_gate(tmp_path):
    import elastic_smoke
    result = elastic_smoke.run_smoke(steps=4, kill_at=2,
                                     root=str(tmp_path / "ckpts"))
    assert result["bitwise_loss_trace"] is True, result
    assert result["bitwise_params"] is True, result
    assert result["value"] == 4 and result["logical_dp"] == 8, result
    # the 25 s tier-1 budget is dominated by mesh COMPILES, which are
    # host-load dependent (the shard_smoke precedent: report, don't
    # hard-assert) — wall_s is reported in the JSON; the assertion here
    # is a generous hang guard only (typical: ~5 s)
    assert result["wall_s"] < 120, result


@pytest.mark.slow  # duplicates the in-process gate via a subprocess
def test_elastic_smoke_cli_prints_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "elastic_smoke.py"),
         "--steps", "4", "--kill-at", "2"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["bitwise_loss_trace"] is True
    assert result["resumed_checkpoint_step"] is not None

"""Gloo-analog tests (C11): real 2-process barrier + all_gather over the
FILE rendezvous, plus the KV-server HTTP-store path in-process."""
import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest


def test_gloo_file_store_two_processes(tmp_path):
    worker = textwrap.dedent("""
        import json, os, sys
        sys.path.insert(0, %r)
        os.environ["JAX_PLATFORMS"] = "cpu"
        from paddle_tpu.distributed.gloo import Gloo, RENDEZVOUS
        rank = int(sys.argv[1]); path = sys.argv[2]
        g = Gloo()
        g.init(RENDEZVOUS.FILE, "worker", rank, 2,
               kwargs={"dfs.path": path})
        g.barrier()
        got = g.all_gather({"rank": rank, "val": rank * 10})
        s = g.all_reduce(rank + 1, "sum")
        g.barrier()
        with open(os.path.join(path, f"out{rank}.json"), "w") as f:
            json.dump({"gather": got, "sum": int(s)}, f)
    """ % os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    script = tmp_path / "gloo_worker.py"
    script.write_text(worker)
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(r), str(tmp_path)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    outs = [p.communicate(timeout=120)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), \
        [o[-1500:].decode() for o in outs]
    for r in range(2):
        with open(tmp_path / f"out{r}.json") as f:
            res = json.load(f)
        assert res["gather"] == [{"rank": 0, "val": 0},
                                 {"rank": 1, "val": 10}]
        assert res["sum"] == 3


def test_gloo_kv_store_roundtrip():
    from paddle_tpu.distributed.gloo import Gloo, RENDEZVOUS
    from paddle_tpu.distributed.ps.kv_server import KVServer
    srv = KVServer("127.0.0.1:0", num_trainers=1)
    srv.serve_in_thread()
    try:
        host, port = srv.endpoint.rsplit(":", 1)
        g = Gloo()
        g.init(RENDEZVOUS.HTTP, "worker", 0, 1,
               kwargs={"http.host": host, "http.port": port})
        g.barrier()
        assert g.all_gather([1, "two"]) == [[1, "two"]]
        np.testing.assert_allclose(g.all_reduce(np.ones(3), "sum"),
                                   np.ones(3))
    finally:
        srv.stop()


def test_role_maker_uses_gloo_env(tmp_path, monkeypatch):
    from paddle_tpu.distributed.fleet.base import role_maker as rm
    # instance created BEFORE the env is set must not poison later ones
    pre = rm.PaddleCloudRoleMaker(is_collective=True)
    assert pre._get_gloo() is None
    monkeypatch.setenv("PADDLE_GLOO_RENDEZVOUS", "2")
    monkeypatch.setenv("PADDLE_GLOO_FS_PATH", str(tmp_path))
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
    r = rm.PaddleCloudRoleMaker(is_collective=True)
    r._barrier()
    assert r._all_gather("x") == ["x"]
    assert r._get_gloo() is not None


def test_gloo_from_env_server_role(tmp_path, monkeypatch):
    """Review r4: server-role rank/size come from the PSERVER env, not
    the trainer vars (two servers must not both be rank 0 of world 2)."""
    from paddle_tpu.distributed.gloo import gloo_from_env
    monkeypatch.setenv("PADDLE_GLOO_RENDEZVOUS", "2")
    monkeypatch.setenv("PADDLE_GLOO_FS_PATH", str(tmp_path))
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "3")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST",
                       "10.0.0.1:7000,10.0.0.2:7000")
    monkeypatch.setenv("POD_IP", "10.0.0.2")
    monkeypatch.setenv("PADDLE_PORT", "7000")
    g = gloo_from_env("server")
    assert g.rank() == 1 and g.size() == 2
    gw = gloo_from_env("worker")
    assert gw.rank() == 1 and gw.size() == 3

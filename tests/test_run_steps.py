"""Executor.run_steps: K training steps scanned inside one jitted
dispatch (device-resident training loop).

TPU-first redesign of the reference's in-runtime trainer loop
(paddle/fluid/framework/trainer.h:1 MultiTrainer::Run — the C++ side
loops batches without returning to Python); here the loop is compiled
onto the device with lax.scan so one dispatch covers K optimizer steps.
Measured motivation (r5, axon tunnel): ~300 ms/step dispatch overhead vs
155 ms/step device compute at BERT-base batch 32.
"""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers


def _build(lr=0.1, seed=0):
    main, startup = static.Program(), static.Program()
    main.random_seed = startup.random_seed = seed
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.SGD(learning_rate=lr).minimize(loss)
    return main, startup, loss


def _data(k, batch=4):
    rng = np.random.RandomState(7)
    xs = rng.rand(k, batch, 8).astype(np.float32)
    ys = xs.sum(2, keepdims=True).astype(np.float32)
    return xs, ys


def test_run_steps_matches_sequential():
    K = 6
    xs, ys = _data(K)

    main, startup, loss = _build()
    exe, sc = static.Executor(), static.Scope()
    seq_losses = []
    with static.scope_guard(sc):
        exe.run(startup)
        for i in range(K):
            (lv,) = exe.run(main, feed={"x": xs[i], "y": ys[i]},
                            fetch_list=[loss])
            seq_losses.append(float(lv))

    main2, startup2, loss2 = _build()
    exe2, sc2 = static.Executor(), static.Scope()
    with static.scope_guard(sc2):
        exe2.run(startup2)
        (stacked,) = exe2.run_steps(main2, feed={"x": xs, "y": ys},
                                    fetch_list=[loss2])
    assert stacked.shape == (K,)
    np.testing.assert_allclose(stacked, seq_losses, rtol=1e-4, atol=1e-5)


def test_run_steps_state_carries_between_calls():
    """Two successive run_steps calls continue training (scope state
    advances on device), and the loss keeps falling."""
    K = 8
    xs, ys = _data(2 * K)
    main, startup, loss = _build()
    exe, sc = static.Executor(), static.Scope()
    with static.scope_guard(sc):
        exe.run(startup)
        (l1,) = exe.run_steps(main, feed={"x": xs[:K], "y": ys[:K]},
                              fetch_list=[loss])
        (l2,) = exe.run_steps(main, feed={"x": xs[K:], "y": ys[K:]},
                              fetch_list=[loss])
    assert float(l2[-1]) < float(l1[0])


def test_run_steps_validates_feed():
    main, startup, loss = _build()
    exe, sc = static.Executor(), static.Scope()
    xs, ys = _data(3)
    with static.scope_guard(sc):
        exe.run(startup)
        with pytest.raises(ValueError):
            exe.run_steps(main, feed={}, fetch_list=[loss])
        with pytest.raises(ValueError):
            exe.run_steps(main, feed={"x": xs, "y": ys[:2]},
                          fetch_list=[loss])
        with pytest.raises(ValueError, match="scalar"):
            exe.run_steps(main, feed={"x": xs, "y": np.float32(0.5)},
                          fetch_list=[loss])


def test_run_steps_honors_check_nan_inf():
    """FLAGS_check_nan_inf raises on the scanned path like run() does."""
    from paddle_tpu.core.flags import set_flags
    main, startup, loss = _build(lr=1e6)  # divergent lr -> inf/nan fast
    exe, sc = static.Executor(), static.Scope()
    xs, ys = _data(6)
    set_flags({"FLAGS_check_nan_inf": True})
    try:
        with static.scope_guard(sc):
            exe.run(startup)
            with pytest.raises(RuntimeError, match="check_nan_inf"):
                exe.run_steps(main, feed={"x": xs, "y": ys},
                              fetch_list=[loss])
    finally:
        set_flags({"FLAGS_check_nan_inf": False})


def test_run_steps_short_final_chunk_no_scan_retrace():
    """A K' < K final chunk is served step-by-step through run()'s cache
    (at most ONE single-step trace, reused forever) instead of retracing
    the whole scan — and numerics match the all-sequential walk."""
    K = 6
    xs, ys = _data(K + 2)
    main, startup, loss = _build()
    exe, sc = static.Executor(), static.Scope()
    with static.scope_guard(sc):
        exe.run(startup)
        (l1,) = exe.run_steps(main, feed={"x": xs[:K], "y": ys[:K]},
                              fetch_list=[loss])
        t0 = exe.cache_stats()["traces"]
        (l2,) = exe.run_steps(main, feed={"x": xs[K:], "y": ys[K:]},
                              fetch_list=[loss])
        assert l2.shape == (2,)
        assert exe.cache_stats()["traces"] - t0 <= 1  # single-step sig
        t1 = exe.cache_stats()["traces"]
        exe.run_steps(main, feed={"x": xs[K:], "y": ys[K:]},
                      fetch_list=[loss])
        assert exe.cache_stats()["traces"] == t1  # steady thereafter

    main2, startup2, loss2 = _build()
    exe2, sc2 = static.Executor(), static.Scope()
    seq = []
    with static.scope_guard(sc2):
        exe2.run(startup2)
        for i in range(K + 2):
            (lv,) = exe2.run(main2, feed={"x": xs[i], "y": ys[i]},
                             fetch_list=[loss2])
            seq.append(float(lv))
    np.testing.assert_allclose(np.concatenate([l1, l2]), seq,
                               rtol=1e-4, atol=1e-5)


def test_run_steps_ragged_batch_buckets_into_compiled_scan():
    """Same K but a smaller PER-STEP batch pads up into the compiled
    stacked bucket (zero new traces) and the stacked fetches un-pad."""
    K = 4
    xs, ys = _data(K)
    main, startup, loss = _build()
    exe, sc = static.Executor(), static.Scope()
    per_row = next(v for v in main.global_block().vars.values()
                   if v.shape == (-1, 1) and not v.is_data
                   and not v.persistable)
    with static.scope_guard(sc):
        exe.run(startup)
        exe.run_steps(main, feed={"x": xs, "y": ys},
                      fetch_list=[loss, per_row])
        t0 = exe.cache_stats()["traces"]
        b0 = exe.cache_stats()["bucket_hits"]
        lv, pred_rows = exe.run_steps(
            main, feed={"x": xs[:, :3], "y": ys[:, :3]},
            fetch_list=[loss, per_row])
        assert exe.cache_stats()["traces"] == t0, "scan retraced"
        assert exe.cache_stats()["bucket_hits"] == b0 + 1
        assert lv.shape == (K,)  # scalar loss: nothing to un-pad
        # per-row fetch un-padded from the bucket batch 4 back to 3
        assert pred_rows.shape[:2] == (K, 3), pred_rows.shape

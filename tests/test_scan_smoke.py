"""Tier-1 scanned-window gate (NOT marked slow — a regression in the
commit-tail hoist, the window's dispatch accounting, the seed/counter
phase, or scanned-vs-looped numerics must fail the suite, not wait for
a perf round).

Drives tools/scan_smoke.py in-process: small Adam model under ZeRO-2 x
gradient merge K=4 on the 8-device CPU mesh in under 15 s — the window
splits with exactly one publish allgather per ZeRO bucket in the tail,
K looped dispatches collapse to ONE hoisted `run_steps` dispatch per
window, every persistable lands bitwise-equal to the looped path, and
nothing re-traces after the first window.  The RNG-phase test seals the
ISSUE 16 seed audit with a model whose numerics DEPEND on the per-step
seed (dropout): the scanned window derives micro-step i's seed as
`seed_for_step + i`, so any drift from K looped `run` calls flips the
dropout masks and the bitwise check.  Mirrors the shard_smoke gate
pattern; the CLI round-trip is `slow`.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_scan_smoke_gate():
    import scan_smoke
    result = scan_smoke.run_smoke(windows=2)
    # the whole point: K dispatches -> 1 per window, publish once
    assert result["value"] == result["k"] == 4, result
    assert result["scanned_dispatches"] == result["windows"], result
    assert result["publish_allgathers_per_window"] >= 1, result
    assert result["compiles_after_warmup"] == 0, result
    assert result["persistables_bitwise_equal"] >= 4, result


def _dropout_model(static, layers, k, world):
    """fc tower with DROPOUT — numerics depend on the per-step seed."""
    from paddle_tpu.core.program import _reset_unique_names
    from paddle_tpu.distributed.sharding import shard_optimizer_states
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 16])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 32, act="relu")
        h = layers.dropout(h, 0.5,
                           dropout_implementation="upscale_in_train")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss)
    shard_optimizer_states(main, startup, dp_degree=world, stage=2)
    static.gradient_merge(main, k, startup_program=startup)
    return main, startup, loss


def test_scan_window_rng_counter_and_dispatch_parity():
    """ISSUE 16 satellite: the hoisted window's host accounting — the
    training-step counter advances K per window (so the NEXT step's
    seed matches K looped calls), `_dispatches` advances 1, and a
    seed-sensitive model (dropout) stays bitwise-equal to looped."""
    import jax
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    from paddle_tpu.distributed.compiled_program import CompiledProgram

    world = len(jax.devices())
    k, windows, batch = 2, 2, 8
    rng = np.random.RandomState(7)
    feeds = [{"x": rng.rand(batch, 16).astype(np.float32),
              "y": rng.rand(batch, 1).astype(np.float32)}
             for _ in range(windows * k)]

    main_l, startup_l, loss_l = _dropout_model(static, layers, k, world)
    cp_l = CompiledProgram(main_l).with_data_parallel(loss_name=loss_l.name)
    exe_l = static.Executor()
    scope_l = static.Scope()
    losses_l = []
    with static.scope_guard(scope_l):
        exe_l.run(startup_l)
        step0 = exe_l._step
        for f in feeds:
            out = exe_l.run(cp_l, feed=f, fetch_list=[loss_l])
            losses_l.append(np.asarray(out[0]))
        assert exe_l._step - step0 == windows * k

    main_s, startup_s, loss_s = _dropout_model(static, layers, k, world)
    cp_s = CompiledProgram(main_s).with_data_parallel(loss_name=loss_s.name)
    exe_s = static.Executor()
    scope_s = static.Scope()
    losses_s = []
    with static.scope_guard(scope_s):
        exe_s.run(startup_s)
        for w in range(windows):
            sfeed = {n: np.stack([feeds[w * k + i][n] for i in range(k)])
                     for n in ("x", "y")}
            step0, d0 = exe_s._step, cp_s._dispatches
            outs = exe_s.run_steps(cp_s, feed=sfeed, fetch_list=[loss_s])
            losses_s.extend(np.asarray(outs[0]))
            # ONE device dispatch, K training steps of counter/RNG phase
            assert cp_s._dispatches - d0 == 1
            assert exe_s._step - step0 == k

    # dropout masks are a function of the micro-step seed: bitwise
    # equality here proves the scanned seed schedule IS the looped one
    for i, (a, b) in enumerate(zip(losses_l, losses_s)):
        assert a.tobytes() == b.tobytes(), (i, a, b)
    assert exe_l._seed_for_step(main_l) == exe_s._seed_for_step(main_s)


@pytest.mark.slow
def test_scan_smoke_cli_prints_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "scan_smoke.py"),
         "--windows", "2"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["value"] == 4.0
    assert result["compiles_after_warmup"] == 0

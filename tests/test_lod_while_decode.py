"""Reference-style While + LoD-array program shapes on the new kernels.

The reference's DynamicRNN/decoder programs are hand-wired While loops
over lod_tensor_to_array slices (book/test_machine_translation.py
decode_main, layers/control_flow.py DynamicRNN internals).  The
DynamicRNN class here lowers to one masked scan instead — but the RAW
program shape must also run, because translated/loaded reference
programs arrive in that form.  These tests wire the ops the reference
way: rank table + to-array outside a While, array_read/array_write +
shrink_rnn_memory + increment inside it, array_to_lod_tensor after.
"""
import numpy as np

import paddle_tpu.static as static
from paddle_tpu.static import layers


def test_while_over_lod_array_matches_dynamic_rnn():
    """A hand-wired While consuming lod_tensor_to_array slices computes
    the same masked accumulation DynamicRNN produces."""
    B, T, D = 3, 4, 2
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [B, T, D])
        lens = layers.data("lens", [B], dtype="int32")
        table = layers.lod_rank_table(length=lens)
        arr = layers.lod_tensor_to_array(x, table)      # rank-ordered
        max_len = layers.max_sequence_len(table)

        step = layers.fill_constant([1], "int64", 0)
        state = layers.fill_constant([1], "float32", 0.0)
        state = layers.expand(layers.reshape(state, [1, 1]), [B, D])
        # per-step outputs collected reference-style via array_write
        out_arr = layers.create_array("float32")
        zero_i = layers.fill_constant([1], "int64", 0)
        init_slice = layers.array_read(arr, zero_i)
        layers.array_write(layers.fill_zeros_like(init_slice), zero_i,
                           array=out_arr, max_len=T)

        cond = layers.less_than(step, max_len)
        w = layers.While(cond, max_iters=T)
        with w.block():
            xt = layers.array_read(arr, step)           # [B, D] slice
            kept = layers.shrink_memory(state, step, table)
            # mask: the reference shrinks; here finished rows freeze
            step_b = layers.expand(layers.reshape(
                layers.cast(step, "int32"), [1, 1]), [B, 1])
            active = layers.cast(
                layers.less_than(step_b, layers.reshape(lens, [B, 1])),
                "float32")                               # [B, 1]
            new_state = layers.elementwise_add(kept, xt)
            merged = layers.elementwise_add(
                layers.elementwise_mul(new_state, active),
                layers.elementwise_mul(
                    kept, layers.increment(
                        layers.scale(active, scale=-1.0), value=1.0,
                        in_place=False)))
            layers.assign(merged, output=state)
            layers.array_write(merged, step, array=out_arr, max_len=T)
            nxt = layers.increment(step, value=1, in_place=False)
            layers.assign(nxt, output=step)
            layers.less_than(step, max_len, cond=cond)

    # NOTE on `active`: lens here is in INPUT order but the array is in
    # RANK order.  Use equal lengths per batch row to keep the check
    # exact while still exercising the full op chain.
    xv = np.random.RandomState(0).randn(B, T, D).astype(np.float32)
    lv = np.full((B,), T, np.int32)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        (sv,) = exe.run(main, feed={"x": xv, "lens": lv},
                        fetch_list=[state])
    # all rows full length: final state = sum over time (rank order ==
    # stable identity permutation for equal lengths)
    np.testing.assert_allclose(np.asarray(sv), xv.sum(axis=1),
                               rtol=1e-5)


def test_while_greedy_decoder_with_array_write():
    """Greedy decode loop the reference book style: While + array_write
    of the argmax token each step, tokens collected via
    tensor_array_to_tensor."""
    B, V, D, STEPS = 2, 8, 4, 5
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        boot = layers.data("boot", [B, D])
        step = layers.fill_constant([1], "int64", 0)
        n_steps = layers.fill_constant([1], "int64", STEPS)
        state = layers.assign(boot)
        tok_arr = layers.create_array("int64")
        zero_i = layers.fill_constant([1], "int64", 0)
        layers.array_write(
            layers.fill_constant([B], "int64", 0), zero_i,
            array=tok_arr, max_len=STEPS)

        cond = layers.less_than(step, n_steps)
        w = layers.While(cond, is_test=True)
        with w.block():
            logits = layers.fc(state, size=V,
                               param_attr=static.ParamAttr(name="dec_w"),
                               bias_attr=static.ParamAttr(name="dec_b"))
            tok = layers.argmax(logits, axis=1)
            layers.array_write(tok, step, array=tok_arr, max_len=STEPS)
            emb = layers.embedding(
                layers.reshape(tok, [B, 1]), size=[V, D],
                param_attr=static.ParamAttr(name="dec_emb"))
            nxt_state = layers.elementwise_add(
                state, layers.reshape(emb, [B, D]))
            layers.assign(nxt_state, output=state)
            nxt = layers.increment(step, value=1, in_place=False)
            layers.assign(nxt, output=step)
            layers.less_than(step, n_steps, cond=cond)

        blk = main.global_block()
        toks = blk.create_var(name="decoded", shape=[STEPS, B],
                              dtype="int64")
        blk.append_op("tensor_array_to_tensor",
                      {"X": [tok_arr.name]}, {"Out": ["decoded"]},
                      {"use_stack": True, "axis": 0})

    rng = np.random.RandomState(1)
    bv = rng.randn(B, D).astype(np.float32)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        (tv,) = exe.run(main, feed={"boot": bv}, fetch_list=["decoded"])
        # replicate on host with the trained-in weights
        w = np.asarray(scope.get("dec_w"))
        b = np.asarray(scope.get("dec_b"))
        emb = np.asarray(scope.get("dec_emb"))
    tv = np.asarray(tv)
    assert tv.shape == (STEPS, B)
    state = bv.copy()
    for t in range(STEPS):
        tok = (state @ w + b).argmax(axis=1)
        np.testing.assert_array_equal(tv[t], tok)
        state = state + emb[tok]

"""OpTest harness — analog of the reference's workhorse single-op test base
(/root/reference/python/paddle/fluid/tests/unittests/op_test.py:183):
a test declares op_type/inputs/attrs (+ optionally expected outputs); the
harness runs the registered kernel and checks outputs against the declared
numpy reference, and checks the registered grad op against float64 central
finite differences."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.ops.registry import get_op_info, run_kernel, OpContext


class OpTest:
    op_type: str = None
    atol = 1e-5
    rtol = 1e-5

    def setup(self):
        self.inputs = {}
        self.outputs = {}
        self.attrs = {}

    # -- helpers ------------------------------------------------------------
    def _ctx(self):
        return OpContext(seed=2024)

    def _to_device(self, v):
        if isinstance(v, (list, tuple)):
            return [jnp.asarray(x) for x in v]
        return jnp.asarray(v)

    def _run_forward(self, inputs=None, attrs=None):
        inputs = self.inputs if inputs is None else inputs
        attrs = self.attrs if attrs is None else attrs
        dev_ins = {k: self._to_device(v) for k, v in inputs.items()}
        return run_kernel(self.op_type, dev_ins, dict(attrs), self._ctx())

    # -- checks -------------------------------------------------------------
    def check_output(self, atol=None, rtol=None, no_check_set=()):
        atol = self.atol if atol is None else atol
        rtol = self.rtol if rtol is None else rtol
        outs = self._run_forward()
        for name, expected in self.outputs.items():
            if name in no_check_set or expected is None:
                continue
            got = outs[name]
            if isinstance(expected, (list, tuple)):
                for e, g in zip(expected, got):
                    np.testing.assert_allclose(
                        np.asarray(g, np.float64) if np.asarray(g).dtype.kind
                        == "f" else np.asarray(g),
                        np.asarray(e, np.float64) if np.asarray(e).dtype.kind
                        == "f" else np.asarray(e),
                        atol=atol, rtol=rtol, err_msg=f"output {name}")
            else:
                g = np.asarray(got)
                e = np.asarray(expected)
                if g.dtype.kind == "f":
                    g = g.astype(np.float64)
                    e = e.astype(np.float64)
                np.testing.assert_allclose(g, e, atol=atol, rtol=rtol,
                                           err_msg=f"output {name}")
        return outs

    def check_grad(self, inputs_to_check, output_names, delta=1e-3,
                   max_relative_error=5e-3, user_defined_grads=None):
        """Compare the registered grad kernel against float64 central
        differences (the reference enforces fp64 grad checks too,
        op_test.py:232-248)."""
        if isinstance(output_names, str):
            output_names = [output_names]
        info = get_op_info(self.op_type)
        assert info is not None and info.has_grad, \
            f"{self.op_type} has no grad op"
        f64_ins = {}
        for k, v in self.inputs.items():
            if isinstance(v, (list, tuple)):
                f64_ins[k] = [np.asarray(x).astype(np.float64)
                              if np.asarray(x).dtype.kind == "f"
                              else np.asarray(x) for x in v]
            else:
                a = np.asarray(v)
                f64_ins[k] = a.astype(np.float64) if a.dtype.kind == "f" else a
        ctx = self._ctx()

        def run_fwd(ins_np):
            dev = {k: ([jnp.asarray(x) for x in v]
                       if isinstance(v, list) else jnp.asarray(v))
                   for k, v in ins_np.items()}
            outs = run_kernel(self.op_type, dev, dict(self.attrs), ctx)
            return outs

        # scalar objective: sum of requested outputs (cotangent of ones),
        # jitted once so the finite-difference loop is cheap
        @jax.jit
        def _objective_dev(dev_ins):
            outs = run_kernel(self.op_type, dev_ins, dict(self.attrs), ctx)
            total = jnp.zeros((), jnp.float64)
            for name in output_names:
                o = outs[name]
                os_ = o if isinstance(o, list) else [o]
                for x in os_:
                    total = total + jnp.sum(x.astype(jnp.float64))
            return total

        def objective(ins_np):
            dev = {k: ([jnp.asarray(x) for x in v]
                       if isinstance(v, list) else jnp.asarray(v))
                   for k, v in ins_np.items()}
            return float(_objective_dev(dev))

        # analytic grads from the registered grad kernel
        fwd_outs = run_fwd(f64_ins)
        grad_ins = {k: ([jnp.asarray(x) for x in v] if isinstance(v, list)
                        else jnp.asarray(v)) for k, v in f64_ins.items()}
        for slot in info.outputs:
            if slot.name in fwd_outs:
                o = fwd_outs[slot.name]
                grad_ins[slot.name] = o
                if slot.name in output_names:
                    grad_ins[slot.name + "@GRAD"] = (
                        [jnp.ones_like(x) for x in o]
                        if isinstance(o, list) else jnp.ones_like(o))
                else:
                    grad_ins[slot.name + "@GRAD"] = (
                        [jnp.zeros_like(x) for x in o]
                        if isinstance(o, list) else jnp.zeros_like(o))
        analytic = run_kernel(info.grad_op_type(), grad_ins,
                              dict(self.attrs), ctx)

        for i, name in enumerate(inputs_to_check):
            a_grad = analytic.get(name + "@GRAD")
            assert a_grad is not None, f"no grad produced for {name}"
            a_grad = np.asarray(a_grad, np.float64)
            if user_defined_grads is not None:
                n_grad = np.asarray(user_defined_grads[i], np.float64)
            else:
                base = np.asarray(f64_ins[name], np.float64)
                n_grad = np.zeros_like(base).ravel()
                flat = base.ravel()
                for j in range(flat.size):
                    orig = flat[j]
                    flat[j] = orig + delta
                    ins_p = dict(f64_ins)
                    ins_p[name] = flat.reshape(base.shape).copy()
                    up = objective(ins_p)
                    flat[j] = orig - delta
                    ins_m = dict(f64_ins)
                    ins_m[name] = flat.reshape(base.shape).copy()
                    down = objective(ins_m)
                    flat[j] = orig
                    n_grad[j] = (up - down) / (2 * delta)
                n_grad = n_grad.reshape(base.shape)
            denom = np.maximum(np.maximum(np.abs(a_grad), np.abs(n_grad)),
                               1e-3)
            rel = np.max(np.abs(a_grad - n_grad) / denom)
            assert rel <= max_relative_error, (
                f"grad check failed for {self.op_type}.{name}: "
                f"max rel err {rel:.2e} > {max_relative_error:.2e}\n"
                f"analytic={a_grad.ravel()[:8]}\nnumeric={n_grad.ravel()[:8]}")

"""paddle.text datasets parity (reference python/paddle/text/datasets/):
each loader parses the OFFICIAL archive format — tests build tiny
synthetic archives in those formats and check ids/shapes/splits."""
import gzip
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

from paddle_tpu.text import (Conll05st, Imikolov, Movielens, WMT14, WMT16)


def _tar_with(path, members):
    """members: {name: bytes} -> tar.gz at path."""
    with tarfile.open(path, "w:gz") as tf:
        for name, data in members.items():
            info = tarfile.TarInfo(name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))


def test_imikolov_ngram_and_seq(tmp_path):
    corpus = b"the cat sat\nthe dog sat\nthe cat ran\n"
    path = str(tmp_path / "simple-examples.tgz")
    _tar_with(path, {
        "./simple-examples/data/ptb.train.txt": corpus,
        "./simple-examples/data/ptb.valid.txt": b"the cat sat\n",
        "./simple-examples/data/ptb.test.txt": b"the cat sat\n",
    })
    ds = Imikolov(path, data_type="NGRAM", window_size=2, mode="train",
                  min_word_freq=0)
    # lines framed <s> w w w <e> -> 4 bigrams per 3-token line, incl.
    # the boundary grams
    assert len(ds) == 12
    first = ds[0]
    assert len(first) == 2
    assert int(first[0]) == ds.word_idx["<s>"]
    assert "<unk>" in ds.word_idx
    assert ds.word_idx["<unk>"] == len(ds.word_idx) - 1  # forced last

    seq = Imikolov(path, data_type="SEQ", mode="test", min_word_freq=0)
    src, trg = seq[0]
    assert src[0] == seq.word_idx["<s>"]
    assert trg[-1] == seq.word_idx["<e>"]
    np.testing.assert_array_equal(src[1:], trg[:-1])

    with pytest.raises(ValueError):
        Imikolov(path, data_type="NGRAM", window_size=0)


def test_movielens_sample_layout(tmp_path):
    movies = ("1::Toy Story (1995)::Animation|Comedy\n"
              "2::Heat (1995)::Action\n").encode("latin1")
    users = ("1::M::25::12::90210\n"
             "2::F::35::7::10001\n").encode("latin1")
    ratings = ("1::1::5::978300760\n"
               "1::2::3::978302109\n"
               "2::1::4::978301968\n").encode("latin1")
    path = str(tmp_path / "ml-1m.zip")
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("ml-1m/movies.dat", movies)
        z.writestr("ml-1m/users.dat", users)
        z.writestr("ml-1m/ratings.dat", ratings)
    tr = Movielens(path, mode="train", test_ratio=0.0)
    te = Movielens(path, mode="test", test_ratio=0.0)
    assert len(tr) == 3 and len(te) == 0
    s = tr[0]
    # uid, gender, age, job, mid, categories, title words, rating
    assert len(s) == 8
    uid, gender, age, job, mid, cats, title, rating = s
    assert uid == [1] and gender == [0] and job == [12]
    assert float(rating[0]) == 5.0 * 2 - 5.0
    assert all(c in range(3) for c in cats)


def test_wmt14_ids_and_framing(tmp_path):
    src_dict = b"<s>\n<e>\n<unk>\nhello\nworld\n"
    trg_dict = b"<s>\n<e>\n<unk>\nhallo\nwelt\n"
    train = b"hello world\thallo welt\nhello novel\thallo neu\n"
    path = str(tmp_path / "wmt14.tgz")
    _tar_with(path, {"wmt14/src.dict": src_dict,
                     "wmt14/trg.dict": trg_dict,
                     "wmt14/train/train": train})
    ds = WMT14(path, mode="train", dict_size=5)
    assert len(ds) == 2
    src, trg, nxt = ds[0]
    sd, td = ds.get_dict()
    assert src[0] == sd["<s>"] and src[-1] == sd["<e>"]
    assert trg[0] == td["<s>"] and nxt[-1] == td["<e>"]
    np.testing.assert_array_equal(trg[1:], nxt[:-1])
    # unknown words map to UNK_IDX=2
    src2, _, _ = ds[1]
    assert src2[2] == 2  # 'novel' not in dict


def test_wmt16_builds_dict_from_train(tmp_path):
    train = b"a b\tx y\na c\tx z\n"
    val = b"a b\tx y\n"
    path = str(tmp_path / "wmt16.tar.gz")
    _tar_with(path, {"wmt16/train": train, "wmt16/val": val,
                     "wmt16/test": val})
    ds = WMT16(path, mode="val", src_dict_size=6, trg_dict_size=6)
    # dict: <s> <e> <unk> + by freq: a(2) then b/c alphabetical
    assert ds.src_dict["<s>"] == 0 and ds.src_dict["a"] == 3
    src, trg, nxt = ds[0]
    assert src[0] == 0 and src[-1] == 1
    np.testing.assert_array_equal(trg[1:], nxt[:-1])
    # reversed lang swaps the columns
    de = WMT16(path, mode="val", src_dict_size=6, trg_dict_size=6,
               lang="de")
    assert de.src_dict["x"] == 3


def test_conll05st_srl_samples(tmp_path):
    words = b"The\ncat\nsat\n\n"
    # props: column 0 = predicate lemma rows, column 1 = role brackets
    props = (b"-\t(A0*\n"
             b"-\t*)\n"
             b"sit\t(V*)\n"
             b"\n")
    wbuf, pbuf = io.BytesIO(), io.BytesIO()
    with gzip.GzipFile(fileobj=wbuf, mode="wb") as g:
        g.write(words)
    with gzip.GzipFile(fileobj=pbuf, mode="wb") as g:
        g.write(props)
    base = tmp_path
    path = str(base / "conll05st-tests.tar.gz")
    _tar_with(path, {
        "conll05st-release/test.wsj/words/test.wsj.words.gz":
            wbuf.getvalue(),
        "conll05st-release/test.wsj/props/test.wsj.props.gz":
            pbuf.getvalue(),
    })
    (base / "wordDict.txt").write_text("<unk>\nThe\ncat\nsat\n")
    (base / "verbDict.txt").write_text("sit\n")
    (base / "targetDict.txt").write_text("B-A0\nB-V\nO\n")
    ds = Conll05st(path, str(base / "wordDict.txt"),
                   str(base / "verbDict.txt"),
                   str(base / "targetDict.txt"))
    assert len(ds) == 1
    sample = ds[0]
    assert len(sample) == 9
    word_idx, n2, n1, c0, p1, p2, pred, mark, label = sample
    assert list(word_idx) == [1, 2, 3]          # The cat sat
    assert list(pred) == [0] * 3                # 'sit'
    assert mark[2] == 1                         # predicate marked
    wd, pd, ld = ds.get_dict()
    assert label[2] == ld["B-V"]
    assert label[0] == ld["B-A0"] and label[1] == ld["I-A0"]


def test_missing_archive_raises(tmp_path):
    with pytest.raises(Exception):
        WMT14(str(tmp_path / "nope.tgz"), dict_size=5)

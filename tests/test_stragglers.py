"""Straggler op tests (VERDICT r3 missing #5) — OpTest-vs-numpy entries
for the 17 coverage-tail ops."""
import numpy as np
import pytest

from paddle_tpu.ops.registry import run_kernel, OpContext, get_op_info


def _run(op, ins, attrs=None):
    import jax.numpy as jnp
    dev = {k: ([jnp.asarray(x) for x in v] if isinstance(v, list)
               else jnp.asarray(x)) if (x := v) is not None else None
           for k, v in ins.items()}
    return run_kernel(op, dev, attrs or {}, OpContext(seed=3))


STRAGGLER_OPS = [
    "crop", "crop_tensor", "proximal_gd", "proximal_adagrad",
    "modified_huber_loss", "teacher_student_sigmoid_loss",
    "positive_negative_pair", "sequence_scatter",
    "sequence_topk_avg_pooling", "fsp", "inplace_abn", "conv_shift",
    "attention_lstm", "match_matrix_tensor", "var_conv_2d", "tree_conv",
    "similarity_focus",
]


def test_registry_probe_stragglers():
    missing = [op for op in STRAGGLER_OPS if get_op_info(op) is None]
    assert not missing, f"unregistered straggler ops: {missing}"


def test_crop_and_crop_tensor():
    x = np.arange(24, dtype=np.float32).reshape(4, 6)
    out = _run("crop", {"X": x}, {"shape": [2, 3], "offsets": [1, 2]})
    np.testing.assert_allclose(np.asarray(out["Out"]), x[1:3, 2:5])
    out = _run("crop_tensor",
               {"X": x, "Offsets": np.array([0, 1], np.int32)},
               {"shape": [2, -1]})
    np.testing.assert_allclose(np.asarray(out["Out"]), x[0:2, 1:6])


def test_proximal_gd_matches_numpy():
    p = np.array([1.0, -2.0, 0.05], np.float32)
    g = np.array([0.5, -0.5, 0.1], np.float32)
    lr = np.array([0.1], np.float32)
    out = _run("proximal_gd",
               {"Param": p, "Grad": g, "LearningRate": lr},
               {"l1": 0.2, "l2": 0.5})
    prox = p - 0.1 * g
    exp = np.sign(prox) * np.maximum(np.abs(prox) - 0.1 * 0.2, 0) / \
        (1 + 0.1 * 0.5)
    np.testing.assert_allclose(np.asarray(out["ParamOut"]), exp,
                               rtol=1e-6)


def test_proximal_adagrad_matches_numpy():
    p = np.array([1.0, -2.0], np.float32)
    m = np.array([0.1, 0.2], np.float32)
    g = np.array([0.5, -0.5], np.float32)
    lr = np.array([0.1], np.float32)
    out = _run("proximal_adagrad",
               {"Param": p, "Moment": m, "Grad": g, "LearningRate": lr},
               {"l1": 0.0, "l2": 0.5})
    m_out = m + g * g
    prox = p - 0.1 * g / np.sqrt(m_out)
    np.testing.assert_allclose(np.asarray(out["MomentOut"]), m_out,
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out["ParamOut"]),
                               prox / 1.05, rtol=1e-6)


def test_modified_huber_loss_pieces():
    x = np.array([-3.0, 0.5, 2.0], np.float32)
    y = np.array([1.0, 1.0, 1.0], np.float32)
    out = _run("modified_huber_loss", {"X": x, "Y": y})
    np.testing.assert_allclose(np.asarray(out["Out"]),
                               [12.0, 0.25, 0.0], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out["IntermediateVal"]), x)


def test_teacher_student_sigmoid_loss_branches():
    x = np.array([0.3, 0.3, 0.3, 0.3], np.float32)
    lbl = np.array([-2.0, -1.0, 0.4, 1.4], np.float32)
    out = _run("teacher_student_sigmoid_loss", {"X": x, "Label": lbl})

    def bce(xx, z):
        return max(xx, 0) - xx * z + np.log1p(np.exp(-abs(xx)))

    exp = [bce(0.3, 0.0), bce(0.3, 1.0),
           bce(0.3, 0.0) + bce(0.3, 0.4),
           bce(0.3, 1.0) + bce(0.3, 0.4)]
    np.testing.assert_allclose(np.asarray(out["Y"]), exp, rtol=1e-5)


def test_positive_negative_pair_counts():
    score = np.array([[0.9], [0.5], [0.3], [0.4]], np.float32)
    label = np.array([1.0, 0.0, 1.0, 0.0], np.float32)
    qid = np.array([7, 7, 8, 8], np.int64)
    out = _run("positive_negative_pair",
               {"Score": score, "Label": label, "QueryID": qid},
               {"column": 0})
    # q7: (0.9,l1) vs (0.5,l0): correct -> pos
    # q8: (0.3,l1) vs (0.4,l0): wrong order -> neg
    assert float(out["PositivePair"][0]) == 1.0
    assert float(out["NegativePair"][0]) == 1.0
    assert float(out["NeutralPair"][0]) == 0.0
    # accumulation chains
    out2 = _run("positive_negative_pair",
                {"Score": score, "Label": label, "QueryID": qid,
                 "AccumulatePositivePair": out["PositivePair"],
                 "AccumulateNegativePair": out["NegativePair"],
                 "AccumulateNeutralPair": out["NeutralPair"]},
                {"column": 0})
    assert float(out2["PositivePair"][0]) == 2.0


def test_sequence_scatter_adds():
    x = np.zeros((2, 5), np.float32)
    ids = np.array([[1, 3, -1], [0, 0, 4]], np.int64)
    upd = np.array([[1.0, 2.0, 9.0], [0.5, 0.25, 3.0]], np.float32)
    out = _run("sequence_scatter", {"X": x, "Ids": ids, "Updates": upd})
    got = np.asarray(out["Out"])
    np.testing.assert_allclose(got[0], [0, 1, 0, 2, 0])
    np.testing.assert_allclose(got[1], [0.75, 0, 0, 0, 3.0])


def test_sequence_topk_avg_pooling():
    x = np.zeros((1, 1, 2, 4), np.float32)
    x[0, 0, 0] = [3.0, 1.0, 2.0, 99.0]   # col 3 beyond length
    x[0, 0, 1] = [0.5, 4.0, 1.5, 99.0]
    out = _run("sequence_topk_avg_pooling",
               {"X": x, "ROW": np.array([2], np.int64),
                "COLUMN": np.array([3], np.int64)},
               {"topks": [1, 2], "channel_num": 1})
    got = np.asarray(out["Out"])[0]      # [R, C*K] = [2, 2]
    np.testing.assert_allclose(got[0], [3.0, (3.0 + 2.0) / 2], rtol=1e-6)
    np.testing.assert_allclose(got[1], [4.0, (4.0 + 1.5) / 2], rtol=1e-6)


def test_fsp_matches_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 4, 5).astype(np.float32)
    y = rng.randn(2, 6, 4, 5).astype(np.float32)
    out = _run("fsp", {"X": x, "Y": y})
    exp = np.einsum("bchw,bdhw->bcd", x, y) / 20.0
    np.testing.assert_allclose(np.asarray(out["Out"]), exp, rtol=1e-4,
                               atol=1e-5)


def test_inplace_abn_is_bn_plus_activation():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 3, 2, 2).astype(np.float32)
    scale = np.ones(3, np.float32)
    bias = np.zeros(3, np.float32)
    mean = np.zeros(3, np.float32)
    var = np.ones(3, np.float32)
    attrs = {"epsilon": 1e-5, "momentum": 0.9, "is_test": False,
             "activation": "leaky_relu", "alpha": 0.1}
    out = _run("inplace_abn", {"X": x, "Scale": scale, "Bias": bias,
                               "Mean": mean, "Variance": var}, attrs)
    bn = _run("batch_norm", {"X": x, "Scale": scale, "Bias": bias,
                             "Mean": mean, "Variance": var},
              {"epsilon": 1e-5, "momentum": 0.9, "is_test": False})
    y = np.asarray(bn["Y"])
    exp = np.where(y >= 0, y, 0.1 * y)
    np.testing.assert_allclose(np.asarray(out["Y"]), exp, rtol=1e-5,
                               atol=1e-6)


def test_conv_shift_matches_numpy():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 5).astype(np.float32)
    y = rng.randn(2, 3).astype(np.float32)
    out = _run("conv_shift", {"X": x, "Y": y})
    exp = np.zeros_like(x)
    half = (3 - 1) // 2
    for b in range(2):
        for i in range(5):
            for j in range(3):
                exp[b, i] += x[b, (i + j - half + 5) % 5] * y[b, j]
    np.testing.assert_allclose(np.asarray(out["Out"]), exp, rtol=1e-5,
                               atol=1e-6)


def test_similarity_focus_greedy_marks():
    x = np.zeros((1, 2, 2, 2), np.float32)
    x[0, 0] = [[5.0, 1.0], [2.0, 4.0]]
    out = _run("similarity_focus", {"X": x}, {"axis": 1, "indexes": [0]})
    got = np.asarray(out["Out"])
    # greedy: (0,0)=5 picked, (1,1)=4 picked (row1/col1 free); all
    # channels lit at those positions
    exp = np.array([[1.0, 0.0], [0.0, 1.0]], np.float32)
    np.testing.assert_allclose(got[0, 0], exp)
    np.testing.assert_allclose(got[0, 1], exp)


def test_attention_lstm_runs_and_pools():
    rng = np.random.RandomState(3)
    B, T, M, D = 2, 4, 3, 5
    x = rng.randn(B, T, M).astype(np.float32)
    c0 = rng.randn(B, D).astype(np.float32) * 0.1
    aw = rng.randn(M + D, 1).astype(np.float32) * 0.2
    lw = rng.randn(D + M, 4 * D).astype(np.float32) * 0.1
    lb = np.zeros((1, 4 * D), np.float32)
    out = _run("attention_lstm",
               {"X": x, "C0": c0, "AttentionWeight": aw,
                "LSTMWeight": lw, "LSTMBias": lb},
               {"gate_activation": "sigmoid"})
    h = np.asarray(out["Hidden"])
    c = np.asarray(out["Cell"])
    assert h.shape == (B, T, D) and c.shape == (B, T, D)
    assert np.isfinite(h).all()

    # numpy reference for step 0 of batch 0
    def sig(v):
        return 1 / (1 + np.exp(-v))

    ax = x[0] @ aw[:M, 0]
    score = np.maximum(ax + c0[0] @ aw[M:, 0], 0)
    e = np.exp(score - score.max())
    attn = e / e.sum()
    pooled = attn @ x[0]
    gates = pooled @ lw[D:] + np.zeros(D) @ lw[:D] + lb[0]
    f, i, o, cand = np.split(gates, 4)
    c_new = sig(f) * c0[0] + sig(i) * np.tanh(cand)
    h_new = sig(o) * np.tanh(c_new)
    np.testing.assert_allclose(c[0, 0], c_new, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(h[0, 0], h_new, rtol=1e-4, atol=1e-5)


def test_match_matrix_tensor_bilinear():
    rng = np.random.RandomState(4)
    x = rng.randn(1, 2, 3).astype(np.float32)
    y = rng.randn(1, 4, 3).astype(np.float32)
    w = rng.randn(3, 2, 3).astype(np.float32)
    out = _run("match_matrix_tensor", {"X": x, "Y": y, "W": w},
               {"dim_t": 2})
    got = np.asarray(out["Out"])
    exp = np.einsum("ld,dte,re->tlr", x[0], w, y[0])
    np.testing.assert_allclose(got[0], exp, rtol=1e-4, atol=1e-5)


def test_var_conv_2d_masks_padding():
    rng = np.random.RandomState(5)
    x = rng.randn(1, 1, 4, 4).astype(np.float32)
    w = rng.randn(2, 1 * 3 * 3).astype(np.float32)
    out = _run("var_conv_2d",
               {"X": x, "W": w, "ROW": np.array([2], np.int64),
                "COLUMN": np.array([3], np.int64)},
               {"kernel_h": 3, "kernel_w": 3, "stride_h": 1,
                "stride_w": 1, "output_channel": 2, "input_channel": 1})
    got = np.asarray(out["Out"])
    assert got.shape == (1, 2, 4, 4)
    # cells beyond (2, 3) are zeroed
    assert (got[0, :, 2:, :] == 0).all()
    assert (got[0, :, :, 3:] == 0).all()
    assert np.abs(got[0, :, :2, :3]).sum() > 0


def test_tree_conv_shapes_and_root_weighting():
    # 3-node tree: 1 -> {2, 3}; features distinct per node
    edges = np.array([[[1, 2], [1, 3], [0, 0]]], np.int32)
    feats = np.zeros((1, 3, 2), np.float32)
    feats[0, 0] = [1.0, 0.0]
    feats[0, 1] = [0.0, 1.0]
    feats[0, 2] = [2.0, 2.0]
    filt = np.zeros((2, 3, 1, 1), np.float32)
    filt[:, 2, 0, 0] = 1.0  # only the eta_t (top) channel, sum features
    out = _run("tree_conv",
               {"NodesVector": feats, "EdgeSet": edges, "Filter": filt},
               {"max_depth": 2})
    got = np.asarray(out["Out"])
    assert got.shape == (1, 3, 1, 1)
    # root patch: eta_t(root)=1, children eta_t=(2-1)/2=0.5
    exp_root = (feats[0, 0] * 1.0 + feats[0, 1] * 0.5 +
                feats[0, 2] * 0.5).sum()
    np.testing.assert_allclose(got[0, 0, 0, 0], exp_root, rtol=1e-5)
    # leaves: patch is just the node itself (no children)
    np.testing.assert_allclose(got[0, 1, 0, 0], feats[0, 1].sum(),
                               rtol=1e-5)


def test_straggler_grads_flow():
    """fsp / conv_shift / match_matrix_tensor / modified_huber are
    differentiable via auto-vjp."""
    import jax
    import jax.numpy as jnp
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(2, 3, 2, 2).astype(np.float32))
    y = jnp.asarray(rng.randn(2, 3, 2, 2).astype(np.float32))

    def f(xx):
        return jnp.sum(run_kernel("fsp", {"X": xx, "Y": y}, {},
                                  OpContext())["Out"])

    g = jax.grad(f)(x)
    assert np.isfinite(np.asarray(g)).all() and np.abs(g).sum() > 0

"""Tier-1 tp-serving gate (NOT marked slow — losing the tp=2 page
capacity win, sharded-decode token equality, or the decode bucket
cache is a multi-chip serving regression that must fail the suite, not
wait for a perf round).

Drives tools/tp_serve_smoke.py in-process: one pinned per-chip HBM
budget sized at tp=1 and tp=2 by ``static.page_budget``, the
``TPShardedDecoder`` CompiledProgram vs the dygraph model on prefill
and cached-decode buckets, and a zero-retrace repeat of both warmed
buckets."""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_tp_serve_smoke_gate():
    import tp_serve_smoke
    result = tp_serve_smoke.run_smoke()
    assert result["pages_tp2"] > result["pages_tp1"], result
    assert result["traces_after_warmup"] == 0, result
    assert result["token_equal"] is True, result
    assert result["buckets_compiled"] >= 2, result

"""API-freeze check (reference: tools/check_api_approvals.sh +
print_signatures.py): the public signature dump must match the checked-in
snapshot; intentional changes regenerate it with
`python tools/print_signatures.py > tests/api_signatures.txt`."""
import os
import importlib.util

_HERE = os.path.dirname(__file__)
_TOOL = os.path.join(_HERE, "..", "tools", "print_signatures.py")
_SNAP = os.path.join(_HERE, "api_signatures.txt")


def _load_tool():
    spec = importlib.util.spec_from_file_location("print_signatures", _TOOL)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_api_signatures_frozen():
    current = sorted(set(_load_tool().iter_api()))
    with open(_SNAP) as f:
        snapshot = [ln.rstrip("\n") for ln in f if ln.strip()]
    removed = sorted(set(snapshot) - set(current))
    added = sorted(set(current) - set(snapshot))
    msg = []
    if removed:
        msg.append("REMOVED/CHANGED (breaks users):\n  " +
                   "\n  ".join(removed[:40]))
    if added:
        msg.append("ADDED (regenerate the snapshot to bless):\n  " +
                   "\n  ".join(added[:40]))
    assert not removed and not added, (
        "public API drifted from tests/api_signatures.txt — if "
        "intentional, run `python tools/print_signatures.py > "
        "tests/api_signatures.txt`\n" + "\n".join(msg))


def test_api_surface_is_substantial():
    # the snapshot is a real freeze, not an empty file
    with open(_SNAP) as f:
        n = sum(1 for ln in f if ln.strip())
    assert n > 800, n

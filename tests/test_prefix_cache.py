"""Retained radix prefix cache (serving/prefix_cache.py + the engine's
reused-prefill path).

Covers the radix tree's own contracts (page-granular insert/match,
split-node on divergence, LRU touch ordering), watermark-bounded
retention (eviction order, reclaim under admission pressure — the
promise that lets ``pages_available`` count retained pages), the
pool-side retention accounting (pin/unpin/adopt/assert_drained), and
the engine's reused prefill: a radix hit skips the hit tokens' prefill
compute while output stays token-equal to a cold pool."""
import numpy as np
import pytest

from paddle_tpu.serving import (ContinuousBatchingEngine, PagedKVPool,
                                RadixPrefixCache, metrics)


def _pool(pages=16, T=4, L=2, H=2, Dh=4):
    return PagedKVPool(num_layers=L, num_heads=H, head_dim=Dh,
                       page_tokens=T, num_pages=pages)


def _open(pool, rng, tokens):
    tokens = np.asarray(tokens, np.int64)
    L, H, Dh = pool.num_layers, pool.num_heads, pool.head_dim
    k = rng.randn(L, H, tokens.size, Dh).astype(np.float32)
    v = rng.randn(L, H, tokens.size, Dh).astype(np.float32)
    return pool.open_sequence(tokens, k, v)


def _retire(pool, radix, rng, tokens):
    """Open, retain, close — the engine's _finish path in miniature."""
    t = _open(pool, rng, tokens)
    radix.insert(np.asarray(tokens, np.int64), t)
    pool.close_sequence(t)
    return t


# -- radix tree contracts ---------------------------------------------------
def test_insert_match_page_granularity():
    pool = _pool()
    radix = RadixPrefixCache(pool, low_watermark=1, high_watermark=2)
    rng = np.random.RandomState(0)
    toks = np.arange(10, 20).astype(np.int64)        # 2 full pages + 2
    _retire(pool, radix, rng, toks)
    # only FULL pages are retained; the partial tail page freed at close
    assert radix.retained_pages == 2
    assert pool.pages_retained == 2
    n, pids = radix.match(toks)
    assert n == 8 and len(pids) == 2
    # page granularity: 5 matching tokens only cover one full page
    n, _ = radix.match(toks[:5])
    assert n == 4
    # max_tokens cap is page-aligned too (the engine passes p - 1)
    n, _ = radix.match(toks, max_tokens=toks.size - 1)
    assert n == 8
    n, _ = radix.match(toks[:8], max_tokens=7)
    assert n == 4
    # a diverging stream misses past the shared head
    other = toks.copy()
    other[6] = 99
    n, _ = radix.match(other)
    assert n == 4
    pool.assert_drained()
    radix.clear()
    assert pool.pages_retained == 0 and pool.pages_free == pool.num_pages


def test_split_node_on_divergence():
    pool = _pool(pages=32)
    radix = RadixPrefixCache(pool, low_watermark=1, high_watermark=2)
    rng = np.random.RandomState(1)
    a = np.arange(0, 12).astype(np.int64)            # 3 full pages
    b = np.concatenate([a[:8], [90, 91, 92, 93]]).astype(np.int64)
    _retire(pool, radix, rng, a)
    assert radix.nodes == 1                          # one 3-page edge
    _retire(pool, radix, rng, b)
    # divergence at page 2 splits the edge: common 2-page vertex with
    # two single-page children
    assert radix.nodes == 3
    assert radix.retained_pages == 4                 # 2 common + 2 tails
    na, pa = radix.match(a)
    nb, pb = radix.match(b)
    assert na == 12 and nb == 12
    assert pa[:2] == pb[:2] and pa[2] != pb[2]
    # inserting an already-covered stream adds nothing
    before = radix.retained_pages
    _retire(pool, radix, rng, a)
    assert radix.retained_pages == before
    radix.clear()
    pool.assert_drained()


def test_watermark_eviction_lru_order():
    pool = _pool(pages=8, T=4)
    # low=3: retention may consume the pool down to 3 free pages; once
    # it dips below, LRU leaves evict until 4 are free again
    radix = RadixPrefixCache(pool, low_watermark=3, high_watermark=4)
    rng = np.random.RandomState(2)
    a = np.arange(0, 8).astype(np.int64)
    b = np.arange(100, 108).astype(np.int64)
    _retire(pool, radix, rng, a)                     # 2 retained, 6 free
    _retire(pool, radix, rng, b)                     # 4 retained, 4 free
    # touch a AFTER b so b is the LRU leaf
    radix.match(a)
    c = np.arange(200, 208).astype(np.int64)
    _retire(pool, radix, rng, c)                     # free dips to 2 < low
    # maintain evicted down to high=4 free: exactly one leaf went, and
    # it was b (least recently used), never the freshly touched a
    assert pool.pages_free >= 4
    assert radix.match(b)[0] == 0, "LRU leaf survived eviction"
    assert radix.match(a)[0] == 8, "recently-touched leaf was evicted"
    assert radix.evicted_pages == 2
    radix.clear()
    pool.assert_drained()


def test_reclaim_under_admission_pressure():
    pool = _pool(pages=4, T=4)
    radix = RadixPrefixCache(pool, low_watermark=1, high_watermark=2)
    rng = np.random.RandomState(3)
    _retire(pool, radix, rng, np.arange(0, 8))       # 2 retained, 2 free
    # available counts retained pages as reclaimable headroom: a
    # 3-page reservation is grantable even though only 2 are free
    assert pool.pages_free == 2 and pool.pages_available == 4
    assert pool.can_reserve(3)
    t = pool.reserve(3)
    # the third allocation finds the free list empty and must pull a
    # page back from retention through the registered reclaimer
    toks = np.arange(100, 112).astype(np.int64)
    k = rng.randn(2, 2, 12, 4).astype(np.float32)
    v = rng.randn(2, 2, 12, 4).astype(np.float32)
    table = pool.open_sequence(toks, k, v, table=t)
    assert table.length == 12
    assert radix.evicted_pages == 2, "allocator never hit the reclaimer"
    assert radix.match(np.arange(0, 8))[0] == 0
    pool.close_sequence(table)
    pool.assert_drained()


def test_retention_accounting_and_drain():
    pool = _pool(pages=8, T=4)
    radix = RadixPrefixCache(pool, low_watermark=1, high_watermark=2)
    rng = np.random.RandomState(4)
    toks = np.arange(0, 8).astype(np.int64)
    t = _open(pool, rng, toks)
    radix.insert(toks, t)
    # while the sequence lives, pinned pages are SHARED, not retained
    assert pool.pages_retained == 0 and pool.pages_shared == 2
    pool.close_sequence(t)
    assert pool.pages_retained == 2 and pool.pages_shared == 0
    # retained-but-unreferenced pages are clean, not leaks
    pool.assert_drained()
    # adopt maps them into a fresh table without charging it
    n, pids = radix.match(toks)
    t2 = pool.reserve(2)
    pool.adopt_prefix(t2, pids, n)
    assert t2.charged == 0 and t2.length == 8
    assert pool.pages_retained == 0          # live again while adopted
    pool.close_sequence(t2)
    assert pool.pages_retained == 2
    radix.clear()
    pool.assert_drained()
    # pinning a free page is a stale-hit bug, loudly rejected
    with pytest.raises(ValueError, match="free"):
        pool.pin_page(pids[0])


def test_watermark_validation():
    pool = _pool(pages=8)
    with pytest.raises(ValueError):
        RadixPrefixCache(pool, low_watermark=4, high_watermark=4)
    with pytest.raises(ValueError):
        RadixPrefixCache(pool, low_watermark=0, high_watermark=2)
    with pytest.raises(ValueError):
        RadixPrefixCache(pool, low_watermark=2, high_watermark=9)


# -- engine integration: reused prefill -------------------------------------
@pytest.fixture(scope="module")
def tiny_lm():
    import paddle_tpu.dygraph as dg
    from paddle_tpu.models import GPTConfig, GPTModel, GPTForGeneration
    with dg.guard():
        cfg = GPTConfig(vocab_size=48, hidden_size=16, num_layers=2,
                        num_heads=2, max_position=64, dropout=0.0)
        m = GPTForGeneration(GPTModel(cfg))
        m.eval()
        yield m


def test_reused_prefill_token_equal_to_cold(tiny_lm):
    rng = np.random.RandomState(5)
    prompt = rng.randint(2, 48, (9,)).astype(np.int64)

    cold_pool = PagedKVPool(2, 2, 8, page_tokens=4, num_pages=64)
    eng = ContinuousBatchingEngine(tiny_lm, max_slots=2,
                                   kv_pool=cold_pool).start()
    try:
        ref = np.asarray(eng.submit(prompt, max_length=5)
                         .result(timeout=60))
    finally:
        eng.stop()
    cold_pool.assert_drained()

    pool = PagedKVPool(2, 2, 8, page_tokens=4, num_pages=64)
    radix = RadixPrefixCache(pool, low_watermark=2, high_watermark=4)
    eng = ContinuousBatchingEngine(tiny_lm, max_slots=2, kv_pool=pool,
                                   prefix_cache=radix).start()
    try:
        out1 = np.asarray(eng.submit(prompt, max_length=5)
                          .result(timeout=60))
        pre = metrics.counter("gen.prefill_tokens")
        pre_hits = metrics.counter("kv.radix_hit_tokens")
        out2 = np.asarray(eng.submit(prompt, max_length=5)
                          .result(timeout=60))
        ran = metrics.counter("gen.prefill_tokens") - pre
        hit = metrics.counter("kv.radix_hit_tokens") - pre_hits
    finally:
        eng.stop()
    np.testing.assert_array_equal(out1, ref)
    np.testing.assert_array_equal(out2, ref)
    assert hit == 8, f"expected a 2-page hit, got {hit} tokens"
    assert ran == prompt.size - hit, \
        f"hit prefill ran {ran} tokens, expected the uncovered suffix"
    assert radix.hits == 1
    pool.assert_drained()
    radix.clear()
    pool.assert_drained()

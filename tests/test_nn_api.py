"""paddle.nn / paddle.tensor 2.0 API tests (dygraph-first).

Mirrors the reference's test_layers.py / imperative layer tests; numerics
checked against numpy/jax.
"""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.dygraph import to_tensor


def _rand(*shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


def test_linear_layer():
    lin = nn.Linear(8, 4)
    x = to_tensor(_rand(2, 8))
    out = lin(x)
    assert out.shape == [2, 4]
    ref = x.numpy() @ lin.weight.numpy() + lin.bias.numpy()
    # atol guards near-zero elements against reassociation-order noise
    # (XLA may pick a different matmul algorithm depending on what the
    # process compiled earlier — observed 2.7e-8 drift in full-suite runs)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)


def test_conv_bn_pool_stack():
    m = nn.Sequential(
        nn.Conv2D(3, 8, 3, padding=1),
        nn.BatchNorm2D(8),
        nn.ReLU(),
        nn.MaxPool2D(2),
    )
    x = to_tensor(_rand(2, 3, 8, 8))
    out = m(x)
    assert out.shape == [2, 8, 4, 4]
    # running stats updated in train mode
    bn = m[1]
    assert abs(float(bn._mean.numpy().sum())) > 0


def test_batchnorm_train_eval_modes():
    bn = nn.BatchNorm1D(4)
    x = to_tensor(_rand(16, 4, seed=3) * 5 + 2)
    y_train = bn(x)
    np.testing.assert_allclose(y_train.numpy().mean(axis=0), 0.0, atol=1e-4)
    bn.eval()
    y_eval = bn(x)
    # eval uses running stats, not batch stats
    assert abs(y_eval.numpy().mean()) > 1e-3


def test_layernorm_vs_numpy():
    ln = nn.LayerNorm(6)
    x = to_tensor(_rand(3, 6, seed=1))
    out = ln(x).numpy()
    xn = x.numpy()
    ref = (xn - xn.mean(-1, keepdims=True)) / np.sqrt(
        xn.var(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_embedding_layer():
    emb = nn.Embedding(10, 4, padding_idx=0)
    ids = to_tensor(np.array([[1, 2, 0]], dtype=np.int64))
    out = emb(ids)
    assert out.shape == [1, 3, 4]
    np.testing.assert_allclose(out.numpy()[0, 2], np.zeros(4), atol=1e-7)


def test_dropout_train_eval():
    d = nn.Dropout(0.5)
    x = to_tensor(np.ones((100,), np.float32))
    y = d(x)
    assert (y.numpy() == 0).sum() > 10
    d.eval()
    np.testing.assert_allclose(d(x).numpy(), x.numpy())


def test_cross_entropy_loss():
    logits = to_tensor(_rand(4, 5))
    labels = to_tensor(np.array([[1], [2], [3], [0]], dtype=np.int64))
    loss = nn.CrossEntropyLoss()(logits, labels)
    # numpy reference
    z = logits.numpy()
    z = z - z.max(-1, keepdims=True)
    logp = z - np.log(np.exp(z).sum(-1, keepdims=True))
    ref = -logp[np.arange(4), labels.numpy().ravel()].mean()
    np.testing.assert_allclose(float(loss), ref, rtol=1e-5)


def test_mse_and_l1():
    a, b = to_tensor(_rand(3, 3)), to_tensor(_rand(3, 3, seed=5))
    np.testing.assert_allclose(
        float(nn.MSELoss()(a, b)),
        ((a.numpy() - b.numpy()) ** 2).mean(), rtol=1e-5)
    np.testing.assert_allclose(
        float(nn.L1Loss()(a, b)),
        np.abs(a.numpy() - b.numpy()).mean(), rtol=1e-5)


def test_activations_numerics():
    x = to_tensor(_rand(10))
    np.testing.assert_allclose(F.relu(x).numpy(),
                               np.maximum(x.numpy(), 0), rtol=1e-6)
    np.testing.assert_allclose(F.sigmoid(x).numpy(),
                               1 / (1 + np.exp(-x.numpy())), rtol=1e-5)
    import math
    np.testing.assert_allclose(F.gelu(x).numpy(),
                               0.5 * x.numpy() * (1 + np.vectorize(
                                   lambda v: math.erf(v / math.sqrt(2)))(
                                   x.numpy())), rtol=1e-4, atol=1e-5)


def test_lstm_layer_shapes():
    lstm = nn.LSTM(input_size=6, hidden_size=8, num_layers=2)
    x = to_tensor(_rand(2, 5, 6))  # [batch, time, feat]
    out, (h, c) = lstm(x)
    assert out.shape == [2, 5, 8]
    assert h.shape == [2, 2, 8]  # [num_layers*ndir, batch, hidden]
    assert c.shape == [2, 2, 8]


def test_gru_and_simple_rnn():
    gru = nn.GRU(4, 6)
    out, h = gru(to_tensor(_rand(3, 7, 4)))
    assert out.shape == [3, 7, 6]
    rnn = nn.SimpleRNN(4, 6)
    out, h = rnn(to_tensor(_rand(3, 7, 4)))
    assert out.shape == [3, 7, 6]


def test_lstm_cell_matches_fused_single_step():
    cell = nn.LSTMCell(4, 4)
    x = to_tensor(_rand(2, 4))
    out, (h, c) = cell(x)
    assert out.shape == [2, 4]


def test_multihead_attention():
    mha = nn.MultiHeadAttention(16, 4)
    q = to_tensor(_rand(2, 5, 16))
    out = mha(q, q, q)
    assert out.shape == [2, 5, 16]


def test_transformer_encoder_backward():
    layer = nn.TransformerEncoderLayer(d_model=16, nhead=2,
                                       dim_feedforward=32, dropout=0.0)
    enc = nn.TransformerEncoder(layer, 2)
    x = to_tensor(_rand(2, 6, 16))
    out = enc(x)
    assert out.shape == [2, 6, 16]
    out.mean().backward()
    grads = [p.gradient() for p in enc.parameters()]
    assert sum(g is not None for g in grads) == len(grads)


def test_transformer_full():
    model = nn.Transformer(d_model=16, nhead=2, num_encoder_layers=1,
                           num_decoder_layers=1, dim_feedforward=32,
                           dropout=0.0)
    src = to_tensor(_rand(2, 4, 16))
    tgt = to_tensor(_rand(2, 3, 16, seed=2))
    out = model(src, tgt)
    assert out.shape == [2, 3, 16]


def test_sync_batch_norm_single_device():
    sbn = nn.SyncBatchNorm(4)
    x = to_tensor(_rand(8, 4, 2, 2))
    y = sbn(x)
    np.testing.assert_allclose(
        y.numpy().mean(axis=(0, 2, 3)), 0.0, atol=1e-4)


def test_convert_sync_batchnorm():
    m = nn.Sequential(nn.Conv2D(3, 4, 3), nn.BatchNorm2D(4))
    m2 = nn.SyncBatchNorm.convert_sync_batchnorm(m)
    assert isinstance(m2[1], nn.SyncBatchNorm)


def test_conv_transpose():
    m = nn.Conv2DTranspose(4, 3, 2, stride=2)
    x = to_tensor(_rand(1, 4, 5, 5))
    assert m(x).shape == [1, 3, 10, 10]


def test_interpolate_and_pixel_shuffle():
    x = to_tensor(_rand(1, 4, 4, 4))
    assert F.interpolate(x, size=[8, 8], mode="nearest").shape == \
        [1, 4, 8, 8]
    assert F.pixel_shuffle(x, 2).shape == [1, 1, 8, 8]


def test_functional_losses():
    logit = to_tensor(_rand(4))
    label = to_tensor((np.random.RandomState(1).rand(4) > 0.5)
                      .astype(np.float32))
    l1 = F.binary_cross_entropy_with_logits(logit, label)
    p = 1 / (1 + np.exp(-logit.numpy()))
    ref = -(label.numpy() * np.log(p) +
            (1 - label.numpy()) * np.log(1 - p)).mean()
    np.testing.assert_allclose(float(l1), ref, rtol=1e-4)


def test_nn_training_convergence():
    paddle.seed(42)
    net = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 1))
    x = to_tensor(_rand(32, 4, seed=7))
    y = to_tensor((_rand(32, 4, seed=7)[:, :1] * 2 + 1))
    losses = []
    for _ in range(80):
        loss = F.mse_loss(net(x), y)
        loss.backward()
        with paddle.no_grad():
            for p in net.parameters():
                p.set_value(p._value - 0.05 * p.grad_._value)
        net.clear_gradients()
        losses.append(float(loss))
    assert losses[-1] < 0.05 * losses[0]


def test_bn_buffers_in_state_dict():
    bn = nn.BatchNorm2D(4)
    sd = bn.state_dict()
    assert "_mean" in sd and "_variance" in sd
    assert len(bn.buffers()) == 2


def test_unstack_default_and_generic_rnn():
    x = to_tensor(_rand(2, 3, 4))
    parts = paddle.unstack(x, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 4]
    cell = nn.GRUCell(4, 5)
    rnn = nn.RNN(cell)
    out, h = rnn(to_tensor(_rand(2, 3, 4)))
    assert out.shape == [2, 3, 5]


def test_pad_last_dim_first():
    x = to_tensor(_rand(1, 1, 2, 3))
    out = F.pad(x, [1, 1, 0, 0])  # pads W only
    assert out.shape == [1, 1, 2, 5]
    out2 = F.pad(x, [0, 0, 2, 1])  # pads H only
    assert out2.shape == [1, 1, 5, 3]


def test_conv_bias_nhwc():
    x = to_tensor(_rand(1, 4, 4, 3))
    w = to_tensor(_rand(8, 3, 3, 3, seed=2))
    b = to_tensor(_rand(8, seed=3))
    out = F.conv2d(x, w, b, data_format="NHWC")
    assert out.shape[-1] == 8


def test_simple_rnn_relu_mode():
    rnn = nn.SimpleRNN(3, 4, activation="relu")
    assert rnn._mode == "RNN_RELU"
    out, _ = rnn(to_tensor(_rand(2, 5, 3)))
    assert (out.numpy() >= 0).all()


def test_gumbel_softmax_hard_axis():
    x = to_tensor(_rand(2, 3, 4))
    y = F.gumbel_softmax(x, hard=True, axis=1)
    assert y.shape == [2, 3, 4]
    s = y.numpy().sum(axis=1)
    np.testing.assert_allclose(s, np.ones_like(s), rtol=1e-5)


def test_grad_after_freed_graph_raises():
    x = to_tensor(np.ones(2, np.float32), stop_gradient=False)
    y = (x * 2.0).sum()
    y.backward()
    with pytest.raises(RuntimeError, match="retain_graph"):
        paddle.grad(y, x)


def test_cross_entropy_with_weight():
    logits = to_tensor(_rand(4, 3))
    labels = to_tensor(np.array([[0], [1], [2], [1]], dtype=np.int64))
    w = to_tensor(np.array([1.0, 2.0, 0.5], np.float32))
    loss = F.cross_entropy(logits, labels, weight=w)
    z = logits.numpy()
    z = z - z.max(-1, keepdims=True)
    logp = z - np.log(np.exp(z).sum(-1, keepdims=True))
    li = -logp[np.arange(4), labels.numpy().ravel()]
    wi = w.numpy()[labels.numpy().ravel()]
    np.testing.assert_allclose(float(loss), (li * wi).sum() / wi.sum(),
                               rtol=1e-5)


def test_scalar_operand_keeps_dtype():
    xi = to_tensor(np.array([1, 2], dtype=np.int32))
    assert paddle.add(xi, 1).dtype == "int32"

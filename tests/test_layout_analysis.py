"""Sharding-propagation analyzer tests (static/layout_analysis.py).

Three halves, mirroring ISSUE 12's acceptance contract:

  * FULL INFERENCE on the three tensor_parallel builders: col/row fc,
    parallel_attention, and the tp transformer LM all infer complete
    layouts with ZERO diagnostics, and the reshard table prices a 4×2
    col→row transformer block's mp-axis wire bytes at exact ring
    accounting (the number the 2-D planner consumes).
  * ZERO FALSE POSITIVES suite-wide: the `layout` verifier level is
    part of `level="all"`, so every sanctioned rewrite composition —
    plain, AMP, gradient_merge, ZeRO-1/2/3, elastic, recompute — must
    stay V6xx-clean (exemptions are stamped-metadata-driven: no model
    axis on a program means no finding, by construction).
  * the partition-rule seeding path: `tensor_parallel_rules` /
    MP_COL / MP_ROW recreate the builders' layout from names alone.

The per-defect mutation matrix lives in tests/test_tensor_parallel.py.
"""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers
from paddle_tpu.static.layout_analysis import (LayoutSpec,
                                               propagate_shardings)
from paddle_tpu.core.program import _reset_unique_names
from paddle_tpu.distributed.sharding import shard_optimizer_states

MESH = {"dp": 4, "mp": 2}


def _v6(report_or_layout):
    diags = getattr(report_or_layout, "diagnostics")
    return [d for d in diags if d.code.startswith("V6")]


def build_tp_pair(tp=2):
    from paddle_tpu.distributed.tensor_parallel import (col_parallel_fc,
                                                        row_parallel_fc)
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = col_parallel_fc(x, 16, act="relu", tp_degree=tp)
        pred = row_parallel_fc(h, 1, tp_degree=tp)
        loss = layers.mean(layers.square_error_cost(pred, y))
        static.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


# ---------------------------------------------------------------------------
# full inference on the three builders
# ---------------------------------------------------------------------------
class TestBuilderInference:
    def test_col_row_pair_full_layout(self):
        main, startup, loss = build_tp_pair()
        layout = propagate_shardings(main, mesh_shape=MESH, batch=16)
        assert not layout.diagnostics, layout.codes()
        assert layout.spec("col_parallel_fc_0.w_0").spec == (None, "mp")
        assert layout.spec("col_parallel_fc_0.b_0").spec == ("mp",)
        assert layout.spec("row_parallel_fc_0.w_0").spec == ("mp",)
        # hidden activation feature-sharded, partial cleared at the g
        assert "mp" in layout.spec("col_parallel_fc_0.tmp_2").axes()
        assert layout.spec("row_parallel_fc_0.tmp_0").partial == {"mp"}
        # feeds and loss replicated
        assert layout.spec("x").replicated
        assert layout.spec(loss.name).replicated

    def test_parallel_attention_head_split_tracked(self):
        from paddle_tpu.distributed.tensor_parallel import \
            parallel_attention
        _reset_unique_names()
        main, startup = static.Program(), static.Program()
        HID, HEADS, T = 16, 4, 6
        with static.program_guard(main, startup):
            x = layers.data("x", [-1, T, HID])
            y = layers.data("y", [-1, T, HID])
            out = parallel_attention(x, HID, HEADS, tp_degree=2)
            loss = layers.mean(layers.square(
                layers.elementwise_sub(out, y)))
            static.SGD(learning_rate=0.05).minimize(loss)
        layout = propagate_shardings(main, mesh_shape=MESH, batch=8)
        assert not layout.diagnostics, layout.codes()
        # q/k/v projections feature-sharded; the head split rides the
        # heads dim through reshape+transpose; scores head-sharded
        assert layout.spec("col_parallel_fc_0.tmp_1").spec == \
            (None, None, "mp")
        assert layout.spec("transpose2_0.tmp_0").spec == (None, "mp")
        assert layout.spec("softmax_0.tmp_0").spec == (None, "mp")
        # the block output (post row-parallel g) replicates again
        assert layout.spec(out.name).replicated

    def test_transformer_block_mp_wire_exact(self):
        """The acceptance number: a 4×2 col→row transformer block's
        mp-axis wire bytes at ring-accounting exactness — what the 2-D
        planner will consume."""
        from paddle_tpu.models import build_transformer_lm
        _reset_unique_names()
        B, S, H, L = 8, 8, 32, 2
        main, startup, loss, _ = build_transformer_lm(
            vocab_size=64, hidden=H, num_layers=L, num_heads=4,
            seq_len=S, tensor_parallel_degree=2)
        with static.program_guard(main, startup):
            static.Adam(learning_rate=1e-2).minimize(loss)
        layout = propagate_shardings(main, mesh_shape=MESH, batch=B)
        assert not layout.diagnostics, layout.codes()
        # per layer: attention g + MLP g, each allreducing [B,S,H] f32
        # over the mp ring: 2(g-1)/g × bytes with g=2
        g = MESH["mp"]
        expected = L * 2 * int(2 * (g - 1) / g * (B * S * H * 4))
        assert layout.wire_bytes_per_axis()["mp"] == expected
        assert layout.wire_bytes("mp") == expected
        # every reshard row carries provenance + spec transition
        for row in layout.reshard_table:
            assert row["op_uid"] is not None and row["var"], row
            assert row["from"] and row["to"], row
        # the table renders (docs example source)
        assert "mp_allreduce_sum" in layout.render_reshard_table()

    def test_mesh_inferred_from_builder_stamps(self):
        """With no mesh_shape, the degrees come from the builders'
        tp_degree stamps — the analyzer sees tp structure, not
        anonymous ops."""
        main, _, _ = build_tp_pair(tp=2)
        layout = propagate_shardings(main)
        assert layout.mesh_shape.get("mp") == 2
        assert not layout.diagnostics, layout.codes()


# ---------------------------------------------------------------------------
# partition-rule seeding (the GSPMD annotate-then-propagate path)
# ---------------------------------------------------------------------------
class TestRuleSeeding:
    def test_tensor_parallel_rules_recreate_builder_layout(self):
        from paddle_tpu.distributed.partition_spec import \
            tensor_parallel_rules
        main, _, _ = build_tp_pair()
        # strip the builder annotations; the name rules must recover them
        for v in main.all_parameters():
            v.attrs.pop("dist_attr", None)
        layout = propagate_shardings(main, mesh_shape=MESH,
                                     rules=tensor_parallel_rules())
        assert not layout.diagnostics, layout.codes()
        assert layout.spec("col_parallel_fc_0.w_0").spec == (None, "mp")
        assert layout.spec("row_parallel_fc_0.w_0").spec == ("mp",)
        assert layout.spec("row_parallel_fc_0.tmp_0").partial == {"mp"}

    def test_user_rule_seeds_intermediate_var(self):
        main, _, _ = build_tp_pair()
        # a rule can pin a non-param var too ("tp" spelling accepted)
        layout = propagate_shardings(
            main, mesh_shape=MESH,
            rules=[(r"^var:col_parallel_fc_0\.tmp_0$", (None, "tp"))])
        assert layout.spec("col_parallel_fc_0.tmp_0").spec == \
            (None, "mp")

    def test_layout_spec_api(self):
        s = LayoutSpec((None, "mp"), partial=("mp",))
        assert s.axis_at(1) == "mp" and s.axis_at(0) is None
        assert s.dim_of("mp") == 1
        assert s.model_axes() == {"mp"} and s.model_partial() == {"mp"}
        assert not s.replicated
        assert s.cleared("mp").partial == frozenset()
        assert s.without_axis("mp").replicated  # drops shard + partial
        assert LayoutSpec((None, None)).replicated  # trailing Nones trim
        assert "partial(mp)" in s.render()


# ---------------------------------------------------------------------------
# suite-wide false-positive pins: every sanctioned composition stays
# V6xx-clean under level="all" (the armed-smoke sweep contract)
# ---------------------------------------------------------------------------
def _build_train(opt_cls=None):
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        (opt_cls or static.Adam)(learning_rate=1e-3).minimize(loss)
    return main, startup, loss


class TestNoFalsePositives:
    def _assert_clean(self, main, startup, loss):
        report = static.check_program(main, level="all", startup=startup,
                                      fetch_list=[loss])
        assert not _v6(report), report.render()

    def test_plain(self):
        self._assert_clean(*_build_train())

    @pytest.mark.parametrize("stage", [1, 2, 3])
    def test_zero_stages(self, stage):
        main, startup, loss = _build_train()
        shard_optimizer_states(main, startup, dp_degree=8, stage=stage)
        self._assert_clean(main, startup, loss)

    def test_gradient_merge(self):
        main, startup, loss = _build_train()
        static.gradient_merge(main, 2, startup)
        self._assert_clean(main, startup, loss)

    def test_zero2_plus_gm(self):
        main, startup, loss = _build_train()
        shard_optimizer_states(main, startup, dp_degree=8, stage=2)
        static.gradient_merge(main, 2, startup)
        self._assert_clean(main, startup, loss)

    def test_elastic(self):
        from paddle_tpu.distributed.elastic import elasticize
        main, startup, loss = _build_train(opt_cls=static.SGD)
        elasticize(main, startup, logical_dp=8, loss_name=loss)
        report = static.check_program(
            main, level="all", startup=startup,
            fetch_list=[loss.name + "@ELASTIC_AVG"])
        assert not _v6(report), report.render()

    def test_amp(self):
        from paddle_tpu import amp
        _reset_unique_names()
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = layers.data("x", [-1, 8])
            y = layers.data("y", [-1, 1])
            h = layers.fc(x, 16, act="relu")
            pred = layers.fc(h, 1)
            loss = layers.mean(layers.square_error_cost(pred, y))
            opt = amp.decorate(static.Adam(learning_rate=1e-3),
                               use_dynamic_loss_scaling=True)
            opt.minimize(loss, startup)
        self._assert_clean(main, startup, loss)

    def test_tp_program_clean_at_level_all(self):
        main, startup, loss = build_tp_pair()
        self._assert_clean(main, startup, loss)

    def test_tp_dist_attr_survives_roundtrip_and_stays_clean(self):
        from paddle_tpu.core.program import Program
        main, _, loss = build_tp_pair()
        clone = Program.parse_from_string(main.serialize_to_string())
        layout = propagate_shardings(clone, mesh_shape=MESH)
        assert not layout.diagnostics, layout.codes()
        assert layout.spec("row_parallel_fc_0.tmp_0").partial == {"mp"}


# ---------------------------------------------------------------------------
# per-ring wire pricing (the satellite: non-dp rings price at their own
# degree, and the per-axis split feeds bench/planner)
# ---------------------------------------------------------------------------
class TestPerAxisWire:
    @staticmethod
    def _static_batch_tp(tp=2):
        """tp pair with a STATIC batch so activation collectives have
        known bytes (collective_sequence prices -1 dims as unknown)."""
        from paddle_tpu.distributed.tensor_parallel import (
            col_parallel_fc, row_parallel_fc)
        _reset_unique_names()
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = layers.data("x", [16, 8])
            y = layers.data("y", [16, 1])
            h = col_parallel_fc(x, 16, act="relu", tp_degree=tp)
            pred = row_parallel_fc(h, 1, tp_degree=tp)
            loss = layers.mean(layers.square_error_cost(pred, y))
            static.SGD(learning_rate=0.05).minimize(loss)
        return main, startup, loss

    def test_tp_ring_priced_at_its_own_degree(self):
        from paddle_tpu.static.verifier import (collective_sequence,
                                                entry_wire_bytes,
                                                program_ring_degrees)
        main, _, _ = self._static_batch_tp(tp=2)
        degrees = program_ring_degrees(main)
        from paddle_tpu.distributed.tensor_parallel import TP_RING_ID
        assert degrees.get(TP_RING_ID) == 2
        ar = next(e for e in collective_sequence(main)
                  if e["type"] == "mp_allreduce_sum")
        assert ar["nbytes"] == 16 * 1 * 4
        # stamped degree 2 wins over any world: 2(2-1)/2 = 1.0 × bytes
        assert entry_wire_bytes(ar, 8) == ar["nbytes"]
        assert entry_wire_bytes(ar, 64) == ar["nbytes"]

    def test_by_axis_split(self):
        from paddle_tpu.distributed.compiled_program import \
            insert_grad_allreduce
        main, _, _ = self._static_batch_tp(tp=2)
        reduced = insert_grad_allreduce(main)
        per = static.collective_wire_bytes_by_axis(reduced, 8)
        assert per.get("dp", 0) > 0 and per.get("mp", 0) > 0, per
        total = static.collective_wire_bytes(reduced, 8)
        assert total == sum(per.values())

    def test_planner_trace_carries_per_axis_wire(self):
        main, startup, loss = _build_train()
        plan = static.plan_program(main, startup, world=8, batch=16,
                                   knobs={"dp_shard": (8,),
                                          "zero_stage": (1,),
                                          "grad_merge": (1,)})
        assert "predicted_wire_bytes_per_axis" in plan.to_dict()
        rec = plan.trace[0]
        assert "wire_bytes_per_axis" in rec
        assert sum(rec["wire_bytes_per_axis"].values()) == \
            rec["wire_bytes"]

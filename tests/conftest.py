"""Test config: run everything on a virtual 8-device CPU mesh so multi-chip
sharding paths compile and execute without TPU hardware (the driver separately
dry-runs the multichip path; bench.py uses the real chip).

NOTE: the container's sitecustomize registers the `axon` TPU-tunnel PJRT
plugin and imports jax at interpreter startup with JAX_PLATFORMS=axon, so env
vars are too late here — use jax.config.update, which takes effect because
backend *initialization* is still lazy at conftest time.
"""
import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: medium-shape dryruns (seq-512 numerics checks)")

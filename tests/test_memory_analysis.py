"""Compile-time HBM accounting (static/memory_analysis.py +
Executor.memory_report).

The estimator's job is ordinal and threshold truth, not byte-exactness:
remat must walk SMALLER than no-remat, bigger batches must walk bigger,
the PADDLE_TPU_HBM_BYTES budget must flip the fits verdict, and where
the installed backend exposes ``compile().memory_analysis()`` the walk
must land within an order-of-magnitude band of XLA's own accounting
(XLA fuses/rematerializes aggressively, so tight tolerances would pin
implementation noise, not correctness).
"""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.core.flags import set_flags
from paddle_tpu.core.program import _reset_unique_names
from paddle_tpu.static import layers, nets


VOCAB, SEQ, HIDDEN, HEADS = 128, 16, 32, 2


@pytest.fixture(autouse=True)
def _clean_flags():
    yield
    set_flags({"recompute": "", "hbm_assume_batch": 0})


def build_toy_transformer(layers_n=4, remat=False):
    _reset_unique_names()
    if remat:
        set_flags({"recompute": "always"})
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            ids = layers.data("ids", [-1, SEQ], dtype="int64")
            labels = layers.data("labels", [-1, SEQ, 1], dtype="int64")
            h = layers.embedding(ids, size=[VOCAB, HIDDEN])
            h = layers.layer_norm(h, begin_norm_axis=2)
            for _ in range(layers_n):
                q = layers.fc(h, HIDDEN, num_flatten_dims=2)
                k = layers.fc(h, HIDDEN, num_flatten_dims=2)
                v = layers.fc(h, HIDDEN, num_flatten_dims=2)
                ctx = nets.scaled_dot_product_attention(q, k, v,
                                                        num_heads=HEADS)
                h = layers.layer_norm(layers.elementwise_add(h, ctx),
                                      begin_norm_axis=2)
                ffn = layers.fc(h, HIDDEN * 2, num_flatten_dims=2,
                                act="gelu")
                h = layers.layer_norm(
                    layers.elementwise_add(
                        h, layers.fc(ffn, HIDDEN, num_flatten_dims=2)),
                    begin_norm_axis=2)
            logits = layers.fc(h, VOCAB, num_flatten_dims=2)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, labels))
            static.Adam(learning_rate=1e-3).minimize(loss)
    finally:
        set_flags({"recompute": ""})
    return main, startup, loss


def test_remat_peak_below_plain_peak_on_4_layer_transformer():
    plain, _, _ = build_toy_transformer(layers_n=4, remat=False)
    remat, _, _ = build_toy_transformer(layers_n=4, remat=True)
    p = static.estimate_peak_bytes(plain, batch=32)
    r = static.estimate_peak_bytes(remat, batch=32)
    assert r < p, (r, p)
    # the saving is activations, not persistables: both walk the same
    # parameter set
    ra = static.analyze_program(remat, batch=32)
    pa = static.analyze_program(plain, batch=32)
    assert ra["persistable_bytes"] == pa["persistable_bytes"]
    assert ra["activation_peak_bytes"] < pa["activation_peak_bytes"]


def test_peak_grows_with_batch():
    main, _, _ = build_toy_transformer(layers_n=2)
    peaks = [static.estimate_peak_bytes(main, batch=b)
             for b in (4, 8, 16, 32)]
    assert peaks == sorted(peaks) and peaks[0] < peaks[-1], peaks


def test_oom_prediction_honors_budget_env(monkeypatch):
    from paddle_tpu.static.memory_analysis import HBM_BUDGET_ENV
    main, _, _ = build_toy_transformer(layers_n=2)
    peak = static.estimate_peak_bytes(main, batch=8)
    monkeypatch.setenv(HBM_BUDGET_ENV, str(peak * 4))
    assert static.analyze_program(main, batch=8)["fits"] is True
    monkeypatch.setenv(HBM_BUDGET_ENV, str(max(1, peak // 4)))
    assert static.analyze_program(main, batch=8)["fits"] is False
    # and the budget itself is reported
    assert static.analyze_program(
        main, batch=8)["budget_bytes"] == max(1, peak // 4)


def test_phase_peaks_and_report_shape():
    main, _, _ = build_toy_transformer(layers_n=2)
    r = static.analyze_program(main, batch=8)
    assert r["peak_bytes"] == max(r["phase_peaks"].values())
    assert set(r["phase_peaks"]) == {"forward", "backward", "optimize"}
    assert r["top_live"] and all(isinstance(c, int)
                                 for _, c in r["top_live"])
    assert r["n_unknown_vars"] == 0
    # optimizer phase holds params + grads + adam moments, far below the
    # activation peak but above the bare persistables
    assert r["phase_peaks"]["optimize"] >= r["persistable_bytes"]


def test_memory_report_estimate_without_device_or_feed():
    main, _, _ = build_toy_transformer(layers_n=2)
    exe = static.Executor()
    rep = exe.memory_report(main, batch=16)
    assert rep["peak_bytes"] == static.estimate_peak_bytes(main, batch=16)
    assert rep["xla"] is None
    assert rep["estimate"]["batch"] == 16


def test_memory_report_vs_xla_ground_truth_on_cpu():
    """Where the backend exposes compile().memory_analysis(), the walked
    peak must sit within an order-of-magnitude band of XLA's number —
    catching unit errors (bytes vs elements) and liveness blowups while
    tolerating XLA's fusion/remat freedom."""
    main, startup, loss = build_toy_transformer(layers_n=2)
    exe, scope = static.Executor(), static.Scope()
    rng = np.random.RandomState(0)
    feed = {"ids": rng.randint(0, VOCAB, (8, SEQ)).astype(np.int32),
            "labels": rng.randint(0, VOCAB, (8, SEQ, 1)).astype(np.int32)}
    with static.scope_guard(scope):
        exe.run(startup)
        rep = exe.memory_report(main, feed=feed, scope=scope)
    if rep["xla"] is None:
        pytest.skip("backend exposes no memory_analysis(): "
                    + rep.get("xla_error", "none returned"))
    xla_peak = rep["xla"]["peak_bytes"]
    est_peak = rep["peak_bytes"]
    assert xla_peak > 0
    assert est_peak / 10 <= xla_peak <= est_peak * 10, \
        (est_peak, rep["xla"])


def test_select_layer_checkpoints_picks_one_per_layer():
    for n in (2, 4):
        main, _, _ = build_toy_transformer(layers_n=n)
        picks = static.select_layer_checkpoints(main)
        assert len(picks) == n, (n, picks)
        # each pick is a layer_norm output declared in the block
        blk = main.global_block()
        assert all(blk.has_var(p) for p in picks)

"""ZeRO sharded data parallelism, stages 1-3 (distributed/sharding.py).

The contracts this tier rests on, all on the virtual 8-device CPU mesh
(conftest.py):
  * numerical equivalence — plain-DP and ZeRO-1/2/3 training produce
    the same loss trajectory and parameters (allclose atol=1e-6 fp32)
    for Adam/AdamW with and without AMP, gradient_merge and remat;
  * the bucketed c_reducescatter / c_allgather round-trip with pow2
    padding un-pads correctly at the kernel level;
  * optimizer slots (stage 1), gradient accumulators (stage 2 under
    gradient_merge) and parameters (stage 3) are genuinely sharded:
    per-chip bytes ≈ 1/8 of the replicated footprint (memory_analysis
    world-size accounting), and stage 3 emits just-in-time per-bucket
    forward/backward allgathers with NO publish allgather;
  * insert_grad_allreduce is idempotent and ZeRO-aware (no double
    reduction, regression for the fleet double-apply bug — including
    the stage-2 shard-accumulator producer chain);
  * the degenerate single-chip path (collectives → identity) matches
    plain training bit-for-bit, including run_steps donated-state
    threading;
  * checkpoint layout converters round-trip across STAGE changes
    (zero3 → zero1 → plain) via unshard_state/reshard_state.

Tier-1 keeps the acceptance bar (Adam 20 steps at stages 1 and 3,
zero2+gm) and the fullest composition (AdamW+AMP+gradient_merge); the
rest of the equivalence matrix is marked `slow` — each is two more
whole-mesh compiles and the tier-1 suite runs against a hard 870 s
timeout (ROADMAP).  Perf rounds run the full matrix.
"""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers
from paddle_tpu import amp
from paddle_tpu.core.program import _reset_unique_names
from paddle_tpu.distributed.compiled_program import (
    CompiledProgram, insert_grad_allreduce)
from paddle_tpu.distributed.sharding import (
    shard_optimizer_states, ShardingPlan, unshard_state, reshard_state)

WORLD = 8


def _build(opt_fn=None, use_amp=False):
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        opt = (opt_fn or (lambda: static.Adam(learning_rate=1e-2)))()
        if use_amp:
            opt = amp.decorate(opt, init_loss_scaling=1.0,
                               use_dynamic_loss_scaling=False,
                               dest_dtype="bfloat16")
        opt.minimize(loss)
    return main, startup, loss


def _feeds(n, batch=16, seed=0):
    rng = np.random.RandomState(seed)
    return [{"x": rng.rand(batch, 8).astype(np.float32),
             "y": rng.rand(batch, 1).astype(np.float32)}
            for _ in range(n)]


def _params_of(main, scope, plan=None):
    """Trainable params as host arrays — through the stage-3 layout
    converter when the params live packed in dp_shard buckets."""
    if plan is not None and getattr(plan, "stage", 1) >= 3 and \
            plan.param_bucket_names():
        from paddle_tpu.static.executor import _persistable_names
        state = {n: np.asarray(scope.get(n))
                 for n in _persistable_names(main)
                 if scope.get(n) is not None}
        unpacked = unshard_state(state, plan)
        return {p.name: unpacked[p.name] for p in main.all_parameters()
                if p.name in unpacked}
    return {p.name: np.asarray(scope.get(p.name))
            for p in main.all_parameters() if scope.get(p.name) is not None}


def _train_mesh(main, startup, loss, steps, plan=None):
    compiled = CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        losses = [float(exe.run(compiled, feed=f, fetch_list=[loss])[0])
                  for f in _feeds(steps)]
        params = _params_of(main, scope, plan)
    return losses, params, scope


def _assert_equiv(opt_fn=None, use_amp=False, gm=0, steps=8, atol=1e-6,
                  stage=1):
    runs = []
    for shard in (False, True):
        main, startup, loss = _build(opt_fn, use_amp)
        plan = None
        if shard:
            plan = shard_optimizer_states(main, startup, dp_degree=WORLD,
                                          stage=stage)
            assert plan.buckets and plan.stage == stage
        if gm:
            static.gradient_merge(main, gm, startup)
        runs.append(_train_mesh(main, startup, loss, steps, plan)[:2])
    (l0, p0), (l1, p1) = runs
    np.testing.assert_allclose(l0, l1, atol=atol, rtol=atol)
    for k in p0:
        np.testing.assert_allclose(p0[k], p1[k], atol=atol, rtol=atol,
                                   err_msg=k)


# ---------------------------------------------------------------------------
# numerical equivalence, 8-device mesh
# ---------------------------------------------------------------------------
def test_adam_equivalence_20_steps():
    # the acceptance bar: ≥20 steps, fp32, allclose atol=1e-6
    _assert_equiv(lambda: static.Adam(learning_rate=1e-2), steps=20)


def test_zero3_adam_equivalence_20_steps():
    # the stage-3 acceptance bar: params sharded + JIT gathers, ≥20
    # steps, allclose atol=1e-6 to the fully replicated run
    _assert_equiv(lambda: static.Adam(learning_rate=1e-2), steps=20,
                  stage=3)


def test_zero2_gm_equivalence_20_steps():
    # stage 2 is only distinct under gradient_merge: the accumulator is
    # the 1/N reduce-scattered shard, numerics must still match plain+gm
    _assert_equiv(lambda: static.Adam(learning_rate=1e-2), steps=20,
                  gm=2, stage=2)


@pytest.mark.slow
def test_zero3_adamw_equivalence_20_steps():
    _assert_equiv(lambda: static.AdamW(learning_rate=1e-2,
                                       weight_decay=0.01), steps=20,
                  stage=3)


@pytest.mark.slow
def test_zero2_adamw_gm_equivalence_20_steps():
    _assert_equiv(lambda: static.AdamW(learning_rate=1e-2,
                                       weight_decay=0.01), steps=20, gm=2,
                  stage=2)


@pytest.mark.slow
def test_zero3_gm_equivalence():
    _assert_equiv(lambda: static.Adam(learning_rate=1e-2), gm=2, stage=3)


@pytest.mark.slow
def test_zero3_amp_equivalence():
    _assert_equiv(lambda: static.Adam(learning_rate=1e-2), use_amp=True,
                  stage=3)


@pytest.mark.slow
def test_zero2_amp_gm_falls_back_and_matches():
    # AMP interposes unscale between backward and the buckets, so the
    # sharded accumulator is unsound — gradient_merge must fall back to
    # full-size accumulators (with a warning) and numerics must hold
    import warnings as _w
    runs = []
    for shard in (False, True):
        main, startup, loss = _build(use_amp=True)
        if shard:
            shard_optimizer_states(main, startup, dp_degree=WORLD, stage=2)
            with _w.catch_warnings(record=True) as rec:
                _w.simplefilter("always")
                static.gradient_merge(main, 2, startup)
            assert any("falling back" in str(x.message) for x in rec)
        else:
            static.gradient_merge(main, 2, startup)
        runs.append(_train_mesh(main, startup, loss, 8)[:2])
    (l0, p0), (l1, p1) = runs
    np.testing.assert_allclose(l0, l1, atol=1e-6, rtol=1e-6)
    for k in p0:
        np.testing.assert_allclose(p0[k], p1[k], atol=1e-6, rtol=1e-6,
                                   err_msg=k)


@pytest.mark.slow
def test_adam_amp_equivalence():
    _assert_equiv(lambda: static.Adam(learning_rate=1e-2), use_amp=True)


@pytest.mark.slow
def test_adamw_equivalence():
    _assert_equiv(lambda: static.AdamW(learning_rate=1e-2,
                                       weight_decay=0.01))


def test_adamw_amp_gradient_merge_equivalence():
    _assert_equiv(lambda: static.AdamW(learning_rate=1e-2,
                                       weight_decay=0.01),
                  use_amp=True, gm=2)


@pytest.mark.slow
def test_adam_gradient_merge_equivalence():
    _assert_equiv(lambda: static.Adam(learning_rate=1e-2), gm=2)


@pytest.mark.slow
def test_momentum_and_sgd_equivalence():
    _assert_equiv(lambda: static.Momentum(learning_rate=1e-2,
                                          momentum=0.9), steps=6)
    _assert_equiv(lambda: static.SGD(learning_rate=1e-2), steps=6)


@pytest.mark.slow
def test_recompute_composes_with_sharding():
    """FLAGS_recompute-style activation checkpointing rewrites
    forward/backward; sharding rewrites the optimize tail — composed,
    training still matches plain DP."""
    def build_remat():
        _reset_unique_names()
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = layers.data("x", [-1, 8])
            y = layers.data("y", [-1, 1])
            h1 = layers.fc(x, 16, act="relu")
            h2 = layers.fc(h1, 16, act="relu")
            pred = layers.fc(h2, 1)
            loss = layers.mean(
                layers.square(layers.elementwise_sub(pred, y)))
            opt = static.RecomputeOptimizer(
                static.Adam(learning_rate=1e-2))
            opt._set_checkpoints([h1])
            opt.minimize(loss)
        return main, startup, loss

    runs = []
    for shard in (False, True):
        main, startup, loss = build_remat()
        # the rewrite replays the h1->h2 segment inside backward: the
        # relu forward runs once more than the plain program's two
        assert sum(1 for op in main.global_block().ops
                   if op.type == "relu") == 3
        if shard:
            shard_optimizer_states(main, startup, dp_degree=WORLD)
        runs.append(_train_mesh(main, startup, loss, 6)[:2])
    (l0, p0), (l1, p1) = runs
    np.testing.assert_allclose(l0, l1, atol=1e-6)
    for k in p0:
        np.testing.assert_allclose(p0[k], p1[k], atol=1e-6, err_msg=k)


@pytest.mark.slow
def test_lamb_equivalence_global_norms():
    # LAMB's trust ratio needs GLOBAL ‖p‖/‖r‖ — the sharded kernel psums
    # the squared norms, so per-param numbers match the unsharded update
    # (reduction-order wiggle only)
    _assert_equiv(lambda: static.Lamb(learning_rate=1e-2), steps=6,
                  atol=1e-5)


# ---------------------------------------------------------------------------
# degenerate single-chip + run_steps threading
# ---------------------------------------------------------------------------
def test_single_device_degenerate_matches_plain():
    runs = []
    for shard in (False, True):
        main, startup, loss = _build()
        if shard:
            shard_optimizer_states(main, startup, dp_degree=WORLD)
        exe = static.Executor()
        scope = static.Scope()
        with static.scope_guard(scope):
            exe.run(startup)
            losses = [float(exe.run(main, feed=f, fetch_list=[loss])[0])
                      for f in _feeds(6)]
            params = {p.name: np.asarray(scope.get(p.name))
                      for p in main.all_parameters()}
        runs.append((losses, params))
    (l0, p0), (l1, p1) = runs
    np.testing.assert_allclose(l0, l1, atol=1e-6)
    for k in p0:
        np.testing.assert_allclose(p0[k], p1[k], atol=1e-6, err_msg=k)


def test_run_steps_threads_sharded_slots():
    runs = []
    for shard in (False, True):
        main, startup, loss = _build()
        if shard:
            shard_optimizer_states(main, startup, dp_degree=WORLD)
        exe = static.Executor()
        scope = static.Scope()
        fs = _feeds(5)
        sfeed = {k: np.stack([f[k] for f in fs]) for k in fs[0]}
        with static.scope_guard(scope):
            exe.run(startup)
            out = exe.run_steps(main, feed=sfeed, fetch_list=[loss])
        runs.append(np.asarray(out[0]))
    np.testing.assert_allclose(runs[0], runs[1], atol=1e-6)


# ---------------------------------------------------------------------------
# kernel-level reduce-scatter / allgather round trip with pow2 padding
# ---------------------------------------------------------------------------
def test_reducescatter_allgather_roundtrip_pow2_pad():
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.utils.shard_map_compat import shard_map_unchecked
    from paddle_tpu.ops.registry import get_op_info, OpContext

    rs = get_op_info("c_reducescatter").kernel
    ag = get_op_info("c_allgather").kernel
    devs = np.array(jax.devices()[:WORLD])
    mesh = Mesh(devs, ("dp",))
    raw = np.arange(10, dtype=np.float32)  # 10 does not divide 8
    padded_len = -(-raw.size // WORLD) * WORLD  # 16 (pow2 world → pow2 pad)
    padded = np.pad(raw, (0, padded_len - raw.size))

    def step(x):
        ctx = OpContext(mesh_axes=("dp",), dist_info={0: "dp"})
        shard = rs({"X": x}, {"ring_id": 0}, ctx)["Out"]
        full = ag({"X": shard}, {"ring_id": 0}, ctx)["Out"]
        return shard, full

    fn = jax.jit(shard_map_unchecked(
        step, mesh, in_specs=(P(),), out_specs=(P("dp"), P())))
    shard, full = fn(padded)
    # reduce-scatter sums the replicated input over 8 ranks, each rank
    # keeping its slice; the gathered result reassembles rank-order
    assert shard.shape == (padded_len,)  # global view of [2]-per-rank
    np.testing.assert_allclose(np.asarray(full), padded * WORLD)
    # un-pad recovers the raw segment exactly
    np.testing.assert_allclose(np.asarray(full)[:raw.size], raw * WORLD)


# ---------------------------------------------------------------------------
# insert_grad_allreduce idempotency (regression: fleet double-apply)
# ---------------------------------------------------------------------------
def test_insert_grad_allreduce_idempotent():
    main, startup, loss = _build()
    once = insert_grad_allreduce(main)
    n1 = sum(1 for op in once.global_block().ops
             if op.type == "c_allreduce_sum")
    assert n1 == len(main.all_parameters())
    twice = insert_grad_allreduce(once)
    n2 = sum(1 for op in twice.global_block().ops
             if op.type == "c_allreduce_sum")
    assert n2 == n1, "double apply double-reduced"


def test_insert_grad_allreduce_skips_sharded_grads():
    main, startup, loss = _build()
    shard_optimizer_states(main, startup, dp_degree=WORLD)
    rewritten = insert_grad_allreduce(main)
    assert not any(op.type == "c_allreduce_sum"
                   for op in rewritten.global_block().ops)


# ---------------------------------------------------------------------------
# memory accounting + plan + wire-byte accounting
# ---------------------------------------------------------------------------
def test_sharded_slot_bytes_one_eighth():
    main, startup, loss = _build()
    plain = static.analyze_program(main, batch=16)
    predicted = static.analyze_program(main, batch=16, dp_shard=WORLD)
    shard_optimizer_states(main, startup, dp_degree=WORLD)
    sharded = static.analyze_program(main, batch=16)
    one_bucket = max(b.shape[0] for b in
                     main.global_block().vars.values()
                     if b.attrs.get("dp_shard")) * 4
    # acceptance: slot bytes ≤ plain/8 + one bucket (padding overhead)
    assert sharded["optimizer_slot_bytes"] <= \
        plain["optimizer_slot_bytes"] // WORLD + one_bucket
    assert predicted["optimizer_slot_bytes"] <= \
        plain["optimizer_slot_bytes"] // WORLD + one_bucket
    assert sharded["persistable_bytes"] < plain["persistable_bytes"]


def test_prediction_skips_unshardable_optimizer_slots():
    """analyze_program(dp_shard=N) must divide ONLY slots the rewrite
    would actually shard — an Adamax moment stays replicated, so the
    predicted verdict never claims memory the pass cannot deliver."""
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        pred = layers.fc(x, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adamax(learning_rate=1e-2).minimize(loss)
    plain = static.analyze_program(main, batch=16)
    predicted = static.analyze_program(main, batch=16, dp_shard=WORLD)
    assert predicted["optimizer_slot_bytes"] == \
        plain["optimizer_slot_bytes"]
    # and the pass itself refuses the op: no buckets
    assert shard_optimizer_states(main, startup,
                                  dp_degree=WORLD).buckets == []


def test_collective_bytes_zero1_matches_allreduce_volume():
    # ZeRO-1's whole point: SAME wire volume (rs + ag == allreduce),
    # 1/N the optimizer memory.  Priced by the verifier's ring-accounted
    # extractor (static.collective_wire_bytes — the planner's wire
    # substrate, which superseded sharding.collective_bytes_per_step).
    main, startup, loss = _build()
    plain = static.collective_wire_bytes(insert_grad_allreduce(main), WORLD)
    shard_optimizer_states(main, startup, dp_degree=WORLD)
    zero = static.collective_wire_bytes(insert_grad_allreduce(main), WORLD)
    assert plain > 0
    # padding can only add a sliver
    assert plain <= zero <= int(plain * 1.25)


def test_collective_bytes_per_step_shim_retired():
    """The PR-5 helper `sharding.collective_bytes_per_step` was a
    warn-once shim since PR 9 and is now RETIRED: the accounting lives
    only in static.collective_wire_bytes (ring-accounted, all
    collective types/rings)."""
    from paddle_tpu.distributed import sharding as sharding_mod
    assert not hasattr(sharding_mod, "collective_bytes_per_step")
    import paddle_tpu.distributed as dist
    assert not hasattr(dist, "collective_bytes_per_step")


def test_zero3_structure_and_per_rank_param_shards():
    """Stage 3 op-chain contracts: params packed into a dp_shard
    persistable bucket at 1/8 per rank, JIT forward AND backward
    gathers present, the stage-1 publish allgather GONE, original
    params no longer persistable, and a short mesh run compiles once."""
    main, startup, loss = _build()
    n_params = len(main.all_parameters())
    plan = shard_optimizer_states(main, startup, dp_degree=WORLD, stage=3)
    assert plan.stage == 3 and plan.buckets
    block = main.global_block()
    # params de-persisted, bucket persistable + marked
    for p in main.all_parameters():
        assert not block.var(p.name).persistable, p.name
    pbuckets = plan.param_bucket_names()
    assert pbuckets
    for name in pbuckets:
        v = block.var(name)
        assert v.persistable and v.attrs.get("dp_shard") == WORLD
        assert v.attrs.get("zero_param_bucket")
    # JIT gathers: one fwd + one bwd per bucket, no publish allgather
    ags = [op for op in block.ops if op.type == "c_allgather"]
    roles = [op.attrs.get("zero_role") for op in ags]
    assert roles.count("gather_fwd") == len(plan.buckets)
    assert roles.count("gather_bwd") == len(plan.buckets)
    assert "publish" not in roles
    # backward readers were renamed onto the re-gathered aliases
    from paddle_tpu.core.program import OpRole
    pnames = {p["param"] for b in plan.buckets for p in b["params"]}
    for op in block.ops:
        role = int(op.attrs.get(OpRole.KEY, 0))
        if role & OpRole.Backward and op.attrs.get("zero_role") is None:
            assert not (pnames & set(op.input_names())), op
    # mesh run: loss finite, param bucket sharded 1/8 per rank
    compiled = CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        for f in _feeds(3):
            out = exe.run(compiled, feed=f, fetch_list=[loss])
        assert np.isfinite(np.asarray(out[0])).all()
        v = scope.get(pbuckets[0])
        shards = getattr(v, "addressable_shards", None)
        if shards:
            b0 = plan.buckets[0]
            assert {tuple(s.data.shape) for s in shards} == \
                {(b0["shard_len"],)}
    assert n_params == len(main.all_parameters())  # still introspectable


def test_zero2_interleaves_reduce_scatter_into_backward():
    """Stage>=2 places each bucket's reduce-scatter right after the
    bucket's last gradient producer (Backward role), so full-size grads
    die bucket-by-bucket instead of pooling in the optimizer tail — the
    walker must see the grad-HBM cut."""
    from paddle_tpu.core.program import OpRole
    main, startup, loss = _build()
    plain = static.analyze_program(main, batch=16)
    shard_optimizer_states(main, startup, dp_degree=WORLD, stage=2)
    block = main.global_block()
    rs_idx = [i for i, op in enumerate(block.ops)
              if op.type == "c_reducescatter"]
    first_opt = next(i for i, op in enumerate(block.ops)
                     if int(op.attrs.get(OpRole.KEY, 0)) == OpRole.Optimize)
    assert rs_idx and all(i < first_opt for i in rs_idx), \
        (rs_idx, first_opt)
    for i in rs_idx:
        assert int(block.ops[i].attrs.get(OpRole.KEY)) == OpRole.Backward
    sharded = static.analyze_program(main, batch=16)
    assert sharded["phase_peaks"]["backward"] <= \
        plain["phase_peaks"]["backward"] + 4 * max(
            b["padded_len"] for b in main._zero_shard_plan.buckets) * 2


def test_zero2_gm_shard_accumulator_is_dp_shard():
    """Under stage 2 + gradient_merge the accumulation buffer is the
    reduce-scattered bucket shard: a dp_shard persistable at the global
    padded length (1/N per chip), and NO full-size per-param
    @GradientMerge accumulators exist for bucketed grads."""
    main, startup, loss = _build()
    plan = shard_optimizer_states(main, startup, dp_degree=WORLD, stage=2)
    static.gradient_merge(main, 2, startup)
    block = main.global_block()
    saccs = [v for v in block.vars.values()
             if "@GSHARD_ACC" in v.name and v.persistable]
    assert len(saccs) == plan.n_buckets
    for v in saccs:
        assert v.attrs.get("dp_shard") == WORLD
        assert v.shape[0] % WORLD == 0
    full_accs = [v for v in block.vars.values()
                 if "@GradientMerge" in v.name and v.persistable]
    assert not full_accs
    # resume contract: the shard accumulators ride _gm_meta like any
    # accumulator (topology-shifted restore zeroes partial windows)
    assert set(v.name for v in saccs) <= set(main._gm_meta["accs"])


def test_checkpoint_roundtrip_across_stage_changes():
    """zero3 → zero1 → plain via the extended converters: a stage-3
    checkpoint restores into a stage-1 program (params unpacked,
    slots re-bucketed), then into a plain program, with the parameter
    payload bitwise intact at every hop."""
    from paddle_tpu.static.executor import _persistable_names
    main, startup, loss = _build()
    plan3 = shard_optimizer_states(main, startup, dp_degree=WORLD, stage=3)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        for f in _feeds(3):
            exe.run(main, feed=f, fetch_list=[loss])
        state3 = {n: np.asarray(scope.get(n))
                  for n in _persistable_names(main)
                  if scope.get(n) is not None}
    # hop 1: zero3 -> plain layout (params unpacked to full shapes)
    plain_state = unshard_state(state3, plan3)
    for b in plan3.buckets:
        assert b["param_bucket"] not in plain_state
        for p in b["params"]:
            assert list(plain_state[p["param"]].shape) == p["shape"]
    # hop 2: plain -> zero1 layout of a FRESH program build
    m1, s1, _ = _build()
    plan1 = shard_optimizer_states(m1, s1, dp_degree=WORLD, stage=1)
    z1_state = reshard_state(plain_state, plan1)
    for b in plan1.buckets:
        for name in b["slots"].values():
            assert name in z1_state
    # params in the zero1 layout stay replicated full-shape
    for b in plan3.buckets:
        for p in b["params"]:
            np.testing.assert_array_equal(z1_state[p["param"]],
                                          plain_state[p["param"]])
    # hop 3: zero1 -> plain -> back to zero3: the bucket payload
    # round-trips bitwise
    back3 = reshard_state(unshard_state(state3, plan3), plan3)
    for b in plan3.buckets:
        np.testing.assert_array_equal(back3[b["param_bucket"]],
                                      state3[b["param_bucket"]])
        for name in b["slots"].values():
            np.testing.assert_array_equal(
                np.asarray(back3[name]).reshape(-1)[:b["raw_len"]],
                np.asarray(state3[name]).reshape(-1)[:b["raw_len"]])


def test_reshard_state_refuses_missing_params():
    main, startup, loss = _build()
    plan3 = shard_optimizer_states(main, startup, dp_degree=WORLD, stage=3)
    with pytest.raises(KeyError):
        reshard_state({}, plan3)


def test_partition_rule_keeps_param_replicated_under_stage3():
    """The declarative layer in action: a user rule pinning one param
    to REPLICATED makes its bucket take the stage-1 chain (flatten /
    c_split / publish) while other buckets pack — no new pass code."""
    main, startup, loss = _build()
    first = main.all_parameters()[0].name
    import re
    plan = shard_optimizer_states(
        main, startup, dp_degree=WORLD, stage=3,
        rules=[(r"^param:" + re.escape(first) + r"$", (), False)])
    packed = [b for b in plan.buckets if b.get("param_bucket")]
    unpacked = [b for b in plan.buckets if not b.get("param_bucket")]
    assert packed and unpacked
    assert any(p["param"] == first for b in unpacked for p in b["params"])
    block = main.global_block()
    assert block.var(first).persistable  # stayed replicated state
    # and the mixed program still verifies clean
    rep = static.check_program(main, level="collective", startup=startup)
    assert rep.ok, rep.render()


def test_plan_and_state_conversion_roundtrip():
    main, startup, loss = _build()
    plan = shard_optimizer_states(main, startup, dp_degree=WORLD)
    assert main._zero_shard_plan is plan
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        for f in _feeds(3):
            exe.run(main, feed=f, fetch_list=[loss])
        from paddle_tpu.static.executor import _persistable_names
        state = {n: np.asarray(scope.get(n))
                 for n in _persistable_names(main)
                 if scope.get(n) is not None}
    # ZeRO-1 -> plain layout: bucket slots sliced to per-param names
    plain_state = unshard_state(state, plan)
    for b in plan.buckets:
        for name in b["slots"].values():
            assert name not in plain_state
        for p in b["params"]:
            m1 = plain_state[b["orig_slots"][p["param"]]["moment1"]]
            assert list(m1.shape) == p["shape"]
    # ... and back: bitwise round trip of the moment payload
    back = reshard_state(plain_state, plan.to_dict())
    for b in plan.buckets:
        for name in b["slots"].values():
            got = np.asarray(back[name]).reshape(-1)
            want = np.asarray(state[name]).reshape(-1)
            np.testing.assert_array_equal(got[:b["raw_len"]],
                                          want[:b["raw_len"]])


def test_dp_shard_attr_survives_serialization():
    main, startup, loss = _build()
    shard_optimizer_states(main, startup, dp_degree=WORLD)
    blob = main.serialize_to_string()
    back = static.Program.parse_from_string(blob)
    marked = [v for v in back.global_block().vars.values()
              if v.attrs.get("dp_shard")]
    assert marked and all(v.attrs["dp_shard"] == WORLD for v in marked)
    # programs sharded for different worlds must fingerprint apart
    # (checkpoint mismatch warnings key off this)
    main4, startup4, _ = _build()
    shard_optimizer_states(main4, startup4, dp_degree=4)
    assert main4.fingerprint() != main.fingerprint()


def test_shard_optimizer_states_idempotent():
    """Double application (fleet strategy.sharding + a script calling the
    pass directly) must be a no-op the second time — re-sharding the
    bucket op would reduce-scatter the already-scattered shard across
    ranks and 1/N-scale twice, invisibly on one device."""
    main, startup, loss = _build()
    plan1 = shard_optimizer_states(main, startup, dp_degree=WORLD)
    ops_before = len(main.global_block().ops)
    plan2 = shard_optimizer_states(main, startup, dp_degree=WORLD)
    assert plan2.buckets == []
    assert len(main.global_block().ops) == ops_before
    # the original plan (checkpoint-conversion layout) survives
    assert main._zero_shard_plan is plan1
    types = [op.type for op in main.global_block().ops]
    assert types.count("c_reducescatter") == plan1.n_buckets
    # sgd buckets carry no slot vars — the op-level marker must guard too
    main2, startup2 = _build(lambda: static.SGD(learning_rate=1e-2))[:2]
    p1 = shard_optimizer_states(main2, startup2, dp_degree=WORLD)
    assert p1.buckets
    p2 = shard_optimizer_states(main2, startup2, dp_degree=WORLD)
    assert p2.buckets == []


def test_fp16_allreduce_wraps_bucket_reduce_scatter():
    """strategy.fp16_allreduce keeps its meaning under sharding: the
    bucket reduce-scatter's wire leg is bf16 (half the ICI bytes) and
    the accounting sees it."""
    main, startup, loss = _build()
    full = static.collective_wire_bytes(insert_grad_allreduce(main), WORLD)
    main._fp16_allreduce = True
    shard_optimizer_states(main, startup, dp_degree=WORLD)
    block = main.global_block()
    rs = next(op for op in block.ops if op.type == "c_reducescatter")
    assert block.var(rs.inputs["X"][0]).dtype == "bfloat16"
    # wire accounting: bf16 reduce-scatter + fp32 allgather < fp32 both
    zero = static.collective_wire_bytes(main, WORLD)
    assert zero < full


def test_world1_is_noop():
    main, startup, loss = _build()
    n_ops = len(main.global_block().ops)
    plan = shard_optimizer_states(main, startup, dp_degree=1)
    assert plan.buckets == [] and len(main.global_block().ops) == n_ops


def test_bucket_bytes_splits_groups():
    main, startup, loss = _build()
    # tiny bucket budget: every param lands in its own bucket
    plan = shard_optimizer_states(main, startup, dp_degree=WORLD,
                                  bucket_bytes=8)
    assert plan.n_buckets == len(main.all_parameters())


# ---------------------------------------------------------------------------
# fleet meta-optimizer wiring
# ---------------------------------------------------------------------------
def test_fleet_sharding_meta_optimizer_applies_and_trains():
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.fleet.base.fleet_base import Fleet
    f = Fleet()
    f.init(is_collective=True)
    main, startup, loss = _build(lambda: static.Adam(learning_rate=5e-2))
    # _build already minimized; fleet needs to drive minimize itself
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        strategy = dist.fleet.DistributedStrategy()
        strategy.sharding = True
        strategy.sharding_configs = {"dp_degree": WORLD, "bucket_mb": 32}
        f.distributed_optimizer(static.Adam(learning_rate=5e-2), strategy)
        f.minimize(loss)
    assert "ShardingOptimizer" in f.applied_meta_list()
    types = [op.type for op in main.global_block().ops]
    assert "c_reducescatter" in types and "c_allgather" in types
    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(0)
    w = rng.rand(8, 1).astype(np.float32)
    with static.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(15):
            xb = rng.rand(16, 8).astype(np.float32)
            (lv,) = exe.run(f.main_program, feed={"x": xb, "y": xb @ w},
                            fetch_list=[loss])
            losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.5, losses

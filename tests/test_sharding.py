"""ZeRO-1 sharded data parallelism (distributed/sharding.py).

The contracts this tier rests on, all on the virtual 8-device CPU mesh
(conftest.py):
  * numerical equivalence — plain-DP and ZeRO-1 training produce the
    same loss trajectory and parameters (allclose atol=1e-6 fp32) for
    Adam/AdamW with and without AMP and gradient_merge;
  * the bucketed c_reducescatter / c_allgather round-trip with pow2
    padding un-pads correctly at the kernel level;
  * optimizer slots are genuinely sharded: per-chip slot bytes ≈ 1/8 of
    the replicated footprint (memory_analysis world-size accounting);
  * insert_grad_allreduce is idempotent and ZeRO-aware (no double
    reduction, regression for the fleet double-apply bug);
  * the degenerate single-chip path (collectives → identity) matches
    plain training bit-for-bit, including run_steps donated-state
    threading.

Tier-1 keeps the acceptance bar (Adam 20 steps) and the fullest
composition (AdamW+AMP+gradient_merge); the rest of the equivalence
matrix (Adam±AMP±merge, AdamW plain, Momentum/SGD, LAMB, recompute) is
marked `slow` — each is two more whole-mesh compiles and the tier-1
suite runs against a hard 870 s timeout (ROADMAP).  Perf rounds run the
full matrix.
"""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers
from paddle_tpu import amp
from paddle_tpu.core.program import _reset_unique_names
from paddle_tpu.distributed.compiled_program import (
    CompiledProgram, insert_grad_allreduce)
from paddle_tpu.distributed.sharding import (
    shard_optimizer_states, ShardingPlan, unshard_state, reshard_state,
    collective_bytes_per_step)

WORLD = 8


def _build(opt_fn=None, use_amp=False):
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        opt = (opt_fn or (lambda: static.Adam(learning_rate=1e-2)))()
        if use_amp:
            opt = amp.decorate(opt, init_loss_scaling=1.0,
                               use_dynamic_loss_scaling=False,
                               dest_dtype="bfloat16")
        opt.minimize(loss)
    return main, startup, loss


def _feeds(n, batch=16, seed=0):
    rng = np.random.RandomState(seed)
    return [{"x": rng.rand(batch, 8).astype(np.float32),
             "y": rng.rand(batch, 1).astype(np.float32)}
            for _ in range(n)]


def _train_mesh(main, startup, loss, steps):
    compiled = CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        losses = [float(exe.run(compiled, feed=f, fetch_list=[loss])[0])
                  for f in _feeds(steps)]
        params = {p.name: np.asarray(scope.get(p.name))
                  for p in main.all_parameters()}
    return losses, params, scope


def _assert_equiv(opt_fn=None, use_amp=False, gm=0, steps=8, atol=1e-6):
    runs = []
    for shard in (False, True):
        main, startup, loss = _build(opt_fn, use_amp)
        if shard:
            plan = shard_optimizer_states(main, startup, dp_degree=WORLD)
            assert plan.buckets
        if gm:
            static.gradient_merge(main, gm, startup)
        runs.append(_train_mesh(main, startup, loss, steps)[:2])
    (l0, p0), (l1, p1) = runs
    np.testing.assert_allclose(l0, l1, atol=atol, rtol=atol)
    for k in p0:
        np.testing.assert_allclose(p0[k], p1[k], atol=atol, rtol=atol,
                                   err_msg=k)


# ---------------------------------------------------------------------------
# numerical equivalence, 8-device mesh
# ---------------------------------------------------------------------------
def test_adam_equivalence_20_steps():
    # the acceptance bar: ≥20 steps, fp32, allclose atol=1e-6
    _assert_equiv(lambda: static.Adam(learning_rate=1e-2), steps=20)


@pytest.mark.slow
def test_adam_amp_equivalence():
    _assert_equiv(lambda: static.Adam(learning_rate=1e-2), use_amp=True)


@pytest.mark.slow
def test_adamw_equivalence():
    _assert_equiv(lambda: static.AdamW(learning_rate=1e-2,
                                       weight_decay=0.01))


def test_adamw_amp_gradient_merge_equivalence():
    _assert_equiv(lambda: static.AdamW(learning_rate=1e-2,
                                       weight_decay=0.01),
                  use_amp=True, gm=2)


@pytest.mark.slow
def test_adam_gradient_merge_equivalence():
    _assert_equiv(lambda: static.Adam(learning_rate=1e-2), gm=2)


@pytest.mark.slow
def test_momentum_and_sgd_equivalence():
    _assert_equiv(lambda: static.Momentum(learning_rate=1e-2,
                                          momentum=0.9), steps=6)
    _assert_equiv(lambda: static.SGD(learning_rate=1e-2), steps=6)


@pytest.mark.slow
def test_recompute_composes_with_sharding():
    """FLAGS_recompute-style activation checkpointing rewrites
    forward/backward; sharding rewrites the optimize tail — composed,
    training still matches plain DP."""
    def build_remat():
        _reset_unique_names()
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = layers.data("x", [-1, 8])
            y = layers.data("y", [-1, 1])
            h1 = layers.fc(x, 16, act="relu")
            h2 = layers.fc(h1, 16, act="relu")
            pred = layers.fc(h2, 1)
            loss = layers.mean(
                layers.square(layers.elementwise_sub(pred, y)))
            opt = static.RecomputeOptimizer(
                static.Adam(learning_rate=1e-2))
            opt._set_checkpoints([h1])
            opt.minimize(loss)
        return main, startup, loss

    runs = []
    for shard in (False, True):
        main, startup, loss = build_remat()
        # the rewrite replays the h1->h2 segment inside backward: the
        # relu forward runs once more than the plain program's two
        assert sum(1 for op in main.global_block().ops
                   if op.type == "relu") == 3
        if shard:
            shard_optimizer_states(main, startup, dp_degree=WORLD)
        runs.append(_train_mesh(main, startup, loss, 6)[:2])
    (l0, p0), (l1, p1) = runs
    np.testing.assert_allclose(l0, l1, atol=1e-6)
    for k in p0:
        np.testing.assert_allclose(p0[k], p1[k], atol=1e-6, err_msg=k)


@pytest.mark.slow
def test_lamb_equivalence_global_norms():
    # LAMB's trust ratio needs GLOBAL ‖p‖/‖r‖ — the sharded kernel psums
    # the squared norms, so per-param numbers match the unsharded update
    # (reduction-order wiggle only)
    _assert_equiv(lambda: static.Lamb(learning_rate=1e-2), steps=6,
                  atol=1e-5)


# ---------------------------------------------------------------------------
# degenerate single-chip + run_steps threading
# ---------------------------------------------------------------------------
def test_single_device_degenerate_matches_plain():
    runs = []
    for shard in (False, True):
        main, startup, loss = _build()
        if shard:
            shard_optimizer_states(main, startup, dp_degree=WORLD)
        exe = static.Executor()
        scope = static.Scope()
        with static.scope_guard(scope):
            exe.run(startup)
            losses = [float(exe.run(main, feed=f, fetch_list=[loss])[0])
                      for f in _feeds(6)]
            params = {p.name: np.asarray(scope.get(p.name))
                      for p in main.all_parameters()}
        runs.append((losses, params))
    (l0, p0), (l1, p1) = runs
    np.testing.assert_allclose(l0, l1, atol=1e-6)
    for k in p0:
        np.testing.assert_allclose(p0[k], p1[k], atol=1e-6, err_msg=k)


def test_run_steps_threads_sharded_slots():
    runs = []
    for shard in (False, True):
        main, startup, loss = _build()
        if shard:
            shard_optimizer_states(main, startup, dp_degree=WORLD)
        exe = static.Executor()
        scope = static.Scope()
        fs = _feeds(5)
        sfeed = {k: np.stack([f[k] for f in fs]) for k in fs[0]}
        with static.scope_guard(scope):
            exe.run(startup)
            out = exe.run_steps(main, feed=sfeed, fetch_list=[loss])
        runs.append(np.asarray(out[0]))
    np.testing.assert_allclose(runs[0], runs[1], atol=1e-6)


# ---------------------------------------------------------------------------
# kernel-level reduce-scatter / allgather round trip with pow2 padding
# ---------------------------------------------------------------------------
def test_reducescatter_allgather_roundtrip_pow2_pad():
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.utils.shard_map_compat import shard_map_unchecked
    from paddle_tpu.ops.registry import get_op_info, OpContext

    rs = get_op_info("c_reducescatter").kernel
    ag = get_op_info("c_allgather").kernel
    devs = np.array(jax.devices()[:WORLD])
    mesh = Mesh(devs, ("dp",))
    raw = np.arange(10, dtype=np.float32)  # 10 does not divide 8
    padded_len = -(-raw.size // WORLD) * WORLD  # 16 (pow2 world → pow2 pad)
    padded = np.pad(raw, (0, padded_len - raw.size))

    def step(x):
        ctx = OpContext(mesh_axes=("dp",), dist_info={0: "dp"})
        shard = rs({"X": x}, {"ring_id": 0}, ctx)["Out"]
        full = ag({"X": shard}, {"ring_id": 0}, ctx)["Out"]
        return shard, full

    fn = jax.jit(shard_map_unchecked(
        step, mesh, in_specs=(P(),), out_specs=(P("dp"), P())))
    shard, full = fn(padded)
    # reduce-scatter sums the replicated input over 8 ranks, each rank
    # keeping its slice; the gathered result reassembles rank-order
    assert shard.shape == (padded_len,)  # global view of [2]-per-rank
    np.testing.assert_allclose(np.asarray(full), padded * WORLD)
    # un-pad recovers the raw segment exactly
    np.testing.assert_allclose(np.asarray(full)[:raw.size], raw * WORLD)


# ---------------------------------------------------------------------------
# insert_grad_allreduce idempotency (regression: fleet double-apply)
# ---------------------------------------------------------------------------
def test_insert_grad_allreduce_idempotent():
    main, startup, loss = _build()
    once = insert_grad_allreduce(main)
    n1 = sum(1 for op in once.global_block().ops
             if op.type == "c_allreduce_sum")
    assert n1 == len(main.all_parameters())
    twice = insert_grad_allreduce(once)
    n2 = sum(1 for op in twice.global_block().ops
             if op.type == "c_allreduce_sum")
    assert n2 == n1, "double apply double-reduced"


def test_insert_grad_allreduce_skips_sharded_grads():
    main, startup, loss = _build()
    shard_optimizer_states(main, startup, dp_degree=WORLD)
    rewritten = insert_grad_allreduce(main)
    assert not any(op.type == "c_allreduce_sum"
                   for op in rewritten.global_block().ops)


# ---------------------------------------------------------------------------
# memory accounting + plan + wire-byte accounting
# ---------------------------------------------------------------------------
def test_sharded_slot_bytes_one_eighth():
    main, startup, loss = _build()
    plain = static.analyze_program(main, batch=16)
    predicted = static.analyze_program(main, batch=16, dp_shard=WORLD)
    shard_optimizer_states(main, startup, dp_degree=WORLD)
    sharded = static.analyze_program(main, batch=16)
    one_bucket = max(b.shape[0] for b in
                     main.global_block().vars.values()
                     if b.attrs.get("dp_shard")) * 4
    # acceptance: slot bytes ≤ plain/8 + one bucket (padding overhead)
    assert sharded["optimizer_slot_bytes"] <= \
        plain["optimizer_slot_bytes"] // WORLD + one_bucket
    assert predicted["optimizer_slot_bytes"] <= \
        plain["optimizer_slot_bytes"] // WORLD + one_bucket
    assert sharded["persistable_bytes"] < plain["persistable_bytes"]


def test_prediction_skips_unshardable_optimizer_slots():
    """analyze_program(dp_shard=N) must divide ONLY slots the rewrite
    would actually shard — an Adamax moment stays replicated, so the
    predicted verdict never claims memory the pass cannot deliver."""
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        pred = layers.fc(x, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adamax(learning_rate=1e-2).minimize(loss)
    plain = static.analyze_program(main, batch=16)
    predicted = static.analyze_program(main, batch=16, dp_shard=WORLD)
    assert predicted["optimizer_slot_bytes"] == \
        plain["optimizer_slot_bytes"]
    # and the pass itself refuses the op: no buckets
    assert shard_optimizer_states(main, startup,
                                  dp_degree=WORLD).buckets == []


def test_collective_bytes_zero1_matches_allreduce_volume():
    # ZeRO-1's whole point: SAME wire volume (rs + ag == allreduce),
    # 1/N the optimizer memory.  Priced by the verifier's ring-accounted
    # extractor (static.collective_wire_bytes — the planner's wire
    # substrate, which superseded sharding.collective_bytes_per_step).
    main, startup, loss = _build()
    plain = static.collective_wire_bytes(insert_grad_allreduce(main), WORLD)
    shard_optimizer_states(main, startup, dp_degree=WORLD)
    zero = static.collective_wire_bytes(insert_grad_allreduce(main), WORLD)
    assert plain > 0
    # padding can only add a sliver
    assert plain <= zero <= int(plain * 1.25)


def test_collective_bytes_per_step_shim_delegates_and_warns_once():
    """The superseded helper survives as a deprecation shim: one
    DeprecationWarning per process, then plain delegation to the
    ring-0 slice of static.collective_wire_bytes."""
    import warnings
    from paddle_tpu.distributed import sharding as sharding_mod
    main, startup, loss = _build()
    shard_optimizer_states(main, startup, dp_degree=WORLD)
    reduced = insert_grad_allreduce(main)
    sharding_mod._collective_bytes_deprecation_warned = False
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        got = collective_bytes_per_step(reduced, WORLD)
        again = collective_bytes_per_step(reduced, WORLD)
    assert got == again == static.collective_wire_bytes(reduced, WORLD,
                                                        ring_id=0)
    deps = [x for x in w if issubclass(x.category, DeprecationWarning)]
    assert len(deps) == 1  # warns ONCE


def test_plan_and_state_conversion_roundtrip():
    main, startup, loss = _build()
    plan = shard_optimizer_states(main, startup, dp_degree=WORLD)
    assert main._zero_shard_plan is plan
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        for f in _feeds(3):
            exe.run(main, feed=f, fetch_list=[loss])
        from paddle_tpu.static.executor import _persistable_names
        state = {n: np.asarray(scope.get(n))
                 for n in _persistable_names(main)
                 if scope.get(n) is not None}
    # ZeRO-1 -> plain layout: bucket slots sliced to per-param names
    plain_state = unshard_state(state, plan)
    for b in plan.buckets:
        for name in b["slots"].values():
            assert name not in plain_state
        for p in b["params"]:
            m1 = plain_state[b["orig_slots"][p["param"]]["moment1"]]
            assert list(m1.shape) == p["shape"]
    # ... and back: bitwise round trip of the moment payload
    back = reshard_state(plain_state, plan.to_dict())
    for b in plan.buckets:
        for name in b["slots"].values():
            got = np.asarray(back[name]).reshape(-1)
            want = np.asarray(state[name]).reshape(-1)
            np.testing.assert_array_equal(got[:b["raw_len"]],
                                          want[:b["raw_len"]])


def test_dp_shard_attr_survives_serialization():
    main, startup, loss = _build()
    shard_optimizer_states(main, startup, dp_degree=WORLD)
    blob = main.serialize_to_string()
    back = static.Program.parse_from_string(blob)
    marked = [v for v in back.global_block().vars.values()
              if v.attrs.get("dp_shard")]
    assert marked and all(v.attrs["dp_shard"] == WORLD for v in marked)
    # programs sharded for different worlds must fingerprint apart
    # (checkpoint mismatch warnings key off this)
    main4, startup4, _ = _build()
    shard_optimizer_states(main4, startup4, dp_degree=4)
    assert main4.fingerprint() != main.fingerprint()


def test_shard_optimizer_states_idempotent():
    """Double application (fleet strategy.sharding + a script calling the
    pass directly) must be a no-op the second time — re-sharding the
    bucket op would reduce-scatter the already-scattered shard across
    ranks and 1/N-scale twice, invisibly on one device."""
    main, startup, loss = _build()
    plan1 = shard_optimizer_states(main, startup, dp_degree=WORLD)
    ops_before = len(main.global_block().ops)
    plan2 = shard_optimizer_states(main, startup, dp_degree=WORLD)
    assert plan2.buckets == []
    assert len(main.global_block().ops) == ops_before
    # the original plan (checkpoint-conversion layout) survives
    assert main._zero_shard_plan is plan1
    types = [op.type for op in main.global_block().ops]
    assert types.count("c_reducescatter") == plan1.n_buckets
    # sgd buckets carry no slot vars — the op-level marker must guard too
    main2, startup2 = _build(lambda: static.SGD(learning_rate=1e-2))[:2]
    p1 = shard_optimizer_states(main2, startup2, dp_degree=WORLD)
    assert p1.buckets
    p2 = shard_optimizer_states(main2, startup2, dp_degree=WORLD)
    assert p2.buckets == []


def test_fp16_allreduce_wraps_bucket_reduce_scatter():
    """strategy.fp16_allreduce keeps its meaning under sharding: the
    bucket reduce-scatter's wire leg is bf16 (half the ICI bytes) and
    the accounting sees it."""
    main, startup, loss = _build()
    full = static.collective_wire_bytes(insert_grad_allreduce(main), WORLD)
    main._fp16_allreduce = True
    shard_optimizer_states(main, startup, dp_degree=WORLD)
    block = main.global_block()
    rs = next(op for op in block.ops if op.type == "c_reducescatter")
    assert block.var(rs.inputs["X"][0]).dtype == "bfloat16"
    # wire accounting: bf16 reduce-scatter + fp32 allgather < fp32 both
    zero = static.collective_wire_bytes(main, WORLD)
    assert zero < full


def test_world1_is_noop():
    main, startup, loss = _build()
    n_ops = len(main.global_block().ops)
    plan = shard_optimizer_states(main, startup, dp_degree=1)
    assert plan.buckets == [] and len(main.global_block().ops) == n_ops


def test_bucket_bytes_splits_groups():
    main, startup, loss = _build()
    # tiny bucket budget: every param lands in its own bucket
    plan = shard_optimizer_states(main, startup, dp_degree=WORLD,
                                  bucket_bytes=8)
    assert plan.n_buckets == len(main.all_parameters())


# ---------------------------------------------------------------------------
# fleet meta-optimizer wiring
# ---------------------------------------------------------------------------
def test_fleet_sharding_meta_optimizer_applies_and_trains():
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed.fleet.base.fleet_base import Fleet
    f = Fleet()
    f.init(is_collective=True)
    main, startup, loss = _build(lambda: static.Adam(learning_rate=5e-2))
    # _build already minimized; fleet needs to drive minimize itself
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        strategy = dist.fleet.DistributedStrategy()
        strategy.sharding = True
        strategy.sharding_configs = {"dp_degree": WORLD, "bucket_mb": 32}
        f.distributed_optimizer(static.Adam(learning_rate=5e-2), strategy)
        f.minimize(loss)
    assert "ShardingOptimizer" in f.applied_meta_list()
    types = [op.type for op in main.global_block().ops]
    assert "c_reducescatter" in types and "c_allgather" in types
    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(0)
    w = rng.rand(8, 1).astype(np.float32)
    with static.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(15):
            xb = rng.rand(16, 8).astype(np.float32)
            (lv,) = exe.run(f.main_program, feed={"x": xb, "y": xb @ w},
                            fetch_list=[loss])
            losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.5, losses

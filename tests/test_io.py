"""paddle.io tests — datasets, samplers, DataLoader, save/load.

Modeled on the reference's dataloader unittests
(/root/reference/python/paddle/fluid/tests/unittests/test_batch_sampler.py,
 test_dataset*.py, test_static_save_load.py) translated to the TPU build.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import io as pio


class RangeDataset(pio.Dataset):
    def __init__(self, n):
        self.n = n

    def __getitem__(self, i):
        return np.asarray([i, i * 2], dtype=np.float32), np.asarray(
            i % 3, dtype=np.int64)

    def __len__(self):
        return self.n


class StreamDataset(pio.IterableDataset):
    def __init__(self, n):
        self.n = n

    def __iter__(self):
        for i in range(self.n):
            yield np.float32(i)


def test_tensor_dataset_and_subset():
    xs = np.arange(20).reshape(10, 2).astype(np.float32)
    ys = np.arange(10).astype(np.int64)
    ds = pio.TensorDataset([xs, ys])
    assert len(ds) == 10
    x, y = ds[3]
    assert (x == xs[3]).all() and y == 3
    sub = pio.Subset(ds, [1, 4])
    assert len(sub) == 2 and sub[1][1] == 4
    a, b = pio.random_split(ds, [7, 3], generator=0)
    assert len(a) == 7 and len(b) == 3
    seen = sorted(a.indices + b.indices)
    assert seen == list(range(10))


def test_compose_chain_concat():
    d1, d2 = RangeDataset(5), RangeDataset(5)
    comp = pio.ComposeDataset([d1, d2])
    s = comp[2]
    assert len(s) == 4
    cat = pio.ConcatDataset([d1, d2])
    assert len(cat) == 10
    assert (cat[7][0] == d2[2][0]).all()
    chain = pio.ChainDataset([StreamDataset(3), StreamDataset(2)])
    assert [float(x) for x in chain] == [0, 1, 2, 0, 1]


def test_samplers():
    ds = RangeDataset(10)
    assert list(pio.SequenceSampler(ds)) == list(range(10))
    r = list(pio.RandomSampler(ds))
    assert sorted(r) == list(range(10))
    w = list(pio.WeightedRandomSampler([0.0, 1.0, 0.0], 5))
    assert w == [1] * 5
    bs = pio.BatchSampler(ds, batch_size=3, drop_last=False)
    batches = list(bs)
    assert len(bs) == 4 and len(batches) == 4
    assert [len(b) for b in batches] == [3, 3, 3, 1]
    bs2 = pio.BatchSampler(ds, batch_size=3, drop_last=True)
    assert len(list(bs2)) == 3 == len(bs2)


def test_distributed_batch_sampler():
    ds = RangeDataset(10)
    all_idx = []
    for rank in range(4):
        s = pio.DistributedBatchSampler(ds, batch_size=2, num_replicas=4,
                                        rank=rank)
        batches = list(s)
        assert len(batches) == len(s)
        all_idx.extend(i for b in batches for i in b)
    # every sample covered; padded to equal share per rank
    assert set(all_idx) == set(range(10)) and len(all_idx) == 12
    # shuffle must be identical across ranks per epoch
    s0 = pio.DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=0,
                                     shuffle=True)
    s1 = pio.DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=1,
                                     shuffle=True)
    s0.set_epoch(5), s1.set_epoch(5)
    i0 = {i for b in s0 for i in b}
    i1 = {i for b in s1 for i in b}
    assert i0 | i1 == set(range(10)) and not (i0 & i1 - set(range(10)))


def test_dataloader_map_style():
    ds = RangeDataset(10)
    dl = pio.DataLoader(ds, batch_size=4, drop_last=False)
    batches = list(dl)
    assert len(batches) == 3
    x, y = batches[0]
    assert np.asarray(x).shape == (4, 2) and np.asarray(y).shape == (4,)
    x_last = np.asarray(batches[-1][0])
    assert x_last.shape == (2, 2)


def test_dataloader_shuffle_covers_all():
    ds = RangeDataset(12)
    dl = pio.DataLoader(ds, batch_size=3, shuffle=True)
    ys = np.concatenate([np.asarray(y) for _, y in dl])
    assert ys.shape == (12,)


def test_dataloader_workers():
    ds = RangeDataset(9)
    dl = pio.DataLoader(ds, batch_size=2, num_workers=2)
    batches = list(dl)
    first = np.concatenate([np.asarray(x)[:, 0] for x, _ in batches])
    assert sorted(first.tolist()) == list(range(9))


def test_dataloader_iterable_dataset():
    dl = pio.DataLoader(StreamDataset(7), batch_size=3, drop_last=False)
    sizes = [np.asarray(b).shape[0] for b in dl]
    assert sizes == [3, 3, 1]


def test_generator_loader():
    gl = pio.GeneratorLoader(feed_list=["x", "y"], iterable=True)

    def sample_gen():
        for i in range(6):
            yield (np.full((2,), i, np.float32), np.int64(i))

    gl.set_sample_generator(sample_gen, batch_size=2)
    feeds = list(gl)
    assert len(feeds) == 3
    assert set(feeds[0]) == {"x", "y"}
    assert feeds[0]["x"].shape == (2, 2)

    gl2 = pio.GeneratorLoader(feed_list=["x"], iterable=True)
    gl2.set_batch_generator(lambda: iter([[np.zeros((4, 2), np.float32)]]))
    (f,) = list(gl2)
    assert f["x"].shape == (4, 2)


def test_save_load_object(tmp_path):
    obj = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
           "meta": {"step": 7}}
    p = str(tmp_path / "ckpt" / "obj.pdparams")
    pio.save(obj, p)
    back = pio.load(p)
    assert (back["w"] == obj["w"]).all() and back["meta"]["step"] == 7


def _build_linear_prog():
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4])
        y = layers.fc(x, 3)
        loss = layers.mean(y)
    return main, startup, loss


def test_static_save_load_params(tmp_path):
    import paddle_tpu.static as static
    main, startup, loss = _build_linear_prog()
    exe = static.Executor()
    exe.run(startup)
    from paddle_tpu.static.executor import global_scope
    w_name = main.all_parameters()[0].name
    orig = np.asarray(global_scope().get(w_name))

    d = str(tmp_path / "params")
    pio.save_params(exe, d, main)
    global_scope().set(w_name, np.zeros_like(orig))
    pio.load_params(exe, d, main)
    assert np.allclose(np.asarray(global_scope().get(w_name)), orig)

    # combined-file format
    pio.save_persistables(exe, d, main, filename="all.npz")
    global_scope().set(w_name, np.zeros_like(orig))
    pio.load_persistables(exe, d, main, filename="all.npz")
    assert np.allclose(np.asarray(global_scope().get(w_name)), orig)


def test_static_save_load_vars_bf16(tmp_path):
    """save_vars/load_vars round-trip a bf16 var bit-exactly in BOTH
    formats — np.save silently degrades bf16 to a void descr ('|V2'), so
    the per-var path writes a .npt tensor record and the combined npz
    tags the uint16 view in a __tensor_dtypes__ sidecar entry."""
    import ml_dtypes
    import paddle_tpu.static as static
    main, startup, loss = _build_linear_prog()
    exe = static.Executor()
    exe.run(startup)
    from paddle_tpu.static.executor import global_scope
    w_name = main.all_parameters()[0].name
    orig32 = np.asarray(global_scope().get(w_name))
    bf = orig32.astype(ml_dtypes.bfloat16)
    global_scope().set(w_name, bf)

    d = str(tmp_path / "vars_bf16")
    pio.save_params(exe, d, main)
    assert os.path.exists(os.path.join(d, w_name + ".npt"))
    global_scope().set(w_name, np.zeros_like(orig32))
    pio.load_params(exe, d, main)
    got = np.asarray(global_scope().get(w_name))
    assert got.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(got.view(np.uint16), bf.view(np.uint16))

    global_scope().set(w_name, bf)
    pio.save_persistables(exe, d, main, filename="all.npz")
    global_scope().set(w_name, np.zeros_like(orig32))
    pio.load_persistables(exe, d, main, filename="all.npz")
    got = np.asarray(global_scope().get(w_name))
    assert got.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(got.view(np.uint16), bf.view(np.uint16))

    # a re-save that switches the var's dtype class must remove the
    # opposite-extension file: load prefers .npy, so a stale one from a
    # bf16→fp32→bf16 cycle would silently restore old values
    global_scope().set(w_name, orig32)
    pio.save_params(exe, d, main)
    assert os.path.exists(os.path.join(d, w_name + ".npy"))
    assert not os.path.exists(os.path.join(d, w_name + ".npt"))
    pio.load_params(exe, d, main)
    assert np.asarray(global_scope().get(w_name)).dtype == np.float32


def test_static_save_load_prefix(tmp_path):
    import paddle_tpu.static as static
    main, startup, loss = _build_linear_prog()
    exe = static.Executor()
    exe.run(startup)
    from paddle_tpu.static.executor import global_scope
    w_name = main.all_parameters()[0].name
    orig = np.asarray(global_scope().get(w_name))
    prefix = str(tmp_path / "model" / "final")
    pio.static_save(main, prefix)
    assert os.path.exists(prefix + ".pdmodel")
    global_scope().set(w_name, np.zeros_like(orig))
    pio.static_load(main, prefix)
    assert np.allclose(np.asarray(global_scope().get(w_name)), orig)


def test_save_load_inference_model(tmp_path):
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4])
        y = layers.fc(x, 3, act="relu")
        loss = layers.mean(y)
    exe = static.Executor()
    exe.run(startup)
    xv = np.random.RandomState(0).randn(2, 4).astype(np.float32)
    (ref,) = exe.run(main, feed={"x": xv}, fetch_list=[y])

    d = str(tmp_path / "infer")
    pio.save_inference_model(d, ["x"], [y], exe, main)

    prog, feed_names, fetch_targets = pio.load_inference_model(d, exe)
    assert feed_names == ["x"]
    (out,) = exe.run(prog, feed={"x": xv}, fetch_list=fetch_targets)
    assert np.allclose(out, ref, atol=1e-5)


def test_dygraph_save_load(tmp_path):
    was_dynamic = paddle.in_dynamic_mode()
    paddle.disable_static()
    try:
        import paddle_tpu.nn as nn
        lin = nn.Linear(4, 3)
        sd = lin.state_dict()
        p = str(tmp_path / "dy")
        pio.save_dygraph(sd, p)
        params, opt = pio.load_dygraph(p)
        assert opt is None
        lin2 = nn.Linear(4, 3)
        lin2.set_state_dict(params)
        for k in sd:
            assert np.allclose(np.asarray(sd[k].numpy()),
                               np.asarray(lin2.state_dict()[k].numpy()))
    finally:
        if not was_dynamic:
            paddle.enable_static()


def test_distributed_sampler_heavy_padding():
    # padding larger than dataset: every rank must still get equal batches
    ds = RangeDataset(2)
    lens = []
    for rank in range(8):
        s = pio.DistributedBatchSampler(ds, batch_size=1, num_replicas=8,
                                        rank=rank)
        batches = list(s)
        assert len(batches) == len(s)
        lens.append(len(batches))
    assert len(set(lens)) == 1


def test_combined_file_roundtrip_any_name(tmp_path):
    import paddle_tpu.static as static
    main, startup, loss = _build_linear_prog()
    exe = static.Executor()
    exe.run(startup)
    from paddle_tpu.static.executor import global_scope
    w_name = main.all_parameters()[0].name
    orig = np.asarray(global_scope().get(w_name))
    d = str(tmp_path / "c")
    pio.save_persistables(exe, d, main, filename="__params__")
    assert os.path.exists(os.path.join(d, "__params__"))
    global_scope().set(w_name, np.zeros_like(orig))
    pio.load_persistables(exe, d, main, filename="__params__")
    assert np.allclose(np.asarray(global_scope().get(w_name)), orig)


class _UnserialisableRange(pio.Dataset):
    """Carries a lock so even cloudpickle refuses — forces the
    thread-pool fallback path (lambdas alone now go through the
    cloudpickle envelope and get real processes)."""

    def __init__(self, n):
        import threading
        self.n = n
        self.lock = threading.Lock()

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.asarray(i, np.int64)


def test_dataloader_early_break_no_thread_leak():
    import threading
    import warnings as _w
    ds = _UnserialisableRange(64)
    before = threading.active_count()
    with _w.catch_warnings():
        _w.simplefilter("ignore")
        for _ in range(5):
            # unserialisable dataset forces the thread-pool path — this
            # test covers thread cleanup; process cleanup is covered below
            for i, batch in enumerate(pio.DataLoader(
                    ds, batch_size=2, num_workers=2,
                    collate_fn=lambda b: pio.default_collate_fn(b))):
                if i == 1:
                    break
    import gc, time
    gc.collect()
    time.sleep(0.3)
    assert threading.active_count() <= before + 2


def test_dataloader_early_break_terminates_worker_processes():
    import multiprocessing as mp
    import gc
    import time
    for i, batch in enumerate(pio.DataLoader(PidDataset(64), batch_size=2,
                                             num_workers=2)):
        if i == 1:
            break
    gc.collect()
    deadline = time.time() + 10
    while mp.active_children() and time.time() < deadline:
        time.sleep(0.2)
    assert not mp.active_children()


def test_random_sampler_short_generator():
    ds = RangeDataset(10)
    s = pio.RandomSampler(ds, generator=iter(range(3)))
    assert list(s) == [0, 1, 2]


def test_batch_sampler_validation():
    ds = RangeDataset(4)
    with pytest.raises(ValueError):
        pio.BatchSampler(ds, batch_size=0)
    with pytest.raises(ValueError):
        pio.DistributedBatchSampler(ds, batch_size=0, num_replicas=2, rank=0)


# ---------------------------------------------------------------------------
# multiprocess DataLoader workers
# (reference dataloader_iter.py:436 _DataLoaderIterMultiProcess)
# ---------------------------------------------------------------------------
class PidDataset(pio.Dataset):
    """Samples carry the producing pid so tests can prove process
    isolation (module-level: spawn workers unpickle it by import)."""

    def __init__(self, n):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return (np.asarray(i, np.int64), np.asarray(os.getpid(), np.int64))


class FailingDataset(pio.Dataset):
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 5:
            raise ValueError("boom at 5")
        return np.asarray(i, np.int64)


def _worker_seed_init(worker_id):
    # runs inside the worker process
    os.environ["PTPU_TEST_WORKER_ID"] = str(worker_id)


def test_dataloader_workers_are_processes_and_ordered():
    dl = pio.DataLoader(PidDataset(24), batch_size=4, num_workers=2,
                        shuffle=False)
    order, pids = [], set()
    for batch in dl:
        order.extend(np.asarray(batch[0]).tolist())
        pids.update(np.asarray(batch[1]).tolist())
    assert order == list(range(24))          # order restored across workers
    assert os.getpid() not in pids           # NOT the parent process
    assert len(pids) == 2                    # one pid per worker


def test_dataloader_worker_exception_propagates():
    dl = pio.DataLoader(FailingDataset(), batch_size=2, num_workers=2)
    with pytest.raises(RuntimeError, match="boom at 5"):
        list(dl)


def test_dataloader_lambda_still_gets_worker_processes():
    """VERDICT r3 weak #7: a lambda collate_fn (plain-pickle-hostile but
    cloudpickle-able) must still get REAL worker processes via the
    cloudpickle envelope — no thread degradation, no warning."""
    import warnings as _w
    dl = pio.DataLoader(PidDataset(8), batch_size=2, num_workers=2,
                        shuffle=False,
                        collate_fn=lambda b: (np.stack([s[0] for s in b]),
                                              np.stack([s[1] for s in b])))
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        pids = set()
        n = 0
        for batch in dl:
            pids.update(np.asarray(batch[1]).tolist())
            n += 1
    assert n == 4
    assert os.getpid() not in pids           # real worker processes
    assert not any("thread pool" in str(r.message) for r in rec)


def test_dataloader_truly_unserialisable_falls_back_to_threads():
    import warnings as _w
    import threading as _t
    ds = RangeDataset(8)

    class LockySet(pio.Dataset):
        """A lock is unserialisable even for cloudpickle."""

        def __init__(self):
            self.lock = _t.Lock()

        def __len__(self):
            return 8

        def __getitem__(self, i):
            return np.asarray(i, np.int64)

    dl = pio.DataLoader(LockySet(), batch_size=2, num_workers=2)
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        batches = list(dl)
    assert len(batches) == 4
    assert any("thread pool" in str(r.message) for r in rec)


# ---------------------------------------------------------------------------
# model crypto (C23 tail — reference framework/io/crypto/)
# ---------------------------------------------------------------------------

def test_cipher_roundtrip_and_tamper_detection():
    from paddle_tpu.io.crypto import Cipher, CipherUtils
    key = CipherUtils.gen_key(256)
    c = Cipher()
    blob = b"model bytes \x00\x01" * 100
    enc = c.encrypt(blob, key)
    assert enc != blob and enc.startswith(b"PTPUENC1")
    assert c.decrypt(enc, key) == blob
    # authenticated: bit-flips must be rejected, not silently decrypted
    bad = bytearray(enc)
    bad[-1] ^= 0xFF
    with pytest.raises(Exception):
        c.decrypt(bytes(bad), key)


def test_encrypted_inference_model_roundtrip(tmp_path):
    from paddle_tpu.io.crypto import (CipherUtils, encrypt_inference_model,
                                      decrypt_inference_model,
                                      is_encrypted)
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    from paddle_tpu.io.framework_io import (save_inference_model,
                                            load_inference_model)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4])
        y = layers.fc(x, 2)
    exe = static.Executor()
    scope = static.Scope()
    plain = tmp_path / "model"
    enc = tmp_path / "enc"
    dec = tmp_path / "dec"
    rng = np.random.RandomState(0)
    with static.scope_guard(scope):
        exe.run(startup)
        save_inference_model(str(plain), ["x"], [y], exe,
                             main_program=main)
        key = CipherUtils.gen_key_to_file(256, str(tmp_path / "k"))
        encrypt_inference_model(str(plain), key, str(enc))
        assert all(is_encrypted(str(enc / n)) for n in os.listdir(enc))
        decrypt_inference_model(str(enc), key, str(dec))
        prog, feeds, fetches = load_inference_model(str(dec), exe)
        xb = rng.randn(3, 4).astype(np.float32)
        (out,) = exe.run(prog, feed={feeds[0]: xb}, fetch_list=fetches)
    assert np.asarray(out).shape == (3, 2)

"""ModelAverage, LookaheadOptimizer, namespace aliases, mean_iou/Print
layers (reference fluid/optimizer.py ModelAverage/LookaheadOptimizer,
layers/nn.py mean_iou, layers/control_flow.py Print)."""
import numpy as np

import paddle_tpu.static as static
from paddle_tpu.static import layers
from paddle_tpu.static.optimizer import ModelAverage, LookaheadOptimizer

rng = np.random.RandomState(0)
XB = rng.rand(8, 4).astype(np.float32)
YB = (XB @ rng.rand(4, 1)).astype(np.float32)


def _linreg():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4])
        y = layers.data("y", [-1, 1])
        pred = layers.fc(x, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
    return main, startup, loss


def test_lookahead_trains_and_syncs():
    main, startup, loss = _linreg()
    with static.program_guard(main, startup):
        LookaheadOptimizer(static.SGD(learning_rate=0.1), alpha=0.5,
                           k=4).minimize(loss)
    exe, sc = static.Executor(), static.Scope()
    with static.scope_guard(sc):
        exe.run(startup)
        losses = [float(exe.run(main, feed={"x": XB, "y": YB},
                                fetch_list=[loss])[0])
                  for _ in range(40)]
        # slow copies exist and track the fast weights after sync steps
        slows = [n for n in sc.keys() if "_slow" in n]
        assert slows
    assert losses[-1] < losses[0] * 0.2


def test_model_average_apply_restore():
    main, startup, loss = _linreg()
    with static.program_guard(main, startup):
        static.SGD(learning_rate=0.1).minimize(loss)
        ma = ModelAverage(0.15, min_average_window=2,
                          max_average_window=10)
    exe, sc = static.Executor(), static.Scope()
    with static.scope_guard(sc):
        exe.run(startup)
        for _ in range(12):
            exe.run(main, feed={"x": XB, "y": YB}, fetch_list=[loss])
        pname = main.all_parameters()[0].name
        final = np.asarray(sc.get(pname)).copy()
        ma.apply(exe)
        averaged = np.asarray(sc.get(pname)).copy()
        # averaged weights differ from the final step's weights...
        assert not np.allclose(final, averaged)
        ma.restore(exe)
        # ...and restore brings the exact final weights back
        np.testing.assert_array_equal(
            np.asarray(sc.get(pname)), final)


def test_model_average_constant_params_multi_window():
    """lr=0 keeps params constant, so after ANY number of completed
    averaging windows the average must equal the param exactly (guards
    the window-rollover semantics of average_accumulates: s3 is
    replaced by s1+s2, not accumulated into)."""
    main, startup, loss = _linreg()
    with static.program_guard(main, startup):
        static.SGD(learning_rate=0.0).minimize(loss)
        ma = ModelAverage(0.15, min_average_window=2,
                          max_average_window=2)
    exe, sc = static.Executor(), static.Scope()
    with static.scope_guard(sc):
        exe.run(startup)
        for _ in range(8):  # 4 completed windows
            exe.run(main, feed={"x": XB, "y": YB}, fetch_list=[loss])
        pname = main.all_parameters()[0].name
        const = np.asarray(sc.get(pname)).copy()
        ma.apply(exe)
        averaged = np.asarray(sc.get(pname)).copy()
        ma.restore(exe)
    np.testing.assert_allclose(averaged, const, rtol=1e-6)


def test_mean_iou_and_print_layers():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        p = layers.data("p", [-1, 4], dtype="int64")
        l = layers.data("l", [-1, 4], dtype="int64")
        miou, wrong, correct = layers.mean_iou(p, l, num_classes=3)
        printed = layers.Print(layers.cast(p, "float32"),
                               message="dbg")
        s = layers.mean(printed)
    exe, sc = static.Executor(), static.Scope()
    pred = np.array([[0, 1, 2, 2]], np.int64)
    lab = np.array([[0, 1, 1, 2]], np.int64)
    with static.scope_guard(sc):
        exe.run(startup)
        out = exe.run(main, feed={"p": pred, "l": lab},
                      fetch_list=[miou, s])
    # classes: 0 -> iou 1, 1 -> 1/2, 2 -> 1/2  => mean 2/3
    np.testing.assert_allclose(float(out[0]), 2.0 / 3.0, rtol=1e-5)


def test_namespace_aliases():
    import paddle_tpu.optimizer as opt
    assert opt.ExponentialLR is opt.lr_scheduler.ExponentialDecay
    assert opt.ReduceLROnPlateau is opt.lr_scheduler.ReduceOnPlateau
    assert opt.SGDOptimizer is static.SGDOptimizer
    import paddle_tpu.metric as metric
    assert callable(metric.auc) and callable(metric.chunk_eval)
    assert static.ParallelExecutor is not None
    assert static.InputSpec is not None
    from paddle_tpu.io.framework_io import load_program_state
    assert static.load_program_state is load_program_state

def test_gradient_merge_standalone_api():
    """paddle_tpu.static.gradient_merge: k-step accumulation without the
    fleet-strategy detour — k=2 over identical batches equals half the
    plain steps, the accumulators/counter are persistable (survive
    checkpoint snapshots and run_steps state threading), and k<=1 is a
    no-op."""
    def build():
        main, startup, loss = _linreg()
        with static.program_guard(main, startup):
            static.SGD(learning_rate=0.1).minimize(loss)
        return main, startup, loss

    main, startup, loss = build()
    with static.program_guard(main, startup):
        static.gradient_merge(main, 2)
    exe, sc = static.Executor(), static.Scope()
    with static.scope_guard(sc):
        exe.run(startup)
        feed = {"x": np.stack([XB] * 4), "y": np.stack([YB] * 4)}
        exe.run_steps(main, feed=feed, fetch_list=[loss])
        w_merge = [np.asarray(sc.get(p.name))
                   for p in main.all_parameters()]
        _, state, _ = exe.checkpoint_snapshot(main, sc)
        assert any("@GradientMerge" in n for n in state), sorted(state)
        assert any("@gm_step" in n for n in state), sorted(state)

    main2, startup2, loss2 = build()
    exe2, sc2 = static.Executor(), static.Scope()
    with static.scope_guard(sc2):
        exe2.run(startup2)
        for _ in range(2):
            exe2.run(main2, feed={"x": XB, "y": YB}, fetch_list=[loss2])
        w_plain = [np.asarray(sc2.get(p.name))
                   for p in main2.all_parameters()]
    for a, b in zip(w_merge, w_plain):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    # no params_grads recorded -> loud error, not a silent no-op
    main3, _, _ = _linreg()
    try:
        static.gradient_merge(main3, 2)
    except ValueError as e:
        assert "minimize" in str(e)
    else:
        raise AssertionError("expected ValueError without minimize()")
    # k=1 is a no-op
    main4, startup4, loss4 = build()
    n_ops = len(main4.global_block().ops)
    static.gradient_merge(main4, 1)
    assert len(main4.global_block().ops) == n_ops

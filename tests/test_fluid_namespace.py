"""`import paddle.fluid as fluid` compatibility surface: classic
fluid-era book code must run unchanged against this namespace
(reference python/paddle/fluid/__init__.py)."""
import numpy as np

import paddle_tpu.fluid as fluid


def test_fluid_book_style_training():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="x", shape=[13], dtype="float32")
        y = fluid.layers.data(name="y", shape=[1], dtype="float32")
        hidden = fluid.layers.fc(input=x, size=32, act="relu",
                                 param_attr=fluid.ParamAttr(
                                     regularizer=fluid.regularizer.L2Decay(
                                         1e-4)))
        pred = fluid.layers.fc(input=hidden, size=1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(input=pred, label=y))
        opt = fluid.optimizer.SGDOptimizer(learning_rate=0.05)
        opt.minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    xb = rng.rand(16, 13).astype(np.float32)
    yb = xb.sum(1, keepdims=True).astype(np.float32) / 13.0
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        losses = [float(exe.run(main, feed={"x": xb, "y": yb},
                                fetch_list=[loss])[0])
                  for _ in range(60)]
    assert losses[-1] < losses[0] * 0.2


def test_fluid_surface():
    assert fluid.default_main_program() is not None
    assert fluid.layers.While is not None          # control flow merged
    assert fluid.layers.fill_constant is not None
    assert fluid.io.load_inference_model is not None
    assert fluid.io.PyReader is not None
    assert fluid.clip.GradientClipByGlobalNorm is not None
    assert fluid.metrics is not None
    assert fluid.ParallelExecutor is not None
    assert fluid.Variable is not None
    assert callable(fluid.in_dygraph_mode)
    import paddle_tpu
    assert paddle_tpu.fluid is fluid               # auto-loaded subpackage


def test_fluid_parallel_executor():
    """The fluid ParallelExecutor constructor idiom runs a DP step."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.layers.data(name="px", shape=[4])
        y = fluid.layers.data(name="py", shape=[1])
        loss = fluid.layers.mean(fluid.layers.square_error_cost(
            fluid.layers.fc(x, 1), y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        with fluid.program_guard(main, startup):
            pe = fluid.ParallelExecutor(use_cuda=False,
                                        loss_name=loss.name)
        xb = rng.rand(16, 4).astype(np.float32)
        yb = xb.sum(1, keepdims=True).astype(np.float32)
        (lv,) = exe.run(pe, feed={"px": xb, "py": yb},
                        fetch_list=[loss])
        assert np.isfinite(float(np.asarray(lv)))


def test_fluid_dygraph_guard():
    with fluid.dygraph.guard():
        t = fluid.dygraph.to_variable(np.ones((2, 2), np.float32))
        out = t * 3.0
        assert float(np.asarray(out.numpy()).sum()) == 12.0

"""Tier-1 int8-serving gate (NOT marked slow — losing the int8 page
capacity win, quantized-decode token equality, or the radix/spec
composition over int8 pages is a serving regression that must fail the
suite, not wait for a perf round).

Drives tools/int8_serve_smoke.py in-process: one pinned HBM budget
sized at fp32 and int8 by ``static.page_budget``, the Int8Linear
engine over int8 KV pages with radix retention and a speculative
draft, token-equality vs the fp32 paged engine, and a zero-retrace
repeat of the warmed buckets."""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_int8_serve_smoke_gate():
    import int8_serve_smoke
    result = int8_serve_smoke.run_smoke()
    assert result["page_capacity_ratio"] >= 1.9, result
    assert result["token_equal"] is True, result
    assert result["traces_after_warmup"] == 0, result
    assert result["quant_scale_clips"] == 0, result
    assert result["radix_hit_tokens"] > 0, result
    assert result["accepted_per_step"] > 1.0, result

"""Tier-1 auto-parallel-planner gate (NOT marked slow — a regression in
the planner's argmax, its strict-clean contract, or the `bench.py
--auto` plan+apply path must fail the suite, not wait for a perf round).

Drives tools/plan_smoke.py in-process: `static.plan_program` on a toy
transformer returns a verified plan that ties or beats the knob-free
baseline on predicted step time, the applied plan is
`check_program(level="collective")`-clean with the plan on record
(V504 drift surface), and the `bench.py --auto` dry-run path emits a
well-formed plan record — all under 10 s.  Mirrors the
mem_smoke/verify_smoke gate pattern.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_plan_smoke_gate():
    import plan_smoke
    result = plan_smoke.run_smoke()
    assert result["value"] < 10, result           # wall budget
    assert result["n_candidates"] >= 4, result    # the lattice was real
    assert result["predicted_step_ms"] <= result["baseline_step_ms"], result
    assert result["auto_dry_run_ok"] is True, result


@pytest.mark.slow
def test_plan_smoke_cli():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "plan_smoke.py")],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    assert '"metric": "plan_smoke_wall_s"' in out.stdout

"""Single-op tests vs numpy for the north-star op set (SURVEY.md §7 stage 3).
Mirrors the reference's test_matmul_op.py / test_softmax_op.py / ... pattern."""
import numpy as np
import pytest

from op_test import OpTest


class TestMatmul(OpTest):
    op_type = "matmul"

    def test_basic(self):
        self.setup()
        x = np.random.rand(4, 8).astype(np.float64)
        y = np.random.rand(8, 5).astype(np.float64)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x @ y}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")

    def test_transpose(self):
        self.setup()
        x = np.random.rand(8, 4).astype(np.float64)
        y = np.random.rand(5, 8).astype(np.float64)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_X": True, "transpose_Y": True, "alpha": 2.0}
        self.outputs = {"Out": 2.0 * (x.T @ y.T)}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")

    def test_batched(self):
        self.setup()
        x = np.random.rand(3, 4, 8).astype(np.float64)
        y = np.random.rand(3, 8, 5).astype(np.float64)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": np.matmul(x, y)}
        self.check_output()


class TestMul(OpTest):
    op_type = "mul"

    def test_basic(self):
        self.setup()
        x = np.random.rand(4, 2, 3).astype(np.float64)
        y = np.random.rand(6, 5).astype(np.float64)
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x.reshape(4, 6) @ y}
        self.check_output()
        self.check_grad(["X", "Y"], "Out")


class TestElementwise(OpTest):
    def _run(self, op, fn, grad=True):
        self.op_type = op
        self.setup()
        x = np.random.rand(3, 4).astype(np.float64) + 0.5
        y = np.random.rand(3, 4).astype(np.float64) + 0.5
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": fn(x, y)}
        self.check_output()
        if grad:
            self.check_grad(["X", "Y"], "Out")

    def test_add(self):
        self._run("elementwise_add", np.add)

    def test_sub(self):
        self._run("elementwise_sub", np.subtract)

    def test_mul(self):
        self._run("elementwise_mul", np.multiply)

    def test_div(self):
        self._run("elementwise_div", np.divide)

    def test_max(self):
        self._run("elementwise_max", np.maximum, grad=False)

    def test_min(self):
        self._run("elementwise_min", np.minimum, grad=False)

    def test_pow(self):
        self._run("elementwise_pow", np.power)

    def test_broadcast_axis(self):
        self.op_type = "elementwise_add"
        self.setup()
        x = np.random.rand(2, 3, 4, 5).astype(np.float64)
        y = np.random.rand(3, 4).astype(np.float64)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": x + y.reshape(1, 3, 4, 1)}
        self.check_output()


class TestActivations(OpTest):
    def _run(self, op, fn, grad=True, x=None):
        self.op_type = op
        self.setup()
        if x is None:
            x = np.random.rand(3, 7).astype(np.float64) + 0.25
        self.inputs = {"X": x}
        self.outputs = {"Out": fn(x)}
        self.check_output()
        if grad:
            self.check_grad(["X"], "Out")

    def test_relu(self):
        x = np.random.randn(3, 7).astype(np.float64)
        x[np.abs(x) < 0.05] = 0.5
        self._run("relu", lambda v: np.maximum(v, 0), x=x)

    def test_sigmoid(self):
        self._run("sigmoid", lambda v: 1 / (1 + np.exp(-v)))

    def test_tanh(self):
        self._run("tanh", np.tanh)

    def test_exp(self):
        self._run("exp", np.exp)

    def test_log(self):
        self._run("log", np.log)

    def test_sqrt(self):
        self._run("sqrt", np.sqrt)

    def test_square(self):
        self._run("square", np.square)

    def test_gelu(self):
        from scipy.special import erf
        self._run("gelu", lambda v: 0.5 * v * (1 + erf(v / np.sqrt(2))))

    def test_abs(self):
        self._run("abs", np.abs)


class TestReduce(OpTest):
    def _run(self, op, fn, attrs, expected=None, grad=True):
        self.op_type = op
        self.setup()
        x = np.random.rand(2, 3, 4).astype(np.float64)
        self.inputs = {"X": x}
        self.attrs = attrs
        self.outputs = {"Out": fn(x) if expected is None else expected}
        self.check_output()
        if grad:
            self.check_grad(["X"], "Out")

    def test_sum_all(self):
        self._run("reduce_sum", lambda x: x.sum(), {"reduce_all": True})

    def test_sum_dim(self):
        self._run("reduce_sum", lambda x: x.sum(axis=1), {"dim": [1]})

    def test_mean_keepdim(self):
        self._run("reduce_mean", lambda x: x.mean(axis=(0, 2), keepdims=True),
                  {"dim": [0, 2], "keep_dim": True})

    def test_max(self):
        self._run("reduce_max", lambda x: x.max(axis=2), {"dim": [2]},
                  grad=False)

    def test_prod(self):
        self._run("reduce_prod", lambda x: x.prod(axis=0), {"dim": [0]})


class TestSoftmax(OpTest):
    op_type = "softmax"

    def test_basic(self):
        self.setup()
        x = np.random.rand(3, 10).astype(np.float64)
        e = np.exp(x - x.max(-1, keepdims=True))
        self.inputs = {"X": x}
        self.outputs = {"Out": e / e.sum(-1, keepdims=True)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestSoftmaxWithCrossEntropy(OpTest):
    op_type = "softmax_with_cross_entropy"

    def test_hard_label(self):
        self.setup()
        logits = np.random.rand(5, 7).astype(np.float64)
        label = np.random.randint(0, 7, (5, 1)).astype(np.int64)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        loss = -np.log(sm[np.arange(5), label.ravel()]).reshape(5, 1)
        self.inputs = {"Logits": logits, "Label": label}
        self.outputs = {"Softmax": sm, "Loss": loss}
        self.check_output()
        self.check_grad(["Logits"], "Loss")

    def test_soft_label(self):
        self.setup()
        logits = np.random.rand(5, 7).astype(np.float64)
        label = np.random.rand(5, 7).astype(np.float64)
        label /= label.sum(-1, keepdims=True)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        loss = -(label * np.log(sm)).sum(-1, keepdims=True)
        self.inputs = {"Logits": logits, "Label": label}
        self.attrs = {"soft_label": True}
        self.outputs = {"Softmax": sm, "Loss": loss}
        self.check_output()


class TestLayerNorm(OpTest):
    op_type = "layer_norm"

    def test_basic(self):
        self.setup()
        x = np.random.rand(4, 10).astype(np.float64)
        scale = np.random.rand(10).astype(np.float64)
        bias = np.random.rand(10).astype(np.float64)
        m = x.mean(-1, keepdims=True)
        v = x.var(-1, keepdims=True)
        y = (x - m) / np.sqrt(v + 1e-5) * scale + bias
        self.inputs = {"X": x, "Scale": scale, "Bias": bias}
        self.attrs = {"epsilon": 1e-5, "begin_norm_axis": 1}
        self.outputs = {"Y": y, "Mean": m.ravel(), "Variance": v.ravel()}
        self.check_output(atol=1e-4)
        self.check_grad(["X", "Scale", "Bias"], "Y", max_relative_error=1e-2)


class TestBatchNorm(OpTest):
    op_type = "batch_norm"

    def test_train(self):
        self.setup()
        x = np.random.rand(4, 3, 5, 5).astype(np.float64)
        scale = np.random.rand(3).astype(np.float64)
        bias = np.random.rand(3).astype(np.float64)
        mean = np.zeros(3, np.float64)
        var = np.ones(3, np.float64)
        m = x.mean(axis=(0, 2, 3))
        v = x.var(axis=(0, 2, 3))
        y = (x - m.reshape(1, 3, 1, 1)) / np.sqrt(
            v.reshape(1, 3, 1, 1) + 1e-5) * scale.reshape(1, 3, 1, 1) + \
            bias.reshape(1, 3, 1, 1)
        self.inputs = {"X": x, "Scale": scale, "Bias": bias, "Mean": mean,
                       "Variance": var}
        self.attrs = {"epsilon": 1e-5, "momentum": 0.9}
        self.outputs = {"Y": y, "MeanOut": 0.9 * mean + 0.1 * m,
                        "VarianceOut": 0.9 * var + 0.1 * v}
        self.check_output(atol=1e-4)


class TestConv2d(OpTest):
    op_type = "conv2d"

    def test_basic(self):
        self.setup()
        x = np.random.rand(2, 3, 8, 8).astype(np.float64)
        w = np.random.rand(4, 3, 3, 3).astype(np.float64)
        self.inputs = {"Input": x, "Filter": w}
        self.attrs = {"strides": [1, 1], "paddings": [1, 1],
                      "dilations": [1, 1]}
        import jax
        ref = jax.lax.conv_general_dilated(
            x, w, (1, 1), [(1, 1), (1, 1)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        self.outputs = {"Output": np.asarray(ref)}
        self.check_output()
        self.check_grad(["Input", "Filter"], "Output", delta=1e-4,
                        max_relative_error=2e-2)


class TestPool2d(OpTest):
    op_type = "pool2d"

    def test_max(self):
        self.setup()
        x = np.random.rand(2, 3, 4, 4).astype(np.float64)
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "max", "ksize": [2, 2],
                      "strides": [2, 2], "paddings": [0, 0]}
        ref = x.reshape(2, 3, 2, 2, 2, 2).max(axis=(3, 5))
        self.outputs = {"Out": ref}
        self.check_output()

    def test_avg(self):
        self.setup()
        x = np.random.rand(2, 3, 4, 4).astype(np.float64)
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "avg", "ksize": [2, 2],
                      "strides": [2, 2], "paddings": [0, 0]}
        ref = x.reshape(2, 3, 2, 2, 2, 2).mean(axis=(3, 5))
        self.outputs = {"Out": ref}
        self.check_output()

    def test_global(self):
        self.setup()
        x = np.random.rand(2, 3, 4, 4).astype(np.float64)
        self.inputs = {"X": x}
        self.attrs = {"pooling_type": "avg", "global_pooling": True,
                      "ksize": [1, 1]}
        self.outputs = {"Out": x.mean(axis=(2, 3), keepdims=True)}
        self.check_output()


class TestLookupTable(OpTest):
    op_type = "lookup_table_v2"

    def test_basic(self):
        self.setup()
        w = np.random.rand(10, 4).astype(np.float64)
        ids = np.random.randint(0, 10, (3, 5)).astype(np.int64)
        self.inputs = {"W": w, "Ids": ids}
        self.outputs = {"Out": w[ids]}
        self.check_output()
        self.check_grad(["W"], "Out")


class TestManip(OpTest):
    def test_reshape(self):
        self.op_type = "reshape2"
        self.setup()
        x = np.random.rand(2, 6).astype(np.float64)
        self.inputs = {"X": x}
        self.attrs = {"shape": [3, 4]}
        self.outputs = {"Out": x.reshape(3, 4)}
        self.check_output(no_check_set=("XShape",))
        self.check_grad(["X"], "Out")

    def test_transpose(self):
        self.op_type = "transpose2"
        self.setup()
        x = np.random.rand(2, 3, 4).astype(np.float64)
        self.inputs = {"X": x}
        self.attrs = {"axis": [2, 0, 1]}
        self.outputs = {"Out": x.transpose(2, 0, 1)}
        self.check_output(no_check_set=("XShape",))

    def test_concat(self):
        self.op_type = "concat"
        self.setup()
        xs = [np.random.rand(2, 3).astype(np.float64) for _ in range(3)]
        self.inputs = {"X": xs}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": np.concatenate(xs, axis=1)}
        self.check_output()

    def test_split(self):
        self.op_type = "split"
        self.setup()
        x = np.random.rand(2, 6).astype(np.float64)
        self.inputs = {"X": x}
        self.attrs = {"axis": 1, "num": 3}
        self.outputs = {"Out": np.split(x, 3, axis=1)}
        self.check_output()

    def test_cast(self):
        self.op_type = "cast"
        self.setup()
        x = np.random.rand(3, 4).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"out_dtype": "float64"}
        self.outputs = {"Out": x.astype(np.float64)}
        self.check_output()

    def test_slice(self):
        self.op_type = "slice"
        self.setup()
        x = np.random.rand(4, 5, 6).astype(np.float64)
        self.inputs = {"Input": x}
        self.attrs = {"axes": [0, 2], "starts": [1, 2], "ends": [3, 5]}
        self.outputs = {"Out": x[1:3, :, 2:5]}
        self.check_output()
        self.check_grad(["Input"], "Out")

    def test_stack(self):
        self.op_type = "stack"
        self.setup()
        xs = [np.random.rand(2, 3).astype(np.float64) for _ in range(4)]
        self.inputs = {"X": xs}
        self.attrs = {"axis": 1}
        self.outputs = {"Y": np.stack(xs, axis=1)}
        self.check_output()

    def test_gather(self):
        self.op_type = "gather"
        self.setup()
        x = np.random.rand(10, 4).astype(np.float64)
        idx = np.array([1, 3, 5], np.int64)
        self.inputs = {"X": x, "Index": idx}
        self.outputs = {"Out": x[idx]}
        self.check_output()
        self.check_grad(["X"], "Out")

    def test_one_hot(self):
        self.op_type = "one_hot_v2"
        self.setup()
        x = np.array([1, 0, 3], np.int64)
        self.inputs = {"X": x}
        self.attrs = {"depth": 4}
        self.outputs = {"Out": np.eye(4, dtype=np.float32)[x]}
        self.check_output()

    def test_top_k(self):
        self.op_type = "top_k_v2"
        self.setup()
        x = np.array([[3.0, 1.0, 2.0], [0.5, 0.1, 0.9]], np.float64)
        self.inputs = {"X": x}
        self.attrs = {"k": 2}
        self.outputs = {"Out": np.array([[3.0, 2.0], [0.9, 0.5]]),
                        "Indices": np.array([[0, 2], [2, 0]])}
        self.check_output()


class TestDropout(OpTest):
    op_type = "dropout"

    def test_test_mode(self):
        self.setup()
        x = np.random.rand(4, 8).astype(np.float64)
        self.inputs = {"X": x}
        self.attrs = {"dropout_prob": 0.5, "is_test": True,
                      "dropout_implementation": "upscale_in_train"}
        self.outputs = {"Out": x}
        self.check_output(no_check_set=("Mask",))

    def test_train_statistics(self):
        self.setup()
        x = np.ones((100, 100), np.float32)
        self.inputs = {"X": x}
        self.attrs = {"dropout_prob": 0.3,
                      "dropout_implementation": "upscale_in_train",
                      "op_uid": 7}
        outs = self._run_forward()
        keep = np.asarray(outs["Mask"]).mean()
        assert abs(keep - 0.7) < 0.02
        # kept values upscaled
        o = np.asarray(outs["Out"])
        nz = o[o != 0]
        np.testing.assert_allclose(nz, 1.0 / 0.7, rtol=1e-5)

    def test_deterministic_replay(self):
        self.setup()
        x = np.random.rand(16, 16).astype(np.float32)
        self.inputs = {"X": x}
        self.attrs = {"dropout_prob": 0.5, "op_uid": 11,
                      "dropout_implementation": "upscale_in_train"}
        m1 = np.asarray(self._run_forward()["Mask"])
        m2 = np.asarray(self._run_forward()["Mask"])
        np.testing.assert_array_equal(m1, m2)


class TestOptimizerOps(OpTest):
    def test_sgd(self):
        self.op_type = "sgd"
        self.setup()
        p = np.random.rand(5, 3).astype(np.float32)
        g = np.random.rand(5, 3).astype(np.float32)
        lr = np.array([0.1], np.float32)
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr}
        self.outputs = {"ParamOut": p - 0.1 * g}
        self.check_output(atol=1e-6)

    def test_adam(self):
        self.op_type = "adam"
        self.setup()
        p = np.random.rand(4).astype(np.float32)
        g = np.random.rand(4).astype(np.float32)
        m1 = np.zeros(4, np.float32)
        m2 = np.zeros(4, np.float32)
        lr = np.array([0.001], np.float32)
        b1p = np.array([0.9], np.float32)
        b2p = np.array([0.999], np.float32)
        self.inputs = {"Param": p, "Grad": g, "LearningRate": lr,
                       "Moment1": m1, "Moment2": m2, "Beta1Pow": b1p,
                       "Beta2Pow": b2p}
        m1_o = 0.1 * g
        m2_o = 0.001 * g * g
        lr_t = 0.001 * np.sqrt(1 - b2p) / (1 - b1p)
        p_o = p - lr_t * m1_o / (np.sqrt(m2_o) + 1e-8)
        self.outputs = {"ParamOut": p_o, "Moment1Out": m1_o,
                        "Moment2Out": m2_o, "Beta1PowOut": b1p * 0.9,
                        "Beta2PowOut": b2p * 0.999}
        self.check_output(atol=1e-5)

    def test_momentum(self):
        self.op_type = "momentum"
        self.setup()
        p = np.random.rand(4).astype(np.float32)
        g = np.random.rand(4).astype(np.float32)
        v = np.random.rand(4).astype(np.float32)
        lr = np.array([0.01], np.float32)
        self.inputs = {"Param": p, "Grad": g, "Velocity": v,
                       "LearningRate": lr}
        self.attrs = {"mu": 0.9}
        v_o = 0.9 * v + g
        self.outputs = {"ParamOut": p - 0.01 * v_o, "VelocityOut": v_o}
        self.check_output(atol=1e-6)


class TestLosses(OpTest):
    def test_bce(self):
        self.op_type = "bce_loss"
        self.setup()
        x = np.random.uniform(0.1, 0.9, (4, 3)).astype(np.float64)
        l = np.random.randint(0, 2, (4, 3)).astype(np.float64)
        self.inputs = {"X": x, "Label": l}
        self.outputs = {"Out": -(l * np.log(x + 1e-12) +
                                 (1 - l) * np.log(1 - x + 1e-12))}
        self.check_output()
        self.check_grad(["X"], "Out")

    def test_huber(self):
        self.op_type = "huber_loss"
        self.setup()
        x = np.random.rand(5, 1).astype(np.float64)
        y = np.random.rand(5, 1).astype(np.float64)
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"delta": 0.5}
        r = y - x
        loss = np.where(np.abs(r) <= 0.5, 0.5 * r * r,
                        0.5 * (np.abs(r) - 0.25))
        self.outputs = {"Residual": r, "Out": loss}
        self.check_output()


class TestMetrics(OpTest):
    def test_accuracy(self):
        self.op_type = "accuracy"
        self.setup()
        idx = np.array([[0, 2], [1, 3], [2, 0]], np.int64)
        label = np.array([[2], [0], [1]], np.int64)
        self.inputs = {"Out": np.zeros((3, 2), np.float32), "Indices": idx,
                       "Label": label}
        self.outputs = {"Accuracy": np.array([1.0 / 3], np.float32),
                        "Correct": np.array([1], np.int32),
                        "Total": np.array([3], np.int32)}
        self.check_output()


class TestRandomOps(OpTest):
    def test_uniform_range(self):
        self.op_type = "uniform_random"
        self.setup()
        self.attrs = {"shape": [100, 100], "min": -2.0, "max": 3.0,
                      "op_uid": 3}
        out = np.asarray(self._run_forward()["Out"])
        assert out.min() >= -2.0 and out.max() < 3.0
        assert abs(out.mean() - 0.5) < 0.1

    def test_gaussian_moments(self):
        self.op_type = "gaussian_random"
        self.setup()
        self.attrs = {"shape": [200, 200], "mean": 1.0, "std": 2.0,
                      "op_uid": 5}
        out = np.asarray(self._run_forward()["Out"])
        assert abs(out.mean() - 1.0) < 0.05
        assert abs(out.std() - 2.0) < 0.05

"""Long-context attention tests: Pallas flash kernel + ring attention
sequence parallelism (SURVEY.md §5.7 — the TPU-native capability the
reference lacks)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.attention import (flash_attention, reference_attention,
                                      ring_attention,
                                      enable_flash_attention)


def _qkv(B=2, H=2, S=128, D=32, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.rand(B, H, S, D).astype(np.float32))
    return mk(), mk(), mk()


def test_flash_matches_reference():
    q, k, v = _qkv()
    np.testing.assert_allclose(np.asarray(flash_attention(q, k, v)),
                               np.asarray(reference_attention(q, k, v)),
                               rtol=1e-4, atol=1e-5)


def test_flash_causal_and_grads():
    q, k, v = _qkv(S=256, D=64)
    ref = reference_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)

    def loss_flash(q):
        return (flash_attention(q, k, v, causal=True) ** 2).sum()

    def loss_ref(q):
        return (reference_attention(q, k, v, causal=True) ** 2).sum()

    g1 = jax.grad(loss_flash)(q)
    g2 = jax.grad(loss_ref)(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("sq,sk", [(128, 128), (64, 128)])
def test_flash_blockwise_backward_qkv(causal, sq, sk):
    """The Pallas blockwise backward (dq/dk/dv kernels) must match the
    reference vjp for every input, incl. cross-attention shapes."""
    rng = np.random.RandomState(1)
    B, H, D = 2, 2, 32
    q = jnp.asarray(rng.rand(B, H, sq, D).astype(np.float32))
    k = jnp.asarray(rng.rand(B, H, sk, D).astype(np.float32))
    v = jnp.asarray(rng.rand(B, H, sk, D).astype(np.float32))
    g = jnp.asarray(rng.rand(B, H, sq, D).astype(np.float32))

    _, vjp_f = jax.vjp(
        lambda q_, k_, v_: flash_attention(q_, k_, v_, causal=causal,
                                           block_q=32, block_k=32),
        q, k, v)
    _, vjp_r = jax.vjp(
        lambda q_, k_, v_: reference_attention(q_, k_, v_, causal=causal),
        q, k, v)
    for name, a, b in zip("qkv", vjp_f(g), vjp_r(g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4,
                                   err_msg=f"d{name} causal={causal}")


def test_flash_backward_bf16():
    """bf16 inputs (the AMP path) go through the Pallas backward with f32
    accumulation; compare against the f32 reference loosely."""
    rng = np.random.RandomState(2)
    B, H, S, D = 1, 2, 128, 32
    qf = rng.rand(B, H, S, D).astype(np.float32)
    kf = rng.rand(B, H, S, D).astype(np.float32)
    vf = rng.rand(B, H, S, D).astype(np.float32)
    q, k, v = (jnp.asarray(a, jnp.bfloat16) for a in (qf, kf, vf))

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True).astype(jnp.float32)
                ** 2).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True)
                .astype(jnp.float32) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=0.05, atol=0.1, err_msg=f"d{name} bf16")


def test_flash_irregular_len_falls_back():
    q, k, v = _qkv(S=100)  # not a multiple of the block size
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(reference_attention(q, k, v)),
                               rtol=1e-4, atol=1e-5)


def test_ring_attention_sharded():
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.utils.shard_map_compat import shard_map_unchecked
    q, k, v = _qkv(S=128, D=32)
    mesh = Mesh(np.array(jax.devices()[:8]), ("sp",))

    for causal in (False, True):
        ref = reference_attention(q, k, v, causal=causal)

        def fn(q, k, v, causal=causal):
            return ring_attention(q, k, v, "sp", causal=causal)

        sharded = shard_map_unchecked(
            fn, mesh, in_specs=(P(None, None, "sp", None),) * 3,
            out_specs=P(None, None, "sp", None))
        out = jax.jit(sharded)(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=f"causal={causal}")


def test_ring_attention_grads_sharded():
    from jax.sharding import Mesh, PartitionSpec as P
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
    q, k, v = _qkv(S=64, D=16)
    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))

    def ring_loss(q, k, v):
        def fn(q, k, v):
            return ring_attention(q, k, v, "sp", causal=True)
        try:
            f = shard_map(fn, mesh=mesh,
                          in_specs=(P(None, None, "sp", None),) * 3,
                          out_specs=P(None, None, "sp", None),
                          check_vma=False)
        except TypeError:
            f = shard_map(fn, mesh=mesh,
                          in_specs=(P(None, None, "sp", None),) * 3,
                          out_specs=P(None, None, "sp", None),
                          check_rep=False)
        return (f(q, k, v) ** 2).sum()

    def ref_loss(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).sum()

    g1 = jax.grad(ring_loss)(q, k, v)
    g2 = jax.grad(ref_loss)(q, k, v)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-3, atol=1e-4)


def test_mha_flash_path_matches():
    import paddle_tpu
    import paddle_tpu.nn as nn
    layer = nn.MultiHeadAttention(32, 4, dropout=0.0)
    layer.eval()
    x = paddle_tpu.to_tensor(
        np.random.RandomState(0).rand(2, 16, 32).astype(np.float32))
    base = layer(x).numpy()
    enable_flash_attention(True)
    try:
        fl = layer(x).numpy()
    finally:
        enable_flash_attention(False)
    np.testing.assert_allclose(fl, base, rtol=1e-4, atol=1e-5)


def test_mha_flash_backward():
    import paddle_tpu
    import paddle_tpu.nn as nn
    layer = nn.MultiHeadAttention(32, 4, dropout=0.0)
    x = paddle_tpu.to_tensor(
        np.random.RandomState(0).rand(2, 16, 32).astype(np.float32))
    enable_flash_attention(True)
    try:
        out = layer(x)
        out.sum().backward()
    finally:
        enable_flash_attention(False)
    assert layer.q_proj.weight.grad is not None


def test_static_ring_attention_op_sequence_parallel():
    """Static program using the ring_attention op under a (dp=2, sp=4)
    mesh; loss must match the single-device run."""
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    from paddle_tpu.static.layer_helper import LayerHelper
    from paddle_tpu.distributed import CompiledProgram, BuildStrategy

    B, H, S, D = 4, 2, 32, 16

    def build():
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            q = layers.data("q", [-1, S, H * D])
            helper = LayerHelper("ring_attention")
            out = helper.create_variable_for_type_inference("float32")
            out.shape = (-1, S, H * D)
            helper.append_op("ring_attention",
                             inputs={"Q": [q], "K": [q], "V": [q]},
                             outputs={"Out": [out]},
                             attrs={"causal": True, "ring_id": 101,
                                    "num_heads": H})
            loss = layers.mean(layers.square(out))
        return main, startup, loss

    rng = np.random.RandomState(0)
    qb = rng.rand(B, S, H * D).astype(np.float32)

    main, startup, loss = build()
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        (single,) = exe.run(main, feed={"q": qb}, fetch_list=[loss])

    main2, startup2, loss2 = build()
    bs = BuildStrategy()
    bs.sequence_parallel_degree = 4
    cp = CompiledProgram(main2, build_strategy=bs).with_data_parallel()
    exe2 = static.Executor()
    scope2 = static.Scope()
    with static.scope_guard(scope2):
        exe2.run(startup2)
        (sharded,) = exe2.run(cp, feed={"q": qb}, fetch_list=[loss2])
    np.testing.assert_allclose(float(sharded), float(single),
                               rtol=1e-4, atol=1e-6)


def test_flash_cross_length_causal():
    """sq != sk causal must be bottom-right aligned like the reference
    (decode-with-KV-prefix shape)."""
    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.rand(1, 2, 128, 32).astype(np.float32))
    k = jnp.asarray(rng.rand(1, 2, 256, 32).astype(np.float32))
    v = jnp.asarray(rng.rand(1, 2, 256, 32).astype(np.float32))
    out = flash_attention(q, k, v, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_flash_sq_gt_sk_causal_valid_rows():
    """Bottom-right causal with MORE queries than keys: the first sq-sk
    rows see no key at all (undefined — flash outputs zero); every valid
    row must match the reference exactly."""
    import jax
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    sq, sk = 128, 64
    q = jnp.asarray(rng.randn(1, 2, sq, 32), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, sk, 32), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, sk, 32), jnp.float32)
    out = flash_attention(q, k, v, causal=True)
    ref = reference_attention(q, k, v, causal=True)
    # valid rows (those with >= 1 visible key) agree
    np.testing.assert_allclose(np.asarray(out[:, :, sq - sk:]),
                               np.asarray(ref[:, :, sq - sk:]),
                               rtol=1e-5, atol=1e-5)
    # undefined rows are zero by convention
    np.testing.assert_allclose(np.asarray(out[:, :, : sq - sk]), 0.0,
                               atol=1e-6)
    # dq, dk AND dv agree (the dkv kernel's causal start index goes
    # through its negative sk-sq branch exactly in this configuration)
    gs = jax.grad(lambda a, b, c: flash_attention(
        a, b, c, causal=True)[:, :, sq - sk:].sum(),
        argnums=(0, 1, 2))(q, k, v)
    grs = jax.grad(lambda a, b, c: reference_attention(
        a, b, c, causal=True)[:, :, sq - sk:].sum(),
        argnums=(0, 1, 2))(q, k, v)
    for g, gr, tag in zip(gs, grs, ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                                   rtol=1e-4, atol=1e-5, err_msg=tag)


# ---------------------------------------------------------------------------
# Pallas fused softmax-cross-entropy (ops/fused_xent.py — second kernel,
# VERDICT r3 missing #4) — interpret-mode numerics vs XLA
# ---------------------------------------------------------------------------

def test_fused_xent_forward_matches_xla():
    from paddle_tpu.ops.fused_xent import fused_softmax_xent
    rng = np.random.RandomState(0)
    T, V = 64, 777  # ragged vocab tail exercises the masked last block
    logits = jnp.asarray(rng.randn(T, V).astype(np.float32) * 3)
    labels = jnp.asarray(rng.randint(0, V, (T,)).astype(np.int32))
    loss = fused_softmax_xent(logits, labels, -100, 32, 256, True)
    ref = -jax.nn.log_softmax(logits)[jnp.arange(T), labels]
    np.testing.assert_allclose(np.asarray(loss)[:, 0], np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_fused_xent_backward_matches_xla():
    from paddle_tpu.ops.fused_xent import fused_softmax_xent
    rng = np.random.RandomState(1)
    T, V = 32, 300
    logits = jnp.asarray(rng.randn(T, V).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, V, (T,)).astype(np.int32))

    d1 = jax.grad(lambda lg: jnp.sum(
        fused_softmax_xent(lg, labels, -100, 16, 128, True)))(logits)
    d2 = jax.grad(lambda lg: jnp.sum(
        -jax.nn.log_softmax(lg)[jnp.arange(T), labels]))(logits)
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                               rtol=1e-5, atol=1e-6)


def test_fused_xent_ignore_index():
    from paddle_tpu.ops.fused_xent import fused_softmax_xent
    rng = np.random.RandomState(2)
    T, V = 16, 100
    logits = jnp.asarray(rng.randn(T, V).astype(np.float32))
    labels = np.asarray(rng.randint(0, V, (T,)), np.int32)
    labels[::4] = 7  # use 7 as ignore_index
    loss = fused_softmax_xent(logits, jnp.asarray(labels), 7, 8, 128,
                              True)
    assert (np.asarray(loss)[::4, 0] == 0).all()
    g = jax.grad(lambda lg: jnp.sum(
        fused_softmax_xent(lg, jnp.asarray(labels), 7, 8, 128, True)))(
        logits)
    assert (np.abs(np.asarray(g)[::4]) == 0).all()


def test_fused_xent_through_op_flag():
    from paddle_tpu.ops.registry import run_kernel, OpContext
    from paddle_tpu.ops.fused_xent import enable_fused_xent
    rng = np.random.RandomState(3)
    logits = jnp.asarray(rng.randn(8, 16, 500).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 500, (8, 16, 1)).astype(np.int64))
    base = run_kernel("softmax_with_cross_entropy",
                      {"Logits": logits, "Label": labels}, {},
                      OpContext())
    enable_fused_xent(True)
    try:
        fused = run_kernel("softmax_with_cross_entropy",
                           {"Logits": logits, "Label": labels}, {},
                           OpContext())
    finally:
        enable_fused_xent(False)
    np.testing.assert_allclose(np.asarray(fused["Loss"]),
                               np.asarray(base["Loss"]), rtol=1e-5,
                               atol=1e-5)


def test_wired_sequence_parallel_transformer_lm():
    """The PUBLIC long-seq wiring: build_transformer_lm(
    sequence_parallel=True) emits ring_attention ops per layer, runs
    single-device (ring degrades to plain attention), matches the
    non-sp build numerically there, and composes with FLAGS_recompute
    auto-remat (barriers + ring op in the same block).  The dp×sp mesh
    execution of the ring op itself is pinned by
    test_static_ring_attention_op_sequence_parallel above."""
    import paddle_tpu.static as static
    from paddle_tpu.core.flags import set_flags
    from paddle_tpu.core.program import _reset_unique_names
    from paddle_tpu.models.static_lm import build_transformer_lm

    VOCAB, HID, SEQ, B = 64, 32, 16, 4
    rng = np.random.RandomState(0)
    feed = {"ids": rng.randint(0, VOCAB, (B, SEQ)).astype(np.int32),
            "pos": np.tile(np.arange(SEQ), (B, 1)).astype(np.int32),
            "labels": rng.randint(0, VOCAB,
                                  (B, SEQ, 1)).astype(np.int32)}

    def build(sp, remat=False):
        _reset_unique_names()
        if remat:
            set_flags({"recompute": "always"})
        try:
            main, startup, loss, _ = build_transformer_lm(
                VOCAB, HID, 2, 2, SEQ, sequence_parallel=sp)
            with static.program_guard(main, startup):
                static.SGD(learning_rate=0.0).minimize(loss)
        finally:
            set_flags({"recompute": ""})
        return main, startup, loss

    def run_single(main, startup, loss):
        exe, sc = static.Executor(), static.Scope()
        with static.scope_guard(sc):
            exe.run(startup)
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
        return float(lv)

    main_sp, startup_sp, loss_sp = build(sp=True)
    ring_ops = [op for op in main_sp.global_block().ops
                if op.type == "ring_attention"]
    assert len(ring_ops) == 2  # one per layer
    l_sp = run_single(main_sp, startup_sp, loss_sp)
    main_plain, startup_plain, loss_plain = build(sp=False)
    l_plain = run_single(main_plain, startup_plain, loss_plain)
    np.testing.assert_allclose(l_sp, l_plain, rtol=1e-4, atol=1e-6)

    # remat × ring compose in one block, numerics preserved
    main_r, startup_r, loss_r = build(sp=True, remat=True)
    ops_r = [op.type for op in main_r.global_block().ops]
    assert "optimization_barrier" in ops_r and "ring_attention" in ops_r
    l_r = run_single(main_r, startup_r, loss_r)
    np.testing.assert_allclose(l_r, l_plain, rtol=1e-4, atol=1e-6)

"""HTTP inference server + remote-client protocol (C28).

The Go (go/paddle/predictor.go) and R (r/paddle.R) clients speak this
protocol; Python's stdlib client exercises it end-to-end here, byte-for
-byte the same routes/payloads the Go client sends."""
import json
import urllib.request

import numpy as np

import paddle_tpu.static as static
from paddle_tpu.static import layers


def _save_model(tmp_path):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        out = layers.fc(x, 3, act="softmax",
                        param_attr=static.ParamAttr(name="srv_w"),
                        bias_attr=static.ParamAttr(name="srv_b"))
    exe = static.Executor()
    scope = static.Scope()
    xb = np.random.RandomState(0).rand(4, 8).astype(np.float32)
    with static.scope_guard(scope):
        exe.run(startup)
        from paddle_tpu.io.framework_io import save_inference_model
        save_inference_model(str(tmp_path), ["x"], [out], exe, main)
        (ref,) = exe.run(main, feed={"x": xb}, fetch_list=[out])
    return xb, np.asarray(ref), out.name


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def test_server_metadata_predict_and_error(tmp_path):
    from paddle_tpu.inference.server import InferenceServer
    xb, ref, out_name = _save_model(tmp_path)
    srv = InferenceServer(str(tmp_path))
    srv.start()
    try:
        base = f"http://{srv.host}:{srv.port}"
        with urllib.request.urlopen(base + "/health", timeout=10) as r:
            assert json.loads(r.read())["status"] == "ok"
        with urllib.request.urlopen(base + "/metadata", timeout=10) as r:
            md = json.loads(r.read())
        assert md["inputs"] == ["x"]
        assert md["outputs"] == [out_name]

        # nested-list form
        reply = _post(base + "/predict", {"inputs": {"x": xb.tolist()}})
        got = np.asarray(reply["outputs"][out_name]["data"]).reshape(
            reply["outputs"][out_name]["shape"])
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-6)

        # explicit tensor form (what the Go client sends)
        reply2 = _post(base + "/predict", {"inputs": {"x": {
            "data": xb.ravel().tolist(), "shape": list(xb.shape),
            "dtype": "float32"}}})
        got2 = np.asarray(reply2["outputs"][out_name]["data"]).reshape(
            reply2["outputs"][out_name]["shape"])
        np.testing.assert_allclose(got2, ref, rtol=1e-4, atol=1e-6)

        # malformed request -> structured 400, server stays alive
        try:
            _post(base + "/predict", {"inputs": {}})
            assert False, "expected HTTPError"
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "error" in json.loads(e.read())
        reply3 = _post(base + "/predict", {"inputs": {"x": xb.tolist()}})
        assert reply3["outputs"][out_name]["shape"] == list(ref.shape)
    finally:
        srv.stop()

"""Tier-1 compute-sharing gate (NOT marked slow — a regression in
radix retention, reused prefill, speculative token-equality, or the
bounded-compiled-shapes contract must fail the suite, not wait for a
perf round).

Drives tools/spec_smoke.py in-process: the second identical prompt
hits the retained radix tree and prefills only the uncovered suffix,
speculative decode through a stamped draft commits more than one token
per target verify step while staying token-equal to the plain engine,
compiled KV buckets stop growing after warmup, and the pool drains
leak-free with retained pages still resident.  Mirrors the page_smoke
gate pattern; the CLI round-trip is `slow` (a fresh interpreter buys
no extra coverage in-process).
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_spec_smoke_gate():
    import spec_smoke
    result = spec_smoke.run_smoke()
    assert result["traces_after_warmup"] == 0, result
    assert result["radix_hit_tokens"] > 0, result
    assert result["prefill_tokens_on_hit"] < result["prompt_tokens"], \
        result
    assert result["accepted_per_step"] > 1.0, result
    assert result["retained_pages_at_drain"] > 0, result
    assert result["value"] < 60, result  # in-process gate stays fast


@pytest.mark.slow
def test_spec_smoke_cli_prints_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "spec_smoke.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["traces_after_warmup"] == 0
    assert result["accepted_per_step"] > 1.0
    assert result["radix_hit_tokens"] > 0

"""Systematic API-surface parity against the reference's public
__init__ files: every quoted public name in a reference namespace's
__init__ must resolve on the corresponding paddle_tpu module.

Skipped when the reference checkout is not mounted (the suite must be
self-contained elsewhere); under the build/judge environment this locks
the audited namespaces at zero missing names.
"""
import os
import re

import pytest

REF = "/root/reference/python/paddle/"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference checkout not mounted")

# (reference __init__ relative path, paddle_tpu module path)
NAMESPACES = [
    ("__init__.py", "paddle_tpu"),
    ("nn/__init__.py", "paddle_tpu.nn"),
    ("nn/functional/__init__.py", "paddle_tpu.nn.functional"),
    ("nn/layer/__init__.py", "paddle_tpu.nn.layer"),
    ("nn/initializer/__init__.py", "paddle_tpu.nn.initializer"),
    ("tensor/__init__.py", "paddle_tpu.tensor"),
    ("optimizer/__init__.py", "paddle_tpu.optimizer"),
    ("metric/__init__.py", "paddle_tpu.metric"),
    ("distributed/__init__.py", "paddle_tpu.distributed"),
    ("distributed/fleet/__init__.py", "paddle_tpu.distributed.fleet"),
    ("distributed/fleet/utils/__init__.py",
     "paddle_tpu.distributed.fleet.utils"),
    ("static/__init__.py", "paddle_tpu.static"),
    ("static/nn/__init__.py", "paddle_tpu.static.nn"),
    ("io/__init__.py", "paddle_tpu.io"),
    ("vision/__init__.py", "paddle_tpu.vision"),
    ("vision/models/__init__.py", "paddle_tpu.vision.models"),
    ("vision/transforms/__init__.py", "paddle_tpu.vision.transforms"),
    ("text/__init__.py", "paddle_tpu.text"),
    ("hapi/__init__.py", "paddle_tpu.hapi"),
    ("jit/__init__.py", "paddle_tpu.jit"),
    ("inference/__init__.py", "paddle_tpu.inference"),
    ("incubate/__init__.py", "paddle_tpu.incubate"),
    ("utils/__init__.py", "paddle_tpu.utils"),
    ("framework/__init__.py", "paddle_tpu.framework"),
    ("compat.py", "paddle_tpu.compat"),
    ("sysconfig.py", "paddle_tpu.sysconfig"),
    ("distribution.py", "paddle_tpu.distribution"),
]

# docstring/header tokens the quoted-string scrape inevitably picks up
NOISE = {"License", "Apache", "AS", "print_function", "unicode_literals",
         "division", "utf", "paddle", "fluid"}


def _public_names(ref_file):
    # drop comment lines first: commented-out __all__ entries (e.g.
    # io's '#Transform') are not public surface
    text = "\n".join(l for l in open(ref_file).read().splitlines()
                     if not l.lstrip().startswith("#"))
    # prefer explicit LITERAL __all__ blocks (exact surface); any
    # computed __all__ (concatenation, += module.__all__) falls back to
    # the whole-file scrape — a partial literal would silently shrink
    # the check, and `__all__ = []` would make it vacuous
    blocks = re.findall(r"__all__\s*\+?=\s*\[([^\]]*)\]", text)
    computed = re.search(r"__all__\s*\+?=(?!\s*\[)", text) or \
        re.search(r"__all__\s*\+?=\s*\[[^\]]*\]\s*\+", text)
    block_names = set()
    for b in blocks:
        block_names |= set(re.findall(r"['\"]([A-Za-z_]\w*)['\"]", b))
    if blocks and not computed and block_names:
        names = block_names
    else:
        names = set(re.findall(r"'([A-Za-z_]\w*)'", text))
        names |= set(re.findall(r'"([A-Za-z_]\w*)"', text))
    return {n for n in names if not n.startswith("_") and n not in NOISE}


@pytest.mark.parametrize("ref_rel,mod_path", NAMESPACES,
                         ids=[m for _, m in NAMESPACES])
def test_namespace_surface(ref_rel, mod_path):
    import importlib
    ref_file = os.path.join(REF, ref_rel)
    if not os.path.exists(ref_file):
        pytest.skip(f"no reference file {ref_rel}")
    import types
    mod = importlib.import_module(mod_path)
    mine = set(dir(mod))
    missing = sorted(_public_names(ref_file) - mine)
    # a name counts as present if a direct SUBMODULE exposes it (the
    # reference scatters re-exports across submodules); arbitrary class
    # attributes do NOT count — hasattr over every attr would let any
    # class's 'name'/'shape' property vacuously satisfy the check
    truly_missing = []
    for n in missing:
        found = False
        for attr in mine:
            sub = getattr(mod, attr, None)
            if isinstance(sub, types.ModuleType) and hasattr(sub, n):
                found = True
                break
        if not found:
            truly_missing.append(n)
    assert not truly_missing, (
        f"{mod_path} lacks reference names: {truly_missing}")

"""GPT decoder family + model-level beam search (reference decode loop
over beam_search_op.cc; 2.x generate() contract)."""
import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.dygraph as dg


def _tiny_gpt(vocab=50):
    from paddle_tpu.models import GPTConfig, GPTModel, GPTForGeneration
    cfg = GPTConfig(vocab_size=vocab, hidden_size=32, num_layers=2,
                    num_heads=2, max_position=64, dropout=0.0)
    return GPTForGeneration(GPTModel(cfg))


def test_gpt_trains_and_causal():
    """LM loss on a fixed batch decreases; logits at position t must not
    depend on tokens after t (causal mask)."""
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    rng = np.random.RandomState(0)
    ids = rng.randint(2, 50, (4, 12)).astype(np.int64)
    with dg.guard():
        m = _tiny_gpt()
        m.train()
        ce = nn.CrossEntropyLoss()
        adam = opt.Adam(learning_rate=1e-2, parameters=m.parameters())
        first = None
        for _ in range(15):
            logits = m(paddle_tpu.to_tensor(ids[:, :-1]))
            loss = ce(logits.reshape([-1, 50]),
                      paddle_tpu.to_tensor(ids[:, 1:].reshape(-1)))
            loss.backward()
            adam.step()
            adam.clear_grad()
            first = first or float(loss.numpy())
        assert float(loss.numpy()) < first

        m.eval()
        base = np.asarray(m(paddle_tpu.to_tensor(ids)).numpy())
        ids2 = ids.copy()
        ids2[:, -1] = (ids2[:, -1] + 7) % 50  # change LAST token only
        pert = np.asarray(m(paddle_tpu.to_tensor(ids2)).numpy())
        np.testing.assert_allclose(base[:, :-1], pert[:, :-1],
                                   rtol=1e-4, atol=1e-5)
        assert np.abs(base[:, -1] - pert[:, -1]).max() > 1e-4


def test_generate_strategies():
    rng = np.random.RandomState(1)
    prefix = rng.randint(2, 50, (2, 3)).astype(np.int64)
    with dg.guard():
        m = _tiny_gpt()
        m.eval()
        g = m.generate(prefix, max_length=5,
                       decode_strategy="greedy_search")
        assert g.shape[0] == 2 and g.shape[1] <= 8
        np.testing.assert_array_equal(g[:, :3], prefix)
        # greedy is deterministic
        g2 = m.generate(prefix, max_length=5,
                        decode_strategy="greedy_search")
        np.testing.assert_array_equal(g, g2)
        s = m.generate(prefix, max_length=5, decode_strategy="sampling",
                       top_k=5, seed=3)
        assert s.shape[0] == 2
        b = m.generate(prefix, max_length=5,
                       decode_strategy="beam_search", num_beams=3)
        assert b.shape[0] == 2
        np.testing.assert_array_equal(b[:, :3], prefix)


def _seq_logprob(m, seq):
    """Sum log p(token_t | tokens_<t) under the model."""
    logits = np.asarray(m(paddle_tpu.to_tensor(seq[:, :-1])).numpy())
    lp = logits - np.log(np.exp(logits - logits.max(-1, keepdims=True))
                         .sum(-1, keepdims=True)) - \
        logits.max(-1, keepdims=True)
    tgt = seq[:, 1:]
    return np.take_along_axis(lp, tgt[..., None], -1)[..., 0].sum(-1)


def test_beam_width_one_is_greedy():
    """num_beams=1 must reproduce greedy exactly (the degenerate beam), and
    wider beams must return equal-or-better full-sequence log-prob when
    both run to the same untruncated length."""
    rng = np.random.RandomState(2)
    prefix = rng.randint(2, 50, (3, 2)).astype(np.int64)
    with dg.guard():
        m = _tiny_gpt()
        m.eval()
        L = 6
        g = m.generate(prefix, max_length=L,
                       decode_strategy="greedy_search")
        b1 = m.generate(prefix, max_length=L,
                        decode_strategy="beam_search", num_beams=1)
        n = min(g.shape[1], b1.shape[1])
        np.testing.assert_array_equal(g[:, :n], b1[:, :n])
        # wider beam: compare only when both emitted full length (beam
        # may legitimately prefer a short EOS path under raw scores)
        b4 = m.generate(prefix, max_length=L,
                        decode_strategy="beam_search", num_beams=4)
        if b4.shape[1] == g.shape[1] and \
                not (b4[:, -1] == 1).any() and not (g[:, -1] == 1).any():
            lp_g = _seq_logprob(m, g)
            lp_b = _seq_logprob(m, b4)
            assert (lp_b >= lp_g - 1e-4).all(), (lp_b, lp_g)


def test_transformer_beam_search_runs():
    from paddle_tpu.models import TransformerModel, TransformerConfig
    cfg = TransformerConfig(src_vocab_size=40, trg_vocab_size=40,
                            d_model=32, n_head=2, num_encoder_layers=1,
                            num_decoder_layers=1, d_inner_hid=64,
                            dropout=0.0, max_length=16)
    rng = np.random.RandomState(0)
    src = rng.randint(3, 40, (2, 6)).astype(np.int64)
    with dg.guard():
        model = TransformerModel(cfg)
        model.eval()
        out_g = model.beam_search(src, beam_size=1, max_len=6)
        out_b = model.beam_search(src, beam_size=3, max_len=6)
    assert out_g.shape[0] == 2 and out_b.shape[0] == 2
    assert out_b.shape[1] <= 6
    assert (out_b[:, 0] == cfg.bos_id).all()


def test_generate_guards():
    with dg.guard():
        m = _tiny_gpt()
        with pytest.raises(ValueError, match="decode_strategy"):
            m.generate(np.zeros((1, 2), np.int64),
                       decode_strategy="top_k_sampling")
        with pytest.raises(ValueError, match="max_position"):
            m.generate(np.zeros((1, 60), np.int64), max_length=10)

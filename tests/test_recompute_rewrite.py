"""Recompute (activation checkpointing) rewrite: numerical equivalence
and composition.

The rewrite (static/recompute_rewrite.py) replays forward segments from
checkpoint vars during backward, with segment inputs routed through an
`optimization_barrier` op so XLA cannot CSE the replay back into the
original forward (which would silently keep every activation alive and
defeat the memory saving).  These tests pin the contract the
memory-for-throughput tier rests on:

  * forward loss AND parameter gradients are numerically equal with vs.
    without the rewrite — for a MANUAL checkpoint list and for
    FLAGS_recompute auto selection;
  * the rewritten block actually contains optimization_barrier ops;
  * the rewrite composes with AMP's cast-inserting program rewrite and
    with Executor.run_steps' scanned megastep (donated state, one scan);
  * FLAGS_recompute=auto only rewrites when the HBM estimator predicts
    the PADDLE_TPU_HBM_BYTES budget is exceeded.
"""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.core.flags import set_flags
from paddle_tpu.core.program import _reset_unique_names
from paddle_tpu.static import layers, nets


VOCAB, SEQ, HIDDEN, HEADS, LAYERS = 128, 16, 32, 2, 2
BATCH = 4


@pytest.fixture(autouse=True)
def _clean_flags():
    yield
    set_flags({"recompute": "", "hbm_assume_batch": 0})


def build_tiny_transformer(use_amp=False, lr=0.0):
    """bert-tiny-style MLM step; lr=0 keeps params frozen so grads can
    be fetched and compared across program variants."""
    _reset_unique_names()
    from paddle_tpu import amp
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ids = layers.data("ids", [-1, SEQ], dtype="int64")
        labels = layers.data("labels", [-1, SEQ, 1], dtype="int64")
        h = layers.embedding(ids, size=[VOCAB, HIDDEN])
        h = layers.layer_norm(h, begin_norm_axis=2)
        boundaries = []
        for _ in range(LAYERS):
            boundaries.append(h)
            q = layers.fc(h, HIDDEN, num_flatten_dims=2)
            k = layers.fc(h, HIDDEN, num_flatten_dims=2)
            v = layers.fc(h, HIDDEN, num_flatten_dims=2)
            ctx = nets.scaled_dot_product_attention(q, k, v,
                                                    num_heads=HEADS)
            h = layers.layer_norm(layers.elementwise_add(h, ctx),
                                  begin_norm_axis=2)
            ffn = layers.fc(h, HIDDEN * 2, num_flatten_dims=2, act="gelu")
            h = layers.layer_norm(
                layers.elementwise_add(
                    h, layers.fc(ffn, HIDDEN, num_flatten_dims=2)),
                begin_norm_axis=2)
        logits = layers.fc(h, VOCAB, num_flatten_dims=2)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, labels))
        opt = static.SGD(learning_rate=lr)
        if use_amp:
            opt = amp.decorate(opt, init_loss_scaling=1.0,
                               use_dynamic_loss_scaling=False,
                               dest_dtype="bfloat16")
        _, params_grads = opt.minimize(loss)
    return main, startup, loss, params_grads, boundaries


def _feed():
    rng = np.random.RandomState(0)
    return {"ids": rng.randint(0, VOCAB, (BATCH, SEQ)).astype(np.int32),
            "labels": rng.randint(0, VOCAB,
                                  (BATCH, SEQ, 1)).astype(np.int32)}


def _run_loss_and_grads(main, startup, loss, params_grads):
    exe, scope = static.Executor(), static.Scope()
    fetch = [loss] + [g for _, g in params_grads]
    with static.scope_guard(scope):
        exe.run(startup)
        out = exe.run(main, feed=_feed(), fetch_list=fetch)
    grads = {p.name: np.asarray(g) for (p, _), g
             in zip(params_grads, out[1:])}
    return float(np.asarray(out[0])), grads


def _barrier_count(program):
    return sum(1 for op in program.global_block().ops
               if op.type == "optimization_barrier")


_PLAIN_REF = {}


def _plain_reference():
    """Loss+grads of the UNREWRITTEN program, computed once per module —
    three tests compare against it and each whole-block jit compile is
    the expensive part of this file."""
    if not _PLAIN_REF:
        main_p, start_p, loss_p, pg_p, _ = build_tiny_transformer()
        loss0, grads0 = _run_loss_and_grads(main_p, start_p, loss_p, pg_p)
        assert _barrier_count(main_p) == 0
        _PLAIN_REF["ref"] = (loss0, grads0)
    return _PLAIN_REF["ref"]


def test_manual_checkpoints_match_plain_backward():
    loss0, grads0 = _plain_reference()

    # manual checkpoints through RecomputeOptimizer (fluid contract)
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ids = layers.data("ids", [-1, SEQ], dtype="int64")
        labels = layers.data("labels", [-1, SEQ, 1], dtype="int64")
        h = layers.embedding(ids, size=[VOCAB, HIDDEN])
        h = layers.layer_norm(h, begin_norm_axis=2)
        ckpts = []
        for _ in range(LAYERS):
            ckpts.append(h)
            q = layers.fc(h, HIDDEN, num_flatten_dims=2)
            k = layers.fc(h, HIDDEN, num_flatten_dims=2)
            v = layers.fc(h, HIDDEN, num_flatten_dims=2)
            ctx = nets.scaled_dot_product_attention(q, k, v,
                                                    num_heads=HEADS)
            h = layers.layer_norm(layers.elementwise_add(h, ctx),
                                  begin_norm_axis=2)
            ffn = layers.fc(h, HIDDEN * 2, num_flatten_dims=2, act="gelu")
            h = layers.layer_norm(
                layers.elementwise_add(
                    h, layers.fc(ffn, HIDDEN, num_flatten_dims=2)),
                begin_norm_axis=2)
        logits = layers.fc(h, VOCAB, num_flatten_dims=2)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, labels))
        opt = static.RecomputeOptimizer(static.SGD(learning_rate=0.0))
        opt._set_checkpoints(ckpts)
        _, pg = opt.minimize(loss)
    assert _barrier_count(main) >= 1, \
        "rewritten block lost its optimization_barrier"
    loss1, grads1 = _run_loss_and_grads(main, startup, loss, pg)

    np.testing.assert_allclose(loss1, loss0, rtol=1e-5, atol=1e-6)
    assert set(grads1) == set(grads0)
    for name in grads0:
        np.testing.assert_allclose(grads1[name], grads0[name],
                                   rtol=1e-4, atol=1e-6, err_msg=name)


def test_auto_checkpoint_selection_matches_plain_backward():
    loss0, grads0 = _plain_reference()

    set_flags({"recompute": "always"})
    main, startup, loss, pg, _ = build_tiny_transformer()
    set_flags({"recompute": ""})
    assert _barrier_count(main) >= 1
    loss1, grads1 = _run_loss_and_grads(main, startup, loss, pg)
    np.testing.assert_allclose(loss1, loss0, rtol=1e-5, atol=1e-6)
    for name in grads0:
        np.testing.assert_allclose(grads1[name], grads0[name],
                                   rtol=1e-4, atol=1e-6, err_msg=name)


def test_auto_mode_gates_on_estimated_budget(monkeypatch):
    from paddle_tpu.static.memory_analysis import HBM_BUDGET_ENV
    # generous budget: no rewrite
    monkeypatch.setenv(HBM_BUDGET_ENV, str(1 << 40))
    set_flags({"recompute": "auto", "hbm_assume_batch": BATCH})
    main_big, *_ = build_tiny_transformer()
    assert _barrier_count(main_big) == 0
    # starvation budget (below the tiny model's ~450 kB walked peak):
    # rewrite engages
    monkeypatch.setenv(HBM_BUDGET_ENV, str(100_000))
    main_small, *_ = build_tiny_transformer()
    assert _barrier_count(main_small) >= 1


def test_estimator_says_remat_is_smaller():
    main_p, *_ = build_tiny_transformer()
    set_flags({"recompute": "always"})
    main_r, *_ = build_tiny_transformer()
    set_flags({"recompute": ""})
    plain = static.estimate_peak_bytes(main_p, batch=BATCH)
    remat = static.estimate_peak_bytes(main_r, batch=BATCH)
    assert remat < plain, (remat, plain)


def test_rewrite_composes_with_amp():
    main_p, start_p, loss_p, pg_p, _ = build_tiny_transformer(use_amp=True)
    loss0, grads0 = _run_loss_and_grads(main_p, start_p, loss_p, pg_p)

    set_flags({"recompute": "always"})
    main, startup, loss, pg, _ = build_tiny_transformer(use_amp=True)
    set_flags({"recompute": ""})
    assert _barrier_count(main) >= 1
    # AMP inserted cast ops in the forward; the replayed segments carry
    # them too — same bf16 compute path both ways
    assert any(op.type == "cast" for op in main.global_block().ops)
    loss1, grads1 = _run_loss_and_grads(main, startup, loss, pg)
    np.testing.assert_allclose(loss1, loss0, rtol=1e-3, atol=1e-4)
    for name in grads0:
        np.testing.assert_allclose(grads1[name], grads0[name],
                                   rtol=2e-2, atol=2e-3, err_msg=name)


def test_rewrite_composes_with_run_steps():
    """Remat program under the scanned megastep: K steps in one dispatch
    match K sequential run() dispatches of the SAME program."""
    K = 3
    set_flags({"recompute": "always"})
    main, startup, loss, _, _ = build_tiny_transformer(lr=0.05)
    set_flags({"recompute": ""})
    assert _barrier_count(main) >= 1

    rng = np.random.RandomState(1)
    ids = rng.randint(0, VOCAB, (K, BATCH, SEQ)).astype(np.int32)
    labels = rng.randint(0, VOCAB, (K, BATCH, SEQ, 1)).astype(np.int32)

    exe, sc = static.Executor(), static.Scope()
    seq_losses = []
    with static.scope_guard(sc):
        exe.run(startup)
        for i in range(K):
            (lv,) = exe.run(main, feed={"ids": ids[i],
                                        "labels": labels[i]},
                            fetch_list=[loss])
            seq_losses.append(float(lv))

    set_flags({"recompute": "always"})
    main2, startup2, loss2, _, _ = build_tiny_transformer(lr=0.05)
    set_flags({"recompute": ""})
    exe2, sc2 = static.Executor(), static.Scope()
    with static.scope_guard(sc2):
        exe2.run(startup2)
        (stacked,) = exe2.run_steps(main2,
                                    feed={"ids": ids, "labels": labels},
                                    fetch_list=[loss2])
    np.testing.assert_allclose(stacked, seq_losses, rtol=1e-4, atol=1e-5)

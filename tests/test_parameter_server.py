"""Parameter-server tier tests — in-process loopback, the reference's own
pattern (operators/distributed/rpc_server_test.cc, collective_server_test.cc
run client+server in one process)."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers


def _start_server(num_trainers=1):
    from paddle_tpu.distributed.ps.kv_server import KVServer
    srv = KVServer("127.0.0.1:0", num_trainers=num_trainers)
    srv.serve_in_thread()
    return srv


def test_kv_roundtrip_and_modes():
    from paddle_tpu.distributed.ps.kv_server import KVClient
    srv = _start_server()
    try:
        c = KVClient([srv.endpoint])
        c.wait_server_ready()
        w = np.arange(6, dtype=np.float32).reshape(2, 3)
        c.init_param("w", w)
        c.init_param("w", w * 100)  # first writer wins
        np.testing.assert_allclose(c.pull("w"), w)
        # async push: applied immediately, p -= lr*g
        g = np.ones_like(w)
        c.push_grad("w", g, lr=0.5, sync=False)
        np.testing.assert_allclose(c.pull("w"), w - 0.5)
        # sync push with 1 trainer applies directly
        c.push_grad("w", g, lr=0.5, sync=True)
        np.testing.assert_allclose(c.pull("w"), w - 1.0)
        # geo delta
        c.push_delta("w", np.full_like(w, 0.25))
        np.testing.assert_allclose(c.pull("w"), w - 0.75)
        c.barrier()
        c.close()
    finally:
        srv.stop()


def test_kv_sync_two_trainers():
    """Two client threads push; server applies the MEAN once both arrive."""
    from paddle_tpu.distributed.ps.kv_server import KVClient
    srv = _start_server(num_trainers=2)
    try:
        c0 = KVClient([srv.endpoint])
        c0.init_param("w", np.zeros(4, np.float32))
        results = []

        def trainer(gval):
            c = KVClient([srv.endpoint])
            c.push_grad("w", np.full(4, gval, np.float32), lr=1.0,
                        sync=True)
            results.append(gval)
            c.close()

        t0 = threading.Thread(target=trainer, args=(1.0,))
        t1 = threading.Thread(target=trainer, args=(3.0,))
        t0.start(); t1.start()
        t0.join(10); t1.join(10)
        assert len(results) == 2
        # mean grad = 2.0, lr 1.0 → w = -2
        np.testing.assert_allclose(c0.pull("w"), -2.0 * np.ones(4))
        c0.close()
    finally:
        srv.stop()


def _linreg():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def _transpile_and_train(cfg, endpoints, iters=25):
    """Shared scaffold for the PS e2e tests: build linreg, transpile with
    `cfg` against `endpoints`, train `iters` steps on a fixed batch;
    returns (losses, main_program)."""
    from paddle_tpu.distributed.ps.ps_optimizer import DistributeTranspiler
    main, startup, loss = _linreg()
    t = DistributeTranspiler(cfg)
    t.transpile(trainer_id=0, program=main, pservers=endpoints, trainers=1,
                startup_program=startup)
    prog = t.get_trainer_program()
    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(0)
    xb = rng.rand(16, 8).astype(np.float32)
    yb = xb.sum(1, keepdims=True).astype(np.float32)
    with static.scope_guard(scope):
        exe.run(startup)
        losses = [float(np.asarray(exe.run(prog, feed={"x": xb, "y": yb},
                                           fetch_list=[loss])[0]))
                  for _ in range(iters)]
    return losses, main


@pytest.mark.parametrize("sync_mode", [True, False])
def test_ps_transpiler_end_to_end(sync_mode):
    from paddle_tpu.distributed.ps.ps_optimizer import (
        DistributeTranspiler, DistributeTranspilerConfig)
    srv = _start_server(num_trainers=1)
    try:
        main, startup, loss = _linreg()
        cfg = DistributeTranspilerConfig()
        cfg.sync_mode = sync_mode
        t = DistributeTranspiler(cfg)
        t.transpile(trainer_id=0, program=main, pservers=srv.endpoint,
                    trainers=1)
        trainer_prog = t.get_trainer_program()
        exe = static.Executor()
        scope = static.Scope()
        rng = np.random.RandomState(0)
        xb = rng.rand(16, 8).astype(np.float32)
        yb = xb.sum(1, keepdims=True).astype(np.float32)
        with static.scope_guard(scope):
            exe.run(startup)
            losses = []
            for _ in range(25):
                (lv,) = exe.run(trainer_prog, feed={"x": xb, "y": yb},
                                fetch_list=[loss])
                losses.append(float(lv))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    finally:
        srv.stop()


def test_ps_geo_mode():
    from paddle_tpu.distributed.ps.ps_optimizer import (
        DistributeTranspiler, DistributeTranspilerConfig)
    srv = _start_server(num_trainers=1)
    try:
        main, startup, loss = _linreg()
        cfg = DistributeTranspilerConfig()
        cfg.geo_sgd_mode = True
        cfg.geo_sgd_need_push_nums = 5
        t = DistributeTranspiler(cfg)
        t.transpile(trainer_id=0, program=main, pservers=srv.endpoint,
                    trainers=1)
        trainer_prog = t.get_trainer_program()
        exe = static.Executor()
        scope = static.Scope()
        rng = np.random.RandomState(0)
        xb = rng.rand(16, 8).astype(np.float32)
        yb = xb.sum(1, keepdims=True).astype(np.float32)
        with static.scope_guard(scope):
            exe.run(startup)
            losses = []
            for _ in range(20):
                (lv,) = exe.run(trainer_prog, feed={"x": xb, "y": yb},
                                fetch_list=[loss])
                losses.append(float(lv))
            # after a sync point the server holds the merged params
            wname = main.all_parameters()[0].name
            assert srv.get(wname) is not None
        assert losses[-1] < losses[0] * 0.5
    finally:
        srv.stop()


def test_fleet_ps_mode(monkeypatch):
    """fleet.init PS flow: role maker env + strategy.a_sync."""
    srv = _start_server(num_trainers=1)
    try:
        monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
        monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
        monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", srv.endpoint)
        from paddle_tpu.distributed.fleet.base.fleet_base import Fleet
        import paddle_tpu.distributed as dist
        f = Fleet()
        f.init(is_collective=False)
        main, startup, loss_prog = static.Program(), static.Program(), None
        with static.program_guard(main, startup):
            x = layers.data("x", [-1, 8])
            y = layers.data("y", [-1, 1])
            pred = layers.fc(x, size=1)
            loss = layers.mean(
                layers.square(layers.elementwise_sub(pred, y)))
            strategy = dist.fleet.DistributedStrategy()
            strategy.a_sync = True
            f.distributed_optimizer(static.SGD(learning_rate=0.05),
                                    strategy)
            f.minimize(loss)
        assert "ParameterServerOptimizer" in f.applied_meta_list()
        exe = static.Executor()
        scope = static.Scope()
        rng = np.random.RandomState(1)
        xb = rng.rand(16, 8).astype(np.float32)
        yb = xb.sum(1, keepdims=True).astype(np.float32)
        with static.scope_guard(scope):
            exe.run(startup)
            l0 = None
            for _ in range(20):
                (lv,) = exe.run(f.main_program, feed={"x": xb, "y": yb},
                                fetch_list=[loss])
                l0 = l0 if l0 is not None else float(lv)
        assert float(lv) < l0 * 0.6
    finally:
        srv.stop()


def test_sync_push_timeout_withdraws_pending_and_reports():
    """A sync-push waiter that times out must (a) surface a TimeoutError to
    the client instead of a dropped connection and (b) withdraw its gradient
    so the next complete round's mean is not polluted by the stale grad."""
    from paddle_tpu.distributed.ps.kv_server import KVServer, KVClient
    srv = KVServer("127.0.0.1:0", num_trainers=2, sync_timeout=0.4)
    srv.serve_in_thread()
    try:
        c = KVClient([srv.endpoint])
        c.wait_server_ready()
        c.init_param("w", np.zeros(2, dtype=np.float32))
        # only 1 of 2 trainers pushes -> timeout, surfaced as TimeoutError
        with pytest.raises(TimeoutError):
            c.push_grad("w", np.full(2, 100.0, np.float32), lr=1.0,
                        sync=True)
        # stale grad must be withdrawn: a fresh complete round of two
        # pushes averages only the fresh grads
        done = []

        def other():
            c2 = KVClient([srv.endpoint])
            c2.push_grad("w", np.ones(2, np.float32), lr=1.0, sync=True)
            done.append(1)

        t = threading.Thread(target=other, daemon=True)
        t.start()
        c.push_grad("w", np.ones(2, np.float32), lr=1.0, sync=True)
        t.join(5)
        assert done
        # w = 0 - 1.0 * mean([1, 1]) = -1 (not polluted by the 100s)
        np.testing.assert_allclose(c.pull("w"), -np.ones(2), atol=1e-6)
    finally:
        srv.stop()


@pytest.mark.parametrize("sync_mode", [True, False])
def test_ps_transpiler_graph_ops(sync_mode):
    """C8 parity: the transpiled trainer program carries send →
    fetch_barrier → recv GRAPH OPS (reference distributed_ops/send_op.cc);
    exe.run of the plain Program is the whole PS step."""
    from paddle_tpu.distributed.ps.ps_optimizer import (
        DistributeTranspiler, DistributeTranspilerConfig)
    srv = _start_server(num_trainers=1)
    try:
        main, startup, loss = _linreg()
        cfg = DistributeTranspilerConfig()
        cfg.sync_mode = sync_mode
        cfg.use_graph_ops = True
        t = DistributeTranspiler(cfg)
        t.transpile(trainer_id=0, program=main, pservers=srv.endpoint,
                    trainers=1, startup_program=startup)
        trainer_prog = t.get_trainer_program()
        from paddle_tpu.core.program import Program
        assert isinstance(trainer_prog, Program)
        types = [op.type for op in trainer_prog.global_block().ops]
        assert "send" in types and "recv" in types and \
            "fetch_barrier" in types
        assert types.index("send") < types.index("fetch_barrier") < \
            types.index("recv")

        exe = static.Executor()
        scope = static.Scope()
        rng = np.random.RandomState(0)
        xb = rng.rand(16, 8).astype(np.float32)
        yb = xb.sum(1, keepdims=True).astype(np.float32)
        with static.scope_guard(scope):
            exe.run(startup)   # includes the init-mode send
            losses = []
            for _ in range(25):
                (lv,) = exe.run(trainer_prog, feed={"x": xb, "y": yb},
                                fetch_list=[loss])
                losses.append(float(np.asarray(lv)))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    finally:
        srv.stop()
        from paddle_tpu.ops.kernels.distributed_ops import _reset_clients
        _reset_clients()


def test_heartbeat_monitor_shrinks_sync_fanin():
    """heart_beat_monitor.h parity: a trainer that stops heartbeating is
    dropped from the sync fanin, so the survivor's push completes instead
    of hanging until sync_timeout."""
    from paddle_tpu.distributed.ps.kv_server import KVServer, KVClient
    srv = KVServer("127.0.0.1:0", num_trainers=2, sync_timeout=30.0,
                   heartbeat_timeout=1.5)
    srv.serve_in_thread()
    try:
        alive = KVClient([srv.endpoint])
        dead = KVClient([srv.endpoint])
        alive.wait_server_ready()
        alive.start_heartbeat(0, interval=0.3)
        dead.start_heartbeat(1, interval=0.3)
        alive.init_param("w", np.ones(4, np.float32))
        time.sleep(0.6)           # both registered as alive
        dead.stop_heartbeat()     # trainer 1 "dies"
        t0 = time.time()
        alive.push_grad("w", np.ones(4, np.float32), lr=0.5, sync=True)
        dt = time.time() - t0
        # completed once the dead trainer aged out (~1.5s), well before
        # the 30s sync timeout — and with the survivor's grad alone
        assert dt < 10, dt
        np.testing.assert_allclose(alive.pull("w"),
                                   np.full(4, 0.5, np.float32))
        alive.close()
        dead.close()
    finally:
        srv.stop()


def test_heartbeat_absent_keeps_configured_fanin():
    # nobody heartbeats -> classic behavior: both pushes required
    from paddle_tpu.distributed.ps.kv_server import KVServer, KVClient
    import threading as th
    srv = KVServer("127.0.0.1:0", num_trainers=2, sync_timeout=15.0)
    srv.serve_in_thread()
    try:
        c0, c1 = KVClient([srv.endpoint]), KVClient([srv.endpoint])
        c0.wait_server_ready()
        c0.init_param("w", np.zeros(2, np.float32))
        done = []

        def push(c):
            c.push_grad("w", np.ones(2, np.float32), lr=1.0, sync=True)
            done.append(1)

        t0 = th.Thread(target=push, args=(c0,))
        t0.start()
        time.sleep(0.5)
        assert not done          # still waiting for trainer 2
        push(c1)
        t0.join(timeout=10)
        assert len(done) == 2
        np.testing.assert_allclose(c0.pull("w"),
                                   np.full(2, -1.0, np.float32))
        c0.close(); c1.close()
    finally:
        srv.stop()


def test_multi_pserver_sharding_end_to_end():
    """Params shard across TWO pservers (crc32 round-robin,
    DistributeTranspiler VarBlock analog); training works with both and
    each server holds only its shard of the keys."""
    from paddle_tpu.distributed.ps.ps_optimizer import (
        DistributeTranspiler, DistributeTranspilerConfig)
    srv_a = _start_server(num_trainers=1)
    srv_b = _start_server(num_trainers=1)
    try:
        cfg = DistributeTranspilerConfig()
        cfg.use_graph_ops = True
        losses, main = _transpile_and_train(
            cfg, f"{srv_a.endpoint},{srv_b.endpoint}")
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        # every param lives on exactly one server, and both got some
        # (with >1 param the crc32 split puts w and b apart or together —
        # assert disjoint union covers all params)
        params = [p.name for p, _ in main._ps_params_grads]
        held_a = {n for n in params if srv_a.get(n) is not None}
        held_b = {n for n in params if srv_b.get(n) is not None}
        assert held_a | held_b == set(params)
        assert not (held_a & held_b)
    finally:
        srv_a.stop()
        srv_b.stop()
        from paddle_tpu.ops.kernels.distributed_ops import _reset_clients
        _reset_clients()


def test_distributed_lookup_table_two_pservers():
    """VERDICT r2 item 9: sparse embedding row-sharded over TWO pservers —
    forward pulls only touched rows (distributed_lookup_table), backward
    pushes SelectedRows grads (sparse send, server-side row SGD), the
    dense tail keeps the ordinary send/recv round, and the loss falls."""
    from paddle_tpu.distributed.ps.kv_server import KVServer
    from paddle_tpu.distributed.ps.ps_optimizer import (
        DistributeTranspiler, DistributeTranspilerConfig)

    srv0 = KVServer("127.0.0.1:0", num_trainers=1)
    srv1 = KVServer("127.0.0.1:0", num_trainers=1)
    srv0.serve_in_thread()
    srv1.serve_in_thread()
    V, D = 20, 8
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            ids = layers.data("ids", [-1, 4], dtype="int64")
            y = layers.data("y", [-1, 1])
            emb = layers.embedding(ids, size=[V, D], is_sparse=True,
                                   is_distributed=True,
                                   param_attr=static.ParamAttr(
                                       name="dist_emb"))
            flat = layers.reshape(emb, [-1, 4 * D])
            pred = layers.fc(flat, 1)
            loss = layers.mean(layers.square(pred - y))
            static.SGD(learning_rate=0.1).minimize(loss)

        cfg = DistributeTranspilerConfig()
        cfg.use_graph_ops = True
        cfg.sync_mode = True
        t = DistributeTranspiler(cfg)
        eps = f"{srv0.endpoint},{srv1.endpoint}"
        t.transpile(trainer_id=0, program=main, pservers=eps, trainers=1,
                    startup_program=startup)
        prog = t.get_trainer_program()
        types = [op.type for op in prog.global_block().ops]
        assert "distributed_lookup_table" in types
        assert "lookup_table_v2" not in types
        sparse_sends = [op for op in prog.global_block().ops
                        if op.type == "send"
                        and op.attrs.get("mode") == "sparse_grad"]
        assert len(sparse_sends) == 1
        assert sparse_sends[0].attrs["send_varnames"] == ["dist_emb"]

        exe = static.Executor()
        scope = static.Scope()
        rng = np.random.RandomState(0)
        idb = rng.randint(0, V, (16, 4)).astype(np.int64)
        yb = (idb.sum(1, keepdims=True) / (4.0 * V)).astype(np.float32)
        with static.scope_guard(scope):
            exe.run(startup)
            # the table is sharded: each server holds V/2 rows, neither
            # holds the whole table
            assert srv0.get("dist_emb").shape == (V // 2, D)
            assert srv1.get("dist_emb").shape == (V // 2, D)
            losses = []
            for _ in range(30):
                (lv,) = exe.run(prog, feed={"ids": idb, "y": yb},
                                fetch_list=[loss])
                losses.append(float(np.asarray(lv)))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
        # server-side rows actually moved (sparse SGD applied)
        moved0 = srv0.get("dist_emb")
        assert np.abs(moved0).sum() > 0
    finally:
        srv0.stop()
        srv1.stop()
        from paddle_tpu.ops.kernels.distributed_ops import _reset_clients
        _reset_clients()


# ---------------------------------------------------------------------------
# fault tolerance: client retry/backoff + pserver restart (VERDICT r3 #5)
# ---------------------------------------------------------------------------

def test_kv_client_retries_through_server_restart():
    """Kill the pserver mid-session and restart it on the same port with
    its store restored (the auto-checkpoint resume contract): the
    client's next call must reconnect and succeed instead of dying on
    the first dropped connection (grpc_client.h FLAGS_rpc_deadline +
    retry parity)."""
    from paddle_tpu.distributed.ps.kv_server import KVServer, KVClient
    srv = KVServer("127.0.0.1:0", num_trainers=1)
    srv.serve_in_thread()
    port = int(srv.endpoint.rsplit(":", 1)[1])
    c = KVClient([srv.endpoint], sock_timeout=5.0, rpc_deadline=20.0)
    try:
        c.wait_server_ready()
        w = np.arange(8, dtype=np.float32).reshape(2, 4)
        c.init_param("w", w)
        np.testing.assert_allclose(c.pull("w"), w)
        snapshot = {k: v.copy() for k, v in srv._store.items()}

        # hard-kill the server, then restart it shortly after on the
        # SAME port with the snapshot restored, while the client is
        # already retrying its pull
        srv.stop()

        def restart():
            time.sleep(0.8)
            srv2 = KVServer(f"127.0.0.1:{port}", num_trainers=1)
            srv2._store.update(snapshot)
            srv2.serve_in_thread()
            restart.srv = srv2

        t = threading.Thread(target=restart)
        t.start()
        got = c.pull("w")  # first attempt hits a dead port -> retries
        t.join()
        np.testing.assert_allclose(got, w)
        # pushes also survive
        c.push_grad("w", np.ones_like(w), lr=0.5, sync=False)
        np.testing.assert_allclose(c.pull("w"), w - 0.5)
    finally:
        c.close()
        try:
            restart.srv.stop()
        except Exception:
            pass


def test_kv_client_deadline_gives_typed_error():
    """With no server at all, the retry loop must fail with a clear
    ConnectionError once the deadline budget is spent - not hang."""
    from paddle_tpu.distributed.ps.kv_server import KVClient
    c = KVClient(["127.0.0.1:1"], sock_timeout=0.3, rpc_deadline=1.0,
                 max_retries=3)
    t0 = time.time()
    with pytest.raises(ConnectionError, match="failed after"):
        c.pull("nope")
    assert time.time() - t0 < 10.0


def test_kv_push_rows_missing_table_errors():
    """ADVICE r3: a sparse push to a table the server does not hold must
    reply OP_ERROR (surfaced as TimeoutError) instead of silently
    dropping the gradient."""
    from paddle_tpu.distributed.ps.kv_server import KVClient
    srv = _start_server()
    try:
        c = KVClient([srv.endpoint], rpc_deadline=5.0)
        c.wait_server_ready()
        with pytest.raises((TimeoutError, KeyError),
                           match="not on this server"):
            c.push_sparse("ghost_table", np.array([0, 1]),
                          np.ones((2, 4), np.float32), lr=0.1)
        c.close()
    finally:
        srv.stop()


def test_sync_sparse_push_scaled_by_trainer_count():
    """ADVICE r3 (medium): in sync mode the sparse row update must step
    by the trainer-average, not N independent full-lr steps."""
    from paddle_tpu.distributed.ps.kv_server import KVClient
    srv = _start_server(num_trainers=2)
    try:
        c = KVClient([srv.endpoint], rpc_deadline=5.0)
        c.wait_server_ready()
        tab = np.zeros((4, 2), np.float32)
        c.init_sparse_table("tab", tab)
        g = np.ones((2, 2), np.float32)
        # two trainers push the same rows with grad_scale = 1/2
        c.push_sparse("tab", np.array([0, 1]), g, lr=1.0, grad_scale=0.5)
        c.push_sparse("tab", np.array([0, 1]), g, lr=1.0, grad_scale=0.5)
        got = c.pull_sparse("tab", np.array([0, 1]))
        # average of two unit grads at lr 1 -> -1.0, not -2.0
        np.testing.assert_allclose(got, -np.ones((2, 2)), atol=1e-6)
        c.close()
    finally:
        srv.stop()


def test_training_survives_pserver_restart():
    """End-to-end: transpiled trainer keeps stepping while the pserver
    is killed and resurrected with its store intact (simulates the
    auto-checkpoint recovery path)."""
    from paddle_tpu.distributed.ps.kv_server import KVServer
    from paddle_tpu.distributed.ps.ps_optimizer import (
        DistributeTranspiler, DistributeTranspilerConfig)
    from paddle_tpu.ops.kernels.distributed_ops import _reset_clients

    srv = KVServer("127.0.0.1:0", num_trainers=1)
    srv.serve_in_thread()
    port = int(srv.endpoint.rsplit(":", 1)[1])
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = layers.data("x", [-1, 4])
            y = layers.data("y", [-1, 1])
            pred = layers.fc(x, 1)
            loss = layers.mean(layers.square(pred - y))
            static.SGD(learning_rate=0.1).minimize(loss)
        cfg = DistributeTranspilerConfig()
        cfg.use_graph_ops = True
        cfg.sync_mode = False
        t = DistributeTranspiler(cfg)
        t.transpile(trainer_id=0, program=main,
                    pservers=f"127.0.0.1:{port}", trainers=1,
                    startup_program=startup)
        prog = t.get_trainer_program()

        exe = static.Executor()
        scope = static.Scope()
        rng = np.random.RandomState(0)
        xb = rng.randn(16, 4).astype(np.float32)
        yb = (xb @ np.array([[1.0], [-2.0], [0.5], [3.0]],
                            np.float32)).astype(np.float32)
        with static.scope_guard(scope):
            exe.run(startup)
            losses = []
            for _ in range(5):
                (lv,) = exe.run(prog, feed={"x": xb, "y": yb},
                                fetch_list=[loss])
                losses.append(float(np.asarray(lv)))
            # kill + restart with state carried over
            snapshot = {k: v.copy() for k, v in srv._store.items()}
            srv.stop()
            time.sleep(0.3)
            srv2 = KVServer(f"127.0.0.1:{port}", num_trainers=1)
            srv2._store.update(snapshot)
            srv2.serve_in_thread()
            try:
                for _ in range(10):
                    (lv,) = exe.run(prog, feed={"x": xb, "y": yb},
                                    fetch_list=[loss])
                    losses.append(float(np.asarray(lv)))
            finally:
                srv2.stop()
        assert losses[-1] < losses[0] * 0.5, losses
    finally:
        _reset_clients()
        try:
            srv.stop()
        except Exception:
            pass

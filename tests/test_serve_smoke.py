"""Tier-1 serving gate (NOT marked slow — losing request coalescing or
retracing on coalesced batches is a serving regression that must fail
the suite, not wait for a perf round).

Drives tools/serve_smoke.py in-process: tiny fc model behind the HTTP
server with dynamic batching, pow2-bucket warmup, concurrent clients,
hard assertions that batches coalesced and nothing retraced."""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_serve_smoke_gate(tmp_path):
    import serve_smoke
    result = serve_smoke.run_smoke(clients=4, requests=6,
                                   model_dir=str(tmp_path))
    assert result["traces_after_warmup"] == 0, result
    assert result["coalesced_batches"] > 0, result
    assert result["value"] > 0, result
    assert result["p99_ms"] > 0, result

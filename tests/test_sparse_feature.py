"""Industrial sparse-feature op tests (VERDICT r3 missing #2) — OpTests
vs numpy for cvm/shuffle_batch/filter_by_instag/hash/pyramid_hash/
tdm_child/tdm_sampler, plus the CTR-shaped book test: sparse features ->
distributed embedding -> cvm -> fc -> auc training through the PS tier."""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers
from paddle_tpu.ops.registry import run_kernel, OpContext, get_op_info


def _run(op, ins, attrs, seed=11):
    import jax.numpy as jnp
    dev = {k: ([jnp.asarray(x) for x in v] if isinstance(v, list)
               else jnp.asarray(v)) for k, v in ins.items()}
    return run_kernel(op, dev, attrs, OpContext(seed=seed))


def test_registry_probe_sparse_feature_ops():
    ops = ["cvm", "shuffle_batch", "filter_by_instag", "hash",
           "pyramid_hash", "tdm_child", "tdm_sampler"]
    missing = [op for op in ops if get_op_info(op) is None]
    assert not missing, f"unregistered sparse feature ops: {missing}"


# ---------------------------------------------------------------------------
# cvm
# ---------------------------------------------------------------------------

def test_cvm_use_cvm_true_matches_numpy():
    x = np.array([[3.0, 1.0, 0.5, -0.2],
                  [0.0, 0.0, 2.0, 2.5]], np.float32)
    cvm_in = x[:, :2].copy()
    out = _run("cvm", {"X": x, "CVM": cvm_in}, {"use_cvm": True})
    y = np.asarray(out["Y"])
    exp_show = np.log(x[:, 0] + 1)
    exp_click = np.log(x[:, 1] + 1) - exp_show
    np.testing.assert_allclose(y[:, 0], exp_show, atol=1e-6)
    np.testing.assert_allclose(y[:, 1], exp_click, atol=1e-6)
    np.testing.assert_allclose(y[:, 2:], x[:, 2:], atol=1e-6)


def test_cvm_use_cvm_false_drops_counters():
    x = np.array([[3.0, 1.0, 0.5, -0.2]], np.float32)
    out = _run("cvm", {"X": x, "CVM": x[:, :2]}, {"use_cvm": False})
    np.testing.assert_allclose(np.asarray(out["Y"]), x[:, 2:])


def test_cvm_grad_feeds_counters_back():
    x = np.array([[3.0, 1.0, 0.5, -0.2]], np.float32)
    cvm_in = np.array([[7.0, 9.0]], np.float32)
    dy = np.ones((1, 4), np.float32) * 0.5
    out = _run("cvm_grad", {"X": x, "CVM": cvm_in, "Y@GRAD": dy},
               {"use_cvm": True})
    dx = np.asarray(out["X@GRAD"])
    # reference CvmGradComputeKernel: counter slots get the CVM values
    np.testing.assert_allclose(dx[0, :2], [7.0, 9.0])
    np.testing.assert_allclose(dx[0, 2:], [0.5, 0.5])


# ---------------------------------------------------------------------------
# shuffle_batch
# ---------------------------------------------------------------------------

def test_shuffle_batch_permutes_and_inverts():
    x = np.arange(20, dtype=np.float32).reshape(5, 4)
    out = _run("shuffle_batch", {"X": x}, {"startup_seed": 5, "op_uid": 3})
    got = np.asarray(out["Out"])
    perm = np.asarray(out["ShuffleIdx"])
    # out[perm[i]] = x[i]
    np.testing.assert_allclose(got[perm], x)
    # same content, shuffled rows
    assert sorted(got.sum(1).tolist()) == sorted(x.sum(1).tolist())
    # grad inverts the scatter
    g = _run("shuffle_batch_grad",
             {"ShuffleIdx": perm, "Out@GRAD": got},
             {"startup_seed": 5, "op_uid": 3})
    np.testing.assert_allclose(np.asarray(g["X@GRAD"]), x)


def test_shuffle_batch_seed_chained():
    x = np.zeros((4, 2), np.float32)
    out = _run("shuffle_batch", {"X": x},
               {"startup_seed": 1, "op_uid": 0})
    s1 = int(np.asarray(out["SeedOut"])[0])
    out2 = _run("shuffle_batch",
                {"X": x, "Seed": np.array([s1], np.int64)}, {"op_uid": 0})
    assert int(np.asarray(out2["SeedOut"])[0]) != s1


# ---------------------------------------------------------------------------
# filter_by_instag
# ---------------------------------------------------------------------------

def test_filter_by_instag_keeps_matching_rows():
    ins = np.arange(12, dtype=np.float32).reshape(3, 4)
    tags = np.array([[1, 2, -1], [3, -1, -1], [4, 5, -1]], np.int64)
    filt = np.array([2, 5], np.int64)
    out = _run("filter_by_instag",
               {"Ins": ins, "Ins_tag": tags, "Filter_tag": filt},
               {"out_val_if_empty": 0})
    got = np.asarray(out["Out"])
    lw = np.asarray(out["LossWeight"])[:, 0]
    np.testing.assert_allclose(lw, [1, 0, 1])
    np.testing.assert_allclose(got[0], ins[0])
    np.testing.assert_allclose(got[1], np.zeros(4))
    np.testing.assert_allclose(got[2], ins[2])
    # grad masks dropped rows
    g = _run("filter_by_instag_grad",
             {"Out@GRAD": np.ones_like(ins), "LossWeight": lw[:, None]},
             {})
    np.testing.assert_allclose(np.asarray(g["Ins@GRAD"])[1], np.zeros(4))


# ---------------------------------------------------------------------------
# hash
# ---------------------------------------------------------------------------

def test_hash_shape_deterministic_and_bounded():
    x = np.array([[1, 2], [3, 4], [1, 2]], np.int64)
    out = _run("hash", {"X": x}, {"mod_by": 1000, "num_hash": 4})
    h = np.asarray(out["Out"])
    assert h.shape == (3, 4, 1)
    assert (h >= 0).all() and (h < 1000).all()
    # same input tuple -> same hashes; different seeds -> different values
    np.testing.assert_array_equal(h[0], h[2])
    assert len(np.unique(h[0])) > 1
    # deterministic across runs
    h2 = np.asarray(_run("hash", {"X": x},
                         {"mod_by": 1000, "num_hash": 4})["Out"])
    np.testing.assert_array_equal(h, h2)


def test_hash_distribution_is_spread():
    rng = np.random.RandomState(0)
    x = rng.randint(0, 1 << 30, (512, 2)).astype(np.int64)
    h = np.asarray(_run("hash", {"X": x},
                        {"mod_by": 64, "num_hash": 1})["Out"])[:, 0, 0]
    counts = np.bincount(h, minlength=64)
    # roughly uniform: no bucket more than 4x the mean
    assert counts.max() < 4 * counts.mean()


# ---------------------------------------------------------------------------
# pyramid_hash
# ---------------------------------------------------------------------------

def test_pyramid_hash_shapes_and_padding():
    x = np.array([[1, 2, 0], [3, 4, 5]], np.int64)  # row0 has a pad
    space = 64
    rand_len = 4
    num_emb = 8
    w = np.random.RandomState(0).randn(space + rand_len) \
        .astype(np.float32)
    out = _run("pyramid_hash", {"X": x, "W": w},
               {"num_emb": num_emb, "space_len": space,
                "rand_len": rand_len, "pyramid_layer": 2})
    got = np.asarray(out["Out"])
    drop = np.asarray(out["DropPos"])
    # windows: layer1 -> 3, layer2 -> 2 => 5 rows
    assert got.shape == (2, 5, num_emb)
    # row0 window (2,0) and (2..3 with pad) are dead
    assert drop[0].tolist() == [0, 0, 1, 0, 1]
    assert (got[0, 2] == 0).all() and (got[0, 4] == 0).all()
    assert (got[1] != 0).any(axis=1).all()
    # embeddings are slices of W
    assert np.isin(np.round(got[1, 0], 5),
                   np.round(w, 5)).all()


def test_pyramid_hash_grad_scatters_to_w():
    import jax
    import jax.numpy as jnp
    x = np.array([[1, 2]], np.int64)
    space, rand_len, num_emb = 32, 4, 8
    w = np.ones(space + rand_len, np.float32)
    attrs = {"num_emb": num_emb, "space_len": space, "rand_len": rand_len,
             "pyramid_layer": 2, "lr": 1.0}
    dy = np.ones((1, 3, num_emb), np.float32)
    g = _run("pyramid_hash_grad",
             {"X": x, "W": w, "Out@GRAD": dy}, attrs)
    dw = np.asarray(g["W@GRAD"])
    # 3 windows x 2 chunks x rand_len elements of mass 1 scattered
    np.testing.assert_allclose(dw.sum(), 3 * num_emb, atol=1e-5)


# ---------------------------------------------------------------------------
# tdm_child / tdm_sampler
# ---------------------------------------------------------------------------

def _toy_tree():
    """7-node binary tree: 0 unused/pad; 1 root (layer0); 2,3 mid
    (layer1); 4,5,6 leaves (layer2, items 10,11,12).
    TreeInfo rows: (item_id, layer_id, ancestor, child0, child1)."""
    info = np.zeros((7, 5), np.int32)
    info[1] = [0, 0, 0, 2, 3]
    info[2] = [0, 1, 1, 4, 5]
    info[3] = [0, 1, 1, 6, 0]
    info[4] = [10, 2, 2, 0, 0]
    info[5] = [11, 2, 2, 0, 0]
    info[6] = [12, 2, 3, 0, 0]
    return info


def test_tdm_child_gathers_children():
    info = _toy_tree()
    x = np.array([[1], [2], [4], [0]], np.int32)
    out = _run("tdm_child", {"X": x, "TreeInfo": info}, {"child_nums": 2})
    child = np.asarray(out["Child"]).reshape(4, 2)
    mask = np.asarray(out["LeafMask"]).reshape(4, 2)
    assert child[0].tolist() == [2, 3]      # root -> mid nodes
    assert mask[0].tolist() == [0, 0]       # mid nodes are not items
    assert child[1].tolist() == [4, 5]
    assert mask[1].tolist() == [1, 1]       # leaves are items
    assert child[2].tolist() == [0, 0]      # leaf has no children
    assert child[3].tolist() == [0, 0]      # pad id


def test_tdm_sampler_labels_and_exclusion():
    # travel path per leaf item: layers (root-child, leaf)
    travel = np.array([[2, 4], [2, 5], [3, 6]], np.int32)
    layer = np.array([[2, 3, 0], [4, 5, 6]], np.int32)
    x = np.array([[0], [1], [2]], np.int32)
    out = _run("tdm_sampler",
               {"X": x, "Travel": travel, "Layer": layer},
               {"neg_samples_num_list": [1, 1],
                "layer_node_num_list": [2, 3],
                "output_positive": True})
    o = np.asarray(out["Out"])
    lbl = np.asarray(out["Labels"])
    msk = np.asarray(out["Mask"])
    assert o.shape == (3, 4)  # (1 pos + 1 neg) * 2 layers
    # positives at slots 0 and 2
    np.testing.assert_array_equal(o[:, 0], travel[:, 0])
    np.testing.assert_array_equal(o[:, 2], travel[:, 1])
    np.testing.assert_array_equal(lbl[:, 0], [1, 1, 1])
    np.testing.assert_array_equal(lbl[:, 1], [0, 0, 0])
    # negatives never equal the positive of their layer
    assert (o[:, 1] != o[:, 0]).all()
    assert (o[:, 3] != o[:, 2]).all()
    assert msk.min() == 1  # no padding rows here


def test_tdm_sampler_padding_path():
    travel = np.array([[2, 0]], np.int32)   # second layer is padding
    layer = np.array([[2, 3], [4, 5]], np.int32)
    x = np.array([[0]], np.int32)
    out = _run("tdm_sampler",
               {"X": x, "Travel": travel, "Layer": layer},
               {"neg_samples_num_list": [1, 1],
                "layer_node_num_list": [2, 2],
                "output_positive": True})
    o = np.asarray(out["Out"])[0]
    msk = np.asarray(out["Mask"])[0]
    assert msk[:2].tolist() == [1, 1]
    assert msk[2:].tolist() == [0, 0] and o[2:].tolist() == [0, 0]


# ---------------------------------------------------------------------------
# CTR book test: sparse slots -> distributed embedding -> cvm -> fc -> auc
# through the parameter-server tier (VERDICT done-criterion)
# ---------------------------------------------------------------------------

def test_ctr_book_through_ps_tier():
    from paddle_tpu.distributed.ps.kv_server import KVServer
    from paddle_tpu.distributed.ps.ps_optimizer import (
        DistributeTranspiler, DistributeTranspilerConfig)

    srv = KVServer("127.0.0.1:0", num_trainers=1)
    srv.serve_in_thread()
    V, D = 32, 8
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            slots = layers.data("slots", [-1, 3], dtype="int64")
            show_clk = layers.data("show_clk", [-1, 2], dtype="float32")
            label = layers.data("label", [-1, 1], dtype="int64")
            emb = layers.embedding(slots, size=[V, D], is_sparse=True,
                                   is_distributed=True,
                                   param_attr=static.ParamAttr(
                                       name="ctr_emb"))
            pooled = layers.reduce_sum(emb, dim=1)        # [B, D]
            feat = layers.concat([show_clk, pooled], axis=1)
            feat = layers.continuous_value_model(feat, show_clk,
                                                 use_cvm=True)
            fc1 = layers.fc(feat, 16, act="relu")
            pred = layers.fc(fc1, 2, act="softmax")
            auc_out = layers.auc(pred, label)[0]
            loss = layers.mean(layers.cross_entropy(pred, label))
            static.SGD(learning_rate=0.5).minimize(loss)

        cfg = DistributeTranspilerConfig()
        cfg.use_graph_ops = True
        cfg.sync_mode = True
        t = DistributeTranspiler(cfg)
        t.transpile(trainer_id=0, program=main, pservers=srv.endpoint,
                    trainers=1, startup_program=startup)
        prog = t.get_trainer_program()
        types = [op.type for op in prog.global_block().ops]
        assert "distributed_lookup_table" in types
        assert "cvm" in types

        exe = static.Executor()
        scope = static.Scope()
        rng = np.random.RandomState(0)
        B = 32
        slot_b = rng.randint(0, V, (B, 3)).astype(np.int64)
        # separable labels: click iff slot sum above median
        y = (slot_b.sum(1) > 1.5 * V).astype(np.int64)[:, None]
        sc = np.stack([np.full(B, 5.0), y[:, 0] * 3.0], axis=1) \
            .astype(np.float32)
        with static.scope_guard(scope):
            exe.run(startup)
            losses = []
            for _ in range(40):
                lv, av = exe.run(
                    prog, feed={"slots": slot_b, "show_clk": sc,
                                "label": y},
                    fetch_list=[loss, auc_out])
                losses.append(float(np.asarray(lv)))
            assert losses[-1] < losses[0] * 0.7, losses[::10]
            assert 0.5 <= float(np.asarray(av)) <= 1.0
    finally:
        srv.stop()

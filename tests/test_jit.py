"""paddle_tpu.jit tests: to_static tracing, whole-block jit execution,
grad bridging to the dygraph tape, save/load round-trip
(reference: fluid/tests/unittests/dygraph_to_static/, test_jit_save_load.py)."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.nn as nn
from paddle_tpu import jit
from paddle_tpu.jit import InputSpec


class SmallNet(nn.Layer):
    def __init__(self, din=4, dh=8):
        super().__init__()
        self.l1 = nn.Linear(din, dh)
        self.l2 = nn.Linear(dh, 1)

    def forward(self, x):
        return self.l2(paddle_tpu.nn.functional.relu(self.l1(x)))


def _x(b=3, d=4, seed=0):
    return paddle_tpu.to_tensor(
        np.random.RandomState(seed).rand(b, d).astype(np.float32))


def test_to_static_function_matches_eager():
    net = SmallNet()
    x = _x()
    eager = net(x).numpy()

    traced = jit.to_static(lambda t: net.forward(t))
    out = traced(x)
    np.testing.assert_allclose(out.numpy(), eager, rtol=1e-5, atol=1e-6)
    # second call hits the signature cache (no retrace)
    assert len(traced._cache) == 1
    out2 = traced(_x(seed=1))
    assert len(traced._cache) == 1


def test_to_static_layer_decorator():
    net = jit.to_static(SmallNet())
    x = _x()
    ref = SmallNet()
    # copy params so outputs are comparable
    for p_dst, p_src in zip(net.parameters(), ref.parameters()):
        p_src._value = p_dst._value
    np.testing.assert_allclose(net(x).numpy(), ref(x).numpy(),
                               rtol=1e-5, atol=1e-6)


def test_to_static_training_updates_params():
    """backward() through the traced computation must put grads on the
    eager Parameters and train to convergence (whole-block jit path)."""
    import paddle_tpu.optimizer as opt
    net = SmallNet()
    net.train()
    traced = jit.to_static(net)
    optimizer = opt.Adam(learning_rate=0.05,
                         parameters=net.parameters())
    rng = np.random.RandomState(0)
    xv = rng.rand(16, 4).astype(np.float32)
    yv = xv.sum(1, keepdims=True).astype(np.float32)
    x = paddle_tpu.to_tensor(xv)
    y = paddle_tpu.to_tensor(yv)
    first = None
    for i in range(80):
        pred = traced(x)
        loss = paddle_tpu.nn.functional.mse_loss(pred, y)
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
        if first is None:
            first = float(loss.numpy())
    last = float(loss.numpy())
    assert last < first * 0.05, (first, last)


def test_jit_save_load_roundtrip():
    net = SmallNet()
    net.eval()
    x = _x()
    ref = net(x).numpy()
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model")
        jit.save(net, path, input_spec=[InputSpec([-1, 4], "float32")])
        loaded = jit.load(path)
        loaded.eval()
        out = loaded(x)
        out = out[0] if isinstance(out, (list, tuple)) else out
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)


def test_jit_load_finetune():
    """Loaded TranslatedLayer parameters are trainable."""
    import paddle_tpu.optimizer as opt
    net = SmallNet()
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model")
        jit.save(net, path, input_spec=[InputSpec([-1, 4], "float32")])
        loaded = jit.load(path)
    loaded.train()
    params = loaded.parameters()
    assert params, "loaded layer exposes no trainable parameters"
    optimizer = opt.Adam(learning_rate=0.05, parameters=params)
    rng = np.random.RandomState(1)
    xv = rng.rand(16, 4).astype(np.float32)
    yv = (2 * xv.sum(1, keepdims=True)).astype(np.float32)
    x = paddle_tpu.to_tensor(xv)
    y = paddle_tpu.to_tensor(yv)
    first = last = None
    for i in range(60):
        out = loaded(x)
        out = out[0] if isinstance(out, (list, tuple)) else out
        loss = paddle_tpu.nn.functional.mse_loss(out, y)
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
        if first is None:
            first = float(loss.numpy())
        last = float(loss.numpy())
    assert last < first * 0.2, (first, last)


def test_to_static_multi_output():
    class TwoHead(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(4, 2)
            self.b = nn.Linear(4, 3)

        def forward(self, x):
            return self.a(x), self.b(x)

    net = TwoHead()
    x = _x()
    ea, eb = net.a(x).numpy(), net.b(x).numpy()
    traced = jit.to_static(net)
    oa, ob = traced(x)
    np.testing.assert_allclose(oa.numpy(), ea, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(ob.numpy(), eb, rtol=1e-5, atol=1e-6)


def test_hapi_model_with_to_static():
    """hapi Model.fit drives its train step through the whole-block jit
    path when the network is wrapped with jit.to_static (hapi/model.py
    docstring contract)."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu.hapi import Model
    from paddle_tpu.io import DataLoader
    from paddle_tpu.io.dataset import Dataset

    rng = np.random.RandomState(0)
    xv = rng.rand(32, 4).astype(np.float32)
    yv = xv.sum(1, keepdims=True).astype(np.float32)

    class DS(Dataset):
        def __len__(self):
            return 32

        def __getitem__(self, i):
            return xv[i], yv[i]

    net = jit.to_static(SmallNet())
    model = Model(net)
    model.prepare(opt.Adam(learning_rate=0.05,
                           parameters=net.parameters()),
                  paddle_tpu.nn.MSELoss())
    loader = DataLoader(DS(), batch_size=16, shuffle=False)
    def _loss(h):
        v = h["loss"]
        return float(v[0]) if isinstance(v, (list, tuple)) else float(v)

    h0 = _loss(model.evaluate(loader, verbose=0))
    model.fit(loader, epochs=15, verbose=0)
    h1 = _loss(model.evaluate(loader, verbose=0))
    assert h1 < h0 * 0.2, (h0, h1)


def test_to_static_updates_batchnorm_running_stats():
    """Buffer rebindings (BN running mean/var via set_value) must keep
    updating across replays of the compiled program, matching eager."""
    import numpy as np
    import paddle_tpu
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import StaticFunction

    rng = np.random.RandomState(0)
    batches = [rng.rand(8, 3).astype(np.float32) * 4 - 1
               for _ in range(5)]

    def run(use_jit):
        with paddle_tpu.dygraph.guard():
            paddle_tpu.seed(0)
            bn = nn.BatchNorm1D(3)
            bn.train()
            fwd = StaticFunction(lambda x: bn(x), layer=bn) if use_jit \
                else (lambda x: bn(x))
            for b in batches:
                y = fwd(paddle_tpu.to_tensor(b))
            return (np.asarray(bn._mean.numpy()).copy(),
                    np.asarray(bn._variance.numpy()).copy(),
                    np.asarray(y.numpy()))

    m_e, v_e, y_e = run(False)
    m_j, v_j, y_j = run(True)
    np.testing.assert_allclose(m_j, m_e, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(v_j, v_e, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(y_j, y_e, rtol=1e-4, atol=1e-5)
    # the stats actually moved from init (0 mean / 1 var)
    assert np.abs(m_j).max() > 0.05


# ---------------------------------------------------------------------------
# dy2static: tensor-dependent `if` recorded as a real cond op
# (jit/dy2static.py; reference dygraph_to_static/ifelse_transformer.py)
# ---------------------------------------------------------------------------
def test_dy2static_tensor_if_both_paths():
    import paddle_tpu.tensor as pt

    def f(x):
        if pt.mean(x) > 0:
            y = x * 2.0
        else:
            y = x - 3.0
        return y

    traced = jit.to_static(f)
    xp = paddle_tpu.to_tensor(np.full((2, 3), 1.0, np.float32))
    xn = paddle_tpu.to_tensor(np.full((2, 3), -1.0, np.float32))
    # ONE trace serves BOTH branches — the program carries a real cond op
    np.testing.assert_allclose(traced(xp).numpy(), np.full((2, 3), 2.0),
                               rtol=1e-6)
    np.testing.assert_allclose(traced(xn).numpy(), np.full((2, 3), -4.0),
                               rtol=1e-6)
    assert len(traced._cache) == 1
    cp = next(iter(traced._cache.values()))
    types = [op.type for b in cp.program.blocks for op in b.ops]
    assert "cond" in types
    assert len(cp.program.blocks) >= 3  # global + two branch blocks


def test_dy2static_python_if_unaffected():
    def f(x, flag=True):
        if flag:
            return x * 3.0
        return x

    traced = jit.to_static(lambda t: f(t))
    x = _x()
    np.testing.assert_allclose(traced(x).numpy(), x.numpy() * 3.0,
                               rtol=1e-6)


def test_dy2static_branch_var_merging():
    import paddle_tpu.tensor as pt

    def f(x):
        scale = x * 0.0 + 1.0
        if pt.sum(x) > 10.0:
            scale = scale * 5.0
            shift = x * 0.0 + 1.0
        else:
            shift = x * 0.0
        return x * scale + shift

    traced = jit.to_static(f)
    big = paddle_tpu.to_tensor(np.full((2, 4), 9.0, np.float32))
    small = paddle_tpu.to_tensor(np.full((2, 4), 0.5, np.float32))
    np.testing.assert_allclose(traced(big).numpy(),
                               np.full((2, 4), 46.0), rtol=1e-6)
    np.testing.assert_allclose(traced(small).numpy(),
                               np.full((2, 4), 0.5), rtol=1e-6)
    assert len(traced._cache) == 1


def test_dy2static_gradients_through_cond():
    import paddle_tpu.tensor as pt

    net = SmallNet()

    def f(x):
        h = net.forward(x)
        if pt.mean(h) > 0:
            return h * 2.0
        else:
            return h * 0.5

    traced = jit.to_static(f)
    x = _x()
    out = traced(x)
    loss = paddle_tpu.tensor.mean(out)
    loss.backward()
    g = net.l1.weight.grad
    assert g is not None and np.isfinite(np.asarray(g)).all()
    assert float(np.abs(np.asarray(g)).sum()) > 0


def test_dy2static_save_load_keeps_cond(tmp_path):
    import paddle_tpu.tensor as pt

    class CondNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(4, 4)

        def forward(self, x):
            h = self.lin(x)
            if pt.mean(h) > 0:
                y = h * 2.0
            else:
                y = -h
            return y

    net = CondNet()
    traced = jit.to_static(net)
    x = _x()
    ref = traced.forward(x).numpy()
    path = str(tmp_path / "condnet")
    jit.save(net, path, input_spec=[InputSpec([3, 4])])
    loaded = jit.load(path)
    got = loaded(x)
    np.testing.assert_allclose(np.asarray(got.numpy()), ref, rtol=1e-5,
                               atol=1e-6)


def test_dy2static_return_in_nested_loop_falls_back():
    # `return` inside a for within an if-branch can't be hoisted — the
    # transform must refuse and fall back to tracing with correct values
    def f(x, flag=True):
        if flag:
            for _ in range(1):
                return x * 2.0
        return x

    traced = jit.to_static(lambda t: f(t))
    x = _x()
    np.testing.assert_allclose(traced(x).numpy(), x.numpy() * 2.0,
                               rtol=1e-6)


def test_dy2static_for_target_propagates():
    # names bound by for-loops inside a branch must survive past the if
    def g(x, flag=True):
        if flag:
            vals = []
            for i in range(3):
                vals.append(i)
        return x * float(i)

    traced = jit.to_static(lambda t: g(t))
    x = _x()
    np.testing.assert_allclose(traced(x).numpy(), x.numpy() * 2.0,
                               rtol=1e-6)


def test_dy2static_late_bound_global():
    # a global defined AFTER decoration must still resolve (late binding)
    import types
    mod = types.ModuleType("dy2st_late_mod")
    src = (
        "import paddle_tpu.tensor as pt\n"
        "def h(x):\n"
        "    if _flag:\n"
        "        y = x * 2.0\n"
        "    else:\n"
        "        y = x\n"
        "    return y\n")
    exec(src, mod.__dict__)
    import sys as _sys
    import linecache
    linecache.cache["<dy2st_late_mod>"] = (
        len(src), None, src.splitlines(True), "<dy2st_late_mod>")
    # re-exec with a filename so inspect.getsource works
    code = compile(src, "<dy2st_late_mod>", "exec")
    exec(code, mod.__dict__)
    traced = jit.to_static(mod.h)
    mod._flag = True  # defined only after to_static
    x = _x()
    np.testing.assert_allclose(traced(x).numpy(), x.numpy() * 2.0,
                               rtol=1e-6)

"""paddle_tpu.jit tests: to_static tracing, whole-block jit execution,
grad bridging to the dygraph tape, save/load round-trip
(reference: fluid/tests/unittests/dygraph_to_static/, test_jit_save_load.py)."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.nn as nn
from paddle_tpu import jit
from paddle_tpu.jit import InputSpec


class SmallNet(nn.Layer):
    def __init__(self, din=4, dh=8):
        super().__init__()
        self.l1 = nn.Linear(din, dh)
        self.l2 = nn.Linear(dh, 1)

    def forward(self, x):
        return self.l2(paddle_tpu.nn.functional.relu(self.l1(x)))


def _x(b=3, d=4, seed=0):
    return paddle_tpu.to_tensor(
        np.random.RandomState(seed).rand(b, d).astype(np.float32))


def test_to_static_function_matches_eager():
    net = SmallNet()
    x = _x()
    eager = net(x).numpy()

    traced = jit.to_static(lambda t: net.forward(t))
    out = traced(x)
    np.testing.assert_allclose(out.numpy(), eager, rtol=1e-5, atol=1e-6)
    # second call hits the signature cache (no retrace)
    assert len(traced._cache) == 1
    out2 = traced(_x(seed=1))
    assert len(traced._cache) == 1


def test_to_static_layer_decorator():
    net = jit.to_static(SmallNet())
    x = _x()
    ref = SmallNet()
    # copy params so outputs are comparable
    for p_dst, p_src in zip(net.parameters(), ref.parameters()):
        p_src._value = p_dst._value
    np.testing.assert_allclose(net(x).numpy(), ref(x).numpy(),
                               rtol=1e-5, atol=1e-6)


def test_to_static_training_updates_params():
    """backward() through the traced computation must put grads on the
    eager Parameters and train to convergence (whole-block jit path)."""
    import paddle_tpu.optimizer as opt
    net = SmallNet()
    net.train()
    traced = jit.to_static(net)
    optimizer = opt.Adam(learning_rate=0.05,
                         parameters=net.parameters())
    rng = np.random.RandomState(0)
    xv = rng.rand(16, 4).astype(np.float32)
    yv = xv.sum(1, keepdims=True).astype(np.float32)
    x = paddle_tpu.to_tensor(xv)
    y = paddle_tpu.to_tensor(yv)
    first = None
    for i in range(80):
        pred = traced(x)
        loss = paddle_tpu.nn.functional.mse_loss(pred, y)
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
        if first is None:
            first = float(loss.numpy())
    last = float(loss.numpy())
    assert last < first * 0.05, (first, last)


def test_jit_save_load_roundtrip():
    net = SmallNet()
    net.eval()
    x = _x()
    ref = net(x).numpy()
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model")
        jit.save(net, path, input_spec=[InputSpec([-1, 4], "float32")])
        loaded = jit.load(path)
        loaded.eval()
        out = loaded(x)
        out = out[0] if isinstance(out, (list, tuple)) else out
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)


def test_jit_load_finetune():
    """Loaded TranslatedLayer parameters are trainable."""
    import paddle_tpu.optimizer as opt
    net = SmallNet()
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model")
        jit.save(net, path, input_spec=[InputSpec([-1, 4], "float32")])
        loaded = jit.load(path)
    loaded.train()
    params = loaded.parameters()
    assert params, "loaded layer exposes no trainable parameters"
    optimizer = opt.Adam(learning_rate=0.05, parameters=params)
    rng = np.random.RandomState(1)
    xv = rng.rand(16, 4).astype(np.float32)
    yv = (2 * xv.sum(1, keepdims=True)).astype(np.float32)
    x = paddle_tpu.to_tensor(xv)
    y = paddle_tpu.to_tensor(yv)
    first = last = None
    for i in range(60):
        out = loaded(x)
        out = out[0] if isinstance(out, (list, tuple)) else out
        loss = paddle_tpu.nn.functional.mse_loss(out, y)
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
        if first is None:
            first = float(loss.numpy())
        last = float(loss.numpy())
    assert last < first * 0.2, (first, last)


def test_to_static_multi_output():
    class TwoHead(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(4, 2)
            self.b = nn.Linear(4, 3)

        def forward(self, x):
            return self.a(x), self.b(x)

    net = TwoHead()
    x = _x()
    ea, eb = net.a(x).numpy(), net.b(x).numpy()
    traced = jit.to_static(net)
    oa, ob = traced(x)
    np.testing.assert_allclose(oa.numpy(), ea, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(ob.numpy(), eb, rtol=1e-5, atol=1e-6)


def test_hapi_model_with_to_static():
    """hapi Model.fit drives its train step through the whole-block jit
    path when the network is wrapped with jit.to_static (hapi/model.py
    docstring contract)."""
    import paddle_tpu.optimizer as opt
    from paddle_tpu.hapi import Model
    from paddle_tpu.io import DataLoader
    from paddle_tpu.io.dataset import Dataset

    rng = np.random.RandomState(0)
    xv = rng.rand(32, 4).astype(np.float32)
    yv = xv.sum(1, keepdims=True).astype(np.float32)

    class DS(Dataset):
        def __len__(self):
            return 32

        def __getitem__(self, i):
            return xv[i], yv[i]

    net = jit.to_static(SmallNet())
    model = Model(net)
    model.prepare(opt.Adam(learning_rate=0.05,
                           parameters=net.parameters()),
                  paddle_tpu.nn.MSELoss())
    loader = DataLoader(DS(), batch_size=16, shuffle=False)
    def _loss(h):
        v = h["loss"]
        return float(v[0]) if isinstance(v, (list, tuple)) else float(v)

    h0 = _loss(model.evaluate(loader, verbose=0))
    model.fit(loader, epochs=15, verbose=0)
    h1 = _loss(model.evaluate(loader, verbose=0))
    assert h1 < h0 * 0.2, (h0, h1)


def test_to_static_updates_batchnorm_running_stats():
    """Buffer rebindings (BN running mean/var via set_value) must keep
    updating across replays of the compiled program, matching eager."""
    import numpy as np
    import paddle_tpu
    import paddle_tpu.nn as nn
    from paddle_tpu.jit import StaticFunction

    rng = np.random.RandomState(0)
    batches = [rng.rand(8, 3).astype(np.float32) * 4 - 1
               for _ in range(5)]

    def run(use_jit):
        with paddle_tpu.dygraph.guard():
            paddle_tpu.seed(0)
            bn = nn.BatchNorm1D(3)
            bn.train()
            fwd = StaticFunction(lambda x: bn(x), layer=bn) if use_jit \
                else (lambda x: bn(x))
            for b in batches:
                y = fwd(paddle_tpu.to_tensor(b))
            return (np.asarray(bn._mean.numpy()).copy(),
                    np.asarray(bn._variance.numpy()).copy(),
                    np.asarray(y.numpy()))

    m_e, v_e, y_e = run(False)
    m_j, v_j, y_j = run(True)
    np.testing.assert_allclose(m_j, m_e, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(v_j, v_e, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(y_j, y_e, rtol=1e-4, atol=1e-5)
    # the stats actually moved from init (0 mean / 1 var)
    assert np.abs(m_j).max() > 0.05


# ---------------------------------------------------------------------------
# dy2static: tensor-dependent `if` recorded as a real cond op
# (jit/dy2static.py; reference dygraph_to_static/ifelse_transformer.py)
# ---------------------------------------------------------------------------
def test_dy2static_tensor_if_both_paths():
    import paddle_tpu.tensor as pt

    def f(x):
        if pt.mean(x) > 0:
            y = x * 2.0
        else:
            y = x - 3.0
        return y

    traced = jit.to_static(f)
    xp = paddle_tpu.to_tensor(np.full((2, 3), 1.0, np.float32))
    xn = paddle_tpu.to_tensor(np.full((2, 3), -1.0, np.float32))
    # ONE trace serves BOTH branches — the program carries a real cond op
    np.testing.assert_allclose(traced(xp).numpy(), np.full((2, 3), 2.0),
                               rtol=1e-6)
    np.testing.assert_allclose(traced(xn).numpy(), np.full((2, 3), -4.0),
                               rtol=1e-6)
    assert len(traced._cache) == 1
    cp = next(iter(traced._cache.values()))
    types = [op.type for b in cp.program.blocks for op in b.ops]
    assert "cond" in types
    assert len(cp.program.blocks) >= 3  # global + two branch blocks


def test_dy2static_python_if_unaffected():
    def f(x, flag=True):
        if flag:
            return x * 3.0
        return x

    traced = jit.to_static(lambda t: f(t))
    x = _x()
    np.testing.assert_allclose(traced(x).numpy(), x.numpy() * 3.0,
                               rtol=1e-6)


def test_dy2static_branch_var_merging():
    import paddle_tpu.tensor as pt

    def f(x):
        scale = x * 0.0 + 1.0
        if pt.sum(x) > 10.0:
            scale = scale * 5.0
            shift = x * 0.0 + 1.0
        else:
            shift = x * 0.0
        return x * scale + shift

    traced = jit.to_static(f)
    big = paddle_tpu.to_tensor(np.full((2, 4), 9.0, np.float32))
    small = paddle_tpu.to_tensor(np.full((2, 4), 0.5, np.float32))
    np.testing.assert_allclose(traced(big).numpy(),
                               np.full((2, 4), 46.0), rtol=1e-6)
    np.testing.assert_allclose(traced(small).numpy(),
                               np.full((2, 4), 0.5), rtol=1e-6)
    assert len(traced._cache) == 1


def test_dy2static_gradients_through_cond():
    import paddle_tpu.tensor as pt

    net = SmallNet()

    def f(x):
        h = net.forward(x)
        if pt.mean(h) > 0:
            return h * 2.0
        else:
            return h * 0.5

    traced = jit.to_static(f)
    x = _x()
    out = traced(x)
    loss = paddle_tpu.tensor.mean(out)
    loss.backward()
    g = net.l1.weight.grad
    assert g is not None and np.isfinite(np.asarray(g)).all()
    assert float(np.abs(np.asarray(g)).sum()) > 0


def test_dy2static_save_load_keeps_cond(tmp_path):
    import paddle_tpu.tensor as pt

    class CondNet(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(4, 4)

        def forward(self, x):
            h = self.lin(x)
            if pt.mean(h) > 0:
                y = h * 2.0
            else:
                y = -h
            return y

    net = CondNet()
    traced = jit.to_static(net)
    x = _x()
    ref = traced.forward(x).numpy()
    path = str(tmp_path / "condnet")
    jit.save(net, path, input_spec=[InputSpec([3, 4])])
    loaded = jit.load(path)
    got = loaded(x)
    np.testing.assert_allclose(np.asarray(got.numpy()), ref, rtol=1e-5,
                               atol=1e-6)


def test_dy2static_return_in_nested_loop_falls_back():
    # `return` inside a for within an if-branch can't be hoisted — the
    # transform must refuse and fall back to tracing with correct values
    def f(x, flag=True):
        if flag:
            for _ in range(1):
                return x * 2.0
        return x

    traced = jit.to_static(lambda t: f(t))
    x = _x()
    np.testing.assert_allclose(traced(x).numpy(), x.numpy() * 2.0,
                               rtol=1e-6)


def test_dy2static_for_target_propagates():
    # names bound by for-loops inside a branch must survive past the if
    def g(x, flag=True):
        if flag:
            vals = []
            for i in range(3):
                vals.append(i)
        return x * float(i)

    traced = jit.to_static(lambda t: g(t))
    x = _x()
    np.testing.assert_allclose(traced(x).numpy(), x.numpy() * 2.0,
                               rtol=1e-6)


def test_dy2static_late_bound_global():
    # a global defined AFTER decoration must still resolve (late binding)
    import types
    mod = types.ModuleType("dy2st_late_mod")
    src = (
        "import paddle_tpu.tensor as pt\n"
        "def h(x):\n"
        "    if _flag:\n"
        "        y = x * 2.0\n"
        "    else:\n"
        "        y = x\n"
        "    return y\n")
    exec(src, mod.__dict__)
    import sys as _sys
    import linecache
    linecache.cache["<dy2st_late_mod>"] = (
        len(src), None, src.splitlines(True), "<dy2st_late_mod>")
    # re-exec with a filename so inspect.getsource works
    code = compile(src, "<dy2st_late_mod>", "exec")
    exec(code, mod.__dict__)
    traced = jit.to_static(mod.h)
    mod._flag = True  # defined only after to_static
    x = _x()
    np.testing.assert_allclose(traced(x).numpy(), x.numpy() * 2.0,
                               rtol=1e-6)


# ---------------------------------------------------------------------------
# dy2static loop conversion (reference loop_transformer.py:367,
# break_continue_transformer.py:86)
# ---------------------------------------------------------------------------
def _t(arr, dtype=np.float32):
    return paddle_tpu.to_tensor(np.asarray(arr, dtype))


def test_dy2static_while_records_while_op_and_reuses():
    @jit.to_static
    def countdown(x):
        s = x * 0.0
        while x.sum() > 0:
            s = s + x
            x = x - 1.0
        return s

    def ref(xv):
        s = xv * 0
        while xv.sum() > 0:
            s = s + xv
            xv = xv - 1
        return s

    out = countdown(_t([3.0, 2.0]))
    np.testing.assert_allclose(out.numpy(), ref(np.array([3.0, 2.0])),
                               rtol=1e-6)
    cp = countdown.concrete_program(_t([3.0, 2.0]))
    types = [op.type for op in cp.program.global_block().ops]
    assert "while" in types, types
    assert len(cp.program.blocks) >= 2
    # the SAME compiled program must be right for a different trip count —
    # the point of a real while op vs trace-time unrolling
    out2 = countdown(_t([5.0, 1.0]))
    np.testing.assert_allclose(out2.numpy(), ref(np.array([5.0, 1.0])),
                               rtol=1e-6)
    assert len(countdown._cache) == 1


def test_dy2static_decode_loop_with_break():
    # GPT-style greedy decode shape: fixed buffer, tensor stop condition,
    # data-dependent break
    @jit.to_static
    def decode(seed, buf, i):
        while i.sum() < 6:
            tok = (seed + i).sum() % 5.0
            if tok > 3.0:
                break
            buf = buf + tok
            i = i + 1
        return buf, i

    def ref(sv, bv, iv):
        while iv.sum() < 6:
            tok = (sv + iv).sum() % 5.0
            if tok > 3.0:
                break
            bv = bv + tok
            iv = iv + 1
        return bv, iv

    for sv in (1.0, 2.0):
        out, iend = decode(_t([sv]), _t(np.zeros(4)), _t([0.0]))
        ro, ri = ref(np.array([sv], np.float32), np.zeros(4, np.float32),
                     np.array([0.0], np.float32))
        np.testing.assert_allclose(out.numpy(), ro, rtol=1e-6)
        np.testing.assert_allclose(iend.numpy(), ri, rtol=1e-6)
    cp = decode.concrete_program(_t([1.0]), _t(np.zeros(4)), _t([0.0]))
    types = [op.type for op in cp.program.global_block().ops]
    assert "while" in types, types
    assert len(decode._cache) == 1


def test_dy2static_continue_in_while():
    @jit.to_static
    def skip_odd(x):
        s = x * 0.0
        k = x.sum() * 0.0
        while k < 5:
            k = k + 1
            if (k % 2) > 0:
                continue
            s = s + k
        return s

    got = skip_odd(_t([0.0]))
    np.testing.assert_allclose(got.numpy(), [6.0], rtol=1e-6)  # 2 + 4
    cp = skip_odd.concrete_program(_t([0.0]))
    assert "while" in [op.type for op in cp.program.global_block().ops]


def test_dy2static_for_range_tensor_bound():
    @jit.to_static
    def tsum(n, x):
        acc = x * 0.0
        for _ in range(n):
            acc = acc + x
        return acc

    got = tsum(_t(4, np.int32), _t([1.5]))
    np.testing.assert_allclose(got.numpy(), [6.0], rtol=1e-6)
    cp = tsum.concrete_program(_t(4, np.int32), _t([1.5]))
    assert "while" in [op.type for op in cp.program.global_block().ops]
    # same compiled program, different bound
    got2 = tsum(_t(7, np.int32), _t([2.0]))
    np.testing.assert_allclose(got2.numpy(), [14.0], rtol=1e-6)
    assert len(tsum._cache) == 1


def test_dy2static_for_over_tensor_unrolls_with_gather():
    @jit.to_static
    def rowsum(m):
        acc = m.sum(axis=0) * 0.0
        for row in m:
            acc = acc + row
        return acc

    m = np.arange(6, dtype=np.float32).reshape(3, 2)
    got = rowsum(_t(m))
    np.testing.assert_allclose(got.numpy(), m.sum(0), rtol=1e-6)
    cp = rowsum.concrete_program(_t(m))
    types = [op.type for op in cp.program.global_block().ops]
    assert "gather" in types  # leading-axis iteration via named op


def test_dy2static_nested_while():
    @jit.to_static
    def nested(x):
        total = x * 0.0
        i = x.sum() * 0.0
        while i < 3:
            j = x.sum() * 0.0
            while j < 2:
                total = total + 1.0
                j = j + 1
            i = i + 1
        return total

    got = nested(_t([0.0]))
    np.testing.assert_allclose(got.numpy(), [6.0], rtol=1e-6)
    cp = nested.concrete_program(_t([0.0]))
    # outer while in block 0, inner while inside the outer sub-block
    assert "while" in [op.type for op in cp.program.global_block().ops]
    sub_types = [op.type for b in cp.program.blocks[1:] for op in b.ops]
    assert "while" in sub_types


def test_dy2static_python_condition_unrolls():
    # plain python bounds stay trace-time (jax.jit contract): no while op
    @jit.to_static
    def unrolled(x):
        for _ in range(3):
            x = x * 2.0
        return x

    got = unrolled(_t([1.0]))
    np.testing.assert_allclose(got.numpy(), [8.0], rtol=1e-6)
    cp = unrolled.concrete_program(_t([1.0]))
    types = [op.type for op in cp.program.global_block().ops]
    assert "while" not in types
    assert types.count("elementwise_mul") == 3


def test_dy2static_loop_save_load_roundtrip():
    @jit.to_static
    def triple_until(x):
        while x.sum() < 20:
            x = x * 3.0
        return x

    out = triple_until(_t([1.0]))
    np.testing.assert_allclose(out.numpy(), [27.0], rtol=1e-6)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "loopmod")
        jit.save(triple_until, path,
                 input_spec=[InputSpec([1], "float32")])
        loaded = jit.load(path)
        got = loaded(_t([2.0]))
        got = got[0] if isinstance(got, (list, tuple)) else got
        np.testing.assert_allclose(got.numpy(), [54.0], rtol=1e-6)


def test_dy2static_late_changing_python_loop_var():
    # a python counter that only moves in later iterations must still be
    # lifted to loop-carried state (multi-iteration discovery)
    @jit.to_static
    def late_k(x):
        k = 0.0
        while x.sum() < 4:
            x = x + 1.0
            if x.sum() > 2:
                k = k + 1.0
        return x + k

    def ref(xv):
        k = 0.0
        while xv.sum() < 4:
            xv = xv + 1.0
            if xv.sum() > 2:
                k = k + 1.0
        return xv + k

    for v in (0.0, 1.0):
        got = late_k(_t([v]))
        np.testing.assert_allclose(got.numpy(), ref(np.array([v],
                                                            np.float32)))
    assert len(late_k._cache) == 1


def test_dy2static_tensor_break_in_python_for():
    # condition becomes tensor-dependent mid-unroll: the unrolled prefix
    # is python-decided, the remainder must become a real while op
    @jit.to_static
    def for_break(x):
        for _ in range(5):
            if x.sum() > 3.0:
                break
            x = x + 1.0
        return x

    def ref(xv):
        for _ in range(5):
            if xv.sum() > 3.0:
                break
            xv = xv + 1.0
        return xv

    # trace with an input that breaks immediately, then reuse with one
    # that runs all iterations — the cached program must be right
    got = for_break(_t([3.5]))
    np.testing.assert_allclose(got.numpy(), ref(np.array([3.5],
                                                         np.float32)))
    got = for_break(_t([0.0]))
    np.testing.assert_allclose(got.numpy(), ref(np.array([0.0],
                                                         np.float32)))
    assert len(for_break._cache) == 1


def test_dy2static_boolop_condition():
    # python `and` in the loop condition must not concretize the tensor
    # operands at trace time
    @jit.to_static
    def both(x, y):
        s = x * 0.0
        while x.sum() > 0 and y.sum() > 0:
            s = s + 1.0
            x = x - 1.0
            y = y - 1.0
        return s

    def ref(xv, yv):
        s = xv * 0
        while xv.sum() > 0 and yv.sum() > 0:
            s = s + 1.0
            xv = xv - 1.0
            yv = yv - 1.0
        return s

    got = both(_t([3.0]), _t([1.0]))
    np.testing.assert_allclose(
        got.numpy(), ref(np.array([3.0], np.float32),
                         np.array([1.0], np.float32)))
    got = both(_t([1.0]), _t([3.0]))
    np.testing.assert_allclose(
        got.numpy(), ref(np.array([1.0], np.float32),
                         np.array([3.0], np.float32)))
    assert len(both._cache) == 1


def test_dy2static_for_over_dict_and_value_boolop():
    cfg = {"a": 1.0, "b": 2.0}

    @jit.to_static
    def dict_iter(x):
        for k in cfg:           # mappings iterate keys, not positions
            x = x + cfg[k]
        y = x or 123.0          # value-context BoolOp: python semantics
        return y + 0.0

    got = dict_iter(_t([0.0]))
    np.testing.assert_allclose(got.numpy(), [3.0])


def test_dy2static_break_does_not_reevaluate_test():
    data = [1.0, 2.0, 3.0]

    @jit.to_static
    def walk(x):
        i = 0
        while data[i] > 0:      # would IndexError if re-evaluated at i==3
            x = x + data[i]
            i = i + 1
            if i == len(data):
                break
        return x

    got = walk(_t([0.0]))
    np.testing.assert_allclose(got.numpy(), [6.0])


def test_dy2static_return_loop_keeps_if_conversion():
    # a python loop containing `return` stays untransformed, but the
    # tensor-if elsewhere in the SAME function must still convert —
    # cache reuse with the opposite branch has to be correct
    @jit.to_static
    def f(x):
        for v in [1.0, 2.0]:
            if v > 5.0:
                return x
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x - 1.0
        return y

    got = f(_t([3.0]))
    np.testing.assert_allclose(got.numpy(), [6.0])
    got = f(_t([-3.0]))   # cached program, other branch
    np.testing.assert_allclose(got.numpy(), [-4.0])
    assert len(f._cache) == 1


# ---------------------------------------------------------------------------
# dy2static polish transformers (VERDICT r3 missing #3):
# print / assert / cast / list-append-in-loop
# ---------------------------------------------------------------------------

def test_dy2static_print_tensor_converts(capfd):
    import jax
    @jit.to_static
    def f(x):
        if x.sum() > 0:
            x = x * 2.0
        print("val:", x)
        return x + 1.0

    out = f(_t([1.0, 2.0]))
    np.testing.assert_allclose(out.numpy(), [3.0, 5.0], rtol=1e-6)
    # jax.debug.print fires at execution: the traced value must appear
    jax.effects_barrier()
    captured = capfd.readouterr()
    assert "val:" in captured.out or "val:" in captured.err


def test_dy2static_assert_converts_and_fires():
    import jax
    @jit.to_static
    def f(x):
        assert x.sum() > 0, "sum must be positive"
        return x * 2.0

    out = f(_t([1.0, 2.0]))
    np.testing.assert_allclose(out.numpy(), [2.0, 4.0], rtol=1e-6)
    # failing assert surfaces when results are consumed (runtime-abort
    # contract of the reference Assert op)
    with pytest.raises(Exception, match="sum must be positive"):
        bad = f(_t([-5.0, 1.0]))
        np.asarray(bad.numpy())
        jax.effects_barrier()


def test_dy2static_cast_int_float_convert():
    @jit.to_static
    def f(x):
        n = int(x.sum())          # cast op under trace
        y = float(n) + 0.5
        if x.sum() > 0:
            x = x * y
        return x

    out = f(_t([1.0, 3.0]))
    np.testing.assert_allclose(out.numpy(), [4.5, 13.5], rtol=1e-6)


def test_dy2static_list_append_in_loop():
    @jit.to_static
    def f(x):
        acc = []
        for i in range(3):
            acc.append(x * float(i + 1))
        if x.sum() > 0:
            x = x * 0.0
        return acc[0] + acc[1] + acc[2] + x

    out = f(_t([1.0, 2.0]))
    np.testing.assert_allclose(out.numpy(), [6.0, 12.0], rtol=1e-6)


def test_dy2static_list_append_in_tensor_loop():
    # a list.append value escaping a tensor-dependent loop cannot be
    # loop-carried (reference needs the TensorArray list transformer);
    # the contract here: loop-carried ASSIGNED accumulation works, and
    # escaping an append raises a clear error naming the array-ops route
    @jit.to_static
    def ok(x):
        acc = x * 0.0
        while x.sum() < 10:
            x = x * 2.0
            acc = acc + x
        return x, acc

    out, acc = ok(_t([1.0]))
    np.testing.assert_allclose(out.numpy(), [16.0], rtol=1e-6)
    np.testing.assert_allclose(acc.numpy(), [2 + 4 + 8 + 16.0], rtol=1e-6)

    @jit.to_static
    def bad(x):
        seen = []
        while x.sum() < 10:
            x = x * 2.0
            seen.append(x.sum())
        return x, seen[-1]

    with pytest.raises(TypeError, match="loop-carried"):
        bad(_t([1.0]))

"""Worker script for test_multihost_launch — launched by
distributed/launch.py with the PADDLE_* env contract.  Each "host" is one
process on a virtual 8-device CPU mesh.  Trains a fixed linreg batch via
fleet (role_maker from env + graph_execution meta-optimizer), coordinates
with its peer through the KV server (real cross-process barrier), and
writes its losses to a JSON file for the test to compare."""
import json
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np
import jax

jax.config.update("jax_platforms", "cpu")

import paddle_tpu.static as static
import paddle_tpu.distributed as dist
from paddle_tpu.static import layers


def main():
    out_dir = sys.argv[1]
    kv_endpoint = sys.argv[2]
    rank = int(os.environ["PADDLE_TRAINER_ID"])
    nranks = int(os.environ["PADDLE_TRAINERS_NUM"])
    endpoints = os.environ["PADDLE_TRAINER_ENDPOINTS"].split(",")
    assert len(endpoints) == nranks
    assert os.environ["PADDLE_CURRENT_ENDPOINT"] == endpoints[rank]

    from paddle_tpu.distributed.fleet.base.fleet_base import fleet
    role = dist.fleet.PaddleCloudRoleMaker(is_collective=True)
    fleet.init(role)
    assert fleet.worker_num() == nranks
    assert fleet.worker_index() == rank

    main_p, startup = static.Program(), static.Program()
    with static.program_guard(main_p, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        pred = layers.fc(x, 1, param_attr=static.ParamAttr(
            initializer=static.Constant(0.0)))
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        strategy = dist.fleet.DistributedStrategy()
        fleet.distributed_optimizer(static.SGD(learning_rate=0.05),
                                    strategy)
        fleet.minimize(loss)
    assert "GraphExecutionOptimizer" in fleet.applied_meta_list()

    rng = np.random.RandomState(42)  # SAME data on every host
    xb = rng.rand(16, 8).astype(np.float32)
    yb = xb.sum(1, keepdims=True).astype(np.float32)

    exe = static.Executor()
    scope = static.Scope()
    losses = []
    with static.scope_guard(scope):
        exe.run(startup)
        for _ in range(5):
            (lv,) = exe.run(fleet.main_program, feed={"x": xb, "y": yb},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv)))

    # real cross-process coordination: barrier + loss exchange via the KV
    # server the test started (PS rendezvous path)
    from paddle_tpu.distributed.ps.kv_server import KVClient
    c = KVClient([kv_endpoint])
    c.wait_server_ready()
    c.set_param(f"losses_{rank}", np.asarray(losses, np.float32))
    c.barrier()
    peer = c.pull(f"losses_{(rank + 1) % nranks}")
    np.testing.assert_allclose(np.asarray(losses), peer, rtol=1e-5)

    with open(os.path.join(out_dir, f"rank{rank}.json"), "w") as f:
        json.dump({"rank": rank, "nranks": nranks, "losses": losses}, f)


if __name__ == "__main__":
    main()

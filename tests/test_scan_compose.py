"""Scanned-window composition (ISSUE 16 satellite): `Executor.run_steps`
x {ZeRO-1, ZeRO-2 x gradient-merge (commit-tail HOISTED), ZeRO-3,
tensor-parallel, elastic} matches the looped per-step path to 1e-6 on
the 8-device CPU mesh — per-micro-step losses AND final parameters.

The zero2+gm (the hoisted default hot path) and tp legs stay tier-1;
the remaining composes are `slow` (each costs a mesh XLA compile and
the tier-1 budget is guarded — same split as test_elastic_compose).
"""
import os

import numpy as np
import pytest

os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")

import jax  # noqa: E402

import paddle_tpu.static as static  # noqa: E402
from paddle_tpu.core.program import _reset_unique_names  # noqa: E402
from paddle_tpu.distributed.compiled_program import (  # noqa: E402
    BuildStrategy, CompiledProgram)
from paddle_tpu.distributed.sharding import shard_optimizer_states  # noqa: E402
from paddle_tpu.static import layers  # noqa: E402

WORLD = 8
GB = 8  # global batch: divides the dp mesh under every variant


def _model():
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss)
    return main, startup, loss


def _variant(name):
    """(main, startup, loss, build_strategy, steps-per-window)."""
    main, startup, loss = _model()
    bs, k = None, 4
    if name == "zero1":
        shard_optimizer_states(main, startup, dp_degree=WORLD, stage=1)
    elif name == "zero2_gm":
        shard_optimizer_states(main, startup, dp_degree=WORLD, stage=2)
        static.gradient_merge(main, 2, startup_program=startup)
        k = 2  # window == merge window, so the hoist gate engages
    elif name == "zero3":
        shard_optimizer_states(main, startup, dp_degree=WORLD, stage=3)
    elif name == "tp2":
        bs = BuildStrategy()
        bs.tensor_parallel_degree = 2
    else:
        raise AssertionError(name)
    return main, startup, loss, bs, k


def _feeds(n):
    rng = np.random.RandomState(3)
    return [{"x": rng.rand(GB, 8).astype(np.float32),
             "y": rng.rand(GB, 1).astype(np.float32)}
            for _ in range(n)]


def _run(name, scanned, windows=2):
    main, startup, loss, bs, k = _variant(name)
    feeds = _feeds(windows * k)
    exe = static.Executor()
    scope = static.Scope()
    cp = CompiledProgram(main).with_data_parallel(loss_name=loss.name,
                                                  build_strategy=bs)
    losses = []
    with static.scope_guard(scope):
        exe.run(startup)
        if scanned:
            d0 = cp._dispatches
            for w in range(windows):
                sfeed = {fn: np.stack([feeds[w * k + i][fn]
                                       for i in range(k)])
                         for fn in ("x", "y")}
                outs = exe.run_steps(cp, feed=sfeed, fetch_list=[loss])
                losses.extend(np.asarray(outs[0]).reshape(-1))
            # the window IS one device dispatch, whatever the variant
            assert cp._dispatches - d0 == windows
        else:
            for f in feeds:
                out = exe.run(cp, feed=f, fetch_list=[loss])
                losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
        # every persistable materialized in the scope — under zero3 the
        # raw params are packed into dp_shard buckets, so comparing the
        # scope's persistables covers params, master state, and the gm
        # counter uniformly across variants
        params = {}
        for vn, v in main.global_block().vars.items():
            arr = scope.get(vn) if v.persistable else None
            if arr is not None:
                params[vn] = np.asarray(arr)
    assert len(params) >= 4, sorted(params)
    hoisted = any(key[0] == "steps" and key[1] for key in cp._cache)
    return np.asarray(losses, np.float64), params, hoisted


def _assert_compose(name, expect_hoist=False):
    l_loss, l_params, _ = _run(name, scanned=False)
    s_loss, s_params, hoisted = _run(name, scanned=True)
    np.testing.assert_allclose(l_loss, s_loss, rtol=1e-6, atol=1e-6)
    assert l_params.keys() == s_params.keys()
    for n in sorted(l_params):
        np.testing.assert_allclose(l_params[n], s_params[n],
                                   rtol=1e-6, atol=1e-6, err_msg=n)
    if expect_hoist:
        assert hoisted, ("the zero2 x gm window must take the HOISTED "
                         "scan variant (cache key flag)")


# -- tier-1: the default hot path and the tp mesh ---------------------------
def test_scan_zero2_gm_hoisted_matches_looped():
    _assert_compose("zero2_gm", expect_hoist=True)


def test_scan_tp2_matches_looped():
    _assert_compose("tp2")


# -- slow: the remaining composes (one mesh compile each) -------------------
@pytest.mark.slow
def test_scan_zero1_matches_looped():
    _assert_compose("zero1")


@pytest.mark.slow
def test_scan_zero3_matches_looped():
    _assert_compose("zero3")


@pytest.mark.slow
def test_scan_elastic_matches_looped_window():
    """elastic x run_steps: the K-micro-step elastic window scanned
    into one dispatch tracks the looped schedule to 1e-6 (the bitwise
    contract lives in test_elastic_compose; this seals the compose
    matrix from the scanned side)."""
    from paddle_tpu.distributed.elastic import elasticize, rebucket_feeds
    world, logical = 4, 8
    feeds = _feeds(3)

    def build():
        main, startup, loss = _model()
        meta = elasticize(main, startup, logical_dp=logical,
                          loss_name=loss)
        return main, startup, loss, meta

    main, startup, loss, meta = build()
    exe = static.Executor()
    scope = static.Scope()
    cp = CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, places=list(jax.devices())[:world])
    looped = []
    with static.scope_guard(scope):
        exe.run(startup)
        for f in feeds:
            for mf in rebucket_feeds(f, logical, world):
                out = exe.run(cp, feed=mf, fetch_list=[meta["loss_avg"]])
            looped.append(np.asarray(out[0]).reshape(-1)[0])
        lp = {p.name: np.asarray(scope.get(p.name))
              for p in main.all_parameters()}

    main2, startup2, loss2, meta2 = build()
    exe2 = static.Executor()
    scope2 = static.Scope()
    cp2 = CompiledProgram(main2).with_data_parallel(
        loss_name=loss2.name, places=list(jax.devices())[:world])
    scanned = []
    with static.scope_guard(scope2):
        exe2.run(startup2)
        for f in feeds:
            micro = rebucket_feeds(f, logical, world)
            stacked = {n: np.stack([m[n] for m in micro])
                       for n in micro[0]}
            outs = exe2.run_steps(cp2, feed=stacked,
                                  fetch_list=[meta2["loss_avg"]])
            scanned.append(np.asarray(outs[0])[-1].reshape(-1)[0])
        sp = {p.name: np.asarray(scope2.get(p.name))
              for p in main2.all_parameters()}

    np.testing.assert_allclose(looped, scanned, rtol=1e-6, atol=1e-6)
    for n in sorted(lp):
        np.testing.assert_allclose(lp[n], sp[n], rtol=1e-6, atol=1e-6,
                                   err_msg=n)

"""Server-resident sparse optimizers (pslib analog).

Reference: /root/reference/paddle/fluid/operators/distributed_ops/
lookup_sparse_table_fuse_adam_op.cc:145 (+ fuse_sgd, init/read/write/
merge/grad_split) and the FleetWrapper pull/push contract
(framework/fleet/fleet_wrapper.h:66): Adam moment state lives ON the
pserver, and sync-mode averaging is the SERVER's job, not a
client-grad_scale convention.
"""
import threading

import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers


def _start_server(num_trainers=1):
    from paddle_tpu.distributed.ps.kv_server import KVServer
    srv = KVServer("127.0.0.1:0", num_trainers=num_trainers)
    srv.serve_in_thread()
    return srv


def _client(srvs, **kw):
    from paddle_tpu.distributed.ps.kv_server import KVClient
    c = KVClient([s.endpoint for s in srvs], rpc_deadline=10.0, **kw)
    c.wait_server_ready()
    return c


def _lazy_adam_ref(tab, pushes, lr, b1=0.9, b2=0.999, eps=1e-8):
    """Reference recipe: merge duplicate rows, global beta-pow schedule,
    per-row moments (lookup_sparse_table_fuse_adam_op.cc math)."""
    m1 = np.zeros_like(tab)
    m2 = np.zeros_like(tab)
    t = 0
    tab = tab.copy()
    for ids, vals in pushes:
        uids, inv = np.unique(ids, return_inverse=True)
        g = np.zeros((uids.size,) + vals.shape[1:], np.float32)
        np.add.at(g, inv, vals)
        t += 1
        m1[uids] = b1 * m1[uids] + (1 - b1) * g
        m2[uids] = b2 * m2[uids] + (1 - b2) * g * g
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        tab[uids] -= lr_t * m1[uids] / (np.sqrt(m2[uids]) + eps)
    return tab


def test_server_side_sparse_adam_matches_reference_math():
    srv = _start_server()
    try:
        c = _client([srv])
        tab = np.zeros((6, 3), np.float32)
        c.init_sparse_table("tab", tab)
        c.config_sparse_optimizer("tab", "adam", beta1=0.9, beta2=0.999,
                                  epsilon=1e-8)
        rng = np.random.RandomState(0)
        pushes = [(np.array([0, 2, 0]), rng.randn(3, 3).astype(np.float32)),
                  (np.array([2, 5]), rng.randn(2, 3).astype(np.float32)),
                  (np.array([0]), rng.randn(1, 3).astype(np.float32))]
        for ids, vals in pushes:
            c.push_sparse("tab", ids, vals, lr=0.1)
        got = c.pull_sparse("tab", np.arange(6))
        np.testing.assert_allclose(got, _lazy_adam_ref(tab, pushes, 0.1),
                                   rtol=1e-5, atol=1e-6)
        c.close()
    finally:
        srv.stop()


def test_sync_sparse_push_server_averages_without_grad_scale():
    """Weak #3 fix: two trainers push full (unscaled) grads with
    sync=True; the server accumulates and applies the AVERAGE once —
    a client omitting grad_scale can no longer train at N x lr."""
    srv = _start_server(num_trainers=2)
    try:
        tab = np.zeros((4, 2), np.float32)
        boot = _client([srv])
        boot.init_sparse_table("tab", tab)
        g = np.ones((2, 2), np.float32)
        results = []

        def trainer():
            c = _client([srv])
            # NOTE: no grad_scale — correctness must not depend on it
            c.push_sparse("tab", np.array([0, 1]), g, lr=1.0, sync=True)
            results.append(True)
            c.close()

        ts = [threading.Thread(target=trainer) for _ in range(2)]
        [t.start() for t in ts]
        [t.join(timeout=30) for t in ts]
        assert len(results) == 2
        got = boot.pull_sparse("tab", np.array([0, 1, 2, 3]))
        # ONE averaged application: rows 0,1 -> -1.0 (not -2.0); rest 0
        np.testing.assert_allclose(got[:2], -np.ones((2, 2)), atol=1e-6)
        np.testing.assert_allclose(got[2:], 0, atol=0)
        boot.close()
    finally:
        srv.stop()


def test_sync_sparse_push_empty_shard_completes_fanin():
    """A trainer whose batch touches no row of some shard still counts
    toward that shard's fanin via an empty push."""
    srv = _start_server(num_trainers=2)
    try:
        boot = _client([srv])
        boot.init_sparse_table("tab", np.zeros((4, 2), np.float32))
        done = []

        def trainer(ids, vals):
            c = _client([srv])
            c.push_sparse("tab", ids, vals, lr=1.0, sync=True)
            done.append(True)
            c.close()

        ts = [threading.Thread(
                  target=trainer,
                  args=(np.array([1]), np.ones((1, 2), np.float32))),
              threading.Thread(
                  target=trainer,
                  args=(np.zeros((0,), np.int64),
                        np.zeros((0, 2), np.float32)))]
        [t.start() for t in ts]
        [t.join(timeout=30) for t in ts]
        assert len(done) == 2
        got = boot.pull_sparse("tab", np.array([1]))
        # one trainer contributed, average over 2 live trainers -> -0.5
        np.testing.assert_allclose(got, -0.5 * np.ones((1, 2)), atol=1e-6)
        boot.close()
    finally:
        srv.stop()


def test_fuse_adam_op_matches_dense_adam_on_touched_rows():
    """The registered lookup_sparse_table_fuse_adam kernel (lazy Adam,
    masked rows) against the reference math."""
    from paddle_tpu.core.selected_rows import SelectedRows
    from paddle_tpu.ops.registry import OpContext, run_kernel
    import jax.numpy as jnp
    V, D = 5, 2
    rng = np.random.RandomState(1)
    w = rng.randn(V, D).astype(np.float32)
    rows = np.array([1, 3, 1], np.int32)
    vals = rng.randn(3, D).astype(np.float32)
    outs = run_kernel(
        "lookup_sparse_table_fuse_adam",
        {"Grad": SelectedRows(jnp.asarray(rows), jnp.asarray(vals), V),
         "Param": jnp.asarray(w),
         "Moment1": jnp.zeros((V, D)), "Moment2": jnp.zeros((V, D)),
         # repo accumulator convention: beta pows START at beta (the
         # kernel corrects with the INPUT pows, reference recipe)
         "Beta1Pow": jnp.asarray(0.9), "Beta2Pow": jnp.asarray(0.999),
         "LearningRate": jnp.asarray(0.1)},
        {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8}, OpContext())
    ref = _lazy_adam_ref(w, [(rows, vals)], 0.1)
    np.testing.assert_allclose(np.asarray(outs["ParamOut"]), ref,
                               rtol=1e-5, atol=1e-6)
    # untouched rows keep zero moments
    np.testing.assert_allclose(np.asarray(outs["Moment1Out"])[[0, 2, 4]],
                               0, atol=0)
    assert float(outs["Beta1PowOut"]) == pytest.approx(0.9 ** 2)
    assert float(outs["Beta2PowOut"]) == pytest.approx(0.999 ** 2)


def test_ctr_book_sparse_adam_two_pservers():
    """VERDICT r4 'done' bar: the CTR model converges with server-side
    sparse Adam over 2 pservers (the transpiler reads the Adam config off
    the stripped optimizer op and installs it on every shard)."""
    from paddle_tpu.distributed.ps.ps_optimizer import (
        DistributeTranspiler, DistributeTranspilerConfig)

    srvs = [_start_server(), _start_server()]
    V, D = 32, 8
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            slots = layers.data("slots", [-1, 3], dtype="int64")
            label = layers.data("label", [-1, 1], dtype="int64")
            emb = layers.embedding(slots, size=[V, D], is_sparse=True,
                                   is_distributed=True,
                                   param_attr=static.ParamAttr(
                                       name="ctr_emb"))
            pooled = layers.reduce_sum(emb, dim=1)
            fc1 = layers.fc(pooled, 16, act="relu")
            pred = layers.fc(fc1, 2, act="softmax")
            loss = layers.mean(layers.cross_entropy(pred, label))
            static.Adam(learning_rate=0.05).minimize(loss)

        cfg = DistributeTranspilerConfig()
        cfg.use_graph_ops = True
        cfg.sync_mode = True
        t = DistributeTranspiler(cfg)
        t.transpile(trainer_id=0, program=main,
                    pservers=",".join(s.endpoint for s in srvs),
                    trainers=1, startup_program=startup)
        prog = t.get_trainer_program()

        exe = static.Executor()
        scope = static.Scope()
        rng = np.random.RandomState(0)
        B = 32
        slot_b = rng.randint(0, V, (B, 3)).astype(np.int64)
        y = (slot_b.sum(1) > 1.5 * V).astype(np.int64)[:, None]
        with static.scope_guard(scope):
            exe.run(startup)
            # the startup send installed adam on every shard
            for s in srvs:
                assert s._sparse_opt.get("ctr_emb", {}).get("type") == \
                    "adam", s._sparse_opt
            losses = []
            for _ in range(40):
                (lv,) = exe.run(prog, feed={"slots": slot_b, "label": y},
                                fetch_list=[loss])
                losses.append(float(np.asarray(lv)))
        assert losses[-1] < losses[0] * 0.5, losses[::10]
    finally:
        for s in srvs:
            s.stop()

"""Tier-1 observability gate (NOT marked slow — a regression in the
FLOPs walker or the journal schema must fail the suite, not wait for a
perf round to notice the MFU denominator went wrong).

Drives tools/obs_smoke.py in-process: the 2-layer-toy matmul FLOPs match
the hand count, one journaled train step yields parseable JSONL with the
step-event schema, and prometheus_text() renders the minted metrics —
all under 10 s.  Mirrors the verify_smoke/mem_smoke gate pattern; the
CLI round-trip is `slow`.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_obs_smoke_gate():
    import obs_smoke
    result = obs_smoke.run_smoke()
    assert result["matmul_flops"] == result["hand_counted_flops"], result
    assert result["journal_events"] >= 3, result
    assert "step" in result["journal_kinds"], result
    assert result["prometheus_bytes"] > 0, result
    assert result["value"] < 10, result


@pytest.mark.slow
def test_obs_smoke_cli_prints_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_smoke.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["matmul_flops"] == result["hand_counted_flops"]

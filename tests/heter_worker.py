"""CPU-side heter worker for test_heter_ps — the HeterWrapper CPU-trainer
role (heter_wrapper.h:54): owns the sparse/embedding section (pulls rows
from the KV PS, ships boundary activations to the device worker over the
KV queues, receives activation grads back, pushes the SelectedRows table
grad).  Runs the serialized CPU section program in its own process."""
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")


def main():
    spec_path = sys.argv[1]
    with open(spec_path) as f:
        spec = json.load(f)
    os.environ["PADDLE_TRAINER_ID"] = "0"

    import numpy as np
    import paddle_tpu.static as static
    from paddle_tpu.core.program import Program

    startup = Program.from_dict(spec["startup"])
    cpu_prog = Program.from_dict(spec["cpu_program"])
    feeds = np.asarray(spec["slots"], np.int64)

    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        for _ in range(spec["steps"]):
            exe.run(cpu_prog, feed={spec["feed_name"]: feeds},
                    fetch_list=[])
    print("CPU_WORKER_DONE")


if __name__ == "__main__":
    main()

"""Binary (proto) Program serialization + op-version upgrade tests
(reference: framework.proto round-trips in framework/program_desc_test.cc,
op_version_registry_test.cc)."""
import numpy as np

import paddle_tpu.static as static
from paddle_tpu.static import layers
from paddle_tpu.core.program import Program
from paddle_tpu.core import op_version


def _small_program():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        # constant init so both programs start from identical weights
        h = layers.fc(x, 16, act="relu",
                      param_attr=static.ParamAttr(
                          initializer=static.Constant(0.3)))
        pred = layers.fc(h, 1,
                         param_attr=static.ParamAttr(
                             initializer=static.Constant(0.1)))
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_proto_roundtrip_runs_identically():
    main, startup, loss = _small_program()
    data = main.serialize_to_string(format="proto")
    assert not data.lstrip().startswith(b"{")  # actually binary
    clone = Program.parse_from_string(data)
    # structural identity
    assert clone.fingerprint() == main.fingerprint()

    rng = np.random.RandomState(0)
    xb = rng.rand(4, 8).astype(np.float32)
    yb = rng.rand(4, 1).astype(np.float32)
    exe = static.Executor()
    outs = []
    for prog in (main, clone):
        scope = static.Scope()
        with static.scope_guard(scope):
            exe.run(startup)
            losses = [float(np.asarray(
                exe.run(prog, feed={"x": xb, "y": yb},
                        fetch_list=[loss.name])[0])) for _ in range(3)]
            outs.append(losses)
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-6)


def test_json_and_proto_agree():
    main, _, _ = _small_program()
    via_json = Program.parse_from_string(main.serialize_to_string())
    via_proto = Program.parse_from_string(
        main.serialize_to_string(format="proto"))
    assert via_json.fingerprint() == via_proto.fingerprint()


def test_attr_type_fidelity():
    p = Program()
    b = p.global_block()
    b.create_var("x", [2, 2])
    attrs = {"i": 7, "f": 0.5, "s": "hello", "b_true": True, "b_false": False,
             "ints": [1, 2, 3], "floats": [1.5, 2.5], "strs": ["a", "b"],
             "bools": [True, False], "empty": [],
             "nested": {"k": [1, 2], "s": "v"}, "none": None}
    b.append_op("scale", {"X": ["x"]}, {"Out": ["x"]}, dict(attrs))
    clone = Program.parse_from_string(p.serialize_to_string(format="proto"))
    got = clone.global_block().ops[0].attrs
    for k, v in attrs.items():
        assert got[k] == v, (k, got[k], v)
    assert isinstance(got["i"], int) and isinstance(got["f"], float)
    assert got["b_true"] is True and got["none"] is None


def test_mixed_type_lists_and_var_type_roundtrip():
    p = Program()
    b = p.global_block()
    v = b.create_var("rows", [10, 4])
    v.attrs["var_type"] = "SELECTED_ROWS"
    b.append_op("scale", {"X": ["rows"]}, {"Out": ["rows"]},
                {"mixed_if": [1, 2.5], "mixed_bi": [True, 2]})
    for fmt in ("json", "proto"):
        clone = Program.parse_from_string(p.serialize_to_string(format=fmt))
        got = clone.global_block().ops[0].attrs
        assert got["mixed_if"] == [1, 2.5], (fmt, got)
        assert got["mixed_bi"] == [True, 2], (fmt, got)
        assert clone.global_block().var("rows").attrs["var_type"] == \
            "SELECTED_ROWS", fmt
        # survives a second serialize (write side reads the same place)
        again = Program.parse_from_string(
            clone.serialize_to_string(format=fmt))
        assert again.global_block().var("rows").attrs["var_type"] == \
            "SELECTED_ROWS", fmt


def test_op_version_upgrade_on_load():
    # a program saved before lookup_table_v2 v2 (no is_sparse attr, no
    # op_versions map) must load with the v1-behaviour default filled in
    p = Program()
    b = p.global_block()
    b.create_var("W", [10, 4], is_parameter=True, persistable=True)
    b.create_var("Ids", [2, 3], dtype="int64")
    b.create_var("Out", [2, 3, 4])
    b.append_op("lookup_table_v2", {"W": ["W"], "Ids": ["Ids"]},
                {"Out": ["Out"]}, {"padding_idx": -1})
    import json
    d = json.loads(p.serialize_to_string().decode())
    d.pop("op_versions", None)                      # simulate v1 artifact
    for od in d["blocks"][0]["ops"]:
        od["attrs"].pop("is_sparse", None)
    clone = Program.parse_from_string(json.dumps(d).encode())
    op = clone.global_block().ops[0]
    assert op.attrs["is_sparse"] is False


def test_op_version_registry_rules():
    reg = op_version.OpVersionRegistry()
    reg.register("myop", 2, renamed_attrs={"old": "new"})
    reg.register("myop", 3, new_attrs={"extra": 5}, deleted_attrs=["dead"])
    assert reg.version("myop") == 3
    assert reg.version("other") == 1
    attrs = reg.upgrade("myop", {"old": 1, "dead": 2}, saved_version=1)
    assert attrs == {"new": 1, "extra": 5}
    # already-current attrs untouched
    attrs = reg.upgrade("myop", {"new": 1, "extra": 9}, saved_version=3)
    assert attrs == {"new": 1, "extra": 9}
    # monotonic version enforcement
    import pytest
    with pytest.raises(ValueError):
        reg.register("myop", 3)


def test_tensor_codec_bf16_roundtrip():
    # TPU checkpoints are predominantly bf16, which numpy cannot express
    # natively: the codec stores a uint16 bit view + dtype tag and must
    # round-trip BIT-exactly (core/serialization.py encode/decode_tensor)
    import ml_dtypes
    from paddle_tpu.core.serialization import (
        decode_tensor, encode_tensor, tensor_from_bytes, tensor_to_bytes)
    rng = np.random.RandomState(3)
    a = rng.randn(5, 7).astype(ml_dtypes.bfloat16)
    view, tag = encode_tensor(a)
    assert tag == "bfloat16" and view.dtype == np.uint16
    back = decode_tensor(view, tag)
    assert back.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(back.view(np.uint16), a.view(np.uint16))
    # bytes container round-trip, native dtypes included
    for arr in (a, rng.randn(3).astype(np.float32),
                rng.randint(0, 9, (2, 2)).astype(np.int32),
                np.float32(2.5).reshape(())):
        out = tensor_from_bytes(tensor_to_bytes(arr))
        assert out.dtype == arr.dtype and out.shape == np.shape(arr)
        # must own its memory (not alias the input bytes): a read-only
        # frombuffer view would be zero-copy aliased by jnp.asarray and
        # freed by donate_argnums out from under the caller
        assert out.flags.writeable
        np.testing.assert_array_equal(
            out.view(np.uint16) if out.dtype == ml_dtypes.bfloat16
            else out,
            arr.view(np.uint16) if out.dtype == ml_dtypes.bfloat16
            else arr)


def test_tensor_codec_rejects_truncation():
    import pytest
    from paddle_tpu.core.serialization import (
        tensor_from_bytes, tensor_to_bytes)
    blob = tensor_to_bytes(np.arange(64, dtype=np.float32))
    with pytest.raises(ValueError):
        tensor_from_bytes(blob[:-8])
    with pytest.raises(ValueError):
        tensor_from_bytes(b"XXXX" + blob[4:])


def test_accumulator_link_survives_binary_roundtrip():
    # accum_of (optimizer accumulator -> param) feeds sharding inheritance
    # in CompiledProgram; it must survive serialization or the name-prefix
    # heuristic silently comes back
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4])
        y = layers.data("y", [-1, 1])
        loss = layers.mean(layers.square(layers.fc(x, 1) - y))
        static.Adam(1e-3).minimize(loss)
    links = {v.name: v.attrs["accum_of"]
             for b in main.blocks for v in b.vars.values()
             if v.attrs.get("accum_of")}
    assert links, "Adam must register accumulator links"
    m2 = static.Program.parse_from_string(main.serialize_to_string())
    links2 = {v.name: v.attrs.get("accum_of")
              for b in m2.blocks for v in b.vars.values()
              if v.attrs.get("accum_of")}
    assert links2 == links

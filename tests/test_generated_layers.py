"""Auto-generated layer surface (static/layer_generator.py —
layer_function_generator.py analog): build + execute a representative
sample through the static executor."""
import numpy as np

import paddle_tpu.static as static
from paddle_tpu.static import layers


def _run(build_fn, feeds):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        outs = build_fn()
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        res = exe.run(main, feed=feeds, fetch_list=list(outs))
    return [np.asarray(r) for r in res]


def test_generated_count():
    assert len(layers._GENERATED_LAYERS) >= 100
    # hand-written layers are never shadowed by generated ones
    assert "fc" not in layers._GENERATED_LAYERS
    assert "dropout" not in layers._GENERATED_LAYERS


def test_generated_unary_binary():
    x = np.random.RandomState(0).rand(4, 5).astype(np.float32)
    y = np.random.RandomState(1).rand(4, 5).astype(np.float32)

    def build():
        xv = layers.data("x", [-1, 5])
        yv = layers.data("y", [-1, 5])
        return (layers.acos(layers.clip(xv, min=-0.9, max=0.9)),
                layers.dot(xv, yv),
                layers.erf(xv))

    a, d, e = _run(build, {"x": x, "y": y})
    np.testing.assert_allclose(a, np.arccos(np.clip(x, -0.9, 0.9)),
                               rtol=1e-5)
    np.testing.assert_allclose(d.reshape(-1), (x * y).sum(1), rtol=1e-5)


def test_generated_attr_ops():
    x = np.random.RandomState(2).rand(3, 7).astype(np.float32)

    def build():
        xv = layers.data("x", [-1, 7])
        return (layers.arg_max(xv, axis=1),
                layers.flip(xv, axis=[1]),
                layers.log_loss(layers.sigmoid(xv[:, :1]),
                                layers.ones([3, 1], "float32"))
                if hasattr(layers, "log_loss") else layers.arg_min(xv,
                                                                   axis=1),
                )

    am, fl, _ = _run(build, {"x": x})
    np.testing.assert_array_equal(am.reshape(-1), x.argmax(1))
    np.testing.assert_allclose(fl, x[:, ::-1], rtol=1e-6)


def test_generated_matmul_family():
    rng = np.random.RandomState(3)
    a = rng.rand(2, 3, 4).astype(np.float32)
    b = rng.rand(2, 4, 5).astype(np.float32)

    def build():
        av = layers.data("a", [-1, 3, 4])
        bv = layers.data("b", [-1, 4, 5])
        return (layers.bmm(av, bv),)

    (out,) = _run(build, {"a": a, "b": b})
    np.testing.assert_allclose(out, a @ b, rtol=1e-5)


def test_generated_interp():
    x = np.random.RandomState(4).rand(1, 3, 8, 8).astype(np.float32)

    def build():
        xv = layers.data("x", [-1, 3, 8, 8])
        return (layers.bilinear_interp_v2(xv, None, None, None,
                                          out_h=16, out_w=16),)

    (out,) = _run(build, {"x": x})
    assert out.shape == (1, 3, 16, 16)


def test_generated_grad_flows():
    # generated layers participate in autodiff like hand-written ones
    x = np.random.RandomState(5).rand(4, 5).astype(np.float32)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        xv = layers.data("x", [-1, 5])
        h = layers.fc(xv, 6)
        loss = layers.mean(layers.erf(h))
        static.SGD(learning_rate=0.1).minimize(loss)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        l0 = float(np.asarray(
            exe.run(main, feed={"x": x}, fetch_list=[loss])[0]))
        for _ in range(10):
            lv = exe.run(main, feed={"x": x}, fetch_list=[loss])[0]
    assert float(np.asarray(lv)) < l0

"""HDFSClient tested against a PATH-shimmed fake `hadoop` binary.

The shim maps HDFS paths onto a local sandbox directory and implements the
`hadoop fs` subcommands the client issues (-test/-ls/-mkdir/-rm/-mv/-touchz/
-put/-get), so ls/upload/download/mv round-trip without a cluster.
Reference behavior: /root/reference/python/paddle/distributed/fleet/utils/fs.py
"""
import os
import stat
import subprocess
import sys

import pytest

from paddle_tpu.distributed.fleet.utils.fs import (
    FSFileExistsError, FSFileNotExistsError, HDFSClient, LocalFS)

FAKE_HADOOP = r'''#!/usr/bin/env python3
"""Fake `hadoop fs` CLI mapping hdfs paths into $FAKE_HDFS_ROOT."""
import os, shutil, sys

root = os.environ["FAKE_HDFS_ROOT"]

def local(p):
    return os.path.join(root, p.lstrip("/"))

args = sys.argv[1:]
assert args and args[0] == "fs", args
args = args[1:]
# strip -D k=v config pairs
while args and args[0] == "-D":
    args = args[2:]
cmd, rest = args[0], args[1:]
if cmd == "-test":
    flag, path = rest
    p = local(path)
    ok = os.path.isdir(p) if flag == "-d" else os.path.exists(p)
    sys.exit(0 if ok else 1)
elif cmd == "-ls":
    p = local(rest[0])
    if not os.path.exists(p):
        sys.exit(1)
    for name in sorted(os.listdir(p)):
        full = os.path.join(p, name)
        kind = "d" if os.path.isdir(full) else "-"
        print(f"{kind}rwxr-xr-x 1 u g 0 2026-01-01 00:00 {rest[0].rstrip('/')}/{name}")
elif cmd == "-mkdir":
    os.makedirs(local(rest[-1]), exist_ok=True)
elif cmd == "-rm":
    p = local(rest[-1])
    if os.path.isdir(p):
        shutil.rmtree(p, ignore_errors=True)
    elif os.path.exists(p):
        os.remove(p)
elif cmd == "-mv":
    src, dst = local(rest[0]), local(rest[1])
    if not os.path.exists(src) or os.path.exists(dst):
        sys.exit(1)
    shutil.move(src, dst)
elif cmd == "-touchz":
    open(local(rest[0]), "a").close()
elif cmd == "-put":
    rest = [a for a in rest if a != "-f"]
    dst = local(rest[1])
    os.makedirs(os.path.dirname(dst), exist_ok=True)
    shutil.copy(rest[0], dst)
elif cmd == "-get":
    src = local(rest[0])
    if not os.path.exists(src):
        sys.exit(1)
    shutil.copy(src, rest[1])
else:
    sys.exit(2)
'''


@pytest.fixture
def hdfs(tmp_path, monkeypatch):
    """An HDFSClient wired to a fake hadoop shim over a sandbox dir."""
    bin_dir = tmp_path / "bin"
    bin_dir.mkdir()
    shim = bin_dir / "hadoop"
    shim.write_text(FAKE_HADOOP)
    shim.chmod(shim.stat().st_mode | stat.S_IEXEC)
    hdfs_root = tmp_path / "hdfs_root"
    hdfs_root.mkdir()
    monkeypatch.setenv("PATH", f"{bin_dir}:{os.environ['PATH']}")
    monkeypatch.setenv("FAKE_HDFS_ROOT", str(hdfs_root))
    return HDFSClient(configs={"fs.default.name": "hdfs://fake:9000"})


def test_hdfs_mkdir_exist_isdir(hdfs):
    assert not hdfs.is_exist("/data")
    hdfs.mkdirs("/data/sub")
    assert hdfs.is_exist("/data/sub")
    assert hdfs.is_dir("/data/sub")
    assert not hdfs.is_file("/data/sub")


def test_hdfs_upload_download_roundtrip(hdfs, tmp_path):
    src = tmp_path / "model.bin"
    src.write_bytes(b"\x00weights\x01")
    hdfs.mkdirs("/ckpt")
    hdfs.upload(str(src), "/ckpt/model.bin")
    assert hdfs.is_file("/ckpt/model.bin")
    dst = tmp_path / "fetched.bin"
    hdfs.download("/ckpt/model.bin", str(dst))
    assert dst.read_bytes() == b"\x00weights\x01"


def test_hdfs_ls_dir(hdfs, tmp_path):
    hdfs.mkdirs("/job/output")
    f = tmp_path / "log.txt"
    f.write_text("ok")
    hdfs.upload(str(f), "/job/log.txt")
    dirs, files = hdfs.ls_dir("/job")
    assert dirs == ["output"]
    assert files == ["log.txt"]


def test_hdfs_mv_touch_delete(hdfs, tmp_path):
    hdfs.mkdirs("/a")
    hdfs.touch("/a/x")
    assert hdfs.is_file("/a/x")
    hdfs.mv("/a/x", "/a/y")
    assert not hdfs.is_exist("/a/x")
    assert hdfs.is_file("/a/y")
    # mv without overwrite refuses an existing destination
    hdfs.touch("/a/x")
    with pytest.raises(FSFileExistsError):
        hdfs.mv("/a/x", "/a/y")
    # mv with overwrite replaces the destination
    hdfs.mv("/a/x", "/a/y", overwrite=True)
    assert not hdfs.is_exist("/a/x")
    assert hdfs.is_file("/a/y")
    hdfs.delete("/a")
    assert not hdfs.is_exist("/a")


def test_hdfs_unavailable_raises_cleanly(monkeypatch, tmp_path):
    monkeypatch.setenv("PATH", str(tmp_path))  # no hadoop anywhere
    client = HDFSClient()
    with pytest.raises(FSFileNotExistsError):
        client.is_exist("/whatever")


def test_localfs_mv_no_overwrite(tmp_path):
    fs = LocalFS()
    a, b = tmp_path / "a", tmp_path / "b"
    fs.touch(str(a))
    fs.touch(str(b))
    with pytest.raises(FSFileExistsError):
        fs.mv(str(a), str(b))
    fs.mv(str(a), str(b), overwrite=True)
    assert not fs.is_exist(str(a))

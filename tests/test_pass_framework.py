"""Pass-framework tests (core/pass_framework.py — the generalized C16
registry: training-graph passes + BuildStrategy wiring; reference pattern:
ir/*_tester.cc build a tiny graph, apply a pass, assert graph shape)."""
import os

import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers
from paddle_tpu.core.pass_framework import (apply_passes, PassContext,
                                            all_passes, get_pass)


def test_registry_is_shared_with_inference():
    # inference passes and training passes live in one registry
    import paddle_tpu.inference.passes as ip
    names = all_passes()
    assert "fc_fuse_pass" in names            # inference-side
    assert "sync_batch_norm_pass" in names    # training-side
    assert ip.all_passes() == names


def test_sync_batch_norm_pass_rewrites_training_bn():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4, 8, 8])
        h = layers.conv2d(x, 8, 3, padding=1)
        h = layers.batch_norm(h)
        test_h = layers.batch_norm(h)
        test_op = main.global_block().ops[-1]
        test_op.attrs["is_test"] = True       # inference bn must be left alone
    ctx = PassContext()
    out = apply_passes(main, ["sync_batch_norm_pass"], ctx)
    types = [op.type for op in out.global_block().ops]
    assert types.count("sync_batch_norm") == 1
    assert types.count("batch_norm") == 1
    assert ctx.stats["sync_batch_norm_pass"] == 1
    sbn = next(op for op in out.global_block().ops
               if op.type == "sync_batch_norm")
    assert sbn.attrs["ring_id"] == 0


def test_sync_batch_norm_via_build_strategy_runs():
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs multi-device mesh")
    from paddle_tpu.distributed.compiled_program import (CompiledProgram,
                                                         BuildStrategy)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 8)
        h = layers.batch_norm(h)
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.SGD(learning_rate=0.01).minimize(loss)
    bs = BuildStrategy()
    bs.sync_batch_norm = True
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        cp = CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, build_strategy=bs)
        rng = np.random.RandomState(0)
        for _ in range(3):
            (lv,) = exe.run(cp, feed={
                "x": rng.rand(16, 4).astype(np.float32),
                "y": rng.rand(16, 1).astype(np.float32)},
                fetch_list=[loss])
        assert np.isfinite(np.asarray(lv)).all()
    # the executed program really got the rewrite — including the grad op,
    # whose vjp replays the forward and must see the synced statistics
    types = [op.type for op in cp._get_program().global_block().ops]
    assert "sync_batch_norm" in types and "batch_norm" not in types
    assert "sync_batch_norm_grad" in types and \
        "batch_norm_grad" not in types


def test_graphviz_without_data_parallel(tmp_path):
    # BuildStrategy knobs must work on a plain CompiledProgram too
    from paddle_tpu.distributed.compiled_program import (CompiledProgram,
                                                         BuildStrategy)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4])
        layers.fc(x, 2)
    bs = BuildStrategy()
    path = str(tmp_path / "plain.dot")
    bs.debug_graphviz_path = path
    cp = CompiledProgram(main, build_strategy=bs)
    cp._get_program()
    assert "digraph" in open(path).read()


def test_dead_code_elimination():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4])
        live = layers.fc(x, 2)
        dead = layers.scale(layers.fc(x, 3), scale=2.0)  # nothing reads it
    main._fetch_names = [live.name]
    n_before = len(main.global_block().ops)
    ctx = PassContext()
    out = apply_passes(main, ["dead_code_elimination_pass"], ctx)
    n_after = len(out.global_block().ops)
    assert ctx.stats["dead_code_elimination_pass"] >= 2  # fc chain + scale
    assert n_after < n_before
    names = {n for op in out.global_block().ops for n in op.output_names()}
    assert dead.name not in names
    assert live.name in names


def test_dead_code_elimination_refuses_without_roots():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4])
        layers.fc(x, 2)
    n = len(main.global_block().ops)
    out = apply_passes(main, ["dead_code_elimination_pass"], PassContext())
    assert len(out.global_block().ops) == n  # no roots -> no-op, not wipeout


def test_graph_viz_pass(tmp_path):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4])
        layers.fc(x, 2)
    path = str(tmp_path / "g.dot")
    apply_passes(main, ["graph_viz_pass"], PassContext(graph_viz_path=path))
    dot = open(path).read()
    assert "digraph" in dot and "mul" in dot

"""Numpy checks for the registry-diff mop-up ops (ops/kernels/mop_up.py)
+ the scripted diff itself staying at zero residue."""
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.ops.registry import OpContext, run_kernel


def _run(op, ins, attrs=None):
    return run_kernel(op, ins, attrs or {}, OpContext())


def test_registry_diff_residue_is_zero():
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(root, "tools", "registry_diff.py")],
        capture_output=True, text=True, cwd=root)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "REAL GAPS:             0" in out.stdout, out.stdout


def test_batch_fc_matches_loop():
    rng = np.random.RandomState(0)
    x = rng.randn(3, 4, 5).astype(np.float32)
    w = rng.randn(3, 5, 6).astype(np.float32)
    b = rng.randn(3, 6).astype(np.float32)
    out = _run("batch_fc", {"Input": jnp.asarray(x), "W": jnp.asarray(w),
                            "Bias": jnp.asarray(b)})["Out"]
    ref = np.stack([x[s] @ w[s] + b[s] for s in range(3)])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5)


def test_rank_attention_matches_loop():
    rng = np.random.RandomState(1)
    ins_num, fea, para_col, max_rank = 4, 3, 2, 2
    x = rng.randn(ins_num, fea).astype(np.float32)
    param = rng.randn(max_rank * max_rank * fea,
                      para_col).astype(np.float32)
    # rows: rank, (faster_1, index_1), (faster_2, index_2); 1-based
    ro = np.array([[1, 1, 0, 2, 1],
                   [2, 1, 2, 0, 0],      # second slot absent
                   [0, 0, 0, 0, 0],      # invalid instance
                   [2, 2, 3, 1, 1]], np.int32)
    outs = _run("rank_attention",
                {"X": jnp.asarray(x), "RankOffset": jnp.asarray(ro),
                 "RankParam": jnp.asarray(param)}, {"MaxRank": max_rank})
    ref = np.zeros((ins_num, para_col), np.float32)
    p3 = param.reshape(max_rank * max_rank, fea, para_col)
    for i in range(ins_num):
        rank = ro[i, 0]
        if rank <= 0:
            continue
        for k in range(max_rank):
            faster, index = ro[i, 1 + 2 * k], ro[i, 2 + 2 * k]
            if faster <= 0:
                continue
            blk = p3[(rank - 1) * max_rank + (faster - 1)]
            ref[i] += x[index] @ blk
    np.testing.assert_allclose(np.asarray(outs["Out"]), ref, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(outs["InsRank"]).ravel(),
                               ro[:, 0].astype(np.float32))


def test_bilateral_slice_constant_grid_identity():
    """A grid whose coefficients are an identity affine map must return
    the input unchanged regardless of the guide."""
    rng = np.random.RandomState(2)
    n, ci, h, w = 1, 2, 4, 4
    co, gd, gh, gw = 2, 3, 2, 2
    x = rng.rand(n, ci, h, w).astype(np.float32)
    guide = rng.rand(n, h, w).astype(np.float32)
    grid = np.zeros((n, co * (ci + 1), gd, gh, gw), np.float32)
    for c in range(co):                   # out c = in c (identity matrix)
        grid[:, c * (ci + 1) + c] = 1.0
    out = _run("bilateral_slice",
               {"X": jnp.asarray(x), "Grid": jnp.asarray(grid),
                "Guide": jnp.asarray(guide)}, {"has_offset": True})["Out"]
    np.testing.assert_allclose(np.asarray(out), x, rtol=1e-5, atol=1e-5)


def test_deformable_psroi_pooling_no_trans_matches_average():
    """With no offsets and a single group, every bin averages its
    bilinear samples of the (only) channel slice."""
    rng = np.random.RandomState(3)
    x = rng.rand(1, 2, 8, 8).astype(np.float32)
    rois = np.array([[0, 0, 0, 7, 7]], np.float32)
    outs = _run("deformable_psroi_pooling",
                {"Input": jnp.asarray(x), "ROIs": jnp.asarray(rois)},
                {"no_trans": True, "spatial_scale": 1.0, "output_dim": 2,
                 "group_size": [1, 1], "pooled_height": 2,
                 "pooled_width": 2, "part_size": [2, 2],
                 "sample_per_part": 2, "trans_std": 0.0})
    out = np.asarray(outs["Out"])
    assert out.shape == (1, 2, 2, 2)
    # channel mapping with group 1: output c reads input channel c
    assert np.all(np.asarray(outs["TopCount"]) > 0)
    # bins over the whole roi stay within data range (bilinear average)
    assert out.min() >= x.min() - 1e-5 and out.max() <= x.max() + 1e-5
    # spot value: bin (0,0) of channel 0 averages 4 samples around the
    # upper-left quadrant — recompute directly
    ref = 0.0
    x1, y1 = -0.5, -0.5
    bin_w = bin_h = (7.5 - (-0.5)) / 2
    sub = bin_w / 2
    cnt = 0
    for ih in range(2):
        for iw in range(2):
            wp, hp = x1 + iw * sub, y1 + ih * sub
            if wp < -0.5 or wp > 7.5 or hp < -0.5 or hp > 7.5:
                continue
            wc, hc = np.clip(wp, 0, 7), np.clip(hp, 0, 7)
            x1i, y1i = int(np.floor(wc)), int(np.floor(hc))
            x2i, y2i = min(x1i + 1, 7), min(y1i + 1, 7)
            dx, dy = wc - x1i, hc - y1i
            v = (x[0, 0, y1i, x1i] * (1 - dx) * (1 - dy)
                 + x[0, 0, y1i, x2i] * dx * (1 - dy)
                 + x[0, 0, y2i, x1i] * (1 - dx) * dy
                 + x[0, 0, y2i, x2i] * dx * dy)
            ref += v
            cnt += 1
    np.testing.assert_allclose(out[0, 0, 0, 0], ref / cnt, rtol=1e-5)


def test_quant_tail_ops():
    rng = np.random.RandomState(4)
    q = rng.randint(-127, 128, (3, 4)).astype(np.int8)
    s = np.float32(2.5)
    out = _run("dequantize_abs_max",
               {"X": jnp.asarray(q), "Scale": jnp.asarray([s])},
               {"max_range": 127.0})["Out"]
    np.testing.assert_allclose(np.asarray(out),
                               q.astype(np.float32) * s / 127.0,
                               rtol=1e-6)
    d = np.linspace(0.0, 1.0, 128).astype(np.float32)
    codes = np.array([[3, -5, 0, -128]], np.int8)
    out = _run("dequantize_log",
               {"X": jnp.asarray(codes), "Dict": jnp.asarray(d)})["Out"]
    ref = np.array([[d[3], -d[123], d[0], -d[0]]], np.float32)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)

    x = rng.randn(4, 4).astype(np.float32)
    outs = _run("fake_quantize_range_abs_max",
                {"X": jnp.asarray(x),
                 "InScale": jnp.asarray([0.001], np.float32)},
                {"bit_length": 8})
    scale = float(np.asarray(outs["OutScale"]).ravel()[0])
    assert scale == pytest.approx(np.abs(x).max(), rel=1e-6)
    np.testing.assert_allclose(
        np.asarray(outs["Out"]),
        np.clip(np.round(x / scale * 127), -127, 127) * scale / 127,
        rtol=1e-5)


def test_lookup_table_dequant():
    # rows: [min, max, 4 packed uint8 codes in one float32]
    emb = 4
    codes = np.array([7, 130, 255, 0], np.uint8)
    packed = codes.view(np.float32)[0]
    row = np.array([[-1.0, 1.0, packed]], np.float32)
    out = _run("lookup_table_dequant",
               {"W": jnp.asarray(row),
                "Ids": jnp.asarray([0], np.int64)},
               {"quant_bits": 8})["Out"]
    scale = 2.0 / 256.0
    ref = scale * codes.astype(np.float32) - 1.0
    np.testing.assert_allclose(np.asarray(out).ravel()[:emb], ref,
                               rtol=1e-5)


def test_dgc_momentum_switches_at_rampup():
    p = jnp.ones((4,))
    g = jnp.full((4,), 0.5)
    v = jnp.full((4,), 0.2)
    lr = jnp.asarray([0.1])
    common = {"Param": p, "Grad": g, "Velocity": v, "LearningRate": lr}
    pre = _run("dgc_momentum",
               {**common, "current_step": jnp.asarray([1.0])},
               {"mu": 0.9, "rampup_begin_step": 10.0})
    v_new = 0.9 * 0.2 + 0.5
    np.testing.assert_allclose(np.asarray(pre["ParamOut"]),
                               1.0 - 0.1 * v_new, rtol=1e-6)
    post = _run("dgc_momentum",
                {**common, "current_step": jnp.asarray([11.0])},
                {"mu": 0.9, "rampup_begin_step": 10.0})
    np.testing.assert_allclose(np.asarray(post["ParamOut"]),
                               1.0 - 0.1 * 0.5, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(post["VelocityOut"]), 0.2)

    x = jnp.asarray(np.full((4,), 3.0, np.float32))
    clip = _run("dgc_clip_by_norm",
                {"X": x, "current_step": jnp.asarray([5.0])},
                {"max_norm": 1.0, "rampup_begin_step": 0.0})["Out"]
    np.testing.assert_allclose(np.linalg.norm(np.asarray(clip)), 1.0,
                               rtol=1e-5)


def test_fill_family():
    out = _run("fill", {}, {"shape": [2, 2], "dtype": "float32",
                            "value": [1.0, 2.0, 3.0, 4.0]})["Out"]
    np.testing.assert_allclose(np.asarray(out), [[1, 2], [3, 4]])
    z = _run("fill_zeros_like2", {"X": jnp.ones((2, 3))},
             {"dtype": "int32"})["Out"]
    assert np.asarray(z).dtype == np.int32 and not np.asarray(z).any()
    g = _run("gaussian_random_batch_size_like",
             {"Input": jnp.zeros((5, 2))},
             {"shape": [-1, 8], "mean": 0.0, "std": 1.0,
              "op_uid": 7})["Out"]
    assert np.asarray(g).shape == (5, 8)
    f = _run("fake_init", {}, {"shape": [3, 2]})["Out"]
    assert np.asarray(f).shape == (3, 2)


def test_tensor_array_to_tensor_and_aliases():
    from paddle_tpu.ops.kernels.tensor_array import TensorArrayVal
    buf = jnp.arange(24.0).reshape(3, 2, 4)
    arr = TensorArrayVal(buf, jnp.asarray(3, jnp.int32))
    stacked = _run("tensor_array_to_tensor", {"X": arr},
                   {"use_stack": True, "axis": 0})["Out"]
    np.testing.assert_allclose(np.asarray(stacked), np.asarray(buf))
    cat = _run("tensor_array_to_tensor", {"X": arr},
               {"use_stack": False, "axis": 0})["Out"]
    assert np.asarray(cat).shape == (6, 4)
    from paddle_tpu.ops.registry import get_op_info
    for alias in ("conditional_block_infer", "merge_lod_tensor_infer",
                  "multiclass_nms2", "recurrent", "run_program",
                  "delete_var", "get_places", "send_barrier", "recv_save",
                  "send_and_recv", "pull_sparse", "pull_sparse_v2",
                  "push_sparse", "push_sparse_v2", "push_dense"):
        assert get_op_info(alias) is not None, alias


def test_split_selected_rows_and_merge_ids():
    from paddle_tpu.core.selected_rows import SelectedRows
    sr = SelectedRows(jnp.asarray([1, 5, 3], jnp.int32),
                      jnp.asarray([[1.0], [5.0], [3.0]]), 8)
    outs = _run("split_selected_rows", {"X": sr},
                {"height_sections": [4, 4]})["Out"]
    d0 = np.asarray(outs[0].to_dense()).ravel()
    d1 = np.asarray(outs[1].to_dense()).ravel()
    np.testing.assert_allclose(d0, [0, 1, 0, 3])
    np.testing.assert_allclose(d1, [0, 5, 0, 0])

    merged = _run(
        "merge_ids",
        {"Ids": [jnp.asarray([3, 1, 5, 1], jnp.int64)],
         "Rows": [jnp.asarray([1, 3], jnp.int64),
                  jnp.asarray([5], jnp.int64)],
         "X": [jnp.asarray([[10.0], [30.0]]),
               jnp.asarray([[50.0]])]})["Out"][0]
    np.testing.assert_allclose(np.asarray(merged).ravel(),
                               [30, 10, 50, 10])


def test_box_sparse_pull_push():
    """BoxPS redesign: the 'device-resident PS' is a dense HBM table."""
    w = jnp.arange(12.0).reshape(6, 2)
    ids = jnp.asarray([[1, 4]], jnp.int64)
    (out,) = _run("pull_box_sparse", {"Ids": [ids], "W": w})["Out"]
    np.testing.assert_allclose(np.asarray(out),
                               [[[2, 3], [8, 9]]])
    g = jnp.ones((1, 2, 2))
    new_w = _run("push_box_sparse",
                 {"Ids": [ids], "Grads": [g], "W": w},
                 {"lr": 0.5})["Out"]
    ref = np.arange(12.0).reshape(6, 2)
    ref[1] -= 0.5
    ref[4] -= 0.5
    np.testing.assert_allclose(np.asarray(new_w), ref)


def test_send_and_recv_round_trip_over_kv_queues():
    import threading

    from paddle_tpu.distributed.ps.kv_server import KVClient, KVServer
    srv = KVServer("127.0.0.1:0")
    srv.serve_in_thread()
    try:
        # a fake peer section: pops the sent tensor, replies doubled
        def peer():
            c = KVClient([srv.endpoint], rpc_deadline=20.0)
            c.wait_server_ready()
            a = c.q_pop("heter/xin", timeout=30.0)
            c.q_push("heter/yout", a * 2.0)
            c.close()

        t = threading.Thread(target=peer)
        t.start()
        outs = _run("send_and_recv",
                    {"X": [jnp.asarray([[1.0, 2.0]])]},
                    {"send_var_name": ["xin"],
                     "recv_var_name": ["yout"],
                     "endpoints": [srv.endpoint],
                     "shapes": [[1, 2]], "dtypes": ["float32"],
                     "timeout": 30.0})["Out"]
        t.join(timeout=30)
        np.testing.assert_allclose(np.asarray(outs[0]), [[2.0, 4.0]])
    finally:
        srv.stop()

"""Multi-host rehearsal (VERDICT #10): distributed/launch.py spawns two
"host" worker processes with the PADDLE_* env contract (reference harness
pattern fluid/tests/unittests/test_dist_base.py:785); each builds a fleet
collective job from its env-derived role, trains on a CPU mesh, and
cross-checks its losses with its peer through the KV server.  The test
then compares against a single-host run."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest


def test_launch_two_hosts_losses_match_single(tmp_path):
    from paddle_tpu.distributed.launch_utils import (
        find_free_ports, get_cluster, start_local_trainers,
        terminate_procs)
    from paddle_tpu.distributed.ps.kv_server import KVServer

    # rendezvous KV server owned by the test (the "PS heart" of the job)
    srv = KVServer("127.0.0.1:0", num_trainers=2)
    srv.serve_in_thread()

    script = os.path.join(os.path.dirname(__file__), "launch_worker.py")
    ports = find_free_ports(2)
    endpoints = [[f"127.0.0.1:{p}"] for p in ports]
    # two "hosts" (node ips both local; one proc each)
    cluster, pod0 = get_cluster(["127.0.0.1", "127.0.0.2"], "127.0.0.1",
                                endpoints, [[0]])
    assert cluster.trainers_nranks() == 2
    procs = []
    try:
        for pod in cluster.pods:
            # per-pod log dirs: both pods have local_rank 0, so a shared
            # dir would interleave their workerlog.0 files
            procs.extend(start_local_trainers(
                cluster, pod, script, [str(tmp_path), srv.endpoint],
                log_dir=str(tmp_path / "logs" / f"pod{pod.id}")))
        deadline = time.time() + 240
        while time.time() < deadline:
            if all(tp.proc.poll() is not None for tp in procs):
                break
            time.sleep(0.5)
        rcs = [tp.proc.poll() for tp in procs]
        logs = ""
        for pod_dir in sorted((tmp_path / "logs").glob("*/workerlog.*")):
            logs += f"\n--- {pod_dir}:\n" + pod_dir.read_text()[-2000:]
        assert all(rc == 0 for rc in rcs), f"worker rcs={rcs}\n{logs}"
    finally:
        terminate_procs(procs)
        srv.stop()

    results = {}
    for r in range(2):
        with open(tmp_path / f"rank{r}.json") as f:
            results[r] = json.load(f)
    assert results[0]["nranks"] == results[1]["nranks"] == 2
    np.testing.assert_allclose(results[0]["losses"], results[1]["losses"],
                               rtol=1e-5)

    # single-host reference run (same fixed data/seeds)
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    main_p, startup = static.Program(), static.Program()
    with static.program_guard(main_p, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        pred = layers.fc(x, 1, param_attr=static.ParamAttr(
            initializer=static.Constant(0.0)))
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.SGD(learning_rate=0.05).minimize(loss)
    rng = np.random.RandomState(42)
    xb = rng.rand(16, 8).astype(np.float32)
    yb = xb.sum(1, keepdims=True).astype(np.float32)
    exe = static.Executor()
    with static.scope_guard(static.Scope()):
        exe.run(startup)
        single = [float(exe.run(main_p, feed={"x": xb, "y": yb},
                                fetch_list=[loss])[0]) for _ in range(5)]
    np.testing.assert_allclose(results[0]["losses"], single,
                               rtol=1e-4, atol=1e-6)

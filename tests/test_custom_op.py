"""Out-of-tree custom op: compile a .cc with the host toolchain, load it,
use the op in a static program with gradients (reference:
fluid/tests/custom_op/ relu_op.cc + load_op_library)."""
import shutil
import subprocess

import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers
from paddle_tpu.static.layer_helper import LayerHelper

RELU_CC = r"""
#include <cstdint>
#include <cstring>

extern "C" {

int ptpu_num_ops() { return 1; }

const char* ptpu_op_name(int) { return "custom_relu"; }

void ptpu_forward(int, const float* x, float* y, int64_t n) {
  for (int64_t i = 0; i < n; ++i) y[i] = x[i] > 0.f ? x[i] : 0.f;
}

int ptpu_has_backward(int) { return 1; }

void ptpu_backward(int, const float* x, const float* dy, float* dx,
                   int64_t n) {
  for (int64_t i = 0; i < n; ++i) dx[i] = x[i] > 0.f ? dy[i] : 0.f;
}

}  // extern "C"
"""


@pytest.fixture(scope="module")
def relu_lib(tmp_path_factory):
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    d = tmp_path_factory.mktemp("custom_op")
    src = d / "relu_op.cc"
    src.write_text(RELU_CC)
    from paddle_tpu.utils.cpp_extension import (build_op_library,
                                                load_op_library)
    so = build_op_library(str(src))
    return load_op_library(so)


def test_custom_op_forward_backward(relu_lib):
    assert relu_lib == ["custom_relu"]
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 6])
        w = layers.fc(x, 6)
        helper = LayerHelper("custom_relu")
        out = helper.create_variable_for_type_inference("float32")
        helper.append_op("custom_relu", {"X": [w]}, {"Out": [out]}, {})
        loss = layers.mean(out)
        static.SGD(learning_rate=0.1).minimize(loss)

    rng = np.random.RandomState(0)
    xb = rng.randn(4, 6).astype(np.float32)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        o1, l1 = exe.run(main, feed={"x": xb}, fetch_list=[out, loss])
        # relu semantics from the C++ kernel
        assert np.all(np.asarray(o1) >= 0)
        # gradient flowed through the C++ backward: params changed
        l_prev = float(np.asarray(l1))
        for _ in range(5):
            _, lv = exe.run(main, feed={"x": xb}, fetch_list=[out, loss])
        assert float(np.asarray(lv)) < l_prev


def test_custom_op_matches_numpy(relu_lib):
    import jax.numpy as jnp
    from paddle_tpu.ops.registry import get_op_info, OpContext
    info = get_op_info("custom_relu")
    x = np.random.RandomState(1).randn(3, 5).astype(np.float32)
    out = info.kernel({"X": jnp.asarray(x)}, {}, OpContext())["Out"]
    np.testing.assert_allclose(np.asarray(out), np.maximum(x, 0), rtol=0)

"""Tier-1 paged-KV gate (NOT marked slow — a regression in planner
sizing, prefix sharing, COW isolation, paged decode equality, or the
bounded-compiled-shapes contract must fail the suite, not wait for a
perf round).

Drives tools/page_smoke.py in-process: pool allocated at the
planner-chosen budget (page_budget, never hand-set), two prompts
sharing a head occupying fewer pages than 2x solo, token-equal greedy
decode through the paged engine, and zero post-warmup KV-bucket growth.
Mirrors the mem_smoke/serve_smoke gate pattern; the CLI round-trip is
`slow` (a fresh interpreter buys no extra coverage in-process).
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_page_smoke_gate():
    import page_smoke
    result = page_smoke.run_smoke()
    assert result["traces_after_warmup"] == 0, result
    assert result["shared_pages_for_two"] < 2 * result["solo_pages"], \
        result
    assert result["prefix_hits"] == 2, result
    assert result["pages"] >= 1 and result["max_slots"] >= 1, result
    assert result["value"] < 60, result  # in-process gate stays fast


@pytest.mark.slow
def test_page_smoke_cli_prints_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "page_smoke.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["traces_after_warmup"] == 0
    assert result["shared_pages_for_two"] < 2 * result["solo_pages"]

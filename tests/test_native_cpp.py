"""C27 — C++ test tier: build + run the native assert runner
(paddle_tpu/native/src/native_test.cc), exercising blocking_queue.cc and
tensor_io.cc through their C ABI from C++, below the Python bindings."""
import os
import shutil
import subprocess

import pytest

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "paddle_tpu", "native", "src")


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")
def test_native_cpp_suite(tmp_path):
    exe = str(tmp_path / "native_test")
    build = subprocess.run(
        ["g++", "-O1", "-std=c++17",
         os.path.join(_SRC, "native_test.cc"),
         os.path.join(_SRC, "blocking_queue.cc"),
         os.path.join(_SRC, "tensor_io.cc"),
         "-pthread", "-o", exe],
        capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr
    run = subprocess.run([exe, str(tmp_path / "nt.bin")],
                         capture_output=True, text=True, timeout=120)
    assert run.returncode == 0, run.stdout + run.stderr
    assert "ALL NATIVE TESTS PASSED" in run.stdout

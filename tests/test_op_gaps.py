"""Registry-gap batch tests (round-4 systematic diff vs the reference's
REGISTER_OPERATOR list)."""
import numpy as np
import pytest

from paddle_tpu.ops.registry import run_kernel, OpContext, get_op_info


def _run(op, ins, attrs=None):
    import jax.numpy as jnp
    dev = {k: ([jnp.asarray(x) for x in v] if isinstance(v, list) else
               jnp.asarray(v)) for k, v in ins.items()}
    return run_kernel(op, dev, attrs or {}, OpContext(seed=5))


GAP_OPS = ["label_smooth", "unfold", "segment_pool", "partial_concat",
           "partial_sum", "max_pool3d_with_index",
           "depthwise_conv2d_transpose", "lod_reset", "select_output",
           "get_tensor_from_selected_rows", "merge_selected_rows",
           "save", "load", "save_combine", "load_combine",
           "correlation", "linear_interp_v2", "trilinear_interp_v2"]


def test_registry_probe_gap_ops():
    missing = [op for op in GAP_OPS if get_op_info(op) is None]
    assert not missing, f"unregistered gap ops: {missing}"


def test_label_smooth():
    x = np.eye(4, dtype=np.float32)[:2]
    out = np.asarray(_run("label_smooth", {"X": x},
                          {"epsilon": 0.1})["Out"])
    np.testing.assert_allclose(out, 0.9 * x + 0.1 / 4, rtol=1e-6)
    prior = np.array([0.4, 0.3, 0.2, 0.1], np.float32)
    out = np.asarray(_run("label_smooth", {"X": x, "PriorDist": prior},
                          {"epsilon": 0.1})["Out"])
    np.testing.assert_allclose(out, 0.9 * x + 0.1 * prior, rtol=1e-6)


def test_unfold_matches_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(1, 2, 4, 4).astype(np.float32)
    out = np.asarray(_run("unfold", {"X": x},
                          {"kernel_sizes": [2, 2], "strides": [1, 1],
                           "paddings": [0, 0, 0, 0],
                           "dilations": [1, 1]})["Y"])
    assert out.shape == (1, 8, 9)
    # first patch = x[:, :, 0:2, 0:2] flattened channel-major
    exp0 = x[0, :, 0:2, 0:2].reshape(-1)
    np.testing.assert_allclose(out[0, :, 0], exp0, rtol=1e-6)
    # last patch
    expl = x[0, :, 2:4, 2:4].reshape(-1)
    np.testing.assert_allclose(out[0, :, -1], expl, rtol=1e-6)


def test_segment_pool_modes():
    x = np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]], np.float32)
    ids = np.array([0, 0, 2], np.int64)
    s = np.asarray(_run("segment_pool", {"X": x, "SegmentIds": ids},
                        {"pooltype": "SUM", "num_segments": 3})["Out"])
    np.testing.assert_allclose(s, [[4, 6], [0, 0], [5, 6]])
    m = np.asarray(_run("segment_pool", {"X": x, "SegmentIds": ids},
                        {"pooltype": "MEAN", "num_segments": 3})["Out"])
    np.testing.assert_allclose(m, [[2, 3], [0, 0], [5, 6]])
    mx = np.asarray(_run("segment_pool", {"X": x, "SegmentIds": ids},
                         {"pooltype": "MAX", "num_segments": 3})["Out"])
    np.testing.assert_allclose(mx, [[3, 4], [0, 0], [5, 6]])


def test_partial_concat_and_sum():
    a = np.arange(8, dtype=np.float32).reshape(2, 4)
    b = a + 10
    out = np.asarray(_run("partial_concat", {"X": [a, b]},
                          {"start_index": 1, "length": 2})["Out"])
    np.testing.assert_allclose(out, np.concatenate(
        [a[:, 1:3], b[:, 1:3]], axis=1))
    s = np.asarray(_run("partial_sum", {"X": [a, b]},
                        {"start_index": 1, "length": 2})["Out"])
    np.testing.assert_allclose(s, a[:, 1:3] + b[:, 1:3])


def test_max_pool3d_with_index():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 1, 4, 4, 4).astype(np.float32)
    out = _run("max_pool3d_with_index", {"X": x},
               {"ksize": [2, 2, 2], "strides": [2, 2, 2]})
    o = np.asarray(out["Out"])
    mask = np.asarray(out["Mask"])
    assert o.shape == (1, 1, 2, 2, 2)
    # verify indices point at the max values
    flat = x[0, 0].reshape(-1)
    np.testing.assert_allclose(flat[mask[0, 0]], o[0, 0], rtol=1e-6)


def test_lod_reset_and_select_output():
    x = np.ones((3, 2), np.float32)
    out = _run("lod_reset", {"X": x}, {"target_lod": [0, 2, 3]})
    np.testing.assert_allclose(np.asarray(out["Out"]), x)
    assert np.asarray(out["Length"]).tolist() == [2, 1]
    outs = _run("select_output",
                {"X": x, "Mask": np.array([1], np.int32)},
                {"num_outputs": 2})["Out"]
    assert (np.asarray(outs[0]) == 0).all()
    np.testing.assert_allclose(np.asarray(outs[1]), x)


def test_selected_rows_densify_and_merge():
    import jax.numpy as jnp
    from paddle_tpu.core.selected_rows import SelectedRows
    sr = SelectedRows(jnp.asarray([1, 3, 1], jnp.int32),
                      jnp.asarray([[1.0], [2.0], [10.0]]), 5)
    dense = np.asarray(run_kernel(
        "get_tensor_from_selected_rows", {"X": sr}, {},
        OpContext())["Out"])
    np.testing.assert_allclose(dense[:, 0], [0, 11, 0, 2, 0])
    merged = run_kernel("merge_selected_rows", {"X": sr}, {},
                        OpContext())["Out"]
    np.testing.assert_allclose(np.asarray(merged.values)[:, 0],
                               [0, 11, 0, 2, 0])


def test_save_load_ops_roundtrip(tmp_path):
    import jax
    import jax.numpy as jnp
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    path = str(tmp_path / "w")

    def step(v):
        run_kernel("save", {"X": v}, {"file_path": path}, OpContext())
        return v * 2

    out = jax.jit(step)(jnp.asarray(x))
    jax.effects_barrier()
    np.asarray(out)
    back = run_kernel("load", {}, {"file_path": path}, OpContext())
    np.testing.assert_allclose(np.asarray(back["Out"]), x)
    run_kernel("save_combine",
               {"X": [jnp.asarray(x), jnp.asarray(x + 1)]},
               {"file_path": str(tmp_path / "all"),
                "var_names": ["a", "b"]}, OpContext())
    jax.effects_barrier()
    outs = run_kernel("load_combine", {},
                      {"file_path": str(tmp_path / "all"),
                       "var_names": ["a", "b"]}, OpContext())["Out"]
    np.testing.assert_allclose(np.asarray(outs[1]), x + 1)


def test_correlation_matches_reference_contract():
    rng = np.random.RandomState(2)
    x = rng.randn(1, 3, 4, 4).astype(np.float32)
    y = rng.randn(1, 3, 4, 4).astype(np.float32)
    out = np.asarray(_run("correlation",
                          {"Input1": x, "Input2": y},
                          {"max_displacement": 1, "stride1": 1,
                           "stride2": 1, "pad_size": 0,
                           "kernel_size": 1})["Output"])
    # GetOutputSize: border=1 -> centers at rows/cols {1, 2}
    assert out.shape == (1, 9, 2, 2)
    # center channel (0,0 displacement): mean over C of x*y at centers
    exp = (x[0] * y[0]).mean(0)[1:3, 1:3]
    np.testing.assert_allclose(out[0, 4], exp, rtol=1e-5)
    # displacement (-1,-1) channel at center (1,1): x(1,1) . y(0,0) / C
    exp_d = (x[0, :, 1, 1] * y[0, :, 0, 0]).mean()
    np.testing.assert_allclose(out[0, 0, 0, 0], exp_d, rtol=1e-5)
    # border displacement reaching outside the image contributes ZEROS
    # (no wrap): displacement (+1,+1) at the last center (2,2) reads
    # y(3,3) which is valid; use pad-free (-1,-1) at center (1,1) -> ok;
    # instead check wrap-freedom via a one-hot: x2 nonzero ONLY at
    # (0,0); displacement (+1,+1) at center (2,2) would wrap to (3,3)=0
    y2 = np.zeros_like(y)
    y2[0, :, 0, 0] = 1.0
    out2 = np.asarray(_run("correlation",
                           {"Input1": np.ones_like(x), "Input2": y2},
                           {"max_displacement": 1, "stride1": 1,
                            "stride2": 1, "pad_size": 0,
                            "kernel_size": 1})["Output"])
    # only displacement (-1,-1) at center (1,1) sees the hot pixel
    assert out2[0, 0, 0, 0] > 0
    assert out2[0, 8, 1, 1] == 0  # (+1,+1) at (2,2) -> (3,3) is zero


def test_interp_v2_aliases():
    x = np.arange(8, dtype=np.float32).reshape(1, 1, 8)
    out = np.asarray(_run("linear_interp_v2", {"X": x},
                          {"out_w": 4})["Out"])
    assert out.shape == (1, 1, 4)
    x3 = np.ones((1, 1, 2, 2, 2), np.float32)
    out3 = np.asarray(_run("trilinear_interp_v2", {"X": x3},
                           {"out_d": 4, "out_h": 4, "out_w": 4})["Out"])
    assert out3.shape == (1, 1, 4, 4, 4)
    np.testing.assert_allclose(out3, 1.0, atol=1e-6)


def test_depthwise_conv2d_transpose_runs():
    rng = np.random.RandomState(3)
    x = rng.randn(1, 4, 5, 5).astype(np.float32)
    w = rng.randn(4, 1, 3, 3).astype(np.float32)
    out = np.asarray(_run("depthwise_conv2d_transpose",
                          {"Input": x, "Filter": w},
                          {"strides": [1, 1], "paddings": [1, 1]})
                     ["Output"])
    assert out.shape[1] == 4 and np.isfinite(out).all()


def test_correlation_stride2_grid_includes_zero():
    """Review r4: stride2 grid = {i*s2 : |i*s2| <= max_d} ALWAYS
    including 0 — 2*(max_d//s2)+1 channels per axis."""
    rng = np.random.RandomState(4)
    x = rng.randn(1, 2, 8, 8).astype(np.float32)
    out = np.asarray(_run("correlation", {"Input1": x, "Input2": x},
                          {"max_displacement": 3, "stride1": 1,
                           "stride2": 2, "pad_size": 0,
                           "kernel_size": 1})["Output"])
    # grid {-2, 0, 2} per axis -> 9 channels; centers start at border=3
    assert out.shape == (1, 9, 2, 2)
    # center channel is the zero-displacement self-correlation (>= 0)
    assert (out[0, 4] >= 0).all()

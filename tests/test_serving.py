"""paddle_tpu.serving: dynamic batching + continuous-batching generation.

Covers the serving-tier contracts: K concurrent callers coalesce into
<= ceil(K/max_batch) device runs with row-exact results, queue-full and
deadline backpressure, monitor gauges/histograms, continuous-batching
decode equivalence with per-sequence generate(), and a threaded
end-to-end server pass."""
import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddle_tpu.serving import (DynamicBatcher, QueueFullError,
                                DeadlineExceededError, BatcherStoppedError)
from paddle_tpu.serving import metrics


def test_batcher_coalesces_rows_exact():
    """12 callers / max_batch 4 -> exactly ceil(12/4)=3 device runs once
    the scheduler unblocks, every caller getting its own rows back."""
    sizes = []
    gate = threading.Event()

    def runner(feeds):
        if not gate.is_set():  # the plug request holds the scheduler
            gate.wait(10)
        else:
            sizes.append(feeds[0].shape[0])
        return [feeds[0] * 3.0, np.float32(7.0)]

    b = DynamicBatcher(runner, max_batch=4, max_wait_ms=0.0,
                       pad_to_bucket=False).start()
    try:
        plug = b.submit([np.zeros((1, 2), np.float32)])
        time.sleep(0.05)  # scheduler is now blocked inside the plug run
        futs = [b.submit([np.full((1, 2), float(i), np.float32)])
                for i in range(12)]
        gate.set()
        plug.result(timeout=10)
        outs = [f.result(timeout=10) for f in futs]
    finally:
        b.stop()
    assert sizes == [4, 4, 4], sizes
    for i, (rows, scalar) in enumerate(outs):
        np.testing.assert_array_equal(rows, np.full((1, 2), 3.0 * i))
        # batch-level (non-row) outputs are shared to every caller
        assert float(scalar) == 7.0
    assert metrics.counter("batch.coalesced") >= 3


def test_batcher_pow2_padding_and_mixed_shapes():
    """Ragged coalesced batches are padded to the pow2 bucket before the
    runner; requests with different row shapes never share a run."""
    sizes = []

    def runner(feeds):
        sizes.append(feeds[0].shape[0])
        return [feeds[0] + 1.0]

    b = DynamicBatcher(runner, max_batch=8, max_wait_ms=40.0).start()
    try:
        f1 = b.submit([np.zeros((2, 3), np.float32)])
        f2 = b.submit([np.ones((1, 3), np.float32)])
        f3 = b.submit([np.zeros((1, 5), np.float32)])  # other signature
        r1 = f1.result(timeout=10)[0]
        r2 = f2.result(timeout=10)[0]
        r3 = f3.result(timeout=10)[0]
    finally:
        b.stop()
    assert r1.shape == (2, 3) and np.all(r1 == 1.0)
    assert r2.shape == (1, 3) and np.all(r2 == 2.0)
    assert r3.shape == (1, 5)
    # 2+1 rows coalesced -> padded to 4; the [1,5] request ran alone
    assert 4 in sizes and 1 in sizes, sizes


def test_batcher_queue_full_and_deadline():
    release = threading.Event()

    def slow(feeds):
        release.wait(10)
        return [feeds[0]]

    b = DynamicBatcher(slow, max_batch=1, max_wait_ms=0.0,
                       max_queue=2).start()
    try:
        first = b.submit([np.zeros((1, 1), np.float32)])
        time.sleep(0.05)  # scheduler now blocked in `slow`
        expired = b.submit([np.zeros((1, 1), np.float32)], timeout_s=0.01)
        b.submit([np.zeros((1, 1), np.float32)])
        with pytest.raises(QueueFullError) as ei:
            b.submit([np.zeros((1, 1), np.float32)])
        assert ei.value.http_status == 503
        assert ei.value.retry_after_s > 0
        time.sleep(0.05)  # let the 10ms deadline lapse before release
        release.set()
        first.result(timeout=10)
        with pytest.raises(DeadlineExceededError):
            expired.result(timeout=10)
    finally:
        b.stop()
    # stopped batcher rejects synchronously
    with pytest.raises(BatcherStoppedError):
        b.submit([np.zeros((1, 1), np.float32)])
    assert metrics.counter("requests.timeout") >= 1


def test_backpressure_retry_after_is_jittered_and_load_scaled():
    """A fixed Retry-After marches every rejected client back in one
    synchronized wave (thundering herd); the hint must be load-scaled
    AND jittered so concurrent rejects decorrelate."""
    release = threading.Event()

    def slow(feeds):
        release.wait(10)
        return [feeds[0]]

    hints = []
    shallow_hints = []
    for depth in (2, 32):
        b = DynamicBatcher(slow, max_batch=1, max_wait_ms=50.0,
                           max_queue=depth).start()
        try:
            b.submit([np.zeros((1, 1), np.float32)])
            time.sleep(0.05)  # scheduler blocked inside `slow`
            for _ in range(depth):
                b.submit([np.zeros((1, 1), np.float32)])
            got = []
            for _ in range(24):
                with pytest.raises(QueueFullError) as ei:
                    b.submit([np.zeros((1, 1), np.float32)])
                got.append(ei.value.retry_after_s)
            (shallow_hints if depth == 2 else hints).extend(got)
        finally:
            release.set()
            b.stop(drain=False)
            release.clear()
    # jitter: repeated rejects at identical load must NOT repeat the hint
    assert len(set(hints)) > 1
    assert len(set(shallow_hints)) > 1
    # load scaling: a 16x deeper backlog earns a larger hint even at the
    # jitter extremes (bounds: base*[0.5, 1.5))
    assert min(hints) > max(shallow_hints)
    for h in hints + shallow_hints:
        assert h > 0
    # a draining batcher's rejection hint is jittered too, not 1.0 flat
    stopped = [BatcherStoppedError().retry_after_s for _ in range(16)]
    assert len(set(stopped)) > 1
    assert all(0.5 <= s <= 1.5 for s in stopped)


def test_batcher_error_fanout():
    def broken(feeds):
        raise RuntimeError("kernel exploded")

    b = DynamicBatcher(broken, max_batch=4, max_wait_ms=20.0).start()
    try:
        futs = [b.submit([np.zeros((1, 1), np.float32)])
                for _ in range(3)]
        for f in futs:
            with pytest.raises(RuntimeError, match="kernel exploded"):
                f.result(timeout=10)
    finally:
        b.stop()


def test_monitor_gauges_and_histograms():
    from paddle_tpu.core.monitor import (gauge_set, gauge_get,
                                         hist_observe, hist_snapshot,
                                         monitor_snapshot, stat_reset)
    gauge_set("t.depth", 5)
    gauge_set("t.depth", 3)
    assert gauge_get("t.depth") == 3
    assert hist_snapshot("t.lat")["count"] == 0
    for v in range(1, 101):
        hist_observe("t.lat", float(v))
    snap = hist_snapshot("t.lat")
    assert snap["count"] == 100 and snap["min"] == 1.0
    assert snap["max"] == 100.0
    assert abs(snap["p50"] - 50) <= 2
    assert abs(snap["p99"] - 99) <= 2
    full = monitor_snapshot("t.")
    assert full["t.depth"] == 3 and full["t.lat"]["count"] == 100
    stat_reset("t.depth")
    stat_reset("t.lat")
    assert gauge_get("t.depth") == 0
    assert hist_snapshot("t.lat")["count"] == 0


# ---------------------------------------------------------------------------
# Prometheus exposition (core/monitor.prometheus_text + /metrics)
# ---------------------------------------------------------------------------
_PROM_LINE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>.*)\})? (?P<value>\S+)$')
_PROM_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_prometheus(text):
    """Minimal exposition-format parser: {(name, labels): value},
    {name: type}.  Raises on any line that violates the line grammar —
    the round-trip IS the conformance check."""
    series, types = {}, {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, name, typ = line.split(" ", 3)
            types[name] = typ
            continue
        if line.startswith("#"):
            assert line.startswith("# HELP "), line
            continue
        m = _PROM_LINE.match(line)
        assert m, f"malformed exposition line: {line!r}"
        labels = {}
        raw = m.group("labels")
        if raw:
            consumed = ",".join(f'{k}="{v}"'
                                for k, v in _PROM_LABEL.findall(raw))
            assert consumed == raw, f"malformed labels: {raw!r}"
            for k, v in _PROM_LABEL.findall(raw):
                labels[k] = re.sub(
                    r'\\(["\\n])',
                    lambda mm: {'"': '"', '\\': '\\', 'n': '\n'}[
                        mm.group(1)], v)
        series[(m.group("name"),
                tuple(sorted(labels.items())))] = float(m.group("value"))
    return series, types


def test_prometheus_text_spec_conformance_roundtrip():
    """HELP/TYPE lines, counter _total suffix, summary quantile series,
    and label escaping all survive a round-trip through a strict line
    parser."""
    from paddle_tpu.core.monitor import (prometheus_text, stat_add,
                                         gauge_set, hist_observe,
                                         stat_reset)
    stat_add("promtest.requests", 7)
    gauge_set("promtest.depth", 2.5)
    for v in range(1, 101):
        hist_observe("promtest.lat_ms", float(v))
    try:
        nasty = 'a"b\\c\nd'
        text = prometheus_text(prefix="promtest.",
                               labels={"rank": "0", "job": nasty})
        series, types = _parse_prometheus(text)
        assert types["promtest_requests_total"] == "counter"
        assert types["promtest_depth"] == "gauge"
        assert types["promtest_lat_ms"] == "summary"
        base = (("job", nasty), ("rank", "0"))
        assert series[("promtest_requests_total", base)] == 7
        assert series[("promtest_depth", base)] == 2.5
        q50 = series[("promtest_lat_ms",
                      tuple(sorted(base + (("quantile", "0.5"),))))]
        assert abs(q50 - 50) <= 2
        assert series[("promtest_lat_ms_count", base)] == 100
        assert series[("promtest_lat_ms_sum", base)] == 5050
        # every TYPE-declared metric has at least one sample line
        for name in types:
            assert any(k[0].startswith(name) for k in series), name
    finally:
        for n in ("promtest.requests", "promtest.depth",
                  "promtest.lat_ms"):
            stat_reset(n)


def test_server_metrics_scrape_live(tmp_path):
    """GET /metrics on the live inference server: text/plain exposition
    a scraper can parse, carrying the serving metrics the request
    traffic just minted."""
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import serve_smoke
    from paddle_tpu.inference.server import InferenceServer
    xb, ref, out_name = serve_smoke.save_tiny_model(str(tmp_path))
    srv = InferenceServer(str(tmp_path), max_wait_ms=5.0)
    srv.start()
    try:
        base = f"http://{srv.host}:{srv.port}"
        _post(base + "/predict", {"inputs": {"x": xb[:1].tolist()}})
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            assert r.status == 200
            ctype = r.headers["Content-Type"]
            body = r.read().decode()
        assert ctype.startswith("text/plain")
        assert "version=0.0.4" in ctype
        series, types = _parse_prometheus(body)
        assert types["serving_requests_completed_total"] == "counter"
        completed = series[("serving_requests_completed_total", ())]
        assert completed >= 1
        assert types["serving_latency_ms"] == "summary"
    finally:
        srv.stop()


def _tiny_gpt(vocab=30):
    from paddle_tpu.models import GPTConfig, GPTModel, GPTForGeneration
    cfg = GPTConfig(vocab_size=vocab, hidden_size=16, num_layers=1,
                    num_heads=2, max_position=32, dropout=0.0)
    return GPTForGeneration(GPTModel(cfg))


def test_continuous_batching_matches_sequential_generate():
    """Sequences admitted into a shared fixed-slot batch (joining and
    leaving mid-decode) must reproduce per-sequence greedy generate()
    token for token."""
    import paddle_tpu.dygraph as dg
    from paddle_tpu.serving import ContinuousBatchingEngine
    rng = np.random.RandomState(3)
    prompts = [rng.randint(2, 30, (n,)).astype(np.int64)
               for n in (3, 5, 2)]
    with dg.guard():
        m = _tiny_gpt()
        m.eval()
        refs = [m.generate(p[None], max_length=4,
                           decode_strategy="greedy_search")[0]
                for p in prompts]
        # 2 slots, 3 requests: the third must join when a slot frees
        eng = ContinuousBatchingEngine(m, max_slots=2).start()
        try:
            futs = [eng.submit(p, max_length=4) for p in prompts]
            outs = [f.result(timeout=120) for f in futs]
        finally:
            eng.stop()
    for ref, out in zip(refs, outs):
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
    assert metrics.counter("gen.completed") >= 3
    assert metrics.counter("gen.steps") >= 1


def test_engine_rejects_bad_requests():
    import paddle_tpu.dygraph as dg
    from paddle_tpu.serving import ContinuousBatchingEngine
    with dg.guard():
        m = _tiny_gpt()
        eng = ContinuousBatchingEngine(m, max_slots=2)
        with pytest.raises(ValueError, match="beam"):
            eng.submit([2, 3], decode_strategy="beam_search")
        with pytest.raises(ValueError, match="max_position"):
            eng.submit(list(range(2, 30)), max_length=30)
        with pytest.raises(BatcherStoppedError):
            eng.submit([2, 3])  # not started
        eng.start()
        eng.stop()
        with pytest.raises(BatcherStoppedError):
            eng.submit([2, 3])


def test_server_stop_without_start(tmp_path):
    """stop() on a never-started server must not hang in shutdown()."""
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import serve_smoke
    from paddle_tpu.inference.server import InferenceServer
    serve_smoke.save_tiny_model(str(tmp_path))
    srv = InferenceServer(str(tmp_path))
    done = threading.Event()

    def stopper():
        srv.stop(drain_timeout_s=1.0)
        done.set()

    t = threading.Thread(target=stopper, daemon=True)
    t.start()
    assert done.wait(10), "stop() hung on a never-started server"
    assert srv.status == "stopped"


def test_server_keepalive_survives_error_replies(tmp_path):
    """Early error replies (404 route) must drain the POST body, or the
    next request on the same keep-alive connection desyncs."""
    import sys, os, http.client
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import serve_smoke
    from paddle_tpu.inference.server import InferenceServer
    xb, ref, out_name = serve_smoke.save_tiny_model(str(tmp_path))
    srv = InferenceServer(str(tmp_path))
    srv.start()
    try:
        conn = http.client.HTTPConnection(srv.host, srv.port, timeout=10)
        body = json.dumps({"inputs": {"x": xb[:1].tolist()}}).encode()
        conn.request("POST", "/nope", body,
                     {"Content-Type": "application/json"})
        assert conn.getresponse().read() and True  # 404, body drained
        # the SAME connection must still serve a real predict
        conn.request("POST", "/predict", body,
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200
        reply = json.loads(resp.read())
        got = np.asarray(reply["outputs"][out_name]["data"]).reshape(
            reply["outputs"][out_name]["shape"])
        np.testing.assert_allclose(got, ref[:1], rtol=1e-4, atol=1e-6)
        conn.close()
    finally:
        srv.stop()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def test_server_end_to_end_threaded(tmp_path):
    """Concurrent /predict through the batcher (row-exact), /generate
    through the engine (greedy-equal), /stats, readiness /health, and
    graceful stop()."""
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import serve_smoke
    import paddle_tpu.dygraph as dg
    from paddle_tpu.inference.server import InferenceServer

    xb, ref, out_name = serve_smoke.save_tiny_model(str(tmp_path))
    with dg.guard():
        gen = _tiny_gpt()
        gen.eval()
        seq_ref = gen.generate(np.array([[4, 9]], np.int64),
                               max_length=3)[0]
        srv = InferenceServer(str(tmp_path), max_wait_ms=10.0,
                              generator=gen, gen_slots=2)
        srv.start()
        try:
            base = f"http://{srv.host}:{srv.port}"
            with urllib.request.urlopen(base + "/health", timeout=10) as r:
                assert json.loads(r.read())["status"] == "ok"

            results = [None] * 6
            def client(i):
                k = i % xb.shape[0]
                reply = _post(base + "/predict",
                              {"inputs": {"x": xb[k:k + 1].tolist()}})
                o = reply["outputs"][out_name]
                results[i] = (k, np.asarray(o["data"]).reshape(o["shape"]))
            ts = [threading.Thread(target=client, args=(i,))
                  for i in range(6)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            for k, got in results:
                np.testing.assert_allclose(got, ref[k:k + 1],
                                           rtol=1e-4, atol=1e-6)

            g = _post(base + "/generate",
                      {"input_ids": [4, 9], "max_length": 3})
            assert g["output_ids"][0] == list(seq_ref)

            with urllib.request.urlopen(base + "/stats", timeout=10) as r:
                st = json.loads(r.read())
            assert st["status"] == "ok"
            assert st["serving"].get("serving.requests.completed", 0) >= 6
            assert "predictor_cache" in st

            # structured client error: missing input -> 400 + json body
            try:
                _post(base + "/predict", {"inputs": {}})
                assert False, "expected HTTPError"
            except urllib.error.HTTPError as e:
                assert e.code == 400
                body = json.loads(e.read())
                assert "error" in body and "type" in body
        finally:
            srv.stop()
        assert srv.status == "stopped"
        # post-stop: socket is closed, no handler raced server_close
        with pytest.raises(Exception):
            urllib.request.urlopen(base + "/health", timeout=2)


# ---------------------------------------------------------------------------
# tp-sharded decode: the 4×2-mesh engine vs single-chip greedy
# ---------------------------------------------------------------------------

def _tp_gpt(vocab=48):
    """4-head sibling of _tiny_gpt: the KV slab shards on heads, so the
    tp=2 matrix needs H % 2 == 0 with at least 2 heads per chip."""
    from paddle_tpu.models import GPTConfig, GPTModel, GPTForGeneration
    cfg = GPTConfig(vocab_size=vocab, hidden_size=16, num_layers=2,
                    num_heads=4, max_position=64, dropout=0.0)
    return GPTForGeneration(GPTModel(cfg))


def test_tp_sharded_engine_token_equal_matrix():
    """The ISSUE-19 equality matrix in one drain: a tp=2 engine with a
    planner-sized sharded pool, radix prefix retention, and a shallow
    speculative draft (partial acceptance forces real rollbacks) must
    reproduce the tp=1 paged engine token for token — greedy decode,
    radix-hit resume on a page-aligned shared head, and speculative
    verify/rollback all riding the sharded tables — and both pools
    must drain clean after the churn."""
    import paddle_tpu.dygraph as dg
    from paddle_tpu.serving import (ContinuousBatchingEngine, PagedKVPool,
                                    RadixPrefixCache, SpeculativeDecoder,
                                    stamp_draft)
    from paddle_tpu.static import page_budget
    rng = np.random.RandomState(17)
    # page-aligned shared head (page_tokens=4 -> exactly 2 pages) so the
    # repeat prompt resumes from retained radix pages, not cold prefill
    head = rng.randint(2, 48, (8,)).astype(np.int64)
    prompts = [np.concatenate([head, rng.randint(2, 48, (3,))
                               .astype(np.int64)]) for _ in range(2)]
    prompts += [rng.randint(2, 48, (n,)).astype(np.int64) for n in (3, 6)]
    prompts.append(prompts[0].copy())          # whole-prompt radix hit
    with dg.guard():
        m = _tp_gpt()
        m.eval()
        plan1 = page_budget(m, page_tokens=4, max_context=64)
        ref_pool = PagedKVPool.from_plan(plan1)
        eng = ContinuousBatchingEngine(m, max_slots=2,
                                       kv_pool=ref_pool).start()
        try:
            refs = [np.asarray(eng.submit(p, max_length=6)
                               .result(timeout=120)) for p in prompts]
        finally:
            eng.stop()
        ref_pool.assert_drained()

        plan2 = page_budget(m, page_tokens=4, max_context=64,
                            tp_degree=2)
        pool = PagedKVPool.from_plan(plan2)
        radix = RadixPrefixCache(pool, low_watermark=2, high_watermark=4)
        # 1-of-2-layer draft: proposals diverge from the target, so the
        # sharded verify path must take BOTH branches (accept + rollback)
        spec = SpeculativeDecoder(stamp_draft(m, num_layers=1), k=2)
        eng = ContinuousBatchingEngine(m, max_slots=2, kv_pool=pool,
                                       prefix_cache=radix,
                                       speculative=spec).start()
        assert eng.tp_degree == 2
        try:
            outs = [np.asarray(eng.submit(p, max_length=6)
                               .result(timeout=300)) for p in prompts]
        finally:
            eng.stop()
    for i, (ref, out) in enumerate(zip(refs, outs)):
        np.testing.assert_array_equal(
            ref, out, err_msg=f"prompt {i} diverged on the tp=2 mesh")
    assert radix.hits >= 1, "page-aligned repeat never hit the radix tree"
    assert metrics.counter("spec.accepted") >= 1
    assert metrics.counter("spec.rollback_cols") >= 1, \
        "shallow draft produced no rollbacks — verify path untested"
    pool.assert_drained()
    radix.clear()
    pool.assert_drained()


def test_tp_decode_program_layout_is_v6xx_clean():
    """Every decode bucket shape (prefill, single-token decode, and the
    speculative verify window) must analyze clean under the V6xx
    sharding propagator on the 4×2 mesh — the gather-by-page-table view
    composes with the head-sharded cache feeds, col/row projections,
    and the c_concat KV gathers without a single diagnostic."""
    from paddle_tpu.models import GPTConfig
    from paddle_tpu.serving import build_decode_program
    from paddle_tpu.static.layout_analysis import propagate_shardings
    cfg = GPTConfig(vocab_size=48, hidden_size=16, num_layers=2,
                    num_heads=4, max_position=64, dropout=0.0)
    for (B, lc, W) in ((1, 0, 8), (4, 16, 1), (4, 16, 3)):
        prog, _, _ = build_decode_program(cfg, batch=B, cache_len=lc,
                                          width=W, tp_degree=2)
        layout = propagate_shardings(prog, mesh_shape={"dp": 4, "tp": 2},
                                     batch=B)
        assert layout.diagnostics == [], \
            f"decode bucket B={B} lc={lc} W={W}: {layout.diagnostics}"


def test_tp2_serves_model_infeasible_at_tp1():
    """The ISSUE-19 'done' demo: under a pinned per-chip HBM budget the
    tp=1 page budget cannot even hold one decode slot — and the SAME
    budget at tp=2 carves a real pool that serves token-for-token equal
    to unconstrained single-chip greedy, pool drained clean."""
    import paddle_tpu.dygraph as dg
    import pytest as _pytest
    from paddle_tpu.serving import ContinuousBatchingEngine, PagedKVPool
    from paddle_tpu.static import page_budget
    rng = np.random.RandomState(29)
    prompts = [rng.randint(2, 48, (n,)).astype(np.int64) for n in (4, 7)]
    with dg.guard():
        m = _tp_gpt()
        m.eval()
        weight_bytes = int(sum(np.asarray(p.numpy()).nbytes
                               for p in m.gpt.parameters()))
        # weights + ~2 KiB: tp=1 cannot place a single max-context slot
        hbm = weight_bytes + 2048
        with _pytest.raises(ValueError, match="not enough for one"):
            page_budget(m, page_tokens=4, max_context=64, hbm_bytes=hbm)
        plan = page_budget(m, page_tokens=4, max_context=64,
                           hbm_bytes=hbm, tp_degree=2)
        assert plan["pages"] >= 1
        refs = [np.asarray(m.generate(p[None], max_length=4,
                                      decode_strategy="greedy_search")[0])
                for p in prompts]
        pool = PagedKVPool.from_plan(plan)
        eng = ContinuousBatchingEngine(m, max_slots=1,
                                       kv_pool=pool).start()
        assert eng.tp_degree == 2
        try:
            outs = [np.asarray(eng.submit(p, max_length=4)
                               .result(timeout=300)) for p in prompts]
        finally:
            eng.stop()
    for ref, out in zip(refs, outs):
        np.testing.assert_array_equal(ref, out)
    pool.assert_drained()

"""Declarative partition-spec engine (distributed/partition_spec.py).

The three rule-engine contracts from ISSUE 11:
  * precedence — first matching rule wins (the exemplar's re.search
    loop order);
  * no-match fallback — unmatched names are REPLICATED and recorded
    (or an error under require_match);
  * over-match refusal — a strict rule assigning a sharded spec to a
    var the pass cannot partition raises, naming the rule.

Plus the stage-rule ladder itself and its wiring into
`shard_optimizer_states`.
"""
import re

import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers
from paddle_tpu.core.program import _reset_unique_names
from paddle_tpu.distributed.partition_spec import (
    DP_SHARD, REPLICATED, PartitionRule, build_sharding_specs,
    match_partition_rules, zero_stage_rules)
from paddle_tpu.distributed.sharding import shard_optimizer_states

WORLD = 8


def _build(opt_fn=None):
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        (opt_fn or (lambda: static.Adam(learning_rate=1e-2)))().minimize(
            loss)
    return main, startup, loss


# ---------------------------------------------------------------------------
# rule matching core
# ---------------------------------------------------------------------------
def test_first_match_wins_precedence():
    rules = [(r"embed", REPLICATED), (r".*", DP_SHARD)]
    a = match_partition_rules(rules, ["param:embed_w", "param:fc_w"])
    assert a.spec("param:embed_w") == REPLICATED
    assert a.spec("param:fc_w") == DP_SHARD
    # swap the order: the catch-all now shadows the embed rule
    a2 = match_partition_rules(list(reversed(rules)),
                               ["param:embed_w", "param:fc_w"])
    assert a2.spec("param:embed_w") == DP_SHARD


def test_no_match_falls_back_replicated_and_records():
    a = match_partition_rules([(r"^slot:", DP_SHARD)],
                              ["slot:m1", "param:w"])
    assert a.spec("slot:m1") == DP_SHARD
    assert a.spec("param:w") == REPLICATED
    assert a.unmatched == ["param:w"]


def test_require_match_raises_like_the_exemplar():
    with pytest.raises(ValueError, match="partition rule not found"):
        match_partition_rules([(r"^slot:", DP_SHARD)], ["param:w"],
                              require_match=True)


def test_scalars_are_never_partitioned():
    a = match_partition_rules([(r".*", DP_SHARD)], ["scalar:beta1_pow"],
                              numels={"scalar:beta1_pow": 1})
    assert a.spec("scalar:beta1_pow") == REPLICATED
    assert a.rule_of["scalar:beta1_pow"] is None


def test_bad_rule_shapes_are_rejected():
    with pytest.raises(TypeError):
        match_partition_rules([("only-a-pattern",)], ["param:w"])


# ---------------------------------------------------------------------------
# the ZeRO ladder as rules
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("stage,slot,grad_acc,param", [
    (0, REPLICATED, REPLICATED, REPLICATED),
    (1, DP_SHARD, REPLICATED, REPLICATED),
    (2, DP_SHARD, DP_SHARD, REPLICATED),
    (3, DP_SHARD, DP_SHARD, DP_SHARD),
])
def test_zero_stage_ladder(stage, slot, grad_acc, param):
    rules = zero_stage_rules(stage)
    a = match_partition_rules(
        rules, ["slot:w_moment1", "grad_acc:w@GRAD", "param:w",
                "scalar:beta1_pow"])
    assert a.spec("slot:w_moment1") == slot
    assert a.spec("grad_acc:w@GRAD") == grad_acc
    assert a.spec("param:w") == param
    assert a.spec("scalar:beta1_pow") == REPLICATED
    assert not a.unmatched   # the stage default always terminates


def test_zero_stage_rules_rejects_bad_stage():
    with pytest.raises(ValueError):
        zero_stage_rules(4)


# ---------------------------------------------------------------------------
# program-level assignment + over-match refusal
# ---------------------------------------------------------------------------
def test_build_sharding_specs_covers_the_program_surface():
    main, _, _ = _build()
    a = build_sharding_specs(main, 3)
    param_qs = [q for q in a.specs if q.startswith("param:")]
    slot_qs = [q for q in a.specs if q.startswith("slot:")]
    scalar_qs = [q for q in a.specs if q.startswith("scalar:")]
    assert len(param_qs) == len(main.all_parameters())
    assert slot_qs and scalar_qs
    assert all(a.sharded(q) for q in param_qs + slot_qs)
    assert not any(a.sharded(q) for q in scalar_qs)


def test_over_match_refusal_on_unshardable_param():
    """A STRICT rule claiming a param the pass must skip (Adamax —
    unsupported optimizer) is refused with the rule named; the same
    rule marked non-strict degrades to replicated silently."""
    main, _, _ = _build(lambda: static.Adamax(learning_rate=1e-2))
    strict = [PartitionRule(r"^param:", DP_SHARD, strict=True)]
    with pytest.raises(ValueError, match="over-match refused"):
        build_sharding_specs(main, 3, extra_rules=strict)
    lax = [PartitionRule(r"^param:", DP_SHARD, strict=False)]
    a = build_sharding_specs(main, 3, extra_rules=lax)
    assert a is not None  # no refusal; pass-level warning covers it
    # the SLOT surface of an unshardable op refuses too (the Adamax
    # moments are accum_of-linked even though the op has no bucket spec)
    with pytest.raises(ValueError, match="over-match refused"):
        build_sharding_specs(
            main, 1, extra_rules=[PartitionRule(r"^slot:", DP_SHARD)])


def test_user_rule_overrides_stage_default_in_the_pass():
    """End-to-end: a prepended REPLICATED rule keeps one param's slots
    out of the stage-1 bucketing entirely (its per-param optimizer op
    survives for the allreduce path)."""
    main, startup, _ = _build()
    first = main.all_parameters()[0].name
    slot_rule = (r"^slot:" + re.escape(first), REPLICATED, False)
    plan = shard_optimizer_states(main, startup, dp_degree=WORLD,
                                  stage=1, rules=[slot_rule])
    bucketed = {p["param"] for b in plan.buckets for p in b["params"]}
    assert first not in bucketed
    assert bucketed  # the others still shard
    types = [op.type for op in main.global_block().ops]
    assert types.count("adam") == plan.n_buckets + 1  # one survivor


def test_stage_rules_drive_memory_accounting_end_to_end():
    """The declarative plan and the walker agree: what the rules shard
    is what the per-chip accounting divides."""
    main, startup, _ = _build()
    plain = static.analyze_program(main, batch=16)
    shard_optimizer_states(main, startup, dp_degree=WORLD, stage=3)
    sharded = static.analyze_program(main, batch=16)
    # every param + slot byte is now in dp_shard buckets at 1/8
    assert sharded["persistable_bytes"] < plain["persistable_bytes"] // 4

"""Quantization tier (reference: operators/fake_quantize_op.cc,
contrib/slim/quantization/quantization_pass.py QuantizationTransformPass /
QuantizationFreezePass, post_training_quantization.py)."""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers


# ---------------------------------------------------------------------------
# op level
# ---------------------------------------------------------------------------
def _run(op, ins, attrs):
    from paddle_tpu.ops.registry import run_kernel, OpContext
    import jax.numpy as jnp
    return run_kernel(op, {k: (jnp.asarray(v) if v is not None else None)
                           for k, v in ins.items()}, attrs, OpContext())


def test_fake_quant_dequant_abs_max_roundtrip():
    x = np.linspace(-2.0, 2.0, 16).astype(np.float32)
    out = _run("fake_quantize_dequantize_abs_max", {"X": x},
               {"bit_length": 8})
    y = np.asarray(out["Out"])
    assert abs(float(out["OutScale"][0]) - 2.0) < 1e-6
    np.testing.assert_allclose(y, x, atol=2.0 / 127 + 1e-6)
    assert not np.allclose(y, x)  # rounding actually happened


def test_fake_quant_channel_wise():
    w = np.stack([np.full((3,), 1.0), np.full((3,), 10.0)]) \
        .astype(np.float32)
    out = _run("fake_channel_wise_quantize_abs_max", {"X": w},
               {"bit_length": 8, "quant_axis": 0})
    np.testing.assert_allclose(np.asarray(out["OutScale"]), [1.0, 10.0])
    q = np.asarray(out["Out"])
    assert q.max() == 127.0
    deq = _run("fake_channel_wise_dequantize_max_abs",
               {"X": q, "Scales": [np.asarray(out["OutScale"])]},
               {"max_range": 127.0, "quant_axis": 0})
    np.testing.assert_allclose(np.asarray(deq["Out"]), w, rtol=1e-2)


def test_quant_dequant_int8_roundtrip():
    x = np.array([-1.0, -0.5, 0.0, 0.5, 1.0], np.float32)
    q = _run("fake_quantize_abs_max", {"X": x}, {"bit_length": 8})
    deq = _run("fake_dequantize_max_abs",
               {"X": np.asarray(q["Out"]),
                "Scale": np.asarray(q["OutScale"])},
               {"max_range": 127.0})
    np.testing.assert_allclose(np.asarray(deq["Out"]), x, atol=1.0 / 127)


def test_moving_average_scale_state():
    x = np.ones(4, np.float32) * 3.0
    out = _run("fake_quantize_dequantize_moving_average_abs_max",
               {"X": x, "InScale": np.asarray([1.0], np.float32),
                "InState": np.asarray([1.0], np.float32),
                "InAccum": np.asarray([1.0], np.float32)},
               {"bit_length": 8, "moving_rate": 0.9})
    # state = .9*1+1 = 1.9; accum = .9*1+3 = 3.9; scale = 3.9/1.9
    np.testing.assert_allclose(float(out["OutState"][0]), 1.9, rtol=1e-6)
    np.testing.assert_allclose(float(out["OutAccum"][0]), 3.9, rtol=1e-6)
    np.testing.assert_allclose(float(out["OutScale"][0]), 3.9 / 1.9,
                               rtol=1e-6)
    # is_test consumes InScale untouched
    t = _run("fake_quantize_dequantize_moving_average_abs_max",
             {"X": x, "InScale": np.asarray([4.0], np.float32),
              "InState": None, "InAccum": None},
             {"bit_length": 8, "is_test": True})
    np.testing.assert_allclose(np.asarray(t["Out"]), x, atol=4 / 127)


def test_ste_gradient_identity():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.registry import run_kernel, OpContext
    g = run_kernel("fake_quantize_dequantize_abs_max_grad",
                   {"X": jnp.asarray([0.3, -0.7]),
                    "Out@GRAD": jnp.asarray([1.5, -2.5])},
                   {"bit_length": 8}, OpContext())
    np.testing.assert_allclose(np.asarray(g["X@GRAD"]), [1.5, -2.5])


# ---------------------------------------------------------------------------
# QAT end-to-end
# ---------------------------------------------------------------------------
def _mlp_program():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
    return main, startup, loss, pred


def test_qat_transform_and_train():
    from paddle_tpu.slim import QuantizationTransformPass
    main, startup, loss, _ = _mlp_program()
    tp = QuantizationTransformPass()
    with static.program_guard(main, startup):
        tp.apply(main, startup)
        static.Adam(learning_rate=0.01).minimize(loss)
    types = [op.type for op in main.global_block().ops]
    assert "fake_channel_wise_quantize_dequantize_abs_max" in types
    assert "fake_quantize_dequantize_moving_average_abs_max" in types
    # STE grads appended
    assert any(t.endswith("_grad") and t.startswith("fake_") for t in types)

    rng = np.random.RandomState(0)
    xb = rng.rand(32, 8).astype(np.float32)
    yb = xb.sum(1, keepdims=True).astype(np.float32)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        losses = [float(exe.run(main, feed={"x": xb, "y": yb},
                                fetch_list=[loss])[0]) for _ in range(40)]
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])
    # moving-average scale state actually updated
    svars = [n for n in scope.keys() if ".quant_scale" in n
             and scope.get(n) is not None]
    assert svars and any(float(np.asarray(scope.get(n))[0]) > 0.01
                         for n in svars)


def test_ptq_freeze_and_predict():
    """PTQ: calibrate a float model, freeze to int8 weights, accuracy of the
    quantized predictor stays close to float."""
    from paddle_tpu.slim import PostTrainingQuantization
    main, startup, loss, pred = _mlp_program()
    with static.program_guard(main, startup):
        static.Adam(learning_rate=0.02).minimize(loss)
    rng = np.random.RandomState(1)
    xb = rng.rand(64, 8).astype(np.float32)
    yb = xb.sum(1, keepdims=True).astype(np.float32)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        for _ in range(150):
            exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        # inference clone: strip training roles first (the
        # save_inference_model recipe — _prune alone keeps optimizer ops
        # because they write persistables)
        from paddle_tpu.core.program import OpRole
        infer = main.clone(for_test=True)
        blk = infer.global_block()
        train_roles = (OpRole.Backward, OpRole.Optimize, OpRole.LRSched,
                       OpRole.Optimize | OpRole.LRSched)
        blk.ops = [op for op in blk.ops
                   if op.attrs.get(OpRole.KEY, OpRole.Forward)
                   not in train_roles]
        infer = infer._prune([pred.name])
        float_out = exe.run(infer, feed={"x": xb[:8]},
                            fetch_list=[pred])[0]

        ptq = PostTrainingQuantization(exe, infer, ["x"], scope=scope)
        quant = ptq.quantize([{"x": xb[i:i + 8]} for i in range(0, 64, 8)])
        qtypes = [op.type for op in quant.global_block().ops]
        assert "fake_channel_wise_dequantize_max_abs" in qtypes
        # weights now stored int8
        int8_vars = [n for n in scope.keys() if n.endswith(".int8_0")
                     or ".int8" in n]
        assert any(np.asarray(scope.get(n)).dtype == np.int8
                   for n in int8_vars if scope.get(n) is not None)
        q_out = exe.run(quant, feed={"x": xb[:8]}, fetch_list=[pred])[0]
    err = np.abs(q_out - float_out).max() / (np.abs(float_out).max() + 1e-6)
    assert err < 0.1, f"quantization error too large: {err}"


def test_ptq_rejects_qat_program():
    """PTQ on an already-QAT program would double-quantize; it must refuse
    and point at the freeze pass."""
    from paddle_tpu.slim import (QuantizationTransformPass,
                                 PostTrainingQuantization)
    main, startup, loss, pred = _mlp_program()
    with static.program_guard(main, startup):
        QuantizationTransformPass().apply(main, startup)
    exe = static.Executor()
    ptq = PostTrainingQuantization(exe, main, ["x"], scope=static.Scope())
    with pytest.raises(ValueError, match="QAT"):
        ptq.quantize([{"x": np.zeros((2, 8), np.float32)}])


def test_qat_freeze_roundtrip():
    """QAT train -> freeze -> int8 inference matches the QAT eval output
    exactly (same quantization grid)."""
    from paddle_tpu.slim import (QuantizationTransformPass,
                                 QuantizationFreezePass)
    from paddle_tpu.core.program import OpRole
    main, startup, loss, pred = _mlp_program()
    with static.program_guard(main, startup):
        QuantizationTransformPass().apply(main, startup)
        static.Adam(learning_rate=0.01).minimize(loss)
    rng = np.random.RandomState(2)
    xb = rng.rand(16, 8).astype(np.float32)
    yb = xb.sum(1, keepdims=True).astype(np.float32)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        for _ in range(30):
            exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        infer = main.clone(for_test=True)
        blk = infer.global_block()
        roles = (OpRole.Backward, OpRole.Optimize, OpRole.LRSched,
                 OpRole.Optimize | OpRole.LRSched)
        blk.ops = [op for op in blk.ops
                   if op.attrs.get(OpRole.KEY, OpRole.Forward) not in roles]
        infer = infer._prune([pred.name])
        qat_out = exe.run(infer, feed={"x": xb[:4]}, fetch_list=[pred])[0]
        frozen = QuantizationFreezePass().apply(infer, scope)
        int8_out = exe.run(frozen, feed={"x": xb[:4]}, fetch_list=[pred])[0]
    np.testing.assert_allclose(int8_out, qat_out, rtol=1e-5, atol=1e-6)


def test_freeze_keeps_float_scope_and_act_types():
    """Freeze must not delete float weights from the shared scope (the
    original program still runs); activation_quantize_type='abs_max' emits
    dynamic quant ops; unknown types raise."""
    from paddle_tpu.slim import (QuantizationTransformPass,
                                 QuantizationFreezePass)
    main, startup, loss, pred = _mlp_program()
    rng = np.random.RandomState(3)
    xb = rng.rand(8, 8).astype(np.float32)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        infer = main._prune([pred.name])
        f0 = exe.run(infer, feed={"x": xb}, fetch_list=[pred])[0]
        qat = infer.clone(for_test=True)
        QuantizationTransformPass(
            activation_quantize_type="abs_max").apply(qat, None)
        types = [op.type for op in qat.global_block().ops]
        assert "fake_quantize_dequantize_abs_max" in types
        assert "fake_quantize_dequantize_moving_average_abs_max" \
            not in types
        QuantizationFreezePass().apply(qat, scope)
        # original float program still runs on the same scope
        f1 = exe.run(infer, feed={"x": xb}, fetch_list=[pred])[0]
        np.testing.assert_allclose(f1, f0, rtol=1e-6)
    with pytest.raises(ValueError, match="activation_quantize_type"):
        QuantizationTransformPass(
            activation_quantize_type="bogus").apply(
                _mlp_program()[0], None)

"""hapi Model API + model-family tests (reference: python/paddle/tests/
test_model.py pattern + book-test convergence assertions)."""
import os

import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.act = nn.ReLU()
        self.fc2 = nn.Linear(16, 3)

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


def _toy_dataset(n=64, seed=0):
    from paddle_tpu.io import TensorDataset
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 8).astype(np.float32)
    y = (x[:, :3].argmax(1)).astype(np.int64)[:, None]
    return TensorDataset([x, y]), x, y


def test_model_fit_evaluate_predict(tmp_path):
    from paddle_tpu.metric import Accuracy
    ds, x, y = _toy_dataset()
    net = _MLP()
    model = paddle_tpu.Model(net)
    model.prepare(opt.Adam(learning_rate=0.05,
                           parameters=net.parameters()),
                  nn.CrossEntropyLoss(),
                  Accuracy())
    hist = model.fit(ds, batch_size=16, epochs=8, verbose=0)
    assert hist[-1]["loss"] < hist[0]["loss"]
    logs = model.evaluate(ds, batch_size=16, verbose=0)
    assert logs["acc"] > 0.6, logs
    preds = model.predict(ds, batch_size=16, stack_outputs=True)
    assert preds[0].shape == (64, 3)

    path = str(tmp_path / "ckpt" / "model")
    model.save(path)
    assert os.path.exists(path + ".pdparams")
    net2 = _MLP()
    model2 = paddle_tpu.Model(net2)
    model2.prepare(opt.Adam(learning_rate=0.05,
                            parameters=net2.parameters()),
                   nn.CrossEntropyLoss(), Accuracy())
    model2.load(path)
    for p1, p2 in zip(net.parameters(), net2.parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy())


def test_model_callbacks_early_stopping():
    from paddle_tpu.hapi.callbacks import EarlyStopping
    ds, _, _ = _toy_dataset(32)
    net = _MLP()
    model = paddle_tpu.Model(net)
    model.prepare(opt.SGD(learning_rate=0.0,
                          parameters=net.parameters()),
                  nn.CrossEntropyLoss())
    es = EarlyStopping(monitor="loss", patience=1, min_delta=1e-12)
    model.fit(ds, eval_data=ds, batch_size=16, epochs=10, verbose=0,
              callbacks=[es])
    assert model.stop_training  # lr=0 → no improvement → stops early


def test_model_summary():
    net = _MLP()
    model = paddle_tpu.Model(net)
    info = model.summary()
    # 8*16+16 + 16*3+3 = 195
    assert info["total_params"] == 8 * 16 + 16 + 16 * 3 + 3


def test_bert_pretraining_memorizes():
    from paddle_tpu.models import (BertConfig, BertModel,
                                   BertForPretraining,
                                   BertPretrainingCriterion)
    cfg = BertConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=2, intermediate_size=64,
                     max_position_embeddings=32, hidden_dropout_prob=0.0,
                     attention_probs_dropout_prob=0.0)
    model = BertForPretraining(BertModel(cfg))
    crit = BertPretrainingCriterion(cfg.vocab_size)
    optimizer = opt.Adam(learning_rate=1e-3,
                         parameters=model.parameters())
    rng = np.random.RandomState(0)
    ids = paddle_tpu.to_tensor(
        rng.randint(0, 64, (4, 16)).astype(np.int64))
    labels = paddle_tpu.to_tensor(
        rng.randint(0, 64, (4, 16)).astype(np.int64))
    nsp = paddle_tpu.to_tensor(rng.randint(0, 2, (4,)).astype(np.int64))
    losses = []
    for _ in range(30):
        scores, rel = model(ids)
        loss = crit(scores, rel, labels, nsp)
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


def test_transformer_seq2seq_trains():
    from paddle_tpu.models import (TransformerConfig, TransformerModel,
                                   CrossEntropyCriterion)
    cfg = TransformerConfig(src_vocab_size=50, trg_vocab_size=50,
                            d_model=32, n_head=2, num_encoder_layers=1,
                            num_decoder_layers=1, d_inner_hid=64,
                            max_length=32, dropout=0.0)
    model = TransformerModel(cfg)
    crit = CrossEntropyCriterion(label_smooth_eps=0.0)
    optimizer = opt.Adam(learning_rate=2e-3,
                         parameters=model.parameters())
    rng = np.random.RandomState(1)
    src = paddle_tpu.to_tensor(rng.randint(2, 50, (4, 8)).astype(np.int64))
    trg_in = paddle_tpu.to_tensor(
        rng.randint(2, 50, (4, 6)).astype(np.int64))
    trg_out = paddle_tpu.to_tensor(
        rng.randint(2, 50, (4, 6)).astype(np.int64))
    losses = []
    for _ in range(30):
        logits = model(src, trg_in)
        loss = crit(logits, trg_out)
        loss.backward()
        optimizer.step()
        optimizer.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
    decoded = model.beam_search(src, max_len=5)
    assert decoded.shape[0] == 4 and decoded.shape[1] <= 5


def test_model_with_hapi_vision():
    """LeNet from the vision zoo through Model.fit (hapi integration)."""
    from paddle_tpu.vision.models import LeNet
    from paddle_tpu.io import TensorDataset
    rng = np.random.RandomState(0)
    x = rng.rand(32, 1, 28, 28).astype(np.float32)
    y = rng.randint(0, 10, (32, 1)).astype(np.int64)
    ds = TensorDataset([x, y])
    net = LeNet()
    model = paddle_tpu.Model(net)
    model.prepare(opt.Adam(learning_rate=1e-3,
                           parameters=net.parameters()),
                  nn.CrossEntropyLoss())
    hist = model.fit(ds, batch_size=16, epochs=2, verbose=0)
    assert np.isfinite(hist[-1]["loss"])

"""Draft/target speculative decoding (serving/speculative.py + the
engine's batched verify step).

Covers the pure acceptance rule (full-accept, reject-all, mid-chain
rejection), the engine's verify/rollback protocol against scripted
drafts whose proposals are forced to accept or reject (page-table tail
truncation, token-equality either way), real stamped drafts (exact
full-depth stamp accepts everything; a shallow stamp rejects and stays
token-equal), sampling rows riding the spec batch at width 1, and
leak-free pool drain after mixed radix + speculative churn."""
import numpy as np
import pytest

from paddle_tpu.serving import (ContinuousBatchingEngine, PagedKVPool,
                                RadixPrefixCache, SpeculativeDecoder,
                                longest_accepted, metrics, stamp_draft)


# -- acceptance rule (pure math) --------------------------------------------
def test_longest_accepted_matrix():
    # full accept: every draft matches the target's greedy chain
    assert longest_accepted([3, 4, 5], [3, 4, 5, 6]) == 3
    # reject-all: first draft already disagrees -> zero accepted
    assert longest_accepted([9, 4, 5], [3, 4, 5, 6]) == 0
    # chain acceptance: a mid-chain miss invalidates the (coincidental)
    # later match too
    assert longest_accepted([3, 9, 5], [3, 4, 5, 6]) == 1
    # no proposals (the k=0 degenerate row) accepts nothing
    assert longest_accepted([], [3]) == 0


def test_decoder_validation():
    class _Cfg:
        vocab_size, max_position, eos_id, num_layers = 48, 64, 1, 2
        num_heads, hidden_size = 2, 16

    class _M:
        config = _Cfg()

    with pytest.raises(ValueError, match="k must be"):
        SpeculativeDecoder(_M(), k=0)
    spec = SpeculativeDecoder(_M(), k=4)

    class _Other:
        vocab_size, max_position, eos_id = 99, 64, 1
    with pytest.raises(ValueError, match="vocab_size"):
        spec.geometry_check(_Other())


# -- scripted drafts: force the accept/reject matrix through the engine -----
class _ScriptedDecoder(SpeculativeDecoder):
    """Proposals scripted from a known greedy reference sequence: the
    ``mode`` decides whether every proposal matches the target's chain
    (accept) or is perturbed off it (reject).  No draft model runs —
    open/commit/close are bookkeeping no-ops — so the test isolates the
    ENGINE's verify/rollback protocol."""

    def __init__(self, model, script, mode, k=3):
        super().__init__(model, k=k)
        self.script = [int(t) for t in script]
        self.mode = mode
        self.calls = 0

    def open(self, slot, prompt_tokens):
        pass

    def close(self, slot):
        pass

    def commit(self, slot, committed, pending):
        pass

    def propose(self, slot, committed, pending, n=None):
        self.calls += 1
        n = self.k if n is None else min(int(n), self.k)
        pos = len(committed) + 1        # stream = committed + [pending]
        out = self.script[pos:pos + n]
        if self.mode == "reject":
            out = [(t + 1) % self.config.vocab_size for t in out]
        return out


@pytest.fixture(scope="module")
def tiny_lm():
    import paddle_tpu.dygraph as dg
    from paddle_tpu.models import GPTConfig, GPTModel, GPTForGeneration
    with dg.guard():
        cfg = GPTConfig(vocab_size=48, hidden_size=16, num_layers=2,
                        num_heads=2, max_position=64, dropout=0.0)
        m = GPTForGeneration(GPTModel(cfg))
        m.eval()
        yield m


def _ref(model, prompt, max_new):
    pool = PagedKVPool(2, 2, 8, page_tokens=4, num_pages=64)
    eng = ContinuousBatchingEngine(model, max_slots=2,
                                   kv_pool=pool).start()
    try:
        out = np.asarray(eng.submit(prompt, max_length=max_new)
                         .result(timeout=60))
    finally:
        eng.stop()
    pool.assert_drained()
    return out


@pytest.mark.parametrize("mode", ["accept", "reject"])
def test_scripted_accept_reject_token_equal(tiny_lm, mode):
    rng = np.random.RandomState(7)
    prompt = rng.randint(2, 48, (6,)).astype(np.int64)
    ref = _ref(tiny_lm, prompt, 6)
    pool = PagedKVPool(2, 2, 8, page_tokens=4, num_pages=64)
    spec = _ScriptedDecoder(tiny_lm, ref, mode, k=3)
    eng = ContinuousBatchingEngine(tiny_lm, max_slots=2, kv_pool=pool,
                                   speculative=spec).start()
    pre_acc = metrics.counter("spec.accepted")
    pre_prop = metrics.counter("spec.proposed")
    pre_roll = metrics.counter("spec.rollback_cols")
    pre_steps = metrics.counter("spec.steps")
    try:
        out = np.asarray(eng.submit(prompt, max_length=6)
                         .result(timeout=60))
    finally:
        eng.stop()
    np.testing.assert_array_equal(out, ref)
    accepted = metrics.counter("spec.accepted") - pre_acc
    proposed = metrics.counter("spec.proposed") - pre_prop
    rolled = metrics.counter("spec.rollback_cols") - pre_roll
    steps = metrics.counter("spec.steps") - pre_steps
    assert spec.calls > 0 and proposed > 0
    if mode == "accept":
        # full accept: every proposal verified, nothing rolled back,
        # strictly fewer target steps than tokens emitted
        assert accepted == proposed
        assert rolled == 0
        assert steps < 6
    else:
        # reject-all: nothing accepted, every proposed column rolled
        # back through pool.truncate, one target step per token (the
        # plain-greedy floor — never worse than no speculation)
        assert accepted == 0
        assert rolled == proposed
        assert steps == 6 - 1   # prefill emits the first of 6 tokens
    pool.assert_drained()


def test_stamped_draft_full_depth_accepts_all(tiny_lm):
    rng = np.random.RandomState(8)
    prompt = rng.randint(2, 48, (6,)).astype(np.int64)
    ref = _ref(tiny_lm, prompt, 6)
    draft = stamp_draft(tiny_lm, num_layers=2)   # exact copy
    pool = PagedKVPool(2, 2, 8, page_tokens=4, num_pages=64)
    spec = SpeculativeDecoder(draft, k=3)
    eng = ContinuousBatchingEngine(tiny_lm, max_slots=2, kv_pool=pool,
                                   speculative=spec).start()
    pre_steps = metrics.counter("spec.steps")
    pre_tokens = metrics.counter("gen.tokens")
    try:
        out = np.asarray(eng.submit(prompt, max_length=6)
                         .result(timeout=60))
    finally:
        eng.stop()
    np.testing.assert_array_equal(out, ref)
    steps = metrics.counter("spec.steps") - pre_steps
    tokens = metrics.counter("gen.tokens") - pre_tokens
    assert tokens / max(1, steps) > 1.0, (tokens, steps)
    assert spec.draft_tokens > 0
    assert spec.open_slots == 0        # retire closed the draft state
    pool.assert_drained()


def test_shallow_stamp_rejections_stay_token_equal(tiny_lm):
    rng = np.random.RandomState(9)
    prompts = [rng.randint(2, 48, (n,)).astype(np.int64)
               for n in (5, 9)]
    refs = [_ref(tiny_lm, p, 6) for p in prompts]
    draft = stamp_draft(tiny_lm, num_layers=1)   # genuinely wrong draft
    pool = PagedKVPool(2, 2, 8, page_tokens=4, num_pages=64)
    eng = ContinuousBatchingEngine(tiny_lm, max_slots=2, kv_pool=pool,
                                   speculative=SpeculativeDecoder(
                                       draft, k=4)).start()
    try:
        futs = [eng.submit(p, max_length=6) for p in prompts]
        outs = [np.asarray(f.result(timeout=60)) for f in futs]
    finally:
        eng.stop()
    for out, ref in zip(outs, refs):
        np.testing.assert_array_equal(out, ref)
    pool.assert_drained()


def test_sampling_rides_spec_batch_at_width_one(tiny_lm):
    rng = np.random.RandomState(10)
    prompt = rng.randint(2, 48, (5,)).astype(np.int64)
    pool0 = PagedKVPool(2, 2, 8, page_tokens=4, num_pages=64)
    eng0 = ContinuousBatchingEngine(tiny_lm, max_slots=2,
                                    kv_pool=pool0).start()
    try:
        ref = np.asarray(eng0.submit(
            prompt, max_length=6, decode_strategy="sampling", top_k=5,
            seed=21).result(timeout=60))
    finally:
        eng0.stop()
    pool = PagedKVPool(2, 2, 8, page_tokens=4, num_pages=64)
    spec = SpeculativeDecoder(stamp_draft(tiny_lm, num_layers=2), k=3)
    eng = ContinuousBatchingEngine(tiny_lm, max_slots=2, kv_pool=pool,
                                   speculative=spec).start()
    try:
        out = np.asarray(eng.submit(
            prompt, max_length=6, decode_strategy="sampling", top_k=5,
            seed=21).result(timeout=60))
    finally:
        eng.stop()
    # a sampling row never consumes draft proposals, so its per-request
    # RNG stream is untouched and output matches the plain engine
    np.testing.assert_array_equal(out, ref)
    assert spec.draft_tokens == 0
    pool.assert_drained()


def test_pool_drained_after_mixed_radix_spec_churn(tiny_lm):
    rng = np.random.RandomState(11)
    pool = PagedKVPool(2, 2, 8, page_tokens=4, num_pages=32)
    radix = RadixPrefixCache(pool, low_watermark=3, high_watermark=6)
    spec = SpeculativeDecoder(stamp_draft(tiny_lm, num_layers=1), k=3)
    eng = ContinuousBatchingEngine(tiny_lm, max_slots=2, kv_pool=pool,
                                   prefix_cache=radix,
                                   speculative=spec).start()
    head = rng.randint(2, 48, (8,)).astype(np.int64)
    try:
        futs = []
        for i in range(8):
            if i % 2:
                p = np.concatenate([head, [2 + i]]).astype(np.int64)
            else:
                p = rng.randint(2, 48, (4 + i,)).astype(np.int64)
            futs.append(eng.submit(p, max_length=5))
        for f in futs:
            f.result(timeout=120)
    finally:
        eng.stop()
    # retention is active (shared head retired into the tree) yet the
    # drained pool is leak-free; dropping retention frees everything
    assert pool.pages_retained > 0
    pool.assert_drained()
    radix.clear()
    pool.assert_drained()
    assert pool.pages_free == pool.num_pages
    assert spec.open_slots == 0

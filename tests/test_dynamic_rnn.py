"""DynamicRNN + LoD rank-table machinery.

Reference surface: fluid.layers.DynamicRNN
(/root/reference/python/paddle/fluid/layers/control_flow.py:2938) and the
lod_rank_table / lod_tensor_to_array / array_to_lod_tensor /
shrink_rnn_memory / reorder_lod_tensor_by_rank / split_lod_tensor /
merge_lod_tensor op family.  TPU redesign: padded [B, T, ...] + lengths,
one masked lax.scan (ops/kernels/control.py dynamic_rnn), where-masking
instead of batch shrinking.
"""
import numpy as np

import paddle_tpu.static as static
from paddle_tpu.static import layers


def _run(main, startup, feed, fetch):
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        return [np.asarray(o) for o in
                exe.run(main, feed=feed, fetch_list=fetch)]


# ---------------------------------------------------------------------------
# LoD-array op family
# ---------------------------------------------------------------------------
def test_lod_rank_table_and_max_seq_len():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        lens = layers.data("lens", [-1], dtype="int32")
        table = layers.lod_rank_table(length=lens)
        msl = layers.max_sequence_len(table)
    t, m = _run(main, startup,
                {"lens": np.array([2, 5, 3, 5], np.int32)}, [table, msl])
    # stable descending sort: lengths [5,5,3,2], ties keep input order
    assert t[0].tolist() == [1, 3, 2, 0]
    assert t[1].tolist() == [5, 5, 3, 2]
    assert int(m.ravel()[0]) == 5


def test_lod_tensor_array_round_trip():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4, 3], dtype="float32")
        lens = layers.data("lens", [-1], dtype="int32")
        table = layers.lod_rank_table(length=lens)
        arr = layers.lod_tensor_to_array(x, table)
        i = layers.fill_constant([1], "int64", 1)
        step1 = layers.array_read(arr, i)
        back = layers.array_to_lod_tensor(arr, table)
        reord = layers.reorder_lod_tensor_by_rank(x, table)
    xv = np.arange(36, dtype=np.float32).reshape(3, 4, 3)
    lv = np.array([2, 4, 3], np.int32)
    s1, b, r = _run(main, startup, {"x": xv, "lens": lv},
                    [step1, back, reord])
    order = [1, 2, 0]                      # lengths 4, 3, 2
    # step slice 1 = time index 1 of every sequence, in rank order
    np.testing.assert_allclose(s1, xv[order][:, 1])
    # round trip restores input order exactly
    np.testing.assert_allclose(b, xv)
    np.testing.assert_allclose(r, xv[order])


def test_split_merge_lod_tensor_round_trip():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 3], dtype="float32")
        mask = layers.data("mask", [-1], dtype="bool")
        t, f = layers.split_lod_tensor(x, mask)
        merged = layers.merge_lod_tensor(t, f, x, mask)
        # shrink_rnn_memory is identity on TPU (masking replaces shrink)
        i = layers.fill_constant([1], "int64", 0)
        table = layers.lod_rank_table(
            length=layers.cast(mask, "int32"))
        kept = layers.shrink_memory(x, i, table)
    xv = np.arange(12, dtype=np.float32).reshape(4, 3)
    mv = np.array([True, False, True, False])
    tv, fv, mg, kp = _run(main, startup, {"x": xv, "mask": mv},
                          [t, f, merged, kept])
    np.testing.assert_allclose(tv[mv], xv[mv])
    np.testing.assert_allclose(tv[~mv], 0)
    np.testing.assert_allclose(fv[~mv], xv[~mv])
    np.testing.assert_allclose(fv[mv], 0)
    np.testing.assert_allclose(mg, xv)
    np.testing.assert_allclose(kp, xv)


def test_lod_array_backward():
    """Gradients flow through the to/from-array permutation pair (each
    grad is the inverse transform — explicit kernels in lod_array.py)."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [3, 4, 2], dtype="float32")
        lens = layers.data("lens", [3], dtype="int32")
        table = layers.lod_rank_table(length=lens)
        h = layers.fc(x, size=2, num_flatten_dims=2)
        arr = layers.lod_tensor_to_array(h, table)
        back = layers.array_to_lod_tensor(arr, table)
        proj = layers.fc(back, size=2, num_flatten_dims=2)  # uses shape
        loss = layers.mean(proj)
        static.SGD(learning_rate=0.1).minimize(loss)
    exe = static.Executor()
    scope = static.Scope()
    xv = np.random.RandomState(0).randn(3, 4, 2).astype(np.float32)
    lv = np.array([2, 4, 1], np.int32)
    with static.scope_guard(scope):
        exe.run(startup)
        l0 = float(np.asarray(exe.run(
            main, feed={"x": xv, "lens": lv}, fetch_list=[loss])[0]))
        l1 = float(np.asarray(exe.run(
            main, feed={"x": xv, "lens": lv}, fetch_list=[loss])[0]))
    assert l1 != l0  # parameters moved: grads reached the fc weights


# ---------------------------------------------------------------------------
# DynamicRNN forward semantics
# ---------------------------------------------------------------------------
def test_dynamic_rnn_masked_accumulation():
    """Memory freezes at each sequence's last real step; outputs zero in
    padding; sequence_last_step reads the frozen value — the observable
    contract of the reference's shrinking executor."""
    B, T, D = 3, 5, 2
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [B, T, D], dtype="float32")
        lens = layers.data("lens", [B], dtype="int32")
        drnn = layers.DynamicRNN()
        with drnn.block():
            xt = drnn.step_input(x, length=lens)
            mem = drnn.memory(shape=[D])
            acc = layers.elementwise_add(mem, xt)
            drnn.update_memory(mem, acc)
            drnn.output(acc)
        out = drnn()
        last = layers.sequence_last_step(out, length=lens)
    xv = np.random.RandomState(0).randn(B, T, D).astype(np.float32)
    lv = np.array([3, 5, 1], np.int32)
    ov, lastv = _run(main, startup, {"x": xv, "lens": lv}, [out, last])
    for b in range(B):
        n = lv[b]
        expect = np.cumsum(xv[b, :n], axis=0)
        np.testing.assert_allclose(ov[b, :n], expect, rtol=1e-5)
        np.testing.assert_allclose(ov[b, n:], 0, atol=0)
        np.testing.assert_allclose(lastv[b], expect[-1], rtol=1e-5)


def test_dynamic_rnn_static_input_and_boot_memory():
    """static_input visibility + memory(init=..., need_reorder=True)."""
    B, T, D, H = 2, 4, 3, 3
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [B, T, D], dtype="float32")
        lens = layers.data("lens", [B], dtype="int32")
        boot = layers.data("boot", [B, H], dtype="float32")
        stat = layers.data("stat", [B, H], dtype="float32")
        drnn = layers.DynamicRNN()
        with drnn.block():
            xt = drnn.step_input(x, length=lens)
            sv = drnn.static_input(stat)
            mem = drnn.memory(init=boot, need_reorder=True)
            nxt = layers.elementwise_add(layers.elementwise_add(mem, xt),
                                         sv)
            drnn.update_memory(mem, nxt)
            drnn.output(nxt)
        out = drnn()
    rng = np.random.RandomState(1)
    xv = rng.randn(B, T, D).astype(np.float32)
    bv = rng.randn(B, H).astype(np.float32)
    sv_ = rng.randn(B, H).astype(np.float32)
    lv = np.array([4, 2], np.int32)
    (ov,) = _run(main, startup,
                 {"x": xv, "lens": lv, "boot": bv, "stat": sv_}, [out])
    for b in range(B):
        h = bv[b].copy()
        for t in range(lv[b]):
            h = h + xv[b, t] + sv_[b]
            np.testing.assert_allclose(ov[b, t], h, rtol=1e-5)
        np.testing.assert_allclose(ov[b, lv[b]:], 0, atol=0)


# ---------------------------------------------------------------------------
# training through DynamicRNN
# ---------------------------------------------------------------------------
def _train(main, startup, feeds_fn, loss, iters=30):
    exe = static.Executor()
    scope = static.Scope()
    losses = []
    with static.scope_guard(scope):
        exe.run(startup)
        for i in range(iters):
            out = exe.run(main, feed=feeds_fn(i), fetch_list=[loss])
            losses.append(float(np.asarray(out[0])))
    return losses


def test_dynamic_rnn_trains():
    """Gradients flow through the masked scan: a tanh RNN learns to
    classify ragged sequences by their (masked) mean sign."""
    B, T, D, H = 8, 6, 4, 8
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [B, T, D], dtype="float32")
        lens = layers.data("lens", [B], dtype="int32")
        y = layers.data("y", [B, 1], dtype="int64")
        drnn = layers.DynamicRNN()
        with drnn.block():
            xt = drnn.step_input(x, length=lens)
            mem = drnn.memory(shape=[H])
            h = layers.fc(layers.concat([xt, mem], axis=1), size=H,
                          act="tanh")
            drnn.update_memory(mem, h)
            drnn.output(h)
        out = drnn()
        last = layers.sequence_last_step(out, length=lens)
        logits = layers.fc(last, size=2)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, y))
        static.Adam(learning_rate=1e-2).minimize(loss)

    rng = np.random.RandomState(3)

    def feeds(i):
        xv = rng.randn(B, T, D).astype(np.float32)
        lv = rng.randint(1, T + 1, B).astype(np.int32)
        mask = (np.arange(T)[None, :] < lv[:, None])[..., None]
        yv = (np.sum(xv * mask, axis=(1, 2)) > 0).astype(np.int64)
        return {"x": xv, "lens": lv, "y": yv[:, None]}

    losses = _train(main, startup, feeds, loss, iters=60)
    assert losses[-1] < losses[0] * 0.8, losses


def test_machine_translation_dynamic_decoder():
    """book/test_machine_translation.py shape: GRU encoder over ragged
    source, DynamicRNN teacher-forced decoder with the encoder summary as
    boot memory (need_reorder=True in the reference) — learns to copy."""
    vocab, emb_dim, hid = 20, 16, 16
    B, seq = 16, 6
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        src = layers.data("src", [B, seq], dtype="int64")
        src_len = layers.data("src_len", [B], dtype="int32")
        tgt_in = layers.data("tgt_in", [B, seq], dtype="int64")
        tgt_out = layers.data("tgt_out", [B, seq, 1], dtype="int64")
        tgt_len = layers.data("tgt_len", [B], dtype="int32")
        # encoder
        semb = layers.embedding(src, size=[vocab, emb_dim])
        egate = layers.fc(semb, size=3 * hid, num_flatten_dims=2)
        enc = layers.dynamic_gru(egate, size=hid)
        boot = layers.sequence_last_step(enc, length=src_len)   # [B, hid]
        # decoder on DynamicRNN (reference uses gru_unit inside the block)
        temb = layers.embedding(tgt_in, size=[vocab, emb_dim])
        drnn = layers.DynamicRNN()
        with drnn.block():
            word = drnn.step_input(temb, length=tgt_len)
            mem = drnn.memory(init=boot, need_reorder=True)
            dec_in = layers.fc(layers.concat([word, mem], axis=1),
                               size=3 * hid)
            h, _, _ = layers.gru_unit(input=dec_in, hidden=mem,
                                      size=3 * hid)
            drnn.update_memory(mem, h)
            out = layers.fc(h, size=vocab)
            drnn.output(out)
        logits = drnn()                                    # [B, seq, vocab]
        mask = layers.cast(layers.sequence_mask(tgt_len, maxlen=seq),
                           "float32")
        ce = layers.softmax_with_cross_entropy(logits, tgt_out)
        loss = layers.reduce_sum(ce * layers.unsqueeze(mask, [2])) \
            / layers.reduce_sum(mask)
        static.Adam(learning_rate=1e-2).minimize(loss)

    rng = np.random.RandomState(2)

    def feeds(i):
        s = rng.randint(2, vocab, (B, seq)).astype(np.int64)
        lv = rng.randint(2, seq + 1, B).astype(np.int32)
        ti = np.concatenate([np.ones((B, 1), np.int64), s[:, :-1]], axis=1)
        return {"src": s, "src_len": lv, "tgt_in": ti,
                "tgt_out": s[..., None], "tgt_len": lv}

    losses = _train(main, startup, feeds, loss, iters=80)
    assert losses[-1] < losses[0] * 0.8, losses

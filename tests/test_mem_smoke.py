"""Tier-1 memory-accounting gate (NOT marked slow — a regression in the
HBM estimator or a remat-induced retrace must fail the suite, not wait
for a perf round).

Drives tools/mem_smoke.py in-process: bert-tiny estimated with and
without the FLAGS_recompute=always rewrite in under 10 s, the expected
activation-peak reduction, and zero post-warmup retraces on the
rewritten program.  Mirrors the perf_smoke/ckpt_smoke gate pattern;
the CLI round-trip is `slow` (a fresh interpreter + jit warmup buys no
extra coverage over the in-process gate — run it in perf rounds).
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_mem_smoke_gate():
    import mem_smoke
    result = mem_smoke.run_smoke(steps=2)
    assert result["value"] > 0, result            # peak actually shrank
    assert result["estimate_wall_s"] < 10, result
    assert result["traces_after_warmup"] == 0, result
    assert result["barriers"] >= 1, result
    assert result["remat_peak_bytes"] < result["plain_peak_bytes"], result


@pytest.mark.slow
def test_mem_smoke_cli_prints_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "mem_smoke.py"),
         "--steps", "2"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["traces_after_warmup"] == 0
    assert result["value"] > 0

"""Auto-parallel planner (static/planner.py): argmax correctness,
candidate-verification property, cost-model monotonicity, the post-hoc
remat rewrite's numerical equivalence, and the V504 plan-drift code.

The planner's contract (ISSUE 10): every candidate is a REAL rewrite on
a clone, priced by the three substrates (HBM walker / FLOPs walker /
ring-accounted wire bytes), gated through
`check_program(level="collective")` — so the search space never
contains a deadlocking plan — and the chosen plan is recorded in the
applied-passes registry so later hand-edits are flagged as drift.
"""
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import paddle_tpu.static as static
from paddle_tpu.core.pass_framework import applied_passes, has_applied
from paddle_tpu.core.program import _reset_unique_names

WORLD = 8


def _tiny(layers_n=2, seq=32, hidden=64, vocab=256):
    import perf_smoke
    _reset_unique_names()
    return perf_smoke.build_bert_tiny(vocab=vocab, seq=seq, hidden=hidden,
                                      layers_n=layers_n)


# ---------------------------------------------------------------------------
# property: every emitted plan is collective-clean under strict mode
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("knobs", [
    None,
    {"grad_merge": (1,)},
    {"dp_shard": (WORLD,), "bucket_mb": (1,)},
    {"remat": (True,), "grad_merge": (2,)},
])
def test_every_emitted_plan_is_strict_clean(knobs):
    main, startup, loss, _ = _tiny()
    plan = static.plan_program(main, startup, world=WORLD, batch=8,
                               knobs=knobs)
    # every candidate the search kept feasible was verified clean
    for cand in plan.trace:
        if cand["fits"]:
            assert cand["verdict"].startswith("verified"), cand
    # the chosen plan, applied for real, is strict-clean with ZERO
    # diagnostics — including the V504 drift check against the record
    static.apply_plan(main, startup, plan)
    report = static.check_program(main, level="collective",
                                  startup=startup, fetch_list=[loss],
                                  raise_on_error=True)
    assert not report.diagnostics, report.render()
    assert has_applied(main, "auto_parallel_plan")


def test_chosen_plan_ties_or_beats_every_feasible_candidate():
    main, startup, loss, _ = _tiny()
    plan = static.plan_program(main, startup, world=WORLD, batch=8)
    feas = [c for c in plan.trace if c["fits"]]
    assert feas
    best = max(c["samples_per_sec"] for c in feas)
    assert plan.predicted_samples_per_sec >= best - 1e-9


# ---------------------------------------------------------------------------
# monotonicity
# ---------------------------------------------------------------------------
def test_budget_monotonicity_looser_budget_never_slower():
    """The planner is a proper argmin over a feasibility set: shrinking
    the HBM budget can only shrink the feasible set, so the chosen
    plan's predicted step time is non-decreasing as the budget tightens
    (equivalently: a looser budget never yields a slower plan)."""
    main, startup, loss, _ = _tiny()
    plain = static.analyze_program(main, batch=8)
    # budgets: loose (everything fits) .. tight (plain no longer fits,
    # remat should) — derived from the walked peaks so the test does
    # not bake in absolute byte counts
    loose = plain["peak_bytes"] * 2
    tight = int(plain["peak_bytes"] / 1.10) - 1  # plain misses the slack
    prev_ms = None
    for budget in (loose, tight):
        m, s, loss_i, _ = _tiny()
        plan = static.plan_program(m, s, world=1, batch=8,
                                   hbm_budget=budget)
        if not plan.predicted_fits:
            break  # nothing fits at all: no feasible step time to rank
        if prev_ms is not None:
            assert plan.predicted_step_ms >= prev_ms - 1e-9, (
                f"tighter budget produced a FASTER plan "
                f"({plan.predicted_step_ms} < {prev_ms})")
        prev_ms = plan.predicted_step_ms
    # and the tight budget actually flipped the knob: remat chosen
    m, s, loss_i, _ = _tiny()
    plan_tight = static.plan_program(m, s, world=1, batch=8,
                                     hbm_budget=tight)
    assert plan_tight.predicted_fits
    assert plan_tight.knobs["remat"] is True


def test_world_monotonicity_wire_time_per_sample_never_worsens():
    """Growing the data-parallel world never worsens predicted wire
    time per GLOBAL sample: per-rank ring bytes grow like 2(N-1)/N
    (bounded) while samples per step grow like N."""
    per_sample = []
    for world in (2, 4, 8):
        main, startup, loss, _ = _tiny()
        plan = static.plan_program(
            main, startup, world=world, batch=8,
            knobs={"remat": (False,), "dp_shard": (0,),
                   "grad_merge": (1,)})
        per_sample.append(plan.predicted_wire_ms / (plan.batch * world))
    assert per_sample[0] >= per_sample[1] >= per_sample[2], per_sample


# ---------------------------------------------------------------------------
# post-hoc remat rewrite (the planner's remat knob)
# ---------------------------------------------------------------------------
def test_apply_recompute_posthoc_numerics_and_peak():
    """`apply_recompute` on a finished program must (a) cut the walked
    activation peak like the build-time rewrite and (b) leave training
    numerics unchanged — the replay computes the same values the
    backward read before."""
    main, startup, loss, _ = _tiny()
    clone = main.clone()
    static.apply_recompute(clone)
    assert has_applied(clone, "recompute")
    n_barriers = sum(1 for op in clone.global_block().ops
                     if op.type == "optimization_barrier")
    assert n_barriers >= 1
    plain_mem = static.analyze_program(main, batch=8)
    remat_mem = static.analyze_program(clone, batch=8)
    assert remat_mem["activation_peak_bytes"] < \
        plain_mem["activation_peak_bytes"]

    rng = np.random.RandomState(0)
    feed = {"ids": rng.randint(0, 256, (4, 32)).astype(np.int64),
            "labels": rng.randint(0, 256, (4, 32, 1)).astype(np.int64)}

    def run(prog):
        exe, scope = static.Executor(), static.Scope()
        with static.scope_guard(scope):
            exe.run(startup)
            return [np.asarray(
                exe.run(prog, feed=feed, fetch_list=[loss.name])[0])
                for _ in range(3)]

    for a, b in zip(run(main), run(clone)):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_apply_recompute_idempotent():
    main, startup, loss, _ = _tiny()
    static.apply_recompute(main)
    n_ops = len(main.global_block().ops)
    static.apply_recompute(main)  # registry-guarded no-op
    assert len(main.global_block().ops) == n_ops


# ---------------------------------------------------------------------------
# V504 plan drift
# ---------------------------------------------------------------------------
def test_plan_drift_v504_fires_on_hand_edit_after_planning():
    """Mutation test (ISSUE 10 acceptance): apply a plan, then hand-
    apply a knob the plan did not choose — the verifier must flag V504
    with the planned-vs-applied values."""
    main, startup, loss, _ = _tiny()
    plan = static.plan_program(main, startup, world=1, batch=8,
                               knobs={"remat": (False,),
                                      "grad_merge": (1,)})
    static.apply_plan(main, startup, plan)
    clean = static.check_program(main, level="collective", startup=startup)
    assert "V504" not in clean.codes()
    # the hand-edit: gradient_merge k=4 was never planned
    static.gradient_merge(main, 4, startup)
    drifted = static.check_program(main, level="collective",
                                   startup=startup)
    assert any(d.code == "V504" for d in drifted.errors), drifted.render()
    msg = next(d.message for d in drifted.errors if d.code == "V504")
    assert "grad_merge" in msg


def test_plan_drift_v504_fires_on_missing_pass():
    """The reverse mutation: the plan chose remat but the rewrite was
    stripped (or never applied) — same drift code."""
    main, startup, loss, _ = _tiny()
    from paddle_tpu.core.pass_framework import record_applied
    record_applied(main, "auto_parallel_plan", batch=8, remat=True,
                   dp_shard=0, grad_merge=1, bucket_mb=0, ring=False)
    report = static.check_program(main, level="collective")
    assert any(d.code == "V504" and "remat" in d.message
               for d in report.errors), report.render()


def test_plan_drift_v504_scan_hoist_missing_pass():
    """Mutation (ISSUE 16): the plan chose the scanned commit-tail
    hoist but `mark_scan_hoist` never recorded — the runtime would
    silently run the looped K-publish window the plan priced away."""
    main, startup, loss, _ = _tiny()
    from paddle_tpu.core.pass_framework import record_applied
    static.gradient_merge(main, 4, startup)
    record_applied(main, "auto_parallel_plan", batch=8, remat=False,
                   dp_shard=0, zero_stage=0, grad_merge=4, bucket_mb=0,
                   ring=False, tp_degree=0, scan_hoist=True)
    report = static.check_program(main, level="collective",
                                  startup=startup)
    assert any(d.code == "V504" and "scan_hoist" in d.message
               for d in report.errors), report.render()


def test_plan_drift_v504_scan_hoist_hand_marked():
    """The reverse mutation: the plan said LOOPED (scan_hoist False)
    but someone hand-marked the hoist after planning."""
    from paddle_tpu.distributed.scan_window import mark_scan_hoist
    main, startup, loss, _ = _tiny()
    plan = static.plan_program(main, startup, world=1, batch=8,
                               knobs={"remat": (False,),
                                      "grad_merge": (4,),
                                      "scan_hoist": (False,)})
    static.apply_plan(main, startup, plan)
    clean = static.check_program(main, level="collective", startup=startup)
    assert "V504" not in clean.codes(), clean.render()
    mark_scan_hoist(main)
    drifted = static.check_program(main, level="collective",
                                   startup=startup)
    assert any(d.code == "V504" and "scan_hoist" in d.message
               for d in drifted.errors), drifted.render()


def test_plan_prefers_fitting_knobs_over_infeasible_plain():
    """The planner's whole point: when plain doesn't fit, the chosen
    plan carries the knob that makes it fit (remat here), with a FITS
    verdict."""
    main, startup, loss, _ = _tiny(layers_n=3)
    plain = static.analyze_program(main, batch=8)
    tight = int(plain["peak_bytes"] / 1.10) - 1
    plan = static.plan_program(main, startup, world=1, batch=8,
                               hbm_budget=tight)
    assert plan.predicted_fits
    assert plan.knobs["remat"] is True
    plain_cand = [c for c in plan.trace
                  if not c["remat"] and c["grad_merge"] == 1][0]
    assert not plain_cand["fits"]


# ---------------------------------------------------------------------------
# BASELINE decision-table acceptance (ISSUE 10)
# ---------------------------------------------------------------------------
def test_planner_rediscovers_bert96_remat_verdict():
    """Tier-1 slice of the decision-table acceptance: on the real
    bert-base b96 shape the planner must rediscover the hand-tuned
    verdict (remat flips predicted OOM to FITS) with the documented
    walked peak, unprompted."""
    import bench
    _reset_unique_names()
    main, startup, _ = bench.build_bert_base(30522, 512, 768, 12, 12, 96,
                                             use_amp=True)
    plan = static.plan_program(main, startup, world=1, batch=96,
                               knobs={"grad_merge": (1,)})
    assert plan.predicted_fits
    assert plan.knobs["remat"] is True
    # the docs/perf.md hand row: b96+remat walks 7.8 GiB.  (Was 14.0
    # before the ISSUE-11 liveness fix: buffers read only through
    # alias/fusable views — remat's replay aliases among them — were
    # never freed by the sweep; un-rematerialized peaks are unchanged,
    # see the "Full parameter sharding" docs section.)
    assert abs(plan.predicted_peak_bytes / 2 ** 30 - 7.8) < 0.5
    plain = [c for c in plan.trace if not c["remat"]][0]
    assert not plain["fits"]          # b96 plain walks 24.9 GiB: OOM


def _fc_tower(width=512, depth=6):
    from paddle_tpu.static import layers
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, width])
        y = layers.data("y", [-1, 1])
        h = x
        for _ in range(depth):
            h = layers.fc(h, width, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss)
    return main, startup, loss


def test_planner_searches_zero_stages_and_picks_zero3_unprompted():
    """ISSUE 11 acceptance: for a shape whose PARAM bytes exceed the
    chip budget — so replicated-param plans (plain AND ZeRO-1) are
    infeasible — the planner searches the zero2/zero3 axes and picks a
    stage unprompted, with a walker-verified predicted_fits flip."""
    import numpy as np
    main, startup, loss = _fc_tower()
    param_bytes = sum(int(np.prod(p.shape)) * 4
                      for p in main.all_parameters())
    # params alone exceed the chip; the +2 MiB headroom covers the
    # stage-3 backward-gather PREFETCH double buffer (two gathered
    # 1-MiB buckets live at once — the walker charges the overlap the
    # prefetch really costs), still far under any replicated-param peak
    budget = int(param_bytes * 0.9) + 2 * 2 ** 20
    plan = static.plan_program(main, startup, world=8, batch=4,
                               hbm_budget=budget,
                               knobs={"batch": (4,), "grad_merge": (1,),
                                      "bucket_mb": (1,)})
    stages = {c["zero_stage"] for c in plan.trace}
    assert {0, 1, 3} <= stages        # the axes were actually searched
    assert plan.predicted_fits
    assert plan.knobs["zero_stage"] == 3
    assert plan.predicted_peak_bytes < param_bytes
    for c in plan.trace:              # every replicated-param plan OOMs
        if c["zero_stage"] < 3:
            assert not c["fits"]


def test_zero3_plan_trains_on_the_mesh():
    """The chosen zero3 plan is not just priced — applied for real it
    trains on the 8-device mesh with finite loss and zero post-warmup
    retraces."""
    import numpy as np
    from paddle_tpu.distributed.compiled_program import CompiledProgram
    main, startup, loss = _fc_tower(width=8, depth=2)
    plan = static.plan_program(main, startup, world=8, batch=8,
                               knobs={"batch": (8,), "grad_merge": (1,),
                                      "dp_shard": (8,),
                                      "zero_stage": (3,)})
    assert plan.knobs["zero_stage"] == 3
    static.apply_plan(main, startup, plan)
    rep = static.check_program(main, level="collective", startup=startup)
    assert rep.ok, rep.render()
    compiled = CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(0)
    with static.scope_guard(scope):
        exe.run(startup)
        for i in range(4):
            out = exe.run(compiled,
                          feed={"x": rng.rand(8, 8).astype("float32"),
                                "y": rng.rand(8, 1).astype("float32")},
                          fetch_list=[loss])
            if i == 0:
                warm = len(compiled._cache)
        assert np.isfinite(np.asarray(out[0])).all()
        assert len(compiled._cache) == warm


@pytest.mark.slow
def test_decision_table_planner_matches_or_beats_hand_verdicts():
    """Full ISSUE 10 acceptance: the planner ties or beats the
    hand-tuned docs/perf.md decision table (predicted step time, FITS)
    on every BASELINE shape — tools/plan_decision_table.py exits 0."""
    import subprocess
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "plan_decision_table.py")],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, (out.stdout + out.stderr)[-3000:]


def test_planner_pins_preapplied_knobs():
    """A program already rewritten (build-time remat, pre-sharded)
    cannot un-apply those knobs — the lattice must pin them instead of
    emitting candidates the clone cannot realize."""
    from paddle_tpu.distributed.sharding import shard_optimizer_states
    main, startup, loss, _ = _tiny()
    shard_optimizer_states(main, startup, dp_degree=WORLD)
    plan = static.plan_program(main, startup, world=WORLD, batch=8)
    assert all(c["dp_shard"] == WORLD for c in plan.trace)
    assert plan.knobs["dp_shard"] == WORLD
    # ... and plan+apply on the pinned program must not V504
    static.apply_plan(main, startup, plan)
    report = static.check_program(main, level="collective",
                                  startup=startup)
    assert "V504" not in report.codes(), report.render()
    # a pre-sharded degree OUTSIDE the default (0, world) axis pins
    # through the axis — the batch search must survive, not collapse
    # to the batch=1 fallback
    main4, startup4, loss4, _ = _tiny()
    shard_optimizer_states(main4, startup4, dp_degree=4)
    plan4 = static.plan_program(main4, startup4, world=WORLD)
    assert plan4.knobs["dp_shard"] == 4
    assert len({c["batch"] for c in plan4.trace}) > 1
    assert plan4.batch > 1


def test_planner_pins_preapplied_gradient_merge():
    """A pre-merged program pins grad_merge=k: the plan records the
    truth, apply_plan is a no-op for that knob, and no spurious V504
    fires (the plan/apply round-trip on an already-rewritten program is
    a legitimate, drift-free flow)."""
    main, startup, loss, _ = _tiny()
    static.gradient_merge(main, 2, startup)
    plan = static.plan_program(main, startup, world=1, batch=8)
    assert plan.knobs["grad_merge"] == 2
    assert all(c["grad_merge"] == 2 for c in plan.trace)
    static.apply_plan(main, startup, plan)
    report = static.check_program(main, level="collective",
                                  startup=startup)
    assert "V504" not in report.codes(), report.render()


def test_planner_pins_ring_built_program():
    """A program built with ring attention can't drop the op — the ring
    knob pins True even without a variants= pair, the trace is labeled
    truthfully, and apply_plan accepts the plan on the same program."""
    from paddle_tpu.static import layers, nets
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ids = layers.data("ids", [-1, 16], dtype="int64")
        labels = layers.data("labels", [-1, 16, 1], dtype="int64")
        h = layers.embedding(ids, size=[64, 32])
        q = layers.fc(h, 32, num_flatten_dims=2)
        k = layers.fc(h, 32, num_flatten_dims=2)
        v = layers.fc(h, 32, num_flatten_dims=2)
        ctx = nets.scaled_dot_product_attention(q, k, v, num_heads=2,
                                                sequence_parallel=True)
        logits = layers.fc(ctx, 64, num_flatten_dims=2)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits,
                                                             labels))
        static.Adam(learning_rate=1e-3).minimize(loss)
    plan = static.plan_program(main, startup, world=1, batch=4)
    assert plan.knobs["ring"] is True
    assert all(c["ring"] for c in plan.trace)
    static.apply_plan(main, startup, plan)   # must not raise
    report = static.check_program(main, level="collective",
                                  startup=startup)
    assert "V504" not in report.codes(), report.render()


# ---------------------------------------------------------------------------
# tp_degree lattice axis (ISSUE 15)
# ---------------------------------------------------------------------------
_TP_GEOM = dict(vocab_size=128, hidden=64, num_layers=2, num_heads=4,
                seq_len=32, learning_rate=1e-2)


def _build_lm(tp=1):
    from paddle_tpu.models import build_transformer_lm
    _reset_unique_names()
    main, startup, loss, _ = build_transformer_lm(
        vocab_size=_TP_GEOM["vocab_size"], hidden=_TP_GEOM["hidden"],
        num_layers=_TP_GEOM["num_layers"], num_heads=_TP_GEOM["num_heads"],
        seq_len=_TP_GEOM["seq_len"], tensor_parallel_degree=tp)
    import paddle_tpu.static as _s
    with _s.program_guard(main, startup):
        _s.Adam(learning_rate=_TP_GEOM["learning_rate"]).minimize(loss)
    return main, startup, loss


def test_tp_lattice_from_hand_variants_prices_both_axes():
    """A hand-fed {"tp": {2: pair}} variant puts tp on the lattice:
    2-D candidates carry per-axis wire with the mp ring priced at its
    OWN degree (batch-proportional activations included), and dp_shard
    candidates under tp shrink to the dp sub-world."""
    base = _build_lm(tp=1)
    tp2 = _build_lm(tp=2)
    plan = static.plan_program(base[0], base[1], world=WORLD, batch=8,
                               knobs={"grad_merge": (1,)},
                               variants={"tp": {2: (tp2[0], tp2[1])}})
    tp_cands = [c for c in plan.trace if c["tp_degree"] == 2]
    assert tp_cands, plan.render_table()
    for c in tp_cands:
        if c["fits"]:
            assert c["wire_bytes_per_axis"].get("mp", 0) > 0, c
            assert c["verdict"].startswith("verified"), c
        assert c["dp_shard"] in (0, WORLD // 2), c
    # the mp wire is batch-proportional: replanning at twice the batch
    # must grow it
    base2 = _build_lm(tp=1)
    tp2b = _build_lm(tp=2)
    plan2 = static.plan_program(base2[0], base2[1], world=WORLD, batch=16,
                                knobs={"grad_merge": (1,)},
                                variants={"tp": {2: (tp2b[0], tp2b[1])}})
    mp8 = next(c["wire_bytes_per_axis"]["mp"] for c in plan.trace
               if c["tp_degree"] == 2 and not c["remat"]
               and not c["dp_shard"])
    mp16 = next(c["wire_bytes_per_axis"]["mp"] for c in plan2.trace
                if c["tp_degree"] == 2 and not c["remat"]
                and not c["dp_shard"])
    assert mp16 > mp8, (mp8, mp16)


def test_tp_lattice_charges_compute_and_hbm_at_one_over_tp():
    """The 2-D pricing contract: a tp=2 candidate's walked HBM peak and
    compute leg both drop below the same-batch pure-dp candidate's
    (sharded weights/activations at 1/tp, mp-stamped matmul FLOPs at
    1/tp)."""
    base = _build_lm(tp=1)
    tp2 = _build_lm(tp=2)
    plan = static.plan_program(base[0], base[1], world=WORLD, batch=8,
                               knobs={"grad_merge": (1,), "remat": (False,),
                                      "dp_shard": (0,)},
                               variants={"tp": {2: (tp2[0], tp2[1])}})
    dp_c = next(c for c in plan.trace if not c["tp_degree"])
    tp_c = next(c for c in plan.trace if c["tp_degree"] == 2)
    assert tp_c["peak_bytes"] < dp_c["peak_bytes"], (dp_c, tp_c)
    assert tp_c["compute_ms"] < dp_c["compute_ms"], (dp_c, tp_c)


def test_planner_picks_4x2_unprompted_when_pure_dp_infeasible():
    """The ISSUE 15 acceptance core (also gated by tools/
    tp_plan_smoke.py): with tp variants auto-generated from a model
    config — never hand-fed — and a budget below the best pure-dp walk,
    the planner chooses the 4×2 dp×tp plan."""
    from paddle_tpu.static.memory_analysis import XLA_REMAT_SLACK
    base = _build_lm(tp=1)
    knobs = {"batch": (8,), "grad_merge": (1,), "zero_stage": (1,)}
    probe = static.plan_program(base[0], base[1], world=WORLD,
                                hbm_budget=1 << 50,
                                knobs=dict(knobs, tp_degree=(0, 2)),
                                model_config=_TP_GEOM, verify=False)
    best_dp = min(c["peak_bytes"] for c in probe.trace
                  if not c["tp_degree"] and c["peak_bytes"] > 0)
    base2 = _build_lm(tp=1)
    plan = static.plan_program(base2[0], base2[1], world=WORLD,
                               hbm_budget=int(best_dp / XLA_REMAT_SLACK) - 1,
                               knobs=dict(knobs), model_config=_TP_GEOM)
    assert plan.predicted_fits, plan.render_table()
    assert plan.knobs["tp_degree"] == 2, plan.render_table()
    assert all(not c["fits"] for c in plan.trace if not c["tp_degree"])
    assert 2 in plan.build_variants


def test_global_batch_constraint_gm_tp_candidate_wins():
    """ISSUE 15 acceptance: when the user demands a global batch no
    single-chip plan can hold, the effective-global-batch constraint
    turns gm×tp candidates into feasible winners instead of the search
    returning predicted_fits=False."""
    from paddle_tpu.static.memory_analysis import XLA_REMAT_SLACK
    base = _build_lm(tp=1)
    # dp_shard pinned off: ZeRO slot sharding would undercut the
    # pure-dp floor below the gm accumulators' cost and close the
    # budget window this scenario needs (demanded batch + tight HBM)
    knobs = {"batch": (4, 8), "zero_stage": (1,), "remat": (False,),
             "dp_shard": (0,), "tp_degree": (0, 2)}
    probe = static.plan_program(base[0], base[1], world=WORLD,
                                hbm_budget=1 << 50, knobs=dict(knobs),
                                model_config=_TP_GEOM, verify=False)
    # premise: every batch-8 plan (any axis) and every pure-dp plan is
    # walker-infeasible, while the gm×tp winner (batch 4, tp 2, gm 2 —
    # the only lattice point reaching the demanded global batch) fits
    floor = min(c["peak_bytes"] for c in probe.trace
                if c["peak_bytes"] > 0 and
                (not c["tp_degree"] or c["batch"] > 4))
    win_peak = min(c["peak_bytes"] for c in probe.trace
                   if c["tp_degree"] == 2 and c["batch"] == 4
                   and c["grad_merge"] == 2 and c["peak_bytes"] > 0)
    assert win_peak < floor, probe.render_table()
    budget = int(floor / XLA_REMAT_SLACK) - 1
    # demand a global batch only a gm window can reach at batch 4 on
    # the dp=4 sub-axis: 4 × 4 × 2 = 32
    base2 = _build_lm(tp=1)
    plan = static.plan_program(base2[0], base2[1], world=WORLD,
                               hbm_budget=budget, knobs=dict(knobs),
                               model_config=_TP_GEOM, global_batch=32)
    assert plan.predicted_fits, plan.render_table()
    assert plan.knobs["tp_degree"] == 2, plan.render_table()
    assert plan.knobs["grad_merge"] == 2, plan.render_table()
    assert plan.predicted_effective_global_batch >= 32
    # and WITHOUT the constraint the same search picks gm=1 (gm is a
    # priced no-win that only the batch demand justifies)
    base3 = _build_lm(tp=1)
    plan_free = static.plan_program(base3[0], base3[1], world=WORLD,
                                    hbm_budget=budget, knobs=dict(knobs),
                                    model_config=_TP_GEOM)
    assert plan_free.knobs["grad_merge"] == 1, plan_free.render_table()

"""Control-flow tests: while / cond / case / switch_case / Switch /
StaticRNN / tensor arrays (reference test models:
fluid/tests/unittests/test_while_op.py, test_cond.py, test_case.py,
test_switch.py, test_recurrent_op.py)."""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers


def _run(main, startup, feed=None, fetch=None, steps=1, scope=None):
    exe = static.Executor()
    scope = scope or static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        for _ in range(steps):
            out = exe.run(main, feed=feed or {}, fetch_list=fetch or [])
    return out, scope


def test_while_sum():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        i = layers.fill_constant([1], "int64", 0)
        n = layers.fill_constant([1], "int64", 10)
        acc = layers.fill_constant([1], "float32", 0.0)
        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            layers.assign(layers.elementwise_add(acc, layers.cast(i, "float32")),
                          output=acc)
            layers.increment(i, value=1)
            layers.less_than(i, n, cond=cond)
        total = layers.elementwise_add(acc, layers.fill_constant(
            [1], "float32", 0.0))
    (out,), _ = _run(main, startup, fetch=[total])
    assert float(out) == sum(range(10))


def test_while_matmul_power():
    """Loop-carried matrix state (exercises non-scalar carries)."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4])
        i = layers.fill_constant([1], "int64", 0)
        n = layers.fill_constant([1], "int64", 3)
        h = layers.elementwise_add(x, layers.fill_constant([1], "float32", 0.0))
        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            layers.assign(layers.scale(h, scale=2.0), output=h)
            layers.increment(i, value=1)
            layers.less_than(i, n, cond=cond)
    xv = np.ones((2, 4), np.float32)
    (out,), _ = _run(main, startup, feed={"x": xv}, fetch=[h])
    np.testing.assert_allclose(out, xv * 8.0)


def test_cond_value_and_both_branches():
    for flag_val, expect in ((1.0, 5.0), (-1.0, -6.0)):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = layers.data("x", [-1, 1])
            pred = layers.greater_than(
                layers.reduce_sum(x), layers.fill_constant([1], "float32", 0.0))
            out = layers.cond(
                pred,
                lambda: layers.elementwise_add(
                    x, layers.fill_constant([1], "float32", 4.0)),
                lambda: layers.elementwise_sub(
                    x, layers.fill_constant([1], "float32", 5.0)))
        xv = np.full((1, 1), flag_val, np.float32)
        (o,), _ = _run(main, startup, feed={"x": xv}, fetch=[out])
        assert float(o.reshape(())) == pytest.approx(expect)


def test_cond_grad_flows():
    """Gradients must flow through the taken branch (lax.cond vjp)."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 2])
        x.stop_gradient = False
        pred = layers.greater_than(layers.reduce_sum(x),
                                   layers.fill_constant([1], "float32", 0.0))
        y = layers.cond(pred,
                        lambda: layers.scale(x, scale=3.0),
                        lambda: layers.scale(x, scale=7.0))
        loss = layers.reduce_sum(y)
        grads = static.gradients([loss], [x])
    xv = np.ones((1, 2), np.float32)
    (g,), _ = _run(main, startup, feed={"x": xv}, fetch=[grads[0]])
    np.testing.assert_allclose(g, np.full((1, 2), 3.0))
    xv = -np.ones((1, 2), np.float32)
    (g,), _ = _run(main, startup, feed={"x": xv}, fetch=[grads[0]])
    np.testing.assert_allclose(g, np.full((1, 2), 7.0))


def test_case_chain():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 1])
        s = layers.reduce_sum(x)

        def branch(v):
            return lambda: layers.fill_constant([1], "float32", v)

        out = layers.case(
            [(layers.less_than(s, layers.fill_constant([1], "float32", 0.0)),
              branch(-1.0)),
             (layers.less_than(s, layers.fill_constant([1], "float32", 10.0)),
              branch(1.0))],
            default=branch(99.0))
    for xv, expect in ((-5.0, -1.0), (5.0, 1.0), (50.0, 99.0)):
        (o,), _ = _run(main, startup,
                       feed={"x": np.full((1, 1), xv, np.float32)},
                       fetch=[out])
        assert float(o.reshape(())) == expect


def test_switch_case_indexed():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        idx = layers.data("idx", [1], dtype="int64")
        out = layers.switch_case(
            idx,
            {0: lambda: layers.fill_constant([1], "float32", 10.0),
             1: lambda: layers.fill_constant([1], "float32", 20.0),
             2: lambda: layers.fill_constant([1], "float32", 30.0)})
    for i in range(3):
        (o,), _ = _run(main, startup,
                       feed={"idx": np.array([i], np.int64)}, fetch=[out])
        assert float(o.reshape(())) == 10.0 * (i + 1)


def test_switch_lr_warmup():
    """The reference's Switch workhorse: LR warmup schedule over a
    persistable step counter, one jitted graph, many steps."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        step = layers.create_global_var([1], 0.0, "float32",
                                        persistable=True, name="gstep")
        lr = layers.create_global_var([1], 0.0, "float32",
                                      persistable=True, name="lr")
        layers.increment(step, value=1)
        warm_end = layers.fill_constant([1], "float32", 3.0)
        with layers.Switch() as sw:
            with sw.case(layers.less_equal(step, warm_end)):
                layers.assign(layers.scale(step, scale=0.1), output=lr)
            with sw.default():
                layers.assign(layers.fill_constant([1], "float32", 1.0),
                              output=lr)
    exe = static.Executor()
    scope = static.Scope()
    seen = []
    with static.scope_guard(scope):
        exe.run(startup)
        for _ in range(5):
            (lrv,) = exe.run(main, fetch_list=[lr])
            seen.append(round(float(lrv), 5))
    assert seen == [0.1, 0.2, 0.3, 1.0, 1.0], seen


def test_static_rnn_matches_numpy():
    T, B, D, H = 5, 3, 4, 6
    rng = np.random.RandomState(0)
    xv = rng.rand(T, B, D).astype(np.float32)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [T, B, D])
        h0 = layers.fill_constant([B, H], "float32", 0.0)
        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)
            h_prev = rnn.memory(init=h0)
            h = layers.fc(layers.concat([x_t, h_prev], axis=1), H,
                          act="tanh",
                          param_attr=static.ParamAttr(
                              name="rnn_w",
                              initializer=static.NumpyArrayInitializer(
                                  rng.rand(D + H, H).astype(np.float32))),
                          bias_attr=False)
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        out = rnn()
    (o,), scope = _run(main, startup, feed={"x": xv}, fetch=[out])

    w = None
    with static.scope_guard(scope):
        pass
    w = np.asarray(scope.get("rnn_w"))
    hs = []
    h = np.zeros((B, H), np.float32)
    for t in range(T):
        h = np.tanh(np.concatenate([xv[t], h], 1) @ w)
        hs.append(h)
    np.testing.assert_allclose(o, np.stack(hs), rtol=1e-5, atol=1e-5)


def test_static_rnn_trains():
    """RNN loop training E2E: memorize a sequence-sum regression task
    through the scan-lowered recurrence."""
    T, B, D, H = 6, 8, 3, 8
    rng = np.random.RandomState(1)
    xv = rng.rand(T, B, D).astype(np.float32)
    yv = xv.sum(axis=(0, 2), keepdims=False).reshape(B, 1).astype(np.float32)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [T, B, D])
        y = layers.data("y", [B, 1])
        h0 = layers.fill_constant([B, H], "float32", 0.0)
        rnn = layers.StaticRNN()
        with rnn.step():
            x_t = rnn.step_input(x)
            h_prev = rnn.memory(init=h0)
            h = layers.fc(layers.concat([x_t, h_prev], axis=1), H,
                          act="tanh")
            rnn.update_memory(h_prev, h)
            rnn.step_output(h)
        hs = rnn()
        last = layers.slice(hs, axes=[0], starts=[T - 1], ends=[T])
        pred = layers.fc(layers.reshape(last, [B, H]), 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=0.05).minimize(loss)

    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        first = None
        for i in range(60):
            (lv,) = exe.run(main, feed={"x": xv, "y": yv},
                            fetch_list=[loss])
            if first is None:
                first = float(lv)
    assert float(lv) < first * 0.1, (first, float(lv))


def test_tensor_array_write_read():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4])
        arr = layers.create_array("float32")
        i0 = layers.fill_constant([1], "int64", 0)
        i1 = layers.fill_constant([1], "int64", 1)
        layers.array_write(x, i0, array=arr, max_len=8)
        layers.array_write(layers.scale(x, scale=2.0), i1, array=arr)
        n = layers.array_length(arr)
        r0 = layers.array_read(arr, i0)
        r1 = layers.array_read(arr, i1)
    xv = np.ones((2, 4), np.float32)
    (nv, a0, a1), _ = _run(main, startup, feed={"x": xv},
                           fetch=[n, r0, r1])
    assert int(nv) == 2
    np.testing.assert_allclose(a0, xv)
    np.testing.assert_allclose(a1, xv * 2)


def test_tensor_array_in_while_loop():
    """Decode-loop shape: write one step result per iteration."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 2])
        arr = layers.create_array("float32")
        i = layers.fill_constant([1], "int64", 0)
        n = layers.fill_constant([1], "int64", 4)
        # first write OUTSIDE the loop fixes the buffer capacity
        layers.array_write(x, i, array=arr, max_len=8)
        layers.increment(i, value=1)
        h = layers.elementwise_add(x, layers.fill_constant(
            [1], "float32", 0.0))
        cond = layers.less_than(i, n)
        w = layers.While(cond)
        with w.block():
            layers.assign(layers.scale(h, scale=2.0), output=h)
            layers.array_write(h, i, array=arr)
            layers.increment(i, value=1)
            layers.less_than(i, n, cond=cond)
        n_out = layers.array_length(arr)
        last = layers.array_read(arr, layers.fill_constant([1], "int64", 3))
    xv = np.ones((1, 2), np.float32)
    (cnt, lastv), _ = _run(main, startup, feed={"x": xv},
                           fetch=[n_out, last])
    assert int(cnt) == 4
    np.testing.assert_allclose(lastv, xv * 8.0)


def test_nested_cond_in_while():
    """Nested control flow: alternating add inside a loop."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        i = layers.fill_constant([1], "int64", 0)
        n = layers.fill_constant([1], "int64", 6)
        acc = layers.fill_constant([1], "float32", 0.0)
        two = layers.fill_constant([1], "int64", 2)
        cond_v = layers.less_than(i, n)
        w = layers.While(cond_v)
        with w.block():
            rem = layers.elementwise_mod(i, two)
            is_even = layers.equal(rem, layers.fill_constant([1], "int64", 0))
            delta = layers.cond(
                is_even,
                lambda: layers.fill_constant([1], "float32", 1.0),
                lambda: layers.fill_constant([1], "float32", 10.0))
            layers.assign(layers.elementwise_add(acc, delta), output=acc)
            layers.increment(i, value=1)
            layers.less_than(i, n, cond=cond_v)
    (out,), _ = _run(main, startup, fetch=[acc])
    assert float(out) == 3 * 1.0 + 3 * 10.0


# ---------------------------------------------------------------------------
# differentiable While (bounded lax.scan lowering; reference
# while_op.cc:167 WhileGradOp)
# ---------------------------------------------------------------------------
def _make_while_loss(max_iters):
    from paddle_tpu.static.layer_helper import LayerHelper
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [2])
        w = LayerHelper("w").create_parameter(
            static.ParamAttr(name="w",
                             initializer=static.initializer.Constant(1.0)),
            [2], "float32")
        s0 = layers.fill_constant([2], "float32", 0.0)

        def cond_fn(s):
            return layers.less_than(
                layers.reduce_sum(s),
                layers.fill_constant([1], "float32", 10.0))

        def body_fn(s):
            return layers.elementwise_add(
                s, layers.elementwise_mul(w, x))

        (s_fin,) = layers.while_loop(cond_fn, body_fn, [s0],
                                     max_iters=max_iters)
        loss = layers.reduce_sum(layers.elementwise_mul(s_fin, s_fin))
        grads = static.append_backward(loss)
    return main, startup, loss, grads


def test_while_loop_grad_matches_finite_differences():
    main, startup, loss, grads = _make_while_loss(max_iters=16)
    assert grads and grads[0][0].name == "w"
    xv = np.array([1.5, 2.0], np.float32)
    (lv, gw), _ = _run(main, startup, feed={"x": xv},
                       fetch=[loss, grads[0][1]])

    def run_loss(wv):
        s = np.zeros(2, np.float64)
        it = 0
        while s.sum() < 10 and it < 16:
            s = s + wv * xv
            it += 1
        return float((s * s).sum())

    eps = 1e-3
    w0 = np.ones(2, np.float64)
    for i in range(2):
        wp, wm = w0.copy(), w0.copy()
        wp[i] += eps
        wm[i] -= eps
        fd = (run_loss(wp) - run_loss(wm)) / (2 * eps)
        np.testing.assert_allclose(np.asarray(gw)[i], fd, rtol=2e-2)


def test_while_loop_trains_through_dynamic_loop():
    from paddle_tpu.static.layer_helper import LayerHelper
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [2])
        tgt = layers.data("tgt", [2])
        w = LayerHelper("w2").create_parameter(
            static.ParamAttr(name="w2",
                             initializer=static.initializer.Constant(0.3)),
            [2], "float32")
        s0 = layers.fill_constant([2], "float32", 0.0)

        def cond_fn(s):
            return layers.less_than(
                layers.reduce_sum(s),
                layers.fill_constant([1], "float32", 3.0))

        def body_fn(s):
            return layers.elementwise_add(
                s, layers.elementwise_mul(w, x))

        (s_fin,) = layers.while_loop(cond_fn, body_fn, [s0], max_iters=8)
        loss = layers.reduce_sum(
            layers.square(layers.elementwise_sub(s_fin, tgt)))
        static.SGD(learning_rate=0.05).minimize(loss)
    exe = static.Executor()
    scope = static.Scope()
    feed = {"x": np.array([1.0, 1.0], np.float32),
            "tgt": np.array([2.0, 1.5], np.float32)}
    with static.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(40):
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_unbounded_while_is_not_differentiable():
    # without max_iters the carried vars keep stop_gradient=True, so the
    # requires-grad sweep cuts the path and no param grad is produced
    main, startup, loss, grads = _make_while_loss(max_iters=0)
    assert grads == []
    # the grad kernel itself refuses with actionable guidance if reached
    # (e.g. hand-marked loop vars)
    from paddle_tpu.ops.registry import get_op_info
    with pytest.raises(ValueError, match="max_iters"):
        get_op_info("while_grad").kernel({}, {"max_iters": 0}, None)
    # forward-only execution still works
    (lv,), _ = _run(main, startup,
                    feed={"x": np.array([1.5, 2.0], np.float32)},
                    fetch=[loss])


def test_bounded_while_matches_unbounded_forward():
    m1, s1, l1, _ = _make_while_loss(max_iters=16)
    xv = np.array([0.7, 1.1], np.float32)
    (a,), _ = _run(m1, s1, feed={"x": xv}, fetch=[l1])
    main, startup = static.Program(), static.Program()
    from paddle_tpu.static.layer_helper import LayerHelper
    with static.program_guard(main, startup):
        x = layers.data("x", [2])
        w = LayerHelper("w").create_parameter(
            static.ParamAttr(name="w",
                             initializer=static.initializer.Constant(1.0)),
            [2], "float32")
        s0 = layers.fill_constant([2], "float32", 0.0)
        (s_fin,) = layers.while_loop(
            lambda s: layers.less_than(
                layers.reduce_sum(s),
                layers.fill_constant([1], "float32", 10.0)),
            lambda s: layers.elementwise_add(
                s, layers.elementwise_mul(w, x)),
            [s0])
        loss = layers.reduce_sum(layers.elementwise_mul(s_fin, s_fin))
    (b,), _ = _run(main, startup, feed={"x": xv}, fetch=[loss])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_bounded_while_grad_nan_safe_and_aligned():
    # two regressions in one scenario:
    # 1. dead scan iterations must NOT execute the body (z/i with i==0
    #    would emit inf whose cotangent poisons grads through where-vjp)
    # 2. Out@GRAD cotangent lists must stay position-aligned when some
    #    carried outputs (here: i, cond) have no gradient
    from paddle_tpu.static.layer_helper import LayerHelper
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        z = layers.data("z", [1])
        w = LayerHelper("w").create_parameter(
            static.ParamAttr(name="w",
                             initializer=static.initializer.Constant(2.0)),
            [1], "float32")
        i0 = layers.fill_constant([1], "float32", 3.0)
        acc0 = layers.fill_constant([1], "float32", 0.0)

        def cond_fn(i, acc):
            return layers.less_than(
                layers.fill_constant([1], "float32", 0.0), i)

        def body_fn(i, acc):
            return (layers.elementwise_sub(
                        i, layers.fill_constant([1], "float32", 1.0)),
                    layers.elementwise_add(acc, layers.elementwise_div(
                        layers.elementwise_mul(w, z), i)))

        i_f, acc_f = layers.while_loop(cond_fn, body_fn, [i0, acc0],
                                       max_iters=10)
        loss = layers.reduce_sum(acc_f)
        grads = static.append_backward(loss)
    (lv, gw), _ = _run(main, startup,
                       feed={"z": np.array([6.0], np.float32)},
                       fetch=[loss, grads[0][1]])
    # acc = w*z*(1/3 + 1/2 + 1) -> dloss/dw = z*11/6 = 11
    assert np.isfinite(np.asarray(gw)).all()
    np.testing.assert_allclose(np.asarray(gw), [11.0], rtol=1e-5)

"""Tier-1 ZeRO-1 sharding gate (NOT marked slow — a regression in the
bucket rewrite, the shard shapes, the estimator's world-size slot
accounting, or a sharding-induced retrace must fail the suite, not wait
for a perf round).

Drives tools/shard_smoke.py in-process: small Adam model sharded for the
8-device CPU mesh in under 15 s — rewrite applied, slot shapes correct
and genuinely rank-sharded, slot bytes ≈ 1/8, zero post-warmup
recompiles.  Mirrors the mem_smoke/ckpt_smoke gate pattern; the CLI
round-trip is `slow` (a fresh interpreter + jit warmup buys no extra
coverage over the in-process gate — run it in perf rounds).
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_shard_smoke_gate():
    import shard_smoke
    result = shard_smoke.run_smoke(steps=2)
    # the whole point: ~8x smaller optimizer slots per chip
    assert result["value"] >= 4, result
    assert result["compiles_after_warmup"] == 0, result
    assert result["buckets"] >= 1, result
    assert result["sharded_slot_bytes"] < result["plain_slot_bytes"], result


@pytest.mark.slow
def test_shard_smoke_cli_prints_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "shard_smoke.py"),
         "--steps", "2"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["compiles_after_warmup"] == 0
    assert result["value"] >= 4

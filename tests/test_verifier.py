"""IR verifier & distributed-correctness analyzer tests
(static/verifier.py).

Two halves, mirroring the acceptance contract:

  * ZERO FALSE POSITIVES: every program the rewrite passes legitimately
    produce — plain, AMP, gradient_merge, ZeRO-1, elastic, recompute,
    and their sanctioned compositions — verifies clean in strict mode.
  * MUTATION DETECTION: ≥10 seeded defect classes (swapped collective
    order, mismatched ring_id, read-after-donate, rank-conditional
    collective, dangling @GRAD, dtype clash, ...) are each caught with
    their STABLE diagnostic code (docs/static_analysis.md) and carry
    op/var provenance.
"""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers
from paddle_tpu.static.verifier import (
    ProgramVerificationError, check_program, collective_sequence,
    collective_wire_bytes, entry_wire_bytes, self_check, verify_mode)
from paddle_tpu.core.pass_framework import (applied_passes, has_applied,
                                            record_applied)
from paddle_tpu.core.program import OpDesc, OpRole, _reset_unique_names
from paddle_tpu.distributed.sharding import shard_optimizer_states


def build_train(opt_cls=None, lr=1e-3):
    """Small minimized training program: (main, startup, loss)."""
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = (opt_cls or static.Adam)(learning_rate=lr)
        opt.minimize(loss)
    return main, startup, loss


def build_sharded(dp=8, stage=1, gm=0, **kw):
    main, startup, loss = build_train(**kw)
    plan = shard_optimizer_states(main, startup, dp_degree=dp, stage=stage)
    if gm:
        static.gradient_merge(main, gm, startup)
    return main, startup, loss, plan


def assert_code(report, code):
    hits = report.by_code(code)
    assert hits, f"expected {code}, got {report.codes()}:\n{report.render()}"
    return hits


# ---------------------------------------------------------------------------
# zero false positives on sanctioned programs
# ---------------------------------------------------------------------------
class TestCleanPrograms:
    def test_plain_training_program_is_clean(self):
        main, startup, loss = build_train()
        rep = check_program(main, level="all", startup=startup,
                            fetch_list=[loss])
        assert rep.ok and not rep.diagnostics, rep.render()

    def test_every_optimizer_is_clean(self):
        for cls in (static.SGD, static.Momentum, static.Adam,
                    static.AdamW, static.Lamb):
            main, startup, loss = build_train(opt_cls=cls)
            rep = check_program(main, level="all", startup=startup,
                                fetch_list=[loss])
            assert not rep.diagnostics, \
                f"{cls.__name__}:\n{rep.render()}"

    def test_zero1_sharded_is_clean_and_strict_passes(self):
        main, startup, loss, plan = build_sharded()
        rep = check_program(main, level="all", startup=startup,
                            fetch_list=[loss], raise_on_error=True)
        assert not rep.diagnostics, rep.render()

    def test_zero1_plus_gradient_merge_is_clean(self):
        # the only sanctioned diagnostic on a looped zero×gm program is
        # the V208 hoist advisory (warn-level): K-1 of K dispatches move
        # the publish allgather's bytes for a masked-out commit.  The
        # hoist-marked program — the scanned-window default — is fully
        # clean.
        main, startup, loss, plan = build_sharded()
        static.gradient_merge(main, 4, startup_program=startup)
        rep = check_program(main, level="all", startup=startup,
                            fetch_list=[loss])
        assert not rep.errors, rep.render()
        assert {d.code for d in rep.diagnostics} <= {"V208"}, rep.render()
        from paddle_tpu.distributed.scan_window import mark_scan_hoist
        mark_scan_hoist(main)
        rep2 = check_program(main, level="all", startup=startup,
                             fetch_list=[loss])
        assert not rep2.diagnostics, rep2.render()

    def test_elastic_is_clean(self):
        from paddle_tpu.distributed.elastic import elasticize
        main, startup, loss = build_train()
        elasticize(main, startup, logical_dp=8, loss_name=loss)
        rep = check_program(main, level="all", startup=startup,
                            fetch_list=[loss.name + "@ELASTIC_AVG"])
        assert not rep.diagnostics, rep.render()

    def test_amp_is_clean(self):
        from paddle_tpu import amp
        _reset_unique_names()
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = layers.data("x", [-1, 8])
            y = layers.data("y", [-1, 1])
            h = layers.fc(x, 16, act="relu")
            pred = layers.fc(h, 1)
            loss = layers.mean(layers.square_error_cost(pred, y))
            opt = amp.decorate(static.Adam(learning_rate=1e-3),
                               use_dynamic_loss_scaling=True)
            opt.minimize(loss, startup)
        rep = check_program(main, level="all", startup=startup,
                            fetch_list=[loss])
        assert not rep.diagnostics, rep.render()

    def test_recompute_is_clean(self):
        _reset_unique_names()
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = layers.data("x", [-1, 8])
            y = layers.data("y", [-1, 1])
            h1 = layers.fc(x, 16, act="relu")
            h2 = layers.fc(h1, 16, act="relu")
            pred = layers.fc(h2, 1)
            loss = layers.mean(layers.square_error_cost(pred, y))
            opt = static.RecomputeOptimizer(
                static.Adam(learning_rate=1e-3))
            opt._set_checkpoints([h1])
            opt.minimize(loss)
        rep = check_program(main, level="all", startup=startup,
                            fetch_list=[loss])
        assert not rep.diagnostics, rep.render()
        assert has_applied(main, "recompute")

    def test_grad_allreduce_rewrite_is_clean(self):
        from paddle_tpu.distributed.compiled_program import \
            insert_grad_allreduce
        main, startup, loss = build_train()
        rewritten = insert_grad_allreduce(main)
        rep = check_program(rewritten, level="all", startup=startup,
                            fetch_list=[loss])
        assert not rep.diagnostics, rep.render()
        # idempotent re-apply stays clean (no V207 double reduction)
        again = insert_grad_allreduce(rewritten)
        rep2 = check_program(again, level="all", fetch_list=[loss])
        assert not rep2.by_code("V207"), rep2.render()

    def test_clean_program_executes_after_verification(self):
        # verification is read-only: the verified program still runs
        main, startup, loss = build_train()
        check_program(main, level="all", startup=startup,
                      fetch_list=[loss])
        exe = static.Executor()
        scope = static.Scope()
        with static.scope_guard(scope):
            exe.run(startup)
            out = exe.run(main, feed={
                "x": np.random.rand(4, 8).astype(np.float32),
                "y": np.random.rand(4, 1).astype(np.float32)},
                fetch_list=[loss])
        assert np.isfinite(np.asarray(out[0])).all()


# ---------------------------------------------------------------------------
# mutation detection: seeded defect classes -> stable codes
# ---------------------------------------------------------------------------
class TestMutations:
    def test_def_before_use_V101(self):
        main, _, loss = build_train()
        main.global_block().ops.insert(0, OpDesc(
            "scale", {"X": ["never_defined"]}, {"Out": ["q"]},
            {"scale": 1.0, "op_uid": main._next_uid()}))
        hits = assert_code(check_program(main, fetch_list=[loss]), "V101")
        assert hits[0].var == "never_defined"       # provenance
        assert hits[0].op_type == "scale"

    def test_dangling_grad_var_V102(self):
        main, _, loss = build_train()
        blk = main.global_block()
        blk.create_var(name="phantom@GRAD", shape=[8], dtype="float32")
        blk.ops.append(OpDesc(
            "fill_constant", {}, {"Out": ["phantom@GRAD"]},
            {"shape": [8], "value": 0.0, "dtype": "float32",
             "op_uid": main._next_uid()}))
        hits = assert_code(check_program(main, fetch_list=[loss]), "V102")
        assert hits[0].var == "phantom@GRAD"

    def test_dtype_clash_V103(self):
        main, _, loss = build_train()
        main.global_block().var(loss.name).dtype = "int32"
        assert_code(check_program(main, fetch_list=[loss]), "V103")

    def test_shape_clash_V104(self):
        main, _, loss = build_train()
        # corrupt a declared activation shape behind the kernel's back
        blk = main.global_block()
        fc_out = next(op for op in blk.ops if op.type == "mul")
        v = blk.var(fc_out.outputs["Out"][0])
        v.shape = tuple(d + 3 for d in v.shape)
        assert_code(check_program(main, fetch_list=[loss]), "V104")

    def test_duplicate_write_V106(self):
        main, _, loss = build_train()
        blk = main.global_block()
        tmp = next(n for op in blk.ops for n in op.output_names()
                   if not blk.var(n).persistable)
        blk.ops.append(OpDesc(
            "fill_constant", {}, {"Out": [tmp]},
            {"shape": [1], "value": 0.0, "dtype": "float32",
             "op_uid": main._next_uid()}))
        assert_code(check_program(main, fetch_list=[loss]), "V106")

    def test_feed_var_overwritten_V107(self):
        main, _, loss = build_train()
        main.global_block().ops.append(OpDesc(
            "scale", {"X": ["x"]}, {"Out": ["x"]},
            {"scale": 1.0, "op_uid": main._next_uid()}))
        assert_code(check_program(main, fetch_list=[loss]), "V107")

    def test_missing_fetch_target_V107(self):
        main, _, _ = build_train()
        assert_code(check_program(main, fetch_list=["no_such_var"]),
                    "V107")

    def test_unknown_op_V109(self):
        main, _, loss = build_train()
        main.global_block().ops.append(
            OpDesc("totally_fake_op", {}, {}, {}))
        assert_code(check_program(main, fetch_list=[loss]), "V109")

    def test_swapped_collective_order_V201(self):
        main, startup, loss, _ = build_sharded()
        blk = main.global_block()
        rs = next(i for i, op in enumerate(blk.ops)
                  if op.type == "c_reducescatter")
        ag = next(i for i, op in enumerate(blk.ops)
                  if op.type == "c_allgather")
        blk.ops[rs], blk.ops[ag] = blk.ops[ag], blk.ops[rs]
        assert_code(check_program(main, fetch_list=[loss]), "V201")

    def test_orphan_reducescatter_V201(self):
        main, startup, loss, _ = build_sharded()
        blk = main.global_block()
        blk.ops = [op for op in blk.ops if op.type != "c_allgather"]
        assert_code(check_program(main, fetch_list=[loss]), "V201")

    def test_mismatched_ring_id_V202(self):
        main, startup, loss, _ = build_sharded()
        next(op for op in main.global_block().ops
             if op.type == "c_allgather").attrs["ring_id"] = 1
        hits = assert_code(check_program(main, fetch_list=[loss]), "V202")
        assert hits[0].op_type == "c_allgather"

    def test_mismatched_dp_degree_V202(self):
        main, startup, loss, _ = build_sharded()
        next(op for op in main.global_block().ops
             if op.type == "c_reducescatter").attrs["dp_degree"] = 4
        assert_code(check_program(main, fetch_list=[loss]), "V202")

    def test_indivisible_shard_V203(self):
        main, startup, loss, _ = build_sharded()
        rs = next(op for op in main.global_block().ops
                  if op.type == "c_reducescatter")
        xv = main.global_block().var(rs.inputs["X"][0])
        xv.shape = (int(xv.shape[0]) + 1,)
        assert_code(check_program(main, fetch_list=[loss]), "V203")

    def test_dp_shard_metadata_clash_V204(self):
        main, startup, loss, plan = build_sharded()
        v = main.global_block().var(plan.slot_var_names()[0])
        v.attrs["dp_shard"] = 4
        assert_code(check_program(main, fetch_list=[loss]), "V204")

    def test_rank_conditional_collective_V205(self):
        main, _, loss = build_train()
        sub = main.create_block()
        main.rollback()
        sub.ops.append(OpDesc(
            "c_allreduce_sum", {"X": ["x"]}, {"Out": ["x"]},
            {"ring_id": 0, "op_uid": main._next_uid()}))
        hits = assert_code(check_program(main, fetch_list=[loss]), "V205")
        assert hits[0].block_idx == 1                # provenance

    def test_psum_in_elastic_fold_path_V206(self):
        from paddle_tpu.distributed.elastic import elasticize
        main, startup, loss = build_train()
        elasticize(main, startup, logical_dp=8, loss_name=loss)
        blk = main.global_block()
        blk.create_var(name="hazard_out", shape=[1], dtype="float32")
        blk.ops.append(OpDesc(
            "c_allreduce_sum", {"X": [loss.name]},
            {"Out": ["hazard_out"]},
            {"ring_id": 0, "op_uid": main._next_uid()}))
        assert_code(check_program(main,
                                  fetch_list=[loss.name + "@ELASTIC_AVG"]),
                    "V206")

    def test_double_reduction_V207(self):
        from paddle_tpu.distributed.compiled_program import \
            insert_grad_allreduce
        main, _, loss = build_train()
        p = insert_grad_allreduce(main)
        blk = p.global_block()
        ar_i, ar = next((i, op) for i, op in enumerate(blk.ops)
                        if op.type == "c_allreduce_sum")
        blk.create_var(name="re_reduced", shape=None, dtype="float32")
        blk.ops.insert(ar_i + 1, OpDesc(
            "c_allreduce_sum", {"X": [ar.outputs["Out"][0]]},
            {"Out": ["re_reduced"]},
            {"ring_id": 0, "op_uid": p._next_uid()}))
        assert_code(check_program(p, fetch_list=[loss]), "V207")

    def test_masked_publish_advisory_V208(self):
        """ISSUE 16 mutation pair: a publish collective under a
        gradient-merge mask (K=4 -> 3 of 4 dispatches move dead bytes)
        draws the warn-level hoist advisory; marking the scanned hoist
        OR dropping the merge window silences it."""
        main, startup, loss, _ = build_sharded(gm=4)
        hits = assert_code(check_program(main, startup=startup,
                                         fetch_list=[loss]), "V208")
        assert all(d.severity == "warning" for d in hits), hits
        assert "hoist" in hits[0].message
        # direction 1: the hoist mark deletes the advisory
        from paddle_tpu.distributed.scan_window import mark_scan_hoist
        mark_scan_hoist(main)
        rep = check_program(main, startup=startup, fetch_list=[loss])
        assert not rep.by_code("V208"), rep.render()
        # direction 2: no merge window, no masked re-publish to hoist
        main2, startup2, loss2, _ = build_sharded()
        rep2 = check_program(main2, startup=startup2, fetch_list=[loss2])
        assert not rep2.by_code("V208"), rep2.render()

    def test_startup_alias_assign_V301(self):
        main, startup, loss = build_train()
        ps = main.all_parameters()
        startup.global_block().ops.append(OpDesc(
            "assign", {"X": [ps[0].name]}, {"Out": [ps[1].name]},
            {"op_uid": startup._next_uid()}))
        assert_code(check_program(main, startup=startup,
                                  fetch_list=[loss]), "V301")

    def test_read_after_donate_V302(self):
        main, _, loss = build_train()
        blk = main.global_block()
        param = main.all_parameters()[0]
        blk.create_var(name="post_read", shape=param.shape,
                       dtype=param.dtype, stop_gradient=True)
        blk.ops.append(OpDesc(
            "scale", {"X": [param.name]}, {"Out": ["post_read"]},
            {"scale": 2.0, OpRole.KEY: OpRole.Forward,
             "op_uid": main._next_uid()}))
        hits = assert_code(check_program(main, fetch_list=[loss]), "V302")
        assert hits[0].var == param.name

    def test_fetch_of_sharded_slot_V303(self):
        main, startup, loss, plan = build_sharded()
        slot = plan.slot_var_names()[0]
        assert_code(check_program(main, fetch_list=[slot]), "V303")

    def test_retrace_lints_V401_V402_V403(self):
        main, _, loss = build_train()
        blk = main.global_block()
        blk.create_var(name="ragged", shape=[-1, -1], dtype="float32",
                       is_data=True)
        blk.create_var(name="scalar_feed", shape=[], dtype="float32",
                       is_data=True)
        blk.ops[3].attrs["captured"] = np.zeros(3)
        rep = check_program(main, fetch_list=[loss])
        for code in ("V401", "V402", "V403"):
            assert_code(rep, code)

    def test_pass_order_violation_V502(self):
        main, startup, loss, _ = build_sharded()
        main._applied_passes = [{"pass": "gradient_merge", "k": 2},
                                {"pass": "zero1_sharding"}]
        assert_code(check_program(main, fetch_list=[loss]), "V502")

    def test_elastic_plus_gm_V501(self):
        main, _, loss = build_train()
        record_applied(main, "elastic", logical_dp=8)
        record_applied(main, "gradient_merge", k=2)
        assert_code(check_program(main, fetch_list=[loss]), "V501")

    def test_elastic_plus_zero1_V503(self):
        main, _, loss = build_train()
        record_applied(main, "zero1_sharding", dp_degree=8)
        record_applied(main, "elastic", logical_dp=8)
        assert_code(check_program(main, fetch_list=[loss]), "V503")


# ---------------------------------------------------------------------------
# API surface: levels, suppression, strict mode, env gating
# ---------------------------------------------------------------------------
class TestApi:
    def test_levels_are_cumulative(self):
        main, _, loss = build_train()
        sub = main.create_block()
        main.rollback()
        sub.ops.append(OpDesc(
            "c_allreduce_sum", {"X": ["x"]}, {"Out": ["x"]},
            {"ring_id": 0, "op_uid": main._next_uid()}))
        graph_only = check_program(main, level="graph",
                                   fetch_list=[loss])
        assert not graph_only.by_code("V205")
        for level in ("collective", "donation", "retrace", "all", 2, 4):
            assert check_program(main, level=level,
                                 fetch_list=[loss]).by_code("V205")

    def test_unknown_level_raises(self):
        main, _, _ = build_train()
        with pytest.raises(ValueError):
            check_program(main, level="bogus")

    def test_suppress_allowlists_codes(self):
        main, _, loss = build_train()
        main.global_block().ops.append(
            OpDesc("totally_fake_op", {}, {}, {}))
        rep = check_program(main, fetch_list=[loss], suppress=("V109",))
        assert not rep.by_code("V109")

    def test_raise_on_error(self):
        main, _, loss = build_train()
        main.global_block().ops.append(
            OpDesc("totally_fake_op", {}, {}, {}))
        with pytest.raises(ProgramVerificationError) as ei:
            check_program(main, fetch_list=[loss], raise_on_error=True)
        assert "V109" in str(ei.value)

    def test_env_gated_self_check(self, monkeypatch):
        main, _, loss = build_train()
        main.global_block().ops.append(
            OpDesc("totally_fake_op", {}, {}, {}))
        monkeypatch.delenv("PADDLE_TPU_VERIFY", raising=False)
        assert verify_mode() == ""
        assert self_check(main, "unit") is None      # off: free
        monkeypatch.setenv("PADDLE_TPU_VERIFY", "warn")
        with pytest.warns(RuntimeWarning, match="V109"):
            self_check(main, "unit")
        monkeypatch.setenv("PADDLE_TPU_VERIFY", "strict")
        with pytest.raises(ProgramVerificationError, match="unit"):
            self_check(main, "unit")

    def test_strict_first_compile_catches_broken_program(self, monkeypatch):
        from paddle_tpu.static import verifier as V
        monkeypatch.setenv("PADDLE_TPU_VERIFY", "strict")
        main, startup, loss = build_train()
        blk = main.global_block()
        blk.ops.insert(0, OpDesc(
            "scale", {"X": ["never_defined"]}, {"Out": ["q"]},
            {"scale": 1.0, "op_uid": main._next_uid()}))
        main._fingerprint_cache = None
        exe = static.Executor()
        scope = static.Scope()
        with static.scope_guard(scope):
            exe.run(startup)
            with pytest.raises(ProgramVerificationError):
                exe.run(main, feed={
                    "x": np.zeros((2, 8), np.float32),
                    "y": np.zeros((2, 1), np.float32)},
                    fetch_list=[loss])

    def test_strict_gate_holds_on_retry(self, monkeypatch):
        # the memo records only CLEAN outcomes: re-running the same
        # broken program must hit the gate again, not the memo
        monkeypatch.setenv("PADDLE_TPU_VERIFY", "strict")
        main, startup, loss = build_train()
        main.global_block().ops.insert(0, OpDesc(
            "scale", {"X": ["never_defined"]}, {"Out": ["q"]},
            {"scale": 1.0, "op_uid": main._next_uid()}))
        main._fingerprint_cache = None
        exe = static.Executor()
        scope = static.Scope()
        feed = {"x": np.zeros((2, 8), np.float32),
                "y": np.zeros((2, 1), np.float32)}
        with static.scope_guard(scope):
            exe.run(startup)
            for _ in range(2):
                with pytest.raises(ProgramVerificationError):
                    exe.run(main, feed=feed, fetch_list=[loss])

    def test_first_compile_reverifies_new_fetch_set(self, monkeypatch):
        # the memo keys on (fingerprint, fetch set): a later compile of
        # the SAME program fetching a ZeRO shard must still raise V303
        monkeypatch.setenv("PADDLE_TPU_VERIFY", "strict")
        main, startup, loss, plan = build_sharded()
        slot = plan.slot_var_names()[0]
        exe = static.Executor()
        scope = static.Scope()
        feed = {"x": np.zeros((8, 8), np.float32),
                "y": np.zeros((8, 1), np.float32)}
        from paddle_tpu.distributed.compiled_program import CompiledProgram
        compiled = CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
        with static.scope_guard(scope):
            exe.run(startup)
            exe.run(compiled, feed=feed, fetch_list=[loss])  # clean
            with pytest.raises(ProgramVerificationError, match="V303"):
                exe.run(compiled, feed=feed, fetch_list=[slot])

    def test_strict_mode_clean_program_runs(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_VERIFY", "strict")
        main, startup, loss = build_train()
        exe = static.Executor()
        scope = static.Scope()
        with static.scope_guard(scope):
            exe.run(startup)
            out = exe.run(main, feed={
                "x": np.random.rand(4, 8).astype(np.float32),
                "y": np.random.rand(4, 1).astype(np.float32)},
                fetch_list=[loss])
        assert np.isfinite(np.asarray(out[0])).all()


# ---------------------------------------------------------------------------
# applied-passes registry (core/pass_framework.py)
# ---------------------------------------------------------------------------
class TestAppliedPassRegistry:
    def test_rewrites_record_in_order(self):
        main, startup, loss, plan = build_sharded()
        static.gradient_merge(main, 2, startup_program=startup)
        names = [e["pass"] for e in applied_passes(main)]
        assert names == ["zero1_sharding", "gradient_merge"]
        assert applied_passes(main)[0]["dp_degree"] == 8
        assert applied_passes(main)[1]["k"] == 2

    def test_registry_survives_clone(self):
        main, startup, loss, _ = build_sharded()
        assert has_applied(main.clone(), "zero1_sharding")

    def test_gradient_merge_refuses_double_apply(self):
        main, startup, loss = build_train()
        static.gradient_merge(main, 2, startup_program=startup)
        with pytest.raises(ValueError, match="already applied"):
            static.gradient_merge(main, 2, startup_program=startup)

    def test_elastic_refuses_on_registry_alone(self):
        from paddle_tpu.distributed.elastic import elasticize
        main, startup, loss = build_train()
        record_applied(main, "gradient_merge", k=2)
        with pytest.raises(NotImplementedError):
            elasticize(main, startup, logical_dp=8, loss_name=loss)

    def test_apply_passes_records(self):
        from paddle_tpu.core.pass_framework import apply_passes
        main, _, _ = build_train()
        out = apply_passes(main, ["dead_code_elimination_pass"])
        assert has_applied(out, "dead_code_elimination_pass")


# ---------------------------------------------------------------------------
# ZeRO-2/3 stage-aware validation: clean programs stay clean, mutations
# fire the right code (the ISSUE-11 "validate against the recorded
# stage" contract)
# ---------------------------------------------------------------------------
class TestZeroStageMutations:
    def test_zero2_gm_program_is_clean(self):
        main, startup, loss, _ = build_sharded(stage=2, gm=2)
        rep = check_program(main, startup=startup, fetch_list=[loss])
        assert rep.ok, rep.render()

    def test_zero3_program_is_clean(self):
        main, startup, loss, _ = build_sharded(stage=3)
        rep = check_program(main, startup=startup, fetch_list=[loss])
        assert rep.ok, rep.render()

    def test_zero3_gm_program_is_clean(self):
        main, startup, loss, _ = build_sharded(stage=3, gm=2)
        rep = check_program(main, startup=startup, fetch_list=[loss])
        assert rep.ok, rep.render()

    def test_zero3_rs_without_update_V201(self):
        # mutate: drop the in-place bucket update — the stage-3 rs now
        # reaches neither a sharded update nor a publish allgather
        main, startup, loss, _ = build_sharded(stage=3)
        blk = main.global_block()
        blk.ops = [op for op in blk.ops
                   if not op.attrs.get("zero_sharded")]
        hits = assert_code(check_program(main, fetch_list=[loss]), "V201")
        assert any("deferred-publish" in h.message for h in hits)

    def test_zero3_gather_of_replicated_var_V201(self):
        # mutate: strip the dp_shard mark off the param bucket — the
        # JIT gather would replicate an already-replicated buffer
        main, startup, loss, plan = build_sharded(stage=3)
        blk = main.global_block()
        for name in plan.param_bucket_names():
            blk.var(name).attrs.pop("dp_shard", None)
        hits = assert_code(check_program(main, fetch_list=[loss]), "V201")
        assert any("JIT param gather" in h.message for h in hits)

    def test_zero3_stage_stamp_mismatch_V204(self):
        # mutate: hand-edit one op's stage stamp — two different ZeRO
        # rewrites on one program is unsound
        main, startup, loss, _ = build_sharded(stage=3)
        op = next(op for op in main.global_block().ops
                  if op.attrs.get("zero_stage") == 3)
        op.attrs["zero_stage"] = 1
        assert_code(check_program(main, fetch_list=[loss]), "V204")

    def test_zero3_plan_stage_downgrade_V204(self):
        # mutate: rewrite the recorded plan's stage — a param bucket
        # exists without the stage-3 contract on record
        main, startup, loss, _ = build_sharded(stage=3)
        main._zero_shard_plan.stage = 1
        assert_code(check_program(main, fetch_list=[loss]), "V204")

    def test_zero3_gather_output_numel_V203(self):
        # mutate: shrink the declared gathered-output var — the gather
        # of a dp_shard bucket must produce the declared global numel
        main, startup, loss, _ = build_sharded(stage=3)
        blk = main.global_block()
        ag = next(op for op in blk.ops
                  if op.attrs.get("zero_role") == "gather_fwd")
        out_v = blk.var(ag.outputs["Out"][0])
        out_v.shape = (int(out_v.shape[0]) // 2,)
        assert_code(check_program(main, fetch_list=[loss]), "V203")

    def test_zero2_orphan_rs_still_V201(self):
        # the deferred-counterpart exemption is STAGE-3 ONLY: a stage-2
        # program whose publish allgather is deleted is still a broken
        # stale-params program
        main, startup, loss, _ = build_sharded(stage=2, gm=2)
        blk = main.global_block()
        blk.ops = [op for op in blk.ops if op.type != "c_allgather"]
        assert_code(check_program(main, fetch_list=[loss]), "V201")


# ---------------------------------------------------------------------------
# collective-sequence extraction (the planner substrate)
# ---------------------------------------------------------------------------
class TestCollectiveSequence:
    def test_zero1_sequence_order_and_metadata(self):
        main, startup, loss, plan = build_sharded()
        seq = collective_sequence(main)
        types = [e["type"] for e in seq]
        assert types.index("c_reducescatter") < types.index("c_allgather")
        for e in seq:
            if e["type"] in ("c_reducescatter", "c_allgather"):
                assert e["dp_degree"] == 8
                assert e["ring_id"] == 0
                assert e["nbytes"] and e["nbytes"] > 0

    def test_ring0_slice_prices_the_dist_pass_collectives(self):
        # the retired sharding.collective_bytes_per_step shim's
        # historical scope was exactly the ring-0 slice of THIS
        # extractor — the slice must price the rs/ag pair and nothing
        # else (c_split prices 0 — it's a local slice)
        main, startup, loss, _ = build_sharded()
        ours = collective_wire_bytes(main, 8, ring_id=0)
        by_hand = sum(entry_wire_bytes(e, 8)
                      for e in collective_sequence(main)
                      if e["ring_id"] == 0)
        assert ours == int(by_hand) > 0

    def test_zero3_gather_priced_at_local_shard(self):
        # a ZeRO-3 JIT gather's operand is DECLARED at the global
        # padded shape but each rank holds 1/N — the ring moves
        # (N-1)/N × declared bytes, NOT (N-1) × declared
        main, startup, loss, plan = build_sharded(stage=3)
        gathers = [e for e in collective_sequence(main)
                   if e["zero_role"] in ("gather_fwd", "gather_bwd")]
        assert gathers
        for e in gathers:
            assert e["x_dp_shard"] == 8
            assert entry_wire_bytes(e, 8) == (8 - 1) / 8 * e["nbytes"]

    def test_world_of_one_costs_zero(self):
        main, startup, loss, _ = build_sharded()
        assert collective_wire_bytes(main, 1) == 0


# ---------------------------------------------------------------------------
# FLAGS_check_nan_inf: producing-op provenance (satellite)
# ---------------------------------------------------------------------------
class TestNanInfProvenance:
    def _poisoned(self):
        _reset_unique_names()
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = layers.data("x", [-1, 4])
            h = layers.fc(x, 4)
            bad = layers.log(layers.scale(h, scale=0.0))  # log(0) = -inf
            loss = layers.mean(bad)
        return main, startup, loss

    def test_reports_producing_op_and_dtype(self):
        from paddle_tpu.core.flags import set_flags
        main, startup, loss = self._poisoned()
        exe = static.Executor()
        scope = static.Scope()
        set_flags({"check_nan_inf": True})
        try:
            with static.scope_guard(scope):
                exe.run(startup)
                with pytest.raises(RuntimeError) as ei:
                    exe.run(main, feed={
                        "x": np.ones((2, 4), np.float32)},
                        fetch_list=[loss])
        finally:
            set_flags({"check_nan_inf": False})
        msg = str(ei.value)
        assert "float32" in msg                      # dtype
        assert "produced by op" in msg and "uid" in msg

    def test_run_steps_reports_micro_step(self):
        from paddle_tpu.core.flags import set_flags
        _reset_unique_names()
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = layers.data("x", [2, 4])
            out = layers.log(layers.reduce_sum(x))   # log(<=0) poisons
        exe = static.Executor()
        scope = static.Scope()
        # step 0 finite, step 1 non-finite
        feed = {"x": np.stack([np.ones((2, 4), np.float32),
                               np.zeros((2, 4), np.float32)])}
        set_flags({"check_nan_inf": True})
        try:
            with static.scope_guard(scope):
                with pytest.raises(RuntimeError) as ei:
                    exe.run_steps(main, feed=feed, fetch_list=[out])
        finally:
            set_flags({"check_nan_inf": False})
        assert "micro-step 1" in str(ei.value)

"""Detection op tail tests — OpTest-vs-numpy entries for the round-4 ops
(reference: /root/reference/paddle/fluid/operators/detection/*.cc) plus a
Faster-RCNN-style head built through static.layers."""
import numpy as np
import pytest

from paddle_tpu.ops.registry import run_kernel, OpContext, get_op_info


def _run(op, ins, attrs):
    import jax.numpy as jnp
    dev = {k: ([jnp.asarray(x) for x in v] if isinstance(v, list)
               else jnp.asarray(v)) for k, v in ins.items()}
    return run_kernel(op, dev, attrs, OpContext(seed=11))


ALL_TAIL_OPS = [
    "matrix_nms", "locality_aware_nms", "retinanet_detection_output",
    "rpn_target_assign", "retinanet_target_assign", "target_assign",
    "generate_proposal_labels", "generate_mask_labels",
    "mine_hard_examples", "collect_fpn_proposals",
    "distribute_fpn_proposals", "box_decoder_and_assign",
    "polygon_box_transform", "roi_perspective_transform", "prroi_pool",
    "psroi_pool", "detection_map",
]


def test_registry_probe_all_tail_ops():
    """VERDICT r3 missing #1: every listed detection op must be
    registered."""
    missing = [op for op in ALL_TAIL_OPS if get_op_info(op) is None]
    assert not missing, f"unregistered detection ops: {missing}"


# ---------------------------------------------------------------------------
# matrix_nms
# ---------------------------------------------------------------------------

def test_matrix_nms_linear_decay():
    boxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                       [20, 20, 30, 30], [50, 50, 60, 60]]], np.float32)
    scores = np.zeros((1, 2, 4), np.float32)
    scores[0, 1] = [0.9, 0.8, 0.7, 0.01]
    out = _run("matrix_nms", {"BBoxes": boxes, "Scores": scores},
               {"score_threshold": 0.05, "post_threshold": 0.3,
                "nms_top_k": 4, "keep_top_k": 4, "background_label": 0})
    res = np.asarray(out["Out"])[0]
    # box1 decays to ~0.8*(1-iou)/(1) < 0.3 -> dropped; box3 below
    # score_threshold; two survivors
    assert int(out["RoisNum"][0]) == 2
    np.testing.assert_allclose(res[0, 1], 0.9, atol=1e-6)
    np.testing.assert_allclose(res[1, 1], 0.7, atol=1e-6)
    np.testing.assert_allclose(res[0, 2:], [0, 0, 10, 10], atol=1e-5)
    # numpy reference for the surviving decayed score of box2 (no overlap):
    # min-decay 1.0 so score unchanged
    idx = np.asarray(out["Index"])[0, :, 0]
    assert idx[0] == 0 and idx[1] == 2


def test_matrix_nms_gaussian_matches_numpy():
    rng = np.random.RandomState(0)
    boxes = rng.uniform(0, 50, (1, 6, 4)).astype(np.float32)
    boxes[..., 2:] = boxes[..., :2] + rng.uniform(5, 20, (1, 6, 2))
    scores = rng.uniform(0.1, 1.0, (1, 2, 6)).astype(np.float32)
    attrs = {"score_threshold": 0.0, "post_threshold": 0.0,
             "nms_top_k": 6, "keep_top_k": 6, "background_label": -1,
             "use_gaussian": True, "gaussian_sigma": 2.0}
    out = _run("matrix_nms", {"BBoxes": boxes, "Scores": scores}, attrs)

    # independent numpy model
    def np_iou(a, b):
        lt = np.maximum(a[:2], b[:2])
        rb = np.minimum(a[2:], b[2:])
        inter = np.prod(np.maximum(rb - lt, 0))
        ua = np.prod(np.maximum(a[2:] - a[:2], 0)) + \
            np.prod(np.maximum(b[2:] - b[:2], 0)) - inter
        return inter / max(ua, 1e-10)

    all_rows = []
    for c in range(2):
        sc = scores[0, c]
        order = np.argsort(-sc, kind="stable")
        b = boxes[0][order]
        s = sc[order]
        ious = np.zeros((6, 6))
        for i in range(6):
            for j in range(i):
                ious[i, j] = np_iou(b[i], b[j])
        iou_max = np.array([ious[i, :i].max() if i else 0.0
                            for i in range(6)])
        for i in range(6):
            decay = 1.0
            for j in range(i):
                decay = min(decay, np.exp(
                    (iou_max[j] ** 2 - ious[i, j] ** 2) * 2.0))
            all_rows.append((float(c), decay * s[i]))
    all_rows.sort(key=lambda r: -r[1])
    got = np.asarray(out["Out"])[0]
    n = int(out["RoisNum"][0])
    assert n == 6  # 12 candidates capped at keep_top_k
    for k in range(6):
        np.testing.assert_allclose(got[k, 1], all_rows[k][1], atol=1e-5)
        assert got[k, 0] == all_rows[k][0]


# ---------------------------------------------------------------------------
# locality_aware_nms
# ---------------------------------------------------------------------------

def test_locality_aware_nms_merges_consecutive():
    boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11],
                       [30, 30, 40, 40]]], np.float32)
    scores = np.zeros((1, 1, 3), np.float32)
    scores[0, 0] = [0.6, 0.4, 0.9]
    out = _run("locality_aware_nms", {"BBoxes": boxes, "Scores": scores},
               {"score_threshold": 0.1, "nms_threshold": 0.5,
                "nms_top_k": 3, "keep_top_k": 3, "background_label": -1})
    res = np.asarray(out["Out"])[0]
    assert int(out["RoisNum"][0]) == 2
    # merged box: weighted average (0.6*box0 + 0.4*box1), score 1.0
    np.testing.assert_allclose(res[0, 1], 1.0, atol=1e-6)
    np.testing.assert_allclose(res[0, 2:], [0.4, 0.4, 10.4, 10.4],
                               atol=1e-5)
    np.testing.assert_allclose(res[1, 1], 0.9, atol=1e-6)


def test_locality_aware_nms_no_merge_keeps_all():
    boxes = np.array([[[0, 0, 5, 5], [20, 20, 25, 25],
                       [40, 40, 45, 45]]], np.float32)
    scores = np.zeros((1, 1, 3), np.float32)
    scores[0, 0] = [0.5, 0.6, 0.7]
    out = _run("locality_aware_nms", {"BBoxes": boxes, "Scores": scores},
               {"score_threshold": 0.1, "nms_threshold": 0.5,
                "nms_top_k": 3, "keep_top_k": 3, "background_label": -1})
    assert int(out["RoisNum"][0]) == 3


# ---------------------------------------------------------------------------
# retinanet_detection_output
# ---------------------------------------------------------------------------

def test_retinanet_detection_output_identity_decode():
    anchors = [np.array([[0, 0, 10, 10], [20, 20, 30, 30]], np.float32)]
    bboxes = [np.zeros((1, 2, 4), np.float32)]
    sc = np.zeros((1, 2, 2), np.float32)
    sc[0, 0, 0] = 0.9
    sc[0, 1, 1] = 0.8
    info = np.array([[100, 100, 1.0]], np.float32)
    out = _run("retinanet_detection_output",
               {"BBoxes": bboxes, "Scores": [sc], "Anchors": anchors,
                "ImInfo": info},
               {"score_threshold": 0.05, "nms_top_k": 4, "keep_top_k": 4,
                "nms_threshold": 0.3})
    res = np.asarray(out["Out"])[0]
    assert int(out["RoisNum"][0]) == 2
    np.testing.assert_allclose(res[0], [0, 0.9, 0, 0, 10, 10], atol=1e-4)
    np.testing.assert_allclose(res[1], [1, 0.8, 20, 20, 30, 30],
                               atol=1e-4)


def test_retinanet_detection_output_multi_level_and_scale():
    # two levels; im_scale=2 halves the decoded coords
    anchors = [np.array([[0, 0, 10, 10]], np.float32),
               np.array([[40, 40, 60, 60]], np.float32)]
    bboxes = [np.zeros((1, 1, 4), np.float32)] * 2
    s1 = np.zeros((1, 1, 1), np.float32)
    s1[0, 0, 0] = 0.9
    s2 = np.zeros((1, 1, 1), np.float32)
    s2[0, 0, 0] = 0.7
    info = np.array([[200, 200, 2.0]], np.float32)
    out = _run("retinanet_detection_output",
               {"BBoxes": bboxes, "Scores": [s1, s2], "Anchors": anchors,
                "ImInfo": info},
               {"score_threshold": 0.05, "nms_top_k": 2, "keep_top_k": 4,
                "nms_threshold": 0.3})
    res = np.asarray(out["Out"])[0]
    assert int(out["RoisNum"][0]) == 2
    np.testing.assert_allclose(res[0, 2:], np.array([0, 0, 10, 10]) / 2,
                               atol=1e-4)
    np.testing.assert_allclose(res[1, 2:],
                               np.array([40, 40, 60, 60]) / 2, atol=1e-4)


# ---------------------------------------------------------------------------
# target_assign / mine_hard_examples
# ---------------------------------------------------------------------------

def test_target_assign_matches_numpy():
    x = np.arange(12, dtype=np.float32).reshape(1, 3, 4)
    mi = np.array([[0, -1, 2, 1]], np.int32)
    out = _run("target_assign", {"X": x, "MatchIndices": mi},
               {"mismatch_value": 9})
    o = np.asarray(out["Out"])[0]
    np.testing.assert_allclose(o[0], x[0, 0])
    np.testing.assert_allclose(o[1], np.full(4, 9.0))
    np.testing.assert_allclose(o[2], x[0, 2])
    np.testing.assert_allclose(o[3], x[0, 1])
    np.testing.assert_allclose(np.asarray(out["OutWeight"])[0, :, 0],
                               [1, 0, 1, 1])


def test_target_assign_neg_indices():
    x = np.ones((1, 2, 1), np.float32)
    mi = np.array([[0, 1, -1]], np.int32)
    neg = np.array([[2, -1]], np.int32)
    out = _run("target_assign",
               {"X": x, "MatchIndices": mi, "NegIndices": neg},
               {"mismatch_value": 0})
    np.testing.assert_allclose(np.asarray(out["OutWeight"])[0, :, 0],
                               [1, 1, 1])
    np.testing.assert_allclose(np.asarray(out["Out"])[0, 2], [0.0])


def test_mine_hard_examples_max_negative():
    cls_loss = np.array([[0.1, 0.9, 0.5, 0.3, 0.7]], np.float32)
    mi = np.array([[1, -1, -1, -1, -1]], np.int32)
    dist = np.array([[0.8, 0.1, 0.2, 0.1, 0.3]], np.float32)
    out = _run("mine_hard_examples",
               {"ClsLoss": cls_loss, "MatchIndices": mi,
                "MatchDist": dist},
               {"neg_pos_ratio": 2.0, "neg_dist_threshold": 0.5,
                "mining_type": "max_negative"})
    # 1 positive -> 2 negatives; highest-loss eligible priors are 1 (0.9)
    # and 4 (0.7); emitted ascending
    assert np.asarray(out["NegIndices"])[0].tolist() == [1, 4, -1, -1, -1]
    assert int(out["NegNum"][0]) == 2
    np.testing.assert_array_equal(np.asarray(out["UpdatedMatchIndices"]),
                                  mi)


def test_mine_hard_examples_hard_example_demotes():
    cls_loss = np.array([[0.9, 0.1, 0.8]], np.float32)
    loc_loss = np.zeros((1, 3), np.float32)
    mi = np.array([[0, 1, -1]], np.int32)
    dist = np.zeros((1, 3), np.float32)
    out = _run("mine_hard_examples",
               {"ClsLoss": cls_loss, "LocLoss": loc_loss,
                "MatchIndices": mi, "MatchDist": dist},
               {"sample_size": 2, "mining_type": "hard_example"})
    # top-2 by loss: priors 0 (0.9) and 2 (0.8).  prior 1 is a positive
    # outside the kept set -> match index demoted to -1; prior 2 is an
    # unmatched kept prior -> negative
    upd = np.asarray(out["UpdatedMatchIndices"])[0]
    assert upd.tolist() == [0, -1, -1]
    assert np.asarray(out["NegIndices"])[0].tolist()[:1] == [2]


# ---------------------------------------------------------------------------
# fpn collect / distribute
# ---------------------------------------------------------------------------

def test_collect_fpn_proposals_topk():
    r1 = np.array([[[0, 0, 10, 10], [1, 1, 2, 2]]], np.float32)
    s1 = np.array([[0.9, 0.2]], np.float32)
    r2 = np.array([[[5, 5, 15, 15]]], np.float32)
    s2 = np.array([[0.7]], np.float32)
    out = _run("collect_fpn_proposals",
               {"MultiLevelRois": [r1, r2], "MultiLevelScores": [s1, s2]},
               {"post_nms_topN": 2})
    got = np.asarray(out["FpnRois"])[0]
    np.testing.assert_allclose(got[0], [0, 0, 10, 10])
    np.testing.assert_allclose(got[1], [5, 5, 15, 15])
    assert int(out["RoisNum"][0]) == 2


def test_distribute_fpn_proposals_levels():
    # scales 40, 300, 120: floor(4 + log2(s/224)) -> levels 2, 4, 3
    fr = np.array([[[0, 0, 40, 40], [0, 0, 300, 300], [0, 0, 120, 120],
                    [0, 0, 0, 0]]], np.float32)
    out = _run("distribute_fpn_proposals", {"FpnRois": fr},
               {"min_level": 2, "max_level": 5, "refer_level": 4,
                "refer_scale": 224})
    nums = [int(np.asarray(n)[0]) for n in out["MultiLevelRoIsNum"]]
    assert nums == [1, 1, 1, 0]
    lvl2 = np.asarray(out["MultiFpnRois"][0])[0]
    np.testing.assert_allclose(lvl2[0], [0, 0, 40, 40])
    # restore: concat order is (roi0@l2, roi2@l4, roi1@l5, dead roi3)
    restore = np.asarray(out["RestoreIndex"])[0, :, 0]
    assert restore.tolist() == [0, 2, 1, 3]


# ---------------------------------------------------------------------------
# box_decoder_and_assign / polygon_box_transform
# ---------------------------------------------------------------------------

def test_box_decoder_and_assign_numpy():
    prior = np.array([[0, 0, 10, 10]], np.float32)
    var = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
    tb = np.array([[0, 0, 0, 0,            # class 0 deltas
                    0.1, 0.2, 0.05, -0.05]], np.float32)  # class 1
    bs = np.array([[0.3, 0.7]], np.float32)
    out = _run("box_decoder_and_assign",
               {"PriorBox": prior, "PriorBoxVar": var, "TargetBox": tb,
                "BoxScore": bs}, {"box_clip": 2.302585})
    dec = np.asarray(out["DecodeBox"])[0].reshape(2, 4)
    # class-1 decode by hand: pw=ph=11, pcx=pcy=5.5
    cx = 0.1 * 0.1 * 11 + 5.5
    cy = 0.1 * 0.2 * 11 + 5.5
    w = np.exp(0.2 * 0.05) * 11
    h = np.exp(0.2 * -0.05) * 11
    np.testing.assert_allclose(
        dec[1], [cx - w / 2, cy - h / 2, cx + w / 2 - 1, cy + h / 2 - 1],
        atol=1e-4)
    # assign picks argmax class>0 = class 1
    np.testing.assert_allclose(np.asarray(out["OutputAssignBox"])[0],
                               dec[1], atol=1e-6)


def test_polygon_box_transform_numpy():
    x = np.ones((1, 2, 2, 3), np.float32)
    out = _run("polygon_box_transform", {"Input": x}, {})
    o = np.asarray(out["Output"])[0]
    # even channel: 4*w - 1; odd channel: 4*h - 1
    np.testing.assert_allclose(o[0], [[-1, 3, 7], [-1, 3, 7]])
    np.testing.assert_allclose(o[1], [[-1, -1, -1], [3, 3, 3]])


# ---------------------------------------------------------------------------
# psroi_pool / prroi_pool / roi_perspective_transform
# ---------------------------------------------------------------------------

def test_psroi_pool_numpy():
    np.random.seed(3)
    x = np.random.randn(1, 8, 6, 6).astype(np.float32)
    rois = np.array([[0, 0, 0, 5, 5]], np.float32)
    out = _run("psroi_pool", {"X": x, "ROIs": rois},
               {"pooled_height": 2, "pooled_width": 2,
                "spatial_scale": 1.0, "output_channels": 2})
    got = np.asarray(out["Out"])[0]
    # numpy model straight from psroi_pool_op.h
    exp = np.zeros((2, 2, 2), np.float32)
    bin_h = bin_w = 6 / 2
    for c in range(2):
        for ph in range(2):
            for pw in range(2):
                hs, he = int(ph * bin_h), int(np.ceil((ph + 1) * bin_h))
                ws, we = int(pw * bin_w), int(np.ceil((pw + 1) * bin_w))
                ch = (c * 2 + ph) * 2 + pw
                exp[c, ph, pw] = x[0, ch, hs:he, ws:we].mean()
    np.testing.assert_allclose(got, exp, atol=1e-5)


def test_prroi_pool_constant_field():
    # integral of a constant bilinear field == the constant (roi kept
    # inside [0, 7]: beyond the last pixel center the interpolant decays
    # to the zero padding, reference GetData overflow -> 0)
    x = np.full((1, 3, 8, 8), 2.5, np.float32)
    rois = np.array([[0, 1.3, 2.1, 6.7, 6.9]], np.float32)
    out = _run("prroi_pool", {"X": x, "ROIs": rois},
               {"pooled_height": 3, "pooled_width": 3,
                "spatial_scale": 1.0})
    np.testing.assert_allclose(np.asarray(out["Out"])[0], 2.5, atol=1e-4)


def test_prroi_pool_matches_dense_integration():
    np.random.seed(5)
    x = np.random.randn(1, 1, 6, 6).astype(np.float32)
    rois = np.array([[0, 0.5, 1.0, 4.5, 5.0]], np.float32)
    out = _run("prroi_pool", {"X": x, "ROIs": rois},
               {"pooled_height": 2, "pooled_width": 2,
                "spatial_scale": 1.0})
    got = np.asarray(out["Out"])[0, 0]

    # dense numerical integration of the bilinear interpolant
    def bilin(yy, xx):
        y0 = np.clip(np.floor(yy).astype(int), -1, 6)
        x0 = np.clip(np.floor(xx).astype(int), -1, 6)
        ay = yy - y0
        ax = xx - x0

        def tap(r, c):
            ok = (r >= 0) & (r < 6) & (c >= 0) & (c < 6)
            return np.where(ok, x[0, 0, np.clip(r, 0, 5),
                                  np.clip(c, 0, 5)], 0.0)

        return (tap(y0, x0) * (1 - ay) * (1 - ax) +
                tap(y0, x0 + 1) * (1 - ay) * ax +
                tap(y0 + 1, x0) * ay * (1 - ax) +
                tap(y0 + 1, x0 + 1) * ay * ax)

    S = 400
    exp = np.zeros((2, 2))
    for ph in range(2):
        for pw in range(2):
            ys = np.linspace(1.0 + ph * 2, 1.0 + (ph + 1) * 2, S)
            xs = np.linspace(0.5 + pw * 2, 0.5 + (pw + 1) * 2, S)
            YY, XX = np.meshgrid(ys, xs, indexing="ij")
            exp[ph, pw] = bilin(YY, XX).mean()
    np.testing.assert_allclose(got, exp, atol=2e-3)


def test_prroi_pool_grad_flows():
    import jax
    import jax.numpy as jnp
    x = jnp.asarray(np.random.RandomState(0).randn(1, 1, 6, 6)
                    .astype(np.float32))
    rois = jnp.asarray([[0, 1.0, 1.0, 5.0, 5.0]], dtype=jnp.float32)

    def f(xx):
        out = run_kernel("prroi_pool", {"X": xx, "ROIs": rois},
                         {"pooled_height": 2, "pooled_width": 2,
                          "spatial_scale": 1.0}, OpContext())
        return jnp.sum(out["Out"])

    g = jax.grad(f)(x)
    assert np.isfinite(np.asarray(g)).all() and np.abs(g).sum() > 0


def test_roi_perspective_transform_identity():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 3, 0, 3, 3, 0, 3]], np.float32)
    out = _run("roi_perspective_transform", {"X": x, "ROIs": rois},
               {"transformed_height": 4, "transformed_width": 4,
                "spatial_scale": 1.0})
    np.testing.assert_allclose(np.asarray(out["Out"])[0, 0], x[0, 0],
                               atol=1e-4)
    assert np.asarray(out["Mask"]).min() >= 0
    assert np.asarray(out["TransformMatrix"]).shape == (1, 9)


# ---------------------------------------------------------------------------
# rpn_target_assign / retinanet_target_assign / generate_proposal_labels
# ---------------------------------------------------------------------------

def test_rpn_target_assign_deterministic():
    anchors = np.array([[0, 0, 10, 10], [0, 0, 9, 9], [20, 20, 30, 30],
                        [40, 40, 45, 45]], np.float32)
    gt = np.array([[[0, 0, 10, 10], [21, 21, 30, 30]]], np.float32)
    crowd = np.zeros((1, 2), np.int32)
    info = np.array([[50, 50, 1]], np.float32)
    out = _run("rpn_target_assign",
               {"Anchor": anchors, "GtBoxes": gt, "IsCrowd": crowd,
                "ImInfo": info},
               {"rpn_batch_size_per_im": 4, "rpn_positive_overlap": 0.7,
                "rpn_negative_overlap": 0.3, "rpn_fg_fraction": 0.5,
                "use_random": False, "rpn_straddle_thresh": 0.0})
    # anchor0 = exact gt0 match (fg), anchor1 iou(gt0)=.81 >= .7 (fg),
    # anchor2 iou(gt1)=.81 but is gt1's best -> fg candidate, capped by
    # fg_fraction*batch=2; anchor3 iou 0 -> bg
    assert np.asarray(out["LocationIndex"]).tolist() == [0, 1, -1, -1]
    assert np.asarray(out["TargetLabel"])[:, 0].tolist() == [1, 1, 0, -1]
    assert int(out["LocCount"][0]) == 2
    # anchor0's target delta vs gt0 is zero (exact match)
    np.testing.assert_allclose(np.asarray(out["TargetBBox"])[0],
                               np.zeros(4), atol=1e-5)
    np.testing.assert_allclose(np.asarray(out["BBoxInsideWeight"])[0],
                               np.ones(4))


def test_rpn_target_assign_random_respects_counts():
    rng = np.random.RandomState(1)
    anchors = rng.uniform(0, 90, (32, 2)).astype(np.float32)
    anchors = np.concatenate([anchors, anchors + 10], axis=1)
    gt = np.array([[[10, 10, 25, 25], [50, 50, 70, 70]]], np.float32)
    crowd = np.zeros((1, 2), np.int32)
    info = np.array([[100, 100, 1]], np.float32)
    out = _run("rpn_target_assign",
               {"Anchor": anchors, "GtBoxes": gt, "IsCrowd": crowd,
                "ImInfo": info},
               {"rpn_batch_size_per_im": 8, "rpn_positive_overlap": 0.7,
                "rpn_negative_overlap": 0.3, "rpn_fg_fraction": 0.5,
                "use_random": True, "rpn_straddle_thresh": 0.0})
    n_loc = int(out["LocCount"][0])
    n_sc = int(out["ScoreCount"][0])
    assert 0 < n_loc <= 4 and n_loc <= n_sc <= 8
    loc = np.asarray(out["LocationIndex"])
    assert (loc[:n_loc] >= 0).all() and (loc[n_loc:] == -1).all()


def test_retinanet_target_assign_labels():
    anchors = np.array([[0, 0, 10, 10], [0, 0, 9, 9], [20, 20, 30, 30],
                        [40, 40, 45, 45]], np.float32)
    gt = np.array([[[0, 0, 10, 10], [21, 21, 30, 30]]], np.float32)
    lbl = np.array([[1, 2]], np.int32)
    crowd = np.zeros((1, 2), np.int32)
    info = np.array([[50, 50, 1]], np.float32)
    out = _run("retinanet_target_assign",
               {"Anchor": anchors, "GtBoxes": gt, "GtLabels": lbl,
                "IsCrowd": crowd, "ImInfo": info},
               {"positive_overlap": 0.5, "negative_overlap": 0.4})
    # no sampling: anchors 0,1 -> class 1; anchor 2 -> class 2; 3 -> bg
    assert np.asarray(out["TargetLabel"])[:, 0].tolist() == [1, 1, 2, 0]
    assert int(np.asarray(out["ForegroundNumber"])[0, 0]) == 3


def test_generate_proposal_labels_deterministic():
    rois = np.array([[[0, 0, 10, 10], [18, 18, 31, 31],
                      [40, 40, 45, 45]]], np.float32)
    gt = np.array([[[0, 0, 10, 10], [21, 21, 30, 30]]], np.float32)
    gcls = np.array([[1, 2]], np.int32)
    crowd = np.zeros((1, 2), np.int32)
    info = np.array([[50, 50, 1]], np.float32)
    out = _run("generate_proposal_labels",
               {"RpnRois": rois, "GtClasses": gcls, "IsCrowd": crowd,
                "GtBoxes": gt, "ImInfo": info},
               {"batch_size_per_im": 4, "fg_fraction": 0.5,
                "fg_thresh": 0.5, "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
                "class_nums": 3, "use_random": False})
    labels = np.asarray(out["LabelsInt32"])[0, :, 0]
    assert labels.tolist() == [1, 1, 0, 0]
    assert int(out["RoisNum"][0]) == 4
    # fg rows have one-hot box-target slots at their class
    tgt = np.asarray(out["BboxTargets"])[0].reshape(4, 3, 4)
    inw = np.asarray(out["BboxInsideWeights"])[0].reshape(4, 3, 4)
    assert inw[0, 1].sum() == 4 and inw[0, 0].sum() == 0
    assert inw[2].sum() == 0  # bg row: no box loss
    # roi0 == gt0 -> zero deltas
    np.testing.assert_allclose(tgt[0, 1], np.zeros(4), atol=1e-5)


def test_generate_proposal_labels_random_counts():
    rng = np.random.RandomState(2)
    rois = rng.uniform(0, 40, (1, 16, 2)).astype(np.float32)
    rois = np.concatenate([rois, rois + rng.uniform(5, 20, (1, 16, 2))],
                          axis=2)
    gt = np.array([[[5, 5, 20, 20]]], np.float32)
    gcls = np.array([[3]], np.int32)
    crowd = np.zeros((1, 1), np.int32)
    info = np.array([[64, 64, 1]], np.float32)
    out = _run("generate_proposal_labels",
               {"RpnRois": rois, "GtClasses": gcls, "IsCrowd": crowd,
                "GtBoxes": gt, "ImInfo": info},
               {"batch_size_per_im": 8, "fg_fraction": 0.25,
                "fg_thresh": 0.5, "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0,
                "class_nums": 4, "use_random": True})
    labels = np.asarray(out["LabelsInt32"])[0, :, 0]
    n = int(out["RoisNum"][0])
    n_fg = int((labels > 0).sum())
    assert n_fg <= 2 and n <= 8
    assert ((labels[:n] >= 0)).all()


# ---------------------------------------------------------------------------
# generate_mask_labels / detection_map
# ---------------------------------------------------------------------------

def test_generate_mask_labels_rasterises():
    info = np.array([[32, 32, 1]], np.float32)
    gcls = np.array([[1]], np.int32)
    crowd = np.zeros((1, 1), np.int32)
    poly = np.full((1, 1, 8, 2), np.nan, np.float32)
    poly[0, 0, :4] = [[0, 0], [8, 0], [8, 16], [0, 16]]
    rois = np.array([[[0, 0, 16, 16]]], np.float32)
    labels = np.array([[[1]]], np.int32)
    out = _run("generate_mask_labels",
               {"ImInfo": info, "GtClasses": gcls, "IsCrowd": crowd,
                "GtSegms": poly, "Rois": rois, "LabelsInt32": labels},
               {"num_classes": 2, "resolution": 4})
    m = np.asarray(out["MaskInt32"])[0, 0].reshape(2, 4, 4)
    # polygon covers the left half of the roi
    np.testing.assert_array_equal(m[1][:, :2], np.ones((4, 2)))
    np.testing.assert_array_equal(m[1][:, 2:], np.zeros((4, 2)))
    assert (m[0] == -1).all()  # non-label class slot stays -1
    assert int(out["MaskRoisNum"][0]) == 1


def test_detection_map_perfect_and_miss():
    det = np.array([[[1, 0.9, 0, 0, 10, 10]]], np.float32)
    lbl = np.array([[[1, 0, 0, 0, 10, 10]]], np.float32)
    out = _run("detection_map", {"DetectRes": det, "Label": lbl},
               {"class_num": 2, "overlap_threshold": 0.5,
                "ap_type": "integral", "background_label": 0})
    np.testing.assert_allclose(np.asarray(out["MAP"]), [1.0], atol=1e-6)
    # a detection that misses every gt -> AP 0
    det2 = np.array([[[1, 0.9, 50, 50, 60, 60]]], np.float32)
    out2 = _run("detection_map", {"DetectRes": det2, "Label": lbl},
                {"class_num": 2, "overlap_threshold": 0.5,
                 "ap_type": "integral", "background_label": 0})
    np.testing.assert_allclose(np.asarray(out2["MAP"]), [0.0], atol=1e-6)


def test_detection_map_accumulates_state():
    lbl = np.array([[[1, 0, 0, 0, 10, 10]]], np.float32)
    hit = np.array([[[1, 0.9, 0, 0, 10, 10]]], np.float32)
    miss = np.array([[[1, 0.8, 50, 50, 60, 60]]], np.float32)
    attrs = {"class_num": 2, "overlap_threshold": 0.5,
             "ap_type": "integral", "background_label": 0,
             "state_capacity": 16}
    out1 = _run("detection_map", {"DetectRes": hit, "Label": lbl}, attrs)
    out2 = _run("detection_map",
                {"DetectRes": miss, "Label": lbl,
                 "HasState": np.array([1], np.int32),
                 "PosCount": out1["AccumPosCount"],
                 "TruePos": out1["AccumTruePos"],
                 "FalsePos": out1["AccumFalsePos"]}, attrs)
    # 2 gts, 1 tp @0.9 + 1 fp @0.8: precision-recall integral = 0.5
    np.testing.assert_allclose(np.asarray(out2["MAP"]), [0.5], atol=1e-6)


# ---------------------------------------------------------------------------
# Faster-RCNN-style head through static.layers (VERDICT done-criterion)
# ---------------------------------------------------------------------------

def test_faster_rcnn_head_builds_and_runs():
    import paddle_tpu.static as static
    from paddle_tpu.static import layers

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        feat = layers.data("feat", [-1, 16, 8, 8], dtype="float32")
        im_info = layers.data("im_info", [-1, 3], dtype="float32")
        gt_boxes = layers.data("gt_boxes", [-1, 4, 4], dtype="float32")
        gt_classes = layers.data("gt_classes", [-1, 4], dtype="int32")
        is_crowd = layers.data("is_crowd", [-1, 4], dtype="int32")
        anchors, var = layers.anchor_generator(
            feat, anchor_sizes=[32.0], aspect_ratios=[1.0],
            stride=[16.0, 16.0])
        rpn_cls = layers.conv2d(feat, 1, 1)
        rpn_bbox = layers.conv2d(feat, 4, 1)
        rois, probs, num = layers.generate_proposals(
            rpn_cls, rpn_bbox, im_info,
            layers.reshape(anchors, [-1, 4]),
            layers.reshape(var, [-1, 4]),
            pre_nms_top_n=32, post_nms_top_n=8, return_rois_num=True)
        s_rois, s_labels, s_tgt, s_inw, s_outw = \
            layers.generate_proposal_labels(
                rois, gt_classes, is_crowd, gt_boxes, im_info,
                batch_size_per_im=8, fg_fraction=0.5, fg_thresh=0.5,
                bg_thresh_hi=0.5, bg_thresh_lo=0.0, class_nums=3,
                use_random=False)
        pred_sc, pred_loc, t_lbl, t_bbox, inw = layers.rpn_target_assign(
            rpn_bbox, rpn_cls, layers.reshape(anchors, [-1, 4]),
            layers.reshape(var, [-1, 4]), gt_boxes, is_crowd, im_info,
            rpn_batch_size_per_im=16, use_random=False)

    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(0)
    with static.scope_guard(scope):
        exe.run(startup)
        outs = exe.run(main, feed={
            "feat": rng.randn(1, 16, 8, 8).astype(np.float32),
            "im_info": np.array([[128, 128, 1]], np.float32),
            "gt_boxes": np.array([[[8, 8, 40, 40], [60, 60, 100, 100],
                                   [0, 0, 0, 0], [0, 0, 0, 0]]],
                                 np.float32),
            "gt_classes": np.array([[1, 2, 0, 0]], np.int32),
            "is_crowd": np.array([[0, 0, 1, 1]], np.int32),
        }, fetch_list=[s_rois, s_labels, pred_loc, t_lbl])
    assert np.asarray(outs[0]).shape == (1, 8, 4)
    assert np.asarray(outs[1]).shape == (1, 8, 1)
    assert np.isfinite(np.asarray(outs[2])).all()


def test_retinanet_target_assign_batch_offsets():
    """Review r4: with N=2 images the Location/Score indices must carry
    the i*A global offset (they gather from batch-flattened preds)."""
    anchors = np.array([[0, 0, 10, 10], [40, 40, 45, 45]], np.float32)
    gt = np.array([[[0, 0, 10, 10], [0, 0, 0, 0]],
                   [[0, 0, 10, 10], [0, 0, 0, 0]]], np.float32)
    lbl = np.array([[1, 0], [2, 0]], np.int32)
    crowd = np.array([[0, 1], [0, 1]], np.int32)
    info = np.array([[50, 50, 1], [50, 50, 1]], np.float32)
    out = _run("retinanet_target_assign",
               {"Anchor": anchors, "GtBoxes": gt, "GtLabels": lbl,
                "IsCrowd": crowd, "ImInfo": info},
               {"positive_overlap": 0.5, "negative_overlap": 0.4})
    loc = np.asarray(out["LocationIndex"])
    # image 0's fg anchor is global 0; image 1's fg anchor is global 2
    # (= 1 * A + 0 with A=2)
    live = loc[loc >= 0]
    assert live.tolist() == [0, 2]
    labels = np.asarray(out["TargetLabel"])[:, 0]
    # per-image label blocks: [cls, bg] for each image
    assert labels.tolist() == [1, 0, 2, 0]

"""Worker for test_jax_distributed_two_process — each process joins a real
jax.distributed coordination service (the NCCL2-bootstrap analog,
reference imperative/nccl_context.cc:22-134), forms a GLOBAL mesh spanning
both processes' CPU devices, and runs the framework's c_allreduce_sum
kernel across the process boundary."""
import json
import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")


def main():
    port, rank, out_dir = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2, process_id=rank)

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P, NamedSharding
    from jax.experimental.shard_map import shard_map
    from paddle_tpu.ops.registry import run_kernel, OpContext

    devs = np.array(jax.devices())          # 4 global (2 per process)
    assert devs.size == 4, devs
    mesh = Mesh(devs, ("dp",))
    ctx = OpContext(mesh_axes=("dp",), dist_info={0: "dp"})

    def step(x):
        return run_kernel("c_allreduce_sum", {"X": x},
                          {"ring_id": 0, "use_calc_stream": True},
                          ctx)["Out"]

    fn = jax.jit(shard_map(step, mesh=mesh, in_specs=P("dp"),
                           out_specs=P("dp")))
    # per-device shard value = global shard index + 1 -> allreduce sum
    # over 4 shards = 1+2+3+4 = 10 everywhere
    sharding = NamedSharding(mesh, P("dp"))
    local = np.stack([
        np.full((3,), rank * 2 + 1, np.float32),
        np.full((3,), rank * 2 + 2, np.float32)])
    garr = jax.make_array_from_process_local_data(sharding, local, (4, 3))
    out = fn(garr)
    vals = sorted(float(np.asarray(s.data).ravel()[0])
                  for s in out.addressable_shards)
    with open(os.path.join(out_dir, f"allreduce_rank{rank}.json"),
              "w") as f:
        json.dump({"rank": rank, "shard_values": vals,
                   "n_global_devices": int(devs.size)}, f)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()

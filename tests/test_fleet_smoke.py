"""Tier-1 gate for the fleet control plane: two supervised process
groups rendezvous, one is chaos-killed whole, the survivors agree on one
re-formed world and resume from a rank-merged restore, bitwise-equal to
an uninterrupted run (tools/fleet_smoke.py; docs/elastic.md)."""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_fleet_smoke_gate():
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if "host_platform_device_count" not in env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            " --xla_force_host_platform_device_count=8"
                            ).strip()
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_smoke.py")],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO)
    assert proc.returncode == 0, (
        f"fleet smoke failed\nstdout:\n{proc.stdout[-4000:]}\n"
        f"stderr:\n{proc.stderr[-4000:]}")
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["metric"] == "fleet_smoke_reformed_world"
    assert result["value"] == 4  # survivors' capacity of the logical 8
    assert result["bitwise_loss_trace"] is True
    assert result["bitwise_params"] is True
    assert result["restore_step"] is not None
    # budget: the whole two-launcher chaos scenario + in-process
    # reference; generous headroom over the ~15 s typical so a loaded
    # CI box never flakes the gate
    assert result["wall_s"] < 120, result

"""Tier-1 recompile-regression gate (NOT marked slow — a retrace in the
executor hot path must fail the suite, not wait for a perf round).

Drives tools/perf_smoke.py in-process: bert-tiny, a short prefetched
epoch with a ragged final batch, hard assertions that warmup compiles at
most 2 signatures and the steady-state loop (including the ragged tail)
never traces again.
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_perf_smoke_gate(tmp_path):
    import perf_smoke
    result = perf_smoke.run_smoke(steps=8, cache_dir=str(tmp_path / "xla"))
    assert result["traces"] <= 2, result
    assert result["traces_after_warmup"] == 0, result
    assert result["bucket_hits"] >= 1, result
    assert result["value"] > 0
    # restore the default persistent cache dir for subsequent tests
    from paddle_tpu.core import compile_cache
    compile_cache.initialize(force=True)


def test_perf_smoke_cli_prints_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_smoke.py"),
         "--steps", "6"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    line = out.stdout.strip().splitlines()[-1]
    result = json.loads(line)
    assert result["traces_after_warmup"] == 0
    assert result["value"] > 0

"""Pipeline-parallelism tests (SectionWorker/PipelineTrainer analog,
reference section_worker.cc:82 GPipe schedule).  Run on the virtual
8-device CPU mesh; stages are pinned to distinct cpu devices."""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers
import paddle_tpu.distributed as dist


def _pipeline_model():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        with static.device_guard("xla:0"):
            x = layers.data("x", [-1, 8])
            y = layers.data("y", [-1, 1])
            h = layers.fc(x, size=16, act="relu")
        with static.device_guard("xla:1"):
            pred = layers.fc(h, size=1)
            loss = layers.mean(
                layers.square(layers.elementwise_sub(pred, y)))
    return main, startup, loss


def test_stage_assignment():
    from paddle_tpu.pipeline import assign_stages
    main, startup, loss = _pipeline_model()
    with static.program_guard(main, startup):
        static.SGD(learning_rate=0.05).minimize(loss)
    stages = assign_stages(main.global_block())
    assert max(stages) == 1
    # backward ops inherit their forward op's stage via the copied attrs
    from paddle_tpu.core.program import OpRole
    bwd_stages = [s for op, s in zip(main.global_block().ops, stages)
                  if op.op_role & OpRole.Backward]
    assert 0 in bwd_stages and 1 in bwd_stages


def test_pipeline_trains_and_matches_plain():
    """Pipelined run must match the plain executor numerically: same
    program, same fixed batch, M=4 micro-batches of identical rows →
    identical gradients."""
    xb = np.tile(np.random.RandomState(0).rand(4, 8).astype(np.float32),
                 (4, 1))
    yb = xb.sum(1, keepdims=True).astype(np.float32)

    # plain run
    main, startup, loss = _pipeline_model()
    with static.program_guard(main, startup):
        static.SGD(learning_rate=0.05).minimize(loss)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            (plain_loss,) = exe.run(main, feed={"x": xb, "y": yb},
                                    fetch_list=[loss])
        plain_w = {p.name: np.asarray(scope.get(p.name))
                   for p in main.all_parameters()}

    # pipelined run (fresh, same seed/initialization via same program clone)
    main2, startup2, loss2 = _pipeline_model()
    with static.program_guard(main2, startup2):
        opt = static.SGD(learning_rate=0.05)
        from paddle_tpu.pipeline import PipelineOptimizer
        popt = PipelineOptimizer(opt, num_microbatches=4)
        popt.minimize(loss2)
    pp = main2._pipeline_compiled
    counts = pp.stage_op_counts()
    assert len(counts["fwd"]) == 2, counts
    assert all(c > 0 for c in counts["fwd"]), counts
    exe2 = static.Executor()
    scope2 = static.Scope()
    with static.scope_guard(scope2):
        exe2.run(startup2)
        for _ in range(3):
            (pp_loss,) = exe2.run(pp, feed={"x": xb, "y": yb},
                                  fetch_list=[loss2])
        pp_w = {p.name: np.asarray(scope2.get(p.name))
                for p in main2.all_parameters()}

    assert np.isfinite(pp_loss).all()
    np.testing.assert_allclose(float(pp_loss), float(plain_loss),
                               rtol=1e-4, atol=1e-5)
    for (n1, w1), (n2, w2) in zip(sorted(plain_w.items()),
                                  sorted(pp_w.items())):
        np.testing.assert_allclose(w1, w2, rtol=1e-4, atol=1e-5)


def test_pipeline_converges():
    main, startup, loss = _pipeline_model()
    with static.program_guard(main, startup):
        from paddle_tpu.pipeline import PipelineOptimizer
        PipelineOptimizer(static.Adam(learning_rate=0.01),
                          num_microbatches=2).minimize(loss)
    pp = main._pipeline_compiled
    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(1)
    xb = rng.rand(16, 8).astype(np.float32)
    yb = xb.sum(1, keepdims=True).astype(np.float32)
    with static.scope_guard(scope):
        exe.run(startup)
        losses = []
        for _ in range(40):
            (lv,) = exe.run(pp, feed={"x": xb, "y": yb}, fetch_list=[loss])
            losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])


def test_fleet_pipeline_strategy():
    from paddle_tpu.distributed.fleet.base.fleet_base import Fleet
    f = Fleet()
    f.init(is_collective=True)
    main, startup, loss = _pipeline_model()
    strategy = dist.fleet.DistributedStrategy()
    strategy.pipeline = True
    strategy.pipeline_configs = {"micro_batch": 2, "accumulate_steps": 2}
    with static.program_guard(main, startup):
        f.distributed_optimizer(static.SGD(learning_rate=0.05), strategy)
        f.minimize(loss)
    assert "FleetPipelineOptimizer" in f.applied_meta_list()
    from paddle_tpu.pipeline import PipelineCompiledProgram
    assert isinstance(f.main_program, PipelineCompiledProgram)
    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(2)
    with static.scope_guard(scope):
        exe.run(startup)
        xb = rng.rand(8, 8).astype(np.float32)
        yb = xb.sum(1, keepdims=True).astype(np.float32)
        l0 = None
        for _ in range(20):
            (lv,) = exe.run(f.main_program, feed={"x": xb, "y": yb},
                            fetch_list=[loss])
            l0 = l0 if l0 is not None else float(lv)
        assert float(lv) < l0


def test_pipeline_per_example_fetch_concatenates():
    """Per-example fetches (leading dim == micro-batch size) come back
    concatenated to the full mini-batch, not averaged (section_worker
    fetch semantics)."""
    xb = np.random.RandomState(1).rand(8, 8).astype(np.float32)
    yb = xb.sum(1, keepdims=True).astype(np.float32)
    main, startup, loss = _pipeline_model()
    with static.program_guard(main, startup):
        from paddle_tpu.pipeline import PipelineOptimizer
        PipelineOptimizer(static.SGD(learning_rate=0.01),
                          num_microbatches=4).minimize(loss)
    pp = main._pipeline_compiled
    # locate the prediction var (elementwise_sub X input, per-example [B, 1])
    pred_var = None
    for op in main.global_block().ops:
        if op.type == "elementwise_sub":
            pred_var = op.inputs["X"][0]
            break
    assert pred_var is not None
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        pred_out, loss_out = exe.run(pp, feed={"x": xb, "y": yb},
                                     fetch_list=[pred_var, loss])
    assert pred_out.shape == (8, 1), pred_out.shape
    assert np.asarray(loss_out).ndim == 0 or np.asarray(loss_out).size == 1

"""REAL int8 execution path (VERDICT r4 #5).

Reference ops: /root/reference/paddle/fluid/operators/quantize_op.cc:52,
dequantize_op.cc, requantize_op.cc and the cpu_quantize_pass int8
inference chain (ir/mkldnn/cpu_quantize_pass.cc) — here: quantize /
dequantize / requantize kernels plus the quant_int8_pass that rewrites a
QuantizationFreezePass-frozen program onto int8_matmul (int8 x int8 dot,
int32 accumulation), so a frozen program runs int8 math instead of
dequantize-then-fp32-matmul.
"""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers
from paddle_tpu.ops.registry import OpContext, run_kernel

import jax.numpy as jnp


def test_quantize_dequantize_requantize_kernels():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 6).astype(np.float32)
    scale = 127.0 / np.abs(x).max()
    q = run_kernel("quantize", {"Input": jnp.asarray(x)},
                   {"Scale": scale}, OpContext())["Output"]
    assert np.asarray(q).dtype == np.int8
    back = run_kernel("dequantize", {"Input": q}, {"Scale": scale},
                      OpContext())["Output"]
    np.testing.assert_allclose(np.asarray(back), x, atol=1.0 / scale)
    # requantize into a coarser domain == quantize directly with it
    s2 = scale / 2
    rq = run_kernel("requantize", {"Input": q},
                    {"Scale_in": scale, "Scale_out": s2},
                    OpContext())["Output"]
    direct = run_kernel("quantize", {"Input": jnp.asarray(x)},
                        {"Scale": s2}, OpContext())["Output"]
    assert np.abs(np.asarray(rq).astype(np.int32)
                  - np.asarray(direct).astype(np.int32)).max() <= 1
    # non-negative input -> uint8 domain
    u = run_kernel("quantize", {"Input": jnp.asarray(np.abs(x))},
                   {"Scale": scale, "is_negative_input": False},
                   OpContext())["Output"]
    assert np.asarray(u).dtype == np.uint8


def test_int8_matmul_close_to_float():
    rng = np.random.RandomState(1)
    x = rng.randn(5, 8).astype(np.float32)
    w = rng.randn(8, 4).astype(np.float32)
    bias = rng.randn(4).astype(np.float32)
    # freeze-style weight quantization (per-tensor)
    s = np.abs(w).max()
    wq = np.clip(np.round(w / s * 127.0), -127, 127).astype(np.int8)
    out = run_kernel(
        "int8_matmul",
        {"X": jnp.asarray(x), "W": jnp.asarray(wq),
         "WScale": jnp.asarray([s], np.float32),
         "Bias": jnp.asarray(bias)},
        {"max_range": 127.0}, OpContext())["Out"]
    ref = x @ w + bias
    err = np.abs(np.asarray(out) - ref).max() / (np.abs(ref).max() + 1e-6)
    assert err < 0.05, err
    # per-out-channel scales
    sc = np.abs(w).max(axis=0)
    wqc = np.clip(np.round(w / sc * 127.0), -127, 127).astype(np.int8)
    outc = run_kernel(
        "int8_matmul",
        {"X": jnp.asarray(x), "W": jnp.asarray(wqc),
         "WScale": jnp.asarray(sc, np.float32)},
        {"max_range": 127.0}, OpContext())["Out"]
    errc = np.abs(np.asarray(outc) - x @ w).max() / \
        (np.abs(x @ w).max() + 1e-6)
    assert errc < 0.05, errc


def _trained_mlp(scope, exe):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 3, act="softmax")
        loss = layers.mean(layers.cross_entropy(
            pred, layers.data("y", [-1, 1], dtype="int64")))
        static.Adam(learning_rate=0.02).minimize(loss)
    rng = np.random.RandomState(2)
    xb = rng.rand(64, 8).astype(np.float32)
    yb = (xb.sum(1) > 4).astype(np.int64)[:, None]
    exe.run(startup)
    for _ in range(60):
        exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
    from paddle_tpu.core.program import OpRole
    infer = main.clone(for_test=True)
    blk = infer.global_block()
    train_roles = (OpRole.Backward, OpRole.Optimize, OpRole.LRSched,
                   OpRole.Optimize | OpRole.LRSched)
    blk.ops = [op for op in blk.ops
               if op.attrs.get(OpRole.KEY, OpRole.Forward)
               not in train_roles]
    infer = infer._prune([pred.name])
    return infer, pred, xb


def test_frozen_program_runs_int8_dots(tmp_path):
    """End to end: PTQ-freeze an MLP, save it, load through the
    predictor — the pass pipeline rewrites onto int8_matmul and outputs
    stay within tolerance of the float model."""
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.io.framework_io import save_inference_model
    from paddle_tpu.slim import PostTrainingQuantization

    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        infer, pred, xb = _trained_mlp(scope, exe)
        float_out = exe.run(infer, feed={"x": xb[:8]},
                            fetch_list=[pred])[0]
        ptq = PostTrainingQuantization(exe, infer, ["x"], scope=scope)
        quant = ptq.quantize([{"x": xb[i:i + 8]}
                              for i in range(0, 64, 8)])
        save_inference_model(str(tmp_path), ["x"], [pred], exe, quant)

    config = Config(str(tmp_path))
    predictor = create_predictor(config)
    # the optimized program really contains int8 dots
    prog = predictor._program
    types = [op.type for op in prog.global_block().ops]
    assert "int8_matmul" in types, types
    assert not any(t in ("mul", "fc") for t in types), types
    (q_out,) = predictor.run([xb[:8]])
    err = np.abs(q_out - float_out).max() / \
        (np.abs(float_out).max() + 1e-6)
    assert err < 0.1, err


def test_quant_pass_leaves_float_programs_alone(tmp_path):
    from paddle_tpu.core.pass_framework import PassContext, get_pass
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 4])
        out = layers.fc(x, 2)
    before = [op.type for op in main.global_block().ops]
    ctx = PassContext()
    prog = get_pass("quant_int8_pass")(main, ctx)
    assert [op.type for op in prog.global_block().ops] == before

"""Tier-1 checkpoint-robustness gate (NOT marked slow — a regression in
atomic commit / CRC refusal / resume must fail the suite, not wait for a
fault in production).

Drives tools/ckpt_smoke.py: periodic async checkpoints, truncate the
newest shard, bit-flip the next, assert latest_step() skips the
truncated one and resume lands on the last valid step with a warning.
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_ckpt_smoke_gate(tmp_path):
    import ckpt_smoke
    result = ckpt_smoke.run_smoke(steps=6, root=str(tmp_path / "ckpts"))
    assert result["value"] == result["saved_steps"][-3], result
    assert result["load_fallbacks"] >= 1, result
    assert result["wall_s"] < 30, result


@pytest.mark.slow  # duplicates the in-process gate via a subprocess
def test_ckpt_smoke_cli_prints_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ckpt_smoke.py"),
         "--steps", "5"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    line = out.stdout.strip().splitlines()[-1]
    result = json.loads(line)
    assert result["truncated_step"] == result["saved_steps"][-1]
    assert result["value"] == result["saved_steps"][-3]

"""Elastic composition lifts (ISSUE 14 satellites): elastic × ZeRO-1
sharded window accumulation, and elastic × run_steps — the K-micro-step
window scanned into ONE device dispatch."""
import os

import numpy as np
import pytest

os.environ.setdefault("PADDLE_TPU_VERIFY", "warn")

import jax  # noqa: E402

import paddle_tpu.static as static  # noqa: E402
from paddle_tpu.core.program import _reset_unique_names  # noqa: E402
from paddle_tpu.distributed.compiled_program import CompiledProgram  # noqa: E402
from paddle_tpu.distributed.elastic import (  # noqa: E402
    elasticize, rebucket_feeds)
from paddle_tpu.distributed.sharding import shard_optimizer_states  # noqa: E402
from paddle_tpu.static import layers  # noqa: E402

LOGICAL = 8
STEPS = 5


def _build(zero_stage=0, elastic=True):
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss)
    plan = None
    if zero_stage:
        plan = shard_optimizer_states(main, startup, dp_degree=LOGICAL,
                                      stage=zero_stage)
    meta = None
    if elastic:
        meta = elasticize(main, startup, logical_dp=LOGICAL,
                          loss_name=loss)
    return main, startup, loss, meta, plan


def _feeds(n=STEPS):
    rng = np.random.RandomState(0)
    return [{"x": rng.rand(LOGICAL, 8).astype(np.float32),
             "y": rng.rand(LOGICAL, 1).astype(np.float32)}
            for _ in range(n)]


def _train(zero_stage, elastic, world, feeds=None):
    main, startup, loss, meta, _plan = _build(zero_stage, elastic)
    exe = static.Executor()
    scope = static.Scope()
    cp = CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, places=list(jax.devices())[:world])
    trace = []
    with static.scope_guard(scope):
        exe.run(startup)
        for f in feeds or _feeds():
            if elastic:
                for mf in rebucket_feeds(f, LOGICAL, world):
                    out = exe.run(cp, feed=mf,
                                  fetch_list=[meta["loss_avg"]])
            else:
                out = exe.run(cp, feed=f, fetch_list=[loss])
            trace.append(np.asarray(out[0]).reshape(-1)[0])
        params = {p.name: np.asarray(scope.get(p.name))
                  for p in main.all_parameters()}
    return np.asarray(trace, np.float64), params


# ---------------------------------------------------------------------------
# elastic × ZeRO-1
# ---------------------------------------------------------------------------
def test_elastic_zero1_allclose_to_plain_full_mesh():
    """plain-vs-elastic+zero1 on the 8-device mesh: the sharded window
    accumulation (c_elastic_fold pre_reduced over the reduce-scattered
    shard) reproduces the plain update to 1e-6."""
    t_plain, p_plain = _train(0, False, LOGICAL)
    t_ez, p_ez = _train(1, True, LOGICAL)
    np.testing.assert_allclose(t_ez, t_plain, atol=1e-6, rtol=1e-6)
    for n in p_plain:
        np.testing.assert_allclose(p_ez[n], p_plain[n], atol=1e-6,
                                   rtol=1e-6, err_msg=n)


def test_elastic_zero1_allclose_across_worlds():
    """the SAME elastic+zero1 program on a half mesh (K=2 micro-steps)
    stays allclose to the plain full-mesh run — the composition's
    topology contract (bitwise is traded for allclose by the
    reduce-scatter; docs/elastic.md)."""
    t_plain, p_plain = _train(0, False, LOGICAL)
    t_ez4, p_ez4 = _train(1, True, 4)
    np.testing.assert_allclose(t_ez4, t_plain, atol=1e-6, rtol=1e-6)
    for n in p_plain:
        np.testing.assert_allclose(p_ez4[n], p_plain[n], atol=1e-6,
                                   rtol=1e-6, err_msg=n)


def test_elastic_zero1_program_is_strict_clean():
    """V206/V207/V503 must all accept the composed program (the sharded
    fold is stamped + meta-marked; PADDLE_TPU_VERIFY=strict raises on
    any diagnostic)."""
    from paddle_tpu.static.verifier import check_program
    main, startup, loss, meta, plan = _build(1, True)
    assert meta["zero_stage1"] is True
    assert plan is not None and plan.buckets
    report = check_program(main, level="all")
    assert not report.errors, [str(d) for d in report.errors]


def test_elastic_refuses_zero_stage2():
    main, startup, loss, _meta, _plan = _build(0, False)
    shard_optimizer_states(main, startup, dp_degree=LOGICAL, stage=2)
    with pytest.raises(NotImplementedError, match="stage 1 only"):
        elasticize(main, startup, logical_dp=LOGICAL)


def test_elastic_zero1_sharded_accumulators_are_dp_shard():
    """The window accumulators live at 1/N per chip (dp_shard global
    padded shape), not full-size — the memory point of the lift."""
    main, _startup, _loss, meta, plan = _build(1, True)
    block = main.global_block()
    shard_accs = [a for a in meta["accs"] if "@ELASTIC_ACC" in a
                  and block.var(a).attrs.get("dp_shard")]
    assert len(shard_accs) == len(plan.buckets)
    for a in shard_accs:
        v = block.var(a)
        assert v.persistable
        assert int(v.attrs["dp_shard"]) == LOGICAL
    # and no full-size per-param elastic accumulator shadows the grads
    bucket_grads = {p["grad"] for b in plan.buckets for p in b["params"]}
    for g in bucket_grads:
        assert not any(acc.startswith(g + "@ELASTIC_ACC")
                       for acc in meta["accs"])


# ---------------------------------------------------------------------------
# elastic × run_steps (scanned K-micro-step window)
# ---------------------------------------------------------------------------
def test_elastic_run_steps_one_dispatch_bitwise():
    """One global step through run_steps = ONE device dispatch instead
    of K, with the loss trace and params BITWISE-equal to the looped
    form."""
    world = 4  # K = 2
    k = LOGICAL // world
    feeds = _feeds(4)

    main, startup, loss, meta, _ = _build(0, True)
    exe = static.Executor()
    scope = static.Scope()
    cp = CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, places=list(jax.devices())[:world])
    looped, looped_params = [], None
    with static.scope_guard(scope):
        exe.run(startup)
        d0 = cp._dispatches
        for f in feeds:
            for mf in rebucket_feeds(f, LOGICAL, world):
                out = exe.run(cp, feed=mf, fetch_list=[meta["loss_avg"]])
            looped.append(np.asarray(out[0]))
        looped_disp = cp._dispatches - d0
        looped_params = {p.name: np.asarray(scope.get(p.name))
                         for p in main.all_parameters()}
    assert looped_disp == k * len(feeds)

    main2, startup2, loss2, meta2, _ = _build(0, True)
    exe2 = static.Executor()
    scope2 = static.Scope()
    cp2 = CompiledProgram(main2).with_data_parallel(
        loss_name=loss2.name, places=list(jax.devices())[:world])
    scanned = []
    with static.scope_guard(scope2):
        exe2.run(startup2)
        d0 = cp2._dispatches
        for f in feeds:
            micro = rebucket_feeds(f, LOGICAL, world)
            stacked = {n: np.stack([m[n] for m in micro])
                       for n in micro[0]}
            outs = exe2.run_steps(cp2, feed=stacked,
                                  fetch_list=[meta2["loss_avg"]])
            # fetches stack to [K, ...]; the commit micro-step's value
            # is the global step's committed loss
            scanned.append(np.asarray(outs[0])[-1])
        scanned_disp = cp2._dispatches - d0
        scanned_params = {p.name: np.asarray(scope2.get(p.name))
                          for p in main2.all_parameters()}
    # the dispatch-count claim: K host dispatches collapse to 1
    assert scanned_disp == len(feeds)
    assert looped_disp == k * scanned_disp
    for i, (a, b) in enumerate(zip(looped, scanned)):
        assert np.array_equal(a, b), (i, a, b)
    for n in looped_params:
        assert np.array_equal(looped_params[n], scanned_params[n]), n


def test_elastic_run_steps_resumes_mid_stream_bitwise():
    """Switching dispatch modes mid-run (looped -> scanned) continues
    the same schedule: counters/seeds line up because the scan carries
    the same persistable micro counter."""
    world = 4
    feeds = _feeds(4)
    main, startup, loss, meta, _ = _build(0, True)
    exe = static.Executor()
    scope = static.Scope()
    cp = CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, places=list(jax.devices())[:world])
    mixed = []
    with static.scope_guard(scope):
        exe.run(startup)
        for gi, f in enumerate(feeds):
            micro = rebucket_feeds(f, LOGICAL, world)
            if gi % 2 == 0:
                for mf in micro:
                    out = exe.run(cp, feed=mf,
                                  fetch_list=[meta["loss_avg"]])
                mixed.append(np.asarray(out[0]))
            else:
                outs = exe.run_steps(cp, feed={
                    n: np.stack([m[n] for m in micro])
                    for n in micro[0]}, fetch_list=[meta["loss_avg"]])
                mixed.append(np.asarray(outs[0])[-1])

    main2, startup2, loss2, meta2, _ = _build(0, True)
    exe2 = static.Executor()
    scope2 = static.Scope()
    cp2 = CompiledProgram(main2).with_data_parallel(
        loss_name=loss2.name, places=list(jax.devices())[:world])
    looped = []
    with static.scope_guard(scope2):
        exe2.run(startup2)
        for f in feeds:
            for mf in rebucket_feeds(f, LOGICAL, world):
                out = exe2.run(cp2, feed=mf,
                               fetch_list=[meta2["loss_avg"]])
            looped.append(np.asarray(out[0]))
    for a, b in zip(looped, mixed):
        assert np.array_equal(a, b)


def test_run_steps_refuses_indivisible_per_step_batch():
    """Silently replicating a non-divisible per-step batch would run
    every rank over the full rows with a different summation order —
    the scanned path must fail loudly like the looped path does."""
    main, startup, loss, _m, _ = _build(0, False)
    exe = static.Executor()
    scope = static.Scope()
    cp = CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, places=list(jax.devices())[:4])
    with static.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(ValueError, match="does not divide"):
            exe.run_steps(cp, feed={
                "x": np.zeros((2, 6, 8), np.float32),
                "y": np.zeros((2, 6, 1), np.float32)},
                fetch_list=[loss])


def test_run_steps_raw_elastic_program_still_refused():
    main, startup, loss, meta, _ = _build(0, True)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(NotImplementedError, match="CompiledProgram"):
            exe.run_steps(main, feed={"x": np.zeros((2, 8, 8),
                                                    np.float32),
                                      "y": np.zeros((2, 8, 1),
                                                    np.float32)},
                          fetch_list=[meta["loss_avg"]])


def test_run_steps_compiled_non_elastic_matches_run():
    """The scanned CompiledProgram path is not elastic-only: a plain
    data-parallel program scans bitwise-equal to looped run()."""
    feeds = _feeds(3)
    main, startup, loss, _meta, _ = _build(0, False)
    exe = static.Executor()
    scope = static.Scope()
    cp = CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, places=list(jax.devices())[:LOGICAL])
    with static.scope_guard(scope):
        exe.run(startup)
        looped = [np.asarray(exe.run(cp, feed=f, fetch_list=[loss])[0])
                  for f in feeds]
        lp = {p.name: np.asarray(scope.get(p.name))
              for p in main.all_parameters()}

    main2, startup2, loss2, _m, _ = _build(0, False)
    exe2 = static.Executor()
    scope2 = static.Scope()
    cp2 = CompiledProgram(main2).with_data_parallel(
        loss_name=loss2.name, places=list(jax.devices())[:LOGICAL])
    with static.scope_guard(scope2):
        exe2.run(startup2)
        stacked = {n: np.stack([f[n] for f in feeds])
                   for n in feeds[0]}
        outs = exe2.run_steps(cp2, feed=stacked, fetch_list=[loss2])
        sp = {p.name: np.asarray(scope2.get(p.name))
              for p in main2.all_parameters()}
    scanned = np.asarray(outs[0])
    assert scanned.shape[0] == len(feeds)
    for i in range(len(feeds)):
        assert np.array_equal(scanned[i], looped[i]), i
    for n in lp:
        assert np.array_equal(lp[n], sp[n]), n

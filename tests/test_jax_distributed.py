"""Real 2-process jax.distributed wire-path test (VERDICT r3 #6): two OS
processes each with 2 virtual CPU devices join one coordination service
(the NCCL2-bootstrap analog the launcher env contract feeds,
reference imperative/nccl_context.cc:22-134) and run the framework's
c_allreduce_sum kernel across the process boundary — proving the
collective path under the launcher works over a real wire, not just the
in-process rehearsal of test_multihost_launch."""
import json
import os
import socket
import subprocess
import sys

import pytest


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_jax_distributed_two_process_allreduce(tmp_path):
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "distributed_worker.py")
    port = _free_port()
    # the workers own their XLA/JAX env (2 devices each); scrub the
    # test-session's 8-device forcing
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("XLA_", "JAX_"))}
    procs = [subprocess.Popen(
        [sys.executable, script, str(port), str(r), str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    outs = [p.communicate(timeout=300)[0] for p in procs]
    assert all(p.returncode == 0 for p in procs), \
        [o[-2000:].decode() for o in outs]
    for r in range(2):
        with open(tmp_path / f"allreduce_rank{r}.json") as f:
            res = json.load(f)
        # 4 global devices spanning 2 processes; psum of shard values
        # 1+2+3+4 lands 10 on every shard of every process
        assert res["n_global_devices"] == 4
        assert res["shard_values"] == [10.0, 10.0]

"""Heterogeneous-PS training (HeterWrapper/heterxpu_trainer analog).

Reference: /root/reference/paddle/fluid/framework/fleet/heter_wrapper.h:54
— CPU workers own the sparse embedding pull/push against the PS, device
workers run the dense compute, activations/grads shipped between them.
Here: one program is minimized, PS-transpiled in heter mode (table →
server-side optimizer, dense optimizer kept local), split at the boundary
activation into graph-op sections (distributed/heter.py), and run as two
REAL processes bridged by heter_send/heter_recv over KV queues.  The
bar (VERDICT r4 #3): the 2-process loss trace matches a local
single-process run.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers

B, V, D, STEPS = 16, 32, 8, 6


def _build(main, startup):
    with static.program_guard(main, startup):
        slots = layers.data("slots", [B, 3], dtype="int64")
        label = layers.data("label", [B, 1], dtype="int64")
        emb = layers.embedding(slots, size=[V, D], is_sparse=True,
                               is_distributed=True,
                               param_attr=static.ParamAttr(name="h_emb"))
        pooled = layers.reduce_sum(emb, dim=1)            # boundary [B, D]
        fc1 = layers.fc(pooled, 16, act="relu",
                        param_attr=static.ParamAttr(name="h_fc1_w"),
                        bias_attr=static.ParamAttr(name="h_fc1_b"))
        pred = layers.fc(fc1, 2, act="softmax",
                         param_attr=static.ParamAttr(name="h_fc2_w"),
                         bias_attr=static.ParamAttr(name="h_fc2_b"))
        loss = layers.mean(layers.cross_entropy(pred, label))
        static.SGD(learning_rate=0.2).minimize(loss)
    return pooled, loss


def _batch():
    rng = np.random.RandomState(0)
    slots = rng.randint(0, V, (B, 3)).astype(np.int64)
    y = (slots.sum(1) > 1.5 * V).astype(np.int64)[:, None]
    return slots, y


def _local_baseline():
    """Single-process run of the SAME program (local embedding)."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        slots = layers.data("slots", [B, 3], dtype="int64")
        label = layers.data("label", [B, 1], dtype="int64")
        emb = layers.embedding(slots, size=[V, D], is_sparse=True,
                               param_attr=static.ParamAttr(name="h_emb"))
        pooled = layers.reduce_sum(emb, dim=1)
        fc1 = layers.fc(pooled, 16, act="relu",
                        param_attr=static.ParamAttr(name="h_fc1_w"),
                        bias_attr=static.ParamAttr(name="h_fc1_b"))
        pred = layers.fc(fc1, 2, act="softmax",
                         param_attr=static.ParamAttr(name="h_fc2_w"),
                         bias_attr=static.ParamAttr(name="h_fc2_b"))
        loss = layers.mean(layers.cross_entropy(pred, label))
        static.SGD(learning_rate=0.2).minimize(loss)
    slots_v, y = _batch()
    exe = static.Executor()
    scope = static.Scope()
    losses = []
    with static.scope_guard(scope):
        exe.run(startup)
        for _ in range(STEPS):
            (lv,) = exe.run(main, feed={"slots": slots_v, "label": y},
                            fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
    return losses


def test_kv_queue_push_pop_fifo_and_timeout():
    from paddle_tpu.distributed.ps.kv_server import KVClient, KVServer
    srv = KVServer("127.0.0.1:0")
    srv.serve_in_thread()
    try:
        c = KVClient([srv.endpoint], rpc_deadline=5.0)
        c.wait_server_ready()
        c.q_push("q1", np.arange(3, dtype=np.float32))
        c.q_push("q1", np.arange(3, 6, dtype=np.float32))
        np.testing.assert_allclose(c.q_pop("q1"), [0, 1, 2])
        np.testing.assert_allclose(c.q_pop("q1"), [3, 4, 5])
        with pytest.raises(TimeoutError):
            c.q_pop("q1", timeout=0.5)
        c.close()
    finally:
        srv.stop()


def test_enqueue_dequeue_graph_ops():
    """Reference enqueue/dequeue/queue_generator op names as graph ops
    over the KV queues."""
    from paddle_tpu.distributed.ps.kv_server import KVServer
    srv = KVServer("127.0.0.1:0")
    srv.serve_in_thread()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = layers.data("x", [4], dtype="float32")
            blk = main.global_block()
            blk.append_op("queue_generator", {}, {},
                          {"names": ["opq"]})
            d = blk.create_var(shape=[1], dtype="float32")
            blk.append_op("enqueue", {"X": ["x"]}, {"Out": [d.name]},
                          {"queue_name": "opq",
                           "endpoints": [srv.endpoint]})
            out = blk.create_var(name="popped", shape=[4],
                                 dtype="float32")
            blk.append_op("dequeue", {"Dummy": [d.name]},
                          {"Out": ["popped"]},
                          {"queue_name": "opq", "shape": [4],
                           "dtype": "float32", "timeout": 10.0,
                           "endpoints": [srv.endpoint]})
        exe = static.Executor()
        scope = static.Scope()
        xv = np.array([9, 8, 7, 6], np.float32)
        with static.scope_guard(scope):
            exe.run(startup)
            (got,) = exe.run(main, feed={"x": xv},
                             fetch_list=["popped"])
        np.testing.assert_allclose(np.asarray(got), xv)
    finally:
        srv.stop()


def test_heter_split_sections_are_disjoint_and_complete():
    from paddle_tpu.distributed.heter import split_heter_program
    from paddle_tpu.distributed.ps.ps_optimizer import (
        DistributeTranspiler, DistributeTranspilerConfig)
    main, startup = static.Program(), static.Program()
    pooled, loss = _build(main, startup)
    cfg = DistributeTranspilerConfig()
    cfg.use_graph_ops = True
    cfg.heter_mode = True
    t = DistributeTranspiler(cfg)
    t.transpile(trainer_id=0, program=main, pservers="127.0.0.1:1",
                trainers=1, startup_program=startup)
    prog = t.get_trainer_program()
    cpu, dev = split_heter_program(prog, [pooled], ["127.0.0.1:1"],
                                   batch_size=B)
    cpu_types = [op.type for op in cpu.program.global_block().ops]
    dev_types = [op.type for op in dev.program.global_block().ops]
    # CPU side: pull rows, ship acts, recv grads, push SelectedRows grad
    assert "distributed_lookup_table" in cpu_types
    assert "heter_send" in cpu_types and "heter_recv" in cpu_types
    assert "send" in cpu_types                       # sparse table push
    # device side: dense fwd + loss + local optimizer, no table traffic
    assert "heter_recv" in dev_types and "heter_send" in dev_types
    assert "sgd" in dev_types
    assert "distributed_lookup_table" not in dev_types
    assert cpu.feeds == ["slots"]
    assert dev.feeds == ["label"]


def test_heter_two_process_matches_local_run(tmp_path):
    from paddle_tpu.distributed.heter import split_heter_program
    from paddle_tpu.distributed.ps.kv_server import KVServer
    from paddle_tpu.distributed.ps.ps_optimizer import (
        DistributeTranspiler, DistributeTranspilerConfig)

    baseline = _local_baseline()

    srv = KVServer("127.0.0.1:0", num_trainers=1)
    srv.serve_in_thread()
    proc = None
    try:
        main, startup = static.Program(), static.Program()
        pooled, loss = _build(main, startup)
        cfg = DistributeTranspilerConfig()
        cfg.use_graph_ops = True
        cfg.heter_mode = True
        cfg.sync_mode = True
        t = DistributeTranspiler(cfg)
        t.transpile(trainer_id=0, program=main, pservers=srv.endpoint,
                    trainers=1, startup_program=startup)
        prog = t.get_trainer_program()
        cpu, dev = split_heter_program(prog, [pooled], [srv.endpoint],
                                       batch_size=B)

        slots_v, y = _batch()
        spec = {"startup": t.get_startup_program().to_dict(),
                "cpu_program": cpu.program.to_dict(),
                "slots": slots_v.tolist(), "feed_name": "slots",
                "steps": STEPS}
        spec_path = str(tmp_path / "heter_spec.json")
        with open(spec_path, "w") as f:
            json.dump(spec, f)

        env = dict(os.environ, PADDLE_TRAINER_ID="0")
        proc = subprocess.Popen(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "heter_worker.py"),
             spec_path],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

        # device section in THIS process (the TPU-worker role)
        exe = static.Executor()
        scope = static.Scope()
        losses = []
        with static.scope_guard(scope):
            exe.run(t.get_startup_program())
            for _ in range(STEPS):
                (lv,) = exe.run(dev.program, feed={"label": y},
                                fetch_list=[loss])
                losses.append(float(np.asarray(lv)))

        out, _ = proc.communicate(timeout=120)
        assert proc.returncode == 0, out.decode()
        assert b"CPU_WORKER_DONE" in out
        # embedding on the CPU PS path, dense here — same math as local
        np.testing.assert_allclose(losses, baseline, rtol=1e-4,
                                   atol=1e-5)
        assert losses[-1] < losses[0]
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
        srv.stop()

"""Attention fusion pass (VERDICT r4 #6).

Reference: /root/reference/paddle/fluid/operators/fused/
multihead_matmul_op.cc:1 + ir/multihead_matmul_fuse_pass — the
predictor's BERT win: Q/K/V projections + softmax(QK^T)V collapse into
one fused op.  Here the fused op lowers onto the SHARED attention core
(flash when eligible, XLA otherwise).
"""
import numpy as np

import paddle_tpu.static as static
from paddle_tpu.static import layers

B, L, D, H = 2, 8, 16, 4


def _attention_block(x, mask=None, prefix="a"):
    """The static-graph attention idiom the reference pass matches."""
    def proj(name):
        return layers.fc(x, D, num_flatten_dims=2,
                         param_attr=static.ParamAttr(
                             name=f"{prefix}_{name}_w"),
                         bias_attr=static.ParamAttr(
                             name=f"{prefix}_{name}_b"))

    def heads(t):
        t = layers.reshape(t, [0, 0, H, D // H])
        return layers.transpose(t, [0, 2, 1, 3])

    q, k, v = heads(proj("q")), heads(proj("k")), heads(proj("v"))
    scores = layers.matmul(q, k, transpose_y=True,
                           alpha=1.0 / np.sqrt(D // H))
    if mask is not None:
        scores = layers.elementwise_add(scores, mask)
    ctx = layers.matmul(layers.softmax(scores), v)
    ctx = layers.transpose(ctx, [0, 2, 1, 3])
    ctx = layers.reshape(ctx, [0, 0, D])
    return layers.fc(ctx, D, num_flatten_dims=2,
                     param_attr=static.ParamAttr(name=f"{prefix}_o_w"),
                     bias_attr=static.ParamAttr(name=f"{prefix}_o_b"))


def _build(with_mask):
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [B, L, D])
        mask = layers.data("mask", [B, 1, L, L]) if with_mask else None
        out = _attention_block(x, mask)
    return main, startup, out


def _run(prog, startup, feed, fetch, scope):
    exe = static.Executor()
    with static.scope_guard(scope):
        exe.run(startup)
        return np.asarray(exe.run(prog, feed=feed,
                                  fetch_list=[fetch])[0])


def test_multihead_fuse_collapses_ops_and_matches():
    from paddle_tpu.inference.passes import PassContext, get_pass
    rng = np.random.RandomState(0)
    xv = rng.randn(B, L, D).astype(np.float32)
    mv = (rng.rand(B, 1, L, L) > 0.5).astype(np.float32) * -1e4

    for with_mask in (False, True):
        main, startup, out = _build(with_mask)
        feed = {"x": xv, "mask": mv} if with_mask else {"x": xv}
        scope = static.Scope()
        ref = _run(main, startup, feed, out, scope)

        n_before = len(main.global_block().ops)
        ctx = PassContext()
        fused = get_pass("multihead_matmul_fuse_pass")(main, ctx)
        types = [op.type for op in fused.global_block().ops]
        assert "multihead_matmul" in types, types
        assert "softmax" not in types
        # 17-op attention core + mask-add collapses to 1 fused op
        assert len(types) <= n_before - 14, (n_before, types)
        got = _run(fused, startup, feed, out, scope)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_multihead_fuse_mask_produced_after_projections():
    """The mask tensor computed AFTER the projection ops (valid
    topological order) must still reach the fused op — the fused op is
    inserted at the LAST matched position, not the first."""
    from paddle_tpu.inference.passes import PassContext, get_pass
    rng = np.random.RandomState(4)
    xv = rng.randn(B, L, D).astype(np.float32)
    raw = (rng.rand(B, 1, L, L) > 0.5).astype(np.float32)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [B, L, D])
        raw_mask = layers.data("raw_mask", [B, 1, L, L])

        def proj():
            return layers.fc(x, D, num_flatten_dims=2)

        def heads(t):
            return layers.transpose(
                layers.reshape(t, [0, 0, H, D // H]), [0, 2, 1, 3])

        q, k, v = heads(proj()), heads(proj()), heads(proj())
        mask = layers.scale(raw_mask, scale=-1e4)   # produced HERE
        scores = layers.elementwise_add(
            layers.matmul(q, k, transpose_y=True,
                          alpha=1.0 / np.sqrt(D // H)), mask)
        ctx_t = layers.matmul(layers.softmax(scores), v)
        ctx_t = layers.transpose(ctx_t, [0, 2, 1, 3])
        out = layers.reshape(ctx_t, [0, 0, D])

    feed = {"x": xv, "raw_mask": raw}
    scope = static.Scope()
    ref = _run(main, startup, feed, out, scope)
    fused = get_pass("multihead_matmul_fuse_pass")(main, PassContext())
    types = [op.type for op in fused.global_block().ops]
    assert "multihead_matmul" in types, types
    got = _run(fused, startup, feed, out, scope)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_multihead_fuse_leaves_cross_attention_alone():
    """Projections reading different inputs (cross-attention between two
    sources) must not be fused by the self-attention pattern."""
    from paddle_tpu.inference.passes import PassContext, get_pass
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [B, L, D])
        y = layers.data("y", [B, L, D])

        def heads(t):
            t = layers.reshape(t, [0, 0, H, D // H])
            return layers.transpose(t, [0, 2, 1, 3])

        q = heads(layers.fc(x, D, num_flatten_dims=2))
        k = heads(layers.fc(y, D, num_flatten_dims=2))
        v = heads(layers.fc(y, D, num_flatten_dims=2))
        scores = layers.matmul(q, k, transpose_y=True, alpha=0.5)
        ctx_t = layers.matmul(layers.softmax(scores), v)
        ctx_t = layers.transpose(ctx_t, [0, 2, 1, 3])
        layers.reshape(ctx_t, [0, 0, D])
    before = [op.type for op in main.global_block().ops]
    prog = get_pass("multihead_matmul_fuse_pass")(main, PassContext())
    assert [op.type for op in prog.global_block().ops] == before


def test_embedding_eltwise_layernorm_fuse():
    """BERT input block: 3 lookups + 2 adds + layer_norm -> 1 fused op,
    identical outputs."""
    from paddle_tpu.inference.passes import PassContext, get_pass
    V, Lp = 32, 6
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        w_ids = layers.data("w_ids", [B, Lp], dtype="int64")
        p_ids = layers.data("p_ids", [B, Lp], dtype="int64")
        t_ids = layers.data("t_ids", [B, Lp], dtype="int64")
        we = layers.embedding(w_ids, size=[V, D],
                              param_attr=static.ParamAttr(name="we"))
        pe = layers.embedding(p_ids, size=[Lp, D],
                              param_attr=static.ParamAttr(name="pe"))
        te = layers.embedding(t_ids, size=[2, D],
                              param_attr=static.ParamAttr(name="te"))
        s = layers.elementwise_add(layers.elementwise_add(we, pe), te)
        out = layers.layer_norm(s, begin_norm_axis=2)
    rng = np.random.RandomState(5)
    feed = {"w_ids": rng.randint(0, V, (B, Lp)).astype(np.int64),
            "p_ids": np.tile(np.arange(Lp), (B, 1)).astype(np.int64),
            "t_ids": rng.randint(0, 2, (B, Lp)).astype(np.int64)}
    scope = static.Scope()
    ref = _run(main, startup, feed, out, scope)
    prog = get_pass("embedding_eltwise_layernorm_fuse_pass")(
        main, PassContext())
    types = [op.type for op in prog.global_block().ops]
    assert "fused_embedding_eltwise_layernorm" in types, types
    assert "layer_norm" not in types and \
        "elementwise_add" not in types, types
    got = _run(prog, startup, feed, out, scope)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_embedding_fuse_preserves_padding_and_v1_squeeze():
    """padding_idx rows must stay zero and lookup_table (v1) trailing-1
    squeeze must survive fusion — per-leaf semantics ride in attrs."""
    from paddle_tpu.inference.passes import PassContext, get_pass
    V, Lp = 16, 5
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        w_ids = layers.data("w_ids", [B, Lp], dtype="int64")
        p_ids = layers.data("p_ids", [B, Lp], dtype="int64")
        we = layers.embedding(w_ids, size=[V, D], padding_idx=0,
                              param_attr=static.ParamAttr(name="pwe"))
        pe = layers.embedding(p_ids, size=[Lp, D],
                              param_attr=static.ParamAttr(name="ppe"))
        s = layers.elementwise_add(we, pe)
        out = layers.layer_norm(s, begin_norm_axis=2)
    rng = np.random.RandomState(6)
    wv = rng.randint(0, V, (B, Lp)).astype(np.int64)
    wv[:, 0] = 0                                  # padded positions
    feed = {"w_ids": wv,
            "p_ids": np.tile(np.arange(Lp), (B, 1)).astype(np.int64)}
    scope = static.Scope()
    ref = _run(main, startup, feed, out, scope)
    prog = get_pass("embedding_eltwise_layernorm_fuse_pass")(
        main, PassContext())
    types = [op.type for op in prog.global_block().ops]
    assert "fused_embedding_eltwise_layernorm" in types, types
    got = _run(prog, startup, feed, out, scope)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_embedding_fuse_skips_consumed_mean():
    """A consumed layer_norm Mean output keeps the float pattern."""
    from paddle_tpu.inference.passes import PassContext, get_pass
    V, Lp = 16, 5
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        w_ids = layers.data("w_ids", [B, Lp], dtype="int64")
        p_ids = layers.data("p_ids", [B, Lp], dtype="int64")
        we = layers.embedding(w_ids, size=[V, D])
        pe = layers.embedding(p_ids, size=[Lp, D])
        s = layers.elementwise_add(we, pe)
        blk = main.global_block()
        y = blk.create_var(name="ln_y", shape=[B, Lp, D],
                           dtype="float32")
        mean = blk.create_var(name="ln_mean", dtype="float32")
        var = blk.create_var(name="ln_var", dtype="float32")
        blk.append_op("layer_norm", {"X": [s.name]},
                      {"Y": ["ln_y"], "Mean": ["ln_mean"],
                       "Variance": ["ln_var"]},
                      {"begin_norm_axis": 2, "epsilon": 1e-5})
        layers.scale(blk.var("ln_mean"), scale=2.0)   # Mean consumed
    before = [op.type for op in main.global_block().ops]
    prog = get_pass("embedding_eltwise_layernorm_fuse_pass")(
        main, PassContext())
    assert [op.type for op in prog.global_block().ops] == before


def test_bert_style_predictor_end_to_end(tmp_path):
    """Two stacked attention layers through the saved-model predictor:
    the default pipeline fuses BOTH and outputs match the raw program."""
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.io.framework_io import save_inference_model

    rng = np.random.RandomState(1)
    xv = rng.randn(B, L, D).astype(np.float32)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [B, L, D])
        h = _attention_block(x, prefix="l0")
        h = _attention_block(h, prefix="l1")
        out = layers.reduce_mean(h, dim=[1, 2])

    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        (ref,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
        save_inference_model(str(tmp_path), ["x"], [out], exe, main)

    predictor = create_predictor(Config(str(tmp_path)))
    types = [op.type for op in
             predictor._program.global_block().ops]
    assert types.count("multihead_matmul") == 2, types
    (got,) = predictor.run([xv])
    np.testing.assert_allclose(got, np.asarray(ref), rtol=2e-4,
                               atol=2e-5)

"""Surface-audit tail: fleet data generators (emit the MultiSlot text
format the dataset tier parses), jit.TracedLayer, Bilinear initializer,
paddle.regularizer (reference incubate/data_generator, dygraph/jit.py
TracedLayer, fluid/initializer.py BilinearInitializer)."""
import numpy as np

import paddle_tpu
import paddle_tpu.static as static
from paddle_tpu.static import layers


def test_multislot_data_generator_roundtrip(tmp_path):
    from paddle_tpu.distributed.fleet import MultiSlotDataGenerator
    from paddle_tpu.distributed import DatasetFactory

    class CTRGen(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def it():
                ids = [int(x) for x in line.split()[:4]]
                dense = [float(x) / 50.0 for x in line.split()[:2]]
                label = [float(line.split()[0]) / 50.0]
                yield [("ids", ids), ("dense", dense), ("label", label)]

            return it

    gen = CTRGen()
    lines = [" ".join(str((7 * i + j) % 50) for j in range(4))
             for i in range(32)]
    text = gen.run_from_memory(lines)
    # 4-slot lines: "4 a b c d 2 f f 1 f"
    first = text.splitlines()[0].split()
    assert first[0] == "4" and first[5] == "2" and first[8] == "1"
    assert gen._proto_info[0] == ("ids", "uint64")
    assert gen._proto_info[1] == ("dense", "float")

    # the emitted file trains through the industrial dataset path
    p = tmp_path / "gen.txt"
    p.write_text(text)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ids = layers.data("ids", [-1, 4], dtype="int64")
        dense = layers.data("dense", [-1, 2])
        label = layers.data("label", [-1, 1])
        emb = layers.embedding(ids, size=[50, 8])
        feat = layers.concat([layers.reduce_sum(emb, dim=1), dense],
                             axis=1)
        pred = layers.fc(feat, 1, act="sigmoid")
        loss = layers.mean(layers.square(
            layers.elementwise_sub(pred, label)))
        static.SGD(learning_rate=0.1).minimize(loss)
    ds = DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(8)
    ds.set_filelist([str(p)])
    with static.program_guard(main, startup):
        ds.set_use_var([main.global_block().var(n)
                        for n in ("ids", "dense", "label")])
    exe, sc = static.Executor(), static.Scope()
    with static.scope_guard(sc):
        exe.run(startup)
        for _ in range(5):
            last = exe.train_from_dataset(main, ds, fetch_list=[loss])
    assert np.isfinite(float(np.asarray(last[0])))


def test_multislot_string_generator():
    from paddle_tpu.distributed.fleet import MultiSlotStringDataGenerator

    class G(MultiSlotStringDataGenerator):
        def generate_sample(self, line):
            def it():
                yield [("words", line.split()), ("label", ["1"])]

            return it

    out = G().run_from_memory(["a b c"])
    assert out == "3 a b c 1 1\n"


def test_traced_layer_and_predictor(tmp_path):
    from paddle_tpu import nn
    import paddle_tpu.jit as jit

    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def forward(self, x):
            return paddle_tpu.tensor.tanh(self.fc(x))

    m = M()
    x = paddle_tpu.to_tensor(np.random.RandomState(0).rand(3, 4)
                             .astype(np.float32))
    out, traced = jit.TracedLayer.trace(m, [x])
    out2 = traced([x])
    np.testing.assert_allclose(np.asarray(out.numpy()),
                               np.asarray(out2.numpy()), rtol=1e-6)
    path = str(tmp_path / "m")
    traced.save_inference_model(path)
    from paddle_tpu.inference import Config, create_predictor
    pred = create_predictor(Config(path))
    (got,) = pred.run([np.asarray(x.numpy())])
    np.testing.assert_allclose(got, np.asarray(out.numpy()), atol=1e-5)
    jit.set_verbosity(3)
    jit.set_code_level(50)


def test_bilinear_initializer_upsamples():
    from paddle_tpu.nn.initializer import Bilinear
    from paddle_tpu.static import ParamAttr
    main, startup = static.Program(), static.Program()
    factor = 2
    C = 3
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, C, 4, 4])
        up = layers.conv2d_transpose(
            x, C, filter_size=2 * factor - factor % 2, stride=factor,
            padding=int(np.ceil((factor - 1) / 2.0)), groups=C,
            param_attr=ParamAttr(initializer=Bilinear()),
            bias_attr=False)
    exe, sc = static.Executor(), static.Scope()
    im = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    im = np.tile(im, (1, C, 1, 1))
    with static.scope_guard(sc):
        exe.run(startup)
        (out,) = exe.run(main, feed={"x": im}, fetch_list=[up])
    out = np.asarray(out)
    assert out.shape == (1, C, 8, 8)
    # bilinear upsampling: the interior is a linear ramp at half the
    # input's slope per axis (input slope 1/col -> 0.5/col; 4/row ->
    # 2.0/row), and every channel gets the identical separable kernel
    np.testing.assert_allclose(np.diff(out[0, 0, 3, 2:7]), 0.5,
                               rtol=1e-5)
    np.testing.assert_allclose(np.diff(out[0, 0, 2:7, 3]), 2.0,
                               rtol=1e-5)
    np.testing.assert_allclose(out[0, 1], out[0, 0], rtol=1e-6)


def test_regularizer_namespace():
    import paddle_tpu.regularizer as reg
    from paddle_tpu.static.optimizer import L2Decay
    assert reg.L2Decay is L2Decay


def test_get_worker_info_shards_iterable_dataset():
    """get_worker_info() inside worker processes lets an
    IterableDataset shard its stream (reference dataloader_iter.py:122);
    in the main process it returns None."""
    from paddle_tpu.io import (DataLoader, IterableDataset,
                               get_worker_info)
    assert get_worker_info() is None

    class Sharded(IterableDataset):
        def __iter__(self):
            info = get_worker_info()
            n = 8
            if info is None:
                lo, hi, wid = 0, n, -1
            else:
                per = n // info.num_workers
                lo = info.id * per
                hi = n if info.id == info.num_workers - 1 else lo + per
                wid = info.id
            for i in range(lo, hi):
                yield np.array([i, wid], np.int64)

    loader = DataLoader(Sharded(), batch_size=2, num_workers=2)
    rows = [r for batch in loader
            for r in np.asarray(batch).reshape(-1, 2)]
    seen = sorted(int(r[0]) for r in rows)
    wids = {int(r[1]) for r in rows}
    assert seen == list(range(8)), seen
    # REAL worker processes produced the stream (info was populated
    # with both ids), not a single-process fallback (wid would be -1)
    assert wids == {0, 1}, wids


def test_utils_functions(tmp_path):
    import paddle_tpu.utils as U
    U.require_version("0.0.1")
    import pytest as _pytest
    with _pytest.raises(RuntimeError):
        U.require_version("999.0")

    @U.deprecated(update_to="paddle_tpu.fresh", since="0.1")
    def old_fn():
        return 41

    import warnings
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        assert old_fn() == 41
    assert any("Deprecated" in str(r.message) for r in rec)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        layers.data("x", [-1, 2])
    p = tmp_path / "prog.json"
    U.dump_config(main, str(p))
    assert p.read_text()


def test_static_nn_namespace_and_new_layers():
    """paddle.static.nn (reference python/paddle/static/nn): the 2.0
    static layer namespace + conv3d_transpose/data_norm/multi_box_head
    layers (reference layers/nn.py, layers/detection.py)."""
    import paddle_tpu.static.nn as sn
    assert sn.fc is not None and sn.case is not None
    main, startup = static.Program(), static.Program()
    rng = np.random.RandomState(0)
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 2, 3, 8, 8])
        up = sn.conv3d_transpose(x, 4, filter_size=2, stride=2)
        img = layers.data("img", [-1, 3, 64, 64])
        f1 = layers.data("f1", [-1, 8, 8, 8])
        f2 = layers.data("f2", [-1, 8, 4, 4])
        locs, confs, box, var = sn.multi_box_head(
            [f1, f2], img, base_size=64, num_classes=3,
            aspect_ratios=[[2.0], [2.0, 3.0]], min_ratio=20,
            max_ratio=90)
        d = layers.data("d", [-1, 6])
        dn = sn.data_norm(d)
    exe, sc = static.Executor(), static.Scope()
    with static.scope_guard(sc):
        exe.run(startup)
        out = exe.run(main, feed={
            "x": rng.rand(1, 2, 3, 8, 8).astype(np.float32),
            "img": rng.rand(1, 3, 64, 64).astype(np.float32),
            "f1": rng.rand(1, 8, 8, 8).astype(np.float32),
            "f2": rng.rand(1, 8, 4, 4).astype(np.float32),
            "d": rng.rand(4, 6).astype(np.float32),
        }, fetch_list=[up, locs, confs, box, var, dn])
    assert np.asarray(out[0]).shape == (1, 4, 6, 16, 16)
    locs_a, confs_a, box_a, var_a = (np.asarray(out[1]),
                                     np.asarray(out[2]),
                                     np.asarray(out[3]),
                                     np.asarray(out[4]))
    # SSD contract: one (loc, conf) per prior, aligned across maps
    assert locs_a.shape[1] == box_a.shape[0] == var_a.shape[0]
    assert locs_a.shape[2] == 4 and confs_a.shape[2] == 3
    assert np.asarray(out[5]).shape == (4, 6)

"""DownpourWorker capability: dataset-path training through the PS tier.

Reference: framework/downpour_worker.cc — the industrial device worker
that streams a Dataset while pulling/pushing sparse params against the
pslib PS.  Composition here: the SAME transpiled program (with
distributed_lookup_table pulls + sparse `send` pushes, server-resident
Adam) runs under `exe.train_from_dataset` over MultiSlot files — the
dataset tier and the PS tier working together.
"""
import numpy as np

import paddle_tpu.static as static
from paddle_tpu.static import layers
from paddle_tpu.distributed.dataset import DatasetFactory

V, D, B = 32, 8, 16


def _write_multislot(path, n, seed):
    rng = np.random.RandomState(seed)
    with open(path, "w") as f:
        for _ in range(n):
            ids = rng.randint(0, V, 3)
            label = int(ids.sum() > 1.5 * V)
            parts = ["3"] + [str(i) for i in ids]        # sparse slot
            parts += ["1", str(label)]                   # label slot
            f.write(" ".join(parts) + "\n")


def test_downpour_style_dataset_train_through_ps(tmp_path):
    from paddle_tpu.distributed.ps.kv_server import KVServer
    from paddle_tpu.distributed.ps.ps_optimizer import (
        DistributeTranspiler, DistributeTranspilerConfig)

    srv = KVServer("127.0.0.1:0", num_trainers=1)
    srv.serve_in_thread()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            ids = layers.data("ids", [-1, 3], dtype="int64")
            label = layers.data("label", [-1, 1], dtype="int64")
            emb = layers.embedding(ids, size=[V, D], is_sparse=True,
                                   is_distributed=True,
                                   param_attr=static.ParamAttr(
                                       name="dp_emb"))
            fc1 = layers.fc(layers.reduce_sum(emb, dim=1), 16,
                            act="relu")
            pred = layers.fc(fc1, 2, act="softmax")
            loss = layers.mean(layers.cross_entropy(pred, label))
            static.Adam(learning_rate=0.05).minimize(loss)

        cfg = DistributeTranspilerConfig()
        cfg.use_graph_ops = True
        cfg.sync_mode = True
        t = DistributeTranspiler(cfg)
        t.transpile(trainer_id=0, program=main, pservers=srv.endpoint,
                    trainers=1, startup_program=startup)
        prog = t.get_trainer_program()

        f1 = str(tmp_path / "part-0.txt")
        _write_multislot(f1, 20 * B, seed=0)
        ds = DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(B)
        ds.set_thread(1)
        ds.set_filelist([f1])
        with static.program_guard(main, startup):
            ds.set_use_var([main.global_block().var("ids"),
                            main.global_block().var("label")])
        ds.load_into_memory()
        ds.local_shuffle()

        exe = static.Executor()
        scope = static.Scope()
        with static.scope_guard(scope):
            exe.run(startup)
            # server-side Adam installed by the startup send
            assert srv._sparse_opt.get("dp_emb", {}).get("type") == \
                "adam"
            first = exe.train_from_dataset(prog, ds, fetch_list=[loss])
            l0 = float(np.asarray(first[0]))
            for _ in range(4):
                ds.local_shuffle()
                last = exe.train_from_dataset(prog, ds,
                                              fetch_list=[loss])
            l1 = float(np.asarray(last[0]))
        assert np.isfinite(l0) and np.isfinite(l1)
        assert l1 < l0, (l0, l1)
        # the embedding genuinely trained ON the server
        tab = srv.get("dp_emb")
        assert tab is not None and np.abs(tab).sum() > 0
    finally:
        srv.stop()

"""Op-coverage tail: detection family, CTC, CRF, beam decode, py_func
(reference operators/detection/, warpctc_op.cc, linear_chain_crf_op.cc,
beam_search_op.cc, py_func_op.cc).  DP recursions are checked against
brute-force path enumeration on tiny cases."""
import itertools

import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers


def _run(op, ins, attrs):
    import jax.numpy as jnp
    from paddle_tpu.ops.registry import run_kernel, OpContext

    def conv(v):
        if v is None:
            return None
        if isinstance(v, list):
            return [jnp.asarray(x) for x in v]
        return jnp.asarray(v)

    return run_kernel(op, {k: conv(v) for k, v in ins.items()}, attrs,
                      OpContext())


# ---------------------------------------------------------------------------
# detection
# ---------------------------------------------------------------------------
def test_multiclass_nms_suppresses_and_counts():
    boxes = np.array([[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                       [20, 20, 30, 30], [50, 50, 60, 60]]], np.float32)
    # class 0 = background; class 1 scores
    scores = np.zeros((1, 2, 4), np.float32)
    scores[0, 1] = [0.9, 0.8, 0.7, 0.01]  # box1 overlaps box0; box3 low
    out = _run("multiclass_nms", {"BBoxes": boxes, "Scores": scores},
               {"score_threshold": 0.05, "nms_threshold": 0.5,
                "nms_top_k": 4, "keep_top_k": 4, "background_label": 0})
    res, num = np.asarray(out["Out"])[0], int(out["NmsRoisNum"][0])
    assert num == 2  # overlapping box suppressed, low score dropped
    kept = res[res[:, 0] >= 0]
    assert kept.shape[0] == 2
    np.testing.assert_allclose(kept[0, 1], 0.9)
    np.testing.assert_allclose(kept[0, 2:], [0, 0, 10, 10])
    np.testing.assert_allclose(kept[1, 2:], [20, 20, 30, 30])


def test_anchor_generator_grid():
    x = np.zeros((1, 8, 2, 3), np.float32)
    out = _run("anchor_generator", {"Input": x},
               {"anchor_sizes": [32.0], "aspect_ratios": [1.0],
                "stride": [16.0, 16.0], "offset": 0.5})
    a = np.asarray(out["Anchors"])
    assert a.shape == (2, 3, 1, 4)
    # cell (0,0): center (8, 8), size 32 -> [-8, -8, 24, 24]
    np.testing.assert_allclose(a[0, 0, 0], [-8, -8, 24, 24], atol=1e-5)
    # one stride right
    np.testing.assert_allclose(a[0, 1, 0], [8, -8, 40, 24], atol=1e-5)
    assert np.asarray(out["Variances"]).shape == (2, 3, 1, 4)


def test_bipartite_match_greedy():
    d = np.array([[0.9, 0.2, 0.1],
                  [0.8, 0.7, 0.3]], np.float32)  # 2 rows, 3 cols
    out = _run("bipartite_match", {"DistMat": d}, {})
    idx = np.asarray(out["ColToRowMatchIndices"])[0]
    dist = np.asarray(out["ColToRowMatchDist"])[0]
    # greedy: (0,0)=0.9 binds row0/col0; then (1,1)=0.7
    assert idx.tolist() == [0, 1, -1]
    np.testing.assert_allclose(dist[:2], [0.9, 0.7])
    out2 = _run("bipartite_match", {"DistMat": d},
                {"match_type": "per_prediction", "dist_threshold": 0.25})
    idx2 = np.asarray(out2["ColToRowMatchIndices"])[0]
    assert idx2.tolist() == [0, 1, 1]  # col2 filled by best row >= thr


def test_generate_proposals_shapes_and_clip():
    rng = np.random.RandomState(0)
    N, A, H, W = 1, 3, 4, 4
    scores = rng.rand(N, A, H, W).astype(np.float32)
    deltas = (rng.rand(N, A * 4, H, W).astype(np.float32) - 0.5) * 0.2
    im_info = np.array([[64.0, 64.0, 1.0]], np.float32)
    anchors = _run("anchor_generator",
                   {"Input": np.zeros((N, 1, H, W), np.float32)},
                   {"anchor_sizes": [16.0, 32.0, 48.0],
                    "aspect_ratios": [1.0], "stride": [16.0, 16.0]})
    out = _run("generate_proposals",
               {"Scores": scores, "BboxDeltas": deltas, "ImInfo": im_info,
                "Anchors": np.asarray(anchors["Anchors"]),
                "Variances": np.asarray(anchors["Variances"])},
               {"pre_nms_topN": 32, "post_nms_topN": 8,
                "nms_thresh": 0.7, "min_size": 1.0})
    rois = np.asarray(out["RpnRois"])
    n = int(out["RpnRoisNum"][0])
    assert rois.shape == (1, 8, 4)
    assert 0 < n <= 8
    live = rois[0, :n]
    assert (live >= 0).all() and (live <= 63).all()
    assert (live[:, 2] >= live[:, 0]).all()


def test_yolov3_loss_prefers_matching_predictions():
    rng = np.random.RandomState(0)
    N, C, H, W = 1, 2, 4, 4
    anchors = [10, 14, 23, 27, 37, 58]
    mask = [0, 1, 2]
    A = 3
    gt = np.zeros((N, 2, 4), np.float32)
    gt[0, 0] = [0.4, 0.4, 0.2, 0.2]  # one valid box
    lbl = np.zeros((N, 2), np.int64)
    x_rand = rng.randn(N, A * (5 + C), H, W).astype(np.float32)
    out_r = _run("yolov3_loss", {"X": x_rand, "GTBox": gt, "GTLabel": lbl,
                                 "GTScore": None},
                 {"anchors": anchors, "anchor_mask": mask, "class_num": C,
                  "ignore_thresh": 0.7, "downsample_ratio": 8})
    l_rand = float(np.asarray(out_r["Loss"])[0])
    assert np.isfinite(l_rand) and l_rand > 0
    # gradient flows to X (auto-vjp)
    import jax
    import jax.numpy as jnp

    def loss_fn(xv):
        return _run("yolov3_loss",
                    {"X": xv, "GTBox": jnp.asarray(gt),
                     "GTLabel": jnp.asarray(lbl), "GTScore": None},
                    {"anchors": anchors, "anchor_mask": mask,
                     "class_num": C, "ignore_thresh": 0.7,
                     "downsample_ratio": 8})["Loss"].sum()

    g = jax.grad(loss_fn)(jnp.asarray(x_rand))
    assert np.isfinite(np.asarray(g)).all() and np.abs(g).sum() > 0
    # a few gradient steps reduce the loss
    xv = jnp.asarray(x_rand)
    for _ in range(25):
        xv = xv - 0.5 * jax.grad(loss_fn)(xv)
    assert float(loss_fn(xv)) < l_rand * 0.5


# ---------------------------------------------------------------------------
# CTC vs brute force
# ---------------------------------------------------------------------------
def _ctc_brute(logits, label, blank=0):
    """Sum prob over all T-length paths collapsing to `label`."""
    T, C = logits.shape
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    total = 0.0
    for path in itertools.product(range(C), repeat=T):
        # collapse: merge repeats then drop blanks
        col, prev = [], -1
        for s in path:
            if s != prev and s != blank:
                col.append(s)
            prev = s
        if col == list(label):
            total += np.prod([p[t, s] for t, s in enumerate(path)])
    return -np.log(total)


def test_warpctc_matches_brute_force():
    rng = np.random.RandomState(0)
    T, C = 4, 3
    logits = rng.randn(1, T, C).astype(np.float32)
    label = np.array([[1, 2]], np.int64)
    out = _run("warpctc", {"Logits": logits, "Label": label,
                           "LogitsLength": np.array([T], np.int64),
                           "LabelLength": np.array([2], np.int64)},
               {"blank": 0})
    ref = _ctc_brute(logits[0], [1, 2])
    np.testing.assert_allclose(float(out["Loss"][0, 0]), ref, rtol=1e-4)


def test_warpctc_variable_lengths_and_grad():
    rng = np.random.RandomState(1)
    B, T, C = 3, 5, 4
    logits = rng.randn(B, T, C).astype(np.float32)
    labels = np.array([[1, 2, 3], [2, 2, 0], [3, 0, 0]], np.int64)
    llen = np.array([3, 2, 1], np.int64)
    tlen = np.array([5, 4, 3], np.int64)
    out = _run("warpctc", {"Logits": logits, "Label": labels,
                           "LogitsLength": tlen, "LabelLength": llen},
               {"blank": 0})
    loss = np.asarray(out["Loss"])
    assert loss.shape == (B, 1) and np.isfinite(loss).all()
    for b in range(B):
        ref = _ctc_brute(logits[b, :tlen[b]], list(labels[b, :llen[b]]))
        np.testing.assert_allclose(loss[b, 0], ref, rtol=1e-4)
    # end-to-end grad through the static layer
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, T, C])
        x.stop_gradient = False
        lab = layers.data("lab", [-1, 3], dtype="int64")
        tl = layers.data("tl", [-1], dtype="int64")
        ll = layers.data("ll", [-1], dtype="int64")
        lv = layers.mean(layers.warpctc(x, lab, input_length=tl,
                                        label_length=ll))
        static.append_backward(lv)
    exe = static.Executor()
    with static.scope_guard(static.Scope()):
        exe.run(startup)
        (g,) = exe.run(main, feed={"x": logits, "lab": labels,
                                   "tl": tlen, "ll": llen},
                       fetch_list=[main._grad_map["x"]])
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_ctc_align():
    x = np.array([[0, 1, 1, 0, 2, 2, 0, 3]], np.int64)
    out = _run("ctc_align", {"Input": x, "InputLength": None}, {"blank": 0})
    o = np.asarray(out["Output"])[0]
    n = int(out["OutputLength"][0, 0])
    assert n == 3
    assert o[:3].tolist() == [1, 2, 3]


# ---------------------------------------------------------------------------
# CRF vs brute force
# ---------------------------------------------------------------------------
def _crf_brute(emis, trans_full, T):
    C = emis.shape[-1]
    start, end, trans = trans_full[0], trans_full[1], trans_full[2:]
    scores = {}
    for path in itertools.product(range(C), repeat=T):
        s = start[path[0]] + emis[0, path[0]]
        for t in range(1, T):
            s += trans[path[t - 1], path[t]] + emis[t, path[t]]
        s += end[path[-1]]
        scores[path] = s
    arr = np.array(list(scores.values()))
    logz = np.log(np.exp(arr - arr.max()).sum()) + arr.max()
    best = max(scores, key=scores.get)
    return logz, scores, best


def test_linear_chain_crf_matches_brute_force():
    rng = np.random.RandomState(0)
    B, T, C = 2, 3, 3
    emis = rng.randn(B, T, C).astype(np.float32)
    trans = rng.randn(C + 2, C).astype(np.float32)
    label = np.array([[0, 1, 2], [2, 2, 0]], np.int64)
    length = np.array([3, 2], np.int64)
    out = _run("linear_chain_crf",
               {"Emission": emis, "Transition": trans, "Label": label,
                "Length": length}, {})
    nll = np.asarray(out["LogLikelihood"])
    for b in range(B):
        Tb = length[b]
        logz, scores, _ = _crf_brute(emis[b], trans, Tb)
        gold = scores[tuple(label[b, :Tb])]
        np.testing.assert_allclose(nll[b, 0], logz - gold, rtol=1e-4)


def test_crf_decoding_viterbi():
    rng = np.random.RandomState(1)
    T, C = 4, 3
    emis = rng.randn(1, T, C).astype(np.float32)
    trans = rng.randn(C + 2, C).astype(np.float32)
    out = _run("crf_decoding", {"Emission": emis, "Transition": trans,
                                "Label": None, "Length": None}, {})
    path = np.asarray(out["ViterbiPath"])[0]
    _, _, best = _crf_brute(emis[0], trans, T)
    assert path.tolist() == list(best)


def test_crf_layers_end_to_end():
    """linear_chain_crf + crf_decoding as layers: NLL decreases, decode
    recovers the training labels on a fixed batch."""
    rng = np.random.RandomState(0)
    B, T, C = 4, 5, 3
    emis = rng.randn(B, T, C).astype(np.float32)
    label = emis.argmax(-1).astype(np.int64)  # learnable
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, T, C])
        y = layers.data("y", [-1, T], dtype="int64")
        nll = layers.linear_chain_crf(
            x, y, param_attr=static.ParamAttr(name="crf_T"))
        loss = layers.mean(nll)
        static.SGD(learning_rate=0.2).minimize(loss)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        l0 = None
        for _ in range(60):
            (lv,) = exe.run(main, feed={"x": emis, "y": label},
                            fetch_list=[loss])
            l0 = float(lv) if l0 is None else l0
        assert float(lv) < l0
        # decode with the learned transition
        dec_main = static.Program()
        with static.program_guard(dec_main, static.Program()):
            x2 = layers.data("x", [-1, T, C])
            path = layers.crf_decoding(
                x2, param_attr=static.ParamAttr(name="crf_T"))
        (p,) = exe.run(dec_main, feed={"x": emis}, fetch_list=[path])
    assert (np.asarray(p) == label).mean() > 0.6


# ---------------------------------------------------------------------------
# beam search / decode / py_func
# ---------------------------------------------------------------------------
def test_beam_search_step_and_gather_tree():
    B, W, V = 1, 2, 4
    pre_ids = np.array([[2], [3]], np.int64)           # no end yet
    pre_scores = np.array([[-0.5], [-1.0]], np.float32)
    step_logp = np.log(np.array(
        [[0.1, 0.1, 0.6, 0.2], [0.25, 0.25, 0.25, 0.25]], np.float32))
    out = _run("beam_search",
               {"pre_ids": pre_ids, "pre_scores": pre_scores,
                "scores": step_logp, "ids": None},
               {"beam_size": W, "end_id": 0})
    ids = np.asarray(out["selected_ids"]).ravel()
    par = np.asarray(out["parent_idx"]).ravel()
    sc = np.asarray(out["selected_scores"]).ravel()
    # best: beam0 token2 (-0.5+log0.6); second: beam0 token3 or beam1 ...
    assert ids[0] == 2 and par[0] == 0
    np.testing.assert_allclose(sc[0], -0.5 + np.log(0.6), rtol=1e-5)
    assert sc[0] >= sc[1]

    # gather_tree: [T, B, W]
    step_ids = np.array([[[5, 6]], [[7, 8]]], np.int64)
    parents = np.array([[[0, 0]], [[1, 0]]], np.int64)
    gt = _run("gather_tree", {"Ids": step_ids, "Parents": parents}, {})
    o = np.asarray(gt["Out"])
    # beam0 at t=1 came from parent 1 -> path [6, 7]; beam1 from 0 -> [5, 8]
    assert o[:, 0, 0].tolist() == [6, 7]
    assert o[:, 0, 1].tolist() == [5, 8]


def test_py_func_forward_and_backward():
    def forward(a):
        return a * 2.0

    def backward(a, dy):
        return dy * 2.0

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 3])
        x.stop_gradient = False
        out = main.global_block().create_var(name="pyout", shape=[-1, 3],
                                             dtype="float32")
        layers.py_func(forward, x, out, backward_func=backward,
                       skip_vars_in_backward_input=[out])
        loss = layers.mean(out)
        static.append_backward(loss)
    exe = static.Executor()
    with static.scope_guard(static.Scope()):
        exe.run(startup)
        xv = np.arange(6, dtype=np.float32).reshape(2, 3)
        o, g = exe.run(main, feed={"x": xv},
                       fetch_list=["pyout", main._grad_map["x"]])
    np.testing.assert_allclose(o, xv * 2)
    np.testing.assert_allclose(g, np.full((2, 3), 2.0 / 6))


def test_multiclass_nms_index_output():
    boxes = np.array([[[0, 0, 10, 10], [20, 20, 30, 30]]], np.float32)
    scores = np.zeros((1, 2, 2), np.float32)
    scores[0, 1] = [0.3, 0.9]
    out = _run("multiclass_nms", {"BBoxes": boxes, "Scores": scores},
               {"score_threshold": 0.05, "nms_threshold": 0.5,
                "nms_top_k": 2, "keep_top_k": 2, "background_label": 0})
    idx = np.asarray(out["Index"])[0, :, 0]
    # best detection is input box row 1, second is row 0
    assert idx.tolist() == [1, 0]


def test_beam_search_decode_trims_after_first_end():
    # one beam emits [5, END, 7]: token after the first END must be erased
    ids = np.array([[[5]], [[0]], [[7]]], np.int64)
    parents = np.zeros((3, 1, 1), np.int64)
    out = _run("beam_search_decode",
               {"Ids": ids, "ParentIdx": parents,
                "Scores": np.zeros((3, 1, 1), np.float32),
                "SequenceLength": None},
               {"end_id": 0})
    seq = np.asarray(out["SentenceIds"])[:, 0, 0]
    assert seq.tolist() == [5, 0, 0]


def test_py_func_backward_receives_outputs_and_skips():
    seen = {}

    def forward(a):
        return a * a

    def backward(a, y, dy):   # gets input a AND output y
        seen["shapes"] = (a.shape, y.shape, dy.shape)
        return dy * 2.0 * a

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 2])
        x.stop_gradient = False
        out = main.global_block().create_var(name="sq", shape=[-1, 2],
                                             dtype="float32")
        layers.py_func(forward, x, out, backward_func=backward)
        loss = layers.reduce_sum(out)
        static.append_backward(loss)
    exe = static.Executor()
    with static.scope_guard(static.Scope()):
        exe.run(startup)
        xv = np.array([[1.0, 2.0]], np.float32)
        (g,) = exe.run(main, feed={"x": xv},
                       fetch_list=[main._grad_map["x"]])
    np.testing.assert_allclose(g, 2 * xv)
    assert seen["shapes"] == ((1, 2), (1, 2), (1, 2))


def test_register_py_func_dedups():
    from paddle_tpu.ops.kernels.decode import register_py_func

    def f(a):
        return a

    assert register_py_func(f) == register_py_func(f)


# ---------------------------------------------------------------------------
# round-3 op tail: deformable_conv, chunk_eval, lstmp, density_prior_box
# ---------------------------------------------------------------------------
def _run_prog(main, startup, feed=None, fetch=None):
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        return exe.run(main, feed=feed or {}, fetch_list=fetch or []), scope


def test_deformable_conv_zero_offset_matches_standard_conv():
    # zero offsets + ones mask == ordinary convolution
    N, C, H, W, F, K = 2, 4, 6, 6, 3, 3
    rng = np.random.RandomState(0)
    xv = rng.rand(N, C, H, W).astype(np.float32)
    wv = rng.rand(F, C, K, K).astype(np.float32)
    off = np.zeros((N, 2 * K * K, H, W), np.float32)
    msk = np.ones((N, K * K, H, W), np.float32)

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, C, H, W])
        o = layers.data("o", [-1, 2 * K * K, H, W])
        m = layers.data("m", [-1, K * K, H, W])
        out = layers.deformable_conv(
            x, o, m, F, K, padding=1, bias_attr=False,
            param_attr=static.ParamAttr(
                name="dcw", initializer=static.NumpyArrayInitializer(wv)))
        ref = layers.conv2d(
            x, F, K, padding=1, bias_attr=False,
            param_attr=static.ParamAttr(
                name="rcw", initializer=static.NumpyArrayInitializer(wv)))
    (got, want), _ = _run_prog(main, startup,
                          feed={"x": xv, "o": off, "m": msk},
                          fetch=[out, ref])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_deformable_conv_integer_offset_shifts_sampling():
    # a +1 x-offset on every kernel tap equals convolving the x-shifted
    # image (interior pixels)
    N, C, H, W, K = 1, 2, 8, 8, 3
    rng = np.random.RandomState(1)
    xv = rng.rand(N, C, H, W).astype(np.float32)
    wv = rng.rand(1, C, K, K).astype(np.float32)
    off = np.zeros((N, 2 * K * K, H, W), np.float32)
    off[:, 1::2] = 1.0  # x offsets (odd channels) = +1
    msk = np.ones((N, K * K, H, W), np.float32)
    x_shift = np.zeros_like(xv)
    x_shift[:, :, :, :-1] = xv[:, :, :, 1:]

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, C, H, W])
        o = layers.data("o", [-1, 2 * K * K, H, W])
        m = layers.data("m", [-1, K * K, H, W])
        out = layers.deformable_conv(
            x, o, m, 1, K, padding=1, bias_attr=False,
            param_attr=static.ParamAttr(
                name="dcw2", initializer=static.NumpyArrayInitializer(wv)))
        ref = layers.conv2d(
            x, 1, K, padding=1, bias_attr=False,
            param_attr=static.ParamAttr(
                name="rcw2", initializer=static.NumpyArrayInitializer(wv)))
    (got,), _ = _run_prog(main, startup,
                     feed={"x": xv, "o": off, "m": msk}, fetch=[out])
    (want,), _ = _run_prog(main, startup,
                      feed={"x": x_shift, "o": np.zeros_like(off),
                            "m": msk}, fetch=[ref])
    # interior only: the shifted-image trick differs at the right border
    np.testing.assert_allclose(np.asarray(got)[:, :, 1:-1, 1:-2],
                               np.asarray(want)[:, :, 1:-1, 1:-2],
                               rtol=1e-4, atol=1e-4)


def test_deformable_conv_grads_flow_to_offsets():
    N, C, H, W, K = 1, 2, 5, 5, 3
    rng = np.random.RandomState(2)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, C, H, W])
        o = layers.data("o", [-1, 2 * K * K, H, W])
        o.stop_gradient = False
        m = layers.data("m", [-1, K * K, H, W])
        out = layers.deformable_conv(x, o, m, 2, K, padding=1,
                                     bias_attr=False)
        loss = layers.reduce_sum(out)
        grads = static.gradients([loss], [o])
    (g,), _ = _run_prog(main, startup,
                   feed={"x": rng.rand(N, C, H, W).astype(np.float32),
                         "o": 0.3 * rng.rand(N, 2 * K * K, H, W)
                         .astype(np.float32),
                         "m": np.ones((N, K * K, H, W), np.float32)},
                   fetch=[grads[0]])
    g = np.asarray(g)
    assert g.shape == (N, 2 * K * K, H, W)
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_chunk_eval_iob_counts():
    # IOB with 2 chunk types: tags B-0=0, I-0=1, B-1=2, I-1=3, O=4
    lab = np.array([[0, 1, 4, 2, 3, 4]], np.int64)       # chunks: (0,1,t0),(3,4,t1)
    inf = np.array([[0, 1, 4, 2, 4, 4]], np.int64)       # chunks: (0,1,t0),(3,3,t1)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        i = layers.data("i", [-1, 6], dtype="int64")
        l = layers.data("l", [-1, 6], dtype="int64")
        p, r, f1, ni, nl, nc = layers.chunk_eval(
            i, l, chunk_scheme="IOB", num_chunk_types=2)
    out, _ = _run_prog(main, startup, feed={"i": inf, "l": lab},
                  fetch=[p, r, f1, ni, nl, nc])
    p, r, f1, ni, nl, nc = [np.asarray(v) for v in out]
    assert int(ni) == 2 and int(nl) == 2 and int(nc) == 1
    assert p == pytest.approx(0.5) and r == pytest.approx(0.5)
    assert f1 == pytest.approx(0.5)


def test_chunk_eval_seq_length_masks_padding():
    lab = np.array([[0, 1, 4, 0, 0, 0]], np.int64)
    inf = np.array([[0, 1, 4, 0, 0, 0]], np.int64)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        i = layers.data("i", [-1, 6], dtype="int64")
        l = layers.data("l", [-1, 6], dtype="int64")
        sl = layers.data("sl", [-1], dtype="int64")
        outs = layers.chunk_eval(i, l, chunk_scheme="IOB",
                                 num_chunk_types=2, seq_length=sl)
    out, _ = _run_prog(main, startup,
                  feed={"i": inf, "l": lab,
                        "sl": np.array([3], np.int64)},
                  fetch=[outs[3], outs[4], outs[5]])
    ni, nl, nc = [int(np.asarray(v)) for v in out]
    assert ni == nl == nc == 1  # padding tags (B-0 runs) not counted


def test_lstmp_matches_numpy():
    B, T, D, P = 2, 4, 5, 3
    rng = np.random.RandomState(0)
    xv = rng.rand(B, T, 4 * D).astype(np.float32)
    wv = rng.rand(P, 4 * D).astype(np.float32) * 0.3
    pwv = rng.rand(D, P).astype(np.float32) * 0.3

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, T, 4 * D])
        proj, cell = layers.dynamic_lstmp(
            x, 4 * D, P, bias_attr=False, use_peepholes=False,
            param_attr=static.ParamAttr(
                name="lw", initializer=static.NumpyArrayInitializer(wv)),
            proj_param_attr=static.ParamAttr(
                name="lw_proj",
                initializer=static.NumpyArrayInitializer(pwv)))
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        got_p, got_c = exe.run(main, feed={"x": xv}, fetch_list=[proj, cell])

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    r = np.zeros((B, P), np.float32)
    c = np.zeros((B, D), np.float32)
    ps, cs = [], []
    for t in range(T):
        gates = xv[:, t] + r @ wv
        i, f, cand, o = np.split(gates, 4, axis=-1)
        c = sig(f) * c + sig(i) * np.tanh(cand)
        h = sig(o) * np.tanh(c)
        r = np.tanh(h @ pwv)
        ps.append(r.copy())
        cs.append(c.copy())
    np.testing.assert_allclose(np.asarray(got_p),
                               np.stack(ps, 1), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_c),
                               np.stack(cs, 1), rtol=1e-4, atol=1e-5)


def test_density_prior_box_matches_numpy():
    N, C, H, W = 1, 3, 2, 2
    IH, IW = 16, 16
    densities = [2]
    fixed_sizes = [4.0]
    fixed_ratios = [1.0]
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        feat = layers.data("feat", [-1, C, H, W])
        img = layers.data("img", [-1, 3, IH, IW])
        boxes, vars_ = layers.density_prior_box(
            feat, img, densities=densities, fixed_sizes=fixed_sizes,
            fixed_ratios=fixed_ratios, clip=True)
    (b, v), _ = _run_prog(main, startup,
                     feed={"feat": np.zeros((N, C, H, W), np.float32),
                           "img": np.zeros((N, 3, IH, IW), np.float32)},
                     fetch=[boxes, vars_])
    b, v = np.asarray(b), np.asarray(v)
    assert b.shape == (H, W, 4, 4)  # 1 size * 1 ratio * 2^2 density
    assert v.shape == b.shape
    np.testing.assert_allclose(v[0, 0, 0], [0.1, 0.1, 0.2, 0.2])
    # independent numpy replica of the reference loop (density_prior_box_op.h)
    sw, sh = IW / W, IH / H
    step_avg = int((sw + sh) * 0.5)
    shift = int(step_avg / densities[0])
    exp = np.zeros((H, W, 4, 4), np.float32)
    for h in range(H):
        for w in range(W):
            cx, cy = (w + 0.5) * sw, (h + 0.5) * sh
            idx = 0
            bw = fixed_sizes[0] * np.sqrt(fixed_ratios[0])
            bh = fixed_sizes[0] / np.sqrt(fixed_ratios[0])
            dcx = cx - step_avg / 2.0 + shift / 2.0
            dcy = cy - step_avg / 2.0 + shift / 2.0
            for di in range(2):
                for dj in range(2):
                    xx, yy = dcx + dj * shift, dcy + di * shift
                    exp[h, w, idx] = [
                        max((xx - bw / 2) / IW, 0),
                        max((yy - bh / 2) / IH, 0),
                        min((xx + bw / 2) / IW, 1),
                        min((yy + bh / 2) / IH, 1)]
                    idx += 1
    np.testing.assert_allclose(b, np.clip(exp, 0, 1), rtol=1e-5)


def test_lstmp_peepholes_match_numpy():
    """ADVICE r3: use_peepholes=True (the reference default) — bias
    widens to [1, 7*hidden] with W_ic/W_if/W_oc diagonals."""
    B, T, D, P = 2, 3, 4, 3
    rng = np.random.RandomState(1)
    xv = rng.rand(B, T, 4 * D).astype(np.float32)
    wv = rng.rand(P, 4 * D).astype(np.float32) * 0.3
    pwv = rng.rand(D, P).astype(np.float32) * 0.3
    bv = rng.rand(1, 7 * D).astype(np.float32) * 0.2

    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, T, 4 * D])
        proj, cell = layers.dynamic_lstmp(
            x, 4 * D, P, use_peepholes=True,
            param_attr=static.ParamAttr(
                name="plw", initializer=static.NumpyArrayInitializer(wv)),
            bias_attr=static.ParamAttr(
                name="plb", initializer=static.NumpyArrayInitializer(bv)),
            proj_param_attr=static.ParamAttr(
                name="plw_proj",
                initializer=static.NumpyArrayInitializer(pwv)))
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        got_p, got_c = exe.run(main, feed={"x": xv},
                               fetch_list=[proj, cell])

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    gate_b = bv[0, :4 * D]
    w_ic, w_if, w_oc = (bv[0, 4 * D:5 * D], bv[0, 5 * D:6 * D],
                        bv[0, 6 * D:7 * D])
    r = np.zeros((B, P), np.float32)
    c = np.zeros((B, D), np.float32)
    ps, cs = [], []
    for t in range(T):
        gates = xv[:, t] + r @ wv + gate_b
        i, f, cand, o = np.split(gates, 4, axis=-1)
        i = i + w_ic * c
        f = f + w_if * c
        c = sig(f) * c + sig(i) * np.tanh(cand)
        o = o + w_oc * c
        h = sig(o) * np.tanh(c)
        r = np.tanh(h @ pwv)
        ps.append(r.copy())
        cs.append(c.copy())
    np.testing.assert_allclose(np.asarray(got_p), np.stack(ps, 1),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_c), np.stack(cs, 1),
                               rtol=1e-4, atol=1e-5)


def test_while_strict_truncation_aborts():
    """ADVICE r3: strict_truncation surfaces a runtime error instead of
    silently training on a truncated loop state."""
    from paddle_tpu.static.control_flow import while_loop
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        i = layers.fill_constant([1], "float32", 0.0)
        n = layers.fill_constant([1], "float32", 100.0)
        (i_out,) = while_loop(
            lambda i: layers.less_than(i, n),
            lambda i: (layers.elementwise_add(
                i, layers.fill_constant([1], "float32", 1.0)),),
            [i], max_iters=3, strict_truncation=True)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(Exception, match="truncated"):
            out = exe.run(main, fetch_list=[i_out])
            np.asarray(out[0])


def test_while_strict_truncation_differentiable():
    """Review r4: the strict host check must not break the bounded
    while's reverse-mode path (io_callback is custom_vjp-shielded)."""
    from paddle_tpu.static.control_flow import while_loop
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 2])
        i = layers.fill_constant([1], "float32", 0.0)
        n = layers.fill_constant([1], "float32", 3.0)
        s = layers.reshape(layers.reduce_sum(x), [1])
        i_out, s_out = while_loop(
            lambda i, s: layers.less_than(i, n),
            lambda i, s: (layers.elementwise_add(
                i, layers.fill_constant([1], "float32", 1.0)),
                layers.elementwise_mul(
                    s, layers.fill_constant([1], "float32", 2.0))),
            [i, s], max_iters=8, strict_truncation=True)
        loss = layers.mean(s_out)
        static.SGD(0.1).minimize(loss)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        (lv,) = exe.run(main, feed={"x": np.ones((2, 2), np.float32)},
                        fetch_list=[loss])
    # 3 doublings of sum(x)=4 -> 32; loop NOT truncated so no abort, and
    # backward compiled fine through the shielded check
    np.testing.assert_allclose(float(np.asarray(lv)), 32.0, rtol=1e-5)

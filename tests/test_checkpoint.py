"""paddle_tpu.checkpoint — async atomic checkpointing + auto-resume.

The contracts docs/checkpoint.md promises:
  * kill/resume equivalence — straight-through training and
    train-k/crash/resume/train-rest produce BITWISE-identical params and
    optimizer state;
  * a truncated or checksum-corrupt checkpoint is never loaded — load()
    warns and falls back to the previous valid step;
  * retention keeps last-N ∪ every-M;
  * async saves are bounded in flight and drain on wait()/close();
  * bf16 state round-trips bit-exactly (TPU checkpoints are mostly bf16);
  * hapi Model.fit(resume=True) continues from the saved epoch;
  * SIGTERM/SIGINT handlers write a final synchronous checkpoint.
"""
import os
import signal
import warnings

import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers
from paddle_tpu.core.program import _reset_unique_names
from paddle_tpu.checkpoint import (
    CheckpointManager, CheckpointError, atomic_write,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build():
    """Identical program on every call (fresh name counters, as a process
    restart would have)."""
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss)
    return main, startup, loss


def _feeds(n):
    rng = np.random.RandomState(0)
    return [{"x": rng.rand(4, 8).astype(np.float32),
             "y": rng.rand(4, 1).astype(np.float32)} for _ in range(n)]


def _persistables(program, scope):
    from paddle_tpu.static.executor import _persistable_names
    return {n: np.asarray(scope.get(n))
            for n in _persistable_names(program)
            if scope.get(n) is not None}


def test_kill_resume_bitwise_equivalence(tmp_path):
    """Train 6 straight vs train 3 / 'crash' / auto-resume / train 3 →
    params AND optimizer accumulators bitwise-identical."""
    n, k = 6, 3
    feeds = _feeds(n)

    main, startup, loss = _build()
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        for f in feeds:
            exe.run(main, feed=f, fetch_list=[loss])
        ref = _persistables(main, scope)

    root = str(tmp_path / "ckpts")
    main2, startup2, loss2 = _build()
    assert main2.fingerprint() == main.fingerprint()
    exe2 = static.Executor()
    scope2 = static.Scope()
    mgr = CheckpointManager(root)
    with static.scope_guard(scope2):
        exe2.run(startup2)
        exe2.enable_checkpointing(mgr, program=main2, every_n_steps=k,
                                  scope=scope2)
        for f in feeds[:k]:
            exe2.run(main2, feed=f, fetch_list=[loss2])
    mgr.close()  # drains the async save

    # crash: everything rebuilt from scratch, only the dir survives
    main3, startup3, loss3 = _build()
    exe3 = static.Executor()
    scope3 = static.Scope()
    mgr2 = CheckpointManager(root)
    with static.scope_guard(scope3):
        exe3.run(startup3)
        resumed = exe3.restore_from_checkpoint(mgr2, program=main3,
                                               scope=scope3)
        assert resumed is not None
        for f in feeds[k:]:
            exe3.run(main3, feed=f, fetch_list=[loss3])
        got = _persistables(main3, scope3)
    mgr2.close()

    assert set(ref) == set(got)
    for name in sorted(ref):
        assert ref[name].dtype == got[name].dtype, name
        np.testing.assert_array_equal(ref[name], got[name], err_msg=name)


def test_corrupt_checkpoint_never_loads(tmp_path):
    """Truncation → latest_step() skips; bit-flip → CRC refusal; load()
    falls back with a RuntimeWarning; explicit load(step=) raises."""
    mgr = CheckpointManager(str(tmp_path), keep_last_n=10)
    for s in (1, 2, 3):
        mgr.save(s, {"w": np.full((8, 8), s, np.float32)}, sync=True)
    assert mgr.latest_step() == 3

    shard3 = os.path.join(mgr.step_dir(3), "shard_00000.bin")
    with open(shard3, "r+b") as f:
        f.truncate(os.path.getsize(shard3) // 2)
    assert mgr.latest_step() == 2  # truncated step skipped

    shard2 = os.path.join(mgr.step_dir(2), "shard_00000.bin")
    with open(shard2, "r+b") as f:
        f.seek(3)
        b = f.read(1)
        f.seek(3)
        f.write(bytes([b[0] ^ 0xFF]))

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        ckpt = mgr.load()
    assert ckpt.step == 1
    assert ckpt.state["w"][0, 0] == 1
    assert sum(isinstance(w.message, RuntimeWarning)
               for w in caught) >= 2  # one per refused checkpoint

    with pytest.raises(CheckpointError):
        mgr.load(step=3)
    with pytest.raises(CheckpointError):
        mgr.load(step=2)
    mgr.close()


def test_retention_keep_last_n_and_every_m(tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep_last_n=2,
                            keep_every_m_steps=4)
    for s in range(1, 9):
        mgr.save(s, {"w": np.zeros(3, np.float32)}, sync=True)
    # last 2 = {7, 8}; every 4 = {4, 8}
    assert mgr.all_steps() == [4, 7, 8]
    mgr.close()


def test_async_saves_drain_and_record_metrics(tmp_path):
    from paddle_tpu.core.monitor import gauge_get, hist_snapshot, stat_get
    before = stat_get("checkpoint.saves")
    mgr = CheckpointManager(str(tmp_path), keep_last_n=10, max_in_flight=1)
    for s in range(5):
        mgr.save(s, {"w": np.full((16, 16), s, np.float32)})
    mgr.wait()  # all five persisted despite a budget of 1 in flight
    assert mgr.all_steps() == [0, 1, 2, 3, 4]
    assert stat_get("checkpoint.saves") - before == 5
    assert stat_get("checkpoint.bytes_written") > 0
    assert gauge_get("checkpoint.last_saved_step") == 4
    assert hist_snapshot("checkpoint.save_seconds")["count"] >= 5
    mgr.close()


def test_bf16_state_roundtrips_bit_exact(tmp_path):
    import ml_dtypes
    rng = np.random.RandomState(7)
    bf = rng.randn(33, 9).astype(ml_dtypes.bfloat16)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, {"w_bf16": bf, "w_f32": rng.randn(4).astype(np.float32)},
             sync=True)
    ckpt = mgr.load()
    got = ckpt.state["w_bf16"]
    assert got.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(got.view(np.uint16), bf.view(np.uint16))
    assert ckpt.state["w_f32"].dtype == np.float32
    mgr.close()


def test_extra_sidecar_and_rng_roundtrip(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    extra = {"executor_step": 12, "rng": {"seed": 42, "counter": 7},
             "dataset_position": 3}
    mgr.save(12, {"w": np.ones(2, np.float32)}, extra=extra, sync=True)
    ckpt = mgr.load()
    assert ckpt.extra["rng"] == {"seed": 42, "counter": 7}
    assert ckpt.extra["dataset_position"] == 3
    mgr.close()


def test_empty_state_save_warns(tmp_path):
    """A zero-tensor save commits clean (nothing for CRC to catch) yet
    restores nothing — almost always a wrong-scope caller bug, so save()
    must warn."""
    mgr = CheckpointManager(str(tmp_path))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        mgr.save(1, {}, sync=True)
    assert any(isinstance(w.message, RuntimeWarning) and
               "EMPTY" in str(w.message) for w in caught)
    mgr.close()


def test_preemption_save_drains_and_writes_final(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, {"w": np.zeros(4, np.float32)})  # async, in flight
    mgr.set_state_provider(
        lambda: (2, {"w": np.ones(4, np.float32)}, {"final": True}))
    saved = mgr.preemption_save()
    assert saved == 2
    ckpt = mgr.load()
    assert ckpt.step == 2 and ckpt.extra["final"] is True
    assert mgr.all_steps() == [1, 2]  # the async one drained first
    mgr.close()


def test_preemption_handler_installs_and_chains(tmp_path):
    mgr = CheckpointManager(str(tmp_path))
    mgr.set_state_provider(
        lambda: (5, {"w": np.zeros(2, np.float32)}, {}))
    prev = signal.getsignal(signal.SIGINT)
    mgr.install_preemption_handler(signals=(signal.SIGINT,))
    try:
        assert signal.getsignal(signal.SIGINT) == mgr._handle_preemption
        with pytest.raises(KeyboardInterrupt):
            mgr._handle_preemption(signal.SIGINT, None)
        assert mgr.load().step == 5  # final checkpoint landed first
    finally:
        mgr.uninstall_preemption_handler()
    assert signal.getsignal(signal.SIGINT) == prev
    mgr.close()


def test_preemption_handler_double_install_does_not_recurse(tmp_path):
    """A second install must not record the handler as its own
    'previous' disposition — the chain would recurse on signal instead
    of saving and exiting."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.set_state_provider(
        lambda: (3, {"w": np.zeros(2, np.float32)}, {}))
    prev = signal.getsignal(signal.SIGINT)
    mgr.install_preemption_handler(signals=(signal.SIGINT,))
    mgr.install_preemption_handler(signals=(signal.SIGINT,))  # again
    try:
        assert mgr._prev_handlers[signal.SIGINT] == prev  # original kept
        with pytest.raises(KeyboardInterrupt):  # not RecursionError
            mgr._handle_preemption(signal.SIGINT, None)
        assert mgr.load().step == 3
    finally:
        mgr.uninstall_preemption_handler()
    assert signal.getsignal(signal.SIGINT) == prev
    mgr.close()


def test_unseeded_sampler_salt_differs_and_replays():
    """Unseeded processes draw a per-process entropy salt, so
    independent launches shuffle differently — yet the salt rides the
    checkpointed RNG state, so a resumed unseeded run still replays its
    exact shuffle sequence."""
    from paddle_tpu.core.generator import (get_rng_state, process_salt,
                                           seed, set_rng_state)
    from paddle_tpu.io.sampler import RandomSampler
    orig = get_rng_state()
    try:
        # simulate two independent unseeded processes via distinct salts
        set_rng_state({"seed": 0, "counter": 0, "salt": 11111})
        a = list(RandomSampler(list(range(64))))
        set_rng_state({"seed": 0, "counter": 0, "salt": 22222})
        b = list(RandomSampler(list(range(64))))
        assert a != b
        # resume replay: restoring the full state replays the draw
        set_rng_state({"seed": 0, "counter": 0, "salt": 11111})
        assert list(RandomSampler(list(range(64)))) == a
        # explicit seeding pins the salt to 0 (cross-process reproducible)
        seed(5)
        assert process_salt() == 0
    finally:
        set_rng_state(orig)


def test_multihost_stale_pending_pruned(tmp_path):
    """No-barrier multi-host mode: superseded .pending stages are swept
    once a newer recoverable stage exists and they have gone idle past
    the grace window — a multi-day run must not accumulate one model
    copy per save."""
    import time
    root = str(tmp_path)
    m0 = CheckpointManager(root, rank=0, world_size=2)
    m1 = CheckpointManager(root, rank=1, world_size=2)
    for s in (1, 2):
        m0.save(s, {"w": np.full(4, float(s), np.float32)}, sync=True)
        m1.save(s, {"w": np.full(4, float(s), np.float32)}, sync=True)
    p1 = os.path.join(root, ".pending.step_1")
    p2 = os.path.join(root, ".pending.step_2")
    assert os.path.isdir(p1) and os.path.isdir(p2)
    old = time.time() - 7200
    for dirpath, _dirs, files in os.walk(p1):
        os.utime(dirpath, (old, old))
        for f in files:
            os.utime(os.path.join(dirpath, f), (old, old))
    # next save triggers the prune on rank 0
    m0.save(3, {"w": np.zeros(4, np.float32)}, sync=True)
    assert not os.path.exists(p1)  # superseded by complete step 2, idle
    assert os.path.isdir(p2)  # newest recoverable: kept
    assert os.path.isdir(os.path.join(root, ".pending.step_3"))  # newest
    for m in (m0, m1):
        m.close()


def test_stale_dir_recovered_not_deleted(tmp_path):
    """Crash between commit_dir's two renames (re-publish of an existing
    step) leaves the only complete copy under `.stale.*` — a fresh
    manager must recover it back to `step_<N>`, not delete it."""
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(7, {"w": np.full(4, 7.0, np.float32)}, sync=True)
    mgr.close()
    os.rename(os.path.join(str(tmp_path), "step_7"),
              os.path.join(str(tmp_path), ".stale.step_7.123.abcd1234"))
    mgr2 = CheckpointManager(str(tmp_path))
    assert mgr2.latest_step() == 7
    assert mgr2.load().state["w"][0] == 7.0
    mgr2.close()


def test_preemption_save_proceeds_despite_stale_async_error(tmp_path):
    """A stale background-save failure must not abort the final
    synchronous preemption save; the error surfaces later at close()."""
    mgr = CheckpointManager(str(tmp_path))
    mgr._last_error = RuntimeError("simulated earlier async failure")
    mgr.set_state_provider(
        lambda: (9, {"w": np.ones(2, np.float32)}, {}))
    assert mgr.preemption_save() == 9
    assert mgr.load().step == 9
    with pytest.raises(CheckpointError):
        mgr.close()


def test_shuffle_order_replays_after_rng_restore():
    """RandomSampler derives epoch seeds from the global generator, so a
    restored RNG state replays the same shuffle sequence (bitwise resume
    covers batch order, not just dropout)."""
    from paddle_tpu.core.generator import (get_rng_state, seed,
                                           set_rng_state)
    from paddle_tpu.io.sampler import RandomSampler
    seed(123)
    first = list(RandomSampler(list(range(32))))
    snap = get_rng_state()
    second = list(RandomSampler(list(range(32))))
    assert first != second  # epochs still shuffle differently
    set_rng_state(snap)
    assert list(RandomSampler(list(range(32)))) == second


def test_dataloader_shuffle_seeded_and_replayable():
    """Shuffle seeds are drawn on the DataLoader's prefetch thread; the
    global generator is process-wide, so paddle.seed() reaches it,
    epochs still differ, and a restored RNG state replays the same epoch
    order (resume covers loader-thread shuffle, not just dropout)."""
    import paddle_tpu.io as pio
    from paddle_tpu.core.generator import (get_rng_state, seed,
                                           set_rng_state)

    def epoch(dl):
        return [int(v) for b in dl for v in np.asarray(b).ravel()]

    ds = list(range(16))
    seed(7)
    dl = pio.DataLoader(ds, batch_size=4, shuffle=True)
    e1, e2 = epoch(dl), epoch(dl)
    assert sorted(e1) == list(range(16))
    assert e1 != e2  # epochs reshuffle
    snap = get_rng_state()
    e3 = epoch(dl)
    set_rng_state(snap)
    assert epoch(dl) == e3  # restored RNG replays the loader-thread draw
    seed(7)
    assert epoch(dl) == e1  # seeding controls the prefetch-thread shuffle


def test_multihost_pending_recovered_on_restart(tmp_path):
    """world_size > 1 preemption saves can only STAGE (no cross-host
    barrier inside a dying signal handler); the next rank-0 startup must
    COMMIT a fully-staged pending checkpoint — and drop a partial one."""
    root = str(tmp_path)
    m0 = CheckpointManager(root, rank=0, world_size=2)
    m1 = CheckpointManager(root, rank=1, world_size=2)
    m0.save(3, {"w": np.full(4, 0.0, np.float32)}, sync=True)
    m1.save(3, {"w": np.full(4, 1.0, np.float32)}, sync=True)
    # process dies before commit(3) — stage dir survives
    assert os.path.isdir(os.path.join(root, ".pending.step_3"))
    assert CheckpointManager(root, rank=1, world_size=2
                             ).latest_step() is None  # nothing published

    r0 = CheckpointManager(root, rank=0, world_size=2)  # recovery runs
    assert r0.latest_step() == 3
    r1 = CheckpointManager(root, rank=1, world_size=2)
    assert r0.load().state["w"][0] == 0.0  # each rank strictly own shard
    assert r1.load().state["w"][0] == 1.0
    for m in (m0, m1, r0, r1):
        m.close()

    # a stage missing rank 1's shard is dropped, not published
    m0b = CheckpointManager(root, rank=0, world_size=2)
    m0b.save(9, {"w": np.zeros(4, np.float32)}, sync=True)
    m0b.close()
    fresh = CheckpointManager(root, rank=0, world_size=2)
    assert fresh.latest_step() == 3
    assert not os.path.isdir(os.path.join(root, ".pending.step_9"))
    fresh.close()


def test_tmp_stage_sweep_respects_owner_liveness(tmp_path):
    """A .tmp.* stage owned by a LIVE pid (a concurrent manager mid-save
    on this root) must survive another manager's startup sweep — as must
    a fresh dead-looking stage (the pid test is host-local; on a shared
    mount it may be another host's live writer).  Only a dead owner's
    stage idle past the grace window is removed."""
    import subprocess
    import sys
    import time
    root = str(tmp_path)
    live = os.path.join(root, f".tmp.step_5.{os.getpid()}.deadbeef")
    os.makedirs(live)
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait()
    dead = os.path.join(root, f".tmp.step_6.{p.pid}.deadbeef")
    os.makedirs(dead)
    mgr = CheckpointManager(root)
    assert os.path.isdir(live)  # in-progress stage left alone
    assert os.path.isdir(dead)  # fresh: possibly a foreign live writer
    mgr.close()
    old = time.time() - 7200
    os.utime(dead, (old, old))
    mgr2 = CheckpointManager(root)
    assert os.path.isdir(live)
    assert not os.path.exists(dead)  # idle past grace: abandoned, swept
    mgr2.close()


def test_saver_stage_sweep_has_cross_host_grace(tmp_path):
    """The saver's pid-liveness test is host-local: a dead-LOOKING stage
    with fresh mtime may be another host's live writer on a shared mount
    and must be kept; once idle past the grace window it is swept."""
    import subprocess
    import sys
    import time
    from paddle_tpu.incubate.checkpoint.checkpoint_saver import (
        CheckpointSaver, SerializableBase)

    class Obj(SerializableBase):
        def serialize(self, path):
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "d.txt"), "w") as f:
                f.write("x")

    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait()
    root = str(tmp_path)
    os.makedirs(root, exist_ok=True)
    stage = os.path.join(root,
                         f".tmp.__paddle_checkpoint__.0.{p.pid}.abcd1234")
    os.makedirs(stage)
    saver = CheckpointSaver()
    saver.save_checkpoint(root, [Obj()])
    assert os.path.isdir(stage)  # fresh: possibly a foreign live writer
    old = time.time() - 7200
    os.utime(stage, (old, old))
    saver.save_checkpoint(root, [Obj()])
    assert not os.path.exists(stage)  # idle past grace: abandoned


def test_atomic_write_leaves_target_intact_on_error(tmp_path):
    p = str(tmp_path / "artifact.bin")
    with atomic_write(p) as f:
        f.write(b"good")
    with pytest.raises(RuntimeError):
        with atomic_write(p) as f:
            f.write(b"partial garbage")
            raise RuntimeError("crash mid-write")
    with open(p, "rb") as f:
        assert f.read() == b"good"
    assert [n for n in os.listdir(str(tmp_path))
            if n.startswith(".tmp.")] == []


def test_hapi_fit_resume_continues_from_saved_epoch(tmp_path):
    import paddle_tpu as paddle
    from paddle_tpu.io import Dataset

    class DS(Dataset):
        def __init__(self, n=16):
            rng = np.random.RandomState(0)
            self.x = rng.rand(n, 4).astype(np.float32)
            self.y = self.x.sum(1, keepdims=True).astype(np.float32)

        def __len__(self):
            return len(self.x)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

    def make_model():
        _reset_unique_names()
        paddle.seed(0)
        net = paddle.nn.Linear(4, 1)
        model = paddle.Model(net)
        model.prepare(
            optimizer=paddle.optimizer.Adam(learning_rate=0.01,
                                            parameters=net.parameters()),
            loss=paddle.nn.MSELoss())
        return model

    d = str(tmp_path / "run")
    m1 = make_model()
    h1 = m1.fit(DS(), batch_size=4, epochs=2, shuffle=False, verbose=0,
                save_dir=d)
    assert len(h1) == 2
    assert os.path.isdir(os.path.join(d, "checkpoints", "step_1"))

    # relaunch: runs only the remaining 2 epochs, ends bitwise-equal to
    # a 4-epoch straight run
    m2 = make_model()
    h2 = m2.fit(DS(), batch_size=4, epochs=4, shuffle=False, verbose=0,
                save_dir=d, resume=True)
    assert len(h2) == 2

    m3 = make_model()
    m3.fit(DS(), batch_size=4, epochs=4, shuffle=False, verbose=0)
    a = {k: np.asarray(v.numpy()) for k, v in
         m2.network.state_dict().items()}
    b = {k: np.asarray(v.numpy()) for k, v in
         m3.network.state_dict().items()}
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)

    with pytest.raises(ValueError):
        make_model().fit(DS(), epochs=1, resume=True)  # needs save_dir

    # a NON-resuming fit into the same save_dir must not inherit the old
    # run's higher-numbered checkpoints: retention GC would delete the
    # fresh run's commits the moment they land, and a later resume=True
    # would restore the stale state
    m5 = make_model()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        m5.fit(DS(), batch_size=4, epochs=1, shuffle=False, verbose=0,
               save_dir=d, resume=False)
    assert any("stale checkpoints" in str(w.message) for w in caught)
    from paddle_tpu.checkpoint import CheckpointManager as _CM
    fresh = _CM(os.path.join(d, "checkpoints"))
    assert fresh.all_steps() == [0]  # only the new run's epoch-0 commit
    fresh.close()


def test_incubate_saver_atomic_commit_and_fallback(tmp_path):
    from paddle_tpu.incubate.checkpoint.checkpoint_saver import (
        CheckpointSaver, SerializableBase)

    class Obj(SerializableBase):
        def __init__(self, payload=""):
            self.payload = payload

        def serialize(self, path):
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "data.txt"), "w") as f:
                f.write(self.payload)

        def deserialize(self, path):
            with open(os.path.join(path, "data.txt")) as f:
                self.payload = f.read()

    root = str(tmp_path / "saver")
    saver = CheckpointSaver()
    for i in range(3):
        no = saver.save_checkpoint(root, [Obj(f"v{i}")], max_keep=5)
        assert no == i
    # no staging dirs left behind, meta present in each commit
    assert all(n.startswith("__paddle_checkpoint__.")
               for n in os.listdir(root))
    # corrupt the newest checkpoint's payload in place
    with open(os.path.join(root, "__paddle_checkpoint__.2", "obj_0",
                           "data.txt"), "w") as f:
        f.write("CORRUPTED")
    obj = Obj()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        no = saver.load_checkpoint(root, [obj])
    assert no == 1 and obj.payload == "v1"
    assert any(isinstance(w.message, RuntimeWarning) for w in caught)


def test_executor_hook_fires_through_compiled_program(tmp_path):
    """Registering the raw Program but running it wrapped in
    CompiledProgram (the multi-chip path) must still checkpoint — the
    hook compares underlying Programs, not wrapper identity."""
    from paddle_tpu.distributed.compiled_program import CompiledProgram
    main, startup, loss = _build()
    exe = static.Executor()
    scope = static.Scope()
    mgr = CheckpointManager(str(tmp_path), keep_last_n=10)
    cp = CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    rng = np.random.RandomState(0)
    feeds = [{"x": rng.rand(8, 8).astype(np.float32),
              "y": rng.rand(8, 1).astype(np.float32)}  # 8 = dp mesh size
             for _ in range(4)]
    with static.scope_guard(scope):
        exe.run(startup)
        exe.enable_checkpointing(mgr, program=main, every_n_steps=2,
                                 scope=scope)
        for f in feeds:
            exe.run(cp, feed=f, fetch_list=[loss])
    mgr.wait()
    assert len(mgr.all_steps()) >= 2
    mgr.close()


def test_executor_hook_fires_through_parallel_executor(tmp_path):
    """ParallelExecutor wraps a CompiledProgram which wraps the Program —
    the hook must unwrap BOTH levels: with a registered Program it still
    checkpoints, and with program=None the snapshot reaches the real
    Program instead of crashing on the wrapper."""
    main, startup, loss = _build()
    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(0)
    feeds = [{"x": rng.rand(8, 8).astype(np.float32),
              "y": rng.rand(8, 1).astype(np.float32)}  # 8 = dp mesh size
             for _ in range(4)]
    with static.scope_guard(scope):
        exe.run(startup)
        pe = static.ParallelExecutor(use_cuda=False, loss_name=loss.name,
                                     main_program=main)
        mgr = CheckpointManager(str(tmp_path / "registered"),
                                keep_last_n=10)
        exe.enable_checkpointing(mgr, program=main, every_n_steps=2,
                                 scope=scope)
        for f in feeds:
            exe.run(pe, feed=f, fetch_list=[loss])
        mgr.wait()
        assert len(mgr.all_steps()) >= 2
        mgr.close()

        mgr2 = CheckpointManager(str(tmp_path / "default"), keep_last_n=10)
        exe.enable_checkpointing(mgr2, every_n_steps=2, scope=scope)
        for f in feeds:
            exe.run(pe, feed=f, fetch_list=[loss])
        mgr2.wait()
        assert len(mgr2.all_steps()) >= 2
        mgr2.close()


def test_default_program_latches_on_training_program(tmp_path):
    """enable_checkpointing(program=None) must bind to the first TRAINING
    program (grad/optimizer ops) run afterwards — startup and eval
    programs run through the same executor, before OR after, must
    neither latch (which would silently disable checkpointing of the
    real train loop) nor commit a checkpoint missing the optimizer
    accumulators."""
    main, startup, loss = _build()
    eval_p, eval_start = static.Program(), static.Program()
    with static.program_guard(eval_p, eval_start):
        x = layers.data("x", [-1, 8])
        layers.fc(x, 1)
    exe = static.Executor()
    scope = static.Scope()
    mgr = CheckpointManager(str(tmp_path), keep_last_n=10)
    feeds = _feeds(6)
    with static.scope_guard(scope):
        # enable FIRST: the startup and eval runs below must not latch
        exe.enable_checkpointing(mgr, every_n_steps=2, scope=scope)
        exe.run(startup)
        exe.run(eval_start)
        exe.run(eval_p, feed={"x": feeds[0]["x"]})
        n_train_tensors = None
        for f in feeds:
            exe.run(eval_p, feed={"x": f["x"]})  # must NOT checkpoint
            exe.run(main, feed=f, fetch_list=[loss])
    mgr.wait()
    steps = mgr.all_steps()
    assert len(steps) >= 2
    for s in steps:
        state = mgr.load(step=s).state
        if n_train_tensors is None:
            n_train_tensors = len(state)
        # every checkpoint carries the train program's full persistable
        # set (params + Adam moments + LR), never the eval program's two
        assert len(state) == n_train_tensors and len(state) > 4, (
            s, sorted(state))
    mgr.close()


def test_preemption_provider_uses_run_scope(tmp_path):
    """enable_checkpointing without scope= while every run passes an
    explicit scope: the preemption save must snapshot the scope training
    runs in, not the (empty) global scope — an empty final checkpoint
    would become the newest step and resume would restore nothing."""
    main, startup, loss = _build()
    exe = static.Executor()
    scope = static.Scope()
    mgr = CheckpointManager(str(tmp_path), keep_last_n=10)
    exe.enable_checkpointing(mgr, program=main, every_n_steps=10**6)
    exe.run(startup, scope=scope)
    for f in _feeds(2):
        exe.run(main, feed=f, fetch_list=[loss], scope=scope)
    saved = mgr.preemption_save()
    assert saved == exe._step
    state = mgr.load().state
    assert len(state) > 4, sorted(state)  # params + Adam moments + LR
    mgr.close()


def test_disable_checkpointing_detaches_preemption_provider(tmp_path):
    """After disable_checkpointing() a preemption must not commit a
    snapshot of whatever default_main_program() happens to be."""
    main, startup, loss = _build()
    exe = static.Executor()
    scope = static.Scope()
    mgr = CheckpointManager(str(tmp_path))
    with static.scope_guard(scope):
        exe.run(startup)
        exe.enable_checkpointing(mgr, program=main, every_n_steps=10**6)
        exe.run(main, feed=_feeds(1)[0], fetch_list=[loss])
        exe.disable_checkpointing()
    assert mgr.preemption_save() is None
    assert mgr.all_steps() == []
    mgr.close()


def test_restore_warns_on_program_fingerprint_mismatch(tmp_path):
    """Restoring into a program that differs from the one the checkpoint
    was saved from must warn: absent vars keep fresh-init values — a
    chimera state the user should know about."""
    main, startup, loss = _build()
    exe = static.Executor()
    scope = static.Scope()
    mgr = CheckpointManager(str(tmp_path))
    with static.scope_guard(scope):
        exe.run(startup)
        s, state, extra = exe.checkpoint_snapshot(main, scope)
        mgr.save(s, state, extra=extra, sync=True)

    _reset_unique_names()
    other, other_start = static.Program(), static.Program()
    with static.program_guard(other, other_start):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        pred = layers.fc(x, 1)  # different topology
        loss2 = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss2)
    exe2 = static.Executor()
    scope2 = static.Scope()
    with static.scope_guard(scope2):
        exe2.run(other_start)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            exe2.restore_from_checkpoint(mgr, program=other, scope=scope2)
    assert any("fingerprint mismatch" in str(w.message) for w in caught)
    mgr.close()


def test_async_snapshot_copies_mutable_host_arrays(tmp_path):
    """A numpy array handed to save() must be snapshotted by value: an
    in-place mutation racing the background writer may not tear the
    persisted checkpoint."""
    w = np.zeros((64, 64), np.float32)
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, {"w": w})  # async
    w += 1.0  # next "train step" mutates in place immediately
    mgr.wait()
    ckpt = mgr.load()
    np.testing.assert_array_equal(ckpt.state["w"],
                                  np.zeros((64, 64), np.float32))
    mgr.close()


def test_executor_hook_saves_on_step_boundaries(tmp_path):
    main, startup, loss = _build()
    exe = static.Executor()
    scope = static.Scope()
    mgr = CheckpointManager(str(tmp_path), keep_last_n=10)
    with static.scope_guard(scope):
        exe.run(startup)
        exe.enable_checkpointing(mgr, program=main, every_n_steps=2,
                                 scope=scope)
        for f in _feeds(5):
            exe.run(main, feed=f, fetch_list=[loss])
    mgr.wait()
    assert len(mgr.all_steps()) == 2  # steps 2 and 4 after warm start
    # provider registered for preemption: the final sync save captures
    # the CURRENT (post-step-5) state
    saved = mgr.preemption_save()
    assert saved == exe._step

    # enable-then-restore ordering re-anchors the last-saved marker, so
    # the next run doesn't immediately re-save the state just loaded
    main2, startup2, _ = _build()
    exe2 = static.Executor()
    scope2 = static.Scope()
    with static.scope_guard(scope2):
        exe2.run(startup2)
        exe2.enable_checkpointing(mgr, program=main2, every_n_steps=2,
                                  scope=scope2)
        restored = exe2.restore_from_checkpoint(mgr, main2, scope2)
        assert restored == saved
        assert exe2._ckpt.last == exe2._step
    mgr.close()


@pytest.mark.slow
def test_hapi_fit_sigterm_preemption_commits_epoch_boundary(tmp_path):
    """A SIGTERMed fit() commits the LAST COMPLETED epoch even when
    save_freq skipped it (the chaos kill counts train batches), and
    resume=True continues to a final state bitwise-equal to a straight
    run — the partial epoch replays."""
    import subprocess
    import sys
    d = str(tmp_path / "run")
    prog = r"""
import sys
sys.path.insert(0, {repo!r})
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.core.program import _reset_unique_names
from paddle_tpu.io import Dataset

class DS(Dataset):
    def __init__(self, n=16):
        rng = np.random.RandomState(0)
        self.x = rng.rand(n, 4).astype(np.float32)
        self.y = self.x.sum(1, keepdims=True).astype(np.float32)
    def __len__(self):
        return len(self.x)
    def __getitem__(self, i):
        return self.x[i], self.y[i]

def make_model():
    _reset_unique_names()
    paddle.seed(0)
    net = paddle.nn.Linear(4, 1)
    m = paddle.Model(net)
    m.prepare(optimizer=paddle.optimizer.Adam(
        learning_rate=0.01, parameters=net.parameters()),
        loss=paddle.nn.MSELoss())
    return m

mode = sys.argv[1]
d = sys.argv[2]
m = make_model()
if mode == "crash":
    # 4 batches/epoch; chaos kills at batch 10 = mid-epoch 2
    m.fit(DS(), batch_size=4, epochs=4, shuffle=False, verbose=0,
          save_dir=d, save_freq=10)
elif mode == "resume":
    m.fit(DS(), batch_size=4, epochs=4, shuffle=False, verbose=0,
          save_dir=d, save_freq=10, resume=True)
else:
    m.fit(DS(), batch_size=4, epochs=4, shuffle=False, verbose=0)
w = {{k: np.asarray(v.numpy()).tolist()
     for k, v in m.network.state_dict().items()}}
import json
print("PARAMS=" + json.dumps(w))
""".format(repo=REPO_ROOT)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PADDLE_TPU_CHAOS", None)

    p = subprocess.run(
        [sys.executable, "-c", prog, "crash", d],
        env=dict(env, PADDLE_TPU_CHAOS="kill@10:signal=term"),
        capture_output=True, text=True, timeout=240)
    assert p.returncode == 143, (p.returncode, p.stderr[-2000:])
    from paddle_tpu.checkpoint import CheckpointManager
    mgr = CheckpointManager(os.path.join(d, "checkpoints"))
    # save_freq=10 never saved; the preemption commit carries epoch 1
    assert mgr.all_steps() == [1]
    assert mgr.load().extra["epoch"] == 1
    mgr.close()

    p2 = subprocess.run([sys.executable, "-c", prog, "resume", d],
                        env=env, capture_output=True, text=True,
                        timeout=240)
    assert p2.returncode == 0, p2.stderr[-2000:]
    p3 = subprocess.run([sys.executable, "-c", prog, "straight", d],
                        env=env, capture_output=True, text=True,
                        timeout=240)
    assert p3.returncode == 0, p3.stderr[-2000:]
    import json as _json

    def params_of(out):
        line = [ln for ln in out.splitlines()
                if ln.startswith("PARAMS=")][-1]
        return _json.loads(line[len("PARAMS="):])

    a, b = params_of(p2.stdout), params_of(p3.stdout)
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_array_equal(np.asarray(a[k], np.float32),
                                      np.asarray(b[k], np.float32),
                                      err_msg=k)


def test_sigkill_mid_async_save_sweeps_stage_and_falls_back(tmp_path):
    """Crash consistency: SIGKILL (no SIGTERM drain) a trainer mid-async-
    save must leave the commit log intact — the orphaned staging dir is
    swept on the next startup and load() returns the last CRC-valid
    commit, never the torn step."""
    import subprocess
    import sys
    import time
    root = str(tmp_path / "ckpts")
    child = (
        "import numpy as np\n"
        "from paddle_tpu.checkpoint import CheckpointManager\n"
        f"mgr = CheckpointManager({root!r}, keep_last_n=10)\n"
        "mgr.save(1, {'w': np.full(128, 1.0, np.float32)}, sync=True)\n"
        # step 2 dies between the shard bytes and the manifest: the chaos
        # torn_save hook SIGKILLs the process inside _persist
        "mgr.save(2, {'w': np.full(128, 2.0, np.float32)}, sync=True)\n"
        "raise SystemExit(7)  # unreachable when chaos fires\n")
    env = dict(os.environ, PADDLE_TPU_CHAOS="torn_save@2",
               JAX_PLATFORMS="cpu")
    p = subprocess.run([sys.executable, "-c", child], env=env,
                       capture_output=True, timeout=120)
    assert p.returncode == -signal.SIGKILL, (p.returncode, p.stderr)
    stages = [n for n in os.listdir(root) if n.startswith(".tmp.step_2")]
    assert stages, "torn save must leave its staging dir behind"
    assert not os.path.isdir(os.path.join(root, "step_2"))

    # fresh-start sweep: owner pid is dead; once the stage is idle past
    # the cross-host grace window the next manager removes it
    old = time.time() - 7200
    for s in stages:
        for dirpath, _dirs, files in os.walk(os.path.join(root, s)):
            os.utime(dirpath, (old, old))
            for fname in files:
                os.utime(os.path.join(dirpath, fname), (old, old))
    mgr = CheckpointManager(root)
    assert not any(n.startswith(".tmp.step_2") for n in os.listdir(root))
    ckpt = mgr.load()
    assert ckpt.step == 1 and ckpt.state["w"][0] == 1.0
    assert mgr.latest_step() == 1
    mgr.close()


def test_chaos_slow_save_still_commits(tmp_path):
    """slow_save chaos stretches the shard->manifest window without
    breaking atomicity: the save takes longer but commits clean."""
    from paddle_tpu.testing import chaos
    import time
    os.environ[chaos.CHAOS_ENV] = "slow_save=0.2"
    try:
        chaos.reload()
        mgr = CheckpointManager(str(tmp_path))
        t0 = time.monotonic()
        mgr.save(1, {"w": np.ones(8, np.float32)}, sync=True)
        assert time.monotonic() - t0 >= 0.2
        assert mgr.load().step == 1
        mgr.close()
    finally:
        os.environ.pop(chaos.CHAOS_ENV, None)
        chaos.reload()


# ---------------------------------------------------------------------------
# ZeRO-1 sharded data parallelism (distributed/sharding.py)
# ---------------------------------------------------------------------------
def _build_zero1(dp_degree=8):
    """ZeRO-1-sharded program on the 8-device mesh, identical on every
    call (process-restart semantics)."""
    from paddle_tpu.distributed.compiled_program import CompiledProgram
    from paddle_tpu.distributed.sharding import shard_optimizer_states
    main, startup, loss = _build()
    plan = shard_optimizer_states(main, startup, dp_degree=dp_degree)
    compiled = CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    return main, startup, loss, compiled, plan


def _zero1_feeds(n):
    rng = np.random.RandomState(0)
    return [{"x": rng.rand(8, 8).astype(np.float32),
             "y": rng.rand(8, 1).astype(np.float32)} for _ in range(n)]


def test_zero1_kill_resume_bitwise_equivalence(tmp_path):
    """Kill/resume under ZeRO-1: train 6 straight vs train 3 / crash /
    auto-resume / train 3 on the 8-device mesh → params AND the SHARDED
    bucket slots bitwise-identical.  The snapshot device_gets the
    global-shape bucket arrays (rank-complete), and restore re-shards
    them on the next step's shard_map placement — every rank gets its
    own slice back by construction."""
    from paddle_tpu.checkpoint import CheckpointManager
    n, k = 6, 3
    feeds = _zero1_feeds(n)

    main, startup, loss, compiled, plan = _build_zero1()
    assert plan.buckets
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        for f in feeds:
            exe.run(compiled, feed=f, fetch_list=[loss])
        ref = _persistables(main, scope)
    # the sharded slots are IN the snapshot, at global bucket shape
    for name in plan.slot_var_names():
        assert name in ref, name

    root = str(tmp_path / "ckpts")
    main2, startup2, loss2, compiled2, _ = _build_zero1()
    assert main2.fingerprint() == main.fingerprint()
    exe2 = static.Executor()
    scope2 = static.Scope()
    mgr = CheckpointManager(root)
    with static.scope_guard(scope2):
        exe2.run(startup2)
        exe2.enable_checkpointing(mgr, program=main2, every_n_steps=k,
                                  scope=scope2)
        for f in feeds[:k]:
            exe2.run(compiled2, feed=f, fetch_list=[loss2])
    mgr.close()

    main3, startup3, loss3, compiled3, _ = _build_zero1()
    exe3 = static.Executor()
    scope3 = static.Scope()
    mgr2 = CheckpointManager(root)
    with static.scope_guard(scope3):
        exe3.run(startup3)
        resumed = exe3.restore_from_checkpoint(mgr2, program=main3,
                                               scope=scope3)
        assert resumed is not None
        for f in feeds[k:]:
            exe3.run(compiled3, feed=f, fetch_list=[loss3])
        got = _persistables(main3, scope3)
    mgr2.close()

    assert set(ref) == set(got)
    for name in sorted(ref):
        np.testing.assert_array_equal(ref[name], got[name], err_msg=name)


def test_zero1_restore_warns_on_shard_count_mismatch(tmp_path):
    """A checkpoint saved from a program sharded for 8 ranks restored
    into one sharded for 4 must fire the program-fingerprint warning —
    the bucket paddings/collectives differ, so silent restore would
    build a chimera state."""
    from paddle_tpu.checkpoint import CheckpointManager
    main, startup, loss, compiled, _ = _build_zero1(dp_degree=8)
    exe = static.Executor()
    scope = static.Scope()
    mgr = CheckpointManager(str(tmp_path))
    with static.scope_guard(scope):
        exe.run(startup)
        exe.run(compiled, feed=_zero1_feeds(1)[0], fetch_list=[loss])
        s, state, extra = exe.checkpoint_snapshot(main, scope)
        mgr.save(s, state, extra=extra, sync=True)

    main4, startup4, loss4, compiled4, _ = _build_zero1(dp_degree=4)
    assert main4.fingerprint() != main.fingerprint()
    exe2 = static.Executor()
    scope2 = static.Scope()
    with static.scope_guard(scope2):
        exe2.run(startup4)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            exe2.restore_from_checkpoint(mgr, program=main4, scope=scope2)
    mgr.close()
    assert any("fingerprint mismatch" in str(w.message) for w in caught)


def test_zero1_checkpoint_resumes_unsharded_and_back(tmp_path):
    """Layout conversion fallback: a ZeRO-1 checkpoint converted with
    `unshard_state` restores into the PLAIN program (per-param moments
    recovered from the bucket slices), and a plain checkpoint converted
    with `reshard_state` restores into the ZeRO-1 program — training
    continues identically either way."""
    from paddle_tpu.distributed.sharding import (unshard_state,
                                                 reshard_state)
    feeds = _zero1_feeds(4)

    # ZeRO-1 run -> snapshot -> unshard -> plain program continues
    main, startup, loss, compiled, plan = _build_zero1()
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        for f in feeds[:2]:
            exe.run(compiled, feed=f, fetch_list=[loss])
        _, state, _ = exe.checkpoint_snapshot(main, scope)
    plain_state = unshard_state(state, plan)

    mainp, startupp, lossp = _build()
    exep = static.Executor()
    scopep = static.Scope()
    with static.scope_guard(scopep):
        exep.run(startupp)
        for name, val in plain_state.items():
            if scopep.get(name) is not None or name in \
                    {v.name for v in mainp.global_block().vars.values()}:
                scopep.set(name, np.asarray(val))
        exep._step = 2
        for f in feeds[2:]:
            exep.run(mainp, feed=f, fetch_list=[lossp])
        plain_params = {p.name: np.asarray(scopep.get(p.name))
                        for p in mainp.all_parameters()}

    # straight ZeRO-1 reference over all 4 steps
    main2, startup2, loss2, compiled2, _ = _build_zero1()
    exe2 = static.Executor()
    scope2 = static.Scope()
    with static.scope_guard(scope2):
        exe2.run(startup2)
        for f in feeds:
            exe2.run(compiled2, feed=f, fetch_list=[loss2])
        ref_params = {p.name: np.asarray(scope2.get(p.name))
                      for p in main2.all_parameters()}
    for k in ref_params:
        np.testing.assert_allclose(ref_params[k], plain_params[k],
                                   atol=1e-6, err_msg=k)

    # ...and back: plain state reshards into the ZeRO-1 layout
    back = reshard_state(plain_state, plan)
    main3, startup3, loss3, compiled3, _ = _build_zero1()
    exe3 = static.Executor()
    scope3 = static.Scope()
    with static.scope_guard(scope3):
        exe3.run(startup3)
        for name, val in back.items():
            if name in {v.name
                        for v in main3.global_block().vars.values()}:
                scope3.set(name, np.asarray(val))
        exe3._step = 2
        for f in feeds[2:]:
            exe3.run(compiled3, feed=f, fetch_list=[loss3])
        zero_params = {p.name: np.asarray(scope3.get(p.name))
                       for p in main3.all_parameters()}
    for k in ref_params:
        np.testing.assert_allclose(ref_params[k], zero_params[k],
                                   atol=1e-6, err_msg=k)


# ---------------------------------------------------------------------------
# topology-shifted restore (ISSUE 6): resume across dp_degree changes
# ---------------------------------------------------------------------------
def _topo_cfg(kind):
    """Build one (main, startup, loss, compiled, plan, world) config:
    'plain' (8-dev DP) or 'zeroN' (ZeRO-1 sharded for N, run on N devs)."""
    import jax
    from paddle_tpu.distributed.compiled_program import CompiledProgram
    from paddle_tpu.distributed.sharding import shard_optimizer_states
    main, startup, loss = _build()
    world, plan = 8, None
    if kind.startswith("zero"):
        world = int(kind[4:])
        plan = shard_optimizer_states(main, startup, dp_degree=world)
        assert plan.buckets
    compiled = CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, places=list(jax.devices())[:world])
    return main, startup, loss, compiled, plan, world


def _topo_train(cfg, exe, scope, feeds, fetch=True):
    main, _startup, loss, compiled, _plan, _world = cfg
    losses = []
    with static.scope_guard(scope):
        for f in feeds:
            out = exe.run(compiled, feed=f, fetch_list=[loss])
            losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
    return losses


def _run_topo_shift(src, dst, tmp_path):
    """Train 2 steps at `src`, checkpoint, resume at `dst` through the
    automatic layout conversion, train 2 more; return (losses, params,
    caught warnings)."""
    from paddle_tpu.checkpoint import CheckpointManager
    feeds = _zero1_feeds(4)
    root = str(tmp_path / f"{src}_to_{dst}")

    cfg1 = _topo_cfg(src)
    exe1 = static.Executor()
    scope1 = static.Scope()
    mgr = CheckpointManager(root)
    with static.scope_guard(scope1):
        exe1.run(cfg1[1])
    pre = _topo_train(cfg1, exe1, scope1, feeds[:2])
    with static.scope_guard(scope1):
        s, state, extra = exe1.checkpoint_snapshot(cfg1[0], scope1)
        mgr.save(s, state, extra=extra, sync=True)
    mgr.close()

    cfg2 = _topo_cfg(dst)
    exe2 = static.Executor()
    scope2 = static.Scope()
    mgr2 = CheckpointManager(root)
    with static.scope_guard(scope2):
        exe2.run(cfg2[1])
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            resumed = exe2.restore_from_checkpoint(
                mgr2, program=cfg2[0], scope=scope2, world=cfg2[5])
        assert resumed is not None
    post = _topo_train(cfg2, exe2, scope2, feeds[2:])
    with static.scope_guard(scope2):
        params = {p.name: np.asarray(scope2.get(p.name))
                  for p in cfg2[0].all_parameters()}
    mgr2.close()
    return pre + post, params, caught


_TOPO_REF_CACHE = []


def _topo_reference(tmp_path=None):
    """Straight 4-step plain-DP run (the numerics baseline every config
    is allclose to, per docs/perf.md's sharding contract).  Cached: the
    tier-1 case and the slow matrix share one reference compile+run —
    the tier-1 suite races its 870s budget, every mesh compile counts."""
    if _TOPO_REF_CACHE:
        return _TOPO_REF_CACHE[0]
    feeds = _zero1_feeds(4)
    cfg = _topo_cfg("plain")
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(cfg[1])
    losses = _topo_train(cfg, exe, scope, feeds)
    with static.scope_guard(scope):
        params = {p.name: np.asarray(scope.get(p.name))
                  for p in cfg[0].all_parameters()}
    _TOPO_REF_CACHE.append((losses, params))
    return losses, params


def test_resume_zero8_to_zero4_auto_converts(tmp_path):
    """8->4 shard-count shrink: the fingerprint mismatch is CONVERTED
    (unshard -> reshard), not chimera-loaded, and training continues
    allclose to an uninterrupted run."""
    got, params, caught = _run_topo_shift("zero8", "zero4", tmp_path)
    assert any("automatically converted" in str(w.message)
               for w in caught), [str(w.message) for w in caught]
    ref_losses, ref_params = _topo_reference(tmp_path)
    np.testing.assert_allclose(got, ref_losses, rtol=1e-4, atol=1e-6)
    for k in ref_params:
        np.testing.assert_allclose(params[k], ref_params[k], atol=1e-5,
                                   err_msg=k)


@pytest.mark.slow
@pytest.mark.parametrize("src,dst", [
    ("zero4", "zero8"),   # regrow
    ("zero8", "plain"),   # shed sharding entirely
    ("plain", "zero4"),   # adopt sharding on a shrunk mesh
])
def test_resume_across_dp_degree_matrix(src, dst, tmp_path):
    """The rest of the plain<->ZeRO-1 / 8<->4 resume matrix (the 8->4
    shrink case runs in tier-1 above)."""
    got, params, caught = _run_topo_shift(src, dst, tmp_path)
    assert any("automatically converted" in str(w.message)
               for w in caught), [str(w.message) for w in caught]
    ref_losses, ref_params = _topo_reference(tmp_path)
    np.testing.assert_allclose(got, ref_losses, rtol=1e-4, atol=1e-6)
    for k in ref_params:
        np.testing.assert_allclose(params[k], ref_params[k], atol=1e-5,
                                   err_msg=k)


def test_gradient_merge_counter_rederivation():
    """k_old=4 -> k_new=2 mid-window: counter re-denominated at the last
    commit boundary, accumulators zeroed, and the dataset position
    rewound so the discarded mid-window batches REPLAY (not skip)."""
    import types
    import warnings as warnings_mod
    exe = static.Executor()
    scope = static.Scope()
    scope.set("gm_old", np.array([6], np.int32))  # 1 commit + 2 micro
    scope.set("acc1", np.ones(3, np.float32))
    extra = {"gradient_merge": {"counter": "gm_old", "k": 4, "accs": []},
             "dataset_position": 6}
    target = types.SimpleNamespace(
        _gm_meta={"counter": "gm_new", "k": 2, "accs": ["acc1"]})
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        exe._rederive_gradient_merge(target, scope, extra, warnings_mod)
    assert any("mid-window" in str(w.message) for w in caught)
    assert int(np.asarray(scope.get("gm_new")).reshape(-1)[0]) == 2
    assert np.all(np.asarray(scope.get("acc1")) == 0)  # window replays
    assert extra["dataset_position"] == 2  # 1 commit * k_new


def test_restore_on_mismatch_error_refuses_chimera(tmp_path):
    """on_mismatch='error': an unconvertible fingerprint mismatch (a
    genuinely different topology, no sharding plans) raises instead of
    warning-and-loading a chimera."""
    from paddle_tpu.checkpoint import CheckpointManager, CheckpointError
    main, startup, loss = _build()
    exe = static.Executor()
    scope = static.Scope()
    mgr = CheckpointManager(str(tmp_path))
    with static.scope_guard(scope):
        exe.run(startup)
        s, state, extra = exe.checkpoint_snapshot(main, scope)
        mgr.save(s, state, extra=extra, sync=True)

    _reset_unique_names()
    other, other_start = static.Program(), static.Program()
    with static.program_guard(other, other_start):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        pred = layers.fc(x, 1)  # different topology
        loss2 = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss2)
    exe2 = static.Executor()
    scope2 = static.Scope()
    with static.scope_guard(scope2):
        exe2.run(other_start)
        with pytest.raises(CheckpointError):
            exe2.restore_from_checkpoint(mgr, program=other, scope=scope2,
                                         on_mismatch="error")
    mgr.close()

    # a shard plan must NOT smuggle a chimera past 'error': checkpoint
    # from a ZeRO-sharded model restored into a DIFFERENT (wider) ZeRO
    # model converts the bucket layout but still lacks the extra params
    # — that is not a pure shard-count shift and must raise too.
    # (Startup runs only; no mesh compiles — tier-1 stays cheap.)
    from paddle_tpu.distributed.sharding import shard_optimizer_states
    main_a, startup_a, loss_a = _build()
    shard_optimizer_states(main_a, startup_a, dp_degree=8)
    exe_a = static.Executor()
    scope_a = static.Scope()
    mgr2 = CheckpointManager(str(tmp_path / "zchimera"))
    with static.scope_guard(scope_a):
        exe_a.run(startup_a)
        s, state, extra = exe_a.checkpoint_snapshot(main_a, scope_a)
        mgr2.save(s, state, extra=extra, sync=True)
    assert "zero_shard_plan" in extra

    _reset_unique_names()
    wide, wide_start = static.Program(), static.Program()
    with static.program_guard(wide, wide_start):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 32, act="relu")  # different width
        pred = layers.fc(h, 1)
        loss_w = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss_w)
    shard_optimizer_states(wide, wide_start, dp_degree=4)
    exe_w = static.Executor()
    scope_w = static.Scope()
    with static.scope_guard(scope_w):
        exe_w.run(wide_start)
        with pytest.raises(CheckpointError, match="not a pure"):
            exe_w.restore_from_checkpoint(mgr2, program=wide,
                                          scope=scope_w,
                                          on_mismatch="error")
        # default mode survives the failed conversion with a warning
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            exe_w.restore_from_checkpoint(mgr2, program=wide,
                                          scope=scope_w)
        assert any("FAILED" in str(w.message) or
                   "absent" in str(w.message) for w in caught)
    mgr2.close()

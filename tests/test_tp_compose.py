"""Composition lifts (ISSUE 15): ZeRO-1 × tp and elastic × tp — the two
`CompiledProgram._get_program` refusals replaced by passing numerics.

Contracts:
  * ZeRO-1 × tp on the 8-device 4×2 dp×tp mesh trains allclose 1e-6 to
    the serial reference: the bucket reduce-scatter and publish ride
    ring 0 (the dp sub-axis), slot buckets place ``P("dp")`` on the 2-D
    mesh, and tp-annotated weights stay on the per-param path with
    tp-sharded accumulators.
  * elastic × tp on the same mesh: the ordered fold gathers dp
    sub-ranks only (the tp leg is model parallelism, not data-parallel
    capacity), K = logical_dp / mesh_dp, and per-param fold accumulators
    of tp-sharded weights inherit the ``dist_attr`` sharding.
  * every lifted composition is strict-clean under
    ``check_program(level="all")`` — including the V6xx layout level.
  * V504 plan-drift fires on tp_degree mismatches (the new knob is
    drift-checked like remat/ring).

Tier-1 keeps one config of each matrix; the rest are @slow.
"""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers
from paddle_tpu.core.program import _reset_unique_names


def _need_devices(n):
    import jax
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def _const_attrs(w_val, b_val):
    return (static.ParamAttr(initializer=static.Constant(w_val)),
            static.ParamAttr(initializer=static.Constant(b_val)))


def _build_plain(opt="adam"):
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        w1, b1 = _const_attrs(0.12, 0.01)
        h = layers.fc(x, 16, act="relu", param_attr=w1, bias_attr=b1)
        w2, b2 = _const_attrs(0.07, 0.0)
        pred = layers.fc(h, 1, param_attr=w2, bias_attr=b2)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        o = (static.Adam(learning_rate=0.05) if opt == "adam"
             else static.SGD(learning_rate=0.05))
        o.minimize(loss)
    return main, startup, loss


def _build_tp(opt="adam"):
    from paddle_tpu.distributed.tensor_parallel import (col_parallel_fc,
                                                        row_parallel_fc)
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        w1, b1 = _const_attrs(0.12, 0.01)
        h = col_parallel_fc(x, 16, act="relu", param_attr=w1,
                            bias_attr=b1, tp_degree=2)
        w2, b2 = _const_attrs(0.07, 0.0)
        pred = row_parallel_fc(h, 1, param_attr=w2, bias_attr=b2,
                               tp_degree=2)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        o = (static.Adam(learning_rate=0.05) if opt == "adam"
             else static.SGD(learning_rate=0.05))
        o.minimize(loss)
    return main, startup, loss


def _batches(n=5):
    rng = np.random.RandomState(7)
    return [(rng.rand(16, 8).astype(np.float32),
             rng.rand(16, 1).astype(np.float32)) for _ in range(n)]


def _train(main, startup, loss, compiled=None, fetch=None):
    exe = static.Executor()
    scope = static.Scope()
    out = []
    with static.scope_guard(scope):
        exe.run(startup)
        target = compiled if compiled is not None else main
        for xb, yb in _batches():
            (lv,) = exe.run(target, feed={"x": xb, "y": yb},
                            fetch_list=[fetch if fetch is not None
                                        else loss])
            out.append(float(np.asarray(lv).reshape(-1)[0]))
    return out, scope


def _compiled_tp(main, loss, tp):
    from paddle_tpu.distributed.compiled_program import (CompiledProgram,
                                                         BuildStrategy)
    bs = BuildStrategy()
    bs.tensor_parallel_degree = tp
    return CompiledProgram(main).with_data_parallel(
        loss_name=loss.name, build_strategy=bs)


# ---------------------------------------------------------------------------
# ZeRO-1 × tp
# ---------------------------------------------------------------------------
def _run_zero_tp(dp_degree, tp, stage=1):
    from paddle_tpu.distributed.sharding import shard_optimizer_states
    single, _ = _train(*_build_plain())
    main, startup, loss = _build_tp()
    plan = shard_optimizer_states(main, startup, dp_degree=dp_degree,
                                  stage=stage)
    assert plan.buckets, "nothing sharded — the composition is vacuous"
    cp = _compiled_tp(main, loss, tp)
    assert dict(cp._get_mesh().shape) == {"dp": dp_degree, "tp": tp}
    par, scope = _train(main, startup, loss, compiled=cp)
    np.testing.assert_allclose(single, par, rtol=1e-6, atol=1e-6)
    # strict-clean through EVERY level, the V6xx layout analyzer included
    report = static.check_program(main, level="all", startup=startup,
                                  fetch_list=[loss])
    assert report.ok, report.render()
    return main, scope


def test_zero1_tp_4x2_allclose_serial():
    """The headline lift: ZeRO-1 × tp on the 4×2 mesh trains allclose
    1e-6 to serial, strict-clean at level='all'."""
    _need_devices(8)
    main, scope = _run_zero_tp(dp_degree=4, tp=2)
    # the tp-annotated weights stayed OUT of the dp buckets (their flat
    # layout can't hold a tp-local shard) — per-param path + inherited
    # tp slot sharding cover them
    from paddle_tpu.distributed.sharding import ShardingPlan
    plan = main._zero_shard_plan
    bucketed = {p["param"] for b in plan.buckets for p in b["params"]}
    annotated = {v.name for v in main.all_parameters()
                 if v.attrs.get("dist_attr")}
    assert not (bucketed & annotated), (bucketed, annotated)
    assert annotated, "tp build lost its dist_attr annotations"


@pytest.mark.slow
@pytest.mark.parametrize("dp,tp", [(2, 4)])
def test_zero1_tp_matrix_allclose_serial(dp, tp):
    """The other 8-device factorization: 2×4."""
    _need_devices(dp * tp)
    _run_zero_tp(dp_degree=dp, tp=tp)


# ---------------------------------------------------------------------------
# elastic × tp
# ---------------------------------------------------------------------------
def _run_elastic_tp(logical_dp, tp, n_dev=8):
    from paddle_tpu.distributed.elastic import elasticize, rebucket_feeds
    single, _ = _train(*_build_plain(opt="sgd"))
    main, startup, loss = _build_tp(opt="sgd")
    meta = elasticize(main, startup, logical_dp=logical_dp,
                      loss_name=loss)
    cp = _compiled_tp(main, loss, tp)
    mesh_dp = n_dev // tp
    assert dict(cp._get_mesh().shape) == {"dp": mesh_dp, "tp": tp}
    k = logical_dp // mesh_dp

    exe = static.Executor()
    scope = static.Scope()
    out = []
    with static.scope_guard(scope):
        exe.run(startup)
        for xb, yb in _batches():
            # one GLOBAL batch -> K micro-feeds for this mesh's dp world
            for micro in rebucket_feeds({"x": xb, "y": yb}, logical_dp,
                                        mesh_dp):
                (lv,) = exe.run(cp, feed=micro,
                                fetch_list=[meta["loss_avg"]])
            out.append(float(np.asarray(lv).reshape(-1)[0]))
    np.testing.assert_allclose(single, out, rtol=1e-6, atol=1e-6)
    report = static.check_program(main, level="all", startup=startup)
    assert report.ok, report.render()


def test_elastic_tp_4x2_allclose_serial():
    """elastic × tp on the 4×2 mesh: K = logical_dp / mesh_dp folds
    over dp sub-ranks, the tp leg left intact — allclose 1e-6 to the
    serial reference, strict-clean at level='all'."""
    _need_devices(8)
    _run_elastic_tp(logical_dp=4, tp=2)


@pytest.mark.slow
@pytest.mark.parametrize("logical_dp,tp", [(8, 2), (4, 4)])
def test_elastic_tp_matrix_allclose_serial(logical_dp, tp):
    """K>1 windows (logical 8 on a dp=4 sub-axis) and the tp=4 leg."""
    _need_devices(8)
    _run_elastic_tp(logical_dp=logical_dp, tp=tp)


def test_elastic_tp_fold_accs_inherit_dist_attr():
    """The per-param fold accumulators of tp-sharded weights must carry
    the param's dist_attr — a replicated global-shape accumulator would
    shape-mismatch against the local-shard grad inside the trace."""
    from paddle_tpu.distributed.elastic import elasticize
    main, startup, loss = _build_tp(opt="sgd")
    elasticize(main, startup, logical_dp=4, loss_name=loss)
    block = main.global_block()
    annotated = {v.name: v.attrs["dist_attr"]
                 for v in main.all_parameters()
                 if v.attrs.get("dist_attr")}
    assert annotated
    hits = 0
    for name, var in block.vars.items():
        if "@ELASTIC_ACC" in name and var.attrs.get("dist_attr"):
            hits += 1
    assert hits >= len(annotated), (hits, annotated)


# ---------------------------------------------------------------------------
# V504 plan drift for the tp_degree knob
# ---------------------------------------------------------------------------
def test_plan_drift_v504_tp_degree_claimed_but_not_built():
    """A recorded plan claiming tp on a PLAIN build is drift: the knobs
    the bench record would attribute numbers to never ran."""
    from paddle_tpu.core.pass_framework import record_applied
    main, startup, loss = _build_plain()
    record_applied(main, "auto_parallel_plan", batch=8, remat=False,
                   dp_shard=0, zero_stage=0, grad_merge=1, bucket_mb=0,
                   ring=False, tp_degree=2)
    report = static.check_program(main, level="collective")
    assert any(d.code == "V504" and "tp_degree" in d.message
               for d in report.errors), report.render()


def test_plan_drift_v504_tp_build_with_plan_saying_zero():
    """The reverse mutation: a tp build whose recorded plan says
    tp_degree=0."""
    from paddle_tpu.core.pass_framework import record_applied
    main, startup, loss = _build_tp()
    record_applied(main, "auto_parallel_plan", batch=8, remat=False,
                   dp_shard=0, zero_stage=0, grad_merge=1, bucket_mb=0,
                   ring=False, tp_degree=0)
    report = static.check_program(main, level="collective")
    assert any(d.code == "V504" and "tp_degree" in d.message
               for d in report.errors), report.render()


def test_plan_apply_roundtrip_on_tp_build_no_drift():
    """plan → apply on a tp-built program records tp_degree truthfully:
    the round-trip must NOT V504 (the pinned-knob contract the ring and
    remat axes already honor)."""
    main, startup, loss = _build_tp()
    plan = static.plan_program(main, startup, world=8, batch=8,
                               knobs={"grad_merge": (1,)})
    assert plan.knobs["tp_degree"] == 2
    assert all(c["tp_degree"] == 2 for c in plan.trace)
    static.apply_plan(main, startup, plan)
    report = static.check_program(main, level="all", startup=startup)
    assert "V504" not in report.codes(), report.render()


def test_apply_plan_refuses_tp_mismatch():
    """apply_plan on the WRONG build variant raises, like the ring
    knob: tp is a build property, not a post-hoc rewrite."""
    main, startup, loss = _build_plain()
    with pytest.raises(ValueError, match="tp_degree"):
        static.apply_plan(main, startup,
                          {"batch": 8, "remat": False, "dp_shard": 0,
                           "zero_stage": 0, "grad_merge": 1,
                           "bucket_mb": 0, "ring": False, "tp_degree": 2})


# ---------------------------------------------------------------------------
# mesh-axis canonicalizer regression (the naming seam)
# ---------------------------------------------------------------------------
def test_mesh_axis_canonicalizer_single_source():
    """Runtime mesh axis, analyzer axis, ring table and builder stamps
    must all route through core/mesh_axes — the V604 ring/axis checks
    and program_ring_degrees see ONE name on both paths."""
    from paddle_tpu.core.mesh_axes import (canonical_axis, runtime_axis,
                                           RING_AXIS)
    from paddle_tpu.static.verifier import ring_axis
    from paddle_tpu.distributed.tensor_parallel import TP_RING_ID, MP_AXIS

    assert canonical_axis("tp") == "mp" == MP_AXIS
    assert runtime_axis("mp") == "tp"
    assert canonical_axis("dp") == "dp" and canonical_axis(None) is None
    # the tensor ring resolves to the SAME canonical name from the ring
    # table, from the runtime spelling, and from a builder stamp
    assert RING_AXIS[TP_RING_ID] == "mp"
    assert ring_axis(TP_RING_ID) == "mp"
    assert ring_axis(TP_RING_ID, mp_axis="tp") == "mp"
    assert ring_axis(TP_RING_ID, mp_axis="mp") == "mp"

    # the runtime mesh CompiledProgram builds uses the runtime spelling
    # of the same axis
    import jax
    if len(jax.devices()) >= 8:
        main, startup, loss = _build_tp()
        cp = _compiled_tp(main, loss, 2)
        mesh_axes = tuple(cp._get_mesh().axis_names)
        assert mesh_axes == ("dp", runtime_axis("mp"))
        # and the analyzer's inferred degrees agree with the stamps
        from paddle_tpu.static.verifier import program_ring_degrees
        degrees = program_ring_degrees(main)
        assert degrees.get(TP_RING_ID) == 2, degrees


# ---------------------------------------------------------------------------
# the ISSUE 15 acceptance run: planner-chosen 4×2 vs serial, allclose 1e-6
# ---------------------------------------------------------------------------
def test_planned_4x2_trains_allclose_serial_reference():
    """The planner picks the 4×2 dp×tp plan unprompted (tp variants
    auto-generated, budget derived so pure dp is walker-infeasible),
    and the APPLIED plan trains on the 8-device CPU mesh allclose 1e-6
    to the serial single-device reference."""
    _need_devices(8)
    from paddle_tpu.static.memory_analysis import XLA_REMAT_SLACK
    from paddle_tpu.models import build_transformer_lm
    GEOM = dict(vocab_size=128, hidden=64, num_layers=2, num_heads=4,
                seq_len=32, learning_rate=1e-2)
    KNOBS = {"batch": (16,), "grad_merge": (1,), "zero_stage": (1,)}

    def build(tp=1):
        _reset_unique_names()
        main, startup, loss, _ = build_transformer_lm(
            vocab_size=GEOM["vocab_size"], hidden=GEOM["hidden"],
            num_layers=GEOM["num_layers"], num_heads=GEOM["num_heads"],
            seq_len=GEOM["seq_len"])
        with static.program_guard(main, startup):
            static.Adam(
                learning_rate=GEOM["learning_rate"]).minimize(loss)
        return main, startup, loss

    base = build()
    probe = static.plan_program(base[0], base[1], world=8,
                                hbm_budget=1 << 50,
                                knobs=dict(KNOBS, tp_degree=(0, 2)),
                                model_config=GEOM, verify=False)
    best_dp = min(c["peak_bytes"] for c in probe.trace
                  if not c["tp_degree"] and c["peak_bytes"] > 0)
    base2 = build()
    plan = static.plan_program(
        base2[0], base2[1], world=8,
        hbm_budget=int(best_dp / XLA_REMAT_SLACK) - 1,
        knobs=dict(KNOBS), model_config=GEOM)
    assert plan.knobs["tp_degree"] == 2, plan.render_table()
    win_main, win_startup, loss_name = plan.build_variants[2]
    static.apply_plan(win_main, win_startup, plan)

    rng = np.random.RandomState(0)
    seq = GEOM["seq_len"]
    feeds = []
    for _ in range(4):
        feeds.append({
            "ids": rng.randint(0, GEOM["vocab_size"],
                               (16, seq)).astype(np.int64),
            "pos": np.tile(np.arange(seq), (16, 1)).astype(np.int64),
            "labels": rng.randint(0, GEOM["vocab_size"],
                                  (16, seq, 1)).astype(np.int64)})

    def run(main, startup, fetch, compiled=None):
        exe = static.Executor()
        scope = static.Scope()
        out = []
        with static.scope_guard(scope):
            exe.run(startup)
            for feed in feeds:
                (lv,) = exe.run(compiled if compiled is not None
                                else main, feed=feed,
                                fetch_list=[fetch])
                out.append(float(np.asarray(lv).reshape(-1)[0]))
        return out

    serial_main, serial_startup, serial_loss = build()
    serial = run(serial_main, serial_startup, serial_loss)

    from paddle_tpu.distributed.compiled_program import (CompiledProgram,
                                                         BuildStrategy)
    bs = BuildStrategy()
    bs.tensor_parallel_degree = 2
    cp = CompiledProgram(win_main).with_data_parallel(
        loss_name=loss_name, build_strategy=bs)
    assert dict(cp._get_mesh().shape) == {"dp": 4, "tp": 2}
    par = run(win_main, win_startup, loss_name, compiled=cp)
    np.testing.assert_allclose(serial, par, rtol=1e-6, atol=1e-6)

"""Tier-1 static-analysis gate (NOT marked slow — a regression in the IR
verifier must fail the suite, not wait for an 8-device deadlock to
rediscover it).

Drives tools/verify_smoke.py in-process: a clean ZeRO-1-sharded training
program verifies with ZERO diagnostics, a seeded rank-conditional
collective (guaranteed mesh deadlock) is caught as V205, a seeded
read-after-donate ordering is caught as V302, all in under 10 s.
Mirrors the mem_smoke/shard_smoke gate pattern; the CLI round-trip is
`slow` (a fresh interpreter buys no extra coverage over the in-process
gate).
"""
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_verify_smoke_gate():
    import verify_smoke
    result = verify_smoke.run_smoke()
    assert result["clean_diagnostics"] == 0, result
    assert "V205" in result["deadlock_codes"], result
    assert "V302" in result["read_after_donate_codes"], result
    assert result["collectives_extracted"] >= 2, result
    assert result["value"] < 10, result


@pytest.mark.slow
def test_verify_smoke_cli_prints_json():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "verify_smoke.py")],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr[-2000:]
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["clean_diagnostics"] == 0
    assert "V205" in result["deadlock_codes"]

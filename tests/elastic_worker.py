"""Elastic training worker for the launch/chaos e2e tests.

One "host" of the simulated fleet: builds the elasticized toy model
(logical_dp=8), auto-resumes from its checkpoint root via the
topology-shifted restore, trains the remaining global steps on a mesh of
``world`` devices feeding re-bucketed micro-batches, and writes a JSON
report.  ``PADDLE_TPU_CHAOS`` may kill it at any micro-step — that is
the point.

Usage:
  python elastic_worker.py <ckpt_root> <out_json> <world> <total_steps>

With no argv (launcher mode) everything comes from the launcher env
contract: rank from PADDLE_TRAINER_ID, world = 4 * PADDLE_TRAINERS_NUM
(each "host" owns 4 of the logical 8 chips), restart counter from
PADDLE_TPU_ELASTIC_RESTART, paths from PADDLE_TPU_ELASTIC_TEST_DIR.
"""
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LOGICAL = 8

# standalone invocations need the virtual 8-device CPU mesh too (under
# pytest the conftest already exported this); must happen before jax
# initializes its backends
if "host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") +
        f" --xla_force_host_platform_device_count={LOGICAL}").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_elastic():
    import paddle_tpu.static as static
    from paddle_tpu.static import layers
    from paddle_tpu.core.program import _reset_unique_names
    from paddle_tpu.distributed.elastic import elasticize
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss)
    meta = elasticize(main, startup, logical_dp=LOGICAL, loss_name=loss)
    return main, startup, loss, meta


def feeds_for(total_steps):
    rng = np.random.RandomState(0)
    return [{"x": rng.rand(LOGICAL, 8).astype(np.float32),
             "y": rng.rand(LOGICAL, 1).astype(np.float32)}
            for _ in range(total_steps)]


def run(ckpt_root, out_json, world, total_steps):
    import time
    import jax
    jax.config.update("jax_platforms", "cpu")
    import paddle_tpu.static as static
    from paddle_tpu.checkpoint import CheckpointManager
    from paddle_tpu.distributed.compiled_program import CompiledProgram
    from paddle_tpu.distributed.elastic import rebucket_feeds
    from paddle_tpu.observability import journal as _journal
    from paddle_tpu.testing.chaos import ChaosCollectiveError

    world = int(world)
    total_steps = int(total_steps)
    k = LOGICAL // world
    main, startup, loss, meta = build_elastic()
    exe = static.Executor()
    scope = static.Scope()
    mgr = CheckpointManager(ckpt_root)
    mgr.install_preemption_handler()  # SIGTERM -> final sync checkpoint
    g = 0
    with static.scope_guard(scope):
        exe.run(startup)
        # commit cadence = one checkpoint per GLOBAL step (K micro-steps)
        exe.enable_checkpointing(mgr, program=main, every_n_steps=k,
                                 scope=scope)
        resumed = exe.restore_from_checkpoint(mgr, program=main,
                                              scope=scope, world=world)
        if resumed is not None:
            g = int(exe.last_restored_extra.get("global_step", 0))
        cp = CompiledProgram(main).with_data_parallel(
            loss_name=loss.name, places=list(jax.devices())[:world])
        losses = {}
        for gi, f in enumerate(feeds_for(total_steps)[g:], start=g):
            for mf in rebucket_feeds(f, LOGICAL, world):
                # transient collective failures (flaky ICI / chaos
                # collective_fail) RETRY the same micro-step — an
                # injection that never recovers leaves this rank wedged
                # mid-step, alive but making no progress: exactly the
                # state the launcher's heartbeat stall deadline exists
                # to detect (each retry is journaled for the post-mortem)
                attempt = 0
                while True:
                    try:
                        out = exe.run(cp, feed=mf,
                                      fetch_list=[meta["loss_avg"]])
                        break
                    except ChaosCollectiveError:
                        attempt += 1
                        _journal.emit("collective_retry", step=exe._step,
                                      attempt=attempt)
                        time.sleep(0.2)
            losses[gi] = float(np.asarray(out[0]).reshape(-1)[0])
        params = {p.name: np.asarray(scope.get(p.name)).tolist()
                  for p in main.all_parameters()}
    mgr.close()
    report = {
        "rank": int(os.environ.get("PADDLE_TRAINER_ID", 0)),
        "world": world,
        "restart": int(os.environ.get("PADDLE_TPU_ELASTIC_RESTART", 0)),
        "elastic_env": os.environ.get("PADDLE_TPU_ELASTIC"),
        "logical_env": os.environ.get("PADDLE_TPU_ELASTIC_LOGICAL_WORLD"),
        "resumed_global": g,
        "losses": losses,
        "params": params,
    }
    tmp = out_json + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f)
    os.replace(tmp, out_json)
    return 0


def main():
    if len(sys.argv) >= 5:
        return run(sys.argv[1], sys.argv[2], sys.argv[3], sys.argv[4])
    # launcher mode: everything from the env contract
    base = os.environ["PADDLE_TPU_ELASTIC_TEST_DIR"]
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    nranks = int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    world = min(LOGICAL, 4 * nranks)  # each "host" owns 4 logical chips
    restart = int(os.environ.get("PADDLE_TPU_ELASTIC_RESTART", 0))
    return run(os.path.join(base, f"ckpt_rank{rank}"),
               os.path.join(base, f"out_rank{rank}_r{restart}.json"),
               world, int(os.environ.get("ELASTIC_TOTAL_STEPS", 4)))


if __name__ == "__main__":
    sys.exit(main())

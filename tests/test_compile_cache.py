"""Tentpole coverage for the compile-once hot path (ISSUE 1):

(a) a second run with a ragged final batch causes ZERO new traces
    (shape bucketing serves it from the compiled larger bucket);
(b) bucketed-padded execution is numerically identical to unpadded on
    per-row fetches;
(c) Prefetcher preserves batch order and re-raises worker exceptions at
    the call site;
(d) the persistent cache dir is created and populated.
"""
import os

import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers
from paddle_tpu.core import compile_cache


def _simple_program(width=4):
    # width makes the traced HLO distinct per test — JAX's compilation
    # cache has an in-memory layer keyed on the HLO alone, so tests that
    # assert on-disk population need a computation not seen earlier in
    # the process
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 6], dtype="float32")
        h = layers.fc(x, width, act="relu")
        y = layers.fc(h, 3)
        row = layers.reduce_sum(y, dim=1)  # per-row fetch [B]
    return main, startup, y, row


# -- (a) ragged final batch: zero new traces --------------------------------
def test_ragged_final_batch_zero_new_traces():
    main, startup, y, row = _simple_program()
    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(0)
    full = rng.randn(8, 6).astype(np.float32)
    with static.scope_guard(scope):
        exe.run(startup)
        # "epoch 1": steady batches of 8, ragged tail of 5
        exe.run(main, feed={"x": full}, fetch_list=[row])
        warm = exe.cache_stats()
        assert warm["traces"] == 1
        exe.run(main, feed={"x": full[:5]}, fetch_list=[row])
        # "epoch 2": same shapes again
        exe.run(main, feed={"x": full}, fetch_list=[row])
        exe.run(main, feed={"x": full[:5]}, fetch_list=[row])
    stats = exe.cache_stats()
    assert stats["traces"] == warm["traces"], stats
    assert stats["bucket_hits"] >= 2, stats
    assert stats["hits"] == 3, stats


def test_bucket_requires_matching_trailing_dims():
    # a feed with a DIFFERENT trailing dim must not be padded into the
    # wrong executable — it traces fresh.  The [-1, -1] feed here is the
    # exact shape static.check_program lints as V401 (non-leading dynamic
    # dim escapes the bucketing policy): under PADDLE_TPU_VERIFY that
    # warning is EXPECTED — this test exists to pin the retrace the lint
    # predicts.
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, -1], dtype="float32")
        s = layers.reduce_sum(x, dim=1)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": np.ones((8, 6), np.float32)},
                fetch_list=[s])
        exe.run(main, feed={"x": np.ones((5, 7), np.float32)},
                fetch_list=[s])
    stats = exe.cache_stats()
    assert stats["traces"] == 2
    assert stats["bucket_hits"] == 0


# -- (b) numerically identical fetches --------------------------------------
def test_bucketed_fetches_match_unpadded():
    main, startup, y, row = _simple_program()
    rng = np.random.RandomState(7)
    full = rng.randn(8, 6).astype(np.float32)
    ragged = full[:3]

    def run_with(policy):
        exe = static.Executor()
        exe.bucket_policy = policy
        scope = static.Scope()
        with static.scope_guard(scope):
            exe.run(startup)
            if policy != "off":
                exe.run(main, feed={"x": full}, fetch_list=[y, row])
            outs = exe.run(main, feed={"x": ragged}, fetch_list=[y, row])
        return exe, outs

    exe_b, bucketed = run_with("existing")
    exe_o, unpadded = run_with("off")
    assert exe_b.cache_stats()["bucket_hits"] == 1
    assert exe_o.cache_stats()["bucket_hits"] == 0
    for got, want in zip(bucketed, unpadded):
        assert got.shape == want.shape  # un-padding restored real batch
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_pow2_policy_cold_compiles_at_bucket():
    # inference-style policy: batch 5 cold-compiles the 8-bucket; batch 3
    # compiles its own cheaper 4-bucket (smallest sufficient pow2 wins);
    # batch 7 reuses the 8-bucket without tracing
    main, startup, y, row = _simple_program()
    exe = static.Executor()
    exe.bucket_policy = "pow2"
    scope = static.Scope()
    rng = np.random.RandomState(1)
    with static.scope_guard(scope):
        exe.run(startup)
        r5 = exe.run(main, feed={"x": rng.randn(5, 6).astype(np.float32)},
                     fetch_list=[row])
        r3 = exe.run(main, feed={"x": rng.randn(3, 6).astype(np.float32)},
                     fetch_list=[row])
        r7 = exe.run(main, feed={"x": rng.randn(7, 6).astype(np.float32)},
                     fetch_list=[row])
    assert r5[0].shape == (5,) and r3[0].shape == (3,) and \
        r7[0].shape == (7,)
    stats = exe.cache_stats()
    assert stats["traces"] == 2, stats
    assert stats["bucket_hits"] == 1, stats


def test_pow2_small_requests_do_not_ride_huge_bucket():
    # batch-16 compiled first must NOT capture a batch-3 stream (5.3x the
    # compute per request) — pow2 compiles the cheap 4-bucket instead
    main, startup, y, row = _simple_program()
    exe = static.Executor()
    exe.bucket_policy = "pow2"
    scope = static.Scope()
    rng = np.random.RandomState(2)
    with static.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": rng.randn(16, 6).astype(np.float32)},
                fetch_list=[row])
        r3 = exe.run(main, feed={"x": rng.randn(3, 6).astype(np.float32)},
                     fetch_list=[row])
    assert r3[0].shape == (3,)
    # second trace = the 4-bucket, memoized for the rest of the stream
    assert exe.cache_stats()["traces"] == 2
    _, (b, target_b) = next(iter(exe._bucket_map.values()))
    assert (b, target_b) == (3, 4)


# -- (c) Prefetcher order + exception propagation ---------------------------
def test_prefetcher_preserves_order():
    from paddle_tpu.reader import Prefetcher
    src = [{"i": np.full((2, 2), k, np.float32)} for k in range(50)]
    out = list(Prefetcher(iter(src), depth=2))
    assert len(out) == 50
    for k, feed in enumerate(out):
        assert float(np.asarray(feed["i"])[0, 0]) == k


def test_prefetcher_reraises_worker_exception_in_order():
    from paddle_tpu.reader import Prefetcher

    def source():
        yield np.zeros(2)
        yield np.ones(2)
        raise ValueError("exploded in worker")

    pf = Prefetcher(source(), depth=2)
    got = []
    with pytest.raises(ValueError, match="exploded in worker"):
        for item in pf:
            got.append(item)
    # both good batches were delivered BEFORE the error surfaced
    assert len(got) == 2


def test_prefetcher_close_unblocks_worker():
    from paddle_tpu.reader import Prefetcher

    def endless():
        k = 0
        while True:
            yield np.full(4, k)
            k += 1

    pf = Prefetcher(endless(), depth=1)
    next(pf)
    pf.close()  # must not deadlock on the full queue
    pf.close()  # idempotent


def test_prefetcher_casts_int64_when_x64_off():
    import jax
    from paddle_tpu.reader import place_feed
    placed = place_feed({"ids": np.arange(4, dtype=np.int64)})
    want = np.int64 if jax.config.jax_enable_x64 else np.int32
    assert np.asarray(placed["ids"]).dtype == want


# -- (d) persistent cache dir created and populated -------------------------
def test_persistent_cache_dir_populated(tmp_path):
    d = str(tmp_path / "xla_cache")
    assert compile_cache.initialize(d, min_compile_time_s=0.0,
                                   force=True) == d
    assert os.path.isdir(d)
    before = compile_cache.persistent_entries()
    main, startup, y, row = _simple_program(width=11)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": np.ones((4, 6), np.float32)},
                fetch_list=[row])
    assert compile_cache.persistent_entries() > before
    stats = exe.cache_stats()
    assert stats["persistent_dir"] == d
    # restore the default so later tests don't write into tmp_path
    compile_cache.initialize(force=True)


def test_initialize_disabled_sentinel(monkeypatch):
    monkeypatch.setenv(compile_cache.ENV_CACHE_DIR, "off")
    assert compile_cache.initialize(force=True) is None
    assert not compile_cache.is_enabled()
    monkeypatch.delenv(compile_cache.ENV_CACHE_DIR)
    compile_cache.initialize(force=True)


# -- executor close / cache_stats contracts ---------------------------------
def test_close_idempotent_keeps_disk_cache(tmp_path):
    d = str(tmp_path / "xla_cache2")
    compile_cache.initialize(d, min_compile_time_s=0.0, force=True)
    main, startup, y, row = _simple_program(width=13)
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed={"x": np.ones((4, 6), np.float32)},
                fetch_list=[row])
    entries = compile_cache.persistent_entries()
    assert entries > 0
    exe.close()
    exe.close()  # idempotent
    assert exe._cache == {}
    # on-disk cache untouched by close()
    assert compile_cache.persistent_entries() == entries
    # counters survive close
    assert exe.cache_stats()["traces"] == 1
    compile_cache.initialize(force=True)

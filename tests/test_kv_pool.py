"""Block-paged KV pool: COW prefix sharing, page-count admission,
planner-sized budgets (serving/kv_pool.py + the paged engine mode).

Covers the pool's own contracts (reservation accounting, refcounted
prefix sharing, copy-on-write isolation, retire-frees, leak detection),
the paged ContinuousBatchingEngine's token-equality with the fixed-slot
engine and with per-sequence generate() across admit/retire churn,
admission under page exhaustion (block + eventual completion, jittered
queue-full backpressure), and the planner sizing path (page_budget +
budget_drift)."""
import threading
import time

import numpy as np
import pytest

from paddle_tpu.serving import (PagedKVPool, PagePoolExhaustedError,
                                QueueFullError, budget_drift, metrics)
from paddle_tpu.serving.kv_pool import PageTable


def _pool(pages=16, T=4, L=2, H=2, Dh=4):
    return PagedKVPool(num_layers=L, num_heads=H, head_dim=Dh,
                       page_tokens=T, num_pages=pages)


def _rand_kv(rng, L, H, n, Dh):
    return (rng.randn(L, H, n, Dh).astype(np.float32),
            rng.randn(L, H, n, Dh).astype(np.float32))


# -- pool unit contracts ----------------------------------------------------
def test_reservation_accounting():
    pool = _pool(pages=8)
    assert pool.pages_needed(0) == 0
    assert pool.pages_needed(1) == 1
    assert pool.pages_needed(4) == 1
    assert pool.pages_needed(5) == 2
    t = pool.reserve(5)
    assert pool.pages_available == 3 and pool.pages_free == 8
    assert pool.can_reserve(3) and not pool.can_reserve(4)
    with pytest.raises(PagePoolExhaustedError):
        pool.reserve(4)
    pool.release(t)  # never opened: full reservation returns
    assert pool.pages_available == 8
    pool.assert_drained()


def test_overcharge_beyond_reservation_raises():
    pool = _pool(pages=8, T=4)
    rng = np.random.RandomState(0)
    prompt = rng.randint(2, 30, (4,)).astype(np.int64)
    k, v = _rand_kv(rng, 2, 2, 4, 4)
    table = pool.reserve(1)
    pool.open_sequence(prompt, k, v, table=table)
    kc, vc = _rand_kv(rng, 2, 2, 1, 4)
    with pytest.raises(PagePoolExhaustedError, match="reservation"):
        # position 4 needs a second page the table never reserved
        pool.append_column(table, kc[:, :, 0], vc[:, :, 0])
    pool.close_sequence(table)
    pool.assert_drained()


def test_prefix_sharing_refcounts_and_fewer_pages_than_solo():
    """Two sequences with the same prompt head occupy fewer pages than
    2x solo: full head pages are stored once and refcounted."""
    pool = _pool(pages=16, T=4)
    rng = np.random.RandomState(1)
    head = rng.randint(2, 30, (8,)).astype(np.int64)   # 2 full pages
    p1 = np.concatenate([head, [3]])
    p2 = np.concatenate([head, [5]])
    k1, v1 = _rand_kv(rng, 2, 2, 9, 4)
    solo_pages = pool.pages_needed(p1.size)            # 3
    t1 = pool.open_sequence(p1, k1, v1)
    used_solo = pool.num_pages - pool.pages_free
    assert used_solo == solo_pages
    # second sequence: identical KV on the shared head (causal determinism)
    k2 = k1.copy()
    v2 = v1.copy()
    t2 = pool.open_sequence(p2, k2, v2)
    used_both = pool.num_pages - pool.pages_free
    assert used_both == solo_pages + 1      # only the distinct tail page
    assert used_both < 2 * solo_pages
    assert pool.prefix_hits == 2 and pool.pages_shared == 2
    # retire frees: t1 closes, shared pages survive for t2
    pool.close_sequence(t1)
    assert pool.pages_shared == 0
    assert pool.num_pages - pool.pages_free == solo_pages
    ks, _ = pool.gather(t2)
    np.testing.assert_array_equal(ks[:, :, :8], k1[:, :, :8])
    pool.close_sequence(t2)
    pool.assert_drained()


def test_cow_write_copies_and_isolates_sharers():
    """Appending into a shared page copies it first: the writer gets its
    own column, every sharer's view is bitwise untouched."""
    pool = _pool(pages=16, T=4)
    rng = np.random.RandomState(2)
    prompt = rng.randint(2, 30, (6,)).astype(np.int64)  # page1 partial
    k, v = _rand_kv(rng, 2, 2, 6, 4)
    t1 = pool.open_sequence(prompt, k, v)
    t2 = pool.open_sequence(prompt, k.copy(), v.copy())
    assert pool.pages_shared == 2
    kc, vc = _rand_kv(rng, 2, 2, 1, 4)
    pool.append_column(t2, kc[:, :, 0], vc[:, :, 0])
    assert pool.cow_copies == 1
    assert t1.pages[1] != t2.pages[1]       # diverged
    assert t1.pages[0] == t2.pages[0]       # untouched full page shared
    k1g, _ = pool.gather(t1)
    np.testing.assert_array_equal(k1g, k)
    k2g, _ = pool.gather(t2)
    np.testing.assert_array_equal(k2g[:, :, :6], k)
    np.testing.assert_array_equal(k2g[:, :, 6], kc[:, :, 0])
    # second append lands in the now-exclusive copy: no further COW
    pool.append_column(t2, kc[:, :, 0], vc[:, :, 0])
    assert pool.cow_copies == 1
    pool.close_sequence(t1)
    pool.close_sequence(t2)
    pool.assert_drained()


def test_leak_assertion_fires_on_open_table():
    pool = _pool(pages=8)
    rng = np.random.RandomState(3)
    prompt = rng.randint(2, 30, (4,)).astype(np.int64)
    k, v = _rand_kv(rng, 2, 2, 4, 4)
    t = pool.open_sequence(prompt, k, v)
    with pytest.raises(AssertionError, match="page leak"):
        pool.assert_drained()
    pool.close_sequence(t)
    pool.assert_drained()


def test_freed_prefix_page_is_unregistered():
    """A retired sequence's pages leave the prefix registry: a later
    identical prompt must re-store, never alias freed storage."""
    pool = _pool(pages=4, T=4)
    rng = np.random.RandomState(4)
    prompt = rng.randint(2, 30, (4,)).astype(np.int64)
    k, v = _rand_kv(rng, 2, 2, 4, 4)
    t1 = pool.open_sequence(prompt, k, v)
    pool.close_sequence(t1)
    pool.assert_drained()
    t2 = pool.open_sequence(prompt, k, v)
    assert pool.prefix_hits == 0            # no stale hit
    pool.close_sequence(t2)
    pool.assert_drained()


def test_reservation_covers_cow_of_shared_partial_prompt_page():
    """Regression: a sequence whose own final PARTIAL prompt page gets
    prefix-shared must still afford the COW copy its first decode
    write needs — pages_for_request reserves the allowance, so the
    charge never overruns the reservation."""
    pool = _pool(pages=16, T=4)
    assert pool.pages_for_request(6, 2) == pool.pages_needed(8) + 1
    assert pool.pages_for_request(8, 2) == pool.pages_needed(10)  # full
    rng = np.random.RandomState(8)
    prompt = rng.randint(2, 30, (6,)).astype(np.int64)   # partial page
    k, v = _rand_kv(rng, 2, 2, 6, 4)
    ta = pool.reserve(pool.pages_for_request(6, 2))
    pool.open_sequence(prompt, k, v, table=ta)           # A charges 2
    tb = pool.reserve(pool.pages_for_request(6, 2))
    pool.open_sequence(prompt, k.copy(), v.copy(), table=tb)  # B shares
    col_k, col_v = _rand_kv(rng, 2, 2, 1, 4)
    # A's write hits its now-shared page: the COW charge fits in the
    # allowance instead of raising PagePoolExhaustedError
    pool.append_column(ta, col_k[:, :, 0], col_v[:, :, 0])
    pool.append_column(tb, col_k[:, :, 0], col_v[:, :, 0])
    assert pool.cow_copies == 1
    pool.close_sequence(ta)
    pool.close_sequence(tb)
    pool.assert_drained()


def test_paged_engine_survives_identical_concurrent_prompts():
    """Two identical partial-tail prompts decoding side by side (the
    duplicate-request / retry shape) must both complete token-equal —
    the COW of the shared partial page is covered by admission."""
    import paddle_tpu.dygraph as dg
    from paddle_tpu.serving import ContinuousBatchingEngine
    rng = np.random.RandomState(9)
    prompt = rng.randint(2, 30, (6,)).astype(np.int64)   # 6 % 4 != 0
    with dg.guard():
        m = _tiny_gpt()
        m.eval()
        ref = np.asarray(m.generate(prompt[None], max_length=4,
                                    decode_strategy="greedy_search")[0])
        pool = PagedKVPool(num_layers=1, num_heads=2, head_dim=8,
                           page_tokens=4, num_pages=12)
        eng = ContinuousBatchingEngine(m, max_slots=2,
                                       kv_pool=pool).start()
        try:
            futs = [eng.submit(prompt, max_length=4) for _ in range(2)]
            outs = [np.asarray(f.result(timeout=120)) for f in futs]
        finally:
            eng.stop()
    for out in outs:
        np.testing.assert_array_equal(ref, out)
    pool.assert_drained()


# -- planner sizing ---------------------------------------------------------
def test_page_budget_sizes_pool_and_detects_drift():
    from paddle_tpu.static import page_budget
    cfg = {"vocab_size": 64, "hidden_size": 32, "num_layers": 2,
           "num_heads": 2, "max_position": 128}
    plan = page_budget(config=cfg, page_tokens=16,
                       hbm_bytes=4 * 1024 * 1024, weight_bytes=0)
    assert plan["pages"] >= 1 and plan["max_slots"] >= 1
    assert plan["max_context"] <= 128
    assert plan["head_dim"] == 16
    # the budget actually fits: kv + workspace under headroomed HBM
    assert plan["kv_bytes"] + plan["workspace_bytes"] <= \
        int(4 * 1024 * 1024 * (1 - plan["headroom"]))
    pool = PagedKVPool.from_plan(plan)
    assert pool.num_pages == plan["pages"]
    assert pool.page_bytes == plan["page_bytes"]
    assert budget_drift(pool) == []         # plan-built: no drift
    # hand-resize the pool -> V504-style drift report
    pool.num_pages += 7
    drift = budget_drift(pool)
    assert drift and any("pages" in d for d in drift)
    bare = _pool()
    assert budget_drift(bare)               # no recorded plan at all


def test_budget_drift_clean_when_context_was_clamped():
    """A tiny budget clamps max_context down to the pages' reach; the
    re-derivation must use the recorded REQUESTED context, not the
    clamped one, or an untouched plan reads as drifted."""
    from paddle_tpu.static import page_budget
    cfg = {"vocab_size": 64, "hidden_size": 32, "num_layers": 2,
           "num_heads": 2, "max_position": 128}
    plan = page_budget(config=cfg, page_tokens=16, max_context=128,
                       hbm_bytes=100_000, weight_bytes=0, headroom=0.0)
    assert plan["max_context"] < plan["max_context_requested"]  # clamped
    pool = PagedKVPool.from_plan(plan)
    assert budget_drift(pool) == []
    pool.close_sequence(pool.reserve(0))  # no-op touch; still clean
    assert budget_drift(pool) == []


def test_advertised_max_context_is_always_servable():
    """Regression: every prompt shape within the plan's max_context —
    including a partial final prompt page, whose reservation carries
    the +1 COW allowance — must fit the pool, or an in-limit request
    gets a permanent 'can never fit' rejection."""
    from paddle_tpu.static import page_budget
    cfg = {"vocab_size": 64, "hidden_size": 32, "num_layers": 2,
           "num_heads": 2, "max_position": 128}
    for hbm in (100_000, 140_000, 4 * 1024 * 1024):
        plan = page_budget(config=cfg, page_tokens=16, max_context=128,
                           hbm_bytes=hbm, weight_bytes=0, headroom=0.0)
        pool = PagedKVPool.from_plan(plan)
        ctx = plan["max_context"]
        for p in (1, 15, 16, ctx - 1, ctx):   # aligned + partial shapes
            if 0 < p <= ctx:
                assert pool.pages_for_request(p, ctx - p) <= \
                    plan["pages"], (hbm, p)


def test_page_budget_refuses_impossible_budget():
    from paddle_tpu.static import page_budget
    cfg = {"vocab_size": 64, "hidden_size": 32, "num_layers": 2,
           "num_heads": 2, "max_position": 128}
    with pytest.raises(ValueError, match="not enough"):
        page_budget(config=cfg, hbm_bytes=16 * 1024, weight_bytes=0)


# -- paged engine -----------------------------------------------------------
def _tiny_gpt(vocab=30):
    from paddle_tpu.models import GPTConfig, GPTModel, GPTForGeneration
    cfg = GPTConfig(vocab_size=vocab, hidden_size=16, num_layers=1,
                    num_heads=2, max_position=32, dropout=0.0)
    return GPTForGeneration(GPTModel(cfg))


def test_paged_engine_token_equal_across_churn():
    """Greedy output through the paged engine — sequences of different
    lengths joining and retiring mid-decode, prefix sharing live —
    must match both per-sequence generate() and the fixed-slot engine
    token for token, and the drained pool must hold zero pages."""
    import paddle_tpu.dygraph as dg
    from paddle_tpu.serving import ContinuousBatchingEngine
    rng = np.random.RandomState(5)
    head = rng.randint(2, 30, (6,)).astype(np.int64)
    prompts = [rng.randint(2, 30, (n,)).astype(np.int64)
               for n in (3, 5, 2, 7)]
    prompts += [np.concatenate([head, [3]]), np.concatenate([head, [5]])]
    with dg.guard():
        m = _tiny_gpt()
        m.eval()
        refs = [m.generate(p[None], max_length=5,
                           decode_strategy="greedy_search")[0]
                for p in prompts]
        pool = PagedKVPool(num_layers=1, num_heads=2, head_dim=8,
                           page_tokens=4, num_pages=24)
        paged = ContinuousBatchingEngine(m, max_slots=2,
                                         kv_pool=pool).start()
        try:
            futs = [paged.submit(p, max_length=5) for p in prompts]
            outs = [f.result(timeout=120) for f in futs]
        finally:
            paged.stop()
        fixed = ContinuousBatchingEngine(m, max_slots=2).start()
        try:
            ffuts = [fixed.submit(p, max_length=5) for p in prompts]
            fouts = [f.result(timeout=120) for f in ffuts]
        finally:
            fixed.stop()
    for ref, out, fout in zip(refs, outs, fouts):
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
        np.testing.assert_array_equal(np.asarray(fout), np.asarray(out))
    pool.assert_drained()                   # page-leak check post-drain


def test_paged_engine_admission_blocks_then_completes():
    """A pool holding exactly one worst-case sequence serializes the
    batch: later requests wait for pages, every request still
    completes, and the admission-pressure counter registers the wait."""
    import paddle_tpu.dygraph as dg
    from paddle_tpu.serving import ContinuousBatchingEngine
    rng = np.random.RandomState(6)
    prompts = [rng.randint(2, 30, (3,)).astype(np.int64)
               for _ in range(3)]
    blocked0 = metrics.counter("kv.admit_blocked")
    with dg.guard():
        m = _tiny_gpt()
        m.eval()
        refs = [m.generate(p[None], max_length=4,
                           decode_strategy="greedy_search")[0]
                for p in prompts]
        # 3+4=7 tokens -> 2 pages of 4 + 1 COW allowance (partial
        # prompt page): the pool admits ONE sequence at a time
        pool = PagedKVPool(num_layers=1, num_heads=2, head_dim=8,
                           page_tokens=4, num_pages=3)
        eng = ContinuousBatchingEngine(m, max_slots=2,
                                       kv_pool=pool).start()
        try:
            futs = [eng.submit(p, max_length=4) for p in prompts]
            outs = [f.result(timeout=120) for f in futs]
        finally:
            eng.stop()
    for ref, out in zip(refs, outs):
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(out))
    assert metrics.counter("kv.admit_blocked") > blocked0
    pool.assert_drained()


def test_paged_engine_rejects_and_hints_retry():
    """Queue overflow answers the DynamicBatcher backpressure contract:
    QueueFullError with a jittered load-scaled retry_after_s."""
    import paddle_tpu.dygraph as dg
    from paddle_tpu.serving import ContinuousBatchingEngine
    with dg.guard():
        m = _tiny_gpt()
        eng = ContinuousBatchingEngine(
            m, max_slots=1, max_queue=0,
            kv_pool=PagedKVPool(num_layers=1, num_heads=2, head_dim=8,
                                page_tokens=4, num_pages=4)).start()
        try:
            hints = []
            for _ in range(6):
                with pytest.raises(QueueFullError) as ei:
                    eng.submit([2, 3], max_length=4)
                assert ei.value.http_status == 503
                hints.append(ei.value.retry_after_s)
        finally:
            eng.stop()
    assert all(h > 0 for h in hints)
    assert len(set(hints)) > 1              # jittered, not a constant
    # context guard: the pool's reach, not max_position, is the limit
    with dg.guard():
        m = _tiny_gpt()
        eng = ContinuousBatchingEngine(
            m, max_slots=1,
            kv_pool=PagedKVPool(num_layers=1, num_heads=2, head_dim=8,
                                page_tokens=4, num_pages=2))
        with pytest.raises(ValueError, match="max_context"):
            eng.submit(list(range(2, 12)), max_length=10)  # 20 > 8


def test_queue_expiry_of_never_fitting_request():
    """_admit_locked expires a queued request whose page demand no pool
    state could ever satisfy (reachable only if the pool shrank after
    submit) instead of letting it camp until its deadline."""
    import paddle_tpu.dygraph as dg
    from paddle_tpu.serving import ContinuousBatchingEngine
    from paddle_tpu.serving.generation import GenerationRequest
    with dg.guard():
        m = _tiny_gpt()
        pool = PagedKVPool(num_layers=1, num_heads=2, head_dim=8,
                           page_tokens=4, num_pages=8)
        eng = ContinuousBatchingEngine(m, max_slots=1, kv_pool=pool)
        req = GenerationRequest(np.asarray([2, 3], np.int64), 4,
                                "greedy_search", 0, 1.0, 0, 30.0)
        eng._queue.append(req)
        pool.num_pages = 1                  # pool "shrank" under it
        with eng._mu:
            pending = eng._admit_locked()
        assert pending == []
        with pytest.raises(ValueError, match="never fit"):
            req.future.result(timeout=0)


def test_engine_rejects_mismatched_pool_geometry():
    import paddle_tpu.dygraph as dg
    from paddle_tpu.serving import ContinuousBatchingEngine
    with dg.guard():
        m = _tiny_gpt()
        bad = PagedKVPool(num_layers=3, num_heads=2, head_dim=8,
                          page_tokens=4, num_pages=4)
        with pytest.raises(ValueError, match="geometry"):
            ContinuousBatchingEngine(m, kv_pool=bad)
        with pytest.raises(ValueError, match="kv_pool"):
            ContinuousBatchingEngine(m, kv_pool=7)


def test_paged_metrics_reach_prometheus_exposition():
    """kv.pages_* gauges and the admission counters surface through
    core.monitor.prometheus_text — the autoscaler's scrape."""
    from paddle_tpu.core.monitor import prometheus_text
    pool = _pool(pages=8)
    rng = np.random.RandomState(7)
    prompt = rng.randint(2, 30, (4,)).astype(np.int64)
    k, v = _rand_kv(rng, 2, 2, 4, 4)
    t = pool.open_sequence(prompt, k, v)
    text = prometheus_text()
    for name in ("serving_kv_pages_total", "serving_kv_pages_free",
                 "serving_kv_pages_shared"):
        assert name in text, f"{name} missing from exposition"
    pool.close_sequence(t)
    pool.assert_drained()


def test_server_stats_include_pool(tmp_path):
    """/stats carries the pool occupancy block and /metrics the kv
    gauges when a paged generator is attached."""
    import json
    import urllib.request
    import paddle_tpu.dygraph as dg
    from paddle_tpu.inference.server import InferenceServer
    from paddle_tpu.static import page_budget
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import serve_smoke
    model_dir = str(tmp_path / "m")
    serve_smoke.save_tiny_model(model_dir)
    with dg.guard():
        m = _tiny_gpt()
        m.eval()
        plan = page_budget(m, page_tokens=4,
                           hbm_bytes=2 * 1024 * 1024)
        srv = InferenceServer(model_dir, generator=m, gen_kv_pool=plan,
                              gen_slots=2)
        srv.start()
        try:
            base = f"http://{srv.host}:{srv.port}"
            body = json.dumps({"input_ids": [[2, 3, 4]],
                               "max_length": 4}).encode()
            req = urllib.request.Request(
                base + "/generate", data=body,
                headers={"Content-Type": "application/json"})
            out = json.loads(urllib.request.urlopen(req, timeout=60)
                             .read())
            assert out["output_ids"] and len(out["output_ids"][0]) >= 4
            stats = json.loads(urllib.request.urlopen(
                base + "/stats", timeout=10).read())
            kvs = stats["kv_pool"]
            assert kvs["pages_total"] == plan["pages"]
            assert kvs["pages_free"] == plan["pages"]   # drained
            text = urllib.request.urlopen(
                base + "/metrics", timeout=10).read().decode()
            assert "serving_kv_pages_total" in text
            assert "serving_gen_queue_depth" in text
        finally:
            srv.stop()
        srv.engine.kv_pool.assert_drained()


# -- tp-sharded page budgets ------------------------------------------------

def test_page_budget_tp_divides_per_chip_charges():
    """tp=2 halves the per-chip page bytes and the Megatron-splittable
    weight charge, so the SAME per-chip HBM budget carves strictly more
    pages — while page counts, contexts, and table geometry stay GLOBAL
    (tables are host-side and replicated)."""
    from paddle_tpu.static import page_budget
    cfg = {"vocab_size": 64, "hidden_size": 32, "num_layers": 2,
           "num_heads": 4, "max_position": 128}
    hbm = 256 * 1024
    p1 = page_budget(config=cfg, page_tokens=16, max_context=128,
                     hbm_bytes=hbm)
    p2 = page_budget(config=cfg, page_tokens=16, max_context=128,
                     hbm_bytes=hbm, tp_degree=2)
    assert p2["tp_degree"] == 2
    assert p2["page_bytes_per_chip"] * 2 == p2["page_bytes"]
    assert p2["page_bytes"] == p1["page_bytes"]      # global geometry
    assert p2["pages"] > p1["pages"]
    assert p2["weight_bytes_per_chip"] < p1["weight_bytes_per_chip"]
    pool = PagedKVPool.from_plan(p2)
    assert pool.tp_degree == 2
    assert pool.page_bytes_per_chip * 2 == pool.page_bytes
    assert budget_drift(pool) == []                  # tp plan re-derives
    stats = pool.stats()
    assert stats["tp_degree"] == 2
    assert stats["page_bytes_per_chip"] == pool.page_bytes_per_chip


def test_page_budget_tp_rejects_unsplittable_heads():
    from paddle_tpu.static import page_budget
    cfg = {"vocab_size": 64, "hidden_size": 33, "num_layers": 2,
           "num_heads": 3, "max_position": 128}
    with pytest.raises(ValueError, match="head dim"):
        page_budget(config=cfg, hbm_bytes=1 << 20, tp_degree=2)


def test_page_budget_tp_charges_sharded_draft():
    """The speculative draft's weights and per-slot dense KV shard on
    heads with the target: at tp=2 the per-chip draft charge halves
    (global draft bytes stay put — tables and token geometry are
    global), so the same budget with a draft carves more pages."""
    from paddle_tpu.static import page_budget
    cfg = {"vocab_size": 64, "hidden_size": 32, "num_layers": 4,
           "num_heads": 4, "max_position": 128}
    hbm = 4 * 1024 * 1024
    p1 = page_budget(config=cfg, page_tokens=16, max_context=128,
                     hbm_bytes=hbm, draft_layers=2)
    p2 = page_budget(config=cfg, page_tokens=16, max_context=128,
                     hbm_bytes=hbm, draft_layers=2, tp_degree=2)
    assert p2["draft_weight_bytes"] == p1["draft_weight_bytes"]
    assert p2["pages"] > p1["pages"]
    # the draft's dense per-slot KV rides the workspace: per slot the
    # tp=2 charge must be under the tp=1 charge (heads shard)
    ws1 = p1["workspace_bytes"] // p1["max_slots"]
    ws2 = p2["workspace_bytes"] // p2["max_slots"]
    assert ws2 < ws1

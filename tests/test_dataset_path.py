"""Industrial dataset path (C19): MultiSlot parsing, InMemoryDataset
shuffles, QueueDataset streaming, train_from_dataset hot loop (reference
fluid/dataset.py, framework/data_feed.h:302, data_set.h:101,
executor.py:1345 train_from_dataset)."""
import os

import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers
from paddle_tpu.distributed import DatasetFactory


def _write_multislot(path, n=64, seed=0, ids_len=4):
    """Each line: sparse id slot (<ids_len> ids) + dense slot (2 floats) +
    label slot (1 float)."""
    rng = np.random.RandomState(seed)
    with open(path, "w") as f:
        for _ in range(n):
            ids = rng.randint(0, 50, ids_len)
            dense = rng.rand(2)
            label = float(dense.mean())  # learnable from the dense slot
            parts = ([str(ids_len)] + [str(i) for i in ids]
                     + ["2"] + [f"{v:.4f}" for v in dense]
                     + ["1", f"{label:.4f}"])
            f.write(" ".join(parts) + "\n")


def _ctr_program():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ids = layers.data("ids", [-1, 4], dtype="int64")
        dense = layers.data("dense", [-1, 2])
        label = layers.data("label", [-1, 1])
        emb = layers.embedding(ids, size=[50, 8], is_sparse=True)
        pooled = layers.reduce_sum(emb, dim=1)
        feat = layers.concat([pooled, dense], axis=1)
        pred = layers.fc(feat, size=1, act="sigmoid")
        loss = layers.mean(
            layers.square(layers.elementwise_sub(pred, label)))
        static.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def test_multislot_parse():
    from paddle_tpu.distributed import MultiSlotDataFeed
    feed = MultiSlotDataFeed(["ids", "dense"], ["int64", "float32"])
    rec = feed.parse_line("3 7 8 9 2 0.5 1.5")
    np.testing.assert_array_equal(rec[0], [7, 8, 9])
    np.testing.assert_allclose(rec[1], [0.5, 1.5])
    with pytest.raises(ValueError):
        feed.parse_line("5 1 2")


def test_in_memory_dataset_train(tmp_path):
    f1, f2 = str(tmp_path / "a.txt"), str(tmp_path / "b.txt")
    _write_multislot(f1, 64, seed=1)
    _write_multislot(f2, 64, seed=2)
    main, startup, loss = _ctr_program()

    ds = DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(16)
    ds.set_thread(2)
    ds.set_filelist([f1, f2])
    with static.program_guard(main, startup):
        ds.set_use_var([main.global_block().var(n)
                        for n in ("ids", "dense", "label")])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 128
    ds.local_shuffle()

    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        first = exe.train_from_dataset(main, ds, fetch_list=[loss])
        l0 = float(np.asarray(first[0]))
        for _ in range(4):
            ds.local_shuffle()
            last = exe.train_from_dataset(main, ds, fetch_list=[loss])
        l1 = float(np.asarray(last[0]))
    assert np.isfinite(l0) and np.isfinite(l1)
    assert l1 < l0, (l0, l1)
    ds.release_memory()
    assert ds.get_memory_data_size() == 0


def test_global_shuffle_partitions(tmp_path):
    f1 = str(tmp_path / "g.txt")
    _write_multislot(f1, 100, seed=3)
    main, startup, _ = _ctr_program()

    class _FleetStub:
        def __init__(self, rank, n):
            self._r, self._n = rank, n

        def worker_index(self):
            return self._r

        def worker_num(self):
            return self._n

    sizes = []
    for rank in range(4):
        ds = DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(10)
        ds.set_filelist([f1])
        with static.program_guard(main, startup):
            ds.set_use_var([main.global_block().var(n)
                            for n in ("ids", "dense", "label")])
        ds.load_into_memory()
        ds.global_shuffle(fleet=_FleetStub(rank, 4))
        sizes.append(ds.get_shuffle_data_size())
    assert sum(sizes) == 100          # exact partition, no loss/duplication
    assert all(s > 0 for s in sizes)  # hash spreads across trainers


def test_queue_dataset_streams_and_refuses_shuffle(tmp_path):
    f1 = str(tmp_path / "q.txt")
    _write_multislot(f1, 32, seed=4)
    main, startup, loss = _ctr_program()
    ds = DatasetFactory().create_dataset("QueueDataset")
    ds.set_batch_size(8)
    ds.set_filelist([f1])
    with static.program_guard(main, startup):
        ds.set_use_var([main.global_block().var(n)
                        for n in ("ids", "dense", "label")])
    with pytest.raises(NotImplementedError):
        ds.local_shuffle()
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        out = exe.train_from_dataset(main, ds, fetch_list=[loss])
    assert np.isfinite(float(np.asarray(out[0])))


def test_pipe_command_refused(tmp_path):
    f1 = str(tmp_path / "p.txt")
    _write_multislot(f1, 4)
    main, startup, _ = _ctr_program()
    ds = DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_filelist([f1])
    ds.set_pipe_command("cat")
    with static.program_guard(main, startup):
        ds.set_use_var([main.global_block().var(n)
                        for n in ("ids", "dense", "label")])
    with pytest.raises(NotImplementedError):
        ds.load_into_memory()
    with pytest.raises(ValueError):
        DatasetFactory().create_dataset("NoSuchDataset")


def test_chunked_dataset_train_matches_per_step(tmp_path):
    """FLAGS_dataset_chunk_steps batches same-shape steps into one
    scanned dispatch (Executor.run_steps); the training trajectory must
    match the per-step path exactly (same data order, no shuffle)."""
    from paddle_tpu.core.flags import set_flags
    f1 = str(tmp_path / "c.txt")
    _write_multislot(f1, 64, seed=5)

    def run(chunk):
        main, startup, loss = _ctr_program()
        ds = DatasetFactory().create_dataset("QueueDataset")
        ds.set_batch_size(16)  # 64 rows -> 4 uniform batches
        ds.set_filelist([f1])
        with static.program_guard(main, startup):
            ds.set_use_var([main.global_block().var(n)
                            for n in ("ids", "dense", "label")])
        exe = static.Executor()
        scope = static.Scope()
        set_flags({"FLAGS_dataset_chunk_steps": chunk})
        try:
            with static.scope_guard(scope):
                exe.run(startup)
                for _ in range(3):
                    last = exe.train_from_dataset(main, ds,
                                                  fetch_list=[loss])
        finally:
            set_flags({"FLAGS_dataset_chunk_steps": 1})
        return float(np.asarray(last[0]))

    l_per_step = run(1)
    l_chunked = run(4)
    assert np.isfinite(l_chunked)
    np.testing.assert_allclose(l_chunked, l_per_step, rtol=1e-5)

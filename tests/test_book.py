"""Book-test tier — the reference's end-to-end convergence suite
(/root/reference/python/paddle/fluid/tests/book/): word2vec,
understand_sentiment (LSTM), machine_translation (rnn encoder-decoder),
recommender_system, label_semantic_roles (CRF).  Each builds a model with
the fluid-style static API, trains a few iterations on synthetic learnable
data, asserts the loss decreases, and (word2vec) round-trips through
save/load_inference_model."""
import os

import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers


def _train(main, startup, feeds_fn, loss, iters=30, fetch_extra=()):
    exe = static.Executor()
    scope = static.Scope()
    losses, extras = [], []
    with static.scope_guard(scope):
        exe.run(startup)
        for i in range(iters):
            feed = feeds_fn(i)
            out = exe.run(main, feed=feed,
                          fetch_list=[loss, *fetch_extra])
            losses.append(float(np.asarray(out[0])))
            if fetch_extra:
                extras.append([np.asarray(o) for o in out[1:]])
    return losses, extras, scope


def test_word2vec(tmp_path):
    """book/test_word2vec.py: N-gram next-word prediction; plus an
    inference-model save/load round trip."""
    vocab, emb_dim, ctx_n = 50, 16, 4
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        ctx = layers.data("ctx", [-1, ctx_n], dtype="int64")
        nxt = layers.data("next", [-1, 1], dtype="int64")
        e = layers.embedding(ctx, size=[vocab, emb_dim])          # [b,4,e]
        flat = layers.reshape(e, [-1, ctx_n * emb_dim])
        h = layers.fc(flat, size=64, act="relu")
        logits = layers.fc(h, size=vocab)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, nxt))
        static.Adam(learning_rate=5e-3).minimize(loss)

    rng = np.random.RandomState(0)

    def feeds(i):
        c = rng.randint(0, vocab, (32, ctx_n)).astype(np.int64)
        n = c[:, :1].astype(np.int64)  # next word = first context word
        return {"ctx": c, "next": n}

    losses, _, scope = _train(main, startup, feeds, loss, iters=60)
    assert losses[-1] < losses[0] * 0.8, losses

    # save + reload the inference program, predictions must match
    from paddle_tpu.io import save_inference_model, load_inference_model
    exe = static.Executor()
    path = str(tmp_path / "w2v")
    with static.scope_guard(scope):
        save_inference_model(path, ["ctx"], [logits], exe,
                             main_program=main)
        feed = feeds(999)
        ref = np.asarray(exe.run(main, feed=feed, fetch_list=[logits])[0])
        prog2, feed_names, fetch_vars = load_inference_model(path, exe)
        got = np.asarray(
            exe.run(prog2, feed={feed_names[0]: feed["ctx"]},
                    fetch_list=fetch_vars)[0])
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-5)


def test_understand_sentiment_lstm():
    """book/test_understand_sentiment.py (stacked-LSTM variant, one layer)."""
    vocab, emb_dim, hid, seq = 30, 16, 16, 8
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        words = layers.data("words", [-1, seq], dtype="int64")
        label = layers.data("label", [-1, 1], dtype="int64")
        emb = layers.embedding(words, size=[vocab, emb_dim])
        gates = layers.fc(emb, size=4 * hid, num_flatten_dims=2)
        h, _c = layers.dynamic_lstm(gates, size=4 * hid)
        pooled = layers.sequence_pool(h, "max")
        logits = layers.fc(pooled, size=2)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        static.Adam(learning_rate=5e-3).minimize(loss)

    rng = np.random.RandomState(1)

    def feeds(i):
        w = rng.randint(0, vocab, (16, seq)).astype(np.int64)
        y = (w[:, 0] < vocab // 2).astype(np.int64)[:, None]
        return {"words": w, "label": y}

    losses, _, _ = _train(main, startup, feeds, loss, iters=40,
                          fetch_extra=())
    assert losses[-1] < losses[0] * 0.9, losses


def test_understand_sentiment_conv():
    """book/test_understand_sentiment.py (convolution_net variant):
    embedding → sequence_conv_pool text-CNN → classifier."""
    import paddle_tpu.static.nets as nets
    vocab, emb_dim, seq = 30, 16, 8
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        words = layers.data("words", [-1, seq], dtype="int64")
        label = layers.data("label", [-1, 1], dtype="int64")
        emb = layers.embedding(words, size=[vocab, emb_dim])
        conv3 = nets.sequence_conv_pool(emb, num_filters=16, filter_size=3,
                                        act="tanh")
        conv4 = nets.sequence_conv_pool(emb, num_filters=16, filter_size=4,
                                        act="tanh")
        logits = layers.fc(layers.concat([conv3, conv4], axis=1), size=2)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        static.Adam(learning_rate=5e-3).minimize(loss)

    rng = np.random.RandomState(5)

    def feeds(i):
        w = rng.randint(0, vocab, (16, seq)).astype(np.int64)
        # sentiment = presence of the "good" token anywhere in the text —
        # the bag-of-ngrams signal a text-CNN with max pooling captures
        y = np.any(w == 0, axis=1).astype(np.int64)[:, None]
        return {"words": w, "label": y}

    losses, _, _ = _train(main, startup, feeds, loss, iters=60)
    assert losses[-1] < np.mean(losses[:5]) * 0.8, losses


def test_machine_translation_rnn_encoder_decoder():
    """book/test_rnn_encoder_decoder.py: GRU encoder, teacher-forced GRU
    decoder conditioned on the encoder summary; learn to copy the source."""
    vocab, emb_dim, hid, seq = 20, 16, 16, 6
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        src = layers.data("src", [-1, seq], dtype="int64")
        tgt_in = layers.data("tgt_in", [-1, seq], dtype="int64")
        tgt_out = layers.data("tgt_out", [-1, seq, 1], dtype="int64")
        # encoder
        semb = layers.embedding(src, size=[vocab, emb_dim])
        egate = layers.fc(semb, size=3 * hid, num_flatten_dims=2)
        enc = layers.dynamic_gru(egate, size=hid)
        ctx = layers.sequence_pool(enc, "last")                   # [b, hid]
        # decoder: context concatenated to every target step
        temb = layers.embedding(tgt_in, size=[vocab, emb_dim])
        ctx_t = layers.expand(layers.unsqueeze(ctx, [1]), [1, seq, 1])
        dec_in = layers.concat([temb, ctx_t], axis=2)
        dgate = layers.fc(dec_in, size=3 * hid, num_flatten_dims=2)
        dec = layers.dynamic_gru(dgate, size=hid)
        logits = layers.fc(dec, size=vocab, num_flatten_dims=2)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, tgt_out))
        static.Adam(learning_rate=1e-2).minimize(loss)

    rng = np.random.RandomState(2)

    def feeds(i):
        s = rng.randint(2, vocab, (16, seq)).astype(np.int64)
        ti = np.concatenate([np.ones((16, 1), np.int64), s[:, :-1]], axis=1)
        return {"src": s, "tgt_in": ti, "tgt_out": s[..., None]}

    losses, _, _ = _train(main, startup, feeds, loss, iters=80)
    assert losses[-1] < losses[0] * 0.85, losses


def test_recommender_system():
    """book/test_recommender_system.py: embed user & item ids, cos_sim
    scaled to the rating range, square loss."""
    n_users, n_items, dim = 40, 60, 16
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        uid = layers.data("uid", [-1, 1], dtype="int64")
        iid = layers.data("iid", [-1, 1], dtype="int64")
        rating = layers.data("rating", [-1, 1])
        uvec = layers.reshape(
            layers.embedding(uid, size=[n_users, dim]), [-1, dim])
        ivec = layers.reshape(
            layers.embedding(iid, size=[n_items, dim]), [-1, dim])
        uvec = layers.fc(uvec, size=dim, act="relu")
        ivec = layers.fc(ivec, size=dim, act="relu")
        sim = layers.cos_sim(uvec, ivec)
        pred = layers.scale(sim, scale=5.0)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred,
                                                                rating)))
        static.Adam(learning_rate=1e-2).minimize(loss)

    rng = np.random.RandomState(3)
    u_lat = rng.randn(n_users, 4)
    i_lat = rng.randn(n_items, 4)

    def feeds(i):
        u = rng.randint(0, n_users, (32, 1)).astype(np.int64)
        it = rng.randint(0, n_items, (32, 1)).astype(np.int64)
        r = np.clip((u_lat[u[:, 0]] * i_lat[it[:, 0]]).sum(1), -5, 5)
        return {"uid": u, "iid": it,
                "rating": r.astype(np.float32)[:, None]}

    losses, _, _ = _train(main, startup, feeds, loss, iters=60)
    assert losses[-1] < losses[0] * 0.8, losses


def test_label_semantic_roles_crf():
    """book/test_label_semantic_roles.py: emission net + linear-chain CRF
    log-likelihood loss, viterbi decode via crf_decoding."""
    vocab, n_tags, seq = 25, 5, 6
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        words = layers.data("words", [-1, seq], dtype="int64")
        tags = layers.data("tags", [-1, seq], dtype="int64")
        length = layers.data("length", [-1], dtype="int64")
        emb = layers.embedding(words, size=[vocab, 16])
        feat = layers.fc(emb, size=n_tags, num_flatten_dims=2)
        ll = layers.linear_chain_crf(feat, tags,
                                     param_attr=static.ParamAttr(
                                         name="crf_w"),
                                     length=length)
        loss = layers.mean(ll)
        decoded = layers.crf_decoding(
            feat, param_attr=static.ParamAttr(name="crf_w"), length=length)
        static.SGD(learning_rate=5e-2).minimize(loss)

    rng = np.random.RandomState(4)

    def feeds(i):
        w = rng.randint(0, vocab, (8, seq)).astype(np.int64)
        t = (w % n_tags).astype(np.int64)
        ln = np.full((8,), seq, np.int64)
        return {"words": w, "tags": t, "length": ln}

    losses, extras, _ = _train(main, startup, feeds, loss, iters=40,
                               fetch_extra=(decoded,))
    assert losses[-1] < losses[0] * 0.9, losses
    # decode returns a tag path with the right shape
    assert extras[-1][0].shape[0] == 8


def test_image_classification_conv_static():
    """book/test_image_classification.py analog: conv net on
    CIFAR-shaped [3, 32, 32] images through the STATIC graph path
    (conv -> batch_norm -> relu -> pool stack + fc head); memorizes a
    fixed separable batch."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        img = layers.data("img", [-1, 3, 32, 32])
        label = layers.data("label", [-1, 1], dtype="int64")
        h = img
        for nf in (8, 16):
            h = layers.conv2d(h, num_filters=nf, filter_size=3,
                              padding=1)
            h = layers.batch_norm(h, act="relu")
            h = layers.pool2d(h, pool_size=2, pool_stride=2,
                              pool_type="max")
        h = layers.reshape(h, [-1, 16 * 8 * 8])
        h = layers.fc(h, 32, act="relu")
        pred = layers.fc(h, 10, act="softmax")
        loss = layers.mean(layers.cross_entropy(pred, label))
        acc = layers.accuracy(pred, label)
        static.Adam(learning_rate=2e-3).minimize(loss)

    rng = np.random.RandomState(0)
    B = 16
    # separable synthetic "cifar": class k brightens channel k%3 in a
    # class-specific quadrant
    imgs = rng.rand(B, 3, 32, 32).astype(np.float32)
    ys = rng.randint(0, 10, (B, 1)).astype(np.int64)
    for i in range(B):
        k = int(ys[i, 0])
        imgs[i, k % 3, (k // 3) * 8:(k // 3) * 8 + 8] += 2.0

    losses, extras, _ = _train(main, startup, lambda i: {
        "img": imgs, "label": ys}, loss, iters=40, fetch_extra=(acc,))
    assert losses[-1] < losses[0] * 0.5, losses[::10]
    assert float(np.asarray(extras[-1][0]).ravel()[0]) > 0.8, extras[-1]

"""Tensor parallelism (distributed/tensor_parallel.py): Megatron col/row
parallel fc over a dp×tp mesh must train EXACTLY like the equivalent plain
fc network on one device — weights shard over tp, activations re-replicate
at block boundaries, grads of replicated params stay in sync.

Also the home of the V6xx layout mutation matrix (ISSUE 12): every
seeded defect class against the sharding-propagation analyzer
(static/layout_analysis.py) must fire its stable code with op
provenance."""
import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.static import layers


def _need_devices(n):
    import jax
    if len(jax.devices()) < n:
        pytest.skip(f"needs {n} devices")


def _const_attrs(w_val, b_val):
    return (static.ParamAttr(initializer=static.Constant(w_val)),
            static.ParamAttr(initializer=static.Constant(b_val)))


def _build_plain():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        w1, b1 = _const_attrs(0.12, 0.01)
        h = layers.fc(x, 16, act="relu", param_attr=w1, bias_attr=b1)
        w2, b2 = _const_attrs(0.07, 0.0)
        pred = layers.fc(h, 1, param_attr=w2, bias_attr=b2)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def _build_tp():
    from paddle_tpu.distributed.tensor_parallel import (col_parallel_fc,
                                                        row_parallel_fc)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        w1, b1 = _const_attrs(0.12, 0.01)
        h = col_parallel_fc(x, 16, act="relu", param_attr=w1,
                            bias_attr=b1)
        w2, b2 = _const_attrs(0.07, 0.0)
        pred = row_parallel_fc(h, 1, param_attr=w2, bias_attr=b2)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def _batches(n=5):
    rng = np.random.RandomState(7)
    return [(rng.rand(16, 8).astype(np.float32),
             rng.rand(16, 1).astype(np.float32)) for _ in range(n)]


def _train(main, startup, loss, compiled=None):
    exe = static.Executor()
    scope = static.Scope()
    out = []
    with static.scope_guard(scope):
        exe.run(startup)
        target = compiled if compiled is not None else main
        for xb, yb in _batches():
            (lv,) = exe.run(target, feed={"x": xb, "y": yb},
                            fetch_list=[loss])
            out.append(float(np.asarray(lv)))
    return out, scope


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_matches_single_device(tp):
    _need_devices(8)
    from paddle_tpu.distributed.compiled_program import (CompiledProgram,
                                                         BuildStrategy)
    single, _ = _train(*_build_plain())

    main, startup, loss = _build_tp()
    bs = BuildStrategy()
    bs.tensor_parallel_degree = tp
    cp = CompiledProgram(main).with_data_parallel(loss_name=loss.name,
                                                 build_strategy=bs)
    par, scope = _train(main, startup, loss, compiled=cp)
    np.testing.assert_allclose(single, par, rtol=2e-4, atol=1e-5)

    # scope keeps GLOBAL param shapes (shard_map splits/reassembles)
    for v in main.all_parameters():
        arr = np.asarray(scope.get(v.name))
        assert arr.shape == tuple(v.shape), (v.name, arr.shape, v.shape)


def test_tp_4x2_mesh_matches_serial_1e6():
    """The acceptance run: an 8-device 4×2 dp × tp mesh training the
    col→row fc pair must match the serial fc network allclose 1e-6 —
    the layout the analyzer certifies is the layout the mesh executes."""
    _need_devices(8)
    from paddle_tpu.distributed.compiled_program import (CompiledProgram,
                                                         BuildStrategy)
    single, _ = _train(*_build_plain())

    main, startup, loss = _build_tp()
    bs = BuildStrategy()
    bs.tensor_parallel_degree = 2
    cp = CompiledProgram(main).with_data_parallel(loss_name=loss.name,
                                                 build_strategy=bs)
    assert dict(cp._get_mesh().shape) == {"dp": 4, "tp": 2}
    par, scope = _train(main, startup, loss, compiled=cp)
    np.testing.assert_allclose(single, par, rtol=1e-6, atol=1e-6)

    # and the analyzer agrees this program is layout-clean on that mesh
    layout = static.propagate_shardings(main,
                                        mesh_shape={"dp": 4, "mp": 2})
    assert not layout.diagnostics, layout.codes()


# ---------------------------------------------------------------------------
# V6xx mutation matrix: every seeded defect class fires its stable code
# with op provenance (static/layout_analysis.py)
# ---------------------------------------------------------------------------
MESH_4x2 = {"dp": 4, "mp": 2}


def _codes(layout):
    return {d.code for d in layout.diagnostics}


def _assert_provenance(diag):
    assert diag.op_type is not None, diag
    assert diag.op_uid is not None, diag
    assert diag.var is not None, diag


def test_layout_mutation_dropped_allreduce_V602():
    """Drop the row-parallel mp_allreduce_sum: the partial products are
    read as if complete — the classic silent-garbage tp bug."""
    main, _, _ = _build_tp()
    for op in main.global_block().ops:
        if op.type == "mp_allreduce_sum":
            op.type = "assign"
            op.attrs.pop("ring_id", None)
    layout = static.propagate_shardings(main, mesh_shape=MESH_4x2)
    hits = [d for d in layout.diagnostics if d.code == "V602"]
    assert hits, layout.codes()
    _assert_provenance(hits[0])
    assert hits[0].var.startswith("row_parallel_fc"), hits[0]


def test_layout_mutation_swapped_col_row_V601():
    """Row-parallel fc first (fed the replicated feed): each rank would
    contract the FULL input against its weight shard and the reduction
    double-counts."""
    from paddle_tpu.distributed.tensor_parallel import (col_parallel_fc,
                                                        row_parallel_fc)
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = row_parallel_fc(x, 16, act="relu", tp_degree=2)
        pred = col_parallel_fc(h, 2, tp_degree=2)
        loss = layers.mean(layers.square(layers.elementwise_sub(
            layers.reduce_sum(pred, dim=[1], keep_dim=True), y)))
        static.SGD(learning_rate=0.05).minimize(loss)
    layout = static.propagate_shardings(main, mesh_shape=MESH_4x2)
    hits = [d for d in layout.diagnostics if d.code == "V601"]
    assert hits, layout.codes()
    _assert_provenance(hits[0])
    assert hits[0].op_type == "mul", hits[0]


def test_layout_mutation_misrung_collective_V604():
    """Re-ring the Megatron g onto ring 0 (the dp world): the reduction
    completes over the wrong device group while the mp partial sum is
    never finished."""
    main, _, _ = _build_tp()
    for op in main.global_block().ops:
        if op.type == "mp_allreduce_sum":
            op.attrs["ring_id"] = 0
    layout = static.propagate_shardings(main, mesh_shape=MESH_4x2)
    hits = [d for d in layout.diagnostics if d.code == "V604"]
    assert hits, layout.codes()
    _assert_provenance(hits[0])
    assert hits[0].op_type == "mp_allreduce_sum", hits[0]


def test_layout_mutation_indivisible_degree_V605():
    """tp degree ∤ feature dim: the 16-wide column split cannot divide
    a degree-3 mesh."""
    main, _, _ = _build_tp()
    layout = static.propagate_shardings(main, mesh_shape={"dp": 2,
                                                          "mp": 3})
    hits = [d for d in layout.diagnostics if d.code == "V605"]
    assert hits, layout.codes()
    assert any(d.var == "col_parallel_fc_0.w_0" or
               d.var.startswith("col_parallel_fc") for d in hits), hits
    assert all(d.var is not None for d in hits)


def test_layout_mutation_redundant_gather_V603():
    """A c_concat gather of a propagation-proven-replicated var: the
    program pays (g-1)× wire for a reshard it does not need."""
    from paddle_tpu.core.program import OpDesc
    from paddle_tpu.distributed.tensor_parallel import TP_RING_ID
    main, _, _ = _build_tp()
    blk = main.global_block()
    blk.create_var(name="useless_gather", dtype="float32")
    blk.ops.append(OpDesc("c_concat", {"X": ["x"]},
                          {"Out": ["useless_gather"]},
                          {"ring_id": TP_RING_ID,
                           "op_uid": main._next_uid()}))
    layout = static.propagate_shardings(main, mesh_shape=MESH_4x2)
    hits = [d for d in layout.diagnostics if d.code == "V603"]
    assert hits, layout.codes()
    _assert_provenance(hits[0])
    assert hits[0].op_type == "c_concat", hits[0]


def test_tp_builders_recorded_in_registry():
    """The builders register themselves in the applied-passes registry
    (pass 'tensor_parallel') and stamp their ops with mp_axis/tp_degree
    so the analyzers see tp structure instead of anonymous ops."""
    from paddle_tpu.core.pass_framework import applied_passes
    main, _, _ = _build_tp()
    entries = [e for e in applied_passes(main)
               if e["pass"] == "tensor_parallel"]
    builders = {e["builder"] for e in entries}
    assert builders == {"col_parallel_fc", "row_parallel_fc"}, entries
    stamped = [op for op in main.global_block().ops
               if op.attrs.get("mp_axis") == "mp"]
    types = {op.type for op in stamped}
    assert "mp_allreduce_sum" in types and "c_identity" in types and \
        "mul" in types, types


def test_tp_annotations_and_ops():
    from paddle_tpu.distributed.tensor_parallel import TP_RING_ID
    main, startup, loss = _build_tp()
    block = main.global_block()
    types = [op.type for op in block.ops]
    assert "c_identity" in types and "mp_allreduce_sum" in types
    cid = next(op for op in block.ops if op.type == "c_identity")
    assert cid.attrs["ring_id"] == TP_RING_ID
    sharded = [v for v in main.all_parameters()
               if v.attrs.get("dist_attr")]
    assert len(sharded) == 3  # col w (dim1) + col b (dim0) + row w (dim0)
    dims = {tuple(v.attrs["dist_attr"]) for v in sharded}
    assert dims == {("tp", 1), ("tp", 0)}


def test_tp_dist_attr_survives_serialization():
    from paddle_tpu.core.program import Program
    main, _, _ = _build_tp()
    for fmt in ("json", "proto"):
        clone = Program.parse_from_string(
            main.serialize_to_string(format=fmt))
        sharded = {v.name: v.attrs.get("dist_attr")
                   for v in clone.all_parameters()
                   if v.attrs.get("dist_attr")}
        assert len(sharded) == 3, (fmt, sharded)


def test_tp_program_correct_under_plain_dp():
    """A TP-annotated program run WITHOUT a tp axis must degrade to plain
    (correct) execution: weights stay unsharded and the Megatron
    collectives become identities — not dp-wide psums."""
    _need_devices(2)
    from paddle_tpu.distributed.compiled_program import CompiledProgram
    single, _ = _train(*_build_plain())
    main, startup, loss = _build_tp()
    cp = CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    par, _ = _train(main, startup, loss, compiled=cp)
    np.testing.assert_allclose(single, par, rtol=2e-4, atol=1e-5)


def test_tp_and_sp_exclusive():
    from paddle_tpu.distributed.compiled_program import (CompiledProgram,
                                                         BuildStrategy)
    main, _, loss = _build_tp()
    bs = BuildStrategy()
    bs.tensor_parallel_degree = 2
    bs.sequence_parallel_degree = 2
    cp = CompiledProgram(main).with_data_parallel(loss_name=loss.name,
                                                 build_strategy=bs)
    with pytest.raises(NotImplementedError):
        cp._get_mesh()


def test_parallel_attention_matches_single_device():
    """Megatron parallel attention at tp=2 must equal the identical
    single-device attention graph — weights are overwritten post-startup
    with the SAME seeded global arrays in both runs, so a head/column
    mis-slicing would show up immediately."""
    _need_devices(8)
    from paddle_tpu.distributed.compiled_program import (CompiledProgram,
                                                         BuildStrategy)
    from paddle_tpu.distributed.tensor_parallel import parallel_attention
    import paddle_tpu.static.nets as nets

    HID, HEADS, T = 16, 4, 6

    def build_plain():
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = layers.data("x", [-1, T, HID])
            y = layers.data("y", [-1, T, HID])
            q = layers.fc(x, HID, num_flatten_dims=2)
            k = layers.fc(x, HID, num_flatten_dims=2)
            v = layers.fc(x, HID, num_flatten_dims=2)
            ctx = nets.scaled_dot_product_attention(q, k, v,
                                                    num_heads=HEADS)
            out = layers.fc(ctx, HID, num_flatten_dims=2)
            loss = layers.mean(layers.square(
                layers.elementwise_sub(out, y)))
            static.SGD(learning_rate=0.05).minimize(loss)
        return main, startup, loss

    def build_tp(tp):
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            x = layers.data("x", [-1, T, HID])
            y = layers.data("y", [-1, T, HID])
            out = parallel_attention(x, HID, HEADS, tp_degree=tp)
            loss = layers.mean(layers.square(
                layers.elementwise_sub(out, y)))
            static.SGD(learning_rate=0.05).minimize(loss)
        return main, startup, loss

    def seeded_weights(program):
        # same global arrays by position (plain and tp have matching
        # parameter orders: q w,b / k w,b / v w,b / out w,b)
        ws = {}
        for i, p in enumerate(program.all_parameters()):
            rng = np.random.RandomState(100 + i)
            ws[p.name] = (rng.rand(*p.shape).astype(np.float32) - 0.5) * 0.4
        return ws

    rng = np.random.RandomState(3)
    batches = [(rng.rand(8, T, HID).astype(np.float32),
                rng.rand(8, T, HID).astype(np.float32))
               for _ in range(4)]

    def run(main, startup, loss, compiled=None):
        exe = static.Executor()
        scope = static.Scope()
        out = []
        with static.scope_guard(scope):
            exe.run(startup)
            for name, arr in seeded_weights(main).items():
                scope.set(name, arr)
            target = compiled if compiled is not None else main
            for xb, yb in batches:
                (lv,) = exe.run(target, feed={"x": xb, "y": yb},
                                fetch_list=[loss])
                out.append(float(np.asarray(lv)))
        return out

    single = run(*build_plain())
    main, startup, loss = build_tp(2)
    bs = BuildStrategy()
    bs.tensor_parallel_degree = 2
    cp = CompiledProgram(main).with_data_parallel(loss_name=loss.name,
                                                 build_strategy=bs)
    par = run(main, startup, loss, compiled=cp)
    np.testing.assert_allclose(single, par, rtol=3e-4, atol=1e-5)


def test_static_lm_builder_with_tp_and_fleet():
    """ERNIE-style rehearsal: the static LM builder at tp=2 trains through
    the FLEET path (DistributedStrategy.tensor_parallel → graph_execution
    meta-optimizer → dp×tp CompiledProgram) with finite decreasing loss."""
    _need_devices(8)
    from paddle_tpu.distributed.fleet.base.fleet_base import Fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.models import build_transformer_lm

    main, startup, loss, _ = build_transformer_lm(
        vocab_size=64, hidden=32, num_layers=2, num_heads=4, seq_len=8,
        tensor_parallel_degree=2)

    fleet = Fleet()
    fleet.init(is_collective=True)
    strategy = DistributedStrategy()
    strategy.tensor_parallel = True
    strategy.tensor_parallel_configs = {"tensor_parallel_degree": 2}
    with static.program_guard(main, startup):
        opt = fleet.distributed_optimizer(
            static.Adam(learning_rate=1e-2), strategy)
        opt.minimize(loss)
    compiled = main._compiled_for_fleet
    assert compiled is not None
    mesh = compiled._get_mesh()
    assert dict(mesh.shape) == {"dp": 4, "tp": 2}

    rng = np.random.RandomState(0)
    feed = {
        "ids": rng.randint(0, 64, (8, 8)).astype(np.int64),
        "pos": np.tile(np.arange(8), (8, 1)).astype(np.int64),
        "labels": rng.randint(0, 64, (8, 8, 1)).astype(np.int64),
    }
    exe = static.Executor()
    scope = static.Scope()
    with static.scope_guard(scope):
        exe.run(startup)
        losses = [float(np.asarray(exe.run(compiled, feed=feed,
                                           fetch_list=[loss])[0]))
                  for _ in range(8)]
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses

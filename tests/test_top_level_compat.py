"""2.0-alpha top-level surface (reference python/paddle/__init__.py):
fluid-spelled functionals, einsum, addcmul, default dtype, rng state,
LoD aliases — all importable from the package root and dual-mode where
meaningful."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu.static import layers


def test_eager_compat_functions():
    a = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    b = paddle.to_tensor(np.ones((3, 2), np.float32))
    np.testing.assert_allclose(
        np.asarray(paddle.einsum("ij,jk->ik", a, b).numpy()),
        np.asarray(a.numpy()) @ np.asarray(b.numpy()))
    np.testing.assert_allclose(
        np.asarray(paddle.addcmul(a, a, a, value=2.0).numpy()),
        np.asarray(a.numpy()) + 2.0 * np.asarray(a.numpy()) ** 2)
    assert not bool(np.asarray(paddle.has_inf(a).numpy()))
    bad = paddle.to_tensor(np.array([1.0, np.nan], np.float32))
    assert bool(np.asarray(paddle.has_nan(bad).numpy()))
    np.testing.assert_allclose(
        float(np.asarray(paddle.reduce_mean(a).numpy())), 2.5)
    np.testing.assert_allclose(
        np.asarray(paddle.elementwise_sub(a, a).numpy()), 0.0)
    s = paddle.elementwise_sum([a, a, a])
    np.testing.assert_allclose(np.asarray(s.numpy()),
                               3 * np.asarray(a.numpy()))


def test_static_einsum_records_op():
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 2, 3])
        y = layers.data("y", [-1, 3, 4])
        out = paddle.einsum("bij,bjk->bik", x, y)
        assert any(op.type == "einsum"
                   for op in main.global_block().ops)
    exe, sc = static.Executor(), static.Scope()
    xa = np.random.RandomState(0).rand(2, 2, 3).astype(np.float32)
    ya = np.random.RandomState(1).rand(2, 3, 4).astype(np.float32)
    with static.scope_guard(sc):
        exe.run(startup)
        (got,) = exe.run(main, feed={"x": xa, "y": ya},
                         fetch_list=[out])
    np.testing.assert_allclose(np.asarray(got),
                               np.einsum("bij,bjk->bik", xa, ya),
                               rtol=1e-5)


def test_default_dtype_and_rng_state():
    assert paddle.get_default_dtype() == "float32"
    paddle.set_default_dtype("float64")
    try:
        assert paddle.get_default_dtype() == "float64"
        # creation paths honor the default for UNTYPED (python) inputs
        assert str(paddle.to_tensor([1.0, 2.0]).dtype) == "float64"
        assert str(paddle.full([2], 3.0).dtype) == "float64"
        # ...while typed inputs keep their own dtype
        assert str(paddle.to_tensor(
            np.ones(2, np.float32)).dtype) == "float32"
        with pytest.raises(ValueError):
            paddle.set_default_dtype("int8")
        # numpy dtype CLASS form accepted like the reference
        paddle.set_default_dtype(np.float32)
        assert paddle.get_default_dtype() == "float32"
    finally:
        paddle.set_default_dtype("float32")
    st = paddle.get_cuda_rng_state()
    paddle.set_cuda_rng_state(st)
    paddle.manual_seed(7)


def test_aliases_exist():
    assert paddle.LoDTensor is paddle.Tensor
    assert paddle.LoDTensorArray is list
    assert paddle.Variable is not None
    assert paddle.ParamAttr is not None
    assert paddle.DataParallel is not None
    assert paddle.XPUPlace is not None
    assert paddle.SaveLoadConfig() is not None
    assert paddle.CosineDecay(0.1, step_each_epoch=10, epochs=4) \
        .get_lr() == pytest.approx(0.1)


def test_distribution_module():
    """paddle.distribution Normal/Uniform (reference distribution.py):
    sampling statistics, log_prob/probs consistency, closed-form
    entropy and KL."""
    import paddle_tpu.distribution as D
    n = D.Normal(1.0, 2.0)
    s = np.asarray(n.sample([4000], seed=5).numpy())
    assert abs(s.mean() - 1.0) < 0.15 and abs(s.std() - 2.0) < 0.15
    # entropy of N(mu, sigma) = 0.5 + 0.5 ln(2 pi) + ln sigma
    want = 0.5 + 0.5 * np.log(2 * np.pi) + np.log(2.0)
    np.testing.assert_allclose(float(np.asarray(n.entropy().numpy())),
                               want, rtol=1e-5)
    lp = float(np.asarray(n.log_prob(1.0).numpy()))
    np.testing.assert_allclose(np.exp(lp),
                               1.0 / (2.0 * np.sqrt(2 * np.pi)),
                               rtol=1e-5)
    # KL(N0 || N0) == 0; KL to a different Normal is positive
    np.testing.assert_allclose(
        float(np.asarray(n.kl_divergence(D.Normal(1.0, 2.0)).numpy())),
        0.0, atol=1e-6)
    assert float(np.asarray(
        n.kl_divergence(D.Normal(0.0, 1.0)).numpy())) > 0

    u = D.Uniform(0.0, 4.0)
    su = np.asarray(u.sample([2000], seed=3).numpy())
    assert su.min() >= 0.0 and su.max() <= 4.0
    np.testing.assert_allclose(
        float(np.asarray(u.probs(2.0).numpy())), 0.25, rtol=1e-6)
    assert np.isneginf(float(np.asarray(u.log_prob(5.0).numpy())))
    np.testing.assert_allclose(
        float(np.asarray(u.entropy().numpy())), np.log(4.0), rtol=1e-6)


def test_compat_framework_sysconfig():
    import paddle_tpu.compat as compat
    assert compat.to_text(b"abc") == "abc"
    assert compat.to_bytes("abc") == b"abc"
    assert compat.to_text([b"a", [b"b"]]) == ["a", ["b"]]
    assert compat.round(2.5) == 3.0 and compat.round(-2.5) == -3.0
    assert compat.floor_division(7, 2) == 3
    import paddle_tpu.sysconfig as sysconfig
    import os
    assert os.path.isdir(sysconfig.get_include())
    import paddle_tpu.framework as fw
    assert fw.ParamAttr is not None and fw.SaveLoadConfig is not None

"""Tier-1 2-D-planner gate (NOT marked slow — a regression in the tp
lattice axis, the per-axis wire pricing, the tp HBM division, or the
layout-level candidate gating must fail the suite, not wait for a perf
round).

Drives tools/tp_plan_smoke.py in-process: the planner must pick a 4×2
dp×tp plan UNPROMPTED (tp variants auto-generated from a model config,
never hand-fed) for a shape where every pure-dp candidate is
walker-infeasible, the applied plan must be
`check_program(level="all")`-clean, and the winning build must train on
the real 8-device 4×2 CPU mesh with zero post-warmup retraces — all
under 15 s.  Mirrors the plan_smoke/mem_smoke gate pattern.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def test_tp_plan_smoke_gate():
    import tp_plan_smoke
    result = tp_plan_smoke.run_smoke()
    assert result["value"] < 15, result              # wall budget
    assert result["chosen_knobs"]["tp_degree"] == 2, result
    # the per-axis wire split priced BOTH rings (mp at its own degree)
    assert result["wire_bytes_per_axis"].get("mp", 0) > 0, result
    assert result["wire_bytes_per_axis"].get("dp", 0) > 0, result
    # the premise held: the tp walk is strictly below the pure-dp floor
    assert result["best_tp_peak_bytes"] < result["best_dp_peak_bytes"]
    assert result["losses"][-1] < result["losses"][0], result


@pytest.mark.slow
def test_tp_plan_smoke_cli():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "tp_plan_smoke.py")],
        capture_output=True, text=True, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, (out.stdout + out.stderr)[-2000:]
    assert '"metric": "tp_plan_smoke_wall_s"' in out.stdout

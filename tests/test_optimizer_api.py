"""paddle.optimizer 2.0 API tests (reference: test_adam_op.py dygraph
sections, test_optimizer.py, test_imperative_optimizer.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt


def _setup():
    paddle.disable_static()
    paddle.seed(0)
    lin = nn.Linear(4, 3)
    x = paddle.to_tensor(np.random.RandomState(0).randn(8, 4)
                         .astype(np.float32))
    return lin, x


def _one_step(lin, x, optimizer):
    loss = (lin(x) ** 2).mean()
    loss.backward()
    optimizer.step()
    optimizer.clear_grad()
    return float(loss)


@pytest.mark.parametrize("cls,kw", [
    (opt.SGD, {}),
    (opt.Momentum, {"momentum": 0.9}),
    (opt.Adam, {}),
    (opt.AdamW, {"weight_decay": 0.01}),
    (opt.Adamax, {}),
    (opt.Adagrad, {}),
    (opt.Adadelta, {}),
    (opt.RMSProp, {}),
    (opt.Lamb, {}),
])
def test_optimizers_decrease_loss(cls, kw):
    lin, x = _setup()
    o = cls(learning_rate=0.05, parameters=lin.parameters(), **kw)
    losses = [_one_step(lin, x, o) for _ in range(12)]
    assert losses[-1] < losses[0]


def test_adam_matches_manual():
    lin, x = _setup()
    w0 = lin.weight.numpy().copy()
    o = opt.Adam(learning_rate=0.1, parameters=lin.parameters())
    loss = (lin(x) ** 2).mean()
    loss.backward()
    g = np.asarray(lin.weight.grad_._value if hasattr(lin.weight.grad_,
                                                      "_value")
                   else lin.weight.grad_)
    o.step()
    # manual first adam step: m=.1g/.1? bias-corrected update == lr*sign-ish
    m = 0.1 * g
    v = 0.001 * g * g
    mhat = m / (1 - 0.9)
    vhat = v / (1 - 0.999)
    expect = w0 - 0.1 * mhat / (np.sqrt(vhat) + 1e-8)
    np.testing.assert_allclose(lin.weight.numpy(), expect, rtol=1e-4,
                               atol=1e-5)


def test_weight_decay_coupled():
    lin, x = _setup()
    w0 = lin.weight.numpy().copy()
    o = opt.SGD(learning_rate=0.1, parameters=lin.parameters(),
                weight_decay=0.5)
    lin.weight.grad_ = paddle.to_tensor(np.zeros_like(w0))
    lin.bias.grad_ = paddle.to_tensor(np.zeros((3,), np.float32))
    o.step()
    np.testing.assert_allclose(lin.weight.numpy(), w0 - 0.1 * 0.5 * w0,
                               rtol=1e-5)


def test_clip_by_global_norm():
    lin, x = _setup()
    clip = opt.ClipGradByGlobalNorm(clip_norm=0.01)
    o = opt.SGD(learning_rate=1.0, parameters=lin.parameters(),
                grad_clip=clip)
    w0 = lin.weight.numpy().copy()
    loss = (lin(x) ** 2).mean()
    loss.backward()
    o.step()
    delta = np.sqrt(((lin.weight.numpy() - w0) ** 2).sum()
                    + ((lin.bias.numpy()) ** 2).sum() * 0)
    assert delta <= 0.011  # ||update|| = lr * ||clipped grad|| <= clip_norm


def test_lr_scheduler_integration():
    lin, x = _setup()
    sched = opt.lr.StepDecay(learning_rate=0.1, step_size=1, gamma=0.5)
    o = opt.SGD(learning_rate=sched, parameters=lin.parameters())
    assert o.get_lr() == pytest.approx(0.1)
    _one_step(lin, x, o)
    sched.step()
    assert o.get_lr() == pytest.approx(0.05)


def test_state_dict_roundtrip():
    lin, x = _setup()
    o = opt.Adam(learning_rate=0.01, parameters=lin.parameters())
    for _ in range(3):
        _one_step(lin, x, o)
    sd = o.state_dict()
    assert any("moment1" in k for k in sd)

    lin2 = nn.Linear(4, 3)
    lin2.set_state_dict(lin.state_dict())
    o2 = opt.Adam(learning_rate=0.01, parameters=lin2.parameters())
    # param names differ between instances; remap by position
    name_map = {p2.name: p.name for p, p2 in
                zip(lin.parameters(), lin2.parameters())}
    sd2 = {}
    for k, v in sd.items():
        for new, old in name_map.items():
            if k.startswith(old):
                sd2[new + k[len(old):]] = v
    o2.set_state_dict(sd2)
    l1 = _one_step(lin, x, o)
    l2 = _one_step(lin2, x, o2)
    assert l1 == pytest.approx(l2, rel=1e-5)


def test_minimize_static_delegation():
    paddle.enable_static()
    try:
        import paddle_tpu.static as static
        from paddle_tpu.static import layers
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            xv = layers.data("x", [-1, 4])
            loss = layers.mean(layers.square(layers.fc(xv, 2)))
            opt.Adam(learning_rate=0.1).minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        feed = {"x": np.random.RandomState(0).randn(4, 4).astype(np.float32)}
        l0 = exe.run(main, feed=feed, fetch_list=[loss])[0]
        for _ in range(10):
            ln = exe.run(main, feed=feed, fetch_list=[loss])[0]
        assert ln < l0
    finally:
        paddle.disable_static()


def test_static_delegation_attr_translation():
    """Regression: Momentum/RMSProp/Lamb kernel attrs must translate to the
    fluid ctor kwargs when a 2.0 optimizer is used in static mode."""
    import paddle_tpu.optimizer as opt
    import paddle_tpu.nn as nn
    params = nn.Linear(2, 2).parameters()
    for cls, kw in ((opt.Momentum, {"momentum": 0.8}),
                    (opt.RMSProp, {"rho": 0.9}),
                    (opt.Lamb, {"lamb_weight_decay": 0.02})):
        o = cls(learning_rate=0.1, parameters=params, **kw)
        s = o._static()  # must not raise TypeError
        assert s is not None


def test_adamax_beta1pow_advances():
    import numpy as np
    import paddle_tpu
    import paddle_tpu.optimizer as opt
    import paddle_tpu.nn as nn
    layer = nn.Linear(4, 2)
    o = opt.Adamax(learning_rate=0.1, beta1=0.9,
                   parameters=layer.parameters())
    x = paddle_tpu.to_tensor(np.ones((2, 4), np.float32))
    for _ in range(3):
        loss = layer(x).sum()
        loss.backward()
        o.step()
        o.clear_grad()
    b1p = float(np.asarray(
        o._accumulators["beta1_pow"][layer.weight.name]).reshape(())) \
        if "beta1_pow" in o._accumulators else None
    # accumulator starts at beta^1 and advances once per step → beta^4
    assert b1p is not None and abs(b1p - 0.9 ** 4) < 1e-6, b1p

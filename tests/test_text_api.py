"""paddle.text toolkit tests (reference python/paddle/text/text.py) —
cells/RNNs forward + numerics, CNN encoder, and the SequenceTagging
CRF model training end to end."""
import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.text as text
from paddle_tpu.dygraph import guard, to_variable


def test_basic_lstm_cell_matches_numpy():
    with guard():
        cell = text.BasicLSTMCell(4, 3, forget_bias=1.0)
        x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        out, (h, c) = cell(to_variable(x))
        w = np.asarray(cell.weight.numpy())
        b = np.asarray(cell.bias.numpy())

        def sig(v):
            return 1 / (1 + np.exp(-v))

        xin = np.concatenate([x, np.zeros((2, 3), np.float32)], 1)
        gates = xin @ w + b
        i, f, cand, o = np.split(gates, 4, axis=1)
        c_ref = sig(f + 1.0) * 0 + sig(i) * np.tanh(cand)
        h_ref = sig(o) * np.tanh(c_ref)
        np.testing.assert_allclose(np.asarray(h.numpy()), h_ref,
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(out.numpy()), h_ref,
                                   rtol=1e-5, atol=1e-6)


def test_basic_gru_cell_matches_numpy():
    with guard():
        cell = text.BasicGRUCell(4, 3)
        x = np.random.RandomState(1).randn(2, 4).astype(np.float32)
        out, h = cell(to_variable(x))
        gw = np.asarray(cell.gate_weight.numpy())
        gb = np.asarray(cell.gate_bias.numpy())
        cw = np.asarray(cell.candidate_weight.numpy())
        cb = np.asarray(cell.candidate_bias.numpy())

        def sig(v):
            return 1 / (1 + np.exp(-v))

        h0 = np.zeros((2, 3), np.float32)
        xin = np.concatenate([x, h0], 1)
        u, r = np.split(sig(xin @ gw + gb), 2, axis=1)
        cand = np.tanh(np.concatenate([x, r * h0], 1) @ cw + cb)
        h_ref = u * h0 + (1 - u) * cand
        np.testing.assert_allclose(np.asarray(out.numpy()), h_ref,
                                   rtol=1e-5, atol=1e-6)


def test_stacked_and_bidirectional_shapes():
    with guard():
        x = to_variable(np.random.RandomState(2)
                        .randn(2, 5, 8).astype(np.float32))
        lstm = text.LSTM(8, 16, num_layers=2)
        out, _ = lstm(x)
        assert tuple(out.shape) == (2, 5, 16)
        gru = text.GRU(8, 16)
        out, _ = gru(x)
        assert tuple(out.shape) == (2, 5, 16)
        bl = text.BidirectionalLSTM(8, 6)
        out, _ = bl(x)
        assert tuple(out.shape) == (2, 5, 12)
        br = text.BidirectionalRNN(text.BasicGRUCell(8, 4),
                                   text.BasicGRUCell(8, 4))
        out, _ = br(x)
        assert tuple(out.shape) == (2, 5, 8)


def test_cnn_encoder_and_ffn():
    with guard():
        enc = text.CNNEncoder(num_channels=8, num_filters=4,
                              filter_size=[3, 5], num_layers=2)
        x = to_variable(np.random.RandomState(3)
                        .randn(2, 8, 10).astype(np.float32))
        out = enc(x)
        assert tuple(out.shape) == (2, 8, 5)
        ffn = text.FFN(32, 16)
        y = ffn(to_variable(np.random.RandomState(4)
                            .randn(2, 3, 16).astype(np.float32)))
        assert tuple(y.shape) == (2, 3, 16)
        ppl = text.PrePostProcessLayer("dan", 16, 0.0)
        z = ppl(y, residual=y)
        assert tuple(z.shape) == (2, 3, 16)


def test_dynamic_decode_greedy_stops_at_end():
    with guard():
        rng = np.random.RandomState(5)
        emb_w = to_variable(rng.randn(10, 8).astype(np.float32))
        cell = text.BasicGRUCell(8, 8)
        proj = paddle_tpu.nn.Linear(8, 10)

        def embedding_fn(tok):
            from paddle_tpu.tensor.manipulation import gather
            return gather(emb_w, tok)

        dec = text.DynamicDecode(embedding_fn, proj, cell,
                                 start_token=1, end_token=2,
                                 max_step_num=6)
        out = dec(batch_ref=emb_w)
        assert out.shape[0] == 10 and 1 <= out.shape[1] <= 6


def test_sequence_tagging_crf_trains():
    """Book-sized convergence: the SequenceTagging model's CRF
    log-likelihood loss falls on a fixed batch, and decode returns a
    path of the right shape sharing the SAME transition weights."""
    with guard():
        V, C, T, B = 20, 4, 5, 4
        model = text.SequenceTagging(V, C, word_emb_dim=16,
                                     grnn_hidden_dim=8, bigru_num=1)
        rng = np.random.RandomState(0)
        words = to_variable(rng.randint(0, V, (B, T)).astype(np.int64))
        target = to_variable(rng.randint(0, C, (B, T)).astype(np.int64))
        from paddle_tpu.optimizer import Adam
        opt = Adam(learning_rate=0.05,
                   parameters=model.parameters())
        losses = []
        for _ in range(25):
            # LogLikelihood output IS the negative log-likelihood cost
            # (reference linear_chain_crf_op convention)
            nll, _ = model(words, target)
            from paddle_tpu.tensor import math as M
            loss = M.mean(nll)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss.numpy())))
        assert losses[-1] < losses[0], losses[::6]
        path = model(words)
        assert tuple(path.shape) == (B, T)
        # decode really shares the crf weights (no divergence possible)
        np.testing.assert_allclose(
            np.asarray(model.crf_decoding.transition.numpy()),
            np.asarray(model.linear_chain_crf.transition.numpy()))


def test_rnn_sequence_length_masks_and_copies_through():
    """Review r4: length-aware stepping — padded outputs zero, states
    copy through, and the reverse direction starts at the last VALID
    step (not the padding)."""
    with guard():
        cell = text.BasicGRUCell(3, 4)
        rng = np.random.RandomState(7)
        x_np = rng.randn(2, 5, 3).astype(np.float32)
        lens = np.array([5, 2], np.int64)
        out, st = text.RNN(cell)(to_variable(x_np), None,
                                 to_variable(lens))
        o = np.asarray(out.numpy())
        # padded steps of the short sequence emit zeros
        assert (o[1, 2:] == 0).all() and np.abs(o[1, :2]).sum() > 0
        # final state of the short sequence == its step-2 output state
        ref_out, _ = text.RNN(cell)(to_variable(x_np[1:2, :2]))
        np.testing.assert_allclose(np.asarray(st.numpy())[1],
                                   np.asarray(ref_out.numpy())[0, -1],
                                   rtol=1e-5, atol=1e-6)
        # reverse: first valid output of the short sequence must equal a
        # fresh reverse run over ONLY its valid prefix
        r_out, _ = text.RNN(cell, is_reverse=True)(
            to_variable(x_np), None, to_variable(lens))
        r_ref, _ = text.RNN(cell, is_reverse=True)(
            to_variable(x_np[1:2, :2]))
        np.testing.assert_allclose(np.asarray(r_out.numpy())[1, :2],
                                   np.asarray(r_ref.numpy())[0],
                                   rtol=1e-5, atol=1e-6)


def test_bidirectional_merge_modes():
    with guard():
        x = to_variable(np.random.RandomState(8)
                        .randn(2, 4, 3).astype(np.float32))
        for mode, width in (("concat", 8), ("sum", 4), ("ave", 4),
                            ("mul", 4)):
            br = text.BidirectionalRNN(text.BasicGRUCell(3, 4),
                                       text.BasicGRUCell(3, 4),
                                       merge_mode=mode)
            out, _ = br(x)
            assert tuple(out.shape) == (2, 4, width), mode
        with pytest.raises(ValueError, match="merge_mode"):
            text.BidirectionalRNN(text.BasicGRUCell(3, 4),
                                  text.BasicGRUCell(3, 4),
                                  merge_mode="zip")


def test_prepostprocess_dropout_respects_eval():
    with guard():
        ppl = text.PrePostProcessLayer("d", 4, 0.9)
        ppl.eval()
        x = to_variable(np.ones((2, 4), np.float32))
        out = np.asarray(ppl(x).numpy())
        np.testing.assert_allclose(out, np.ones((2, 4)))

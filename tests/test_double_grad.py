"""Second-order gradients (reference: per-op DoubleGradMakers in
operators/*_op.cc e.g. conv_op.cc Conv2DDoubleGradMaker, activation_op.cc;
imperative double grad via partial_grad_engine.cc).  Here: registry
registers auto-vjp grads for grad ops one level deep (static), and
paddle.grad(create_graph=True) replays the tape under nested jax.vjp
(dygraph)."""
import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.dygraph as dg
import paddle_tpu.static as static
from paddle_tpu.static import layers


def test_dygraph_double_grad_polynomial():
    """y = x^3: dy/dx = 3x^2, d2y/dx2 = 6x."""
    with dg.guard():
        x = dg.to_variable(np.array([2.0, -1.0], np.float32))
        x.stop_gradient = False
        y = x * x * x
        (dx,) = dg.grad([y.sum()], [x], create_graph=True)
        np.testing.assert_allclose(np.asarray(dx.numpy()), [12.0, 3.0],
                                   rtol=1e-5)
        (ddx,) = dg.grad([dx.sum()], [x])
        np.testing.assert_allclose(np.asarray(ddx.numpy()), [12.0, -6.0],
                                   rtol=1e-5)


def test_dygraph_double_grad_through_layers():
    """Gradient-penalty pattern: ||dL/dx||^2 backpropagated into weights."""
    with dg.guard():
        from paddle_tpu.nn import Linear
        lin = Linear(4, 1)
        x = dg.to_variable(np.random.RandomState(0)
                           .rand(3, 4).astype(np.float32))
        x.stop_gradient = False
        y = lin(x)
        loss = (y * y).sum()
        (dx,) = dg.grad([loss], [x], create_graph=True)
        penalty = (dx * dx).sum()
        penalty.backward()
        w_grad = lin.weight.grad
        assert w_grad is not None
        # analytic check: y = xW+b, dL/dx = 2yW^T, penalty = 4 sum(y^2 WW^T)
        W = np.asarray(lin.weight.numpy())
        b = np.asarray(lin.bias.numpy())
        xv = np.asarray(x.numpy())
        yv = xv @ W + b
        pen_ref = 4.0 * float((yv ** 2).sum()) * float((W * W).sum())
        np.testing.assert_allclose(float(penalty.numpy()), pen_ref,
                                   rtol=1e-4)
        # numeric wgrad via finite differences on the penalty
        eps = 1e-3
        num = np.zeros_like(W)
        for i in range(W.shape[0]):
            for j in range(W.shape[1]):
                for s, sign in ((eps, 1), (-eps, -1)):
                    W2 = W.copy()
                    W2[i, j] += s
                    y2 = xv @ W2 + b
                    d2 = 2 * y2 @ W2.T
                    num[i, j] += sign * (d2 * d2).sum()
        num /= (2 * eps)
        np.testing.assert_allclose(np.asarray(w_grad.numpy()), num,
                                   rtol=2e-2, atol=2e-2)


def test_dygraph_double_grad_unused_and_no_grad_vars():
    with dg.guard():
        x = dg.to_variable(np.ones(2, np.float32))
        x.stop_gradient = False
        z = dg.to_variable(np.ones(2, np.float32))
        z.stop_gradient = False
        y = x * x
        with pytest.raises(RuntimeError):
            dg.grad([y.sum()], [z], create_graph=True)
        dx, dz = dg.grad([y.sum()], [x, z], create_graph=True,
                         allow_unused=True)
        assert dz is None
        np.testing.assert_allclose(np.asarray(dx.numpy()), [2.0, 2.0])


def test_static_double_grad():
    """fluid.gradients applied twice: d2(x^3)/dx2 = 6x via registered
    <op>_grad_grad kernels."""
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 2])
        x.stop_gradient = False
        y = layers.reduce_sum(layers.elementwise_mul(
            layers.elementwise_mul(x, x), x))
        (dx,) = static.gradients([y], [x])
        assert dx is not None
        (ddx,) = static.gradients([dx], [x])
        assert ddx is not None
    exe = static.Executor()
    scope = static.Scope()
    xv = np.array([[2.0, -1.0]], np.float32)
    with static.scope_guard(scope):
        exe.run(startup)
        d1, d2 = exe.run(main, feed={"x": xv}, fetch_list=[dx, ddx])
    np.testing.assert_allclose(d1, [[12.0, 3.0]], rtol=1e-5)
    np.testing.assert_allclose(d2, [[12.0, -6.0]], rtol=1e-5)


def test_grad_op_registry_has_double_grads():
    from paddle_tpu.ops.registry import get_op_info
    for op in ("tanh", "matmul", "conv2d", "batch_norm", "relu"):
        info = get_op_info(op + "_grad")
        assert info is not None and info.has_grad, op
        assert get_op_info(op + "_grad_grad") is not None, op


def test_dygraph_third_order_grad():
    """Nested create_graph: d3(x^4)/dx3 = 24x via replaying a grad node
    with multiple outputs."""
    with dg.guard():
        x = dg.to_variable(np.array([1.5, -2.0], np.float32))
        x.stop_gradient = False
        z = dg.to_variable(np.array([2.0, 3.0], np.float32))
        z.stop_gradient = False
        y = (x * x * x * x).sum() + (z * z).sum()
        dx, dz = dg.grad([y], [x, z], create_graph=True)
        ddx, ddz = dg.grad([dx.sum() + dz.sum()], [x, z],
                           create_graph=True)
        np.testing.assert_allclose(np.asarray(ddx.numpy()),
                                   12 * np.array([1.5, -2.0]) ** 2,
                                   rtol=1e-4)
        np.testing.assert_allclose(np.asarray(ddz.numpy()), [2.0, 2.0],
                                   rtol=1e-5)
        (dddx,) = dg.grad([ddx.sum()], [x])
        np.testing.assert_allclose(np.asarray(dddx.numpy()),
                                   24 * np.array([1.5, -2.0]), rtol=1e-4)

"""Medium-shape data-parallel dryrun (VERDICT r4 weak #5).

The driver's dryrun_multichip runs toy shapes (seq 32/16) — enough for
wiring, not for sharding-induced numerics drift.  This runs config 1
(pure dp over the 8-device virtual mesh) at seq 512 / hidden 128 and
checks the sharded loss MATCHES the single-device loss on identical
params + batch, so a sharding bug that only shows at realistic dims
fails here instead of on hardware.
"""
import numpy as np
import pytest

import paddle_tpu.static as static


def _model(seq, hidden, vocab):
    import __graft_entry__ as ge
    return ge._tiny_model(seq=seq, hidden=hidden, heads=4, vocab=vocab,
                          layers_n=2)


@pytest.mark.slow
def test_seq512_dp_matches_single_device():
    import jax
    from paddle_tpu.distributed.compiled_program import CompiledProgram

    seq, hidden, vocab = 512, 128, 256
    n_dev = len(jax.devices())
    assert n_dev >= 8, "conftest forces an 8-device CPU mesh"
    rng = np.random.RandomState(0)
    batch = n_dev  # one row per device
    ids = rng.randint(0, vocab, (batch, seq)).astype(np.int64)
    labels = rng.randint(0, vocab, (batch, seq, 1)).astype(np.int64)

    def run(data_parallel):
        main, startup, loss = _model(seq, hidden, vocab)
        # identical init both runs: init randomness is drawn from the
        # STARTUP program's seed (Executor._seed_for_step reads the seed
        # of the program being run)
        startup.random_seed = 7
        main.random_seed = 7
        exe = static.Executor()
        scope = static.Scope()
        losses = []
        with static.scope_guard(scope):
            exe.run(startup)
            prog = main
            if data_parallel:
                prog = CompiledProgram(main).with_data_parallel(
                    loss_name=loss.name, places=jax.devices()[:n_dev])
            for _ in range(2):
                (lv,) = exe.run(prog,
                                feed={"ids": ids, "labels": labels},
                                fetch_list=[loss])
                losses.append(float(np.asarray(lv)))
        return losses

    single = run(False)
    sharded = run(True)
    assert all(np.isfinite(single)) and all(np.isfinite(sharded))
    # same params, same global batch -> same loss trace (grad allreduce
    # mean == full-batch grad); tolerance covers reduction order
    np.testing.assert_allclose(sharded, single, rtol=5e-4, atol=1e-5)
    # and training moved the loss
    assert sharded[1] < sharded[0]

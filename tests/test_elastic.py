"""Elastic training (distributed/elastic.py + testing/chaos.py + launch).

The ROADMAP "Done =" condition: kill/resume 8→4→8 devices on the CPU
mesh with a loss trace BITWISE-equal to an uninterrupted run after the
schedule re-converges.  Tier-1 keeps the cheap schedule/harness units
(the single-shrink integration gate lives in tests/test_elastic_smoke.py
via tools/elastic_smoke.py); the full chaos-driven kill/shrink/regrow
matrix and the launcher supervision loop are marked ``slow``.
"""
import json
import os
import signal
import subprocess
import sys
import time
import warnings

import numpy as np
import pytest

import paddle_tpu.static as static
from paddle_tpu.core.program import _reset_unique_names
from paddle_tpu.distributed.elastic import (
    elasticize, elastic_meta, micro_steps_per_global, rebucket_feeds,
    rederive_schedule, reanchor_topology)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "elastic_worker.py")


def _build_plain():
    from paddle_tpu.static import layers
    _reset_unique_names()
    main, startup = static.Program(), static.Program()
    with static.program_guard(main, startup):
        x = layers.data("x", [-1, 8])
        y = layers.data("y", [-1, 1])
        h = layers.fc(x, 16, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.mean(layers.square(layers.elementwise_sub(pred, y)))
        static.Adam(learning_rate=1e-2).minimize(loss)
    return main, startup, loss


# ---------------------------------------------------------------------------
# schedule units (tier-1)
# ---------------------------------------------------------------------------
def test_rebucket_feeds_preserves_row_order():
    feed = {"x": np.arange(16).reshape(8, 2), "lr": np.float32(0.1)}
    micro = rebucket_feeds(feed, 8, 2)  # K = 4 micro-feeds of 2 rows
    assert len(micro) == 4
    got = np.concatenate([m["x"] for m in micro], axis=0)
    np.testing.assert_array_equal(got, feed["x"])  # same global row order
    assert all(m["lr"] == np.float32(0.1) for m in micro)  # replicated
    assert [m["x"].shape[0] for m in micro] == [2, 2, 2, 2]
    # K = 1 passthrough
    assert rebucket_feeds(feed, 8, 8)[0]["x"].shape == (8, 2)
    with pytest.raises(ValueError):
        rebucket_feeds(feed, 8, 3)  # 3 does not divide 8
    # a lone big non-batch feed (lookup table) must not hijack the batch
    # axis: the MOST COMMON leading dim wins and the table replicates
    mixed = {"x": np.zeros((8, 2)), "y": np.zeros((8, 1)),
             "table": np.zeros((1024, 4))}
    out = rebucket_feeds(mixed, 8, 2)
    assert out[0]["x"].shape == (2, 2) and out[0]["table"].shape == \
        (1024, 4)
    # ambiguous tie demands an explicit batch_rows
    amb = {"x": np.zeros((8, 2)), "t": np.zeros((6, 2))}
    with pytest.raises(ValueError, match="ambiguous"):
        rebucket_feeds(amb, 8, 2)
    out = rebucket_feeds(amb, 8, 2, batch_rows=8)
    assert out[0]["x"].shape == (2, 2) and out[0]["t"].shape == (6, 2)
    # a non-divisible batch fails loudly instead of replicating rows
    with pytest.raises(ValueError, match="not divisible"):
        rebucket_feeds({"x": np.zeros((10, 2))}, 8, 2)


def test_rederive_schedule_boundary_and_midwindow():
    extra = {"executor_step": 99,  # polluted; counter_value wins
             "elastic": {"logical_dp": 8, "k": 2, "counter_value": 6}}
    red = rederive_schedule(extra, new_world=8)  # 4 -> 8 regrow
    assert red["global_step"] == 3 and red["k_new"] == 1
    assert red["executor_step"] == 3 and red["counter_value"] == 3
    assert red["replayed_micro"] == 0
    # mid-window: micro 7 under k=2 rounds down to global 3 and replays
    extra["elastic"]["counter_value"] = 7
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        red = rederive_schedule(extra, new_world=4)
    assert red["global_step"] == 3 and red["replayed_micro"] == 1
    assert red["executor_step"] == 6  # 3 windows * k_new=2
    assert any("mid-window" in str(w.message) for w in caught)
    # world must divide the logical world
    with pytest.raises(ValueError):
        rederive_schedule(extra, new_world=3)
    assert rederive_schedule({}, 4) is None  # no elastic sidecar


def test_micro_steps_per_global_and_meta():
    main, startup, loss = _build_plain()
    assert elastic_meta(main) is None
    meta = elasticize(main, startup, logical_dp=8, loss_name=loss)
    assert elastic_meta(main) is meta
    assert micro_steps_per_global(main, 8) == 1
    assert micro_steps_per_global(main, 2) == 4
    with pytest.raises(ValueError):
        micro_steps_per_global(main, 3)
    assert meta["loss_avg"].endswith("@ELASTIC_AVG")
    assert len(meta["accs"]) == 5  # 4 param grads + the loss fold


def test_elasticize_guards():
    main, startup, loss = _build_plain()
    with pytest.raises(ValueError):
        elasticize(main, startup, logical_dp=6, loss_name=loss)  # not pow2
    elasticize(main, startup, logical_dp=8, loss_name=loss)
    with pytest.raises(ValueError):
        elasticize(main, startup, logical_dp=8)  # double apply
    # programs without recorded param/grad pairs are rejected loudly
    main2, startup2 = static.Program(), static.Program()
    with pytest.raises(ValueError):
        elasticize(main2, startup2, logical_dp=8)


def test_run_steps_refuses_elastic_programs():
    main, startup, loss = _build_plain()
    elasticize(main, startup, logical_dp=8, loss_name=loss)
    exe = static.Executor()
    with pytest.raises(NotImplementedError, match="elastic"):
        exe.run_steps(main, feed={"x": np.zeros((2, 4, 8), np.float32)})


def test_elasticize_accepts_zero1_rejects_higher_stages():
    """The elastic x ZeRO-1 refusal is LIFTED (ISSUE 14): a stage-1
    sharded program elasticizes — the window folds the reduce-scattered
    bucket shard into dp_shard accumulators (numerics proven in
    tests/test_elastic_compose.py).  Stages 2/3 still refuse: their
    bucket chains interleave into backward."""
    from paddle_tpu.distributed.sharding import shard_optimizer_states
    main, startup, loss = _build_plain()
    plan = shard_optimizer_states(main, startup, dp_degree=8)
    assert plan.buckets
    meta = elasticize(main, startup, logical_dp=8, loss_name=loss)
    assert meta["zero_stage1"] is True
    assert any("@ELASTIC_ACC" in a for a in meta["accs"])

    main2, startup2, loss2 = _build_plain()
    shard_optimizer_states(main2, startup2, dp_degree=8, stage=2)
    with pytest.raises(NotImplementedError, match="stage 1 only"):
        elasticize(main2, startup2, logical_dp=8, loss_name=loss2)


def test_elastic_world_size_rounds_to_pow2_divisor():
    from paddle_tpu.distributed.launch import elastic_world_size
    assert elastic_world_size(8, 8) == 8
    assert elastic_world_size(7, 8) == 4  # odd survivor count -> 4
    assert elastic_world_size(3, 8) == 2
    assert elastic_world_size(1, 8) == 1
    assert elastic_world_size(0, 8) == 0
    assert elastic_world_size(6, 4) == 4  # capped by the logical world


def test_elastic_fold_and_mask_kernels_off_mesh():
    """Kernel degradation contract: off-mesh (no collective axes) the
    c_elastic_fold op is acc + x (one logical rank per micro-step) and
    elastic_commit_mask resolves K = logical_dp — a single process walks
    all N micro-steps of a window."""
    import jax.numpy as jnp
    from paddle_tpu.ops.registry import get_op_info, OpContext
    ctx = OpContext(dist_info={0: None})
    fold = get_op_info("c_elastic_fold").kernel
    acc = jnp.zeros(3, jnp.float32)
    for i in range(3):
        acc = fold({"X": jnp.full(3, float(i + 1), jnp.float32),
                    "Acc": acc}, {"ring_id": 0, "logical_dp": 8}, ctx)["Out"]
    np.testing.assert_array_equal(np.asarray(acc), np.full(3, 6.0))
    mask = get_op_info("elastic_commit_mask").kernel
    got = [bool(np.asarray(mask({"X": jnp.array([c], jnp.int32)},
                                {"ring_id": 0, "logical_dp": 4},
                                ctx)["Out"])[0]) for c in range(1, 9)]
    # off-mesh K = 4: commits after micro-steps 4 and 8
    assert got == [False, False, False, True, False, False, False, True]


# ---------------------------------------------------------------------------
# chaos harness units (tier-1)
# ---------------------------------------------------------------------------
def test_chaos_spec_parsing(monkeypatch):
    from paddle_tpu.testing import chaos
    monkeypatch.setenv(chaos.CHAOS_ENV,
                       "kill@5:rank=1:signal=term; slow_save=0.25; "
                       "torn_save@3; collective_fail@2:times=3")
    chaos.reload()
    assert chaos.enabled()
    kinds = {d.kind: d for d in chaos._directives()}
    assert kinds["kill"].step == 5 and kinds["kill"].rank == 1
    assert kinds["kill"].sig == signal.SIGTERM
    assert kinds["slow_save"].seconds == 0.25
    assert kinds["torn_save"].step == 3
    assert kinds["collective_fail"].times == 3
    monkeypatch.setenv(chaos.CHAOS_ENV, "explode@7")
    with pytest.raises(ValueError, match="unknown"):
        chaos.reload()
    monkeypatch.setenv(chaos.CHAOS_ENV, "")
    chaos.reload()
    assert not chaos.enabled()


def test_chaos_kill_respects_rank_filter(monkeypatch):
    from paddle_tpu.testing import chaos
    # a directive for rank 1 must be inert on rank 0 (this process)
    monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
    monkeypatch.setenv(chaos.CHAOS_ENV, "kill@1:rank=0")
    chaos.reload()
    chaos.step_hook(1)  # rank mismatch: no kill — we are still alive
    monkeypatch.setenv(chaos.CHAOS_ENV, "kill@2:rank=1")
    chaos.reload()
    chaos.step_hook(1)  # step mismatch: alive


def test_chaos_collective_fail_injects_then_recovers(monkeypatch):
    """A transient collective failure surfaces as ChaosCollectiveError
    from the dispatch; the retry (same step) proceeds and training
    continues unaffected."""
    import jax
    from paddle_tpu.testing import chaos
    from paddle_tpu.testing.chaos import ChaosCollectiveError
    from paddle_tpu.distributed.compiled_program import CompiledProgram
    main, startup, loss = _build_plain()
    cp = CompiledProgram(main).with_data_parallel(loss_name=loss.name)
    exe = static.Executor()
    scope = static.Scope()
    rng = np.random.RandomState(0)
    feed = {"x": rng.rand(8, 8).astype(np.float32),
            "y": rng.rand(8, 1).astype(np.float32)}
    monkeypatch.setenv(chaos.CHAOS_ENV, "collective_fail@1:times=1")
    chaos.reload()
    with static.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(ChaosCollectiveError):
            exe.run(cp, feed=feed, fetch_list=[loss])
        # transient: the retry of the SAME step goes through
        out = exe.run(cp, feed=feed, fetch_list=[loss])
        assert np.isfinite(np.asarray(out[0])).all()
    monkeypatch.setenv(chaos.CHAOS_ENV, "")
    chaos.reload()


# ---------------------------------------------------------------------------
# supervision units (tier-1)
# ---------------------------------------------------------------------------
def _mk_proc(code, rank):
    from paddle_tpu.distributed.launch_utils import TrainerProc
    tp = TrainerProc()
    tp.proc = subprocess.Popen([sys.executable, "-c", code])
    tp.rank = rank
    return tp


def test_watchdog_fails_fast_and_kills_peers():
    """A non-zero rank exit must terminate the pod and raise — the peers
    are wedged in the next collective, not 'still healthy'."""
    from paddle_tpu.distributed.launch_utils import (poll_local_trainers,
                                                     watch_local_trainers)
    dead = _mk_proc("raise SystemExit(3)", rank=0)
    sleeper = _mk_proc("import time; time.sleep(60)", rank=1)
    dead.proc.wait()
    procs = [dead, sleeper]
    alive, done, failed = poll_local_trainers(procs)
    assert [tp.rank for tp in failed] == [0]
    assert [tp.rank for tp in alive] == [1]
    with pytest.raises(RuntimeError, match="rank"):
        watch_local_trainers(procs, 2)
    assert sleeper.proc.poll() is not None  # peer was torn down


def test_terminate_escalates_sigterm_to_sigkill():
    """A proc ignoring SIGTERM (wedged in a dead collective) must be
    SIGKILLed after the grace window — and reaped."""
    from paddle_tpu.distributed.launch_utils import terminate_procs
    tp = _mk_proc(
        "import signal, time\n"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
        "print('armed', flush=True)\n"
        "time.sleep(60)\n", rank=0)
    time.sleep(1.0)  # let the child install SIG_IGN
    t0 = time.time()
    terminate_procs([tp], sigterm_grace=0.5)
    took = time.time() - t0
    assert tp.proc.poll() == -signal.SIGKILL
    assert took < 30


# ---------------------------------------------------------------------------
# kill / shrink / regrow (slow)
# ---------------------------------------------------------------------------
def _worker_env(**chaos):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PADDLE_TPU_CHAOS", None)
    env.update(chaos)
    return env


def _run_worker(root, out, world, steps, env=None, timeout=300):
    return subprocess.run(
        [sys.executable, WORKER, root, out, str(world), str(steps)],
        env=env or _worker_env(), capture_output=True, text=True,
        timeout=timeout)


@pytest.mark.slow
def test_chaos_kill_shrink_regrow_bitwise(tmp_path):
    """THE acceptance scenario: 8 -> (SIGKILL) -> 4 -> (SIGTERM mid-
    window) -> 8, driven end-to-end by the chaos harness across real
    process restarts, with the loss trace and final params BITWISE equal
    to an uninterrupted 8-device run."""
    steps = 5
    root = str(tmp_path / "ckpts")
    # uninterrupted reference (its own root; no checkpoints consulted)
    ref_out = str(tmp_path / "ref.json")
    p = _run_worker(str(tmp_path / "ref_ckpts"), ref_out, 8, steps)
    assert p.returncode == 0, p.stderr[-3000:]
    ref = json.load(open(ref_out))
    assert sorted(map(int, ref["losses"])) == list(range(steps))

    # phase A: full world, hard-killed (preempted host: no goodbye)
    # after 2 global steps (train-run counter, startup not counted)
    outA = str(tmp_path / "a.json")
    p = _run_worker(root, outA, 8, steps,
                    env=_worker_env(PADDLE_TPU_CHAOS="kill@2"))
    assert p.returncode == -signal.SIGKILL, (p.returncode, p.stderr[-2000:])
    assert not os.path.exists(outA)  # died mid-run

    # phase B: resume on 4 devices (K=2); graceful SIGTERM mid-window —
    # train-run 3 of this process is the FIRST micro-step of global 3
    outB = str(tmp_path / "b.json")
    p = _run_worker(root, outB, 4, steps,
                    env=_worker_env(PADDLE_TPU_CHAOS="kill@3:signal=term"))
    assert p.returncode == 143, (p.returncode, p.stderr[-2000:])
    assert not os.path.exists(outB)

    # phase C: the fleet is back — regrow to 8, run to completion.  The
    # exact resume point depends on which async save the SIGKILL raced
    # (that is the point of the chaos harness); what is CONTRACTUAL is
    # that some committed step survived, a mid-window SIGTERM save
    # rounds down and replays, and the final math is bitwise-identical.
    outC = str(tmp_path / "c.json")
    p = _run_worker(root, outC, 8, steps)
    assert p.returncode == 0, p.stderr[-3000:]
    c = json.load(open(outC))
    assert 1 <= c["resumed_global"] < steps, c["resumed_global"]

    # bitwise: every global step phase C recomputed matches the
    # uninterrupted trace, and the final params are identical
    for gi, lv in c["losses"].items():
        assert np.float32(lv) == np.float32(ref["losses"][gi]), gi
    for name, want in ref["params"].items():
        np.testing.assert_array_equal(
            np.asarray(want, np.float32), np.asarray(c["params"][name],
                                                     np.float32),
            err_msg=name)


@pytest.mark.slow
def test_inprocess_shrink_regrow_matrix_bitwise():
    """8 -> 2 -> 4 -> 8 live re-anchoring (no checkpoint round-trip):
    reanchor_topology re-derives the schedule between phases and every
    factorization folds in the same order."""
    import jax
    from paddle_tpu.distributed.compiled_program import CompiledProgram
    steps_phase = [(8, 1), (2, 1), (4, 1), (8, 1)]
    rng = np.random.RandomState(0)
    feeds = [{"x": rng.rand(8, 8).astype(np.float32),
              "y": rng.rand(8, 1).astype(np.float32)} for _ in range(4)]

    def run(phases):
        main, startup, loss = _build_plain()
        meta = elasticize(main, startup, logical_dp=8, loss_name=loss)
        exe = static.Executor()
        scope = static.Scope()
        trace, g, first = [], 0, True
        with static.scope_guard(scope):
            exe.run(startup)
            for world, ngs in phases:
                if not first:
                    reanchor_topology(exe, main, scope, world)
                first = False
                cp = CompiledProgram(main).with_data_parallel(
                    loss_name=loss.name,
                    places=list(jax.devices())[:world])
                for _ in range(ngs):
                    for mf in rebucket_feeds(feeds[g], 8, world):
                        out = exe.run(cp, feed=mf,
                                      fetch_list=[meta["loss_avg"]])
                    trace.append(np.asarray(out[0]))
                    g += 1
            params = {p.name: np.asarray(scope.get(p.name))
                      for p in main.all_parameters()}
        return trace, params

    ref_trace, ref_params = run([(8, 4)])
    got_trace, got_params = run(steps_phase)
    for i, (a, b) in enumerate(zip(ref_trace, got_trace)):
        assert np.array_equal(a, b), f"loss diverged at global step {i}"
    for k in ref_params:
        np.testing.assert_array_equal(ref_params[k], got_params[k],
                                      err_msg=k)


@pytest.mark.slow
def test_launcher_elastic_supervision_end_to_end(tmp_path, monkeypatch):
    """Lost-host story through the real launcher: rank 1 chaos-dies, the
    supervisor tears the pod down fail-fast (rank 0's SIGTERM preemption
    handler checkpoints), re-forms the mesh from the survivor and
    relaunches with the elastic env contract; the relaunched worker
    resumes on the shrunk world and finishes the schedule bitwise."""
    from paddle_tpu.distributed import launch
    steps = 4
    base = str(tmp_path)
    monkeypatch.setenv("PADDLE_TPU_ELASTIC_TEST_DIR", base)
    monkeypatch.setenv("ELASTIC_TOTAL_STEPS", str(steps))
    # rank 1 dies after 2 train steps; the relaunched pod has no rank 1
    monkeypatch.setenv("PADDLE_TPU_CHAOS", "kill@2:rank=1")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    rc = launch.main(["--elastic", "--max_restarts", "2",
                      "--nproc_per_node", "2", "--term_grace", "30",
                      "--log_dir", os.path.join(base, "logs"), WORKER])
    assert rc == 0

    # restart 1 = the re-formed pod: one surviving "host" = world 4
    out = os.path.join(base, "out_rank0_r1.json")
    assert os.path.exists(out), os.listdir(base)
    rep = json.load(open(out))
    assert rep["restart"] == 1 and rep["world"] == 4
    assert rep["elastic_env"] == "1" and rep["logical_env"] == "2"
    assert rep["resumed_global"] >= 1  # resumed from rank 0's preemption
    #  or periodic checkpoint, not from scratch

    # and the finished schedule matches an uninterrupted reference
    ref_out = os.path.join(base, "ref.json")
    p = _run_worker(os.path.join(base, "ref_ckpts"), ref_out, 8, steps)
    assert p.returncode == 0, p.stderr[-3000:]
    ref = json.load(open(ref_out))
    for gi, lv in rep["losses"].items():
        assert np.float32(lv) == np.float32(ref["losses"][gi]), gi
    for name, want in ref["params"].items():
        np.testing.assert_array_equal(
            np.asarray(want, np.float32),
            np.asarray(rep["params"][name], np.float32), err_msg=name)

# R inference client (C28).
#
# Reference: /root/reference/r/ wraps the C predictor API; TPU redesign:
# inference runs behind paddle_tpu/inference/server.py and this client
# speaks its JSON/HTTP protocol with base R only (no Rcpp/FFI).
#
#   p <- paddle_predictor("http://127.0.0.1:8866")
#   p$set_input("x", array(runif(32), dim = c(4, 8)))
#   p$run()
#   out <- p$get_output("fc_0.tmp_1")   # list(data=..., shape=...)

paddle_predictor <- function(endpoint, timeout = 60) {
  if (!requireNamespace("jsonlite", quietly = TRUE))
    stop("paddle_predictor needs the jsonlite package")

  meta <- jsonlite::fromJSON(url(paste0(endpoint, "/metadata")))
  feeds <- list()
  fetched <- NULL

  set_input <- function(name, value) {
    # the wire protocol is C-order (row-major): transpose R's
    # column-major layout before flattening, keep dims unreversed
    if (is.null(dim(value))) {
      data <- as.numeric(value)
      shape <- length(value)
    } else {
      data <- as.numeric(aperm(value, rev(seq_along(dim(value)))))
      shape <- dim(value)
    }
    feeds[[name]] <<- list(
      data = data,
      shape = shape,
      dtype = jsonlite::unbox("float32"))  # scalar string on the wire
    invisible(NULL)
  }

  run <- function() {
    body <- jsonlite::toJSON(list(inputs = feeds), auto_unbox = FALSE)
    if (requireNamespace("curl", quietly = TRUE)) {
      h <- curl::new_handle(postfields = body, timeout = timeout)
      curl::handle_setheaders(h, "Content-Type" = "application/json")
      resp <- curl::curl_fetch_memory(paste0(endpoint, "/predict"), h)
      if (resp$status_code != 200)
        stop(sprintf("predict failed (%d): %s", resp$status_code,
                     rawToChar(resp$content)))
      fetched <<- jsonlite::fromJSON(rawToChar(resp$content))$outputs
    } else {
      stop("paddle_predictor$run needs the curl package")
    }
    invisible(NULL)
  }

  get_output <- function(name) {
    if (is.null(fetched)) stop("call run() first")
    out <- fetched[[name]]
    if (is.null(out)) stop(sprintf("no output '%s'", name))
    out
  }

  list(input_names = meta$inputs, output_names = meta$outputs,
       set_input = set_input, run = run, get_output = get_output)
}

// Package paddle — Go inference client (C28).
//
// Reference: /root/reference/go/paddle/predictor.go wraps the C
// predictor API via cgo, which requires linking the C++ runtime into
// the Go process.  TPU redesign: inference executes on the serving
// host's chips behind paddle_tpu/inference/server.py; this client
// speaks its 4-route JSON/HTTP protocol, keeping the reference's
// Predictor API shape (NewPredictor / GetInputNames / SetInput / Run /
// GetOutput) without any FFI.
package paddle

import (
	"bytes"
	"encoding/json"
	"fmt"
	"io"
	"net/http"
	"time"
)

// AnalysisConfig mirrors the reference config object; only the fields
// meaningful for a remote predictor survive.
type AnalysisConfig struct {
	Endpoint string        // e.g. "http://10.0.0.2:8866"
	Timeout  time.Duration // per-request budget
}

func NewAnalysisConfig(endpoint string) *AnalysisConfig {
	return &AnalysisConfig{Endpoint: endpoint, Timeout: 60 * time.Second}
}

// Tensor is the wire form of one named input/output.
type Tensor struct {
	Data  []float32 `json:"data"`
	Shape []int     `json:"shape"`
	Dtype string    `json:"dtype"`
}

type Predictor struct {
	config  *AnalysisConfig
	client  *http.Client
	inputs  []string
	outputs []string
	feeds   map[string]Tensor
	fetched map[string]Tensor
}

type metadata struct {
	Inputs  []string `json:"inputs"`
	Outputs []string `json:"outputs"`
}

// NewPredictor connects and caches the model's input/output names.
func NewPredictor(config *AnalysisConfig) (*Predictor, error) {
	p := &Predictor{
		config:  config,
		client:  &http.Client{Timeout: config.Timeout},
		feeds:   map[string]Tensor{},
		fetched: map[string]Tensor{},
	}
	resp, err := p.client.Get(config.Endpoint + "/metadata")
	if err != nil {
		return nil, err
	}
	defer resp.Body.Close()
	if resp.StatusCode != http.StatusOK {
		raw, _ := io.ReadAll(resp.Body)
		return nil, fmt.Errorf("metadata failed (%d): %s",
			resp.StatusCode, raw)
	}
	var md metadata
	if err := json.NewDecoder(resp.Body).Decode(&md); err != nil {
		return nil, err
	}
	p.inputs, p.outputs = md.Inputs, md.Outputs
	return p, nil
}

func (p *Predictor) GetInputNum() int        { return len(p.inputs) }
func (p *Predictor) GetOutputNum() int       { return len(p.outputs) }
func (p *Predictor) GetInputNames() []string { return p.inputs }
func (p *Predictor) GetOutputNames() []string { return p.outputs }
func (p *Predictor) GetInputName(n int) string  { return p.inputs[n] }
func (p *Predictor) GetOutputName(n int) string { return p.outputs[n] }

// SetInput stages one named input (ZeroCopyTensor.SetValue analog).
func (p *Predictor) SetInput(name string, data []float32, shape []int) {
	p.feeds[name] = Tensor{Data: data, Shape: shape, Dtype: "float32"}
}

// Run posts the staged inputs and caches the outputs (ZeroCopyRun).
func (p *Predictor) Run() error {
	body, err := json.Marshal(map[string]interface{}{"inputs": p.feeds})
	if err != nil {
		return err
	}
	resp, err := p.client.Post(p.config.Endpoint+"/predict",
		"application/json", bytes.NewReader(body))
	if err != nil {
		return err
	}
	defer resp.Body.Close()
	raw, err := io.ReadAll(resp.Body)
	if err != nil {
		return err
	}
	if resp.StatusCode != http.StatusOK {
		return fmt.Errorf("predict failed (%d): %s", resp.StatusCode, raw)
	}
	var reply struct {
		Outputs map[string]Tensor `json:"outputs"`
	}
	if err := json.Unmarshal(raw, &reply); err != nil {
		return err
	}
	p.fetched = reply.Outputs
	return nil
}

// GetOutput returns a named output tensor after Run.
func (p *Predictor) GetOutput(name string) (Tensor, error) {
	t, ok := p.fetched[name]
	if !ok {
		return Tensor{}, fmt.Errorf("no output %q (did Run succeed?)", name)
	}
	return t, nil
}
